"""Synthetic drift-stack generators for the five judged workload configs.

BASELINE.json `configs` (SURVEY.md §0) defines the workloads:

1. rigid translation-only, 512x512x1000-frame synthetic-drift stack
2. affine 6-DoF (ORB keypoints, ~2k matches/frame)
3. piecewise-rigid patch-wise non-rigid (8x8 patch grid)
4. homography 8-DoF wide-field projective drift
5. 3D volumetric rigid (z-stack, 3D keypoints)

Each generator renders a corner-rich synthetic scene, then resamples it
through per-frame ground-truth transforms, so recovered transforms can
be scored against known ground truth (transform-RMSE, utils.metrics).

Pure NumPy on purpose: data generation is host-side, not part of the
TPU pipeline under test.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SyntheticStack:
    """A generated workload: frames plus ground truth."""

    stack: np.ndarray  # (T, H, W) or (T, D, H, W) float32
    transforms: np.ndarray  # (T, 3, 3) / (T, 4, 4) ground-truth maps ref->frame
    fields: np.ndarray | None = None  # (T, gh, gw, 2) for piecewise configs
    reference: np.ndarray | None = None  # the undrifted scene


def _smooth_noise(rng: np.random.Generator, shape, sigma: float, axes=None) -> np.ndarray:
    """Band-limited noise: white noise blurred by a separable box-ish kernel."""
    x = rng.standard_normal(shape).astype(np.float32)
    k = max(1, int(sigma))
    if k > 1:
        kernel = np.ones(k, dtype=np.float32) / k
        for axis in axes if axes is not None else range(x.ndim):
            if x.shape[axis] >= k:
                x = np.apply_along_axis(
                    lambda v: np.convolve(v, kernel, mode="same"), axis, x
                )
    return x


def render_scene(
    rng: np.random.Generator,
    shape: tuple[int, ...],
    n_blobs: int = 400,
    sigma_range: tuple[float, float] = (1.0, 2.5),
) -> np.ndarray:
    """A corner-rich scene: many small anisotropic Gaussian blobs + texture.

    Blobs give the detector stable corners; the smooth background gives
    the warp something to interpolate. `sigma_range` bounds the blob
    radii: dense high-keypoint scenes (config 2's ~2k-matches regime)
    need sharper blobs, or neighbors at >20 blobs/1000 px^2 merge into
    texture and the detectable-corner count saturates.
    """
    nd = len(shape)
    img = np.zeros(shape, dtype=np.float32)
    # keep blob centers off the boundary; shallow axes (z-stacks with a
    # dozen planes) get a proportional margin instead of the fixed 8
    coords = [
        rng.uniform(min(8, s / 4), s - min(8, s / 4), size=n_blobs)
        for s in shape
    ]
    amps = rng.uniform(0.4, 1.0, size=n_blobs).astype(np.float32)
    sigmas = rng.uniform(*sigma_range, size=(n_blobs, nd)).astype(np.float32)
    grids = np.meshgrid(*[np.arange(s, dtype=np.float32) for s in shape], indexing="ij")
    # Render in chunks to bound memory for 3D scenes.
    for i in range(n_blobs):
        sl = []
        for a in range(nd):
            lo = int(max(0, coords[a][i] - 4 * sigmas[i, a]))
            hi = int(min(shape[a], coords[a][i] + 4 * sigmas[i, a] + 1))
            sl.append(slice(lo, hi))
        sl = tuple(sl)
        expo = np.zeros([s.stop - s.start for s in sl], dtype=np.float32)
        for a in range(nd):
            g = grids[a][sl] - coords[a][i]
            expo += (g / sigmas[i, a]) ** 2
        img[sl] += amps[i] * np.exp(-0.5 * expo)
    img += 0.05 * _smooth_noise(rng, shape, sigma=9)
    img -= img.min()
    img /= max(img.max(), 1e-6)
    return img


def _warp_scene(scene: np.ndarray, M: np.ndarray) -> np.ndarray:
    """Inverse-warp a 2D scene through homogeneous matrix M (maps ref->frame
    coordinates; we sample scene at M^-1 [x, y])."""
    H, W = scene.shape
    Minv = np.linalg.inv(M)
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    w = Minv[2, 0] * xs + Minv[2, 1] * ys + Minv[2, 2]
    sx = (Minv[0, 0] * xs + Minv[0, 1] * ys + Minv[0, 2]) / w
    sy = (Minv[1, 0] * xs + Minv[1, 1] * ys + Minv[1, 2]) / w
    return _bilinear(scene, sx, sy)


def _bilinear(scene: np.ndarray, sx: np.ndarray, sy: np.ndarray) -> np.ndarray:
    H, W = scene.shape
    x0 = np.floor(sx).astype(np.int32)
    y0 = np.floor(sy).astype(np.int32)
    fx = sx - x0
    fy = sy - y0
    x0c = np.clip(x0, 0, W - 1)
    x1c = np.clip(x0 + 1, 0, W - 1)
    y0c = np.clip(y0, 0, H - 1)
    y1c = np.clip(y0 + 1, 0, H - 1)
    v = (
        scene[y0c, x0c] * (1 - fx) * (1 - fy)
        + scene[y0c, x1c] * fx * (1 - fy)
        + scene[y1c, x0c] * (1 - fx) * fy
        + scene[y1c, x1c] * fx * fy
    )
    inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
    return (v * inb).astype(np.float32)


def _random_walk(rng, n, dim, step, maxdev):
    """Bounded random-walk drift trajectory, starting at 0."""
    steps = rng.normal(0, step, size=(n, dim)).astype(np.float32)
    traj = np.cumsum(steps, axis=0)
    return np.clip(traj, -maxdev, maxdev)


def make_drift_stack(
    n_frames: int = 64,
    shape: tuple[int, int] = (256, 256),
    model: str = "translation",
    noise: float = 0.01,
    max_drift: float = 12.0,
    seed: int = 0,
    n_blobs: int | None = None,
    sigma_range: tuple[float, float] = (1.0, 2.5),
) -> SyntheticStack:
    """Configs 1/2/4: a 2D stack drifting under the given transform model.

    `n_blobs` overrides the scene's feature density (default ~400 on
    512x512); `sigma_range` the blob radii. Config 2's nominal "~2k
    matches/frame" regime needs a dense, SHARP scene: n_blobs ~ 12000
    with sigma_range (0.7, 1.4) and max_keypoints=4096 sustains ~2k
    surviving matches per frame (soft default-radius blobs merge at
    that density and detection saturates near 2.4k keypoints).
    """
    allowed = ("translation", "rigid", "similarity", "affine", "homography")
    if model not in allowed:
        raise ValueError(
            f"make_drift_stack model must be one of {allowed}, got {model!r}"
            " (3D stacks: make_drift_stack_3d; non-rigid: make_piecewise_stack)"
        )
    rng = np.random.default_rng(seed)
    H, W = shape
    if n_blobs is None:
        n_blobs = max(200, H * W // 650)
    scene = render_scene(rng, shape, n_blobs=n_blobs, sigma_range=sigma_range)
    cx, cy = (W - 1) / 2.0, (H - 1) / 2.0
    trans = _random_walk(rng, n_frames, 2, step=1.0, maxdev=max_drift)
    mats = np.tile(np.eye(3, dtype=np.float32), (n_frames, 1, 1))
    if model in ("rigid", "similarity", "affine", "homography"):
        angles = _random_walk(rng, n_frames, 1, step=0.004, maxdev=0.05)[:, 0]
    if model == "similarity":
        # zoom drift: bounded random walk of the uniform scale
        scales = 1.0 + _random_walk(rng, n_frames, 1, step=0.002, maxdev=0.03)[:, 0]
    for t in range(n_frames):
        M = np.eye(3, dtype=np.float32)
        if model == "translation":
            M[:2, 2] = trans[t]
        else:
            # Compose about the image center so rotation doesn't fling
            # content out of frame.
            c, s = np.cos(angles[t]), np.sin(angles[t])
            L = np.array([[c, -s], [s, c]], dtype=np.float32)
            if model == "similarity":
                L = np.float32(scales[t]) * L
            if model == "affine":
                L = L @ (np.eye(2, dtype=np.float32) + rng.uniform(-0.02, 0.02, (2, 2)).astype(np.float32))
            M[:2, :2] = L
            M[:2, 2] = trans[t] + np.array([cx, cy], np.float32) - L @ np.array([cx, cy], np.float32)
            if model == "homography":
                M[2, :2] = rng.uniform(-2e-5, 2e-5, 2).astype(np.float32)
        mats[t] = M
    stack = np.stack([_warp_scene(scene, mats[t]) for t in range(n_frames)])
    if noise > 0:
        stack = stack + rng.normal(0, noise, stack.shape).astype(np.float32)
    return SyntheticStack(stack=stack.astype(np.float32), transforms=mats, reference=scene)


def make_piecewise_stack(
    n_frames: int = 32,
    shape: tuple[int, int] = (256, 256),
    grid: tuple[int, int] = (8, 8),
    max_disp: float = 6.0,
    noise: float = 0.01,
    seed: int = 0,
    n_blobs: int | None = None,
) -> SyntheticStack:
    """Config 3: smooth non-rigid per-frame displacement fields on a patch grid."""
    rng = np.random.default_rng(seed)
    H, W = shape
    gh, gw = grid
    if n_blobs is None:
        n_blobs = max(200, H * W // 650)
    scene = render_scene(rng, shape, n_blobs=n_blobs)
    fields = np.zeros((n_frames, gh, gw, 2), dtype=np.float32)
    # Temporally-correlated, spatially-smooth displacement fields.
    walk = _random_walk(rng, n_frames, 2, step=0.6, maxdev=max_disp * 0.6)
    for t in range(n_frames):
        base = _smooth_noise(rng, (gh, gw, 2), sigma=3, axes=(0, 1)) * 2.0
        fields[t] = np.clip(base + walk[t], -max_disp, max_disp)
    stack = np.empty((n_frames, H, W), dtype=np.float32)
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    for t in range(n_frames):
        flow = upsample_field(fields[t], shape)  # (H, W, 2) in (dx, dy)
        # frame(x) = scene(x - u(x)): sample the scene at shifted coords so
        # the *forward* field maps ref->frame (matches pipeline convention).
        stack[t] = _bilinear(scene, xs - flow[..., 0], ys - flow[..., 1])
    if noise > 0:
        stack = stack + rng.normal(0, noise, stack.shape).astype(np.float32)
    mats = np.tile(np.eye(3, dtype=np.float32), (n_frames, 1, 1))
    return SyntheticStack(stack=stack.astype(np.float32), transforms=mats, fields=fields, reference=scene)


def upsample_field(field: np.ndarray, shape: tuple[int, int]) -> np.ndarray:
    """Bilinearly upsample a (gh, gw, 2) patch-center field to (H, W, 2).

    Patch centers sit at ((i + 0.5) * H / gh - 0.5) so the field is
    defined on a uniform cell-center grid.
    """
    gh, gw, _ = field.shape
    H, W = shape
    ys = (np.arange(H, dtype=np.float32) + 0.5) * gh / H - 0.5
    xs = (np.arange(W, dtype=np.float32) + 0.5) * gw / W - 0.5
    ys = np.clip(ys, 0, gh - 1)
    xs = np.clip(xs, 0, gw - 1)
    y0 = np.floor(ys).astype(np.int32)
    x0 = np.floor(xs).astype(np.int32)
    y1 = np.minimum(y0 + 1, gh - 1)
    x1 = np.minimum(x0 + 1, gw - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    f00 = field[y0][:, x0]
    f01 = field[y0][:, x1]
    f10 = field[y1][:, x0]
    f11 = field[y1][:, x1]
    return (
        f00 * (1 - fy) * (1 - fx)
        + f01 * (1 - fy) * fx
        + f10 * fy * (1 - fx)
        + f11 * fy * fx
    ).astype(np.float32)


def make_drift_stack_3d(
    n_frames: int = 16,
    shape: tuple[int, int, int] = (32, 96, 96),
    max_drift: float = 4.0,
    max_angle: float = 0.03,
    noise: float = 0.01,
    seed: int = 0,
) -> SyntheticStack:
    """Config 5: z-stack volumes under rigid 3D drift (rotation + translation)."""
    rng = np.random.default_rng(seed)
    D, H, W = shape
    scene = render_scene(rng, shape, n_blobs=max(150, D * H * W // 2000))
    center = (np.array([W, H, D], np.float32) - 1) / 2.0  # (x, y, z)
    trans = _random_walk(rng, n_frames, 3, step=0.5, maxdev=max_drift)
    angs = _random_walk(rng, n_frames, 3, step=0.003, maxdev=max_angle)
    mats = np.tile(np.eye(4, dtype=np.float32), (n_frames, 1, 1))
    zs, ys, xs = np.meshgrid(
        np.arange(D, dtype=np.float32),
        np.arange(H, dtype=np.float32),
        np.arange(W, dtype=np.float32),
        indexing="ij",
    )
    pts = np.stack([xs, ys, zs], axis=-1).reshape(-1, 3)
    stack = np.empty((n_frames,) + shape, dtype=np.float32)
    for t in range(n_frames):
        R = _euler(angs[t])
        M = np.eye(4, dtype=np.float32)
        M[:3, :3] = R
        M[:3, 3] = trans[t] + center - R @ center
        mats[t] = M
        Minv = np.linalg.inv(M)
        sp = pts @ Minv[:3, :3].T + Minv[:3, 3]
        stack[t] = _trilinear(scene, sp).reshape(shape)
    if noise > 0:
        stack = stack + rng.normal(0, noise, stack.shape).astype(np.float32)
    return SyntheticStack(stack=stack.astype(np.float32), transforms=mats, reference=scene)


def _euler(angles: np.ndarray) -> np.ndarray:
    ax, ay, az = angles
    cx, sx = np.cos(ax), np.sin(ax)
    cy, sy = np.cos(ay), np.sin(ay)
    cz, sz = np.cos(az), np.sin(az)
    Rx = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]], np.float32)
    Ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]], np.float32)
    Rz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]], np.float32)
    return Rz @ Ry @ Rx


def _trilinear(vol: np.ndarray, pts_xyz: np.ndarray) -> np.ndarray:
    """Sample a (D, H, W) volume at (N, 3) float (x, y, z) points."""
    D, H, W = vol.shape
    x, y, z = pts_xyz[:, 0], pts_xyz[:, 1], pts_xyz[:, 2]
    x0, y0, z0 = np.floor(x).astype(np.int32), np.floor(y).astype(np.int32), np.floor(z).astype(np.int32)
    fx, fy, fz = x - x0, y - y0, z - z0
    out = np.zeros(len(pts_xyz), dtype=np.float32)
    for dz in (0, 1):
        for dy in (0, 1):
            for dx in (0, 1):
                xi = np.clip(x0 + dx, 0, W - 1)
                yi = np.clip(y0 + dy, 0, H - 1)
                zi = np.clip(z0 + dz, 0, D - 1)
                wgt = (
                    (fx if dx else 1 - fx)
                    * (fy if dy else 1 - fy)
                    * (fz if dz else 1 - fz)
                )
                out += vol[zi, yi, xi] * wgt
    inb = (x >= 0) & (x <= W - 1) & (y >= 0) & (y <= H - 1) & (z >= 0) & (z <= D - 1)
    return (out * inb).astype(np.float32)
