"""Resumable chunked processing of long stacks (SURVEY.md §5).

A 10k-frame stack takes minutes even on TPU; the resume manager
checkpoints per-chunk results (transforms/fields + diagnostics) to an
.npz so an interrupted run continues from the last complete chunk
instead of frame 0. Corrected pixel data is *not* checkpointed — it is
cheap to re-warp from the saved transforms, and 10k x 512 x 512 float32
frames would be 10 GB of checkpoint.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

import numpy as np

from kcmc_tpu.obs.log import advise


def file_digest(path: str) -> str:
    """sha256 of a file's bytes — the per-part content checksum guarding
    resume against torn writes and bit rot. Shared by the streaming
    checkpoints here and the serve session journals
    (`serve/journal.py`)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def quarantine_file(path: str) -> str | None:
    """Rename a corrupt checkpoint/journal file to `<path>.corrupt` so
    the evidence survives for post-mortem while the resume path stops
    tripping over it. Returns the quarantine path (None if the rename
    itself failed — e.g. the file vanished)."""
    q = f"{path}.corrupt"
    try:
        os.replace(path, q)
    except OSError:
        return None
    return q


def atomic_savez(path: str, **payload) -> None:
    """Write an .npz with all-or-nothing visibility: a mid-write kill
    (SIGKILL, power loss) leaves either the previous file or the new
    one, never a torn hybrid."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    try:
        np.savez(tmp, **payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _segment_arrays(segments: list[dict]) -> dict:
    return {
        f"c{i}_{k}": np.asarray(v)
        for i, seg in enumerate(segments)
        for k, v in seg.items()
    }


def _split_segments(arrays: dict) -> list[dict]:
    n = 0
    while any(k.startswith(f"c{n}_") for k in arrays):
        n += 1
    return [
        {k[len(f"c{i}_") :]: arrays[k] for k in arrays if k.startswith(f"c{i}_")}
        for i in range(n)
    ]


def save_stream_checkpoint(
    path: str, meta: dict, new_segments: list[dict], part_index: int,
    arrays: dict | None = None,
) -> dict:
    """Persist one streaming-resume checkpoint increment.

    The segments NEW since the last save go into an append-only part
    file (`<path>.partNNNNN.npz`); then the small meta record (run
    signature, done-cursor, output-TIFF writer state, part count) is
    atomically replaced at `path`. Each save is O(new work), not O(run
    so far) — a million-frame run writes each diagnostic array once.
    A crash between the two writes leaves the old meta pointing at the
    old part count; the orphan part is simply overwritten next time.
    Used by MotionCorrector.correct_file.

    Each written part is recorded in ``meta["parts"]`` — a history of
    ``{"done", "writer", "checksum"}`` snapshots, one per part, taken
    at that part's save. The checksum guards the part's content on
    load; the done/writer snapshots are the rewind points that let a
    resume quarantine a corrupt part and restart from the last good
    prefix instead of from zero (see `load_stream_checkpoint`).

    `arrays`: extra ndarrays stored alongside the meta record (e.g. the
    evolving rolling template); returned under meta["arrays"] on load.

    Returns the meta dict as written (with the updated part history).
    """
    meta = dict(meta)
    if new_segments:
        pp = _part_path(path, part_index)
        atomic_savez(pp, **_segment_arrays(new_segments))
        meta["n_parts"] = part_index + 1
        # part_index re-saves overwrite orphans; truncate history to match
        history = list(meta.get("parts", []))[:part_index]
        history.append({
            "done": meta.get("done"),
            "writer": meta.get("writer"),
            "checksum": file_digest(pp),
        })
        meta["parts"] = history
    atomic_savez(path, meta=json.dumps(meta), **(arrays or {}))
    return meta


def _part_path(path: str, i: int) -> str:
    return f"{path}.part{i:05d}.npz"


def load_stream_checkpoint(path: str, fault_plan=None, report=None):
    """Load a streaming-resume checkpoint; returns (meta, segments) or
    None when absent or unusable.

    "No checkpoint" (the path doesn't exist — a fresh run) returns None
    silently. "Corrupt checkpoint" is different and is never silent:

    * an unreadable META record warns with the path and reason, is
      quarantined to ``<path>.corrupt``, and the run restarts;
    * a corrupt/truncated/missing PART file (detected by its recorded
      sha256 content checksum, or by the load itself failing) warns, is
      quarantined to ``<part>.corrupt``, and — when the meta's part
      history has a rewind point — the load returns the last good
      PREFIX: meta rewound to the done-cursor/writer-state snapshotted
      at the preceding part's save, so the rerun recomputes only the
      lost chunk instead of restarting from zero.

    Rewind is skipped (full restart, with a warning) when the bad part
    is the first one, the checkpoint predates part histories, or a
    rolling template is in play (the stored template matches only the
    final cursor — resuming an earlier cursor with a later template
    would diverge from an uninterrupted run).

    `fault_plan` (utils/faults.FaultPlan) lets chaos runs corrupt a
    part on disk just before it is read (``checkpoint:corrupt_part=N``);
    `report` (utils/metrics.RobustnessReport) collects quarantine paths.
    """
    if not os.path.exists(path):
        return None  # no checkpoint: a fresh run, nothing to report
    try:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            extra = {k: z[k] for k in z.files if k != "meta"}
    except Exception as e:
        q = quarantine_file(path)
        advise(
            f"kcmc: resume checkpoint {path} is corrupt "
            f"({type(e).__name__}: {e}); quarantined it"
            f"{f' to {q}' if q else ''} and restarting from scratch",
            stacklevel=2,
        )
        if report is not None and q:
            report.quarantined_parts.append(q)
        return None
    if extra:
        meta["arrays"] = extra
    history = meta.get("parts", [])
    segments: list[dict] = []
    for p in range(int(meta.get("n_parts", 0))):
        pp = _part_path(path, p)
        if fault_plan is not None and fault_plan.take_checkpoint_corruption(p):
            fault_plan.corrupt_file(pp)
        try:
            if p < len(history) and history[p].get("checksum"):
                digest = file_digest(pp)
                want = history[p]["checksum"]
                if digest != want:
                    raise ValueError(
                        f"content checksum mismatch (recorded "
                        f"{want[:12]}…, found {digest[:12]}…)"
                    )
            with np.load(pp, allow_pickle=False) as z:
                part = _split_segments({k: z[k] for k in z.files})
        except Exception as e:
            q = quarantine_file(pp)
            if report is not None and q:
                report.quarantined_parts.append(q)
            rewind = (
                p > 0
                and p - 1 < len(history)
                and history[p - 1].get("writer") is not None
            )
            if rewind and "template" in meta.get("arrays", {}):
                advise(
                    f"kcmc: checkpoint part {pp} is corrupt "
                    f"({type(e).__name__}: {e}); quarantined it, but a "
                    "rolling-template run cannot rewind past it (the "
                    "stored template matches only the final cursor) — "
                    "restarting from scratch",
                    stacklevel=2,
                )
                return None
            if not rewind:
                advise(
                    f"kcmc: checkpoint part {pp} is corrupt "
                    f"({type(e).__name__}: {e}); quarantined it and "
                    "restarting from scratch (no good prefix to resume "
                    "from)",
                    stacklevel=2,
                )
                return None
            prev = history[p - 1]
            advise(
                f"kcmc: checkpoint part {pp} is corrupt "
                f"({type(e).__name__}: {e}); quarantined it and "
                f"resuming from the last good chunk (frame "
                f"{int(prev['done'])})",
                stacklevel=2,
            )
            meta = dict(
                meta,
                done=int(prev["done"]),
                writer=prev["writer"],
                n_parts=p,
                parts=history[:p],
            )
            return meta, segments
        segments.extend(part)
    return meta, segments


class ResumableCorrector:
    """Wraps a MotionCorrector with chunk-level checkpoint/resume.

    Usage:
        rc = ResumableCorrector(mc, "run1.ckpt.npz", chunk_frames=512)
        result = rc.correct(stack)   # safe to kill + rerun: resumes

    The checkpoint stores recovered transforms/fields and diagnostics for
    all completed chunks plus the frame cursor. `correct` returns the
    same CorrectionResult as MotionCorrector (with corrected frames
    re-warped for any chunks restored from the checkpoint).
    """

    def __init__(self, corrector, path: str, chunk_frames: int = 512):
        if getattr(corrector, "template_update_every", 0) > 0:
            # Each resumed chunk calls correct(start_frame=done), which
            # starts from the INITIAL template — the evolving template
            # is not persisted here, so the merged result would
            # silently diverge from a one-shot run. correct_file's
            # checkpoint path carries the template; use that instead.
            raise ValueError(
                "ResumableCorrector does not support rolling template "
                "updates (template_update_every > 0): a resumed chunk "
                "would restart from the initial template and diverge "
                "from a one-shot run. Use "
                "MotionCorrector.correct_file(checkpoint=...), which "
                "persists the evolving template."
            )
        self.corrector = corrector
        self.path = path
        self.chunk_frames = int(chunk_frames)

    # -- checkpoint io -----------------------------------------------------

    def _load(self):
        if not os.path.exists(self.path):
            return None
        with np.load(self.path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            arrays = {k: z[k] for k in z.files if k != "meta"}
        return meta, arrays

    def _save(self, meta: dict, arrays: dict) -> None:
        # atomic replace so a mid-write kill can't corrupt the checkpoint
        atomic_savez(self.path, meta=json.dumps(meta), **arrays)

    # -- main loop ---------------------------------------------------------

    def correct(self, stack: np.ndarray, progress: bool = False):
        stack = np.asarray(stack)
        T = len(stack)
        cfg_sig = repr(self.corrector.config)

        # Pin the reference frame from the FULL stack before chunking:
        # otherwise every chunk would re-select its reference from the
        # chunk itself (frame `lo`, or the chunk-local mean), and the
        # merged transforms would be mutually inconsistent.
        pinned_reference = self.corrector._select_reference(stack)
        orig_reference = self.corrector.reference
        self.corrector.reference = pinned_reference
        try:
            return self._correct_chunks(stack, T, cfg_sig, progress)
        finally:
            self.corrector.reference = orig_reference

    def _correct_chunks(self, stack, T, cfg_sig, progress):
        from kcmc_tpu.corrector import CorrectionResult
        from kcmc_tpu.utils.metrics import StageTimer

        done = 0
        chunks: list[dict] = []
        state = self._load()
        if state is not None:
            meta, arrays = state
            if meta.get("config") == cfg_sig and meta.get("n_frames") == T:
                done = int(meta["done"])
                chunks = _split_segments(arrays)
            # config/stack mismatch: restart from scratch (stale checkpoint)

        timer = StageTimer()
        with timer.stage("resume_restore"):
            restored = done

        while done < T:
            hi = min(done + self.chunk_frames, T)
            with timer.stage("register_batches"):
                # Full stack + bounds: keeps global frame indices so the
                # chunked run reproduces the one-shot run exactly.
                part = self.corrector.correct(stack, start_frame=done, end_frame=hi)
            chunk = dict(part.diagnostics)
            if part.transforms is not None:
                chunk["transform"] = part.transforms
            if part.fields is not None:
                chunk["field"] = part.fields
            chunks.append(chunk)
            done = hi
            arrays = {
                f"c{i}_{k}": v for i, c in enumerate(chunks) for k, v in c.items()
            }
            self._save(
                {
                    "config": cfg_sig,
                    "n_frames": T,
                    "done": done,
                    "n_chunks": len(chunks),
                },
                arrays,
            )
            if progress:
                print(f"[kcmc.resume] {done}/{T} frames checkpointed", flush=True)

        merged = {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}
        transforms = merged.pop("transform", None)
        fields = merged.pop("field", None)

        # Re-warp restored chunks (cheap relative to registration).
        with timer.stage("warp"):
            corrected = self._rewarp(stack, transforms, fields)
        return CorrectionResult(
            corrected=corrected,
            transforms=transforms,
            fields=fields,
            diagnostics=merged,
            timing={**timer.report(n_frames=T), "restored_frames": restored},
        )

    def _rewarp(self, stack, transforms, fields):
        import jax
        import jax.numpy as jnp

        from kcmc_tpu.ops.warp import (
            fast_apply_fields,
            fast_apply_matrix,
            warp_volume,
        )

        if transforms is not None and transforms.shape[-1] == 4:
            fn = jax.jit(jax.vmap(warp_volume))
            return np.asarray(fn(jnp.asarray(stack, jnp.float32), jnp.asarray(transforms)))
        if transforms is not None:
            return fast_apply_matrix(
                jnp.asarray(stack, jnp.float32), jnp.asarray(transforms)
            )
        return fast_apply_fields(
            jnp.asarray(stack, jnp.float32),
            jnp.asarray(fields, jnp.float32),
        )
