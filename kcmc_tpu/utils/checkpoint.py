"""Resumable chunked processing of long stacks (SURVEY.md §5).

A 10k-frame stack takes minutes even on TPU; the resume manager
checkpoints per-chunk results (transforms/fields + diagnostics) to an
.npz so an interrupted run continues from the last complete chunk
instead of frame 0. Corrected pixel data is *not* checkpointed — it is
cheap to re-warp from the saved transforms, and 10k x 512 x 512 float32
frames would be 10 GB of checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile

import numpy as np


def _atomic_savez(path: str, **payload) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp.npz")
    os.close(fd)
    try:
        np.savez(tmp, **payload)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _segment_arrays(segments: list[dict]) -> dict:
    return {
        f"c{i}_{k}": np.asarray(v)
        for i, seg in enumerate(segments)
        for k, v in seg.items()
    }


def _split_segments(arrays: dict) -> list[dict]:
    n = 0
    while any(k.startswith(f"c{n}_") for k in arrays):
        n += 1
    return [
        {k[len(f"c{i}_") :]: arrays[k] for k in arrays if k.startswith(f"c{i}_")}
        for i in range(n)
    ]


def save_stream_checkpoint(
    path: str, meta: dict, new_segments: list[dict], part_index: int,
    arrays: dict | None = None,
) -> None:
    """Persist one streaming-resume checkpoint increment.

    The segments NEW since the last save go into an append-only part
    file (`<path>.partNNNNN.npz`); then the small meta record (run
    signature, done-cursor, output-TIFF writer state, part count) is
    atomically replaced at `path`. Each save is O(new work), not O(run
    so far) — a million-frame run writes each diagnostic array once.
    A crash between the two writes leaves the old meta pointing at the
    old part count; the orphan part is simply overwritten next time.
    Used by MotionCorrector.correct_file.

    `arrays`: extra ndarrays stored alongside the meta record (e.g. the
    evolving rolling template); returned under meta["arrays"] on load.
    """
    if new_segments:
        _atomic_savez(
            _part_path(path, part_index), **_segment_arrays(new_segments)
        )
        meta = dict(meta, n_parts=part_index + 1)
    _atomic_savez(path, meta=json.dumps(meta), **(arrays or {}))


def _part_path(path: str, i: int) -> str:
    return f"{path}.part{i:05d}.npz"


def load_stream_checkpoint(path: str):
    """Load a streaming-resume checkpoint; returns (meta, segments) or
    None when absent/unreadable (including a missing part file)."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            extra = {k: z[k] for k in z.files if k != "meta"}
        if extra:
            meta["arrays"] = extra
        segments: list[dict] = []
        for p in range(int(meta.get("n_parts", 0))):
            with np.load(_part_path(path, p), allow_pickle=False) as z:
                segments.extend(_split_segments({k: z[k] for k in z.files}))
    except Exception:
        return None  # torn/corrupt checkpoint: restart from scratch
    return meta, segments


class ResumableCorrector:
    """Wraps a MotionCorrector with chunk-level checkpoint/resume.

    Usage:
        rc = ResumableCorrector(mc, "run1.ckpt.npz", chunk_frames=512)
        result = rc.correct(stack)   # safe to kill + rerun: resumes

    The checkpoint stores recovered transforms/fields and diagnostics for
    all completed chunks plus the frame cursor. `correct` returns the
    same CorrectionResult as MotionCorrector (with corrected frames
    re-warped for any chunks restored from the checkpoint).
    """

    def __init__(self, corrector, path: str, chunk_frames: int = 512):
        if getattr(corrector, "template_update_every", 0) > 0:
            # Each resumed chunk calls correct(start_frame=done), which
            # starts from the INITIAL template — the evolving template
            # is not persisted here, so the merged result would
            # silently diverge from a one-shot run. correct_file's
            # checkpoint path carries the template; use that instead.
            raise ValueError(
                "ResumableCorrector does not support rolling template "
                "updates (template_update_every > 0): a resumed chunk "
                "would restart from the initial template and diverge "
                "from a one-shot run. Use "
                "MotionCorrector.correct_file(checkpoint=...), which "
                "persists the evolving template."
            )
        self.corrector = corrector
        self.path = path
        self.chunk_frames = int(chunk_frames)

    # -- checkpoint io -----------------------------------------------------

    def _load(self):
        if not os.path.exists(self.path):
            return None
        with np.load(self.path, allow_pickle=False) as z:
            meta = json.loads(str(z["meta"]))
            arrays = {k: z[k] for k in z.files if k != "meta"}
        return meta, arrays

    def _save(self, meta: dict, arrays: dict) -> None:
        # atomic replace so a mid-write kill can't corrupt the checkpoint
        _atomic_savez(self.path, meta=json.dumps(meta), **arrays)

    # -- main loop ---------------------------------------------------------

    def correct(self, stack: np.ndarray, progress: bool = False):
        stack = np.asarray(stack)
        T = len(stack)
        cfg_sig = repr(self.corrector.config)

        # Pin the reference frame from the FULL stack before chunking:
        # otherwise every chunk would re-select its reference from the
        # chunk itself (frame `lo`, or the chunk-local mean), and the
        # merged transforms would be mutually inconsistent.
        pinned_reference = self.corrector._select_reference(stack)
        orig_reference = self.corrector.reference
        self.corrector.reference = pinned_reference
        try:
            return self._correct_chunks(stack, T, cfg_sig, progress)
        finally:
            self.corrector.reference = orig_reference

    def _correct_chunks(self, stack, T, cfg_sig, progress):
        from kcmc_tpu.corrector import CorrectionResult
        from kcmc_tpu.utils.metrics import StageTimer

        done = 0
        chunks: list[dict] = []
        state = self._load()
        if state is not None:
            meta, arrays = state
            if meta.get("config") == cfg_sig and meta.get("n_frames") == T:
                done = int(meta["done"])
                chunks = _split_segments(arrays)
            # config/stack mismatch: restart from scratch (stale checkpoint)

        timer = StageTimer()
        with timer.stage("resume_restore"):
            restored = done

        while done < T:
            hi = min(done + self.chunk_frames, T)
            with timer.stage("register_batches"):
                # Full stack + bounds: keeps global frame indices so the
                # chunked run reproduces the one-shot run exactly.
                part = self.corrector.correct(stack, start_frame=done, end_frame=hi)
            chunk = dict(part.diagnostics)
            if part.transforms is not None:
                chunk["transform"] = part.transforms
            if part.fields is not None:
                chunk["field"] = part.fields
            chunks.append(chunk)
            done = hi
            arrays = {
                f"c{i}_{k}": v for i, c in enumerate(chunks) for k, v in c.items()
            }
            self._save(
                {
                    "config": cfg_sig,
                    "n_frames": T,
                    "done": done,
                    "n_chunks": len(chunks),
                },
                arrays,
            )
            if progress:
                print(f"[kcmc.resume] {done}/{T} frames checkpointed", flush=True)

        merged = {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}
        transforms = merged.pop("transform", None)
        fields = merged.pop("field", None)

        # Re-warp restored chunks (cheap relative to registration).
        with timer.stage("warp"):
            corrected = self._rewarp(stack, transforms, fields)
        return CorrectionResult(
            corrected=corrected,
            transforms=transforms,
            fields=fields,
            diagnostics=merged,
            timing={**timer.report(n_frames=T), "restored_frames": restored},
        )

    def _rewarp(self, stack, transforms, fields):
        import jax
        import jax.numpy as jnp

        from kcmc_tpu.ops.warp import (
            fast_apply_fields,
            fast_apply_matrix,
            warp_volume,
        )

        if transforms is not None and transforms.shape[-1] == 4:
            fn = jax.jit(jax.vmap(warp_volume))
            return np.asarray(fn(jnp.asarray(stack, jnp.float32), jnp.asarray(transforms)))
        if transforms is not None:
            return fast_apply_matrix(
                jnp.asarray(stack, jnp.float32), jnp.asarray(transforms)
            )
        return fast_apply_fields(
            jnp.asarray(stack, jnp.float32),
            jnp.asarray(fields, jnp.float32),
        )
