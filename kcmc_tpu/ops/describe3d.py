"""3D binary descriptors: BRIEF pairs in an anisotropic ellipsoid.

The 3D analogue of ops/describe.py for z-stack registration (config 5).
Pair offsets are Gaussian-distributed with a smaller z extent (z-stacks
are typically shallow and anisotropic). No orientation steering: the 3D
rigid drift regime has small rotations, and upright descriptors are more
discriminative (same trade-off as upright BRIEF for translation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from kcmc_tpu.ops.describe import _pack_bits
from kcmc_tpu.ops.detect import Keypoints
from kcmc_tpu.ops.detect3d import gaussian_blur_3d
from kcmc_tpu.ops.patterns import PATTERN_3D, RADIUS_XY, RADIUS_Z


def _trilinear_sample(vol: jnp.ndarray, xyz: jnp.ndarray) -> jnp.ndarray:
    """Sample (D, H, W) at (..., 3) float (x, y, z), edge-clamped."""
    D, H, W = vol.shape
    x = jnp.clip(xyz[..., 0], 0.0, W - 1.0)
    y = jnp.clip(xyz[..., 1], 0.0, H - 1.0)
    z = jnp.clip(xyz[..., 2], 0.0, D - 1.0)
    x0 = jnp.floor(x); y0 = jnp.floor(y); z0 = jnp.floor(z)
    fx, fy, fz = x - x0, y - y0, z - z0
    x0i = x0.astype(jnp.int32); y0i = y0.astype(jnp.int32); z0i = z0.astype(jnp.int32)
    x1i = jnp.minimum(x0i + 1, W - 1)
    y1i = jnp.minimum(y0i + 1, H - 1)
    z1i = jnp.minimum(z0i + 1, D - 1)
    flat = vol.reshape(-1)

    def g(zi, yi, xi):
        return flat[(zi * H + yi) * W + xi]

    return (
        g(z0i, y0i, x0i) * (1 - fx) * (1 - fy) * (1 - fz)
        + g(z0i, y0i, x1i) * fx * (1 - fy) * (1 - fz)
        + g(z0i, y1i, x0i) * (1 - fx) * fy * (1 - fz)
        + g(z0i, y1i, x1i) * fx * fy * (1 - fz)
        + g(z1i, y0i, x0i) * (1 - fx) * (1 - fy) * fz
        + g(z1i, y0i, x1i) * fx * (1 - fy) * fz
        + g(z1i, y1i, x0i) * (1 - fx) * fy * fz
        + g(z1i, y1i, x1i) * fx * fy * fz
    )


@functools.partial(jax.jit, static_argnames=("blur_sigma",))
def describe_keypoints_3d(
    vol: jnp.ndarray, kps: Keypoints, blur_sigma: float = 1.5
) -> jnp.ndarray:
    """(K, N_WORDS) uint32 3D-BRIEF descriptors for one volume."""
    smooth = gaussian_blur_3d(vol, blur_sigma)
    pattern = jnp.asarray(PATTERN_3D)  # (B, 2, 3)
    pos = kps.xy[:, None, None, :] + pattern[None]  # (K, B, 2, 3)
    vals = _trilinear_sample(smooth, pos)  # (K, B, 2)
    bits = vals[..., 0] < vals[..., 1]
    desc = _pack_bits(bits)
    return jnp.where(kps.valid[:, None], desc, jnp.zeros_like(desc))
