"""3D binary descriptors: BRIEF pairs in an anisotropic ellipsoid.

The 3D analogue of ops/describe.py for z-stack registration (config 5),
built to the same TPU design rule — zero arbitrary pointwise gathers:
one anisotropic patch per keypoint via batched `lax.dynamic_slice`
(the fast native path), an 8-corner trilinear blend of the whole patch
at the keypoint's subpixel fraction, then a constant one-hot matmul
reading all 512 integer-offset samples at once. Pair offsets are
Gaussian-distributed with a smaller z extent (z-stacks are typically
shallow and anisotropic). No orientation steering: the 3D rigid drift
regime has small rotations, and upright descriptors are more
discriminative (same trade-off as upright BRIEF for translation).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.describe import _pack_bits
from kcmc_tpu.ops.detect import Keypoints
from kcmc_tpu.ops.detect3d import gaussian_blur_3d
from kcmc_tpu.ops.patterns import PATTERN_3D, RADIUS_XY, RADIUS_Z

_RX = int(RADIUS_XY)
_RZ = int(RADIUS_Z)
_SIDE_XY = 2 * _RX + 1
_SIDE_Z = 2 * _RZ + 1


def _selection_matrix_3d(pattern: np.ndarray) -> np.ndarray:
    """(L, 512) one-hot matrix reading integer (x, y, z) offsets out of a
    flattened blended patch of shape (_SIDE_Z, _SIDE_XY, _SIDE_XY)."""
    offs = pattern.reshape(-1, 3).astype(np.int64)  # (512, (x, y, z))
    lin = (
        (offs[:, 2] + _RZ) * (_SIDE_XY * _SIDE_XY)
        + (offs[:, 1] + _RX) * _SIDE_XY
        + (offs[:, 0] + _RX)
    )
    sel = np.zeros((_SIDE_Z * _SIDE_XY * _SIDE_XY, offs.shape[0]), np.float32)
    sel[lin, np.arange(offs.shape[0])] = 1.0
    return sel


_SEL_3D = _selection_matrix_3d(PATTERN_3D)


@functools.partial(jax.jit, static_argnames=("blur_sigma",))
def describe_keypoints_3d(
    vol: jnp.ndarray,
    kps: Keypoints,
    blur_sigma: float = 1.5,
    smooth: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(K, N_WORDS) uint32 3D-BRIEF descriptors for one volume.

    `smooth` optionally supplies the pre-blurred volume (the fused
    detection kernel's free-ride output)."""
    if smooth is None:
        smooth = gaussian_blur_3d(vol, blur_sigma)
    K = kps.xy.shape[0]
    # Edge-pad so patches clamp like pointwise trilinear sampling would.
    pz, pxy = _RZ + 1, _RX + 1
    padded = jnp.pad(smooth, ((pz, pz), (pxy, pxy), (pxy, pxy)), mode="edge")
    Pz, Pxy = 2 * _RZ + 2, 2 * _RX + 2

    x0 = jnp.floor(kps.xy[:, 0])
    y0 = jnp.floor(kps.xy[:, 1])
    z0 = jnp.floor(kps.xy[:, 2])
    # patch origin in padded coords: floor(kp) - r + (r + 1) = floor(kp) + 1
    oz = z0.astype(jnp.int32) + 1
    oy = y0.astype(jnp.int32) + 1
    ox = x0.astype(jnp.int32) + 1
    raw = jax.vmap(
        lambda z, y, x: lax.dynamic_slice(padded, (z, y, x), (Pz, Pxy, Pxy))
    )(oz, oy, ox)  # (K, Pz, Pxy, Pxy)

    fx = (kps.xy[:, 0] - x0)[:, None, None, None]
    fy = (kps.xy[:, 1] - y0)[:, None, None, None]
    fz = (kps.xy[:, 2] - z0)[:, None, None, None]
    c = raw
    pb = (
        (1 - fz) * (1 - fy) * (1 - fx) * c[:, :-1, :-1, :-1]
        + (1 - fz) * (1 - fy) * fx * c[:, :-1, :-1, 1:]
        + (1 - fz) * fy * (1 - fx) * c[:, :-1, 1:, :-1]
        + (1 - fz) * fy * fx * c[:, :-1, 1:, 1:]
        + fz * (1 - fy) * (1 - fx) * c[:, 1:, :-1, :-1]
        + fz * (1 - fy) * fx * c[:, 1:, :-1, 1:]
        + fz * fy * (1 - fx) * c[:, 1:, 1:, :-1]
        + fz * fy * fx * c[:, 1:, 1:, 1:]
    )  # (K, side_z, side_xy, side_xy)

    vals = jnp.matmul(
        pb.reshape(K, -1), jnp.asarray(_SEL_3D),
        precision=lax.Precision.HIGHEST,
    ).reshape(K, -1, 2)
    bits = vals[..., 0] < vals[..., 1]
    desc = _pack_bits(bits)
    return jnp.where(kps.valid[:, None], desc, jnp.zeros_like(desc))


@functools.partial(
    jax.jit, static_argnames=("blur_sigma", "use_pallas", "interpret")
)
def describe_keypoints_3d_batch(
    vols: jnp.ndarray,
    kps: Keypoints,
    blur_sigma: float = 1.5,
    use_pallas: bool = False,
    interpret: bool = False,
    smooth: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(B, K, N_WORDS) descriptors for a (B, D, H, W) batch of volumes.

    The Pallas route cuts each keypoint's slab as its own
    Element-indexed block (dynamic z/y block starts from scalar
    prefetch — VMEM never holds the volume), blends in-plane per slice,
    and completes the trilinear blend as a z-lerp of adjacent blended
    slices — exactly the jnp path's 8-corner blend, decomposed.
    Selection then runs keypoint-first through the split-precision
    one-hot matmul (see ops/describe._onehot_select).
    """
    if not use_pallas:
        if smooth is not None:
            return jax.vmap(
                lambda v, k, s: describe_keypoints_3d(
                    v, k, blur_sigma=blur_sigma, smooth=s
                )
            )(vols, kps, smooth)
        return jax.vmap(
            lambda v, k: describe_keypoints_3d(v, k, blur_sigma=blur_sigma)
        )(vols, kps)

    from kcmc_tpu.ops.describe import _onehot_select
    from kcmc_tpu.ops.pallas_patch import extract_blended_3d

    B, D, H, W = vols.shape
    K = kps.xy.shape[1]
    if smooth is None:
        smooth = jax.vmap(lambda v: gaussian_blur_3d(v, blur_sigma))(vols)
    pz, pxy = _RZ + 1, _RX + 1
    padded = jnp.pad(
        smooth, ((0, 0), (pz, pz), (pxy, pxy), (pxy, pxy)), mode="edge"
    )
    Pz, Pxy = 2 * _RZ + 2, 2 * _RX + 2
    pb = extract_blended_3d(padded, kps.xy, Pz, Pxy, interpret=interpret)
    # (B, K, SIDE_Z, SIDE_XY, SIDE_XY) trilinear-blended patches

    vals = _onehot_select(pb.reshape(B, K, -1), jnp.asarray(_SEL_3D))
    vals = vals.reshape(B, K, -1, 2)
    bits = vals[..., 0] < vals[..., 1]
    desc = _pack_bits(bits)
    return jnp.where(kps.valid[..., None], desc, jnp.zeros_like(desc))
