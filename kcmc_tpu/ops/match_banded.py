"""Spatially-banded KNN matching: exploit bounded drift to skip the
dense (K, K) Hamming matrix.

Motion correction drift is bounded — a frame keypoint can only match
reference keypoints within the drift radius R (SURVEY.md §2, the KNN
matcher row; `BASELINE.json configs[1]`'s ~2k-matches regime is where
the dense matrix hurts: at K=4096 it costs K² = 16.7M descriptor pairs
and ~2 GB of HBM per 32-frame batch, capping both the match stage and
the pipeline's batch overlap). The banded matcher restricts each query
keypoint to the reference keypoints within a window that covers its
±R motion envelope:

* Reference keypoints are bucketed ONCE per batch into fixed-capacity
  spatial sub-buckets (static shapes: capacity overflow drops the
  rarest excess keypoints, masked not resized).
* Query keypoints are bucketed per frame into `tile`-sized tiles by a
  single stable argsort over tile ids — all queries in a tile share one
  candidate set, so the Hamming work stays one MXU matmul per tile:
  (C_q, N_BITS) x (N_BITS, C_cand), batched over tiles. With the
  default geometry at K=4096 on 512² that is ~4x fewer descriptor
  pairs and ~4x less HBM than the dense matrix, at full M=128 MXU
  tile utilization.

When to use (measured, DESIGN.md "Banded matching" round 4): at
K<=4096 the dense matcher is ALREADY faster wall-clock on the v5e
(0.62 vs 0.95 ms/frame — the dense matmul is MXU-efficient and the
banded form pays bucketing/reduction overhead), so `match_radius` is
off by default. Banding is the SCALE path: the dense (B, K, K) matrix
is HBM-infeasible past K~8192 (34 GB at K=16384, batch 32), while the
banded candidate set grows linearly in K.
* The candidate window of tile t covers [t·S - pad, (t+1)·S + pad)
  per axis with pad = ceil(R / sub)·sub ≥ R, so every reference
  keypoint within R of ANY query in the tile is a candidate — recall
  loss comes only from capacity overflow (bounded by the `slack`
  knob), never from geometry.
* The mutual-nearest test runs over the same banded universe: for each
  reference keypoint, its best query across the (statically known ≤4)
  tiles whose window contains its sub-bucket. This is the banded
  semantic — a reference keypoint's competitors are the queries within
  its motion envelope, which is exactly the set that could legitimately
  claim it.

Returns the same `Matches` contract as the dense `ops.match.knn_match`,
in original query-slot order, so the backend's tail is agnostic to
which matcher ran.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.describe import N_BITS
from kcmc_tpu.ops.dispatch import segment_by_key
from kcmc_tpu.ops.match import Matches, pm1_dtype, unpack_pm1

_IBIG = jnp.int32(1 << 16)  # sentinel distance (> N_BITS), int32 flavor


class BandedGeometry(NamedTuple):
    """Static banded-matcher geometry for one (shape, radius, K) tuple.

    Everything here is plain Python/NumPy — computed at trace time, baked
    into the compiled program as constants.
    """

    shape: tuple  # (H, W)
    tile: int  # query tile side, px
    sub: int  # reference sub-bucket side, px
    th: int  # query tile grid rows
    tw: int  # query tile grid cols
    gh: int  # ref sub-bucket grid rows
    gw: int  # ref sub-bucket grid cols
    cq: int  # query slots per tile
    csub: int  # ref slots per sub-bucket
    n_win: int  # candidate window side, in sub-buckets
    window_sub: np.ndarray  # (T, n_win²) int32 sub-bucket id per window slot
    window_ok: np.ndarray  # (T, n_win²) bool — window slot inside the grid
    rev_tile: np.ndarray  # (G, S) int32 serving tile ids per sub-bucket
    rev_wpos: np.ndarray  # (G, S) int32 window position of the sub-bucket
    rev_ok: np.ndarray  # (G, S) bool


def make_geometry(
    shape: tuple,
    radius: float,
    n_query: int,
    n_ref: int,
    tile: int = 64,
    slack: float = 2.0,
    nms_tile: int | None = None,
) -> BandedGeometry:
    """Derive the static geometry: grid sizes, capacities, the per-tile
    candidate window, and the per-sub-bucket reverse (serving-tile) map.

    Bucket capacities are `slack` times the mean occupancy (keypoints
    beyond a bucket's capacity are dropped — the bounded recall-loss
    knob). `nms_tile` (the detector's spatial-spreading tile: at most
    one keypoint per nms_tile² cell) caps capacities at the hard
    occupancy bound NMS guarantees, shrinking buckets for free when the
    statistical estimate overshoots it.
    """
    H, W = int(shape[0]), int(shape[1])
    tile = int(tile)
    if tile < 16:
        raise ValueError(f"match_tile must be >= 16, got {tile}")
    if radius <= 0:
        raise ValueError(f"match_radius must be positive, got {radius}")
    # Finer sub-buckets for small radii shrink the candidate window
    # (fewer wasted candidates); tile//2 keeps per-bucket capacity
    # MXU-reasonable for larger radii.
    if tile % 4:
        # sub-bucket sides are tile//4 or tile//2 and the window
        # arithmetic assumes tile == (tile//sub)*sub exactly — a
        # non-divisible tile would misalign the candidate window by
        # (tile mod sub) px per tile and silently violate the
        # radius-coverage guarantee.
        raise ValueError(f"match tile must be a multiple of 4, got {tile}")
    sub = tile // 4 if radius <= tile // 4 else tile // 2
    pad_subs = int(math.ceil(radius / sub))
    r = tile // sub  # sub-buckets per tile side
    n_win = r + 2 * pad_subs

    th, tw = -(-H // tile), -(-W // tile)
    gh, gw = -(-H // sub), -(-W // sub)
    T, G = th * tw, gh * gw

    def cap(n, cell):
        mean = n * cell * cell / (H * W)
        c = int(math.ceil(slack * mean))
        c = max(8, -(-c // 8) * 8)  # >= 8, rounded up to 8
        if nms_tile is not None and nms_tile >= 1:
            # NMS occupancy ceiling: a window of `cell` px intersects at
            # most floor(cell/nms)+1 origin-aligned NMS cells per axis
            # (NOT ceil(cell/nms), which undercounts whenever nms_tile
            # doesn't divide the cell and would clamp capacity below
            # real occupancy).
            hard = (cell // nms_tile + 1) ** 2
            c = min(c, max(hard, 1))
        return c

    cq = cap(n_query, tile)
    csub = cap(n_ref, sub)

    # Candidate window: for tile (ty, tx), the n_win x n_win block of
    # sub-buckets starting at (ty*r - pad, tx*r - pad).
    tys, txs = np.divmod(np.arange(T), tw)
    wy = tys[:, None] * r - pad_subs + np.arange(n_win)[None, :]  # (T, n_win)
    wx = txs[:, None] * r - pad_subs + np.arange(n_win)[None, :]
    oky = (wy >= 0) & (wy < gh)
    okx = (wx >= 0) & (wx < gw)
    sub_id = (
        np.clip(wy, 0, gh - 1)[:, :, None] * gw
        + np.clip(wx, 0, gw - 1)[:, None, :]
    )  # (T, n_win, n_win)
    window_sub = sub_id.reshape(T, n_win * n_win).astype(np.int32)
    window_ok = (oky[:, :, None] & okx[:, None, :]).reshape(T, n_win * n_win)

    # Reverse map: which tiles' windows contain sub-bucket (sy, sx)?
    # ty*r - pad <= sy < ty*r - pad + n_win, i.e. ty in
    # [ceil((sy + pad - n_win + 1)/r), floor((sy + pad)/r)] — at most
    # ceil(n_win / r) values per axis.
    S_axis = -(-n_win // r)
    sys_, sxs = np.divmod(np.arange(G), gw)

    def serving(s):  # (G,) -> ids (G, S_axis), ok (G, S_axis)
        lo = -(-(s + pad_subs - n_win + 1) // r)
        ids = lo[:, None] + np.arange(S_axis)[None, :]
        ok = ids * r - pad_subs <= s[:, None]  # window still contains s
        return ids, ok

    ty_ids, ty_ok = serving(sys_)
    tx_ids, tx_ok = serving(sxs)
    ty_ok &= (ty_ids >= 0) & (ty_ids < th)
    tx_ok &= (tx_ids >= 0) & (tx_ids < tw)
    rev_tile = (
        np.clip(ty_ids, 0, th - 1)[:, :, None] * tw
        + np.clip(tx_ids, 0, tw - 1)[:, None, :]
    ).reshape(G, S_axis * S_axis).astype(np.int32)
    rev_ok = (ty_ok[:, :, None] & tx_ok[:, None, :]).reshape(G, -1)
    # Window position of sub-bucket s inside serving tile t's window:
    # (sy - (ty*r - pad)) * n_win + (sx - (tx*r - pad)).
    wpy = sys_[:, None] - (np.clip(ty_ids, 0, th - 1) * r - pad_subs)
    wpx = sxs[:, None] - (np.clip(tx_ids, 0, tw - 1) * r - pad_subs)
    rev_wpos = (
        wpy[:, :, None] * n_win + wpx[:, None, :]
    ).reshape(G, -1).astype(np.int32)
    rev_wpos = np.clip(rev_wpos, 0, n_win * n_win - 1)

    return BandedGeometry(
        shape=(H, W), tile=tile, sub=sub, th=th, tw=tw, gh=gh, gw=gw,
        cq=cq, csub=csub, n_win=n_win,
        window_sub=window_sub, window_ok=window_ok,
        rev_tile=rev_tile, rev_wpos=rev_wpos, rev_ok=rev_ok,
    )


def _bucketize(xy, valid, cell: int, gh: int, gw: int, cap: int):
    """Assign keypoints to a (gh, gw) grid of `cell`-px buckets with
    fixed capacity via one stable argsort.

    Returns slot_idx (G, cap) int32 — keypoint index per bucket slot —
    and slot_ok (G, cap) bool. Keypoints beyond a bucket's capacity are
    dropped (their slots simply don't exist); invalid keypoints sort to
    a sentinel bucket past the grid.
    """
    G = gh * gw
    cx = (xy[:, 0] // cell).astype(jnp.int32)
    cy = (xy[:, 1] // cell).astype(jnp.int32)
    # Keypoints outside the grid (cannot occur for detector output, but
    # callers may pass arbitrary xy) are dropped rather than clamped —
    # clamping would hand a border tile candidates arbitrarily far from
    # the keypoint's true position, violating the radius contract.
    in_grid = (cx >= 0) & (cx < gw) & (cy >= 0) & (cy < gh)
    cid = jnp.where(
        valid & in_grid,
        jnp.clip(cy, 0, gh - 1) * gw + jnp.clip(cx, 0, gw - 1),
        G,
    )
    # stable segment-by-key: preserves detection-score order in-bucket
    return segment_by_key(cid, G, cap)


class BandedRef(NamedTuple):
    """Reference-side banded structure (template keypoints bucketed).

    Built once per batch dispatch, outside the per-frame vmap — the
    template is fixed, so every frame in the batch shares it.
    """

    cand_pm1: jnp.ndarray  # (T, C, N_BITS) ±1 candidate descriptors
    # (bf16/f32/int8 per the match precision — both sides of the tile
    # matmul unpack with the same dtype)
    cand_idx: jnp.ndarray  # (T, C) int32 global ref keypoint per slot
    cand_ok: jnp.ndarray  # (T, C) bool
    ref_sub: jnp.ndarray  # (Kr,) int32 sub-bucket of each ref keypoint
    ref_slot: jnp.ndarray  # (Kr,) int32 slot within that sub-bucket


def build_banded_ref(
    geom: BandedGeometry, ref_xy, ref_desc, ref_valid,
    precision: str = "bf16",
) -> BandedRef:
    Kr = ref_xy.shape[0]
    G = geom.gh * geom.gw
    # Zero descriptors are the invalid sentinel (see knn_match).
    ref_valid = ref_valid & jnp.any(ref_desc != 0, axis=-1)
    slot_idx, slot_ok = _bucketize(
        ref_xy, ref_valid, geom.sub, geom.gh, geom.gw, geom.csub
    )  # (G, csub)
    # Inverse map: ref keypoint -> (sub-bucket, slot). Overflow-dropped
    # keypoints keep the scatter default (sub-bucket G, slot 0) and can
    # never be selected as a candidate, so the mutual lookup for them is
    # never consulted.
    flat = jnp.where(slot_ok, slot_idx, Kr).reshape(-1)
    subs = jnp.repeat(
        jnp.arange(G, dtype=jnp.int32), geom.csub
    )
    slots_in = jnp.tile(jnp.arange(geom.csub, dtype=jnp.int32), G)
    ref_sub = jnp.full((Kr + 1,), G, jnp.int32).at[flat].set(subs)[:Kr]
    ref_slot = jnp.zeros((Kr + 1,), jnp.int32).at[flat].set(slots_in)[:Kr]

    wsub = jnp.asarray(geom.window_sub)  # (T, n_win²)
    wok = jnp.asarray(geom.window_ok)
    cand_idx = slot_idx[wsub].reshape(wsub.shape[0], -1)  # (T, W²·csub)
    cand_ok = (slot_ok[wsub] & wok[:, :, None]).reshape(wsub.shape[0], -1)
    cand_pm1 = unpack_pm1(ref_desc[cand_idx], pm1_dtype(precision))
    return BandedRef(
        cand_pm1=cand_pm1, cand_idx=cand_idx, cand_ok=cand_ok,
        ref_sub=ref_sub, ref_slot=ref_slot,
    )


def banded_match(
    geom: BandedGeometry,
    bref: BandedRef,
    q_desc,
    q_xy,
    q_valid,
    ratio: float = 0.85,
    max_dist: int = 80,
    mutual: bool = True,
    precision: str = "bf16",
) -> Matches:
    """2-NN Hamming match of one frame's keypoints against the banded
    reference. Same validity semantics as `knn_match` (distance cap,
    Lowe ratio, optional mutual-nearest), with the candidate universe
    restricted to each query's motion envelope. `precision` selects
    the tile matmul's MXU route (ops/match.MATCH_PRECISIONS — exact in
    every variant) and must match the `build_banded_ref` call's.
    """
    K = q_desc.shape[0]
    T = geom.th * geom.tw
    # Zero descriptors are the invalid sentinel — same rule as the
    # dense matcher (see knn_match): they must never match.
    q_valid = q_valid & jnp.any(q_desc != 0, axis=-1)
    q_slot_idx, q_slot_ok = _bucketize(
        q_xy, q_valid, geom.tile, geom.th, geom.tw, geom.cq
    )  # (T, cq)
    qd = unpack_pm1(q_desc[q_slot_idx], pm1_dtype(precision))

    # One MXU matmul per tile, batched: exact integer dot products
    # (±1 products, sums <= N_BITS fit both the f32 and the i32
    # accumulator without rounding), same identity as the dense
    # matcher's hamming_matrix_mxu — int8 rides the 2x MXU path.
    if precision == "int8":
        s = lax.dot_general(
            qd, bref.cand_pm1,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32,
        )  # (T, cq, C)
        d = (N_BITS - s) >> 1
    else:
        s = lax.dot_general(
            qd, bref.cand_pm1,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (T, cq, C)
        d = ((N_BITS - s) * 0.5).astype(jnp.int32)
    mask = q_slot_ok[:, :, None] & bref.cand_ok[:, None, :]
    D = jnp.where(mask, d, _IBIG)

    best = jnp.min(D, axis=-1)  # (T, cq)
    arg = jnp.argmin(D, axis=-1).astype(jnp.int32)
    C = D.shape[-1]
    taken = arg[:, :, None] == jnp.arange(C, dtype=jnp.int32)[None, None, :]
    second = jnp.min(jnp.where(taken, _IBIG, D), axis=-1)
    ridx = jnp.take_along_axis(bref.cand_idx, arg, axis=1)  # (T, cq) global

    ok = (best < max_dist) & (
        best.astype(jnp.float32) < ratio * second.astype(jnp.float32)
    )
    ok = ok & q_slot_ok & (best < jnp.int32(N_BITS + 1))

    if mutual:
        # Reverse pass, TPU-shaped: reduce FIRST, gather AFTER. For
        # every (tile, window-slot) pair, the best query in that tile
        # for each of the slot's csub candidates is a plain reduction
        # over the already-computed D — no indexing. Each sub-bucket is
        # then the min over its <= S statically-known (tile, window-
        # slot) sources: S row gathers with CONSTANT indices. (A
        # per-sub-bucket advanced-indexing gather over D lowers to
        # element-level scatter/gather on TPU — measured 6.1 ms/frame,
        # 10x this formulation.)
        G = geom.gh * geom.gw
        csub = geom.csub
        n_w2 = geom.n_win * geom.n_win
        # Packed key: distance in the high bits, query index in the
        # low — one min recovers (best distance, lowest query on ties),
        # the same tie order as the dense matcher's argmin. The
        # multiplier is the smallest power of two > K (static), and the
        # distance field is capped at DCAP (> N_BITS, so every real
        # distance keeps its order and masked slots stay maximal) to
        # keep DCAP * mult + K within int32 at any K.
        mult = 1 << int(K + 1).bit_length()
        dcap = jnp.int32(2 * N_BITS)
        if (2 * N_BITS + 1) * mult + K >= 2**31:
            raise ValueError(
                f"banded mutual packing overflows int32 at K={K}"
            )
        q_global = jnp.broadcast_to(
            q_slot_idx[:, :, None, None], (T, geom.cq, n_w2, csub)
        )
        packed = (
            jnp.minimum(D.reshape(T, geom.cq, n_w2, csub), dcap) * mult
            + q_global
        )
        sentinel = jnp.int32((2 * N_BITS) * mult + mult - 1)
        tw_min = jnp.min(packed, axis=1).reshape(T * n_w2, csub)
        S = geom.rev_tile.shape[1]
        # Static source rows: flat (tile, window-slot) index per
        # sub-bucket and serving slot — trace-time constants.
        src = geom.rev_tile * n_w2 + geom.rev_wpos  # (G, S) numpy
        rev = jnp.full((G, csub), sentinel)
        for si in range(S):
            rows = tw_min[jnp.asarray(src[:, si])]  # (G, csub)
            rows = jnp.where(
                jnp.asarray(geom.rev_ok[:, si])[:, None], rows, sentinel
            )
            rev = jnp.minimum(rev, rows)
        rev_q = rev % mult  # the claiming query's global index
        rsub = bref.ref_sub[ridx]  # (T, cq); G for overflow-dropped refs
        rslot = bref.ref_slot[ridx]
        # Overflow-dropped refs can't be candidates, so rsub < G
        # wherever ok can be True — the clip only guards the gather.
        claimed = rev_q[jnp.minimum(rsub, G - 1), rslot]
        ok = ok & (claimed == q_slot_idx)

    # Scatter per-slot results back to original query order. Every valid
    # slot holds a distinct query index; invalid slots route to a
    # scratch row past the end. Dropped/overflowed queries keep the
    # defaults (valid=False).
    dest = jnp.where(q_slot_ok, q_slot_idx, K).reshape(-1)
    out_idx = jnp.zeros((K + 1,), jnp.int32).at[dest].set(ridx.reshape(-1))
    out_dist = jnp.full((K + 1,), _IBIG).at[dest].set(best.reshape(-1))
    out_second = jnp.full((K + 1,), _IBIG).at[dest].set(second.reshape(-1))
    out_ok = jnp.zeros((K + 1,), bool).at[dest].set(ok.reshape(-1))
    return Matches(
        idx=out_idx[:K],
        dist=out_dist[:K],
        second=out_second[:K],
        valid=out_ok[:K],
    )
