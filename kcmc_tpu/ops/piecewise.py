"""Piecewise-rigid (patch-grid) non-rigid correction — judged config 3.

SURVEY.md §2: "8x8 patch grid, per-patch consensus, smoothed displacement
field". TPU-native structure:

1. A *global* translation RANSAC (generous threshold) rejects gross
   mismatches and anchors patches with little data.
2. Per-patch translation consensus runs as one extra vmap axis over the
   grid's patches: each patch sees the matches within ~1.5 patch radii
   of its center (soft membership mask) and votes with a small fixed
   hypothesis budget.
3. Patch displacements blend with the global displacement by inlier
   mass (few-match patches fall back to the global motion), then the
   (gh, gw, 2) field is smoothed by a normalized Gaussian and bilinearly
   upsampled to a dense flow for `warp_frame_flow`.

Field convention matches the synthetic generator and the matcher: the
field u lives on reference coordinates, u(r) = position-in-frame(r) - r,
and the corrected frame is frame(p + u(p)).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kcmc_tpu.models.transforms import MODELS
from kcmc_tpu.ops.ransac import ransac_estimate


class FieldResult(NamedTuple):
    field: jnp.ndarray  # (gh, gw, 2) patch-center displacements
    flow: jnp.ndarray  # (H, W, 2) dense upsampled flow
    n_inliers: jnp.ndarray  # () int32 — global-stage inliers
    rms_residual: jnp.ndarray  # () float32 — global-stage rms


def patch_centers(grid: tuple[int, int], shape: tuple[int, int]) -> jnp.ndarray:
    """(gh, gw, 2) cell-center (x, y) coordinates of the patch grid."""
    gh, gw = grid
    H, W = shape
    cy = (jnp.arange(gh, dtype=jnp.float32) + 0.5) * H / gh - 0.5
    cx = (jnp.arange(gw, dtype=jnp.float32) + 0.5) * W / gw - 0.5
    return jnp.stack(jnp.meshgrid(cx, cy, indexing="xy"), axis=-1)  # (gh, gw, 2)


def _gauss1d(sigma: float, radius: int) -> jnp.ndarray:
    x = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / max(sigma, 1e-6)) ** 2)
    return k / jnp.sum(k)


def smooth_field(field: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Normalized separable Gaussian smoothing of a (gh, gw, 2) field."""
    if sigma <= 0:
        return field
    radius = max(1, int(2.0 * sigma + 0.5))
    k = _gauss1d(sigma, radius)
    ones = jnp.ones(field.shape[:2], field.dtype)
    from jax import lax

    def blur(chan):
        c = chan[None, None]  # NCHW
        kh = k[None, None, :, None]
        kw = k[None, None, None, :]
        c = lax.conv_general_dilated(c, kh, (1, 1), [(radius, radius), (0, 0)])
        c = lax.conv_general_dilated(c, kw, (1, 1), [(0, 0), (radius, radius)])
        return c[0, 0]

    num = jnp.stack([blur(field[..., i]) for i in range(field.shape[-1])], axis=-1)
    den = blur(ones)[..., None]
    return num / jnp.maximum(den, 1e-6)


def upsample_field(field: jnp.ndarray, shape: tuple[int, int]) -> jnp.ndarray:
    """Bilinear cell-centered upsample of (gh, gw, 2) -> (H, W, 2)."""
    gh, gw, _ = field.shape
    H, W = shape
    ys = jnp.clip((jnp.arange(H, dtype=jnp.float32) + 0.5) * gh / H - 0.5, 0, gh - 1)
    xs = jnp.clip((jnp.arange(W, dtype=jnp.float32) + 0.5) * gw / W - 0.5, 0, gw - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, gh - 1)
    x1 = jnp.minimum(x0 + 1, gw - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    f00 = field[y0][:, x0]
    f01 = field[y0][:, x1]
    f10 = field[y1][:, x0]
    f11 = field[y1][:, x1]
    return (
        f00 * (1 - fy) * (1 - fx)
        + f01 * (1 - fy) * fx
        + f10 * fy * (1 - fx)
        + f11 * fy * fx
    )


@functools.partial(
    jax.jit,
    static_argnames=("grid", "shape", "n_global_hyps", "patch_hyps", "smooth_sigma"),
)
def estimate_field(
    src: jnp.ndarray,  # (N, 2) reference keypoint positions of matches
    dst: jnp.ndarray,  # (N, 2) frame keypoint positions of matches
    valid: jnp.ndarray,  # (N,) bool
    key: jnp.ndarray,
    grid: tuple[int, int],
    shape: tuple[int, int],
    n_global_hyps: int = 64,
    patch_hyps: int = 32,
    global_threshold: float = 8.0,
    patch_threshold: float = 2.0,
    prior: float = 8.0,
    smooth_sigma: float = 0.7,
) -> FieldResult:
    """Per-patch consensus displacement field for one frame."""
    gh, gw = grid
    translation = MODELS["translation"]
    kg, kp = jax.random.split(key)

    # 1. Global stage: robust overall translation, generous threshold.
    gres = ransac_estimate(
        translation, src, dst, valid, kg,
        n_hypotheses=n_global_hyps, threshold=global_threshold,
    )
    g_t = gres.transform[:2, 2]  # global displacement
    ok = gres.inlier_mask  # matches consistent with *some* coherent motion

    centers = patch_centers(grid, shape).reshape(-1, 2)  # (P, 2)
    ph, pw = shape[0] / gh, shape[1] / gw
    # Soft membership: matches within 1.5 patch sizes of a center participate
    # (overlap keeps the field smooth and gives edge patches enough data).
    reach = 1.5 * jnp.float32(max(ph, pw))

    def per_patch(center, k):
        d2 = jnp.sum((src - center) ** 2, axis=-1)
        member = ok & (d2 < reach * reach)
        res = ransac_estimate(
            translation, src, dst, member, k,
            n_hypotheses=patch_hyps, threshold=patch_threshold,
        )
        disp = res.transform[:2, 2]
        mass = res.n_inliers.astype(jnp.float32)
        # Blend toward the global displacement when the patch has few inliers.
        lam = mass / (mass + prior)
        return lam * disp + (1.0 - lam) * g_t

    pkeys = jax.random.split(kp, centers.shape[0])
    disps = jax.vmap(per_patch)(centers, pkeys)  # (P, 2)
    field = disps.reshape(gh, gw, 2)
    field = smooth_field(field, smooth_sigma)
    flow = upsample_field(field, shape)
    return FieldResult(
        field=field, flow=flow, n_inliers=gres.n_inliers, rms_residual=gres.rms_residual
    )
