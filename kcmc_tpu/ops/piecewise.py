"""Piecewise-rigid (patch-grid) non-rigid correction — judged config 3.

SURVEY.md §2: "8x8 patch grid, per-patch consensus, smoothed displacement
field". TPU-native structure:

1. A *global* translation RANSAC (generous threshold) rejects gross
   mismatches and anchors patches with little data.
2. Per-patch translation consensus runs as one extra vmap axis over the
   grid's patches: each patch sees the matches within ~1.5 patch radii
   of its center (soft membership mask) and votes with a small fixed
   hypothesis budget.
3. Patch displacements blend with the global displacement by inlier
   mass (few-match patches fall back to the global motion), then the
   (gh, gw, 2) field is smoothed by a normalized Gaussian and bilinearly
   upsampled to a dense flow for `warp_frame_flow`.

Field convention matches the synthetic generator and the matcher: the
field u lives on reference coordinates, u(r) = position-in-frame(r) - r,
and the corrected frame is frame(p + u(p)).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kcmc_tpu.models.transforms import MODELS
from kcmc_tpu.ops.ransac import ransac_estimate


class FieldResult(NamedTuple):
    field: jnp.ndarray  # (gh, gw, 2) patch-center displacements
    flow: jnp.ndarray  # (H, W, 2) dense upsampled flow
    n_inliers: jnp.ndarray  # () int32 — global-stage inliers
    rms_residual: jnp.ndarray  # () float32 — global-stage rms


def patch_centers(grid: tuple[int, int], shape: tuple[int, int]) -> jnp.ndarray:
    """(gh, gw, 2) cell-center (x, y) coordinates of the patch grid."""
    gh, gw = grid
    H, W = shape
    cy = (jnp.arange(gh, dtype=jnp.float32) + 0.5) * H / gh - 0.5
    cx = (jnp.arange(gw, dtype=jnp.float32) + 0.5) * W / gw - 0.5
    return jnp.stack(jnp.meshgrid(cx, cy, indexing="xy"), axis=-1)  # (gh, gw, 2)


def _gauss1d(sigma: float, radius: int) -> jnp.ndarray:
    x = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / max(sigma, 1e-6)) ** 2)
    return k / jnp.sum(k)


def smooth_field(field: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Normalized separable Gaussian smoothing of a (gh, gw, 2) field."""
    if sigma <= 0:
        return field
    radius = max(1, int(2.0 * sigma + 0.5))
    k = _gauss1d(sigma, radius)
    ones = jnp.ones(field.shape[:2], field.dtype)
    from jax import lax

    def blur(chan):
        c = chan[None, None]  # NCHW
        kh = k[None, None, :, None]
        kw = k[None, None, None, :]
        c = lax.conv_general_dilated(c, kh, (1, 1), [(radius, radius), (0, 0)])
        c = lax.conv_general_dilated(c, kw, (1, 1), [(0, 0), (radius, radius)])
        return c[0, 0]

    num = jnp.stack([blur(field[..., i]) for i in range(field.shape[-1])], axis=-1)
    den = blur(ones)[..., None]
    return num / jnp.maximum(den, 1e-6)


def upsample_field(field: jnp.ndarray, shape: tuple[int, int]) -> jnp.ndarray:
    """Bilinear cell-centered upsample of (gh, gw, 2) -> (H, W, 2)."""
    gh, gw, _ = field.shape
    H, W = shape
    ys = jnp.clip((jnp.arange(H, dtype=jnp.float32) + 0.5) * gh / H - 0.5, 0, gh - 1)
    xs = jnp.clip((jnp.arange(W, dtype=jnp.float32) + 0.5) * gw / W - 0.5, 0, gw - 1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, gh - 1)
    x1 = jnp.minimum(x0 + 1, gw - 1)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    f00 = field[y0][:, x0]
    f01 = field[y0][:, x1]
    f10 = field[y1][:, x0]
    f11 = field[y1][:, x1]
    return (
        f00 * (1 - fy) * (1 - fx)
        + f01 * (1 - fy) * fx
        + f10 * fy * (1 - fx)
        + f11 * fy * fx
    )


def sample_field_at(
    field: jnp.ndarray, pts: jnp.ndarray, shape: tuple[int, int]
) -> jnp.ndarray:
    """Bilinearly sample a cell-centered (gh, gw, 2) field at (N, 2)
    (x, y) image points — the point-wise counterpart of upsample_field
    (N tiny gathers; N = match count, not pixels)."""
    gh, gw, _ = field.shape
    H, W = shape
    gx = jnp.clip((pts[:, 0] + 0.5) * gw / W - 0.5, 0, gw - 1)
    gy = jnp.clip((pts[:, 1] + 0.5) * gh / H - 0.5, 0, gh - 1)
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1 = jnp.minimum(x0 + 1, gw - 1)
    y1 = jnp.minimum(y0 + 1, gh - 1)
    fx = (gx - x0)[:, None]
    fy = (gy - y0)[:, None]
    flat = field.reshape(-1, 2)
    return (
        flat[y0 * gw + x0] * (1 - fx) * (1 - fy)
        + flat[y0 * gw + x1] * fx * (1 - fy)
        + flat[y1 * gw + x0] * (1 - fx) * fy
        + flat[y1 * gw + x1] * fx * fy
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "grid", "shape", "n_global_hyps", "patch_hyps", "smooth_sigma",
        "passes", "refine_reach_scale", "patch_model", "refine_hyps",
    ),
)
def estimate_field(
    src: jnp.ndarray,  # (N, 2) reference keypoint positions of matches
    dst: jnp.ndarray,  # (N, 2) frame keypoint positions of matches
    valid: jnp.ndarray,  # (N,) bool
    key: jnp.ndarray,
    grid: tuple[int, int],
    shape: tuple[int, int],
    n_global_hyps: int = 64,
    patch_hyps: int = 32,
    global_threshold: float = 8.0,
    patch_threshold: float = 2.0,
    prior: float = 8.0,
    smooth_sigma: float = 0.7,
    passes: int = 2,
    refine_reach_scale: float = 1.0,
    patch_model: str = "translation",
    refine_hyps: int = 0,
) -> FieldResult:
    """Per-patch consensus displacement field for one frame.

    `passes` > 1 adds residual refinement rounds: each patch's
    membership averages the true field over its ~1.5-pitch reach, a
    REPRESENTATION bias (DESIGN.md "Piecewise regularization sweep").
    Re-estimating the per-patch residual against the previous field's
    point-wise prediction makes that averaging act on the (much
    smaller, smoother) residual instead — second-order error. Measured:
    ~10% lower field RMSE across rich/sparse/noisy regimes at pass 2.

    `refine_reach_scale` < 1 additionally SHRINKS the membership reach
    on each refinement pass (floored at 0.75 patch pitch so every patch
    keeps data): pass 1 needs the wide 1.5-pitch reach for robustness,
    but the refinement passes correct a small residual, where a tighter
    neighborhood means less cross-patch averaging of exactly the
    variation being recovered. See DESIGN.md "Piecewise refinement
    reach" for the measured sweep.

    `patch_model` selects the per-patch consensus model. "translation"
    fits a constant displacement over each patch's reach — for a
    smoothly varying field that constant is the reach-AVERAGED field,
    the representation bias the refinement passes fight. "affine" fits
    the local first-order field (displacement + gradient) and reads it
    off AT the patch center, removing that bias at the source (see
    DESIGN.md "Piecewise patch models").
    """
    gh, gw = grid
    translation = MODELS["translation"]
    pmodel = MODELS[patch_model]
    kg, kp = jax.random.split(key)

    # 1. Global stage: robust overall translation, generous threshold.
    gres = ransac_estimate(
        translation, src, dst, valid, kg,
        n_hypotheses=n_global_hyps, threshold=global_threshold,
    )
    g_t = gres.transform[:2, 2]  # global displacement
    ok = gres.inlier_mask  # matches consistent with *some* coherent motion

    centers = patch_centers(grid, shape).reshape(-1, 2)  # (P, 2)
    ph, pw = shape[0] / gh, shape[1] / gw
    # Soft membership: matches within 1.5 patch sizes of a center participate
    # (overlap keeps the field smooth and gives edge patches enough data).
    reach = 1.5 * jnp.float32(max(ph, pw))

    def per_patch(center, k):
        d2 = jnp.sum((src - center) ** 2, axis=-1)
        member = ok & (d2 < reach * reach)
        res = ransac_estimate(
            pmodel, src, dst, member, k,
            n_hypotheses=patch_hyps, threshold=patch_threshold,
        )
        # Displacement AT the patch center (for translation this is
        # just the constant; for affine it reads the local first-order
        # fit at the one point the field stores). Precision pin: TPU's
        # default matmul precision is bf16-grade even for a 2x2 matvec,
        # and `center` carries O(frame-size) coordinates — unpinned,
        # this line alone can cost ~0.5 px (see ops/polish.py).
        M = res.transform
        disp = (
            jnp.matmul(
                M[:2, :2], center, precision=jax.lax.Precision.HIGHEST
            ) + M[:2, 2] - center
        )
        # Trust region: a degenerate multi-DoF patch fit (few, near-
        # collinear members) can land far from any data-supported
        # motion; cap the deviation from the global displacement at
        # 2x the global inlier threshold — every member was within
        # global_threshold of the global motion, so real local motion
        # can't exceed that scale.
        delta = disp - g_t
        nrm = jnp.sqrt(jnp.sum(delta**2) + 1e-12)
        cap_px = 2.0 * global_threshold
        disp = g_t + delta * jnp.minimum(1.0, cap_px / nrm)
        mass = res.n_inliers.astype(jnp.float32)
        # Blend toward the global displacement when the patch has few inliers.
        lam = mass / (mass + prior)
        return lam * disp + (1.0 - lam) * g_t

    pkeys = jax.random.split(kp, centers.shape[0])
    disps = jax.vmap(per_patch)(centers, pkeys)  # (P, 2)
    field = disps.reshape(gh, gw, 2)
    field = smooth_field(field, smooth_sigma)

    pitch = jnp.float32(max(ph, pw))
    for it in range(passes - 1):
        reach_r = jnp.maximum(
            reach * jnp.float32(refine_reach_scale) ** (it + 1), 0.75 * pitch
        )
        pred = sample_field_at(field, src, shape)  # (N, 2)
        resid = dst - src - pred
        # membership by consistency with the CURRENT field, not just the
        # global motion — gates out matches of different local motion
        gate = ok & (jnp.sum(resid**2, axis=-1) < (2.0 * patch_threshold) ** 2)
        dst_resid = dst - pred

        def per_patch_resid(center, k):
            d2 = jnp.sum((src - center) ** 2, axis=-1)
            member = gate & (d2 < reach_r * reach_r)
            # refine passes fit a 2x-threshold-gated residual: high
            # inlier fraction, so a small budget suffices (see
            # CorrectorConfig.refine_hypotheses) — the scoring work
            # scales with passes x hypotheses
            res = ransac_estimate(
                pmodel, src, dst_resid, member, k,
                n_hypotheses=refine_hyps or patch_hyps,
                threshold=patch_threshold,
            )
            M = res.transform
            # precision pin: same bf16 trap as the first-pass site above
            disp = jnp.matmul(
                M[:2, :2], center, precision=jax.lax.Precision.HIGHEST
            ) + M[:2, 2] - center
            # Trust region: members passed the residual gate
            # (< 2x patch_threshold), so a genuine correction is
            # bounded by it; a degenerate fit beyond that is clamped.
            nrm = jnp.sqrt(jnp.sum(disp**2) + 1e-12)
            cap_px = 2.0 * patch_threshold
            disp = disp * jnp.minimum(1.0, cap_px / nrm)
            mass = res.n_inliers.astype(jnp.float32)
            lam = mass / (mass + prior)
            return lam * disp  # blend toward zero residual

        rkeys = jax.random.split(
            jax.random.fold_in(kp, it + 1), centers.shape[0]
        )
        r = jax.vmap(per_patch_resid)(centers, rkeys).reshape(gh, gw, 2)
        # at the cell-centered patch centers the field samples exactly,
        # so the update is simply additive
        field = smooth_field(field + r, smooth_sigma)

    flow = upsample_field(field, shape)
    return FieldResult(
        field=field, flow=flow, n_inliers=gres.n_inliers, rms_residual=gres.rms_residual
    )


def correlation_polish(
    corrected: jnp.ndarray,  # (B, H, W) flow-warped frames (ref-aligned)
    template: jnp.ndarray,  # (H, W) reference frame
    grid: tuple[int, int],
    window_frac: float = 0.25,
) -> jnp.ndarray:
    """Photometric field correction: per-patch subpixel cross-
    correlation of each corrected frame against the template.

    Keypoint consensus estimates the field from ~40 matched corners per
    patch, each localized to ~0.2-0.3 px — a noise floor the smoothing
    passes can't beat. This NoRMCorre-style polish measures the
    REMAINING shift of every patch photometrically, using all ~4k
    pixels of the patch. The measurement core (center-weighted window,
    two-way symmetric scoring, significance gate, quadratic peak fit)
    lives in ops/polish.measure_shifts, shared with the matrix-model
    transform polish since round 5.

    Returns (B, gh, gw, 2) field corrections (ADD to the field:
    corrected(p) = frame(p + u(p)), so content displaced by ε relative
    to the template peaks at shift d = ε and the fix is u += -d...
    which this function already negates).
    """
    from kcmc_tpu.ops.polish import measure_shifts

    # exact=True: the per-region estimator the piecewise accuracy
    # record is pinned to, through its round-5 bandwidth restructure
    # (values equal to f32 residue — see measure_shifts). The matrix
    # polish's ring/index-shift fast path measures +0.02 px on this
    # workload's pass-2 convergence, and a 2D-quadratic (9-point)
    # vertex measured as a wash across regimes — both recorded in
    # DESIGN.md "Piecewise polish, round 5".
    d, _ = measure_shifts(corrected, template, grid, window_frac, exact=True)
    return -d
