"""Pallas TPU kernel: batched per-keypoint patch extraction.

XLA lowers a vmapped `dynamic_slice` with data-dependent origins to a
gather that moves ~1 GB/s on TPU (measured) — ~70 ms per 64-frame batch
of 512 keypoints, the single largest cost in the registration pipeline.
This kernel does the same extraction at memory speed:

* grid = (frames, keypoint blocks); the padded frame lives in VMEM once
  per frame (rows of the grid iterate keypoint blocks fastest, so the
  frame block is revisited, not re-fetched).
* Per keypoint, one DYNAMIC ROW SLICE (sublane-dim starts are fine in
  Mosaic; it is lane-dim starts that must be tile-aligned) cuts the
  (P, Wp) row slab, and a tiny iota-built one-hot matmul selects the P
  columns at the keypoint's x origin — an MXU op instead of a gather.
* Origins arrive via scalar prefetch, so the kernel is fully static.

Returns patches in the (B, K, P, P) layout the describe stages consume.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _patch_kernel(oy_ref, ox_ref, src_ref, out_ref, *, P: int, KB: int):
    b = pl.program_id(0)
    kb = pl.program_id(1)
    Wp = src_ref.shape[1]
    S = ((P + 7) // 8) * 8 + 8  # aligned slab rows covering P + residual
    lane = jax.lax.broadcasted_iota(jnp.int32, (Wp, P), 0)
    off = jax.lax.broadcasted_iota(jnp.int32, (Wp, P), 1)
    for i in range(KB):
        k = kb * KB + i
        y0 = oy_ref[b, k]
        x0 = ox_ref[b, k]
        # Sublane-dim dynamic starts must be provably 8-aligned: slice an
        # aligned slab, then roll out the sub-tile residual (positive
        # shifts only — see ops/pallas_warp.py).
        y0a = (y0 // 8) * 8
        slab = src_ref[pl.ds(y0a, S), :]  # (S, Wp)
        slab = pltpu.roll(slab, S - (y0 - y0a), 0)[:P]  # (P, Wp)
        sel = (lane == x0 + off).astype(jnp.float32)  # (Wp, P) one-hot
        # HIGHEST precision: the default truncates the (one-nonzero-term)
        # products to bf16, quantizing the extracted values.
        out_ref[i] = jax.lax.dot(
            slab, sel, precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )


@functools.partial(jax.jit, static_argnames=("P", "interpret"))
def extract_patches(
    padded: jnp.ndarray,
    oy: jnp.ndarray,
    ox: jnp.ndarray,
    P: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, Hp, Wp) padded frames + (B, K) int32 origins -> (B, K, P, P).

    patches[b, k, i, j] = padded[b, oy[b,k] + i, ox[b,k] + j].
    Origins must satisfy 0 <= oy <= Hp - P and 0 <= ox <= Wp - P (the
    callers clamp; out-of-range x selects zero columns, out-of-range y
    is clamped by Mosaic's slice semantics).
    """
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    KB = 8  # keypoints per program: amortizes grid overhead
    if K % KB:  # pad the keypoint axis up; callers slice the tail off
        pad = KB - K % KB
        oy = jnp.concatenate([oy, jnp.zeros((B, pad), oy.dtype)], axis=1)
        ox = jnp.concatenate([ox, jnp.zeros((B, pad), ox.dtype)], axis=1)
    Kp = oy.shape[1]
    # The kernel reads an 8-aligned slab of S rows starting at or before
    # each origin; give the frame the bottom margin that can overrun.
    S = ((P + 7) // 8) * 8 + 8
    padded = jnp.pad(padded, ((0, 0), (0, S - P), (0, 0)), mode="edge")
    Hp = Hp + S - P

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Kp // KB),
        in_specs=[
            pl.BlockSpec((None, Hp, Wp), lambda b, kb, oy, ox: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, KB, P, P), lambda b, kb, oy, ox: (b, kb, 0, 0)
        ),
    )
    kernel = functools.partial(_patch_kernel, P=P, KB=KB)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kp, P, P), jnp.float32),
        interpret=interpret,
    )(
        oy.astype(jnp.int32), ox.astype(jnp.int32),
        padded.astype(jnp.float32),
    )
    return out[:, :K]
