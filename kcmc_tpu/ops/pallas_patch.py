"""Pallas TPU kernel: batched per-keypoint patch extraction.

XLA lowers a vmapped `dynamic_slice` with data-dependent origins to a
gather that moves ~1 GB/s on TPU (measured) — ~70 ms per 64-frame batch
of 512 keypoints, the single largest cost in the registration pipeline.
This kernel does the same extraction at memory speed:

* grid = (frames, keypoint blocks); the padded frame lives in VMEM once
  per frame (rows of the grid iterate keypoint blocks fastest, so the
  frame block is revisited, not re-fetched).
* Per keypoint, one DYNAMIC WINDOW SLICE cuts an aligned (S, 256) slab:
  sublane-dim starts must be provably 8-aligned and lane-dim starts
  128-aligned, so the slice starts at the aligned floor of the origin
  and covers the residual. Two `pltpu.roll`s (sublane then lane) rotate
  the patch to the slab's corner, and a static (P, P) slice cuts it out
  — no gathers, no matmuls. (Earlier revisions selected columns with a
  one-hot MXU matmul; rolling the pre-sliced 256-lane window is ~1.8x
  faster — the matmul's contraction over the window width was the cost,
  not the rotate.) Roll amounts are non-negative (dim - shift): Mosaic
  mis-wraps negative dynamic amounts on multi-tile arrays.
* Origins arrive via scalar prefetch, so the kernel is fully static.

Two kernels share the technique: `extract_blended` is the production
descriptor path — it fuses the per-keypoint bilinear blend and the ORB
orientation moments into the cut, emitting keypoint-FIRST patches so
nothing downstream needs the (P, P, K) relayout. `extract_patches` is
the raw-patch primitive (standalone utility; not on the product path
since the blend moved in-kernel, kept for raw-patch consumers and as
the direct oracle check of the slab/roll addressing; resident-frame
layout only — gate on `supports()` for large frames).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_WIN = 256  # lane window: covers the 128-alignment residual + patch width
_KB = 8  # keypoints per program. 16 was the measured best for the
# original wide-slab 4-tap kernel; re-swept after the round-5
# narrow-slab + separable-blend rewrite, 8 wins (9.3 vs 10.2 ms/batch
# at B=32, K=4096, 512²; 32: 12.3, 64: 13.3 — shorter serial
# per-program chains pipeline better than fewer program launches).
# Note _RUN_ALIGN (describe) stays 16: 16-aligned orientation runs are
# also 8-aligned, so extraction blocks never straddle a run boundary
# and the dynamic-block selection keeps its one-bin-per-16-rows
# contract.
# Scalar-prefetch arrays (keypoint origins) live whole in SMEM, which is
# 1 MB on v5e: at batch 64 x K=2048 the two (B, K) i32 origin planes
# alone are exactly 1 MB and the compile dies with "Ran out of memory in
# memory space smem". The extract wrappers chunk the batch axis so the
# scalar arrays stay under this budget (half of SMEM, leaving room for
# grid bookkeeping); chunking costs one extra kernel launch per chunk,
# nothing else — the grid already iterates frames serially.
_SMEM_SCALAR_BUDGET = 512 * 1024


def _smem_batch_limit(n_scalar_arrays: int, K: int, KB: int) -> int:
    """Max frames per pallas_call keeping (B, K) i32 scalar prefetch
    arrays within the SMEM budget."""
    Kp = -(-K // KB) * KB
    return max(1, _SMEM_SCALAR_BUDGET // (n_scalar_arrays * Kp * 4))


# The 2D kernels keep one whole padded frame resident in VMEM per grid
# program (keypoints are scattered, so the frame block is the natural
# unit), and Pallas double-buffers input blocks — so the scoped
# footprint is ~2x the padded frame. Measured: 38.8 MB scoped-vmem OOM
# at 2048^2, where the padded frame is 20.3 MB (ratio 1.9x). The gate
# below uses the 2x-buffered estimate against a 14 MB budget (16 MB
# physical minus slack): 512^2 -> 4 MB, 1024^2 -> 12 MB (both measured
# working), 1440^2 -> 21 MB (correctly rejected), 2048^2 -> 41 MB.
_VMEM_FRAME_BUDGET = 14 * 1024 * 1024


def _slab_rows(P: int, itemsize: int = 4) -> int:
    """Aligned slab rows covering P + the sublane-alignment residual —
    the single source of truth shared by the kernels, the wrappers'
    padding, the VMEM gate, and the HBM chunk estimate. Sublane
    alignment is 8 for f32 tiles and 16 for bf16 ((16, 128) tiling),
    so bf16 slabs carry more rows but half the bytes (~40% less
    traffic at P=32)."""
    a = 16 if itemsize == 2 else 8
    return ((P + a - 1) // a) * a + a


def _slab_dims(P: int, Wp: int, itemsize: int = 4) -> tuple[int, int]:
    """(S, Wpp): `_slab_rows` plus the lane-padded width every 2D
    wrapper pads to."""
    return _slab_rows(P, itemsize), -(-(Wp + _WIN) // 128) * 128


def _chunk_batch(fn, bc: int, B: int, arrays, with_moments: bool):
    """Shared batch-chunking scaffold for the blended extract variants:
    recurse `fn` over `bc`-frame slices of `arrays`, concatenating the
    (pb,) or (pb, m10, m01) outputs."""
    outs = [fn(*(a[i : i + bc] for a in arrays)) for i in range(0, B, bc)]
    if with_moments:
        return tuple(jnp.concatenate([o[j] for o in outs]) for j in range(3))
    return jnp.concatenate(outs)


def _pad_keypoint_axis(KB: int, oy, ox, fx, fy):
    """Zero-pad the keypoint axis up to a KB multiple (the wrappers
    slice the tail back off)."""
    K = oy.shape[1]
    if K % KB == 0:
        return oy, ox, fx, fy
    pad = KB - K % KB
    B = oy.shape[0]
    z = jnp.zeros((B, pad), oy.dtype)
    zf = jnp.zeros((B, pad, 1), jnp.float32)
    return (
        jnp.concatenate([oy, z], axis=1),
        jnp.concatenate([ox, z], axis=1),
        jnp.concatenate([fx, zf], axis=1),
        jnp.concatenate([fy, zf], axis=1),
    )


def supports(shape: tuple[int, int], P: int, itemsize: int = 4) -> bool:
    """Whether the whole-frame (resident-frame) 2D extraction layout
    fits VMEM for a (H, W) frame and patch size P (callers pad by
    (P - 2) // 2 + 1). When False, `extract_blended_planes` switches to
    the per-keypoint Element-indexed slab layout automatically (the
    BLENDED entry points work at any frame size; the raw
    `extract_patches` primitive is resident-frame only and callers must
    gate on this)."""
    H, W = shape
    r1 = (P - 2) // 2 + 1
    Hp, Wp = H + 2 * r1, W + 2 * r1
    return _frame_fits(Hp, Wp, P, itemsize)


def _frame_fits(Hp: int, Wp: int, P: int, itemsize: int = 4) -> bool:
    S, Wpp = _slab_dims(P, Wp, itemsize)
    Hpp = Hp + S - P
    return 2 * Hpp * Wpp * itemsize <= _VMEM_FRAME_BUDGET


def _wpp_2copy(Wp: int) -> int:
    """Lane-padded width of the narrow-slab (2-copy) layout — the
    single source of truth for the gate AND the wrapper's padding (the
    1-copy path routes the same role through _slab_dims)."""
    return -(-(Wp + 128) // 128) * 128


def _frame_fits_2copy(Hp: int, Wp: int, P: int, itemsize: int = 4) -> bool:
    """VMEM gate for the narrow-slab (two pre-shifted copies, 128-lane
    window) resident layout: the block is (2, Hpp, Wpp2), still
    double-buffered. Wpp2 uses a 128-lane margin instead of _WIN.
    The 128-lane window holds residual(<64) + patch, so the layout is
    only CORRECT for P <= 65 — larger P must take the wide window
    (worst case rx = 63 and 63 + P <= 128 exactly at P = 65; the
    kernel re-asserts this statically — see _blended_kernel)."""
    if P > 65:
        return False
    S = _slab_rows(P, itemsize)
    Hpp = Hp + S - P
    return 2 * 2 * Hpp * _wpp_2copy(Wp) * itemsize <= _VMEM_FRAME_BUDGET


def feasible_bands(
    shape: tuple[int, int], P: int, itemsize: int = 4
) -> tuple[int, ...]:
    """Every band count the row-banded layout can run for this frame
    (the PR-13 autotune candidate set): the minimal VMEM-fitting split
    plus every LARGER split (smaller bands always fit once one does).
    Empty when nothing fits; (1,) means whole-frame resident only.
    Numerics are band-count-invariant (each keypoint's patch is cut
    from identical pixels whichever band hosts it), so the choice is a
    pure tiling decision."""
    nb = band_count(shape, P, itemsize)
    if nb == 0:
        return ()
    if nb == 1:
        return (1,)
    return tuple(b for b in (2, 4, 8) if b >= nb)


def band_count(shape: tuple[int, int], P: int, itemsize: int = 4) -> int:
    """Bands for the row-banded extraction layout (round 5, DESIGN.md
    "Large-frame support" item 2): 1 = whole frame resident (use the
    plain kernel), 2/4/8 = smallest split whose (Hb + S)-row band block
    fits VMEM, 0 = nothing fits (callers fall back to the XLA gather
    path). shape is the UNPADDED frame shape, as for `supports`."""
    H, W = shape
    r1 = (P - 2) // 2 + 1
    Hp, Wp = H + 2 * r1, W + 2 * r1
    if _frame_fits(Hp, Wp, P, itemsize):
        return 1
    S, Wpp = _slab_dims(P, Wp, itemsize)
    a = 16 if itemsize == 2 else 8
    for NB in (2, 4, 8):
        Hb = -(-(-(-Hp // NB)) // a) * a
        if 2 * (Hb + S) * Wpp * itemsize <= _VMEM_FRAME_BUDGET:
            return NB
    return 0


def _patch_kernel(oy_ref, ox_ref, src_ref, out_ref, *, P: int, KB: int):
    b = pl.program_id(0)
    kb = pl.program_id(1)
    S = _slab_rows(P)
    for i in range(KB):
        k = kb * KB + i
        y0 = oy_ref[b, k]
        x0 = ox_ref[b, k]
        y0a = (y0 // 8) * 8
        x0a = (x0 // 128) * 128
        slab = src_ref[pl.ds(y0a, S), pl.ds(x0a, _WIN)]  # (S, _WIN)
        slab = pltpu.roll(slab, S - (y0 - y0a), 0)
        slab = pltpu.roll(slab, _WIN - (x0 - x0a), 1)
        out_ref[i] = slab[:P, :P]


def _moment_maps(P: int) -> np.ndarray:
    """(2, 2, 2, P, P) constant weight maps turning the ORB intensity-
    centroid moments into plain masked reductions over the raw patch.

    maps[ry, rx, 0/1] placed so that sum(patch * maps[ry, rx, 0]) equals
    m10 (and [1] m01) of the MOMENT_RADIUS disc centered on the rounded
    keypoint (patch index c + (rx, ry)), matching
    describe._moment_angles' disc selection exactly.
    """
    from kcmc_tpu.ops.patterns import MOMENT_RADIUS, MOMENTS

    c = (P - 2) // 2  # patch center index for offset 0 (= the radius)
    r = MOMENT_RADIUS
    moms = np.asarray(MOMENTS, np.float32)  # (2r+1, 2r+1, 3): dx, dy, inside
    out = np.zeros((2, 2, 2, P, P), np.float32)
    for ry in (0, 1):
        for rx in (0, 1):
            rows = slice(c + ry - r, c + ry + r + 1)
            cols = slice(c + rx - r, c + rx + r + 1)
            out[ry, rx, 0, rows, cols] = moms[..., 0] * moms[..., 2]
            out[ry, rx, 1, rows, cols] = moms[..., 1] * moms[..., 2]
    return out


def _blended_kernel(
    oy_ref, ox_ref, fx_ref, fy_ref, mm_ref, src_ref,
    pb_ref, m10_ref, m01_ref,
    *, P: int, KB: int, with_moments: bool, ncopies: int = 1,
):
    """Patch cut + per-keypoint bilinear blend (+ ORB moments) fused.

    Produces keypoint-FIRST blended patches: with the blend and the
    moment reductions done here against the resident slab, nothing
    downstream shifts patch pixels anymore, so the (P, P, K)
    keypoint-last relayout the XLA path needs (and its ~6 ms/batch
    transpose) disappears — the descriptor selection matmul consumes
    (K, L) rows directly.

    `ncopies=2` is the narrow-slab variant (round 5): the frame block
    carries a second copy pre-shifted LEFT by 64 lanes, so the lane
    residual after picking the right copy is < 64 and a 128-lane slab
    covers residual + patch. Mosaic lowers a dynamic roll as
    log2(lanes) conditional shift passes over the slab's vregs
    regardless of the amount's range — so the win is the slab's SIZE
    (6 vregs instead of 12), which halves every pass of both rolls and
    the upcast: measured 13.8 -> 8.2 ms/batch at B=32, K=4096, 512².
    """
    b = pl.program_id(0)
    kb = pl.program_id(1)
    itemsize = jnp.dtype(src_ref.dtype).itemsize
    align = 16 if itemsize == 2 else 8
    S = _slab_rows(P, itemsize)
    W = 128 if ncopies == 2 else _WIN
    if ncopies == 2:
        # Static wrap-safety (ADVICE r5): in the narrow-slab layout the
        # post-copy lane residual rx = xp - x0a is < 64 by construction
        # (the second copy is pre-shifted 64 lanes), so the 128-lane
        # window covers residual + patch iff 63 + P <= 128 — exactly
        # the P <= 65 gate in _frame_fits_2copy. If the gate and this
        # kernel ever drift apart, the roll below would WRAP patch
        # lanes silently; fail the trace instead. (A real raise, not
        # `assert`, so `python -O` can't strip the guard.)
        if 63 + P > 128:
            raise ValueError(
                f"narrow-slab layout: worst-case rx (63) + P ({P}) "
                "exceeds the 128-lane window — _frame_fits_2copy must "
                "gate P <= 65"
            )
    # Scalar stores to VMEM are unsupported: accumulate the per-keypoint
    # moment scalars into (KB, 1) vectors (iota row-select) and store once.
    row = jax.lax.broadcasted_iota(jnp.int32, (KB, 1), 0)
    acc_x = jnp.zeros((KB, 1), jnp.float32)
    acc_y = jnp.zeros((KB, 1), jnp.float32)
    for i in range(KB):
        k = kb * KB + i
        y0 = oy_ref[b, k]
        x0 = ox_ref[b, k]
        y0a = (y0 // align) * align
        # Mosaic's rotate is 32-bit-only: slice the (bf16 or f32) slab
        # out of the resident block, upcast the SLAB (tiny), roll in
        # f32. The frame block's HBM->VMEM fetch keeps the input
        # dtype's bytes; only the per-keypoint slab work runs f32.
        if ncopies == 2:
            c = (x0 % 128) // 64  # which pre-shifted copy
            xp = x0 - 64 * c
            x0a = (xp // 128) * 128
            slab = src_ref[
                pl.ds(c, 1), pl.ds(y0a, S), pl.ds(x0a, W)
            ][0].astype(jnp.float32)
            rx = xp - x0a  # in [0, 64)
        else:
            x0a = (x0 // 128) * 128
            slab = src_ref[pl.ds(y0a, S), pl.ds(x0a, W)].astype(jnp.float32)
            rx = x0 - x0a
        ry = y0 - y0a
        fx = fx_ref[i, 0]
        fy = fy_ref[i, 0]
        # Separable blend BEFORE the cut, as static +1 rolls on the
        # full-width slab (round 5): the 4-tap form on the cut (P, P)
        # patch was the kernel's LARGEST cost — each of its four
        # 1-offset taps slices a misaligned (31, 31) view, and Mosaic
        # pays a relayout per tap (measured 3.3 ms of a 5.8 ms kernel
        # at B=16, K=4096). Static rolls on the tile-aligned slab are
        # single shuffles; the wrapped row/lane lands outside the
        # patch region for every legal origin, so values are
        # unchanged (the jnp oracle `_bilinear_blend` uses the same
        # separable grouping — bit parity preserved).
        yb = (1.0 - fy) * slab + fy * pltpu.roll(slab, S - 1, 0)
        xb = (1.0 - fx) * yb + fx * pltpu.roll(yb, W - 1, 1)
        v = pltpu.roll(xb, S - ry, 0)
        v = pltpu.roll(v, W - rx, 1)
        pb_ref[i] = v[: P - 1, : P - 1].astype(pb_ref.dtype)
        if with_moments:
            patch = pltpu.roll(slab, S - ry, 0)
            patch = pltpu.roll(patch, W - rx, 1)[:P, :P]
            # mm_ref rows: [x00, x01, x10, x11, y00, y01, y10, y11]
            # (yx order: row 2*qy + qx), see _moment_maps.
            qx = fx >= 0.5
            qy = fy >= 0.5
            wx = jnp.where(
                qy,
                jnp.where(qx, mm_ref[3], mm_ref[2]),
                jnp.where(qx, mm_ref[1], mm_ref[0]),
            )
            wy = jnp.where(
                qy,
                jnp.where(qx, mm_ref[7], mm_ref[6]),
                jnp.where(qx, mm_ref[5], mm_ref[4]),
            )
            pf = patch.astype(jnp.float32)
            acc_x = jnp.where(row == i, jnp.sum(pf * wx), acc_x)
            acc_y = jnp.where(row == i, jnp.sum(pf * wy), acc_y)
    # Outputs must not stay unwritten (the wrapper discards them when
    # moments are off; they hold zeros then).
    m10_ref[:, :] = acc_x
    m01_ref[:, :] = acc_y


@functools.partial(
    jax.jit,
    static_argnames=("P", "with_moments", "interpret", "out_dtype", "bands"),
)
def extract_blended(
    padded: jnp.ndarray,
    xy: jnp.ndarray,
    P: int,
    with_moments: bool = False,
    interpret: bool = False,
    out_dtype=jnp.float32,
    bands: int | None = None,
):
    """Keypoint-first blended patches straight from the padded frames.

    padded: (B, Hp, Wp) frames edge-padded by (P - 2) // 2 + 1 (the
    describe convention); xy: (B, K, 2) subpixel keypoint positions.
    Returns blended (B, K, P-1, P-1) — the bilinear resample of each
    patch at its keypoint's subpixel fraction, identical to
    describe._extract_patches' blended output up to float summation
    order — and, with `with_moments`, the ORB intensity-centroid
    moments (m10, m01), each (B, K, 1).

    `with_moments` note (round 5): production orientation moved to the
    frame-level `moment_maps` route (describe._moments_at_keypoints),
    so the in-kernel moment outputs have no shipping caller. They are
    RETAINED DELIBERATELY as the on-chip moments oracle — the
    independent per-patch computation that tests/test_pallas_patch.py
    and the bins-first bin-agreement checks compare the map route
    against (bin agreement 1.0, DESIGN.md "Bins-first oriented
    descriptors").
    """
    oy = jnp.floor(xy[..., 1]).astype(jnp.int32) + 1
    ox = jnp.floor(xy[..., 0]).astype(jnp.int32) + 1
    fx = (xy[..., 0] - jnp.floor(xy[..., 0]))[..., None].astype(jnp.float32)
    fy = (xy[..., 1] - jnp.floor(xy[..., 1]))[..., None].astype(jnp.float32)
    return extract_blended_planes(
        padded, oy, ox, fx, fy, P, with_moments=with_moments,
        interpret=interpret, out_dtype=out_dtype, bands=bands,
    )


@functools.partial(
    jax.jit,
    static_argnames=("P", "with_moments", "interpret", "out_dtype", "bands"),
)
def extract_blended_planes(
    padded: jnp.ndarray,
    oy: jnp.ndarray,
    ox: jnp.ndarray,
    fx: jnp.ndarray,
    fy: jnp.ndarray,
    P: int,
    with_moments: bool = False,
    interpret: bool = False,
    out_dtype=jnp.float32,
    bands: int | None = None,
):
    """Core entry on explicit integer origins (B, K) and blend
    fractions (B, K, 1): the 3D descriptor path flattens (z, y) into
    plane rows and feeds pseudo-keypoints per z-slice through this.

    `bands` overrides the banded layout's band count (autotune seam;
    must come from `feasible_bands` — an infeasible override falls back
    to the computed minimum rather than compiling a VMEM OOM).
    """
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    isz = padded.dtype.itemsize
    if not _frame_fits(Hp, Wp, P, isz):
        H_unpadded = Hp - 2 * ((P - 2) // 2 + 1)
        W_unpadded = Wp - 2 * ((P - 2) // 2 + 1)
        NB = band_count((H_unpadded, W_unpadded), P, isz)
        if (
            bands is not None
            and NB >= 2
            and bands in feasible_bands((H_unpadded, W_unpadded), P, isz)
        ):
            NB = bands
        if NB >= 2:
            # Large frames (≈2048²+): row-banded resident layout —
            # keypoints dispatched to row bands, each band's block fits
            # VMEM (round 5; see _extract_blended_planes_banded).
            return _extract_blended_planes_banded(
                padded, oy, ox, fx, fy, P, NB,
                with_moments=with_moments, interpret=interpret,
                out_dtype=out_dtype,
            )
        # Beyond even the banded budget: per-keypoint Element-indexed
        # slabs. NOTE: exact but measured much slower than the XLA
        # gather describe path (DESIGN.md) — kept so the kernel API is
        # total. The slab layout's 8-aligned Element-indexed blocks are
        # f32-only; upcast (values are bf16-representable, so the
        # extraction is unchanged).
        return _extract_blended_planes_slab(
            padded.astype(jnp.float32), oy, ox, fx, fy, P,
            with_moments=with_moments, interpret=interpret,
            out_dtype=out_dtype,
        )
    KB = _KB
    bc = _smem_batch_limit(2, K, KB)
    if B > bc:  # chunk the batch to keep scalar prefetch within SMEM
        return _chunk_batch(
            lambda *a: extract_blended_planes(
                *a, P, with_moments=with_moments, interpret=interpret,
                out_dtype=out_dtype, bands=bands,
            ),
            bc, B, (padded, oy, ox, fx, fy), with_moments,
        )
    oy, ox, fx, fy = _pad_keypoint_axis(KB, oy, ox, fx, fy)
    Kp = oy.shape[1]
    isz = padded.dtype.itemsize
    S = _slab_rows(P, isz)
    Hpp = Hp + S - P
    # Narrow-slab layout when two copies fit VMEM (see _blended_kernel's
    # ncopies note): the second copy is the frame pre-shifted left by 64
    # lanes, so the kernel's rolled slab is (S, 128) instead of (S, 256)
    # — roll passes touch half the vregs. Bit-identical values: every
    # patch lane is real (edge-padded) frame data in either copy.
    ncopies = 2 if _frame_fits_2copy(Hp, Wp, P, isz) else 1
    if ncopies == 2:
        Wpp = _wpp_2copy(Wp)
        wide = jnp.pad(
            padded, ((0, 0), (0, S - P), (0, Wpp + 64 - Wp)), mode="edge"
        )
        padded = jnp.stack(
            [wide[:, :, :Wpp], wide[:, :, 64 : 64 + Wpp]], axis=1
        )  # (B, 2, Hpp, Wpp)
        frame_spec = pl.BlockSpec(
            (None, 2, Hpp, Wpp), lambda b, kb, oy, ox: (b, 0, 0, 0)
        )
    else:
        _, Wpp = _slab_dims(P, Wp, isz)
        padded = jnp.pad(
            padded, ((0, 0), (0, S - P), (0, Wpp - Wp)), mode="edge"
        )
        frame_spec = pl.BlockSpec(
            (None, Hpp, Wpp), lambda b, kb, oy, ox: (b, 0, 0)
        )

    Pb = P - 1
    mm = _moment_maps(P)  # constant; tiny even when moments are unused
    mm_in = jnp.asarray(
        np.concatenate([mm[:, :, 0].reshape(4, P, P), mm[:, :, 1].reshape(4, P, P)])
    )  # (8, P, P): rows [x00, x01, x10, x11, y00, y01, y10, y11]
    kernel = functools.partial(
        _blended_kernel, P=P, KB=KB, with_moments=with_moments,
        ncopies=ncopies,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Kp // KB),
        in_specs=[
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((8, P, P), lambda b, kb, oy, ox: (0, 0, 0)),
            frame_spec,
        ],
        out_specs=[
            pl.BlockSpec((None, KB, Pb, Pb), lambda b, kb, oy, ox: (b, kb, 0, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy, ox: (b, kb, 0)),
        ],
    )
    pb, m10, m01 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Kp, Pb, Pb), out_dtype),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        oy.astype(jnp.int32), ox.astype(jnp.int32),
        fx, fy, mm_in, padded,
    )
    if with_moments:
        return pb[:, :K], m10[:, :K], m01[:, :K]
    return pb[:, :K]


def _extract_blended_planes_banded(
    padded: jnp.ndarray,
    oy: jnp.ndarray,
    ox: jnp.ndarray,
    fx: jnp.ndarray,
    fy: jnp.ndarray,
    P: int,
    NB: int,
    with_moments: bool = False,
    interpret: bool = False,
    out_dtype=jnp.float32,
):
    """Row-banded variant of the resident-frame layout for frames whose
    padded block exceeds VMEM (DESIGN.md "Large-frame support" item 2,
    built round 5): the frame splits into NB row bands of Hb rows plus
    an S-row halo, keypoints are laid out in band-sorted KB-ALIGNED
    runs, and the unchanged `_blended_kernel` runs over the slot
    blocks with the band block chosen DYNAMICALLY per program — the
    block's band id rides in a scalar-prefetch array the frame
    BlockSpec's index_map reads. Results gather back to original
    keypoint order (a (B, K) row gather of small keypoint-first rows,
    not pixels).

    Unlike a fixed-capacity segment dispatch, the aligned-runs layout
    has NO capacity drops: every keypoint gets a slot regardless of
    density skew (a tissue scene with every keypoint in one band just
    makes that band's run long), at a static slot count of
    K + NB*KB — the alignment padding is the only overhead.
    """
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    KB = _KB
    S, Wpp = _slab_dims(P, Wp, padded.dtype.itemsize)
    # band starts must respect the slab sublane alignment (16 for bf16)
    _ba = 16 if padded.dtype.itemsize == 2 else 8
    Hb = -(-(-(-Hp // NB)) // _ba) * _ba
    Kp = -(-K // KB) * KB + NB * KB  # aligned-runs worst case

    bc = _smem_batch_limit(3, Kp, KB)
    if B > bc:
        return _chunk_batch(
            lambda *a: _extract_blended_planes_banded(
                *a, P, NB, with_moments=with_moments, interpret=interpret,
                out_dtype=out_dtype,
            ),
            bc, B, (padded, oy, ox, fx, fy), with_moments,
        )

    keys = jnp.clip(oy // Hb, 0, NB - 1).astype(jnp.int32)  # (B, K)
    order = jnp.argsort(keys, axis=1, stable=True)  # (B, K)
    sorted_keys = jnp.take_along_axis(keys, order, axis=1)
    bins = jnp.arange(NB, dtype=jnp.int32)
    starts = jax.vmap(
        lambda sk: jnp.searchsorted(sk, bins, side="left")
    )(sorted_keys)  # (B, NB)
    ends = jax.vmap(
        lambda sk: jnp.searchsorted(sk, bins, side="right")
    )(sorted_keys)
    aligned = -(-(ends - starts) // KB) * KB  # per-band run length
    astart = jnp.cumsum(aligned, axis=1) - aligned  # (B, NB) run starts
    # slot of each sorted item: its band run's start + rank within band
    rank = jnp.arange(K, dtype=jnp.int32)[None, :] - jnp.take_along_axis(
        starts, sorted_keys, axis=1
    )
    slot_of_sorted = jnp.take_along_axis(astart, sorted_keys, axis=1) + rank

    def scatter_slots(ordr, slots):
        idx = jnp.zeros((Kp,), jnp.int32).at[slots].set(ordr, mode="drop")
        ok = jnp.zeros((Kp,), bool).at[slots].set(True, mode="drop")
        return idx, ok

    flat_idx, slot_ok = jax.vmap(scatter_slots)(order, slot_of_sorted)
    # band of every slot (alignment-padding slots included): the run
    # layout makes it a step function of the run starts
    slot_ids = jnp.arange(Kp, dtype=jnp.int32)
    band_of_slot = (
        jnp.sum(
            slot_ids[None, :, None] >= astart[:, None, :], axis=-1
        ).astype(jnp.int32) - 1
    )  # (B, Kp)
    band_of_slot = jnp.clip(band_of_slot, 0, NB - 1)
    # blocks are KB-aligned to the runs, so a block never straddles
    # bands: its band is its first slot's band
    block_band = band_of_slot[:, ::KB]  # (B, Kp // KB)

    take = functools.partial(jnp.take_along_axis, axis=1)
    oy_s = take(oy, flat_idx) - band_of_slot * Hb
    ox_s = take(ox, flat_idx)
    fx_s = take(fx[..., 0], flat_idx)[..., None]
    fy_s = take(fy[..., 0], flat_idx)[..., None]
    # padding slots read the default item; harmless (masked below).
    # Clip to Hb (not Hb + S - P): the kernel's aligned S-row slab read
    # starts at floor-align(oy_s), and the band block has Hb + S rows —
    # a start past Hb would read beyond the block on chip.
    oy_s = jnp.clip(oy_s, 0, Hb)

    # band stacking: (B, NB, Hb + S, Wpp); rows padded so every band
    # slices cleanly, lanes padded for the kernel's 256-lane window
    padded = jnp.pad(
        padded,
        ((0, 0), (0, NB * Hb + S - Hp), (0, Wpp - Wp)),
        mode="edge",
    )
    bands = jnp.stack(
        [
            jax.lax.slice_in_dim(padded, b * Hb, b * Hb + Hb + S, axis=1)
            for b in range(NB)
        ],
        axis=1,
    )

    Pb = P - 1
    mm = _moment_maps(P)
    mm_in = jnp.asarray(
        np.concatenate(
            [mm[:, :, 0].reshape(4, P, P), mm[:, :, 1].reshape(4, P, P)]
        )
    )
    def kernel(band_ref, oy_ref, ox_ref, *rest):
        # band_ref only steers the frame BlockSpec's index_map below;
        # the extraction math is the unchanged resident-frame kernel
        del band_ref
        return _blended_kernel(
            oy_ref, ox_ref, *rest, P=P, KB=KB, with_moments=with_moments
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Kp // KB),
        in_specs=[
            pl.BlockSpec((None, KB, 1), lambda b, kb, bb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, bb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((8, P, P), lambda b, kb, bb, oy, ox: (0, 0, 0)),
            pl.BlockSpec(
                (None, None, Hb + S, Wpp),
                # dynamic block selection: this program's band id from
                # the scalar-prefetch array (runs are KB-aligned, so a
                # block never spans two bands)
                lambda b, kb, bb, oy, ox: (b, bb[b, kb], 0, 0),
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, KB, Pb, Pb), lambda b, kb, bb, oy, ox: (b, kb, 0, 0)
            ),
            pl.BlockSpec((None, KB, 1), lambda b, kb, bb, oy, ox: (b, kb, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, bb, oy, ox: (b, kb, 0)),
        ],
    )
    pb, m10, m01 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Kp, Pb, Pb), out_dtype),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        block_band.astype(jnp.int32),
        oy_s.astype(jnp.int32), ox_s.astype(jnp.int32),
        fx_s, fy_s, mm_in, bands,
    )

    # un-dispatch: original keypoint k's slot position (or -1 if the
    # band capacity dropped it). Empty slots carry a CLAMPED item index
    # (segment_by_key's sentinel) — route their scatter to the dropped
    # out-of-bounds index so they can't clobber a real keypoint's slot.
    slot_pos = jnp.broadcast_to(
        jnp.arange(Kp, dtype=jnp.int32)[None, :], (B, Kp)
    )

    def invert(fi, ok, pos):
        inv = jnp.full((K,), -1, jnp.int32)
        return inv.at[jnp.where(ok, fi, K)].set(pos, mode="drop")

    inv = jax.vmap(invert)(flat_idx, slot_ok.reshape(B, Kp), slot_pos)
    kept = inv >= 0
    safe = jnp.maximum(inv, 0)
    pb_k = take(pb.reshape(B, Kp, -1), safe[..., None]).reshape(
        B, K, Pb, Pb
    )
    pb_k = jnp.where(kept[..., None, None], pb_k, 0.0)
    if with_moments:
        m10_k = jnp.where(kept[..., None], take(m10, safe[..., None]), 0.0)
        m01_k = jnp.where(kept[..., None], take(m01, safe[..., None]), 0.0)
        return pb_k, m10_k, m01_k
    return pb_k


def _blended_slab_kernel(*refs, P: int, KB: int, with_moments: bool):
    """2D slab variant of `_blended_kernel` for frames too large to sit
    whole in VMEM: each keypoint's (S, _WIN) slab arrives as its own
    Element-indexed input block (sublane start 8-aligned, lane start
    128-aligned — exactly the alignment the whole-frame kernel's
    aligned-floor reads use), so VMEM holds KB tiny slabs, never the
    frame. Same roll/cut/blend/moment math as the resident-frame
    kernel."""
    # prefetch: oy8, ox128 (index maps), ry, rx (kernel); then KB slabs,
    # fx, fy, mm, outputs.
    oy8r, ox128r, ryr, rxr = refs[:4]
    slabs = refs[4 : 4 + KB]
    fx_ref, fy_ref, mm_ref = refs[4 + KB : 7 + KB]
    pb_ref, m10_ref, m01_ref = refs[7 + KB :]
    b = pl.program_id(0)
    kb = pl.program_id(1)
    S = _slab_rows(P)
    row = jax.lax.broadcasted_iota(jnp.int32, (KB, 1), 0)
    acc_x = jnp.zeros((KB, 1), jnp.float32)
    acc_y = jnp.zeros((KB, 1), jnp.float32)
    for i in range(KB):
        k = kb * KB + i
        slab = slabs[i][0]  # (S, _WIN)
        ry = ryr[b, k]
        rx = rxr[b, k]
        fx = fx_ref[i, 0]
        fy = fy_ref[i, 0]
        # separable blend before the cut — identical grouping to
        # `_blended_kernel` and `describe._bilinear_blend` (the
        # whole-frame/slab bit-identity contract in
        # test_slab_variant_matches_whole_frame_kernel)
        yb = (1.0 - fy) * slab + fy * pltpu.roll(slab, S - 1, 0)
        xb = (1.0 - fx) * yb + fx * pltpu.roll(yb, _WIN - 1, 1)
        v = pltpu.roll(xb, S - ry, 0)
        v = pltpu.roll(v, _WIN - rx, 1)
        pb_ref[i] = v[: P - 1, : P - 1].astype(pb_ref.dtype)
        if with_moments:
            patch = pltpu.roll(slab, S - ry, 0)
            patch = pltpu.roll(patch, _WIN - rx, 1)[:P, :P]
            qx = fx >= 0.5
            qy = fy >= 0.5
            wx = jnp.where(
                qy,
                jnp.where(qx, mm_ref[3], mm_ref[2]),
                jnp.where(qx, mm_ref[1], mm_ref[0]),
            )
            wy = jnp.where(
                qy,
                jnp.where(qx, mm_ref[7], mm_ref[6]),
                jnp.where(qx, mm_ref[5], mm_ref[4]),
            )
            pf = patch.astype(jnp.float32)
            acc_x = jnp.where(row == i, jnp.sum(pf * wx), acc_x)
            acc_y = jnp.where(row == i, jnp.sum(pf * wy), acc_y)
    m10_ref[:, :] = acc_x
    m01_ref[:, :] = acc_y


# Element-indexed BlockSpecs (`pl.Element`) are how the slab layout
# places per-keypoint 8-aligned blocks; older jaxlib pallas builds
# (<= 0.4.37) predate the API. The slab route is the last-resort
# fallback for frames beyond even the banded VMEM budget (and the
# plane-flattened 3D route), so on such builds it reports cleanly and
# the describe policy's XLA gather path covers those shapes instead.
ELEMENT_INDEXING = hasattr(pl, "Element")


def _extract_blended_planes_slab(
    padded, oy, ox, fx, fy, P: int, with_moments: bool, interpret: bool,
    out_dtype=jnp.float32,
):
    """Slab-blocked implementation behind extract_blended_planes for
    frames past the whole-frame VMEM budget. Identical outputs."""
    if not ELEMENT_INDEXING:
        raise NotImplementedError(
            "this jax/pallas build lacks pl.Element (element-indexed "
            "BlockSpecs), which the slab descriptor layout requires — "
            "use the XLA gather describe path for frames this large"
        )
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    KB = 8  # slabs per program: KB * S * _WIN * 4 B ≈ 0.4-0.8 MB
    # The KB slab inputs are the same padded array passed KB times (one
    # Element-indexed BlockSpec each); the runtime materializes them as
    # separate buffers, so chunk the batch to keep KB copies of the
    # padded chunk within ~1.5 GB of HBM alongside the SMEM limit.
    S0, Wpp0 = _slab_dims(P, Wp)
    frame_bytes = (Hp + S0 - P) * Wpp0 * 4
    bc = min(
        _smem_batch_limit(4, K, KB),
        max(1, (3 << 29) // (KB * frame_bytes)),
    )
    if B > bc:
        return _chunk_batch(
            lambda *a: _extract_blended_planes_slab(
                *a, P, with_moments=with_moments, interpret=interpret,
                out_dtype=out_dtype,
            ),
            bc, B, (padded, oy, ox, fx, fy), with_moments,
        )
    oy, ox, fx, fy = _pad_keypoint_axis(KB, oy, ox, fx, fy)
    Kp = oy.shape[1]
    S, Wpp = _slab_dims(P, Wp, padded.dtype.itemsize)
    padded = jnp.pad(padded, ((0, 0), (0, S - P), (0, Wpp - Wp)), mode="edge")
    Hpp = Hp + S - P

    oy = oy.astype(jnp.int32)
    ox = ox.astype(jnp.int32)
    oy8 = oy // 8
    ry = oy - oy8 * 8
    ox128 = ox // 128
    rx = ox - ox128 * 128

    Pb = P - 1
    mm = _moment_maps(P)
    mm_in = jnp.asarray(
        np.concatenate([mm[:, :, 0].reshape(4, P, P), mm[:, :, 1].reshape(4, P, P)])
    )

    def slab_spec(j):
        return pl.BlockSpec(
            (pl.Element(1), pl.Element(S), pl.Element(_WIN)),
            lambda b, kb, oy8r, ox128r, ryr, rxr, j=j: (
                b, oy8r[b, kb * KB + j] * 8, ox128r[b, kb * KB + j] * 128
            ),
        )

    frac_spec = pl.BlockSpec(
        (None, KB, 1), lambda b, kb, oy8r, ox128r, ryr, rxr: (b, kb, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, Kp // KB),
        in_specs=[slab_spec(j) for j in range(KB)]
        + [
            frac_spec,
            frac_spec,
            pl.BlockSpec((8, P, P), lambda b, kb, oy8r, ox128r, ryr, rxr: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, KB, Pb, Pb), lambda b, kb, oy8r, ox128r, ryr, rxr: (b, kb, 0, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy8r, ox128r, ryr, rxr: (b, kb, 0)),
            pl.BlockSpec((None, KB, 1), lambda b, kb, oy8r, ox128r, ryr, rxr: (b, kb, 0)),
        ],
    )
    kernel = functools.partial(
        _blended_slab_kernel, P=P, KB=KB, with_moments=with_moments
    )
    pb, m10, m01 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Kp, Pb, Pb), out_dtype),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Kp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(
        oy8, ox128, ry, rx,
        *([padded.astype(jnp.float32)] * KB),
        fx, fy, mm_in,
    )
    if with_moments:
        return pb[:, :K], m10[:, :K], m01[:, :K]
    return pb[:, :K]


def _blended3d_kernel(*refs, Pz: int, Pxy: int, KB: int):
    """KB keypoints per program; input spec j holds keypoint j's
    (Pz, SY, Wp) slab, Element-indexed at (oz, 8-aligned oy) — the
    dynamic block indexing that makes per-keypoint 3D extraction
    possible without the whole flattened volume in VMEM."""
    # prefetch: oz, oy8 (index maps), ry, ox (kernel); then KB slabs,
    # fractions, output.
    ozr, oy8r, ryr, oxr = refs[:4]
    slabs = refs[4 : 4 + KB]
    fx_ref, fy_ref, fz_ref = refs[4 + KB : 7 + KB]
    out_ref = refs[7 + KB]
    b = pl.program_id(0)
    kb = pl.program_id(1)
    Pb = Pxy - 1
    for j in range(KB):
        k = kb * KB + j
        slab = slabs[j][0]  # (Pz, SY, Wp)
        SY, Wp = slab.shape[1], slab.shape[2]
        fx = fx_ref[j, 0]
        fy = fy_ref[j, 0]
        fz = fz_ref[j, 0]
        # Separable in-plane lerp BEFORE the cut, as static +1 rolls on
        # the full-width slab (the 2D kernels' round-5 form): the 4-tap
        # blend's 1-offset (Pz, Pb, Pb) taps each paid a misaligned-
        # view relayout. Wrap safety: the y-wrap garbage lands at row
        # SY-1 (reads stop at ry + Pxy <= SY - 1 for ry < 8) and the
        # x-wrap at lane Wp-1 (origins sit >= 128 lanes from the padded
        # right edge). Same trilinear value, different grouping — the
        # jnp oracle's 8-corner blend already differs from the old
        # per-slice 4-tap at tie level, covered by the describe3d
        # tolerance contract.
        yb = (1.0 - fy) * slab + fy * pltpu.roll(slab, SY - 1, 1)
        xb = (1.0 - fx) * yb + fx * pltpu.roll(yb, Wp - 1, 2)
        v = pltpu.roll(xb, SY - ryr[b, k], 1)
        v = pltpu.roll(v, Wp - oxr[b, k], 2)
        pb2 = v[:, :Pb, :Pb]  # (Pz, Pb, Pb) in-plane bilinear per slice
        out_ref[j] = (1.0 - fz) * pb2[: Pz - 1] + fz * pb2[1:]


@functools.partial(jax.jit, static_argnames=("Pz", "Pxy", "interpret"))
def extract_blended_3d(
    padded: jnp.ndarray,
    xyz: jnp.ndarray,
    Pz: int,
    Pxy: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Keypoint-first trilinear-blended 3D patches.

    padded: (B, Dp, Hp, Wp) volumes edge-padded by (rz+1, rxy+1, rxy+1)
    (the describe3d convention); xyz: (B, K, 3) subpixel (x, y, z)
    keypoint positions. Returns (B, K, Pz-1, Pxy-1, Pxy-1) — the
    trilinear resample of each patch at its keypoint's subpixel
    fraction (z-lerp of per-slice bilinear blends — the exact
    decomposition of the 8-corner blend).

    Each keypoint's slab arrives as its own Element-indexed input block
    (z start exact, y start 8-aligned with the residual rolled out, x
    selected by a lane roll), so VMEM holds only KB tiny slabs — not
    the volume.
    """
    if not ELEMENT_INDEXING:
        raise NotImplementedError(
            "this jax/pallas build lacks pl.Element (element-indexed "
            "BlockSpecs), which the 3D slab descriptor layout requires "
            "— use the XLA gather describe path (use_pallas=False)"
        )
    B, Dp, Hp, Wp0 = padded.shape
    K = xyz.shape[1]
    bc = _smem_batch_limit(4, K, 8)
    if B > bc:  # chunk the batch to keep scalar prefetch within SMEM
        return jnp.concatenate(
            [
                extract_blended_3d(
                    padded[i : i + bc], xyz[i : i + bc], Pz, Pxy,
                    interpret=interpret,
                )
                for i in range(0, B, bc)
            ]
        )
    x0 = jnp.floor(xyz[..., 0])
    y0 = jnp.floor(xyz[..., 1])
    z0 = jnp.floor(xyz[..., 2])
    oz = z0.astype(jnp.int32) + 1
    oy = y0.astype(jnp.int32) + 1
    ox = x0.astype(jnp.int32) + 1
    fx = (xyz[..., 0] - x0)[..., None].astype(jnp.float32)
    fy = (xyz[..., 1] - y0)[..., None].astype(jnp.float32)
    fz = (xyz[..., 2] - z0)[..., None].astype(jnp.float32)
    KB = 8
    if K % KB:
        pad = KB - K % KB
        z = jnp.zeros((B, pad), jnp.int32)
        zf = jnp.zeros((B, pad, 1), jnp.float32)
        oz = jnp.concatenate([oz, z], axis=1)
        oy = jnp.concatenate([oy, z], axis=1)
        ox = jnp.concatenate([ox, z], axis=1)
        fx = jnp.concatenate([fx, zf], axis=1)
        fy = jnp.concatenate([fy, zf], axis=1)
        fz = jnp.concatenate([fz, zf], axis=1)
    Kp = oz.shape[1]
    SY = ((Pxy + 7) // 8) * 8 + 8  # aligned rows covering Pxy + residual
    # Margins for the aligned/over-length reads.
    Wp = -(-(Wp0 + 128) // 128) * 128
    padded = jnp.pad(
        padded,
        ((0, 0), (0, Pz), (0, SY), (0, Wp - Wp0)),
        mode="edge",
    )
    Dpp, Hpp = padded.shape[1], padded.shape[2]
    oy8 = oy // 8
    ry = oy - oy8 * 8

    def slab_spec(j):
        return pl.BlockSpec(
            (pl.Element(1), pl.Element(Pz), pl.Element(SY), pl.Element(Wp)),
            lambda b, kb, ozr, oy8r, ryr, oxr, j=j: (
                b, ozr[b, kb * KB + j], oy8r[b, kb * KB + j] * 8, 0
            ),
        )

    frac_spec = pl.BlockSpec(
        (None, KB, 1), lambda b, kb, ozr, oy8r, ryr, oxr: (b, kb, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, Kp // KB),
        in_specs=[slab_spec(j) for j in range(KB)]
        + [frac_spec, frac_spec, frac_spec],
        out_specs=pl.BlockSpec(
            (None, KB, Pz - 1, Pxy - 1, Pxy - 1),
            lambda b, kb, ozr, oy8r, ryr, oxr: (b, kb, 0, 0, 0),
        ),
    )
    out = pl.pallas_call(
        functools.partial(_blended3d_kernel, Pz=Pz, Pxy=Pxy, KB=KB),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (B, Kp, Pz - 1, Pxy - 1, Pxy - 1), jnp.float32
        ),
        interpret=interpret,
    )(
        oz, oy8, ry.astype(jnp.int32), ox,
        *([padded.astype(jnp.float32)] * KB),
        fx, fy, fz,
    )
    return out[:, :K]


@functools.partial(jax.jit, static_argnames=("P", "interpret"))
def extract_patches(
    padded: jnp.ndarray,
    oy: jnp.ndarray,
    ox: jnp.ndarray,
    P: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, Hp, Wp) padded frames + (B, K) int32 origins -> (B, K, P, P).

    patches[b, k, i, j] = padded[b, oy[b,k] + i, ox[b,k] + j].
    Origins must satisfy 0 <= oy <= Hp - P and 0 <= ox <= Wp - P (the
    callers clamp; the slab slice is clamped to the padded footprint by
    Mosaic's slice semantics).
    """
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    KB = _KB
    bc = _smem_batch_limit(2, K, KB)
    if B > bc:  # chunk the batch to keep scalar prefetch within SMEM
        return jnp.concatenate(
            [
                extract_patches(
                    padded[i : i + bc], oy[i : i + bc], ox[i : i + bc], P,
                    interpret=interpret,
                )
                for i in range(0, B, bc)
            ]
        )
    if K % KB:  # pad the keypoint axis up; callers slice the tail off
        pad = KB - K % KB
        oy = jnp.concatenate([oy, jnp.zeros((B, pad), oy.dtype)], axis=1)
        ox = jnp.concatenate([ox, jnp.zeros((B, pad), ox.dtype)], axis=1)
    Kp = oy.shape[1]
    # The kernel reads an 8-aligned row slab at or before each origin and
    # a 128-aligned lane window at or before it; give the frame the
    # bottom/right margins those aligned reads can overrun.
    S, Wpp = _slab_dims(P, Wp)
    padded = jnp.pad(
        padded, ((0, 0), (0, S - P), (0, Wpp - Wp)), mode="edge"
    )
    Hp = Hp + S - P

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Kp // KB),
        in_specs=[
            pl.BlockSpec((None, Hp, Wpp), lambda b, kb, oy, ox: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, KB, P, P), lambda b, kb, oy, ox: (b, kb, 0, 0)
        ),
    )
    kernel = functools.partial(_patch_kernel, P=P, KB=KB)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kp, P, P), jnp.float32),
        interpret=interpret,
    )(
        oy.astype(jnp.int32), ox.astype(jnp.int32),
        padded.astype(jnp.float32),
    )
    return out[:, :K]


def binned_select_rows(
    flat: jnp.ndarray,  # (B, Kp, L) bin-sorted rows (aligned runs)
    ibin: jnp.ndarray,  # (B, Kp // align) int32 bin per align-row block
    sel: jnp.ndarray,  # (nb, L, V) per-bin selection stack
    align: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Dynamic-block selection matmul, in sorted layout: each align-row
    block of `flat` is multiplied by ITS bin's (L, V) selection matrix,
    chosen per program via scalar prefetch driving the sel BlockSpec's
    index map. Returns (B, Kp, V) in the same sorted layout.

    This replaces the round-5 dispatch-layout pipeline (dispatch_copy
    to a (B, nb, cap, L) capacity layout + one batched einsum) for the
    bins-first describe path: measured 6.1 + 2.5 ms/batch at config-2
    scale against ~3 ms here — the M=align matmul runs the MXU at
    12.5% occupancy, but the capacity layout's extra HBM round trip,
    its trash group, and its per-bin capacity DROPS all disappear
    (every keypoint is selected with its run's matrix; orientation
    skew can no longer drop descriptors). Runs are align-aligned by
    construction so a block never spans two bins; consecutive programs
    mostly share a bin, so the sel block is revisited, not re-fetched.

    Exactness: 0/1 one-hot weights with one nonzero per column under
    f32 accumulation select bf16 values exactly in any contraction
    order — bit-identical to the einsum it replaces.
    """
    B, Kp, L = flat.shape
    nb, _, V = sel.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, Kp // align),
        in_specs=[
            pl.BlockSpec((None, align, L), lambda b, kb, ibin: (b, kb, 0)),
            pl.BlockSpec(
                (None, L, V),
                # alignment-padding tail blocks carry bin nb (sentinel):
                # clamp to a real matrix; their rows scatter to the
                # dropped index downstream
                lambda b, kb, ibin: (jnp.minimum(ibin[b, kb], nb - 1), 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec(
            (None, align, V), lambda b, kb, ibin: (b, kb, 0)
        ),
    )

    def kernel(ibin_ref, x_ref, sel_ref, out_ref):
        del ibin_ref
        out_ref[...] = jax.lax.dot_general(
            x_ref[...], sel_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(out_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kp, V), flat.dtype),
        interpret=interpret,
    )(ibin.astype(jnp.int32), flat, sel)


def _moment_band_structure():
    """Disc rows grouped by half-width: {w: [dy, ...]} from the shared
    MOMENTS constant, so the kernel and the conv fallback can never
    disagree about the disc."""
    from kcmc_tpu.ops.patterns import MOMENT_RADIUS, MOMENTS

    mr = MOMENT_RADIUS
    by_w: dict[int, list[int]] = {}
    for i in range(2 * mr + 1):
        inside = MOMENTS[i, :, 2] > 0
        w = int(np.max(np.abs(MOMENTS[i, inside, 0]))) if inside.any() else -1
        if w >= 0:
            by_w.setdefault(w, []).append(i - mr)
    return mr, by_w


_MOM_STRIP = 128  # output rows per moment-map program. Two measured
# constraints: (1) Mosaic keeps every shifted-view temporary of a
# pure-value width loop live on the kernel stack (a whole-frame 512²
# program allocated 49.8 MB of scoped vmem — ~42 map-sized temps — and
# died), so hx/sx accumulate IN SCRATCH REFS — which still leaves a
# measured ~35-temp stack (19.3 MB at 256-row strips: the dx-step
# slice/product temporaries), so 128 rows it is (~10 MB); (2) small
# strips lose to per-program overhead (64-row strips = 288 programs
# measured ~9 ms/batch).


def moment_maps_supported(padded_shape: tuple[int, int]) -> bool:
    """VMEM gate for the strip moment-maps kernel: ~8 live strip-sized
    f32 arrays (input upcast, hx/sx scratch, two out blocks, slack —
    scratch accumulation pins the width loop's footprint)."""
    Hp, Wp = padded_shape
    rows = _MOM_STRIP + 14  # + 2 * MOMENT_RADIUS
    return rows * Wp * (2 + 36 * 4) <= 16 * 1024 * 1024


@functools.partial(jax.jit, static_argnames=("interpret",))
def moment_maps(padded: jnp.ndarray, interpret: bool = False):
    """ORB intensity-centroid moment maps (m10, m01) over a padded
    batch — the frame-level moments of the bins-first describe path.

    padded: (B, Hp, Wp) (bf16 or f32, the describe quantization
    convention). Returns two (B, Hp - 2mr, Wp - 2mr) f32 maps;
    maps[i, j] is the disc moment centered at padded[i + mr, j + mr]
    (identical indexing to a VALID lax.conv with the _MOMENT_KERNELS —
    which XLA lowers at ~27 ms/batch for a 32x512² batch because the
    1-in/2-out channel conv cannot tile the MXU).

    Structure: the disc is a stack of constant-half-width row bands, so
    each distinct width w needs ONE dx-weighted horizontal pass (for
    m10) and ONE horizontal box pass (for m01), then its band rows
    accumulate with pure vertical shifts (dy-weighted for m01). Row
    strips are stacked on the host (the pallas_warp_field pattern —
    overlapping windows cannot be Pallas block indexing), sized by the
    measured ~45-temp kernel stack (_MOM_STRIP).
    """
    B, Hp, Wp = padded.shape
    mr, by_w = _moment_band_structure()
    Hm, Wm = Hp - 2 * mr, Wp - 2 * mr
    R = _MOM_STRIP
    S = -(-Hm // R)
    rows = R + 2 * mr
    # strip s computes output rows [s*R, s*R + R) from padded rows
    # [s*R, s*R + R + 2mr); pad the bottom so the last strip's window
    # exists (its extra output rows are sliced off)
    pad_rows = (S - 1) * R + rows - Hp
    src = jnp.pad(padded, ((0, 0), (0, max(0, pad_rows)), (0, 0)), mode="edge")
    strips = jnp.stack(
        [
            jax.lax.slice_in_dim(src, s * R, s * R + rows, axis=1)
            for s in range(S)
        ],
        axis=1,
    )  # (B, S, rows, Wp)

    def kernel(in_ref, m10_ref, m01_ref, hx_ref, sx_ref):
        p = in_ref[...].astype(jnp.float32)  # (rows, Wp)
        m10_ref[...] = jnp.zeros((R, Wm), jnp.float32)
        m01_ref[...] = jnp.zeros((R, Wm), jnp.float32)
        for w, dys in sorted(by_w.items()):
            # accumulate the horizontal passes in scratch: a pure-value
            # formulation keeps every += step's temporary live on the
            # kernel stack (measured 49.8 MB scoped-vmem OOM)
            hx_ref[...] = jnp.zeros((rows, Wm), jnp.float32)
            sx_ref[...] = jnp.zeros((rows, Wm), jnp.float32)
            for dx in range(-w, w + 1):
                v = p[:, mr + dx : mr + dx + Wm]
                sx_ref[...] = sx_ref[...] + v
                if dx:
                    hx_ref[...] = hx_ref[...] + float(dx) * v
            for dy in dys:
                m10_ref[...] = (
                    m10_ref[...] + hx_ref[mr + dy : mr + dy + R, :]
                )
                if dy:
                    m01_ref[...] = m01_ref[...] + float(dy) * sx_ref[
                        mr + dy : mr + dy + R, :
                    ]

    out = pl.pallas_call(
        kernel,
        grid=(B, S),
        in_specs=[
            pl.BlockSpec((None, None, rows, Wp), lambda b, s: (b, s, 0, 0))
        ],
        out_specs=[
            pl.BlockSpec((None, R, Wm), lambda b, s: (b, s, 0)),
            pl.BlockSpec((None, R, Wm), lambda b, s: (b, s, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S * R, Wm), jnp.float32),
            jax.ShapeDtypeStruct((B, S * R, Wm), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, Wm), jnp.float32),
            pltpu.VMEM((rows, Wm), jnp.float32),
        ],
        interpret=interpret,
    )(strips)
    return out[0][:, :Hm], out[1][:, :Hm]
