"""Pallas TPU kernel: batched per-keypoint patch extraction.

XLA lowers a vmapped `dynamic_slice` with data-dependent origins to a
gather that moves ~1 GB/s on TPU (measured) — ~70 ms per 64-frame batch
of 512 keypoints, the single largest cost in the registration pipeline.
This kernel does the same extraction at memory speed:

* grid = (frames, keypoint blocks); the padded frame lives in VMEM once
  per frame (rows of the grid iterate keypoint blocks fastest, so the
  frame block is revisited, not re-fetched).
* Per keypoint, one DYNAMIC WINDOW SLICE cuts an aligned (S, 256) slab:
  sublane-dim starts must be provably 8-aligned and lane-dim starts
  128-aligned, so the slice starts at the aligned floor of the origin
  and covers the residual. Two `pltpu.roll`s (sublane then lane) rotate
  the patch to the slab's corner, and a static (P, P) slice cuts it out
  — no gathers, no matmuls. (Earlier revisions selected columns with a
  one-hot MXU matmul; rolling the pre-sliced 256-lane window is ~1.8x
  faster — the matmul's contraction over the window width was the cost,
  not the rotate.) Roll amounts are non-negative (dim - shift): Mosaic
  mis-wraps negative dynamic amounts on multi-tile arrays.
* Origins arrive via scalar prefetch, so the kernel is fully static.

Returns patches in the (B, K, P, P) layout the describe stages consume.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_WIN = 256  # lane window: covers the 128-alignment residual + patch width
_KB = 16  # keypoints per program (measured best on v5e)


def _patch_kernel(oy_ref, ox_ref, src_ref, out_ref, *, P: int, KB: int):
    b = pl.program_id(0)
    kb = pl.program_id(1)
    S = ((P + 7) // 8) * 8 + 8  # aligned slab rows covering P + residual
    for i in range(KB):
        k = kb * KB + i
        y0 = oy_ref[b, k]
        x0 = ox_ref[b, k]
        y0a = (y0 // 8) * 8
        x0a = (x0 // 128) * 128
        slab = src_ref[pl.ds(y0a, S), pl.ds(x0a, _WIN)]  # (S, _WIN)
        slab = pltpu.roll(slab, S - (y0 - y0a), 0)
        slab = pltpu.roll(slab, _WIN - (x0 - x0a), 1)
        out_ref[i] = slab[:P, :P]


@functools.partial(jax.jit, static_argnames=("P", "interpret"))
def extract_patches(
    padded: jnp.ndarray,
    oy: jnp.ndarray,
    ox: jnp.ndarray,
    P: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, Hp, Wp) padded frames + (B, K) int32 origins -> (B, K, P, P).

    patches[b, k, i, j] = padded[b, oy[b,k] + i, ox[b,k] + j].
    Origins must satisfy 0 <= oy <= Hp - P and 0 <= ox <= Wp - P (the
    callers clamp; the slab slice is clamped to the padded footprint by
    Mosaic's slice semantics).
    """
    B, Hp, Wp = padded.shape
    K = oy.shape[1]
    KB = _KB
    if K % KB:  # pad the keypoint axis up; callers slice the tail off
        pad = KB - K % KB
        oy = jnp.concatenate([oy, jnp.zeros((B, pad), oy.dtype)], axis=1)
        ox = jnp.concatenate([ox, jnp.zeros((B, pad), ox.dtype)], axis=1)
    Kp = oy.shape[1]
    # The kernel reads an 8-aligned row slab at or before each origin and
    # a 128-aligned lane window at or before it; give the frame the
    # bottom/right margins those aligned reads can overrun.
    S = ((P + 7) // 8) * 8 + 8
    Wpp = -(-(Wp + _WIN) // 128) * 128
    padded = jnp.pad(
        padded, ((0, 0), (0, S - P), (0, Wpp - Wp)), mode="edge"
    )
    Hp = Hp + S - P

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Kp // KB),
        in_specs=[
            pl.BlockSpec((None, Hp, Wpp), lambda b, kb, oy, ox: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, KB, P, P), lambda b, kb, oy, ox: (b, kb, 0, 0)
        ),
    )
    kernel = functools.partial(_patch_kernel, P=P, KB=KB)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Kp, P, P), jnp.float32),
        interpret=interpret,
    )(
        oy.astype(jnp.int32), ox.astype(jnp.int32),
        padded.astype(jnp.float32),
    )
    return out[:, :K]
