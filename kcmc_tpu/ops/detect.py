"""Keypoint detection: Harris corner response, NMS, fixed-K top-k, subpixel.

TPU-native counterpart of the reference's `KeypointExtractor` detect
stage (SURVEY.md §2 — reference source unavailable; contract from
BASELINE.json). Design choices for the TPU:

* Harris response is built from 3x3 convolutions (`lax.conv`) — these
  map onto the MXU/VPU and fuse with the surrounding elementwise ops.
* Non-max suppression is a max-pool equality test — no sorting, no
  dynamic shapes.
* "Detect the strongest corners above a threshold" becomes a fixed-K
  `lax.top_k` plus a validity mask (`score > threshold`), so every frame
  yields exactly K keypoint slots and the downstream pipeline stays
  statically shaped (SURVEY.md §7: fixed-K keypoint selection).
* Subpixel refinement fits a 2D quadratic to the 3x3 response
  neighborhood of each keypoint. This matters for accuracy: a pure
  integer-grid detector quantizes the recovered drift to whole pixels.

All functions operate on a single (H, W) frame and are `vmap`ed over the
frame batch by the pipeline.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class Keypoints(NamedTuple):
    """Fixed-K keypoints for one frame (or a batch, with leading axes)."""

    xy: jnp.ndarray  # (K, 2) float32 (x, y) subpixel positions
    score: jnp.ndarray  # (K,) Harris response at the keypoint
    valid: jnp.ndarray  # (K,) bool — False for padded slots


def _conv2d(img: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Same-padding 2D convolution of a (H, W) image with a small kernel."""
    out = lax.conv_general_dilated(
        img[None, None, :, :],
        kernel[None, None, :, :],
        window_strides=(1, 1),
        padding="SAME",
    )
    return out[0, 0]


def _gaussian_kernel1d(sigma: float, radius: int) -> jnp.ndarray:
    x = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / sigma) ** 2)
    return k / jnp.sum(k)


def gaussian_blur(img: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Separable Gaussian blur of a (H, W) image."""
    radius = max(1, int(3.0 * sigma + 0.5))
    k = _gaussian_kernel1d(sigma, radius)
    img = _conv2d(img, k[None, :])
    img = _conv2d(img, k[:, None])
    return img


_SOBEL_X = jnp.array(
    [[-1.0, 0.0, 1.0], [-2.0, 0.0, 2.0], [-1.0, 0.0, 1.0]], dtype=jnp.float32
) / 8.0
_SOBEL_Y = _SOBEL_X.T


def harris_response(
    img: jnp.ndarray, k: float = 0.04, window_sigma: float = 1.5
) -> jnp.ndarray:
    """Harris corner response R = det(M) - k * trace(M)^2 per pixel.

    M is the Gaussian-windowed structure tensor of the image gradients.
    """
    gx = _conv2d(img, _SOBEL_X)
    gy = _conv2d(img, _SOBEL_Y)
    ixx = gaussian_blur(gx * gx, window_sigma)
    iyy = gaussian_blur(gy * gy, window_sigma)
    ixy = gaussian_blur(gx * gy, window_sigma)
    det = ixx * iyy - ixy * ixy
    trace = ixx + iyy
    return det - k * trace * trace


def _maxpool_same(x: jnp.ndarray, size: int) -> jnp.ndarray:
    return lax.reduce_window(
        x,
        -jnp.inf,
        lax.max,
        window_dimensions=(size, size),
        window_strides=(1, 1),
        padding="SAME",
    )


def _subpixel_offset(patch: jnp.ndarray) -> jnp.ndarray:
    """Quadratic-fit subpixel offset from a 3x3 response patch.

    Fits separable 1D parabolas along x and y through the center; the
    offset is clamped to [-0.5, 0.5] (beyond that the integer NMS peak
    would have been elsewhere).
    """
    c = patch[1, 1]
    dx = 0.5 * (patch[1, 2] - patch[1, 0])
    dy = 0.5 * (patch[2, 1] - patch[0, 1])
    dxx = patch[1, 2] - 2.0 * c + patch[1, 0]
    dyy = patch[2, 1] - 2.0 * c + patch[0, 1]
    ox = jnp.where(jnp.abs(dxx) > 1e-8, -dx / dxx, 0.0)
    oy = jnp.where(jnp.abs(dyy) > 1e-8, -dy / dyy, 0.0)
    return jnp.clip(jnp.stack([ox, oy]), -0.5, 0.5)


@functools.partial(jax.jit, static_argnames=("max_keypoints", "nms_size", "border"))
def detect_keypoints(
    img: jnp.ndarray,
    max_keypoints: int = 512,
    threshold: float = 1e-6,
    nms_size: int = 5,
    border: int = 16,
    harris_k: float = 0.04,
) -> Keypoints:
    """Detect up to `max_keypoints` Harris corners in a (H, W) frame.

    Returns fixed-K arrays; `valid[i]` is False for slots whose response
    fell at/below `threshold` (relative to the frame's peak response).
    """
    H, W = img.shape
    resp = harris_response(img, k=harris_k)
    # NMS: keep strict local maxima of the response.
    is_max = resp >= _maxpool_same(resp, nms_size)
    # Exclude a border so descriptor patches stay in bounds.
    ys = jnp.arange(H)[:, None]
    xs = jnp.arange(W)[None, :]
    inb = (ys >= border) & (ys < H - border) & (xs >= border) & (xs < W - border)
    # Threshold is relative to the frame's max response: robust to
    # global contrast changes across frames.
    peak = jnp.maximum(jnp.max(resp), 1e-12)
    masked = jnp.where(is_max & inb & (resp > threshold * peak), resp, -jnp.inf)

    scores, flat_idx = lax.top_k(masked.reshape(-1), max_keypoints)
    iy = flat_idx // W
    ix = flat_idx % W
    valid = jnp.isfinite(scores)

    # Subpixel: quadratic fit on the 3x3 neighborhood of each peak.
    def patch_at(y, x):
        return lax.dynamic_slice(resp, (y - 1, x - 1), (3, 3))

    patches = jax.vmap(patch_at)(jnp.clip(iy, 1, H - 2), jnp.clip(ix, 1, W - 2))
    offsets = jax.vmap(_subpixel_offset)(patches)  # (K, 2) (ox, oy)

    xy = jnp.stack([ix.astype(jnp.float32), iy.astype(jnp.float32)], axis=-1)
    xy = xy + jnp.where(valid[:, None], offsets, 0.0)
    scores = jnp.where(valid, scores, 0.0)
    xy = jnp.where(valid[:, None], xy, 0.0)
    return Keypoints(xy=xy, score=scores, valid=valid)
