"""Keypoint detection: Harris corner response, NMS, fixed-K top-k, subpixel.

TPU-native counterpart of the reference's `KeypointExtractor` detect
stage (SURVEY.md §2 — reference source unavailable; contract from
BASELINE.json). Design choices for the TPU:

* Harris response is built from SEPARABLE 1D convolutions (Sobel as
  smooth x diff, Gaussian window as two 1D passes) — XLA's fast TPU
  path; a 2D 3x3 single-channel conv lowers ~200x slower.
* Non-max suppression is a (separable) max-pool equality test — no
  sorting, no dynamic shapes.
* "Detect the strongest corners above a threshold" becomes: strongest
  surviving pixel per CAND_TILE x CAND_TILE tile (grid-bucketed spatial
  spreading, at most one keypoint per tile), then a fixed-K selection
  over the tile winners — one stable `sort_key_val`, NOT `lax.top_k`,
  whose partial-selection lowering is 13x slower at these shapes — plus
  a validity mask (`score > threshold`), so every frame yields exactly
  K keypoint slots and the downstream pipeline stays statically shaped
  (SURVEY.md §7: fixed-K selection).
* Subpixel refinement fits separable quadratics to the response around
  each peak, computed as dense offset fields (pure elementwise shifts)
  and sampled at the K peaks. This matters for accuracy: a pure
  integer-grid detector quantizes the recovered drift to whole pixels.

All functions operate on a single (H, W) frame and are `vmap`ed over the
frame batch by the pipeline.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.ops.patterns import CAND_TILE, WINDOW_SIGMA


class Keypoints(NamedTuple):
    """Fixed-K keypoints for one frame (or a batch, with leading axes)."""

    xy: jnp.ndarray  # (K, 2) float32 (x, y) subpixel positions
    score: jnp.ndarray  # (K,) Harris response at the keypoint
    valid: jnp.ndarray  # (K,) bool — False for padded slots


def _conv2d(img: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Same-padding 2D convolution of a (H, W) image with a small kernel.

    Precision.HIGHEST: the default conv precision truncates f32 inputs
    (~bf16 — measured 0.4% relative response error vs a float64 oracle,
    on both the TPU and CPU backends), which is enough to flip NMS
    comparisons between near-equal corner responses. The fused Pallas
    detection kernel (ops/pallas_detect.py) computes the same math in
    true f32; the two paths agree only with exact convs here.
    """
    out = lax.conv_general_dilated(
        img[None, None, :, :],
        kernel[None, None, :, :],
        window_strides=(1, 1),
        padding="SAME",
        precision=lax.Precision.HIGHEST,
    )
    return out[0, 0]


def _gaussian_kernel1d(sigma: float, radius: int) -> jnp.ndarray:
    x = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / sigma) ** 2)
    return k / jnp.sum(k)


def gaussian_blur(img: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Separable Gaussian blur of a (H, W) image."""
    radius = max(1, int(3.0 * sigma + 0.5))
    k = _gaussian_kernel1d(sigma, radius)
    img = _conv2d(img, k[None, :])
    img = _conv2d(img, k[:, None])
    return img


# Sobel, separably: outer([1,2,1]/4, [-1,0,1]/2). XLA lowers 1D spatial
# convs to fast vectorized passes, but a 2D 3x3 single-channel conv hits
# a slow TPU path (measured ~200x slower than the two 1D passes).
_SOBEL_SMOOTH = jnp.array([1.0, 2.0, 1.0], dtype=jnp.float32) / 4.0
_SOBEL_DIFF = jnp.array([-1.0, 0.0, 1.0], dtype=jnp.float32) / 2.0


def harris_response(
    img: jnp.ndarray, k: float = 0.04, window_sigma: float = WINDOW_SIGMA
) -> jnp.ndarray:
    """Harris corner response R = det(M) - k * trace(M)^2 per pixel.

    M is the Gaussian-windowed structure tensor of the image gradients.
    """
    gx = _conv2d(_conv2d(img, _SOBEL_SMOOTH[:, None]), _SOBEL_DIFF[None, :])
    gy = _conv2d(_conv2d(img, _SOBEL_SMOOTH[None, :]), _SOBEL_DIFF[:, None])
    ixx = gaussian_blur(gx * gx, window_sigma)
    iyy = gaussian_blur(gy * gy, window_sigma)
    ixy = gaussian_blur(gx * gy, window_sigma)
    det = ixx * iyy - ixy * ixy
    trace = ixx + iyy
    return det - k * trace * trace


def sorted_top_k(vals: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(top-k values desc, their indices) of a 1D array via one stable
    full `sort_key_val` — NOT `lax.top_k`, whose partial-selection
    lowering is 13x slower at detection shapes on the v5e (measured
    1.08 vs 0.08 ms/frame at n=4096/k=512, worse at k=4096). A stable
    descending sort returns the identical values AND tie order (lowest
    index first). Shared by the 2D and 3D keypoint selectors.

    Negative result (round 3, DESIGN.md "Large-frame support"): a
    grouped two-stage split (batched 4096-wide group sorts, then a
    merge sort of the g*k prefix survivors — bit-identical by a
    prefix-exclusion argument) measured EQUAL to this single sort at
    n=16k-65k under interleaved sustained timing (~0.3-0.4 ms/frame
    both ways at batch 8); an apparent 6x win was a cold-measurement
    artifact. The single sort stays: same speed, less machinery.
    """
    neg, idx = lax.sort_key_val(
        -vals, jnp.arange(vals.shape[0], dtype=jnp.int32)
    )
    return -neg[:k], idx[:k]


def tile_max_argmax(resp: jnp.ndarray, T: int):
    """Per-(T, T)-tile max and first-in-row-major argmax of a dense
    response, via two reduce_window passes (no reshape/transpose, no
    full-field masked copies) — the shared core of the 2D and 3D
    tile-aligned selection fast paths. `resp` is (..., H, W) with any
    leading axes (the 3D path passes (D, H, W): z planes tile
    independently); H and W must be T-multiples (callers gate).

    The argmax tie rule matches `jnp.argmax` over the row-major (T, T)
    flatten exactly: the eq-mask min over (r % T) * T + (c % T) picks
    the lowest within-tile row-major index among maximal pixels.
    """
    nd = resp.ndim
    win = (1,) * (nd - 2) + (T, T)
    tile_val = lax.reduce_window(
        resp, -jnp.inf, lax.max, win, win, "VALID"
    )
    up = jnp.repeat(jnp.repeat(tile_val, T, nd - 2), T, nd - 1)
    ii = (
        lax.broadcasted_iota(jnp.int32, resp.shape, nd - 2) % T * T
        + lax.broadcasted_iota(jnp.int32, resp.shape, nd - 1) % T
    )
    tile_arg = lax.reduce_window(
        jnp.where(resp == up, ii, jnp.int32(1) << 20),
        jnp.int32(1) << 20, lax.min, win, win, "VALID",
    ).astype(jnp.int32)
    return tile_val, tile_arg


def valid_extent_mask(
    shape: tuple[int, int], border: int, valid_hw: jnp.ndarray
) -> jnp.ndarray:
    """Selectable-region mask for a frame zero-PADDED to `shape` whose
    true content occupies the top-left `valid_hw` = (h, w) extent (the
    execution-plan shape buckets, kcmc_tpu/plans).

    Keypoints must come only from [border, h-border) x [border,
    w-border): the pad boundary's response ridge (real content against
    the zero pad) would otherwise inflate the frame's peak response and
    crowd the fixed-K selection — the exact border-ring trap the
    relative threshold already dodges at the frame edge. Masking
    `nms_resp` to -inf outside this region makes padded detection
    IDENTICAL to detection on the unpadded frame: zero padding + the
    SAME-zero-padding convolutions leave every response value inside
    the valid region bit-equal, and selection sees the identical
    candidate set. `valid_hw` is a traced (2,) int array, so one
    compiled program serves every true extent within the bucket.
    """
    H, W = shape
    h = valid_hw[0]
    w = valid_hw[1]
    ys = jnp.arange(H, dtype=jnp.int32)[:, None]
    xs = jnp.arange(W, dtype=jnp.int32)[None, :]
    return (
        (ys >= border) & (ys < h - border) & (xs >= border) & (xs < w - border)
    )


def _maxpool_same(x: jnp.ndarray, size: int) -> jnp.ndarray:
    # Separable: max over rows then columns (max is associative/idempotent).
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (size, 1), (1, 1), "SAME"
    )
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, size), (1, 1), "SAME"
    )


def _subpixel_fields(resp: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dense quadratic-fit subpixel offsets (ox, oy) per pixel.

    Separable 1D parabola fits through each pixel and its axis
    neighbors, clamped to [-0.5, 0.5] (beyond that the integer NMS peak
    would have been elsewhere). Computing the whole field is a handful
    of fused elementwise shifts — far cheaper on TPU than cutting a 3x3
    patch per keypoint — and the per-keypoint values are then two tiny
    pointwise gathers.
    """
    r = jnp.pad(resp, 1, mode="edge")
    c = resp
    left, right = r[1:-1, :-2], r[1:-1, 2:]
    up, down = r[:-2, 1:-1], r[2:, 1:-1]
    dx = 0.5 * (right - left)
    dy = 0.5 * (down - up)
    dxx = right - 2.0 * c + left
    dyy = down - 2.0 * c + up
    ox = jnp.where(jnp.abs(dxx) > 1e-8, -dx / dxx, 0.0)
    oy = jnp.where(jnp.abs(dyy) > 1e-8, -dy / dyy, 0.0)
    return jnp.clip(ox, -0.5, 0.5), jnp.clip(oy, -0.5, 0.5)


def _select_keypoints(
    nms_resp: jnp.ndarray,
    ox_f: jnp.ndarray,
    oy_f: jnp.ndarray,
    max_keypoints: int,
    threshold: float,
    border: int,
    cand_tile: int = CAND_TILE,
    _force_general: bool = False,
) -> Keypoints:
    """Fixed-K keypoint selection from dense detection fields.

    nms_resp holds the Harris response at NMS local maxima and -inf
    elsewhere; ox_f/oy_f are the dense subpixel offset fields. Shared by
    the jnp path (`detect_keypoints`) and the fused Pallas path
    (ops/pallas_detect.py), which produce the same field triple.
    `_force_general` routes tile-aligned geometry through the general
    (pixel-masked) path anyway — the test seam that lets the fast-path
    IDENTICAL-results claim below be asserted mechanically.
    """
    H, W = nms_resp.shape
    # Candidate reduction: strongest surviving pixel per TILE x TILE
    # tile, then an exact top-k over the tile winners. Cuts the top-k
    # from H*W candidates to (H*W)/TILE^2 with an at-most-one-keypoint-
    # per-tile cap (grid-bucketed detection, the ORB-style spatial
    # spreading), which for K << #tiles is benign.
    #
    # Threshold is relative to the max response over the SELECTABLE
    # (border-excluded) region: robust to global contrast changes, and
    # immune to the border-ring response spikes a constant background
    # offset creates (SAME-conv gradients at the frame edge see the
    # offset against zero padding — in 3D those face-wide spikes
    # inflated a full-frame peak ~50x and silently killed every
    # interior keypoint). The interior global max is itself an NMS
    # local max, so masking nms_resp loses nothing.
    T = cand_tile
    if (
        not _force_general
        and border % T == 0 and H % T == 0 and W % T == 0
    ):
        # Tile-aligned fast path (round 5): every tile is fully inside
        # or fully outside the border exclusion, so the border/peak/
        # threshold masking moves to the (H/T, W/T) TILE level and the
        # full-resolution field is read exactly twice (tile max +
        # argmax) instead of ~4 masked-materialize passes — measured
        # 2.5 -> ~1.2 ms/batch of the detect stage at B=64, 512².
        # Results are IDENTICAL to the general path below: same tile
        # maxima, same first-in-row-major argmax tie rule, same peak.
        tile_val, tile_arg = tile_max_argmax(nms_resp, T)
        th, tw = tile_val.shape
        tys = jnp.arange(th)[:, None]
        txs = jnp.arange(tw)[None, :]
        bt = border // T
        tile_inb = (
            (tys >= bt) & (tys < th - bt) & (txs >= bt) & (txs < tw - bt)
        )
        peak = jnp.maximum(
            jnp.max(jnp.where(tile_inb, tile_val, -jnp.inf)), 1e-12
        )
        tile_val = jnp.where(
            tile_inb & (tile_val > threshold * peak), tile_val, -jnp.inf
        )
    else:
        # General path: arbitrary border/frame-size vs tile alignment —
        # mask at pixel level, reduce via reshape + argmax.
        ys = jnp.arange(H)[:, None]
        xs = jnp.arange(W)[None, :]
        inb = (
            (ys >= border) & (ys < H - border)
            & (xs >= border) & (xs < W - border)
        )
        peak = jnp.maximum(jnp.max(jnp.where(inb, nms_resp, -jnp.inf)), 1e-12)
        masked = jnp.where(
            inb & (nms_resp > threshold * peak), nms_resp, -jnp.inf
        )
        Hp, Wp = -(-H // T) * T, -(-W // T) * T
        m = jnp.pad(masked, ((0, Hp - H), (0, Wp - W)), constant_values=-jnp.inf)
        tiles = m.reshape(Hp // T, T, Wp // T, T).transpose(0, 2, 1, 3)
        tiles = tiles.reshape(Hp // T, Wp // T, T * T)
        tile_val = jnp.max(tiles, axis=-1)  # (th, tw)
        tile_arg = jnp.argmax(tiles, axis=-1).astype(jnp.int32)

    n_tiles = tile_val.size
    k = min(max_keypoints, n_tiles)
    scores, cand = sorted_top_k(tile_val.reshape(-1), k)
    if k < max_keypoints:  # tiny frames: pad back up to the fixed K
        pad = max_keypoints - k
        scores = jnp.concatenate([scores, jnp.full((pad,), -jnp.inf)])
        cand = jnp.concatenate([cand, jnp.zeros((pad,), cand.dtype)])
    within = tile_arg.reshape(-1)[cand]  # (K,) pointwise gather, tiny
    tw = tile_val.shape[1]
    iy = (cand // tw) * T + within // T
    ix = (cand % tw) * T + within % T
    valid = jnp.isfinite(scores)

    # Subpixel: sample the dense quadratic-fit offset fields at the peaks.
    flat = jnp.clip(iy, 0, H - 1) * W + jnp.clip(ix, 0, W - 1)
    offsets = jnp.stack(
        [ox_f.reshape(-1)[flat], oy_f.reshape(-1)[flat]], axis=-1
    )  # (K, 2) (ox, oy)

    xy = jnp.stack([ix.astype(jnp.float32), iy.astype(jnp.float32)], axis=-1)
    xy = xy + jnp.where(valid[:, None], offsets, 0.0)
    scores = jnp.where(valid, scores, 0.0)
    xy = jnp.where(valid[:, None], xy, 0.0)
    return Keypoints(xy=xy, score=scores, valid=valid)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_keypoints", "nms_size", "border", "window_sigma", "cand_tile"
    ),
)
def detect_keypoints(
    img: jnp.ndarray,
    max_keypoints: int = 512,
    threshold: float = 1e-6,
    nms_size: int = 5,
    border: int = 16,
    harris_k: float = 0.04,
    window_sigma: float = WINDOW_SIGMA,
    cand_tile: int = CAND_TILE,
    valid_hw: jnp.ndarray | None = None,
) -> Keypoints:
    """Detect up to `max_keypoints` Harris corners in a (H, W) frame.

    Returns fixed-K arrays; `valid[i]` is False for slots whose response
    fell at/below `threshold` (relative to the frame's peak response).
    Dense corner clusters are thinned to at most one keypoint per
    `cand_tile` x `cand_tile` tile (in addition to `nms_size`
    suppression) — the candidate-reduction grid both backends share.
    `window_sigma` is the Harris structure-tensor window: the detector's
    density ceiling (see CorrectorConfig.harris_window_sigma).
    `valid_hw` (traced (2,) ints, optional) restricts selection to the
    top-left (h, w) valid extent of a zero-padded frame — the
    execution-plan shape buckets (see valid_extent_mask).
    """
    resp = harris_response(img, k=harris_k, window_sigma=window_sigma)
    # NMS: keep strict local maxima of the response.
    is_max = resp >= _maxpool_same(resp, nms_size)
    nms_resp = jnp.where(is_max, resp, -jnp.inf)
    if valid_hw is not None:
        nms_resp = jnp.where(
            valid_extent_mask(resp.shape, border, valid_hw), nms_resp, -jnp.inf
        )
    ox_f, oy_f = _subpixel_fields(resp)
    return _select_keypoints(
        nms_resp, ox_f, oy_f, max_keypoints, threshold, border, cand_tile
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_keypoints", "threshold", "nms_size", "border", "harris_k",
        "use_pallas", "smooth_sigma", "interpret", "window_sigma",
        "cand_tile", "strip",
    ),
)
def detect_keypoints_batch(
    frames: jnp.ndarray,
    max_keypoints: int = 512,
    threshold: float = 1e-6,
    nms_size: int = 5,
    border: int = 16,
    harris_k: float = 0.04,
    use_pallas: bool = False,
    smooth_sigma: float | None = None,
    interpret: bool = False,
    window_sigma: float = WINDOW_SIGMA,
    cand_tile: int = CAND_TILE,
    valid_hw: jnp.ndarray | None = None,
    strip: int | None = None,
):
    """Detect keypoints over a (B, H, W) batch; fields carry a batch axis.

    With `use_pallas` (and a frame size the whole-frame kernel supports)
    the dense detection fields come from the fused Pallas kernel
    (ops/pallas_detect.py) — one VMEM-resident pass instead of ~12
    HBM-round-tripping conv/reduce passes; selection stays in XLA.

    With `smooth_sigma` returns (keypoints, smooth) where smooth is the
    sigma-blurred batch for the descriptor stage (`gaussian_blur`
    semantics) — a free ride on the fused kernel's resident slab when
    the Pallas path runs, two separate conv passes otherwise.

    `valid_hw` (traced (2,) ints, optional) restricts selection to the
    top-left (h, w) valid extent of zero-padded frames — the
    execution-plan shape buckets. The mask lands on the dense nms
    field, so the fused Pallas route and the jnp route mask
    identically (see valid_extent_mask).

    `strip` overrides the fused kernel's output rows per program
    (autotuned tiling, PR 13 — numerically neutral; whole-frame Pallas
    route only, ignored elsewhere).
    """
    B, H, W = frames.shape
    if smooth_sigma is not None and smooth_sigma <= 0.0:
        raise ValueError(f"smooth_sigma must be positive, got {smooth_sigma}")
    if use_pallas:
        from kcmc_tpu.ops.pallas_detect import (
            response_fields,
            response_fields_paneled,
            supports,
            supports_paneled,
        )

        # border >= 1: the kernel's subpixel fields differ from the jnp
        # path on the 1-px frame boundary (zero- vs edge-extension);
        # border=0 keypoints could land there, so take the jnp route.
        # Frames wider than the kernel's lane budget run the paneled
        # wrapper instead (border must then also exclude the panel
        # wrapper's frame-edge band — supports_paneled checks it).
        whole = border >= 1 and supports(
            (H, W), nms_size, window_sigma, smooth_sigma
        )
        paneled = not whole and supports_paneled(
            nms_size, window_sigma, smooth_sigma, border
        )
        if whole or paneled:
            fields = response_fields if whole else response_fields_paneled
            kw = {"strip": strip} if whole and strip is not None else {}
            out = fields(
                frames, harris_k=harris_k, nms_size=nms_size,
                window_sigma=window_sigma,
                smooth_sigma=smooth_sigma, interpret=interpret, **kw,
            )
            nms_field = out[0]
            if valid_hw is not None:
                nms_field = jnp.where(
                    valid_extent_mask((H, W), border, valid_hw)[None],
                    nms_field,
                    -jnp.inf,
                )
            kps = jax.vmap(
                lambda nr, ox, oy: _select_keypoints(
                    nr, ox, oy, max_keypoints, threshold, border, cand_tile
                )
            )(nms_field, out[1], out[2])
            return (kps, out[3]) if smooth_sigma is not None else kps
    kps = jax.vmap(
        lambda f: detect_keypoints(
            f,
            max_keypoints=max_keypoints,
            threshold=threshold,
            nms_size=nms_size,
            border=border,
            harris_k=harris_k,
            window_sigma=window_sigma,
            cand_tile=cand_tile,
            valid_hw=valid_hw,
        )
    )(frames)
    if smooth_sigma is not None:
        smooth = jax.vmap(lambda f: gaussian_blur(f, smooth_sigma))(frames)
        return kps, smooth
    return kps
