"""Photometric (correlation) residual-shift measurement and the
matrix-transform polish built on it.

Keypoint consensus leaves every matrix model at a 0.04-0.06 px floor set
by corner-localization noise (BENCH_r04: translation 0.043, homography
0.062). The piecewise path broke the same floor photometrically in
round 4 (0.386 -> 0.184 px field RMSE) by measuring each patch's
REMAINING shift against the template from ~4k pixels instead of ~40
matched corners (ops/piecewise.correlation_polish). This module
generalizes that mechanism:

- `measure_shifts`: the shared core — center-weighted, two-way
  symmetric cross-correlation at the 3x3 integer shifts with a
  separable quadratic peak fit, clamped to ±1 px, plus the
  significance gate. Returns the measured shifts AND the gate, so
  callers can use the gate as a fitting weight.
- `polish_transforms`: the matrix-model polish. After the batch warp,
  the corrected frames' per-region residual shifts d_i at region
  centers c_i define a residual map R(p) ~ p - d(p) in reference
  coordinates (content displaced by eps peaks at shift d = -eps; see
  the derivation below). Fitting the model family's own weighted
  solver to (c_i -> c_i - d_i) and composing M' = M @ A updates the
  transform with photometric accuracy while staying exactly inside
  the model family (a rigid stays rigid, a homography a homography).

Sign/composition derivation: the batch program's convention is
corrected(p) = frame(M p) (ref -> source map). If the corrected frame
still shows residual content displacement eps(p) — corrected(p) =
ref(p - eps(p)) — then ref(p) = corrected(p + eps) = frame(M (p + eps)),
so the fixed map is M' = M o T_{+eps}. `measure_shifts` peaks at
d = -eps (same convention as the piecewise polish, whose field fix is
u += -d), hence A fits p -> p - d(p) and M' = M @ A. For a pure
translation residual this reduces exactly to the piecewise update
(t' = t - d), and for rotated/zoomed models the composition correctly
routes the ref-space shift through M's linear part.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kcmc_tpu.models.transforms import get_model


def region_window(
    sh: int, sw: int, window_frac: float, xp=jnp, dtype=None,
    ring: bool = True,
):
    """Flattened, normalized center-weighted Gaussian window for an
    (sh, sw) region — THE window of the polish family: the correlation
    scores, the coverage gate, and the numpy mirrors must all weight
    with the same function, so it lives in exactly one place. `xp`
    selects the array namespace (jnp for the compiled path, np for the
    mirrors, which weight in float64).

    With `ring` (default), the outer 1-px ring is zeroed (~0.2-0.7% of
    the mass): it makes measure_shifts' index-shifted two-term
    formulation EXACTLY equivalent to the per-region form for the
    ±1 px shifts it scores — without the ring, the region-border pixels
    re-pair across the shift and bias the quadratic vertex by
    ~0.01-0.02 px (measured at 160²). The piecewise field polish uses
    ring=False with the exact per-region formulation instead (its r4
    accuracy record is pinned to that estimator).

    Built in float64 numpy (sh/sw are static) and cast, so the compiled
    path and the mirrors share bit-identical constants."""
    import numpy as _np

    yy = (_np.arange(sh, dtype=_np.float64) - (sh - 1) / 2) / (
        window_frac * sh
    )
    xx = (_np.arange(sw, dtype=_np.float64) - (sw - 1) / 2) / (
        window_frac * sw
    )
    w2 = _np.exp(-0.5 * (yy[:, None] ** 2 + xx[None, :] ** 2))
    if ring and sh > 2 and sw > 2:
        mask = _np.zeros((sh, sw))
        mask[1:-1, 1:-1] = 1.0
        w2 = w2 * mask
    w = (w2 / w2.sum()).reshape(-1)
    if xp is jnp:
        return jnp.asarray(w, dtype or jnp.float32)
    return w.astype(dtype) if dtype else w


def region_patches(x, grid: tuple[int, int]):
    """(..., H, W) -> (..., gh, gw, sh*sw): crop to whole regions and
    flatten each region's pixels (works on numpy and jax arrays — pure
    method calls). The polish family's one region layout."""
    gh, gw = grid
    H, W = x.shape[-2], x.shape[-1]
    sh, sw = H // gh, W // gw
    p = x[..., : gh * sh, : gw * sw].reshape(x.shape[:-2] + (gh, sh, gw, sw))
    return p.swapaxes(-3, -2).reshape(x.shape[:-2] + (gh, gw, sh * sw))


def region_centers(grid: tuple[int, int], shape: tuple[int, int]) -> jnp.ndarray:
    """(gh, gw, 2) cell-center (x, y) coordinates of the region grid
    (identical convention to ops/piecewise.patch_centers; duplicated
    here to keep the import graph acyclic — piecewise imports this
    module for the shared measurement core)."""
    gh, gw = grid
    H, W = shape
    cy = (jnp.arange(gh, dtype=jnp.float32) + 0.5) * H / gh - 0.5
    cx = (jnp.arange(gw, dtype=jnp.float32) + 0.5) * W / gw - 0.5
    return jnp.stack(jnp.meshgrid(cx, cy, indexing="xy"), axis=-1)


def measure_shifts(
    corrected: jnp.ndarray,  # (B, H, W) warped frames (ref-aligned)
    template: jnp.ndarray,  # (H, W) reference frame
    grid: tuple[int, int],
    window_frac: float = 0.25,
    exact: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-region photometric residual shifts of each corrected frame
    against the template.

    Correlation scores at the 3x3 integer shifts (the upstream estimate
    is already sub-pixel-good, so ±1 px covers the residual), then a
    separable quadratic peak fit, clamped to ±1 px. All static slicing
    and reductions — the 9 shifted score maps are elementwise multiplies
    of reshaped views, no gathers.

    Returns (d, significant): d (B, gh, gw, 2) peak shifts — content
    displaced by eps relative to the template peaks at d = -eps — with
    insignificant regions zeroed; significant (B, gh, gw) bool, the
    normalized-correlation gate (featureless regions — vignetted
    corners, saturated areas — have noise-level scores whose SIGN would
    otherwise inject a full ±1 px step via the monotone-surface
    fallback).
    """
    B, H, W = corrected.shape
    gh, gw = grid
    sh, sw = H // gh, W // gw

    def patches(x):
        return region_patches(x, grid)

    # Two-way symmetric correlation: the one-sided form (window fixed
    # on C, T shifting) is NOT symmetric under the window — measured
    # 0.07 px of vertex bias on IDENTICAL images. Summing the mirrored
    # pairing (C shifting, T fixed) makes score(d) == score(-d) exact
    # for identical inputs, killing the bias.
    #
    # Bandwidth structure (the polish is pure HBM traffic): the naive
    # form reads the corrected batch ~18x (5 scores x shifted views x
    # two terms). Both terms are rewritten so only BATCH-INDEPENDENT
    # template-side stacks shift, and the 5 scores become two MXU
    # contractions that read the batch arrays ONCE each:
    #   term1(d) = sum_p w.C(p) . T(p+d)          (C's zero-mean makes
    #              t's mean term vanish, so raw shifted T suffices)
    #   term2(d) = sum_p w(p).c(p-d).T0(p)
    #            = sum_q corrected(q) . (w.T0)(q+d)  — index-shifted
    #              onto the template side. EXACT because the window's
    #              outer 1-px ring is zero (region_window): the only
    #              pixels the shift re-pairs across region borders
    #              carry zero weight on both sides.
    # Identical-input symmetry stays exact: term1(d) + term2(d) =
    # sum w.C.(C(p+d) + C(p-d)).
    if exact:
        # Per-region formulation with the full (ring-less) window — the
        # piecewise field polish's estimator, pinned to its round-4
        # accuracy record (the ring/index-shift fast path below
        # measures +0.02 px on the field workload's pass-2
        # convergence). A bandwidth restructure of this branch
        # (shifted-side zero-means dropped, template term einsum'd) was
        # built, measured SPEED-NEUTRAL on chip (XLA already fuses
        # this form), and reverted: it broke the bitwise
        # score(d) == score(-d) identical-input symmetry this
        # estimator is designed around (f32 summation orders differ
        # between the two terms), costing a spurious ~1e-6 px vertex.
        w = region_window(sh, sw, window_frac, ring=False)

        def zero_mean_x(p):
            return p - jnp.sum(w * p, axis=-1, keepdims=True)

        C = zero_mean_x(patches(corrected))
        T0 = zero_mean_x(patches(template))
        tpad = jnp.pad(template, 1, mode="edge")
        cpad = jnp.pad(corrected, ((0, 0), (1, 1), (1, 1)), mode="edge")

        def score(dy, dx):
            t = zero_mean_x(
                patches(tpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W])
            )
            c = zero_mean_x(
                patches(cpad[:, 1 - dy : 1 - dy + H, 1 - dx : 1 - dx + W])
            )
            return jnp.sum(w * (C * t + c * T0), axis=-1)

        s_c = score(0, 0)
        s_xm, s_xp = score(0, -1), score(0, 1)
        s_ym, s_yp = score(-1, 0), score(1, 0)
        e_c = jnp.sum(w * C * C, axis=-1)
        e_t = jnp.sum(w * T0 * T0, axis=-1)
    else:
        # Center-weighted window: the caller reads the shift AT the
        # region center, but an unweighted correlation measures the
        # region-AVERAGE shift — an averaging bias. Gaussian, sigma =
        # window_frac * region side; outer ring zeroed (see above).
        w = region_window(sh, sw, window_frac)

        def zero_mean(p):  # weighted mean removal
            return p - jnp.sum(w * p, axis=-1, keepdims=True)

        CP = patches(corrected)  # (B, gh, gw, S)
        V = w * zero_mean(CP)
        T0 = zero_mean(patches(template))

        shifts = [(0, 0), (0, -1), (0, 1), (-1, 0), (1, 0)]
        tpad = jnp.pad(template, 1, mode="edge")
        tstack = jnp.stack(
            [
                patches(tpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W])
                for dy, dx in shifts
            ]
        )  # (5, gh, gw, S)
        # full-image (w . T0) layout for the index-shifted second term
        t0w = (w * T0).reshape(gh, gw, sh, sw)
        t0w = jnp.swapaxes(t0w, 1, 2).reshape(gh * sh, gw * sw)
        t0wpad = jnp.pad(t0w, ((1, 1 + H - gh * sh), (1, 1 + W - gw * sw)))
        ustack = jnp.stack(
            [
                patches(t0wpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W])
                for dy, dx in shifts
            ]
        )  # (5, gh, gw, S)
        hi = jax.lax.Precision.HIGHEST
        scores = jnp.einsum("bghs,nghs->nbgh", V, tstack, precision=hi)
        scores = scores + jnp.einsum(
            "bghs,nghs->nbgh", CP, ustack, precision=hi
        )
        s_c, s_xm, s_xp, s_ym, s_yp = scores
        # e_c = sum w.C^2 == sum V.CP exactly (the mean term cancels).
        e_c = jnp.sum(V * CP, axis=-1)
        e_t = jnp.sum(w * T0 * T0, axis=-1)
    # Significance gate: require a real normalized-correlation peak —
    # the center score against the regions' own energies.
    significant = s_c > 0.2 * jnp.sqrt(e_c * e_t * 4.0) + 1e-12
    # (the factor 4 accounts for the two-way score being the sum of two
    # correlation terms, each bounded by sqrt(e_c * e_t))

    def subpixel(sm, sp):
        denom = sm - 2.0 * s_c + sp
        # proper peak: quadratic vertex; monotone surface: full ±1 step
        off = jnp.where(
            denom < -1e-12,
            0.5 * (sm - sp) / jnp.where(denom < -1e-12, denom, -1.0),
            jnp.sign(sp - sm),
        )
        return jnp.clip(jnp.where(significant, off, 0.0), -1.0, 1.0)

    d = jnp.stack([subpixel(s_xm, s_xp), subpixel(s_ym, s_yp)], axis=-1)
    return d, significant


@functools.partial(
    jax.jit, static_argnames=("model_name", "grid", "window_frac")
)
def polish_transforms(
    corrected: jnp.ndarray,  # (B, H, W) warped frames
    template: jnp.ndarray,  # (H, W) reference frame
    transforms: jnp.ndarray,  # (B, 3, 3) ref -> source maps
    model_name: str,
    grid: tuple[int, int] = (4, 4),
    window_frac: float = 0.25,
    valid_hw: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """One photometric polish pass for a batch of matrix transforms.

    Measures per-region residual shifts on the already-warped frames,
    fits the model family's own weighted refine solver to the region
    correspondences (c -> c - d, weighted by the significance gate),
    and composes M' = M @ A. Frames with too few significant regions
    for a well-posed update (< 2x the model's minimal sample size)
    keep their transform unchanged — as do regions the gate zeroed,
    which contribute zero-shift support nowhere (weight 0) rather than
    fake identity evidence.

    `valid_hw` (traced (2,) ints, optional): the true (h, w) extent of
    frames bucket-padded to (H, W) (execution plans). The coverage gate
    then treats everything outside the valid extent — output pixels in
    the pad, and samples the warp drew from it — as uncovered, so
    boundary regions drop out of the fit. Without this, the pad edge
    (real content against synthetic zeros, at the SAME place in
    corrected and template) correlates perfectly at zero shift and
    biases the fitted update toward identity (measured ~0.3 px on a
    50x70-in-64x80 affine run).
    """
    model = get_model(model_name)
    B, H, W = corrected.shape
    d, sig = measure_shifts(corrected, template, grid, window_frac)
    # Coverage gate: the warp writes zeros outside its source coverage,
    # and a region whose window sees that zero boundary correlates
    # template content against synthetic black — at large zooms (where
    # a third of the frame is out-of-coverage) the resulting spurious
    # shifts pass the significance gate and tilt the fit. Gate regions
    # by their WINDOW-WEIGHTED coverage: >= 0.98 keeps ordinary drift
    # edges (a 6 px stripe contaminates ~0.3% of an edge window — and
    # measures fine) while dropping zoom borders (10-100% contaminated).
    from kcmc_tpu.ops.warp import coverage_mask

    if valid_hw is None:
        cov = jax.vmap(lambda M: coverage_mask((H, W), M))(transforms)
    else:
        # Bucketed canvas: a region is covered only where the OUTPUT
        # pixel lies in the valid rect AND its source sample stays in
        # the valid extent (both shared definitions live in ops/warp).
        from kcmc_tpu.ops.warp import valid_rect_mask

        cov = valid_rect_mask((H, W), valid_hw)[None] & jax.vmap(
            lambda M: coverage_mask((H, W), M, valid_hw=valid_hw)
        )(transforms)
    covw = _windowed_mean(cov.astype(jnp.float32), grid, window_frac)
    sig = sig & (covw >= 0.98)
    centers = region_centers(grid, (H, W)).reshape(-1, 2)  # (P, 2)
    # A well-posed family update needs margin beyond the minimal sample:
    # with the default 4x4 grid that is 2 regions for translation, 8 for
    # homography.
    min_regions = 2.0 * float(model.min_samples)

    def upd(M, di, si):
        wts = si.reshape(-1).astype(jnp.float32)
        A = model.resolved_refine_solve(centers, centers - di.reshape(-1, 2), wts)
        ok = jnp.sum(wts) >= min_regions
        A = jnp.where(ok, A, jnp.eye(3, dtype=A.dtype))
        # full-f32 compose: TPU's default matmul precision is bf16-
        # grade, and M carries O(frame-size) translation entries — an
        # unpinned compose costs ~0.05 px at 512², swamping the polish
        # (measured: TPU fit error 0.052 vs 0.032 with the pin)
        return jnp.matmul(
            M, A, precision=jax.lax.Precision.HIGHEST
        ).astype(M.dtype)

    return jax.vmap(upd)(transforms, d, sig)


def _windowed_mean(
    x: jnp.ndarray, grid: tuple[int, int], window_frac: float
) -> jnp.ndarray:
    """Per-region Gaussian-window-weighted mean of a (B, H, W) map —
    the same window `measure_shifts` scores with (region_window), so a
    gate on this quantity reflects exactly the pixels that influence
    the shift."""
    H, W = x.shape[-2], x.shape[-1]
    gh, gw = grid
    w = region_window(H // gh, W // gw, window_frac)
    return jnp.sum(w * region_patches(x, grid), axis=-1)
