"""Pallas TPU kernel: gather-free bilinear warp for translation motion.

The generic warp (ops/warp.py) is 4 arbitrary gathers — exactly what the
TPU memory system dislikes. For *pure translation* (the flagship
config-1 benchmark path) the bilinear resample needs no gathers at all:
every output pixel samples the same fractional offset, so

    out = w00*S(0,0) + w01*S(0,1) + w10*S(1,0) + w11*S(1,1)

where S(dy, dx) are four statically-shifted views of ONE dynamically
positioned VMEM window (origin = floor of the shift, from SMEM scalars),
and the four weights are scalars. The kernel is a pure VPU FMA stream
at full lane utilization.

Out-of-bounds semantics match ops/warp.py: the frame is edge-padded on
the host (so interior blends clamp like the jnp gather version) and an
iota-based validity mask zeroes pixels whose true source falls outside
the frame. Translations beyond PAD pixels (far outside the judged drift
regime of tens of pixels) zero the whole frame rather than silently
returning misregistered content.

Exposed via `warp_frame_translation(frame, t)`, and selected by the jax
backend's `warp="auto"` policy for the translation model on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PAD = 128  # max |shift| handled exactly, pixels


def _warp_kernel(scal_ref, src_ref, out_ref):
    """scal_ref: (7,) float32 scalars in SMEM:
    [y0, x0] window origin into the padded source, [fy, fx] bilinear
    fractions, [ty, tx] the true shift (for the validity mask), and
    [exact] the shift-within-window flag.
    """
    y0 = scal_ref[0].astype(jnp.int32)
    x0 = scal_ref[1].astype(jnp.int32)
    fy = scal_ref[2]
    fx = scal_ref[3]
    ty = scal_ref[4]
    tx = scal_ref[5]
    exact = scal_ref[6]  # 1.0 iff the shift is within the window's range

    H, W = out_ref.shape
    # One dynamically-positioned window read; four static shifted views.
    win = src_ref[pl.ds(y0, H + 1), pl.ds(x0, W + 1)]
    w00 = (1.0 - fy) * (1.0 - fx)
    w01 = (1.0 - fy) * fx
    w10 = fy * (1.0 - fx)
    w11 = fy * fx
    blend = (
        w00 * win[:-1, :-1]
        + w01 * win[:-1, 1:]
        + w10 * win[1:, :-1]
        + w11 * win[1:, 1:]
    )
    # Validity: true source coord (r + ty, c + tx) inside the frame.
    rows = jax.lax.broadcasted_iota(jnp.float32, (H, W), 0) + ty
    cols = jax.lax.broadcasted_iota(jnp.float32, (H, W), 1) + tx
    inb = (
        (rows >= 0.0) & (rows <= H - 1.0) & (cols >= 0.0) & (cols <= W - 1.0)
        & (exact > 0.5)
    )
    out_ref[:, :] = jnp.where(inb, blend, 0.0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def warp_frame_translation(
    frame: jnp.ndarray, t: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Correct a (H, W) frame under pure translation t = (tx, ty).

    Matches `warp_frame(frame, M)` for M = [[1,0,tx],[0,1,ty],[0,0,1]]
    up to float rounding, with zero gathers on TPU.
    """
    H, W = frame.shape
    tx, ty = t[0], t[1]
    # Edge-pad so interior blends clamp exactly like the gather version.
    padded = jnp.pad(frame, PAD, mode="edge")
    y0 = jnp.floor(ty)
    x0 = jnp.floor(tx)
    fy = ty - y0
    fx = tx - x0
    # Exactness range of the dynamic window: origin must not clamp.
    # Beyond it the kernel cannot fetch the right content, so the whole
    # frame is masked to zero (conservative) instead of silently
    # returning misregistered pixels.
    exact = (
        (y0 >= -PAD) & (y0 <= PAD - 1) & (x0 >= -PAD) & (x0 <= PAD - 1)
    ).astype(jnp.float32)
    oy = jnp.clip(y0.astype(jnp.int32) + PAD, 0, 2 * PAD - 1)
    ox = jnp.clip(x0.astype(jnp.int32) + PAD, 0, 2 * PAD - 1)
    scal = jnp.stack(
        [oy.astype(jnp.float32), ox.astype(jnp.float32), fy, fx, ty, tx, exact]
    )

    return pl.pallas_call(
        _warp_kernel,
        out_shape=jax.ShapeDtypeStruct((H, W), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(scal, padded.astype(jnp.float32))


def warp_batch_translation(
    frames: jnp.ndarray, transforms: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """(B, H, W) frames, (B, 3, 3) translation matrices -> corrected batch."""
    ts = transforms[:, :2, 2]  # (B, 2) (tx, ty)
    return jax.vmap(lambda f, t: warp_frame_translation(f, t, interpret=interpret))(
        frames, ts
    )
