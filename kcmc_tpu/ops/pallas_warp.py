"""Pallas TPU kernel: gather-free bilinear warp for translation motion.

The generic warp (ops/warp.py) is 4 arbitrary gathers — exactly what the
TPU memory system dislikes. For *pure translation* (the flagship
config-1 benchmark path) the bilinear resample needs no gathers at all:
every output pixel samples the same fractional offset, so

    out = w00*S(0,0) + w01*S(0,1) + w10*S(1,0) + w11*S(1,1)

where S(dy, dx) are four statically-shifted views of ONE dynamically
positioned VMEM window (origin = floor of the shift, from SMEM scalars),
and the four weights are scalars. The kernel is a pure VPU FMA stream
at full lane utilization.

The batch dimension is a Pallas *grid* axis (one program per frame) with
the per-frame scalars delivered through scalar prefetch — the idiomatic
TPU structure (vmap-of-pallas_call would batch the SMEM operand into a
block shape Mosaic rejects).

Out-of-bounds semantics match ops/warp.py: the frame is edge-padded on
the host (so interior blends clamp like the jnp gather version) and an
iota-based validity mask zeroes pixels whose true source falls outside
the frame. Translations beyond PAD pixels (far outside the judged drift
regime of tens of pixels) zero the whole frame rather than silently
returning misregistered content.

Exposed via `warp_batch_translation(frames, transforms)`, and selected
by the jax backend's `warp="auto"` policy for the translation model on
TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PAD = 128  # max |shift| handled exactly, pixels

# The kernel holds one whole padded frame (plus rotated copies and the
# output block) in VMEM — fine at the judged 512^2 (≈7 MB) but a
# measured 20.5 MB scoped-vmem OOM at 1024^2. Budget: padded frame
# appears ~2x (source + rotate), output ~2x (blend temporaries).
_VMEM_BUDGET = 14 * 1024 * 1024


def supports(shape: tuple[int, int]) -> bool:
    """Whether the whole-frame translation kernel fits VMEM for this
    frame shape. Callers (the backend's warp="auto" policy) must fall
    back to the separable/gather path when False — large frames would
    otherwise die at compile time with a scoped-vmem OOM."""
    H, W = shape
    Hp = -(-(H + 2 * PAD) // 8) * 8
    Wp = -(-(W + 2 * PAD) // 128) * 128
    return (2 * Hp * Wp + 2 * H * W) * 4 <= _VMEM_BUDGET


def _warp_kernel(iscal_ref, fscal_ref, src_ref, out_ref):
    """One program per frame (grid axis 0 = batch).

    iscal_ref: (B, 2) int32 scalar-prefetch: [y0, x0] window origin into
    the padded source. fscal_ref: (B, 8) float32 in SMEM: [fy, fx]
    bilinear fractions, [ty, tx] the true shift (for the validity mask),
    [exact] the shift-within-window flag, + padding.
    """
    b = pl.program_id(0)
    y0 = iscal_ref[b, 0]
    x0 = iscal_ref[b, 1]
    fy = fscal_ref[b, 0]
    fx = fscal_ref[b, 1]
    ty = fscal_ref[b, 2]
    tx = fscal_ref[b, 3]
    exact = fscal_ref[b, 4]  # 1.0 iff the shift is within the window's range

    H, W = out_ref.shape
    # Dynamic positioning via rotate (Mosaic's supported dynamic-shift
    # primitive — arbitrary dynamic slice starts can't be proven tile-
    # aligned), then four static shifted views of the front window.
    # Shifts MUST be non-negative: Mosaic's dynamic rotate mis-wraps
    # negative amounts on multi-tile arrays (verified on TPU v5e), so
    # roll by (dim - y0) ≡ -y0 instead. oy/ox are clipped to
    # [0, 2*PAD-1] on the host, so rows 0..H and cols 0..W of the
    # rotated array never see wrap-around content.
    Hp, Wp = src_ref.shape
    full = src_ref[:, :]
    full = pltpu.roll(full, Hp - y0, 0)
    full = pltpu.roll(full, Wp - x0, 1)
    win = full[: H + 1, : W + 1]
    w00 = (1.0 - fy) * (1.0 - fx)
    w01 = (1.0 - fy) * fx
    w10 = fy * (1.0 - fx)
    w11 = fy * fx
    blend = (
        w00 * win[:-1, :-1]
        + w01 * win[:-1, 1:]
        + w10 * win[1:, :-1]
        + w11 * win[1:, 1:]
    )
    # Validity: true source coord (r + ty, c + tx) inside the frame.
    # (Mosaic only supports integer iota; cast to float after.)
    rows = jax.lax.broadcasted_iota(jnp.int32, (H, W), 0).astype(jnp.float32) + ty
    cols = jax.lax.broadcasted_iota(jnp.int32, (H, W), 1).astype(jnp.float32) + tx
    inb = (
        (rows >= 0.0) & (rows <= H - 1.0) & (cols >= 0.0) & (cols <= W - 1.0)
        & (exact > 0.5)
    )
    out_ref[:, :] = jnp.where(inb, blend, 0.0)


def _shift_scalars(transforms: jnp.ndarray, extra=None):
    """Shared host prologue of both translation kernels: split the
    per-frame shift into window origin + bilinear fraction, apply the
    ±PAD exactness rule, and pack the SMEM scalar operands. `extra`
    (optional (B,) float) rides in fscal slot 5 — the strip kernel's
    true-height channel. Returns (iscal (B,2) i32, fscal (B,8) f32,
    exact (B,) f32)."""
    tx = transforms[:, 0, 2]
    ty = transforms[:, 1, 2]
    y0 = jnp.floor(ty)
    x0 = jnp.floor(tx)
    fy = ty - y0
    fx = tx - x0
    # Exactness range of the dynamic window: origin must not clamp.
    # Beyond it the kernel cannot fetch the right content, so the whole
    # frame is masked to zero (conservative) instead of silently
    # returning misregistered pixels.
    exact = (
        (y0 >= -PAD) & (y0 <= PAD - 1) & (x0 >= -PAD) & (x0 <= PAD - 1)
    ).astype(jnp.float32)
    oy = jnp.clip(y0.astype(jnp.int32) + PAD, 0, 2 * PAD - 1)
    ox = jnp.clip(x0.astype(jnp.int32) + PAD, 0, 2 * PAD - 1)
    iscal = jnp.stack([oy, ox], axis=-1)  # (B, 2) int32
    zeros = jnp.zeros_like(fy)
    fscal = jnp.stack(
        [fy, fx, ty, tx, exact, extra if extra is not None else zeros,
         zeros, zeros],
        axis=-1,
    )  # (B, 8) float32
    return iscal, fscal, exact


@functools.partial(jax.jit, static_argnames=("interpret", "with_ok"))
def warp_batch_translation(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    interpret: bool = False,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames under pure translations.

    transforms: (B, 3, 3) matrices [[1,0,tx],[0,1,ty],[0,0,1]]. Matches
    `vmap(warp_frame)` up to float rounding, with zero gathers on TPU.
    `with_ok` also returns the (B,) bool flag marking frames whose shift
    was within the +-PAD exactness window (False = frame zeroed).
    """
    B, H, W = frames.shape
    # Edge-pad so interior blends clamp exactly like the gather version.
    # The padded dims are additionally rounded up to TPU tile alignment
    # (8 sublanes x 128 lanes — Mosaic's dynamic rotate rejects unaligned
    # shapes); the extra edge rows/cols sit beyond every reachable window
    # (max read row = oy + H <= H + 2*PAD - 1 < the aligned height).
    Hp = -(-(H + 2 * PAD) // 8) * 8
    Wp = -(-(W + 2 * PAD) // 128) * 128
    padded = jnp.pad(
        frames,
        ((0, 0), (PAD, Hp - H - PAD), (PAD, Wp - W - PAD)),
        mode="edge",
    )
    iscal, fscal, exact = _shift_scalars(transforms)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((None, Hp, Wp), lambda b, iscal: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, H, W), lambda b, iscal: (b, 0, 0)),
    )
    out = pl.pallas_call(
        _warp_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, W), jnp.float32),
        interpret=interpret,
    )(iscal, fscal, padded.astype(jnp.float32))
    return (out, exact > 0.5) if with_ok else out


_STRIP_ROWS = 128  # output rows per strip program (256 measured a
# 17.4 MB Mosaic scoped-vmem allocation at 2048² vs the 16 MB limit —
# the roll copies and blend temporaries roughly double the in-block
# budget; 128 compiles at 2048² with ~3 MB headroom)


def supports_strips(
    shape: tuple[int, int], strip_rows: int | None = None
) -> bool:
    """Whether the ROW-STRIP translation kernel fits VMEM for this
    frame shape — the large-frame route (DESIGN.md "Large-frame
    support, round 4" item 1, built in round 5). The whole-frame
    kernel gates at ~512²; strips hold (STRIP + 2*PAD) rows instead of
    the frame, so the budget depends on width only: ~11.5 MB at 2048²,
    ~21 MB at 4096² (beyond the scoped budget — fall back).
    `strip_rows` checks a specific (autotune-candidate) strip height;
    None = the measured default."""
    H, W = shape
    R = strip_rows or _STRIP_ROWS
    Wp = -(-(W + 2 * PAD) // 128) * 128
    rows = R + 2 * PAD
    # in-block appears ~2x (source + rotate), output once
    return (2 * rows * Wp + R * W) * 4 <= _VMEM_BUDGET


def _warp_kernel_strip(iscal_ref, fscal_ref, src_ref, out_ref):
    """One program per (frame, row strip). Identical math to
    _warp_kernel over a (STRIP + 2*PAD)-row window; the validity mask
    offsets the row iota by the strip's base row (static per program)."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    y0 = iscal_ref[b, 0]
    x0 = iscal_ref[b, 1]
    fy = fscal_ref[b, 0]
    fx = fscal_ref[b, 1]
    ty = fscal_ref[b, 2]
    tx = fscal_ref[b, 3]
    exact = fscal_ref[b, 4]
    true_h = fscal_ref[b, 5]  # unpadded frame height (for the mask)

    R, W = out_ref.shape
    Hp, Wp = src_ref.shape
    full = src_ref[:, :]
    full = pltpu.roll(full, Hp - y0, 0)
    full = pltpu.roll(full, Wp - x0, 1)
    win = full[: R + 1, : W + 1]
    w00 = (1.0 - fy) * (1.0 - fx)
    w01 = (1.0 - fy) * fx
    w10 = fy * (1.0 - fx)
    w11 = fy * fx
    blend = (
        w00 * win[:-1, :-1]
        + w01 * win[:-1, 1:]
        + w10 * win[1:, :-1]
        + w11 * win[1:, 1:]
    )
    base = s * R
    rows = (
        jax.lax.broadcasted_iota(jnp.int32, (R, W), 0).astype(jnp.float32)
        + base + ty
    )
    out_rows = (
        jax.lax.broadcasted_iota(jnp.int32, (R, W), 0).astype(jnp.float32)
        + base
    )
    cols = jax.lax.broadcasted_iota(jnp.int32, (R, W), 1).astype(jnp.float32) + tx
    inb = (
        (rows >= 0.0) & (rows <= true_h - 1.0)
        & (cols >= 0.0) & (cols <= W - 1.0)
        & (out_rows <= true_h - 1.0)  # rows padded up to a strip multiple
        & (exact > 0.5)
    )
    out_ref[:, :] = jnp.where(inb, blend, 0.0)


@functools.partial(
    jax.jit, static_argnames=("interpret", "with_ok", "strip_rows")
)
def warp_batch_translation_strips(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    interpret: bool = False,
    with_ok: bool = False,
    strip_rows: int | None = None,
) -> jnp.ndarray:
    """Row-strip variant of `warp_batch_translation` for frames whose
    whole-frame window exceeds VMEM (`supports` False, `supports_strips`
    True — 1024²/2048²). Strips of _STRIP_ROWS output rows, each with a
    2*PAD-row halo, are stacked on the host into an extra array axis
    the grid walks (the column-paneled detect pattern, ops/
    pallas_detect.response_fields_paneled) — strip windows overlap, so
    they cannot be expressed as Pallas block indexing directly.
    Same exactness window (±PAD) and out-of-bounds semantics as the
    whole-frame kernel. `strip_rows` overrides the strip height (the
    PR-13 autotune seam; numerically neutral — each output pixel's
    blend is identical whichever strip hosts it).
    """
    B, H, W = frames.shape
    R = strip_rows or _STRIP_ROWS
    S = -(-H // R)
    Wp = -(-(W + 2 * PAD) // 128) * 128
    # rows: PAD halo + strip-multiple padding; edge-pad like the
    # whole-frame kernel so interior blends clamp like the gather warp.
    padded = jnp.pad(
        frames,
        ((0, 0), (PAD, PAD + S * R - H), (PAD, Wp - W - PAD)),
        mode="edge",
    )
    # host-side strip stacking: (B, S, R + 2*PAD, Wp)
    strips = jnp.stack(
        [
            jax.lax.slice_in_dim(padded, s * R, s * R + R + 2 * PAD, axis=1)
            for s in range(S)
        ],
        axis=1,
    )
    hh = jnp.full((B,), float(H), jnp.float32)
    iscal, fscal, exact = _shift_scalars(transforms, extra=hh)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (None, None, R + 2 * PAD, Wp),
                lambda b, s, iscal: (b, s, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((None, R, W), lambda b, s, iscal: (b, s, 0)),
    )
    out = pl.pallas_call(
        _warp_kernel_strip,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S * R, W), jnp.float32),
        interpret=interpret,
    )(iscal, fscal, strips.astype(jnp.float32))
    out = out[:, :H, :]
    return (out, exact > 0.5) if with_ok else out


def warp_frame_translation(
    frame: jnp.ndarray, t: jnp.ndarray, interpret: bool = False
) -> jnp.ndarray:
    """Correct a (H, W) frame under pure translation t = (tx, ty).

    Single-frame convenience wrapper over the batched kernel.
    """
    M = jnp.array(
        [[1.0, 0.0, 0.0], [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]], dtype=jnp.float32
    )
    M = M.at[0, 2].set(t[0]).at[1, 2].set(t[1])
    return warp_batch_translation(frame[None], M[None], interpret=interpret)[0]
