"""Gather-free batched warp for affine-family transforms: shear/scale passes.

The generic warp (ops/warp.py) is 4 arbitrary gathers per pixel — the
one memory pattern the TPU cannot vectorize. This module resamples
through any *affine* transform (translation / rigid / affine — the
motion-correction families; SURVEY.md §0 configs 1-2) with ZERO
gathers, by the classic multi-pass decomposition (Catmull-Smith),
mapped onto what the TPU does well:

    M2 = Sx(alpha) @ Sy(beta) @ diag(u, v)        (2x2 linear part)

    warp_M = scale_y . scale_x . shear_y . shear_x      (applied order)

* The two SHEAR passes sample `x + alpha*(y - cy)` (resp.
  `y + beta*(x - cx)`): per-row constant fractional shifts. They are
  computed as a short statically-bounded loop of shifted views blended
  by per-row bilinear coefficients — pure VPU elementwise work. The
  static bound `shear_px` covers |alpha| * H/2 pixels with
  alpha ~ tan(theta); drift-correction rotations are small (~4.5 px
  at 1 deg for H=512), and frames whose shear exceeds the bound are
  zeroed and flagged rather than silently mis-resampled.
* The two SCALE passes sample `u*x + c` (uniform stride per row, same
  for all rows) and absorb the WHOLE translation: each is a banded
  bilinear-interpolation matrix built on the fly from iota comparisons
  and applied as one MXU matmul — arbitrary offsets at zero extra cost,
  which is why the translation lives here and not in the shear range.

Multi-pass 1D-linear interpolation is not bit-identical to one-shot 2D
bilinear (it is slightly smoother along the shear direction); the
registration transforms are unaffected (the warp does not feed back
into estimation) and tests bound the interior difference on smooth
imagery.

Out-of-frame samples produce 0, matching ops/warp.py's coverage mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def decompose_affine(M: jnp.ndarray) -> dict:
    """Split a 3x3 affine (row [0,0,1] last) into shear/scale pass params.

    Returns per-frame scalars: alpha, beta (shears), u, v (strides),
    c, d (x/y offsets for the scale passes), and `ok` (False where the
    decomposition degenerates: m11 ~ 0 or u ~ 0, far outside the
    drift-correction regime).
    """
    m00, m01, m02 = M[0, 0], M[0, 1], M[0, 2]
    m10, m11, m12 = M[1, 0], M[1, 1], M[1, 2]
    ok1 = jnp.abs(m11) > 1e-3
    m11s = jnp.where(ok1, m11, 1.0)
    alpha = m01 / m11s
    u = m00 - alpha * m10
    ok2 = jnp.abs(u) > 1e-3
    us = jnp.where(ok2, u, 1.0)
    beta = m10 / us
    v = m11
    c = m02 - alpha * m12
    return {
        "alpha": alpha, "beta": beta, "u": us, "v": v, "c": c, "m12": m12,
        "ok": ok1 & ok2,
    }


def _shear_x(img: jnp.ndarray, alpha: jnp.ndarray, cy: float, R: int) -> jnp.ndarray:
    """Resample rows at x + alpha*(y - cy); |alpha*(y-cy)| must be <= R."""
    H, W = img.shape
    y = jnp.arange(H, dtype=jnp.float32) - cy
    s = alpha * y  # (H,) per-row shift
    m = jnp.floor(s)
    f = (s - m)[:, None]
    mi = m.astype(jnp.int32)[:, None]
    padded = jnp.pad(img, ((0, 0), (R + 1, R + 1)), mode="edge")
    out = jnp.zeros_like(img)
    for k in range(-R, R + 1):
        # rows with floor(shift) == k contribute (1-f) at tap k and rows
        # with floor(shift) == k-1 contribute f at their +1 tap.
        coef = jnp.where(mi == k, 1.0 - f, 0.0) + jnp.where(mi == k - 1, f, 0.0)
        out = out + coef * lax.dynamic_slice_in_dim(padded, R + 1 + k, W, axis=1)
    return out


def _shear_y(img: jnp.ndarray, beta: jnp.ndarray, cx: float, R: int) -> jnp.ndarray:
    """Resample columns at y + beta*(x - cx); |beta*(x-cx)| must be <= R."""
    H, W = img.shape
    x = jnp.arange(W, dtype=jnp.float32) - cx
    s = beta * x
    m = jnp.floor(s)
    f = (s - m)[None, :]
    mi = m.astype(jnp.int32)[None, :]
    padded = jnp.pad(img, ((R + 1, R + 1), (0, 0)), mode="edge")
    out = jnp.zeros_like(img)
    for k in range(-R, R + 1):
        coef = jnp.where(mi == k, 1.0 - f, 0.0) + jnp.where(mi == k - 1, f, 0.0)
        out = out + coef * lax.dynamic_slice_in_dim(padded, R + 1 + k, H, axis=0)
    return out


def _resample_matrix(n_in: int, n_out: int, stride, offset) -> jnp.ndarray:
    """(n_out, n_in) banded bilinear matrix: out[i] = in at stride*i+offset.

    Rows whose source position falls outside [0, n_in-1] are all-zero
    (out-of-frame -> 0, matching the gather warp's coverage semantics).
    """
    pos = stride * jnp.arange(n_out, dtype=jnp.float32) + offset  # (n_out,)
    src = jnp.arange(n_in, dtype=jnp.float32)  # (n_in,)
    w = 1.0 - jnp.abs(pos[:, None] - src[None, :])
    K = jnp.maximum(w, 0.0)
    inb = (pos >= 0.0) & (pos <= n_in - 1.0)
    return K * inb[:, None]


@functools.partial(jax.jit, static_argnames=("shear_px", "with_ok"))
def warp_batch_affine(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    shear_px: int = 8,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames through (B, 3, 3) affine transforms with
    zero gathers. Matches vmap(warp_frame) up to the multi-pass
    interpolation difference; frames whose shear magnitude exceeds
    `shear_px` (or whose transform is projective/degenerate) are zeroed
    rather than silently mis-resampled. `with_ok` also returns the (B,)
    bool flag marking frames that were within bounds (False = zeroed).
    """
    B, H, W = frames.shape
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
    hi = jnp.asarray(frames, jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)[None, :]
    ys = jnp.arange(H, dtype=jnp.float32)[:, None]

    def per_frame(img, M):
        p = decompose_affine(M)
        shear_ok = (
            (jnp.abs(p["alpha"]) * max(cy, H - 1 - cy) <= shear_px)
            & (jnp.abs(p["beta"]) * max(cx, W - 1 - cx) <= shear_px)
            & p["ok"]
            # affine only: projective row must be [0, 0, 1]
            & (jnp.abs(M[2, 0]) < 1e-12) & (jnp.abs(M[2, 1]) < 1e-12)
            & (jnp.abs(M[2, 2] - 1.0) < 1e-6)
        )
        # Shear offsets are center-relative; the residual constants fold
        # into the scale-pass offsets (cX absorbs the x-shear's +alpha*cy;
        # dY is solved from the row-1 offset given the ACTUAL cX, since
        # the y-shear pass sees x coordinates that the x-scale pass will
        # later shift by cX - and re-centers by +beta*cx itself).
        x1 = _shear_x(img, p["alpha"], cy, shear_px)
        x2 = _shear_y(x1, p["beta"], cx, shear_px)
        cX = p["c"] + p["alpha"] * cy
        dY = p["m12"] - p["beta"] * (cX - cx)
        Kx = _resample_matrix(W, W, p["u"], cX)
        Ky = _resample_matrix(H, H, p["v"], dY)
        # x-scale: out[h, j] = sum_w x2[h, w] Kx[j, w]  (MXU)
        x3 = jnp.matmul(x2, Kx.T, precision=lax.Precision.HIGHEST)
        # y-scale: out[i, w] = sum_h x3[h, w] Ky[i, h]
        x4 = jnp.matmul(Ky, x3, precision=lax.Precision.HIGHEST)
        # Coverage from the TRUE 2D source positions (the per-axis masks
        # inside the passes cannot see the other axis, and the shear
        # passes edge-replicate): zero out-of-frame output pixels exactly
        # like the gather warp does.
        sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2]
        sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2]
        inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
        return jnp.where(shear_ok & inb, x4, 0.0), shear_ok

    out, ok = jax.vmap(per_frame)(hi, jnp.asarray(transforms, jnp.float32))
    return (out, ok) if with_ok else out
