"""3D keypoint detection for volumetric (z-stack) registration — config 5.

3D Harris: the structure tensor of the volume gradients, Gaussian-
windowed, scored by det(M) - k * trace(M)^3 (the 3D analogue of the 2D
Harris response). NMS is a 3x3x3 max-pool equality; selection is fixed-K
top-k with validity mask, exactly like the 2D path, so the downstream
matcher/RANSAC code is shared unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.ops.detect import Keypoints, sorted_top_k, tile_max_argmax
from kcmc_tpu.ops.patterns import WINDOW_SIGMA


def _conv3d_axis(vol: jnp.ndarray, k: jnp.ndarray, axis: int) -> jnp.ndarray:
    """1D convolution along one axis of a (D, H, W) volume, SAME padding.

    Implemented as a statically unrolled shift-and-add (a handful of
    fused elementwise FMAs): XLA's 3D `conv_general_dilated` on a
    single-channel volume picks a layout with a 128x lane-padding
    blow-up on TPU and OOMs at production sizes.
    """
    taps = int(k.shape[0])
    R = taps // 2
    pad = [(R, taps - 1 - R) if a == axis else (0, 0) for a in range(3)]
    padded = jnp.pad(vol, pad)
    size = list(vol.shape)
    out = jnp.zeros_like(vol)
    for i in range(taps):
        start = [0, 0, 0]
        start[axis] = i
        limits = [s + sz for s, sz in zip(start, size)]
        out = out + k[i] * lax.slice(padded, start, limits)
    return out


def _gauss1d(sigma: float) -> jnp.ndarray:
    radius = max(1, int(3.0 * sigma + 0.5))
    x = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (x / sigma) ** 2)
    return k / jnp.sum(k)


def gaussian_blur_3d(vol: jnp.ndarray, sigma: float) -> jnp.ndarray:
    k = _gauss1d(sigma)
    for axis in range(3):
        vol = _conv3d_axis(vol, k, axis)
    return vol


_DIFF = jnp.array([-0.5, 0.0, 0.5], dtype=jnp.float32)


def harris_response_3d(vol: jnp.ndarray, k: float = 0.005, window_sigma: float = WINDOW_SIGMA) -> jnp.ndarray:
    gz = _conv3d_axis(vol, _DIFF, 0)
    gy = _conv3d_axis(vol, _DIFF, 1)
    gx = _conv3d_axis(vol, _DIFF, 2)
    # unique structure-tensor entries, Gaussian-windowed
    sxx = gaussian_blur_3d(gx * gx, window_sigma)
    syy = gaussian_blur_3d(gy * gy, window_sigma)
    szz = gaussian_blur_3d(gz * gz, window_sigma)
    sxy = gaussian_blur_3d(gx * gy, window_sigma)
    sxz = gaussian_blur_3d(gx * gz, window_sigma)
    syz = gaussian_blur_3d(gy * gz, window_sigma)
    det = (
        sxx * (syy * szz - syz * syz)
        - sxy * (sxy * szz - syz * sxz)
        + sxz * (sxy * syz - syy * sxz)
    )
    trace = sxx + syy + szz
    return det - k * trace * trace * trace


def _maxpool3_same(x: jnp.ndarray) -> jnp.ndarray:
    """3x3x3 max-pool, SAME padding, as fused shift-maxes.

    `lax.reduce_window` costs ~1 ms/volume for this tiny window on TPU
    (measured: 7.7 ms per 8-volume batch, a quarter of the whole
    detection stage); three padded-slice max chains fuse into
    elementwise work instead. Separable: max is associative/idempotent.
    """
    size = x.shape
    for axis in range(3):
        pad = [(1, 1) if a == axis else (0, 0) for a in range(3)]
        p = jnp.pad(x, pad, constant_values=-jnp.inf)
        s0, s1, s2 = [0, 0, 0], [0, 0, 0], [0, 0, 0]
        s1[axis] = 1
        s2[axis] = 2
        lim = lambda st: [st[a] + size[a] for a in range(3)]
        x = lax.max(
            lax.max(lax.slice(p, s0, lim(s0)), lax.slice(p, s1, lim(s1))),
            lax.slice(p, s2, lim(s2)),
        )
    return x


def _select_keypoints_3d(
    resp: jnp.ndarray,
    nms_resp: jnp.ndarray,
    max_keypoints: int,
    threshold: float,
    border: int,
    _force_general: bool = False,
) -> Keypoints:
    """Fixed-K selection from dense (resp, nms_resp) fields — shared by
    the jnp path and the fused Pallas kernel (ops/pallas_detect3d.py).
    `_force_general` is the test seam asserting the tile-aligned fast
    path's results are identical to the general path's (ops/detect.py
    has the same seam)."""
    D, H, W = resp.shape
    bz = min(border, max(1, D // 8))
    # Peak over the selectable region only — a constant background
    # offset creates face-wide response spikes at the volume border
    # (full-rank structure tensor there, unlike a 2D frame's rank-1
    # edge ring) that inflated a whole-volume peak ~50x and killed
    # every interior keypoint (see ops/detect.py::_select_keypoints).
    #
    # Candidate reduction: strongest surviving voxel per (1, T, T) tile
    # then an exact top-k over the tile winners — the 3D counterpart of
    # the 2D tile bucketing, including its round-5 tile-aligned fast
    # path (z tiles are single planes, so the z border masks exactly at
    # tile level regardless of alignment; y/x need border % T == 0).
    T = 8
    if (
        not _force_general
        and border % T == 0 and H % T == 0 and W % T == 0
    ):
        tile_val, tile_arg = tile_max_argmax(nms_resp, T)  # (D, th, tw)
        th, tw = tile_val.shape[1:]
        tzs = jnp.arange(D)[:, None, None]
        tys = jnp.arange(th)[None, :, None]
        txs = jnp.arange(tw)[None, None, :]
        bt = border // T
        tile_inb = (
            (tzs >= bz) & (tzs < D - bz)
            & (tys >= bt) & (tys < th - bt)
            & (txs >= bt) & (txs < tw - bt)
        )
        peak = jnp.maximum(
            jnp.max(jnp.where(tile_inb, tile_val, -jnp.inf)), 1e-12
        )
        tile_val = jnp.where(
            tile_inb & (tile_val > threshold * peak), tile_val, -jnp.inf
        )
    else:
        zs = jnp.arange(D)[:, None, None]
        ys = jnp.arange(H)[None, :, None]
        xs = jnp.arange(W)[None, None, :]
        inb = (
            (zs >= bz) & (zs < D - bz)
            & (ys >= border) & (ys < H - border)
            & (xs >= border) & (xs < W - border)
        )
        peak = jnp.maximum(jnp.max(jnp.where(inb, nms_resp, -jnp.inf)), 1e-12)
        masked = jnp.where(
            inb & (nms_resp > threshold * peak), nms_resp, -jnp.inf
        )
        Hp, Wp = -(-H // T) * T, -(-W // T) * T
        m = jnp.pad(
            masked, ((0, 0), (0, Hp - H), (0, Wp - W)),
            constant_values=-jnp.inf,
        )
        tiles = m.reshape(D, Hp // T, T, Wp // T, T).transpose(0, 1, 3, 2, 4)
        tiles = tiles.reshape(D, Hp // T, Wp // T, T * T)
        tile_val = jnp.max(tiles, axis=-1)
        tile_arg = jnp.argmax(tiles, axis=-1).astype(jnp.int32)

    n_tiles = tile_val.size
    k = min(max_keypoints, n_tiles)
    scores, cand = sorted_top_k(tile_val.reshape(-1), k)
    if k < max_keypoints:
        pad = max_keypoints - k
        scores = jnp.concatenate([scores, jnp.full((pad,), -jnp.inf)])
        cand = jnp.concatenate([cand, jnp.zeros((pad,), cand.dtype)])
    within = tile_arg.reshape(-1)[cand]
    th, tw = tile_val.shape[1], tile_val.shape[2]
    iz = cand // (th * tw)
    iy = ((cand // tw) % th) * T + within // T
    ix = (cand % tw) * T + within % T
    iy = jnp.clip(iy, 0, H - 1)
    ix = jnp.clip(ix, 0, W - 1)
    valid = jnp.isfinite(scores)

    if border >= 1:
        # Subpixel: per-axis parabola offsets from the 6 axis neighbors
        # of each peak — 7 tiny (K,) gathers. The dense-field form this
        # replaces materialized an edge-padded copy of the volume plus
        # THREE full offset fields to read K values from each (round 5;
        # the 2D path keeps dense fields because its fused detect
        # kernel emits them for free — here they were pure XLA cost).
        # Values are identical for every selectable peak: border >= 1
        # in y/x and bz >= 1 keep all six neighbors in bounds, so the
        # edge-replicated pad the old fields used was never reached;
        # the clamp below only moves INVALID slots, whose offsets the
        # valid mask discards.
        izc = jnp.clip(iz, 1, D - 2)
        iyc = jnp.clip(iy, 1, H - 2)
        ixc = jnp.clip(ix, 1, W - 2)
        rf = resp.reshape(-1)

        def at(z, y, x):
            return rf[(z * H + y) * W + x]

        c0 = at(izc, iyc, ixc)

        def axis_off(plus, minus):
            d1 = 0.5 * (plus - minus)
            d2 = plus - 2.0 * c0 + minus
            return jnp.clip(
                jnp.where(jnp.abs(d2) > 1e-8, -d1 / d2, 0.0), -0.5, 0.5
            )

        ox = axis_off(at(izc, iyc, ixc + 1), at(izc, iyc, ixc - 1))
        oy = axis_off(at(izc, iyc + 1, ixc), at(izc, iyc - 1, ixc))
        oz = axis_off(at(izc + 1, iyc, ixc), at(izc - 1, iyc, ixc))
    else:
        # border = 0: peaks may sit on the volume faces, where the old
        # dense fields' edge-replicated pad matters — keep them.
        r = jnp.pad(resp, 1, mode="edge")

        def axis_field(plus, minus):
            d1 = 0.5 * (plus - minus)
            d2 = plus - 2.0 * resp + minus
            return jnp.clip(
                jnp.where(jnp.abs(d2) > 1e-8, -d1 / d2, 0.0), -0.5, 0.5
            )

        ox_f = axis_field(r[1:-1, 1:-1, 2:], r[1:-1, 1:-1, :-2])
        oy_f = axis_field(r[1:-1, 2:, 1:-1], r[1:-1, :-2, 1:-1])
        oz_f = axis_field(r[2:, 1:-1, 1:-1], r[:-2, 1:-1, 1:-1])
        flat_idx = (iz * H + iy) * W + ix
        ox = ox_f.reshape(-1)[flat_idx]
        oy = oy_f.reshape(-1)[flat_idx]
        oz = oz_f.reshape(-1)[flat_idx]

    xyz = jnp.stack(
        [ix.astype(jnp.float32) + ox, iy.astype(jnp.float32) + oy, iz.astype(jnp.float32) + oz],
        axis=-1,
    )
    xyz = jnp.where(valid[:, None], xyz, 0.0)
    scores = jnp.where(valid, scores, 0.0)
    return Keypoints(xy=xyz, score=scores, valid=valid)


@functools.partial(jax.jit, static_argnames=("max_keypoints", "border"))
def detect_keypoints_3d(
    vol: jnp.ndarray,
    max_keypoints: int = 256,
    threshold: float = 1e-4,
    border: int = 6,
    harris_k: float = 0.005,
) -> Keypoints:
    """Detect fixed-K 3D corners in a (D, H, W) volume.

    Returns Keypoints with xy = (K, 3) float (x, y, z) positions.
    """
    resp = harris_response_3d(vol, k=harris_k)
    nms_resp = jnp.where(resp >= _maxpool3_same(resp), resp, -jnp.inf)
    return _select_keypoints_3d(
        resp, nms_resp, max_keypoints, threshold, border
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_keypoints", "threshold", "border", "harris_k",
        "use_pallas", "smooth_sigma", "interpret",
    ),
)
def detect_keypoints_3d_batch(
    vols: jnp.ndarray,
    max_keypoints: int = 256,
    threshold: float = 1e-4,
    border: int = 6,
    harris_k: float = 0.005,
    use_pallas: bool = False,
    smooth_sigma: float | None = None,
    interpret: bool = False,
):
    """Detect keypoints over a (B, D, H, W) batch; fields carry a batch
    axis. With `use_pallas` the dense response/NMS fields come from the
    fused kernel (ops/pallas_detect3d.py) — one VMEM-resident pass over
    (z-block, y-strip) tiles instead of ~25 HBM-round-tripping
    shift-and-add passes; selection stays in XLA.

    With `smooth_sigma` returns (keypoints, smooth): the sigma-blurred
    batch for the descriptor stage (a free ride on the fused kernel's
    resident slab when the Pallas path runs)."""
    if smooth_sigma is not None and smooth_sigma <= 0.0:
        raise ValueError(f"smooth_sigma must be positive, got {smooth_sigma}")
    if use_pallas:
        from kcmc_tpu.ops.pallas_detect3d import response_fields_3d, supports

        if supports(vols.shape[1:], smooth_sigma=smooth_sigma):
            out = response_fields_3d(
                vols, harris_k=harris_k, smooth_sigma=smooth_sigma,
                interpret=interpret,
            )
            kps = jax.vmap(
                lambda r, n: _select_keypoints_3d(
                    r, n, max_keypoints, threshold, border
                )
            )(*out[:2])
            return (kps, out[2]) if smooth_sigma is not None else kps
    kps = jax.vmap(
        lambda v: detect_keypoints_3d(
            v,
            max_keypoints=max_keypoints,
            threshold=threshold,
            border=border,
            harris_k=harris_k,
        )
    )(vols)
    if smooth_sigma is not None:
        smooth = jax.vmap(lambda v: gaussian_blur_3d(v, smooth_sigma))(vols)
        return kps, smooth
    return kps
