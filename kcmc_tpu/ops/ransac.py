"""Consensus transform estimation: statically-shaped RANSAC for TPU.

Counterpart of the reference's `ConsensusTransformEstimator` (SURVEY.md
§2: hypothesis sampling -> transform solve -> residual/inlier scoring ->
least-squares refinement). Re-designed for XLA rather than translated:

* A *fixed* hypothesis count H (no adaptive early exit — SURVEY.md §7
  "hard parts"): all H minimal-sample solves + scores run as one vmapped
  batch, and the whole thing vmaps again over frames, giving the
  (frames x hypotheses) batching named in BASELINE.json's north star.
* Minimal-set sampling is top-m of iid uniform scores over the
  valid-match mask (m unrolled argmax+mask rounds): an O(m N) way to
  draw m distinct valid indices per hypothesis with no rejection loops,
  deterministic given the PRNG key (so jax-on-CPU and jax-on-TPU
  reproduce each other).
* Samples become one-hot *weights* into the same weighted solver used
  for refinement — one code path, no dynamic gathers of variable size.
* Refinement is fixed-iteration IRLS: re-score inliers, re-solve with
  the inlier mask as weights. The candidate with the most inliers wins
  via argmax; a refinement step that loses inliers is rolled back.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.models.transforms import TransformModel


class RansacResult(NamedTuple):
    transform: jnp.ndarray  # (d+1, d+1) best refined transform
    n_inliers: jnp.ndarray  # () int32
    inlier_mask: jnp.ndarray  # (N,) bool under the final transform
    rms_residual: jnp.ndarray  # () float32 RMS residual over final inliers


def _sample_indices(key, valid: jnp.ndarray, m: int) -> jnp.ndarray:
    """Indices of m distinct valid matches (top-m of iid uniform
    scores — the same uniform-random distinct subset Gumbel top-m
    draws, with a cheaper sampler).

    Selection runs as m sequential argmax+mask rounds instead of
    `lax.top_k` + scatter: for the tiny m (1-4) of minimal sets the
    unrolled masked argmaxes measure ~2x faster vmapped over
    (frames x hypotheses). If fewer than m matches are valid the extra
    rounds argmax an all-(-1) score vector and return slot 0 — usually
    a DUPLICATE of an already-picked valid match, so the caller's
    `valid[idx]` weights do NOT zero it and the weight-mass guard does
    not fire; what actually protects that case is each solver's own
    rank/pivot degeneracy guard on the duplicated-point system (a new
    model's solver must have one — see models/transforms.py).

    The minimal solve consumes the GATHERED m points, not an (N,)
    one-hot weight vector (round 5): the weighted solve ran its ~10
    moment reductions over all N points per hypothesis — (B, H, N)
    traffic for m=3 real values — where an (H, m) gather from the
    per-frame match table is on the fast small-table gather path.
    """
    u = jax.random.uniform(key, valid.shape, dtype=jnp.float32)
    scores = jnp.where(valid, u, -1.0)
    iota = lax.iota(jnp.int32, valid.shape[0])
    picks = []
    for _ in range(m):
        j = jnp.argmax(scores)
        picks.append(j)
        scores = jnp.where(iota == j, -1.0, scores)
    return jnp.stack(picks)


@functools.partial(
    jax.jit,
    static_argnames=("model", "n_hypotheses", "refine_iters", "score_cap"),
)
def ransac_estimate(
    model: TransformModel,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    valid: jnp.ndarray,
    key: jnp.ndarray,
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
    score_cap: int = 0,
) -> RansacResult:
    """Estimate `model`'s transform mapping src -> dst by RANSAC consensus.

    src/dst: (N, d) matched point pairs; valid: (N,) mask of real matches.
    Fully jit/vmap-safe: fixed H hypotheses, masked scoring, fixed-round
    IRLS refinement.

    `score_cap` > 0 bounds the per-hypothesis SCORING work: when N
    exceeds it, inlier scoring runs on an every-stride-th subset of
    the matches (~score_cap of them). The (frames x hypotheses x N)
    residual traffic is the consensus stage's dominant cost at high
    match counts (measured ~20 ms/batch at N=4096, H=128, B=32), and
    ranking hypotheses by inlier count needs only a statistical
    estimate — at 1024 samples the inlier-fraction standard error is
    ~1.5%, far below the gap between a good and a degenerate
    hypothesis. Most hypotheses also SAMPLE and
    solve from the subset (that is where the traffic saving lives),
    but the first eighth of the pool samples from the FULL set: a
    sparse-match frame can leave the strided subset below
    min_samples, degenerating every subset hypothesis to the guarded
    identity — the full-pool hypotheses stay well-formed, and being
    listed FIRST they win argmax on the tied near-zero subset scores.
    The WINNER's IRLS refinement, final polish, and reported
    diagnostics always use the full match set, so the delivered fit
    and n_inliers are full-precision.
    """
    thresh_sq = jnp.float32(threshold * threshold)
    N = src.shape[0]
    subset = bool(score_cap) and N > score_cap
    if subset:
        stride = -(-N // score_cap)
        # strided subset: matches arrive in detector-score slot order,
        # so a stride is a uniform sample across score ranks
        src_s, dst_s, valid_s = src[::stride], dst[::stride], valid[::stride]
    else:
        src_s, dst_s, valid_s = src, dst, valid

    def one_hypothesis_from(srch, dsth, validh):
        def go(k):
            idx = _sample_indices(k, validh, model.min_samples)
            M = model.solve(
                srch[idx], dsth[idx], validh[idx].astype(jnp.float32)
            )
            r = model.residual(M, src_s, dst_s)
            inl = (r < thresh_sq) & valid_s
            return M, jnp.sum(inl)

        return go

    keys = jax.random.split(key, n_hypotheses)
    if subset:
        n_full = max(1, n_hypotheses // 8)
        Mf_, sf_ = jax.vmap(one_hypothesis_from(src, dst, valid))(
            keys[:n_full]
        )
        Msub, ssub = jax.vmap(
            one_hypothesis_from(src_s, dst_s, valid_s)
        )(keys[n_full:])
        Ms = jnp.concatenate([Mf_, Msub])
        scores = jnp.concatenate([sf_, ssub])
    else:
        Ms, scores = jax.vmap(one_hypothesis_from(src, dst, valid))(keys)
    best = jnp.argmax(scores)
    M0 = Ms[best]
    if subset:
        # re-count the winner on the FULL set so the refinement's
        # don't-lose-consensus comparisons are apples to apples
        n0 = jnp.sum((model.residual(M0, src, dst) < thresh_sq) & valid)
    else:
        n0 = scores[best]

    def refine_step(carry, _):
        M, n_in = carry
        r = model.residual(M, src, dst)
        w = ((r < thresh_sq) & valid).astype(jnp.float32)
        M2 = model.resolved_refine_solve(src, dst, w)
        r2 = model.residual(M2, src, dst)
        n2 = jnp.sum((r2 < thresh_sq) & valid)
        # Keep the refinement only if it doesn't lose consensus.
        better = n2 >= n_in
        M_out = jnp.where(better, M2, M)
        return (M_out, jnp.maximum(n2, n_in)), None

    (Mf, _), _ = lax.scan(refine_step, (M0, n0), None, length=refine_iters)

    # Final polish: one least-squares solve (the accurate solver, where a
    # model provides one) on the final consensus set. The in-scan
    # rollback can otherwise pin the result to a minimal-sample
    # hypothesis solve whose inlier count happens to tie the refined
    # one. Accepted while it keeps (almost all of) the consensus — a
    # slight inlier-count dip at the threshold boundary is the expected
    # signature of a better LS fit, but a polish that sheds consensus
    # wholesale (degenerate weighted solve) is rolled back.
    mask_f = (model.residual(Mf, src, dst) < thresh_sq) & valid
    nf = jnp.sum(mask_f)
    wf = mask_f.astype(jnp.float32)
    Mp = model.resolved_refine_solve(src, dst, wf)
    np_ = jnp.sum((model.residual(Mp, src, dst) < thresh_sq) & valid)
    keep = np_.astype(jnp.float32) >= 0.8 * nf.astype(jnp.float32)
    Mf = jnp.where(keep & (np_ >= model.min_samples), Mp, Mf)

    r = model.residual(Mf, src, dst)
    inl = (r < thresh_sq) & valid
    n_in = jnp.sum(inl)
    rms = jnp.sqrt(
        jnp.sum(jnp.where(inl, r, 0.0)) / jnp.maximum(n_in.astype(jnp.float32), 1.0)
    )
    return RansacResult(
        transform=Mf,
        n_inliers=n_in.astype(jnp.int32),
        inlier_mask=inl,
        rms_residual=rms,
    )
