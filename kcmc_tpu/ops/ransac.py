"""Consensus transform estimation: statically-shaped RANSAC for TPU.

Counterpart of the reference's `ConsensusTransformEstimator` (SURVEY.md
§2: hypothesis sampling -> transform solve -> residual/inlier scoring ->
least-squares refinement). Re-designed for XLA rather than translated:

* Hypothesis solves and scores run as BATCH-level (frames x hypotheses)
  blocks (`consensus_batch` — the PR-13 fused-dispatch shape): the
  whole batch's hypothesis work is one uniform program instead of a
  per-frame vmap of per-hypothesis launches, giving XLA large fusion
  regions and the MXU full tiles for the residual reductions.
* An OPTIONAL adaptive hypothesis-budget ladder (`budget_rungs` > 1):
  hypotheses are scored in equal-size rung chunks under one
  `lax.while_loop`, and a frame whose running best inlier count clears
  `early_exit_frac` of its valid matches stops ACCEPTING candidates
  from later rungs (masked per frame, so each frame's result depends
  only on its own data — batch-boundary invariant). The loop itself
  exits once every frame is done, so a steady-state batch pays one
  rung instead of the full budget — the classic adaptive-termination
  RANSAC economy (Fischler & Bolles 1981), expressed jit-safely with a
  STATIC rung set (no retraces; the ladder is one compiled program).
* An optional SEED transform (temporal warm start): the previous
  frame's transform scores as hypothesis zero before any rung runs. A
  good seed on a steady-state frame clears the exit bar immediately
  (zero rungs of sampling); a stale seed (scene cut) scores poorly and
  the ladder proceeds to the full budget — the fallback is automatic,
  not flagged.
* Minimal-set sampling is top-m of iid uniform scores over the
  valid-match mask (m unrolled argmax+mask rounds): an O(m N) way to
  draw m distinct valid indices per hypothesis with no rejection loops,
  deterministic given the PRNG key (so jax-on-CPU and jax-on-TPU
  reproduce each other). Per-hypothesis keys derive as
  fold_in(frame_key, hypothesis_id), so a frame's draws are independent
  of batch boundaries and of how many rungs other frames needed.
* Refinement is fixed-iteration IRLS on the FULL match set: re-score
  inliers, re-solve with the inlier mask as weights. A refinement step
  that loses inliers is rolled back; a final least-squares polish runs
  on the final consensus set. Early-exited frames pay the identical
  refinement, so the delivered fit is full-precision either way.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.models.transforms import TransformModel

# A frame must have at least this many valid (subset-)matches before
# the early-exit bar can arm: below it the inlier FRACTION is too noisy
# a statistic to cut the search on (binomial std err ~ 1/sqrt(n)).
EARLY_EXIT_MIN_MATCHES = 24


class RansacResult(NamedTuple):
    transform: jnp.ndarray  # (d+1, d+1) best refined transform
    n_inliers: jnp.ndarray  # () int32
    inlier_mask: jnp.ndarray  # (N,) bool under the final transform
    rms_residual: jnp.ndarray  # () float32 RMS residual over final inliers


def _sample_indices(key, valid: jnp.ndarray, m: int) -> jnp.ndarray:
    """Indices of m distinct valid matches (top-m of iid uniform
    scores — the same uniform-random distinct subset Gumbel top-m
    draws, with a cheaper sampler).

    Selection runs as m sequential argmax+mask rounds instead of
    `lax.top_k` + scatter: for the tiny m (1-4) of minimal sets the
    unrolled masked argmaxes measure ~2x faster vmapped over
    (frames x hypotheses). If fewer than m matches are valid the extra
    rounds argmax an all-(-1) score vector and return slot 0 — usually
    a DUPLICATE of an already-picked valid match, so the caller's
    `valid[idx]` weights do NOT zero it and the weight-mass guard does
    not fire; what actually protects that case is each solver's own
    rank/pivot degeneracy guard on the duplicated-point system (a new
    model's solver must have one — see models/transforms.py).

    The minimal solve consumes the GATHERED m points, not an (N,)
    one-hot weight vector (round 5): the weighted solve ran its ~10
    moment reductions over all N points per hypothesis — (B, H, N)
    traffic for m=3 real values — where an (H, m) gather from the
    per-frame match table is on the fast small-table gather path.
    """
    u = jax.random.uniform(key, valid.shape, dtype=jnp.float32)
    scores = jnp.where(valid, u, -1.0)
    iota = lax.iota(jnp.int32, valid.shape[0])
    picks = []
    for _ in range(m):
        j = jnp.argmax(scores)
        picks.append(j)
        scores = jnp.where(iota == j, -1.0, scores)
    return jnp.stack(picks)


def _refine_polish(model, M0, n0, src, dst, valid, thresh_sq, refine_iters):
    """IRLS refinement + final LS polish of one frame's winning
    hypothesis, on the FULL match set (identical for every budget
    path — early exit never degrades the delivered fit)."""

    def refine_step(carry, _):
        M, n_in = carry
        r = model.residual(M, src, dst)
        w = ((r < thresh_sq) & valid).astype(jnp.float32)
        M2 = model.resolved_refine_solve(src, dst, w)
        r2 = model.residual(M2, src, dst)
        n2 = jnp.sum((r2 < thresh_sq) & valid)
        # Keep the refinement only if it doesn't lose consensus.
        better = n2 >= n_in
        M_out = jnp.where(better, M2, M)
        return (M_out, jnp.maximum(n2, n_in)), None

    (Mf, _), _ = lax.scan(refine_step, (M0, n0), None, length=refine_iters)

    # Final polish: one least-squares solve (the accurate solver, where a
    # model provides one) on the final consensus set. The in-scan
    # rollback can otherwise pin the result to a minimal-sample
    # hypothesis solve whose inlier count happens to tie the refined
    # one. Accepted while it keeps (almost all of) the consensus — a
    # slight inlier-count dip at the threshold boundary is the expected
    # signature of a better LS fit, but a polish that sheds consensus
    # wholesale (degenerate weighted solve) is rolled back.
    mask_f = (model.residual(Mf, src, dst) < thresh_sq) & valid
    nf = jnp.sum(mask_f)
    wf = mask_f.astype(jnp.float32)
    Mp = model.resolved_refine_solve(src, dst, wf)
    np_ = jnp.sum((model.residual(Mp, src, dst) < thresh_sq) & valid)
    keep = np_.astype(jnp.float32) >= 0.8 * nf.astype(jnp.float32)
    Mf = jnp.where(keep & (np_ >= model.min_samples), Mp, Mf)

    r = model.residual(Mf, src, dst)
    inl = (r < thresh_sq) & valid
    n_in = jnp.sum(inl)
    rms = jnp.sqrt(
        jnp.sum(jnp.where(inl, r, 0.0)) / jnp.maximum(n_in.astype(jnp.float32), 1.0)
    )
    return RansacResult(
        transform=Mf,
        n_inliers=n_in.astype(jnp.int32),
        inlier_mask=inl,
        rms_residual=rms,
    )


def consensus_batch(
    model: TransformModel,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    valid: jnp.ndarray,
    keys: jnp.ndarray,
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
    score_cap: int = 0,
    budget_rungs: int = 0,
    early_exit_frac: float = 0.7,
    seed_transform: jnp.ndarray | None = None,
    seed_ok: jnp.ndarray | None = None,
) -> RansacResult:
    """Batched RANSAC consensus over a whole frame batch.

    src/dst: (B, N, d) matched point pairs; valid: (B, N); keys: (B,)
    per-frame PRNG keys (fold_in of the global frame index upstream).
    Returns a RansacResult whose fields carry a leading batch axis.

    `score_cap` > 0 bounds the per-hypothesis SCORING work: when N
    exceeds it, inlier scoring runs on an every-stride-th subset of
    the matches (~score_cap of them). The (frames x hypotheses x N)
    residual traffic is the consensus stage's dominant cost at high
    match counts (measured ~20 ms/batch at N=4096, H=128, B=32), and
    ranking hypotheses by inlier count needs only a statistical
    estimate — at 1024 samples the inlier-fraction standard error is
    ~1.5%, far below the gap between a good and a degenerate
    hypothesis. Most hypotheses also SAMPLE and solve from the subset
    (that is where the traffic saving lives), but the first eighth of
    the pool samples from the FULL set: a sparse-match frame can leave
    the strided subset below min_samples, degenerating every subset
    hypothesis to the guarded identity — the full-pool hypotheses stay
    well-formed, and running FIRST they win the running-max on the
    tied near-zero subset scores. The WINNER's IRLS refinement, final
    polish, and reported diagnostics always use the full match set, so
    the delivered fit and n_inliers are full-precision.

    `budget_rungs` > 1 arms the adaptive ladder (module docstring):
    the budget splits into that many equal rung chunks (rounded up —
    the ladder spends AT LEAST n_hypotheses when it runs dry) behind
    one `lax.while_loop`. Early-exited frames stop accepting later
    candidates (per-frame masking — results are independent of
    batchmates); the loop stops once all frames are done. <= 1 keeps
    the static full-budget path.

    `seed_transform` ((d+1, d+1) shared, or (B, d+1, d+1) per frame) +
    `seed_ok` (bool, scalar or (B,)) score as hypothesis zero — the
    temporal warm start. A seed never reduces accuracy: it only ever
    ADDS a candidate, and a seed below the exit bar leaves the ladder
    to run exactly as unseeded.
    """
    B, N = src.shape[0], src.shape[1]
    m = int(model.min_samples)
    dd = int(model.ndim) + 1
    thresh_sq = jnp.float32(threshold * threshold)
    H = int(n_hypotheses)
    subset = bool(score_cap) and N > int(score_cap)
    if subset:
        stride = -(-N // int(score_cap))
        # strided subset: matches arrive in detector-score slot order,
        # so a stride is a uniform sample across score ranks
        src_s, dst_s = src[:, ::stride], dst[:, ::stride]
        valid_s = valid[:, ::stride]
    else:
        src_s, dst_s, valid_s = src, dst, valid

    def solve_block(hids, psrc, pdst, pvalid):
        """(B, C, d+1, d+1) minimal-sample solves: hypothesis ids
        `hids` (C,) sampled from the given per-frame pools."""

        def per_frame(key, s, t, v):
            def per_hyp(h):
                k = jax.random.fold_in(key, h)
                idx = _sample_indices(k, v, m)
                return model.solve(s[idx], t[idx], v[idx].astype(jnp.float32))

            return jax.vmap(per_hyp)(hids)

        return jax.vmap(per_frame)(keys, psrc, pdst, pvalid)

    def score_block(Ms):
        """(B, C) inlier counts of a hypothesis block on the scoring
        pool (the subset when score_cap is active)."""

        def per_frame(Mf, s, t, v):
            def per_hyp(M):
                r = model.residual(M, s, t)
                return jnp.sum((r < thresh_sq) & v)

            return jax.vmap(per_hyp)(Mf)

        return jax.vmap(per_frame)(Ms, src_s, dst_s, valid_s)

    bidx = jnp.arange(B)

    def merge(best_M, best_s, done, Ms, scores):
        """Fold one block's best candidate into the running best.
        Strict > keeps the earliest maximum (the static path's concat-
        argmax tie rule); `done` frames ignore new candidates so a
        frame's result never depends on how long batchmates search."""
        j = jnp.argmax(scores, axis=1)
        cs = scores[bidx, j].astype(jnp.int32)
        cM = Ms[bidx, j]
        upd = (cs > best_s) & ~done
        return (
            jnp.where(upd[:, None, None], cM, best_M),
            jnp.where(upd, cs, best_s),
        )

    # Early-exit bar: the running best must explain early_exit_frac of
    # the frame's valid (scoring-pool) matches, with enough matches for
    # the fraction to be a meaningful statistic.
    n_valid_s = jnp.sum(valid_s, axis=1).astype(jnp.int32)
    exit_floor = jnp.maximum(
        jnp.ceil(
            jnp.float32(early_exit_frac) * n_valid_s.astype(jnp.float32)
        ).astype(jnp.int32),
        jnp.int32(m + 2),
    )
    can_exit = n_valid_s >= EARLY_EXIT_MIN_MATCHES

    eye = jnp.broadcast_to(jnp.eye(dd, dtype=jnp.float32), (B, dd, dd))
    never_done = jnp.zeros((B,), bool)
    if seed_transform is not None:
        seedM = jnp.asarray(seed_transform, jnp.float32)
        if seedM.ndim == 2:
            seedM = jnp.broadcast_to(seedM, (B, dd, dd))
        sok = jnp.broadcast_to(jnp.asarray(seed_ok, bool), (B,))

        def seed_score(M, s, t, v):
            r = model.residual(M, s, t)
            return jnp.sum((r < thresh_sq) & v)

        s_sc = jax.vmap(seed_score)(seedM, src_s, dst_s, valid_s).astype(
            jnp.int32
        )
        best_s = jnp.where(sok, s_sc, jnp.int32(-1))
        best_M = jnp.where(sok[:, None, None], seedM, eye)
    else:
        best_s = jnp.full((B,), -1, jnp.int32)
        best_M = eye

    rungs = int(budget_rungs)
    adaptive = rungs > 1 and H > rungs
    n_full = max(1, H // 8) if subset else 0

    if not adaptive:
        # Static full-budget path (the pre-ladder semantics).
        if subset:
            Ms = solve_block(jnp.arange(n_full), src, dst, valid)
            best_M, best_s = merge(best_M, best_s, never_done, Ms, score_block(Ms))
            Ms = solve_block(jnp.arange(n_full, H), src_s, dst_s, valid_s)
            best_M, best_s = merge(best_M, best_s, never_done, Ms, score_block(Ms))
        else:
            Ms = solve_block(jnp.arange(H), src, dst, valid)
            best_M, best_s = merge(best_M, best_s, never_done, Ms, score_block(Ms))
    else:
        done0 = can_exit & (best_s >= exit_floor)
        if subset:
            # Rung 0 = the full-pool block (the sparse-frame guard),
            # rungs 1..R = equal chunks of the subset-sampled pool.
            C0 = n_full
            C = -(-(H - C0) // rungs)
            n_iters = rungs + 1

            def run_block(i, done, bM, bs):
                def full_block(args):
                    done, bM, bs = args
                    Ms = solve_block(jnp.arange(C0), src, dst, valid)
                    return merge(bM, bs, done, Ms, score_block(Ms))

                def sub_block(args):
                    done, bM, bs = args
                    hids = C0 + (i - 1) * C + jnp.arange(C)
                    Ms = solve_block(hids, src_s, dst_s, valid_s)
                    return merge(bM, bs, done, Ms, score_block(Ms))

                return lax.cond(i == 0, full_block, sub_block, (done, bM, bs))

        else:
            C = -(-H // rungs)
            n_iters = rungs

            def run_block(i, done, bM, bs):
                hids = i * C + jnp.arange(C)
                Ms = solve_block(hids, src, dst, valid)
                return merge(bM, bs, done, Ms, score_block(Ms))

        def cond(carry):
            i, done, _, _ = carry
            return (i < n_iters) & ~jnp.all(done)

        def body(carry):
            i, done, bM, bs = carry
            bM, bs = run_block(i, done, bM, bs)
            done = done | (can_exit & (bs >= exit_floor))
            return i + 1, done, bM, bs

        _, _, best_M, best_s = lax.while_loop(
            cond, body, (jnp.int32(0), done0, best_M, best_s)
        )

    if subset:
        # Re-count the winner on the FULL set so the refinement's
        # don't-lose-consensus comparisons are apples to apples.
        def recount(M, s, t, v):
            r = model.residual(M, s, t)
            return jnp.sum((r < thresh_sq) & v)

        n0 = jax.vmap(recount)(best_M, src, dst, valid)
    else:
        n0 = best_s

    return jax.vmap(
        lambda M0, nn, s, t, v: _refine_polish(
            model, M0, nn, s, t, v, thresh_sq, refine_iters
        )
    )(best_M, n0, src, dst, valid)


def _estimate_single(
    model, src, dst, valid, key, n_hypotheses, threshold, refine_iters,
    score_cap,
) -> RansacResult:
    """The pre-PR-13 single-frame path, kept verbatim (same structure,
    same `jax.random.split` hypothesis stream): the piecewise field
    estimator calls this under DEEP vmaps (frames × patches × passes)
    with tiny budgets, where the batch-blocked consensus_batch lowering
    measured ~25% slower on CPU — and keeping the original RNG here
    means every fixed-budget single-frame caller reproduces its
    pre-PR-13 draws exactly."""
    thresh_sq = jnp.float32(threshold * threshold)
    N = src.shape[0]
    m = model.min_samples
    subset = bool(score_cap) and N > score_cap
    if subset:
        stride = -(-N // score_cap)
        src_s, dst_s, valid_s = src[::stride], dst[::stride], valid[::stride]
    else:
        src_s, dst_s, valid_s = src, dst, valid

    def one_hypothesis_from(srch, dsth, validh):
        def go(k):
            idx = _sample_indices(k, validh, m)
            M = model.solve(
                srch[idx], dsth[idx], validh[idx].astype(jnp.float32)
            )
            r = model.residual(M, src_s, dst_s)
            inl = (r < thresh_sq) & valid_s
            return M, jnp.sum(inl)

        return go

    keys = jax.random.split(key, n_hypotheses)
    if subset:
        n_full = max(1, n_hypotheses // 8)
        Mf_, sf_ = jax.vmap(one_hypothesis_from(src, dst, valid))(
            keys[:n_full]
        )
        Msub, ssub = jax.vmap(
            one_hypothesis_from(src_s, dst_s, valid_s)
        )(keys[n_full:])
        Ms = jnp.concatenate([Mf_, Msub])
        scores = jnp.concatenate([sf_, ssub])
    else:
        Ms, scores = jax.vmap(one_hypothesis_from(src, dst, valid))(keys)
    best = jnp.argmax(scores)
    M0 = Ms[best]
    if subset:
        # re-count the winner on the FULL set so the refinement's
        # don't-lose-consensus comparisons are apples to apples
        n0 = jnp.sum((model.residual(M0, src, dst) < thresh_sq) & valid)
    else:
        n0 = scores[best]
    return _refine_polish(
        model, M0, n0, src, dst, valid, thresh_sq, refine_iters
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "model", "n_hypotheses", "refine_iters", "score_cap", "budget_rungs",
    ),
)
def ransac_estimate(
    model: TransformModel,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    valid: jnp.ndarray,
    key: jnp.ndarray,
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
    score_cap: int = 0,
    budget_rungs: int = 0,
    early_exit_frac: float = 0.7,
    seed_transform: jnp.ndarray | None = None,
    seed_ok: jnp.ndarray | None = None,
) -> RansacResult:
    """Estimate `model`'s transform mapping src -> dst by RANSAC consensus.

    src/dst: (N, d) matched point pairs; valid: (N,) mask of real matches.
    Fully jit/vmap-safe. With the default fixed budget and no seed this
    is the original single-frame path (identical draws and lowering to
    pre-PR-13 — the piecewise patch stages vmap it heavily); the
    `budget_rungs` adaptive ladder and the `seed_transform` warm start
    route through `consensus_batch` (which see)."""
    if int(budget_rungs) <= 1 and seed_transform is None:
        return _estimate_single(
            model, src, dst, valid, key, n_hypotheses, threshold,
            refine_iters, score_cap,
        )
    res = consensus_batch(
        model,
        src[None],
        dst[None],
        valid[None],
        key[None],
        n_hypotheses=n_hypotheses,
        threshold=threshold,
        refine_iters=refine_iters,
        score_cap=score_cap,
        budget_rungs=budget_rungs,
        early_exit_frac=early_exit_frac,
        seed_transform=(
            None if seed_transform is None else seed_transform[None]
        ),
        seed_ok=None if seed_transform is None else seed_ok,
    )
    return RansacResult(*(x[0] for x in res))
