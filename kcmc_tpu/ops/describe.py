"""Binary keypoint descriptors: oriented BRIEF (ORB-style), TPU-native.

Counterpart of the reference `KeypointExtractor`'s describe stage
(SURVEY.md §2; BASELINE.json names ORB keypoints for the affine config).
Rebuilt for TPU rather than translated:

* The classic BRIEF sampling pattern (256 Gaussian-distributed point
  pairs in a radius-13 patch) is a host-side constant baked into the
  compiled program.
* Orientation comes from the intensity-centroid moment of a disc around
  the keypoint (the ORB approach), computed with one dynamic-slice patch
  gather per keypoint and vmapped — no per-keypoint Python.
* Descriptor bits are bilinear samples of the blurred frame at the
  rotated pair positions; 256 comparisons pack into 8 uint32 lanes so
  Hamming distance is XOR + popcount on 8 words (ops/match.py).

Everything is fixed-K and mask-aware: invalid keypoint slots produce
all-zero descriptors which the matcher masks out.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.detect import Keypoints, gaussian_blur
from kcmc_tpu.ops.patterns import (  # shared, JAX-free constants
    MOMENTS as _MOMENTS,
    MOMENT_RADIUS as _MOMENT_RADIUS,
    N_BITS,
    N_WORDS,
    PATCH_RADIUS,
    PATTERN,
)


def _bilinear_sample(img: jnp.ndarray, xy: jnp.ndarray) -> jnp.ndarray:
    """Sample (H, W) image at (..., 2) float (x, y) points, edge-clamped."""
    H, W = img.shape
    x = jnp.clip(xy[..., 0], 0.0, W - 1.0)
    y = jnp.clip(xy[..., 1], 0.0, H - 1.0)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    fx = x - x0
    fy = y - y0
    x0i = x0.astype(jnp.int32)
    y0i = y0.astype(jnp.int32)
    x1i = jnp.minimum(x0i + 1, W - 1)
    y1i = jnp.minimum(y0i + 1, H - 1)
    flat = img.reshape(-1)
    v00 = flat[y0i * W + x0i]
    v01 = flat[y0i * W + x1i]
    v10 = flat[y1i * W + x0i]
    v11 = flat[y1i * W + x1i]
    return (
        v00 * (1 - fx) * (1 - fy)
        + v01 * fx * (1 - fy)
        + v10 * (1 - fx) * fy
        + v11 * fx * fy
    )


def _orientation(img: jnp.ndarray, xy: jnp.ndarray) -> jnp.ndarray:
    """ORB intensity-centroid angle at one keypoint. xy: (2,) float."""
    r = _MOMENT_RADIUS
    H, W = img.shape
    cy = jnp.clip(jnp.round(xy[1]).astype(jnp.int32), r, H - r - 1)
    cx = jnp.clip(jnp.round(xy[0]).astype(jnp.int32), r, W - r - 1)
    patch = lax.dynamic_slice(img, (cy - r, cx - r), (2 * r + 1, 2 * r + 1))
    moms = jnp.asarray(_MOMENTS)
    w = patch * moms[..., 2]
    m10 = jnp.sum(w * moms[..., 0])
    m01 = jnp.sum(w * moms[..., 1])
    return jnp.arctan2(m01, m10)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(K, N_BITS) bool -> (K, N_WORDS) uint32."""
    b = bits.reshape(bits.shape[0], N_WORDS, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(b << shifts[None, None, :], axis=-1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("oriented", "blur_sigma"))
def describe_keypoints(
    img: jnp.ndarray,
    kps: Keypoints,
    oriented: bool = True,
    blur_sigma: float = 2.0,
) -> jnp.ndarray:
    """Compute (K, N_WORDS) uint32 BRIEF descriptors for one frame.

    `oriented=True` steers the pattern by the intensity-centroid angle
    (rotation-invariant, ORB-style); `False` is classic upright BRIEF —
    slightly more discriminative when the motion model has no rotation
    (the translation-only config).
    """
    smooth = gaussian_blur(img, blur_sigma)
    pattern = jnp.asarray(PATTERN)  # (B, 2, 2)

    if oriented:
        angles = jax.vmap(lambda p: _orientation(smooth, p))(kps.xy)  # (K,)
        c, s = jnp.cos(angles), jnp.sin(angles)
        # Rotation matrices (K, 2, 2): steer pattern per keypoint.
        R = jnp.stack([jnp.stack([c, -s], -1), jnp.stack([s, c], -1)], -2)
        offs = jnp.einsum("kij,bej->kbei", R, pattern)  # (K, B, 2, 2)
    else:
        offs = jnp.broadcast_to(pattern[None], (kps.xy.shape[0],) + pattern.shape)

    pos = kps.xy[:, None, None, :] + offs  # (K, B, 2, 2)
    vals = _bilinear_sample(smooth, pos)  # (K, B, 2)
    bits = vals[..., 0] < vals[..., 1]  # (K, B)
    desc = _pack_bits(bits)
    return jnp.where(kps.valid[:, None], desc, jnp.zeros_like(desc))
