"""Binary keypoint descriptors: oriented BRIEF (ORB-style), TPU-native.

Counterpart of the reference `KeypointExtractor`'s describe stage
(SURVEY.md §2; BASELINE.json names ORB keypoints for the affine config).
Rebuilt for TPU rather than translated — the design rule is ZERO
arbitrary pointwise gathers (XLA scalarizes them on TPU; the naive
sample-256-points-per-keypoint formulation is ~1M scalar gathers per
frame and dominated the whole pipeline):

* One P x P patch is cut around each keypoint with a vmapped
  `lax.dynamic_slice` — batched slice-gather is a fast native path on
  TPU (whole minor-dim rows move at once).
* The four bilinear taps for the keypoint's subpixel fraction are
  applied to the WHOLE patch as one fused elementwise blend (`pb`),
  after which every integer-offset sample is just an element of `pb`.
* The BRIEF pattern offsets are integers (ops/patterns.py), so reading
  the 512 sample values per keypoint is a CONSTANT one-hot selection:
  a (P-1)^2 x 512 0/1 matmul on the MXU — exact, no gathers.
* Orientation (the ORB intensity-centroid angle) is quantized into
  N_ORIENT_BINS bins with a precomputed rotated integer pattern per bin
  (exactly ORB's own precomputed-rotation trick); each bin is one more
  constant one-hot matmul, masked-accumulated per keypoint. The angle
  itself comes from moments of the already-extracted patch — pure
  elementwise math.

Everything is fixed-K and mask-aware: invalid keypoint slots produce
all-zero descriptors which the matcher masks out.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.detect import Keypoints, gaussian_blur
from kcmc_tpu.ops.patterns import (  # shared, JAX-free constants
    MOMENT_RADIUS as _MOMENT_RADIUS,
    MOMENTS as _MOMENTS,
    N_BITS,
    N_ORIENT_BINS,
    N_WORDS,
    PATCH_RADIUS,
    PATTERN,
    ROT_PATTERNS,
    ROT_RADIUS,
)


def _selection_matrix(pattern: np.ndarray, radius: int) -> np.ndarray:
    """(L, 512) 0/1 one-hot matrix reading integer-offset samples out of a
    flattened (2*radius+1)^2 blended patch. Host-side constant."""
    side = 2 * radius + 1
    offs = pattern.reshape(-1, 2).astype(np.int64)  # (512, 2) integer (dx, dy)
    lin = (offs[:, 1] + radius) * side + (offs[:, 0] + radius)
    sel = np.zeros((side * side, offs.shape[0]), np.float32)
    sel[lin, np.arange(offs.shape[0])] = 1.0
    return sel


_SEL_UPRIGHT = _selection_matrix(PATTERN, PATCH_RADIUS)  # (27^2, 512)
_SEL_ROT = np.stack(
    [_selection_matrix(ROT_PATTERNS[b], ROT_RADIUS) for b in range(N_ORIENT_BINS)]
)  # (NB, 31^2, 512)

# ORB moment correlation kernels (2, 1, 2mr+1, 2mr+1): disc-masked dx
# and dy coordinate weights — the frame-level counterpart of _MOMENTS
# for the bins-first describe path (round 5). Integer values <= 7, so
# they are exact in bf16 and each conv product is exact under f32
# accumulation.
_MOMENT_KERNELS = np.stack(
    [
        (_MOMENTS[..., 0] * _MOMENTS[..., 2]).astype(np.float32),
        (_MOMENTS[..., 1] * _MOMENTS[..., 2]).astype(np.float32),
    ]
)[:, None]

_RUN_ALIGN = 16  # orientation-run alignment: the bf16 sublane tile,
# and the block size of binned_select_rows' one-bin-per-block
# contract. Must stay a MULTIPLE of the extraction kernel's keypoint
# block (pallas_patch._KB, re-swept to 8 in round 5) so extraction
# blocks never straddle a run boundary — it does NOT track _KB itself
# (lowering it to _KB would break the bf16 tile alignment this value
# encodes)

_BINS_FIRST_MIN_K = 2048  # bins-first pays a B*H*W-scaled moment-map
# cost to delete B*K-scaled dispatch traffic; crossover ~K=1250 at
# 512² (DESIGN.md "Bins-first oriented descriptors") — gate with
# margin so small-K configs keep the extract-then-dispatch route


def _extract_patches(
    smooth: jnp.ndarray, xy: jnp.ndarray, radius: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-keypoint patches around each (subpixel) keypoint.

    Returns (raw, blended) in KEYPOINT-LAST layout: raw is the
    (2r+2, 2r+2, K) integer-grid patch stack with origin floor(xy) - r;
    blended is the (2r+1, 2r+1, K) bilinear resample at each keypoint's
    subpixel fraction, i.e. blended[i, j, k] = smooth sampled at
    xy[k] + (j - r, i - r), edge-clamped.

    Keypoint-last matters on TPU: K (512) fills whole 128-lane tiles, so
    the blend's shifted views move only sublanes, whereas a (K, P, P)
    layout leaves P=2r+1 (~27) of 128 lanes occupied and forces a
    relayout per shifted view (~10x slower end to end, measured).
    """
    r = radius
    P = 2 * r + 2  # +1 row/col for the bilinear blend
    padded = jnp.pad(smooth, r + 1, mode="edge")
    # patch origin in padded coords: floor(kp) - r + (r + 1) = floor(kp) + 1
    oy = jnp.floor(xy[:, 1]).astype(jnp.int32) + 1
    ox = jnp.floor(xy[:, 0]).astype(jnp.int32) + 1
    raw = jax.vmap(
        lambda y, x: lax.dynamic_slice(padded, (y, x), (P, P))
    )(oy, ox)  # (K, P, P)
    raw = jnp.transpose(raw, (1, 2, 0))  # (P, P, K): one relayout
    return raw, _bilinear_blend(raw, xy)


def _bilinear_blend(raw: jnp.ndarray, xy: jnp.ndarray) -> jnp.ndarray:
    """(P, P, K) keypoint-last raw patches -> (P-1, P-1, K) bilinear
    resample at each keypoint's subpixel fraction.

    Separable grouping (y-lerp then x-lerp), matching the Pallas
    extraction kernels' static-roll blend exactly — the grouping is
    part of the bit-parity contract between this oracle and the
    kernel paths (same multiplies and adds per element, so f32
    results are identical, not merely close)."""
    fx = (xy[:, 0] - jnp.floor(xy[:, 0]))[None, None, :]
    fy = (xy[:, 1] - jnp.floor(xy[:, 1]))[None, None, :]
    yb = (1.0 - fy) * raw[:-1] + fy * raw[1:]
    return (1.0 - fx) * yb[:, :-1] + fx * yb[:, 1:]


def _moment_angles(patches: jnp.ndarray, xy: jnp.ndarray, radius: int) -> jnp.ndarray:
    """ORB intensity-centroid angle per keypoint, from the extracted patch.

    The moment disc (radius MOMENT_RADIUS) is centered on round(xy) —
    patch index radius + round(frac) — so it matches the integer-centered
    definition of the CPU oracle. patches: (P, P, K) RAW samples in
    keypoint-last layout (the blended patch would shift the centroid by
    the subpixel fraction).
    """
    r = _MOMENT_RADIUS
    c = radius  # patch center index for offset 0

    def disc(dy, dx):
        return patches[c + dy - r : c + dy + r + 1, c + dx - r : c + dx + r + 1]

    fx = xy[:, 0] - jnp.floor(xy[:, 0])
    fy = xy[:, 1] - jnp.floor(xy[:, 1])
    rx = (fx >= 0.5)[None, None, :]
    ry = (fy >= 0.5)[None, None, :]
    patch = jnp.where(
        ry,
        jnp.where(rx, disc(1, 1), disc(1, 0)),
        jnp.where(rx, disc(0, 1), disc(0, 0)),
    )  # (2r+1, 2r+1, K)
    moms = jnp.asarray(_MOMENTS)
    w = patch * moms[..., 2][..., None]
    m10 = jnp.sum(w * moms[..., 0][..., None], axis=(0, 1))
    m01 = jnp.sum(w * moms[..., 1][..., None], axis=(0, 1))
    return jnp.arctan2(m01, m10)


_PACK_HALVES = np.zeros((N_BITS, N_WORDS * 2), np.float32)
for _i in range(N_BITS):
    _PACK_HALVES[_i, _i // 16] = float(1 << (_i % 16))


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., N_BITS) bool -> (..., N_WORDS) uint32.

    Exact MXU formulation (round 5): one constant (N_BITS, 2*N_WORDS)
    matmul producing 16-bit half-words — 0/1 bf16 bits times power-of-two
    bf16 weights under f32 accumulation is exact (each half-word
    <= 65535 < 2^24), and the uint32 combine is integer arithmetic. The
    shift-and-sum form it replaces materialized a (..., N_WORDS, 32)
    uint32 intermediate (201 MB at config-2 scale) and measured 3.0
    ms/batch; the matmul reads the bits once.
    """
    halves = jnp.matmul(
        bits.astype(jnp.bfloat16),
        jnp.asarray(_PACK_HALVES, jnp.bfloat16),
        preferred_element_type=jnp.float32,
    ).astype(jnp.uint32)
    halves = halves.reshape(bits.shape[:-1] + (N_WORDS, 2))
    return halves[..., 0] | (halves[..., 1] << 16)


def _quantize_bins(angles: jnp.ndarray) -> jnp.ndarray:
    """Orientation angles -> N_ORIENT_BINS bin indices (shared by the
    keypoint-last jnp path and the keypoint-first Pallas path — the
    rounding convention must stay identical between them)."""
    nb = N_ORIENT_BINS
    return jnp.mod(
        jnp.rint(angles * (nb / (2.0 * jnp.pi))).astype(jnp.int32), nb
    )


def _finalize_descriptors(vals: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """(..., N_BITS*2) selected sample values -> (..., N_WORDS) packed
    descriptors; invalid keypoint slots zeroed. Shared tie-break rule:
    bit = first endpoint strictly less than the second."""
    vals = vals.reshape(vals.shape[:-1] + (N_BITS, 2))
    bits = vals[..., 0] < vals[..., 1]
    desc = _pack_bits(bits)
    return jnp.where(valid[..., None], desc, jnp.zeros_like(desc))


def _describe_from_patches(raw, pb, kps, oriented: bool):
    """Descriptor bits from extracted patches.

    raw/pb: (P, P, K) keypoint-last raw and blended patches (see
    _extract_patches); returns (K, N_WORDS) uint32 descriptors.
    """
    K = kps.xy.shape[0]

    # Precision.HIGHEST: the default TPU matmul truncates inputs to bf16,
    # which would quantize the selected sample values and flip comparison
    # bits relative to the f32 CPU oracle — the selection must stay exact.
    dot = functools.partial(jnp.matmul, precision=lax.Precision.HIGHEST)

    if oriented:
        angles = _moment_angles(raw, kps.xy, ROT_RADIUS)
        bins = _quantize_bins(angles)
        flat = pb.reshape(-1, K)  # (L, K), keypoint-last
        # One constant 0/1 matmul per orientation bin, masked-accumulated:
        # MXU work, small (K, 512) accumulator, no (K, NB, 512) blow-up.
        vals = jnp.zeros((K, PATTERN.shape[0] * 2), jnp.float32)
        for b in range(N_ORIENT_BINS):
            sel = jnp.asarray(_SEL_ROT[b])  # (L, 512)
            mask = (bins == b).astype(jnp.float32)[:, None]
            vals = vals + mask * dot(flat.T, sel)
    else:
        vals = dot(pb.reshape(-1, K).T, jnp.asarray(_SEL_UPRIGHT))  # (K, 512)

    # Descriptor values are bf16-quantized framework-wide (round 5 —
    # see describe_keypoints_batch): for a one-hot selection,
    # quantizing the selected values equals selecting quantized values
    # exactly, so this path stays the bit oracle of the batched route.
    vals = vals.astype(jnp.bfloat16)
    return _finalize_descriptors(vals, kps.valid)


@functools.partial(
    jax.jit, static_argnames=("oriented", "blur_sigma", "precision")
)
def describe_keypoints(
    img: jnp.ndarray,
    kps: Keypoints,
    oriented: bool = True,
    blur_sigma: float = 2.0,
    smooth: jnp.ndarray | None = None,
    precision: str = "bf16",
) -> jnp.ndarray:
    """Compute (K, N_WORDS) uint32 BRIEF descriptors for one frame.

    `oriented=True` steers the pattern by the quantized intensity-
    centroid angle (rotation-invariant, ORB-style); `False` is classic
    upright BRIEF — slightly more discriminative when the motion model
    has no rotation (the translation-only config). `smooth` optionally
    supplies the blur_sigma-blurred frame so the blur isn't recomputed.
    `precision="float32"` (the `match_precision` reference route) skips
    the bf16 pixel quantization below — the conservative full-precision
    variant the parity gate compares the quantized routes against.
    """
    if smooth is None:
        smooth = gaussian_blur(img, blur_sigma)
    # pixels at descriptor precision (round 5, see
    # describe_keypoints_batch — incl. the per-frame mean removal that
    # keeps large DC backgrounds out of the bf16 quantization step):
    # values identical to the Pallas path's bf16 slab reads,
    # arithmetic in f32 on them identical too
    finite = jnp.isfinite(smooth)
    mu = jnp.sum(jnp.where(finite, smooth, 0.0)) / jnp.maximum(
        jnp.sum(finite), 1
    )
    smooth = smooth - mu
    if precision != "float32":
        smooth = smooth.astype(jnp.bfloat16).astype(jnp.float32)
    r = ROT_RADIUS if oriented else PATCH_RADIUS
    raw, pb = _extract_patches(smooth, kps.xy, r)
    return _describe_from_patches(raw, pb, kps, oriented)


@functools.partial(
    jax.jit,
    static_argnames=(
        "oriented", "blur_sigma", "use_pallas", "interpret", "precision",
        "bands",
    ),
)
def describe_keypoints_batch(
    frames: jnp.ndarray,
    kps: Keypoints,
    oriented: bool = True,
    blur_sigma: float = 2.0,
    use_pallas: bool = False,
    interpret: bool = False,
    smooth: jnp.ndarray | None = None,
    precision: str = "bf16",
    bands: int | None = None,
) -> jnp.ndarray:
    """(B, K, N_WORDS) descriptors for a (B, H, W) batch of frames.

    With `use_pallas` the per-keypoint patch cut runs through the Pallas
    extraction kernel (ops/pallas_patch.py) — XLA lowers the batched
    data-dependent dynamic_slice to a ~1 GB/s gather, which made
    extraction the single largest cost of the whole pipeline; the kernel
    does it at memory speed. kps fields carry a leading batch axis.

    `smooth` optionally supplies the blur_sigma-blurred batch (e.g. the
    fused detection kernel's free-ride output) so the blur isn't
    recomputed here.

    `precision` ("bf16"/"int8" vs "float32", from the `match_precision`
    config field): the quantized routes are today's bf16 pixel/value
    pipeline; "float32" skips the quantization on the XLA path — the
    conservative reference route the parity gate compares against (the
    Pallas extraction slabs are bf16 by construction, so "float32"
    also routes extraction through the XLA gather path).

    `bands` overrides the row-band count of the large-frame banded
    extraction layout (autotuned via the PR-13 tile search; None = the
    smallest VMEM-fitting count, pallas_patch.band_count).
    """
    r = ROT_RADIUS if oriented else PATCH_RADIUS
    P = 2 * r + 2
    quantize = precision != "float32"
    if use_pallas and quantize:
        # Frames past the resident-frame kernel's VMEM budget (≈2048²)
        # run the ROW-BANDED resident layout (round 5 — keypoints
        # dispatched to VMEM-sized row bands; pallas_patch.band_count);
        # only frames beyond even the banded budget take the XLA gather
        # path (the Element-indexed slab variant measured 17x slower
        # there, DESIGN.md "Large-frame patch extraction").
        from kcmc_tpu.ops.pallas_patch import band_count

        # extraction runs on bf16 slabs (itemsize 2) since round 5
        use_pallas = band_count(frames.shape[1:], P, itemsize=2) >= 1
    else:
        use_pallas = False
    if not use_pallas:
        def one(f, k, s=None):
            return describe_keypoints(
                f, k, oriented=oriented, blur_sigma=blur_sigma, smooth=s,
                precision=precision,
            )

        if smooth is None:
            return jax.vmap(one)(frames, kps)
        return jax.vmap(one)(frames, kps, smooth)

    from kcmc_tpu.ops.pallas_patch import extract_blended
    if smooth is None:
        smooth = jax.vmap(lambda f: gaussian_blur(f, blur_sigma))(frames)
    # Pixels quantize to bf16 BEFORE extraction (round 5): the slab
    # reads are the extraction kernel's dominant VMEM traffic and bf16
    # halves them; every path (this one, the jnp fallback below, the
    # single-frame jnp oracle, the numpy mirror) quantizes at the same
    # point, so comparison ties keep falling the same way. The
    # per-frame mean comes OFF first: microscopy backgrounds sit at
    # large DC offsets where bf16's relative step (2^-8) exceeds the
    # content amplitude — a +500 background quantizes in steps of 2 px
    # intensity and wipes the blobs (measured: registration collapse).
    # Descriptor bits are order comparisons and the ORB moment maps'
    # coordinate weights sum to zero over the disc, so subtracting a
    # per-frame constant changes neither — it only restores dynamic
    # range to the quantization.
    # FINITE-pixel mean: a single inf/NaN sensor pixel must degrade
    # descriptors locally (the pre-round-5 behavior), not poison the
    # whole frame through the mean
    finite = jnp.isfinite(smooth)
    n_fin = jnp.maximum(jnp.sum(finite, axis=(1, 2), keepdims=True), 1)
    mu = (
        jnp.sum(jnp.where(finite, smooth, 0.0), axis=(1, 2), keepdims=True)
        / n_fin
    )
    padded = jnp.pad(
        (smooth - mu).astype(jnp.bfloat16),
        ((0, 0), (r + 1, r + 1), (r + 1, r + 1)), mode="edge",
    )
    B, K = kps.xy.shape[:2]

    # Descriptor VALUES are quantized to bf16 between extraction and
    # selection (round 5): the bin dispatch's row gather + scatter and
    # the selection matmuls are the describe stage's dominant HBM
    # traffic at config-2 scale (measured 56 ms/batch at K=4096, B=32),
    # and descriptor bits only consume the values through ORDER
    # comparisons — pairs of blurred intensities within bf16's 2^-8
    # relative step are sensor-noise ties whichever way they fall. The
    # jnp and numpy oracle paths quantize at the same point (selection
    # of a one-hot commutes with quantization exactly), so cross-path
    # bit parity is preserved up to the blend-rounding ties it already
    # had.
    if oriented and K >= _BINS_FIRST_MIN_K:
        # Bins-first (round 5): orientation from frame-level moment
        # correlations, keypoints sorted into aligned orientation runs,
        # extraction + selection with no (B, K, L) gather or value
        # scatter — see _describe_oriented_sorted. Replaces the
        # extract-then-dispatch route (in-kernel moments cost 9
        # ms/batch on top of 22 extraction; _binned_select another 25;
        # the sorted route's overhead is ~6 ms of convs, tiny gathers,
        # one sort, one DMA block-permutation and a packed scatter).
        # K-GATED: the moment maps cost scales with B*H*W while the
        # dispatch route's extras scale with B*K, so below ~K=1250 at
        # 512² the maps LOSE (measured: the K=512 similarity row
        # regressed 2180 -> 1916 fps when bins-first ran ungated).
        m10, m01 = _moments_at_keypoints(
            padded, kps.xy, r, interpret=interpret
        )
        bins = _quantize_bins(jnp.arctan2(m01, m10))
        return _describe_oriented_sorted(
            padded, kps, bins, P, interpret=interpret, bands=bands
        )
    if oriented:
        # small-K oriented route: in-kernel moments ride the extraction
        # slab for free at these K, and the dispatch gather/scatter is
        # proportionally small
        pb, m10, m01 = extract_blended(
            padded, kps.xy, P, with_moments=True, interpret=interpret,
            out_dtype=jnp.bfloat16, bands=bands,
        )
        bins = _quantize_bins(jnp.arctan2(m01[..., 0], m10[..., 0]))
        flat = pb.reshape(B, K, -1)
        vals = jax.vmap(_binned_select)(flat, bins, kps.valid)
    else:
        pb = extract_blended(
            padded, kps.xy, P, interpret=interpret, out_dtype=jnp.bfloat16,
            bands=bands,
        )
        flat = pb.reshape(B, K, -1)
        vals = _onehot_select(flat, jnp.asarray(_SEL_UPRIGHT))

    return _finalize_descriptors(vals, kps.valid)


def _moments_at_keypoints(
    padded: jnp.ndarray, xy: jnp.ndarray, r: int,
    use_pallas: bool = True, interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(B, K) ORB disc moments (m10, m01) at round-half-up(xy), from the
    (quantized, mean-removed) padded batch — WITHOUT patch extraction.

    Two frame-level moment maps (pallas_patch.moment_maps, with a conv
    fallback) + two tiny pointwise gathers per frame (the detect
    stage's subpixel-field pattern).
    This is what breaks round 4's "bin-sorted extraction is circular"
    dead end (DESIGN.md "Oriented descriptors"): orientation bins now
    exist BEFORE extraction, so extraction can run in bin-run order.
    Values match the in-patch moments up to f32 summation order (the
    disc weights are small integers, exact in bf16; order differences
    flip an orientation bin only for angles within ~1e-6 of a bin
    boundary — sensor-noise territory).
    """
    from kcmc_tpu.ops.pallas_patch import moment_maps, moment_maps_supported

    B = padded.shape[0]
    mr = _MOMENT_RADIUS
    if moment_maps_supported(padded.shape[1:]) and use_pallas:
        m10m, m01m = moment_maps(padded, interpret=interpret)
    else:
        # conv fallback (off-accelerator / frames beyond the kernel's
        # VMEM gate). NOTE: XLA lowers this 1-in/2-out-channel conv at
        # ~27 ms for a 32x512² batch on v5e — on-chip callers want the
        # kernel route.
        kern = jnp.asarray(_MOMENT_KERNELS, padded.dtype)
        maps = lax.conv_general_dilated(
            padded[:, None], kern, (1, 1), "VALID",
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.float32,
        )
        m10m, m01m = maps[:, 0], maps[:, 1]
    # map[i, j] is the disc sum centered at padded[i + mr, j + mr] =
    # frame pixel (i + mr - (r + 1), ...)
    Hm, Wm = m10m.shape[-2:]
    fx = xy[..., 0] - jnp.floor(xy[..., 0])
    fy = xy[..., 1] - jnp.floor(xy[..., 1])
    # round-half-up: the _moment_angles disc-center convention
    cx = jnp.floor(xy[..., 0]).astype(jnp.int32) + (fx >= 0.5)
    cy = jnp.floor(xy[..., 1]).astype(jnp.int32) + (fy >= 0.5)
    iy = jnp.clip(cy + (r + 1 - mr), 0, Hm - 1)
    ix = jnp.clip(cx + (r + 1 - mr), 0, Wm - 1)
    flat_idx = iy * Wm + ix  # (B, K)
    m10 = jax.vmap(lambda m, f: m.reshape(-1)[f])(m10m, flat_idx)
    m01 = jax.vmap(lambda m, f: m.reshape(-1)[f])(m01m, flat_idx)
    return m10, m01


def _aligned_runs(keys: jnp.ndarray, n_groups: int, align: int):
    """Stable sort of (N,) integer keys into align-aligned contiguous
    runs, one per group; keys >= n_groups are dropped (sentinel).

    Returns (src, astarts, aends): src (Kp,) int32 — source item index
    per sorted slot, N for padding slots — where Kp is the static bound
    ceil_align(N) + align * n_groups; astarts/aends (n_groups,) int32 —
    each group's aligned run [astarts[g], aends[g]) (aends - astarts =
    ceil_align(count)). Stability keeps detection-score order within a
    run (and makes the layout deterministic for the parity oracles).
    """
    from kcmc_tpu.ops.dispatch import stable_argsort_small_keys

    N = keys.shape[0]
    Kp = -(-N // align) * align + align * n_groups
    order, sk = stable_argsort_small_keys(keys, n_groups)
    ids = jnp.arange(n_groups, dtype=sk.dtype)
    starts = jnp.searchsorted(sk, ids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(sk, ids, side="right").astype(jnp.int32)
    padded_counts = -(-(ends - starts) // align) * align
    astarts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded_counts)[:-1]]
    )
    aends = astarts + padded_counts
    pos = jnp.arange(N, dtype=jnp.int32)
    skc = jnp.clip(sk, 0, n_groups - 1)
    dest = jnp.where(
        sk < n_groups, astarts[skc] + pos - starts[skc], Kp
    )
    src = (
        jnp.full((Kp + 1,), N, jnp.int32)
        .at[dest].set(order.astype(jnp.int32))[:Kp]
    )
    return src, astarts, aends


def _describe_oriented_sorted(
    padded: jnp.ndarray,
    kps: Keypoints,
    bins: jnp.ndarray,
    P: int,
    interpret: bool = False,
    bands: int | None = None,
) -> jnp.ndarray:
    """Bins-first oriented descriptors (round 5): extraction in
    orientation-run order, selection as per-block dynamic matmuls in
    the SAME sorted layout.

    The post-hoc bin dispatch (_binned_select) pays a (B, K, L) row
    gather into the capacity layout and a (B, K, 512) value scatter
    back — 25 ms/batch at K=4096, B=32, on par with extraction itself.
    With bins known BEFORE extraction (_moments_at_keypoints), the
    keypoint arrays are permuted ONCE (K-row copies of 2-4 values),
    extraction emits patch rows already grouped into aligned
    orientation runs, and selection is pallas_patch.binned_select_rows
    — each align-row block multiplied by its run's matrix, no capacity
    layout, no drops (the first sorted-route revision routed blocks
    through a (B, nb, cap, L) dispatch copy + batched einsum; the
    in-layout matmul replaces both at ~1/3 the cost and retires the
    capacity-overflow contract entirely). Descriptors are finalized and
    PACKED in the sorted layout, so the scatter back to original
    keypoint order moves N_WORDS uint32 per keypoint — 60x fewer bytes
    than the value scatter the round-4 path used.
    """
    B, K = kps.xy.shape[:2]
    nb = N_ORIENT_BINS
    align = _RUN_ALIGN
    # invalid keypoints get a REAL run (group nb) instead of being
    # dropped from the sort: with every keypoint present exactly once,
    # the sorted->original back-map is a permutation invertible by one
    # more packed sort + row GATHER (0.8 ms measured) instead of the
    # word scatter it replaces (4.1 ms — TPU scatters are pathological,
    # gathers are not). The extra run costs nothing: extraction's shape
    # is static in Kp either way, and group nb's selection clamps to a
    # real matrix whose garbage output the final valid mask zeroes.
    keys = jnp.where(kps.valid, bins, nb)
    src, _astarts, aends = jax.vmap(
        lambda k: _aligned_runs(k, nb + 1, align)
    )(keys)  # only src (slot -> keypoint) and aends (block bins) drive
    Kp = src.shape[1]

    safe = jnp.minimum(src, K - 1)
    xy_s = jnp.where(
        (src < K)[..., None],
        jnp.take_along_axis(kps.xy, safe[..., None], axis=1),
        0.0,
    )  # (B, Kp, 2)

    from kcmc_tpu.ops.pallas_patch import binned_select_rows, extract_blended

    pb = extract_blended(
        padded, xy_s, P, interpret=interpret, out_dtype=jnp.bfloat16,
        bands=bands,
    )
    flat = pb.reshape(B, Kp, -1)  # (B, Kp, L) bf16, orientation-run order

    # block routing: align-row block i starts at sorted slot align*i;
    # its bin is the run covering that slot (the invalid run nb and
    # alignment-padding tail blocks clamp to a real matrix inside
    # binned_select_rows; their rows are masked below)
    s_blk = jnp.arange(Kp // align, dtype=jnp.int32)[None, :] * align
    ibin = jax.vmap(
        lambda ae, s: jnp.searchsorted(ae, s, side="right").astype(jnp.int32)
    )(aends, jnp.broadcast_to(s_blk, (B, Kp // align)))

    sel = jnp.asarray(_SEL_ROT).astype(jnp.bfloat16)
    vals = binned_select_rows(
        flat, ibin, sel, align, interpret=interpret
    )  # (B, Kp, 512) bf16, sorted layout

    # finalize + pack IN the sorted layout, then map the words back to
    # original keypoint order (_backmap_words: inverse-permutation
    # gather for common K, word scatter beyond the 32-bit pack).
    vals = vals.reshape(B, Kp, N_BITS, 2)
    words = _pack_bits(vals[..., 0] < vals[..., 1])  # (B, Kp, W)
    desc = _backmap_words(words, src, K)
    return jnp.where(kps.valid[..., None], desc, 0)


def _backmap_words(
    words: jnp.ndarray, src: jnp.ndarray, K: int,
    force_scatter: bool = False,
) -> jnp.ndarray:
    """Map packed descriptor words from the sorted slot layout back to
    original keypoint order: words (B, Kp, W), src (B, Kp) — source
    keypoint index per slot, >= K for padding slots — -> (B, K, W).

    Fast path: every keypoint occupies exactly one slot, so sorting
    (src << sh) | slot puts keypoint k's slot at position k (padding
    sentinels sort to the tail) — the inverse permutation for the
    price of one more packed sort + row GATHER (0.8 ms measured at
    K=4096 vs the scatter's 4.1 — TPU scatters are pathological).
    uint32 pack: the padding sentinel src=K packs to K << sh, which
    overflows int32 from K=32768 (sh=16) and would sort the padding
    slots FIRST — silent descriptor corruption. uint32 holds it
    through K=32768; beyond that no lossless 32-bit pack exists, so
    the back-map falls back to the drop-mode word SCATTER (each real
    slot writes its keypoint's words once; padding slots index out of
    bounds and drop) — slower, but correct at any K, and only ever
    taken at scales where extraction itself dominates.
    `force_scatter` exists for the equivalence tests."""
    B, Kp = words.shape[:2]
    sh = max(1, int(Kp - 1).bit_length())
    if not force_scatter and K * (1 << sh) + Kp < 1 << 32:
        packed = (src.astype(jnp.uint32) << sh) | jnp.arange(
            Kp, dtype=jnp.uint32
        )
        inv = (jnp.sort(packed)[:, :K] & ((1 << sh) - 1)).astype(jnp.int32)
        return jnp.take_along_axis(words, inv[..., None], axis=1)
    return jax.vmap(
        lambda w, s: jnp.zeros((K, w.shape[-1]), w.dtype)
        .at[s].set(w, mode="drop")
    )(words, src)


def _binned_select(flat: jnp.ndarray, bins: jnp.ndarray, valid) -> jnp.ndarray:
    """Oriented one-hot selection, dispatched by bin: (K, L) patch
    values + (K,) orientation bins -> (K, 512) selected sample values.

    The earlier formulation ran ALL N_ORIENT_BINS constant matmuls over
    the full keypoint set and masked-accumulated — N_BINS x the matmul
    FLOPs and N_BINS (K, 512) intermediates of HBM traffic for work
    where each keypoint needs exactly ONE bin's matrix. Measured at
    K=4096, batch 32 on the v5e: 70 ms/batch, 66% of the whole config-2
    pipeline. This is the classic expert-dispatch shape: one stable
    argsort groups keypoints by bin, each bin's segment (fixed capacity
    2K/N_BINS + slack, rounded to 8) runs ONE (cap, L) x (L, 512)
    matmul against its own selection matrix, results scatter back to
    keypoint order — ~N_BINS/2 x less MXU work and HBM traffic, and
    every selected value goes through the same hi+lo two-pass as
    `_onehot_select`, so the result is bit-identical per element.

    Keypoints beyond a bin's capacity are dropped: their descriptor
    stays all-zero, which is the matchers' invalid sentinel (knn_match
    and banded_match reject zero descriptors outright, so a dropped
    keypoint can never inject a spurious low-popcount match). With
    capacity 2x the uniform share, drops need >2x orientation
    concentration; scenes that anisotropic lose a few of their weakest
    keypoints (stable argsort keeps detection-score order within a
    bin, so the strongest stay).
    """
    from kcmc_tpu.ops.dispatch import segment_by_key

    K, L = flat.shape
    nb = N_ORIENT_BINS
    cap = min(K, max(32, -(-2 * K // (nb * 8)) * 8))
    b_eff = jnp.where(valid, bins, nb)  # invalid slots: sentinel bin
    # stable segment-by-key: score order kept within bins, so overflow
    # drops each bin's weakest keypoints
    rows_idx, ok = segment_by_key(b_eff, nb, cap)
    rows = flat[rows_idx]  # (nb, cap, L)
    if flat.dtype == jnp.bfloat16:
        # round-5 bandwidth path: the rows are already quantized to the
        # descriptor value precision (see describe_keypoints_batch), so
        # selecting bf16 values with a bf16 one-hot matmul is EXACT
        # (0/1 weights, one nonzero per column, f32 accumulation) — one
        # pass, and the gather above plus the scatter below move half
        # the bytes of the f32 route.
        sel = jnp.asarray(_SEL_ROT).astype(jnp.bfloat16)
        out = jnp.matmul(
            rows, sel, preferred_element_type=jnp.float32
        ).astype(jnp.bfloat16)
        vals = jnp.zeros((K + 1, out.shape[-1]), jnp.bfloat16)
    else:
        # Same split-precision passes as _onehot_select, batched over
        # bins.
        hi = rows.astype(jnp.bfloat16).astype(jnp.float32)
        lo = rows - hi
        sel = jnp.asarray(_SEL_ROT)  # (nb, L, 512)
        out = jnp.matmul(hi, sel) + jnp.matmul(lo, sel)  # (nb, cap, 512)
        vals = jnp.zeros((K + 1, out.shape[-1]), jnp.float32)
    dest = jnp.where(ok, rows_idx, K).reshape(-1)
    vals = vals.at[dest].set(out.reshape(nb * cap, -1))
    return vals[:K]


def _onehot_select(flat: jnp.ndarray, sel: jnp.ndarray) -> jnp.ndarray:
    """(..., L) @ one-hot (L, N) in two bf16 passes, near-exact.

    The selection matrix is 0/1 with a single nonzero per column, so
    each output is one patch value: a default-precision (single bf16
    pass) matmul would quantize it to 8 mantissa bits, while HIGHEST
    (six passes, the earlier implementation) is MXU-bound — measured
    ~16 ms/batch, the whole cost of the oriented descriptor stage.
    Splitting the values into bf16 high + residual parts recovers ~16
    mantissa bits at two passes: no cross-term accumulates because
    every product has exactly one nonzero term. Comparisons of blurred
    intensities differing by < 2^-16 relative are noise anyway (and the
    CPU-parity oracle path is the jnp route, which is exact f32).
    """
    if flat.dtype == jnp.bfloat16:
        # values already at descriptor precision: one exact bf16 pass
        return jnp.matmul(
            flat, sel.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
    hi = (flat.astype(jnp.bfloat16)).astype(jnp.float32)
    lo = flat - hi
    out = jnp.matmul(hi, sel) + jnp.matmul(lo, sel)
    return out.astype(jnp.float32)
