"""Fixed-capacity segment-by-key: the shared dispatch primitive.

Both the banded matcher (keypoints -> spatial buckets) and the oriented
descriptor stage (keypoints -> orientation bins) need the same TPU-
shaped operation: group N items by an integer key into fixed-capacity
segments with static shapes, dropping overflow (masked, never resized).
One stable argsort + searchsorted does it with no scatters; keeping the
mechanism in one place means a tie-order or clamping fix reaches every
user.
"""

from __future__ import annotations

import jax.numpy as jnp


def segment_by_key(keys, n_groups: int, cap: int):
    """Group items by integer key with fixed per-group capacity.

    keys: (N,) int — group id per item; ids outside [0, n_groups) are
    dropped (use n_groups as the drop sentinel). Returns
    (slot_idx (n_groups, cap) int32 — item index per slot — and
    slot_ok (n_groups, cap) bool). The argsort is stable, so items
    keep their original relative order within a group and overflow
    drops the LAST items of each group (callers ordering items by
    priority keep the most important ones).
    """
    N = keys.shape[0]
    order = jnp.argsort(keys)  # stable
    sorted_keys = keys[order]
    bins = jnp.arange(n_groups, dtype=sorted_keys.dtype)
    starts = jnp.searchsorted(sorted_keys, bins, side="left")
    ends = jnp.searchsorted(sorted_keys, bins, side="right")
    slots = starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    slot_ok = slots < ends[:, None]
    slot_idx = order[jnp.minimum(slots, N - 1)].astype(jnp.int32)
    return slot_idx, slot_ok
