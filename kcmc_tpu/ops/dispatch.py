"""Fixed-capacity segment-by-key: the shared dispatch primitive.

Both the banded matcher (keypoints -> spatial buckets) and the oriented
descriptor stage (keypoints -> orientation bins) need the same TPU-
shaped operation: group N items by an integer key into fixed-capacity
segments with static shapes, dropping overflow (masked, never resized).
One stable argsort + searchsorted does it with no scatters; keeping the
mechanism in one place means a tie-order or clamping fix reaches every
user.
"""

from __future__ import annotations

import jax.numpy as jnp


def stable_argsort_small_keys(keys, max_key: int):
    """Stable argsort of small non-negative integer keys via ONE packed
    sort: (key << sh) | index sorts by key with ties broken by
    ascending index — exactly a stable argsort, at ~0 measured cost vs
    argsort's key-value sort (4.3 ms/batch at K=4096, B=32 on v5e).

    `max_key` is the largest key value possible (static), including any
    drop sentinel; the pack must fit int32, which this checks loudly at
    trace time instead of wrapping into silently corrupted order.
    RUNTIME key values are clamped to [0, max_key] before packing: a
    negative or too-large key (upstream arithmetic bug, corrupted
    input) would otherwise shift into the index bits — or past the
    int32 sign bit — and silently scramble the whole sort order, the
    worst possible failure mode for a primitive every dispatch-shaped
    stage shares. Clamped keys are still WRONG keys (negatives land in
    group 0, oversized ones in max_key); the clamp only guarantees the
    corruption stays local to the bad item.
    Returns (order, sorted_keys) like (argsort(keys), keys[order]).
    Shared by describe._aligned_runs, segment_by_key, and the describe
    back-map's inverse-permutation sort (which packs in uint32 for one
    extra bit — see _describe_oriented_sorted).
    """
    N = keys.shape[0]
    sh = max(1, int(N - 1).bit_length())
    if (max_key << sh) + N >= 1 << 31:
        raise ValueError(
            f"packed stable argsort: max_key={max_key} << {sh} | index "
            f"overflows int32 at N={N}; use a key-value argsort for "
            f"this scale"
        )
    keys = jnp.clip(keys.astype(jnp.int32), 0, max_key)
    packed = jnp.sort(
        (keys << sh) | jnp.arange(N, dtype=jnp.int32)
    )
    return packed & ((1 << sh) - 1), packed >> sh


def segment_by_key(keys, n_groups: int, cap: int):
    """Group items by integer key with fixed per-group capacity.

    keys: (N,) int — group id per item, REQUIRED non-negative and
    <= n_groups; ids outside [0, n_groups) are dropped (use n_groups as
    the drop sentinel — never a negative). Runtime values beyond that
    contract are clamped into it (stable_argsort_small_keys), so a
    corrupted key cannot scramble other items' grouping: a negative id
    joins group 0, an oversized one the drop sentinel. Returns
    (slot_idx (n_groups, cap) int32 — item index per slot — and
    slot_ok (n_groups, cap) bool). The argsort is stable, so items
    keep their original relative order within a group and overflow
    drops the LAST items of each group (callers ordering items by
    priority keep the most important ones).
    """
    N = keys.shape[0]
    order, sorted_keys = stable_argsort_small_keys(keys, n_groups)
    sorted_keys = sorted_keys.astype(keys.dtype)
    bins = jnp.arange(n_groups, dtype=sorted_keys.dtype)
    starts = jnp.searchsorted(sorted_keys, bins, side="left")
    ends = jnp.searchsorted(sorted_keys, bins, side="right")
    slots = starts[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    slot_ok = slots < ends[:, None]
    slot_idx = order[jnp.minimum(slots, N - 1)].astype(jnp.int32)
    return slot_idx, slot_ok
