"""TPU kernels: detection, description, matching, consensus, warping.

Every op in this package is statically shaped and jit/vmap-safe: fixed-K
keypoints with validity masks instead of variable-length lists, fixed
hypothesis counts instead of adaptive early exit — the design constraints
that let XLA compile the whole pipeline once and tile it onto the MXU
(SURVEY.md §7 "hard parts").
"""
