"""Frame warping: inverse-map resampling through transforms or flow fields.

Counterpart of the reference's `FrameWarper` (SURVEY.md §2). The output
frame is produced by *inverse* warping: for every output pixel, map its
coordinate through the transform (which maps reference coords -> frame
coords, so corrected(x) = frame(T(x))) and bilinearly sample the input
frame there. Out-of-bounds samples produce 0 (and a coverage mask is
available for downstream use).

This is the pure-jnp implementation: a handful of fused elementwise ops
plus 4 (2D) / 8 (3D) gathers — XLA fuses the lot.

The same machinery warps by a dense displacement *field* (piecewise-
rigid config): sample coords = identity + flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _grid(shape: tuple[int, int], dtype=jnp.float32) -> tuple[jnp.ndarray, jnp.ndarray]:
    H, W = shape
    ys = jnp.arange(H, dtype=dtype)[:, None]
    xs = jnp.arange(W, dtype=dtype)[None, :]
    return jnp.broadcast_to(xs, (H, W)), jnp.broadcast_to(ys, (H, W))


def bilinear_sample(img: jnp.ndarray, sx: jnp.ndarray, sy: jnp.ndarray) -> jnp.ndarray:
    """Sample (H, W) image at float coords; 0 outside, edge-clamped gathers."""
    H, W = img.shape
    x0 = jnp.floor(sx)
    y0 = jnp.floor(sy)
    fx = sx - x0
    fy = sy - y0
    x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    flat = img.reshape(-1)
    v00 = flat[y0i * W + x0i]
    v01 = flat[y0i * W + x1i]
    v10 = flat[y1i * W + x0i]
    v11 = flat[y1i * W + x1i]
    out = (
        v00 * (1 - fx) * (1 - fy)
        + v01 * fx * (1 - fy)
        + v10 * (1 - fx) * fy
        + v11 * fx * fy
    )
    inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
    return out * inb


def warp_frame(frame: jnp.ndarray, M: jnp.ndarray) -> jnp.ndarray:
    """Correct a (H, W) frame with transform M (maps ref coords -> frame
    coords): corrected(p) = frame(M p)."""
    H, W = frame.shape
    xs, ys = _grid((H, W))
    # Homogeneous map of the pixel grid; explicit scalar FMA keeps this a
    # pure VPU elementwise op (no tiny matmuls).
    w = M[2, 0] * xs + M[2, 1] * ys + M[2, 2]
    w = jnp.where(jnp.abs(w) < 1e-8, 1e-8, w)
    sx = (M[0, 0] * xs + M[0, 1] * ys + M[0, 2]) / w
    sy = (M[1, 0] * xs + M[1, 1] * ys + M[1, 2]) / w
    return bilinear_sample(frame, sx, sy)


def warp_batch(frames: jnp.ndarray, transforms: jnp.ndarray) -> jnp.ndarray:
    """(B, H, W) frames, (B, 3, 3) transforms -> corrected batch (vmapped
    gather warp — the generic batched counterpart of the gather-free
    kernels in ops/pallas_warp.py / ops/warp_separable.py)."""
    return jax.vmap(warp_frame)(frames, transforms)


def warp_batch_with_ok(frames: jnp.ndarray, transforms: jnp.ndarray):
    """warp_batch plus an all-True (B,) ok flag — the gather warp handles
    every transform, so it matches the gather-free kernels' with_ok API."""
    return warp_batch(frames, transforms), jnp.ones(frames.shape[0], bool)


def warp_frame_flow(frame: jnp.ndarray, flow: jnp.ndarray) -> jnp.ndarray:
    """Correct a (H, W) frame with a dense (H, W, 2) forward displacement
    field u (frame(x) = scene(x - u(x))): corrected(p) = frame(p + u(p))."""
    H, W = frame.shape
    xs, ys = _grid((H, W))
    return bilinear_sample(frame, xs + flow[..., 0], ys + flow[..., 1])


def coverage_mask(
    shape: tuple[int, int], M: jnp.ndarray, valid_hw: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Boolean mask of output pixels whose source sample is in-bounds.

    `valid_hw` (traced (2,) ints, optional) bounds the SOURCE check to
    the top-left (h, w) valid extent of a bucket-padded frame instead
    of the full canvas — the one definition of the perspective-divide
    source-bounds test the execution-plan masking (backends/
    jax_backend._mask_valid_extent) and the polish coverage gate
    (ops/polish.py) share with the plain coverage path."""
    H, W = shape
    xs, ys = _grid((H, W))
    w = M[2, 0] * xs + M[2, 1] * ys + M[2, 2]
    w = jnp.where(jnp.abs(w) < 1e-8, 1e-8, w)
    sx = (M[0, 0] * xs + M[0, 1] * ys + M[0, 2]) / w
    sy = (M[1, 0] * xs + M[1, 1] * ys + M[1, 2]) / w
    if valid_hw is None:
        wmax, hmax = float(W - 1), float(H - 1)
    else:
        wmax = (valid_hw[1] - 1).astype(jnp.float32)
        hmax = (valid_hw[0] - 1).astype(jnp.float32)
    return (sx >= 0) & (sx <= wmax) & (sy >= 0) & (sy <= hmax)


def valid_rect_mask(
    shape: tuple[int, int], valid_hw: jnp.ndarray
) -> jnp.ndarray:
    """(H, W) bool mask of the top-left (h, w) valid extent of a
    bucket-padded canvas (execution plans) — the one definition shared
    by the batch program's sanitize statistics and the polish coverage
    gate (detection's border-inset variant lives in
    ops/detect.valid_extent_mask)."""
    H, W = shape
    ys = jnp.arange(H, dtype=jnp.int32)[:, None]
    xs = jnp.arange(W, dtype=jnp.int32)[None, :]
    return (ys < valid_hw[0]) & (xs < valid_hw[1])


def coverage_mask_flow(flow: jnp.ndarray) -> jnp.ndarray:
    """Coverage of the dense-flow warp: pixels whose sample p + u(p) is
    in-bounds. flow is (H, W, 2)."""
    H, W = flow.shape[:2]
    xs, ys = _grid((H, W))
    sx = xs + flow[..., 0]
    sy = ys + flow[..., 1]
    return (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)


def coverage_mask_3d(shape: tuple[int, int, int], M: jnp.ndarray) -> jnp.ndarray:
    """Coverage of the volumetric warp: voxels whose source sample is
    in-bounds under the 4x4 transform (same map as warp_volume)."""
    D, H, W = shape
    zs = jnp.arange(D, dtype=jnp.float32)[:, None, None]
    ys = jnp.arange(H, dtype=jnp.float32)[None, :, None]
    xs = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2] * zs + M[0, 3]
    sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2] * zs + M[1, 3]
    sz = M[2, 0] * xs + M[2, 1] * ys + M[2, 2] * zs + M[2, 3]
    return (
        (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
        & (sz >= 0) & (sz <= D - 1)
    )


# --------------------------------------------------------------------------
# 3D (volumetric) warping — config 5.
# --------------------------------------------------------------------------


def trilinear_sample(vol: jnp.ndarray, sx: jnp.ndarray, sy: jnp.ndarray, sz: jnp.ndarray) -> jnp.ndarray:
    """Sample (D, H, W) volume at float (x, y, z) coords; 0 outside."""
    D, H, W = vol.shape
    x0 = jnp.floor(sx)
    y0 = jnp.floor(sy)
    z0 = jnp.floor(sz)
    fx, fy, fz = sx - x0, sy - y0, sz - z0
    x0i = jnp.clip(x0.astype(jnp.int32), 0, W - 1)
    y0i = jnp.clip(y0.astype(jnp.int32), 0, H - 1)
    z0i = jnp.clip(z0.astype(jnp.int32), 0, D - 1)
    x1i = jnp.clip(x0i + 1, 0, W - 1)
    y1i = jnp.clip(y0i + 1, 0, H - 1)
    z1i = jnp.clip(z0i + 1, 0, D - 1)
    flat = vol.reshape(-1)

    def gather(zi, yi, xi):
        return flat[(zi * H + yi) * W + xi]

    out = (
        gather(z0i, y0i, x0i) * (1 - fx) * (1 - fy) * (1 - fz)
        + gather(z0i, y0i, x1i) * fx * (1 - fy) * (1 - fz)
        + gather(z0i, y1i, x0i) * (1 - fx) * fy * (1 - fz)
        + gather(z0i, y1i, x1i) * fx * fy * (1 - fz)
        + gather(z1i, y0i, x0i) * (1 - fx) * (1 - fy) * fz
        + gather(z1i, y0i, x1i) * fx * (1 - fy) * fz
        + gather(z1i, y1i, x0i) * (1 - fx) * fy * fz
        + gather(z1i, y1i, x1i) * fx * fy * fz
    )
    inb = (
        (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1) & (sz >= 0) & (sz <= D - 1)
    )
    return out * inb


def warp_volume(vol: jnp.ndarray, M: jnp.ndarray) -> jnp.ndarray:
    """Correct a (D, H, W) volume with a 4x4 transform (ref -> frame coords,
    acting on (x, y, z) points)."""
    D, H, W = vol.shape
    zs = jnp.arange(D, dtype=jnp.float32)[:, None, None]
    ys = jnp.arange(H, dtype=jnp.float32)[None, :, None]
    xs = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2] * zs + M[0, 3]
    sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2] * zs + M[1, 3]
    sz = M[2, 0] * xs + M[2, 1] * ys + M[2, 2] * zs + M[2, 3]
    return trilinear_sample(vol, sx, sy, sz)


_FAST_APPLY_JITS: dict = {}


def _cached_jit(key, build):
    if key not in _FAST_APPLY_JITS:
        _FAST_APPLY_JITS[key] = build()
    return _FAST_APPLY_JITS[key]


def fast_apply_matrix(
    frames: jnp.ndarray, Ms: jnp.ndarray, force_kernel: bool = False,
    donate: bool = False,
):
    """Batched 2D matrix apply for the APPLY/STABILIZE workflows:
    gather-warp semantics at gather-free speed.

    On accelerators the batch runs through the bounded single-
    interpolation Pallas kernel — the same route the registration path
    warps with, ~10 ms/frame cheaper than the per-frame gather on TPU
    (the pyramid row's round-5 lesson) and within ~1e-4 px of it — and
    the rare frames whose transform exceeds the kernel's envelope
    (residual beyond its bound, center translation beyond ±PAD) fall
    back per frame to the exact unbounded gather, so EVERY transform
    still applies. Off-accelerator this is exactly `warp_batch`
    (bit-identical to the previous behavior; `force_kernel` exercises
    the kernel route in interpret mode for tests). Returns numpy.

    `donate=True` (the kcmc-check donation-audit contract): the caller
    RELINQUISHES `frames` — the gather route's jit donates the batch
    buffer to XLA so the resampled output reuses its allocation
    instead of a second batch-sized one. Only for callers that own the
    buffer (apply_correction's per-chunk upload temp); the Pallas
    kernel route keeps the batch readable for its per-frame fallback
    and never donates.
    """
    import numpy as np

    on_acc = jax.default_backend() in ("tpu", "axon")
    shape = tuple(frames.shape[1:])
    if on_acc or force_kernel:
        from kcmc_tpu.ops.pallas_warp_field import (
            supports_matrix,
            warp_batch_matrix_pallas,
        )

        if supports_matrix(shape, 16):
            out, ok = warp_batch_matrix_pallas(
                frames, Ms, max_px=16, with_ok=True,
                interpret=not on_acc,
            )
            okh = np.asarray(ok)
            res = np.asarray(out)
            if not okh.all():
                wf = _cached_jit(
                    "frame",
                    lambda: jax.jit(warp_frame, donate_argnums=()),
                )
                res = np.array(res)
                for i in np.where(~okh)[0]:
                    res[i] = np.asarray(wf(frames[i], Ms[i]))
            return res
    wb = _cached_jit(
        ("batch", donate),
        lambda: jax.jit(
            warp_batch, donate_argnums=(0,) if donate else ()
        ),
    )
    return np.asarray(wb(frames, Ms))


def fast_apply_fields(
    frames: jnp.ndarray, fields: jnp.ndarray, force_kernel: bool = False,
    donate: bool = False,
):
    """Batched piecewise-field apply, same policy as fast_apply_matrix:
    the fused field kernel (in-kernel upsample + bounded resample) on
    accelerators with exact per-frame gather fallback for flagged
    frames; pure gather off-accelerator. `donate=True`: the caller
    relinquishes `frames` on the gather route (see fast_apply_matrix).
    Returns numpy."""
    import numpy as np

    on_acc = jax.default_backend() in ("tpu", "axon")
    shape = tuple(frames.shape[1:])
    if on_acc or force_kernel:
        from kcmc_tpu.ops.pallas_warp_field import supports, warp_batch_field

        if supports(shape, 6):
            out, ok = warp_batch_field(
                frames, fields, max_px=6, with_ok=True,
                interpret=not on_acc,
            )
            okh = np.asarray(ok)
            res = np.asarray(out)
            if not okh.all():
                from kcmc_tpu.ops.piecewise import upsample_field

                ff = _cached_jit(
                    ("flow", shape),
                    lambda: jax.jit(
                        lambda f, fl: warp_frame_flow(
                            f, upsample_field(fl, shape)
                        ),
                        donate_argnums=(),
                    ),
                )
                res = np.array(res)
                for i in np.where(~okh)[0]:
                    res[i] = np.asarray(ff(frames[i], fields[i]))
            return res
    from kcmc_tpu.ops.piecewise import upsample_field

    fb = _cached_jit(
        ("flow_batch", shape, donate),
        lambda: jax.jit(
            jax.vmap(
                lambda f, fl: warp_frame_flow(f, upsample_field(fl, shape))
            ),
            donate_argnums=(0,) if donate else (),
        ),
    )
    return np.asarray(fb(frames, fields))
