"""Fused match→consensus dispatch (PR 13): one traced region per model
family for the whole registration tail.

Before this module, the batch program's tail ran per frame inside a
vmap — `knn_match` (its own jitted function) feeding `ransac_estimate`
(another) — so the trace carried a nested-pjit seam between the match
matrix and the consensus scoring, and the hypothesis work reached XLA
as B × H small per-frame launches. The PR-4 trace spans put the
launch/transfer seam between `match` and `consensus` among the top
fixed costs of the slow configs (affine@2k, rigid3d), where per-launch
overhead amortizes worst.

`fused_match_consensus` collapses the seam: the Hamming matrices, the
2-NN selection, and the budgeted consensus (`ops/ransac.consensus_batch`
— (frames × hypotheses) blocked solves/scores under the adaptive
budget ladder) trace as ONE region with no jit boundaries inside, so
XLA fuses across the former stage boundary and the MXU sees large
uniform blocks. The same entry serves the 2D and 3D matrix tails; the
piecewise field estimator keeps its own per-frame path
(ops/piecewise.estimate_field has no matrix consensus to fuse into).

Mixed precision rides here too: `precision` (the resolved
`match_precision` config field) selects the exact int8 / bf16 / f32
Hamming matmul variant (ops/match.hamming_matrix_mxu — identical
distance matrices, different MXU paths).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kcmc_tpu.models.transforms import TransformModel
from kcmc_tpu.ops.match import knn_match_impl
from kcmc_tpu.ops.ransac import RansacResult, consensus_batch


def fused_match_consensus(
    model: TransformModel,
    desc: jnp.ndarray,
    kp_xy: jnp.ndarray,
    kp_valid: jnp.ndarray,
    ref_desc: jnp.ndarray,
    ref_xy: jnp.ndarray,
    ref_valid: jnp.ndarray,
    keys: jnp.ndarray,
    ratio: float = 0.85,
    max_dist: int = 80,
    mutual: bool = True,
    precision: str = "bf16",
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
    score_cap: int = 0,
    budget_rungs: int = 0,
    early_exit_frac: float = 0.7,
    seed_transform: jnp.ndarray | None = None,
    seed_ok: jnp.ndarray | None = None,
    matches=None,
) -> tuple[RansacResult, jnp.ndarray]:
    """Match a batch's descriptors against the reference and estimate
    per-frame transforms, in one traced region.

    desc: (B, K, W) packed descriptors; kp_xy: (B, K, d); kp_valid:
    (B, K); ref_*: the prepared reference's (K_r, ...) arrays; keys:
    (B,) per-frame PRNG keys. Returns (RansacResult with a leading
    batch axis, n_matches (B,) int32).

    `matches` optionally supplies precomputed per-frame Matches (the
    banded matcher's output — its spatial bucketing happens upstream);
    then the descriptor arguments are unused and only the consensus
    fuses here.

    `seed_transform` / `seed_ok`: the temporal warm start (see
    consensus_batch) — a shared (d+1, d+1) seed scores as hypothesis
    zero on every frame.
    """
    if matches is None:
        matches = jax.vmap(
            lambda d, v: knn_match_impl(
                d, ref_desc, v, ref_valid,
                ratio=ratio, max_dist=max_dist, mutual=mutual,
                precision=precision,
            )
        )(desc, kp_valid)
    src = ref_xy[matches.idx]  # (B, K, d): reference keypoint -> frame
    dst = kp_xy
    res = consensus_batch(
        model,
        src,
        dst,
        matches.valid,
        keys,
        n_hypotheses=n_hypotheses,
        threshold=threshold,
        refine_iters=refine_iters,
        score_cap=score_cap,
        budget_rungs=budget_rungs,
        early_exit_frac=early_exit_frac,
        seed_transform=seed_transform,
        seed_ok=seed_ok,
    )
    n_matches = jnp.sum(matches.valid, axis=1).astype(jnp.int32)
    return res, n_matches
