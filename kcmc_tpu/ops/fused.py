"""Fused match→consensus dispatch (PR 13): one traced region per model
family for the whole registration tail.

Before this module, the batch program's tail ran per frame inside a
vmap — `knn_match` (its own jitted function) feeding `ransac_estimate`
(another) — so the trace carried a nested-pjit seam between the match
matrix and the consensus scoring, and the hypothesis work reached XLA
as B × H small per-frame launches. The PR-4 trace spans put the
launch/transfer seam between `match` and `consensus` among the top
fixed costs of the slow configs (affine@2k, rigid3d), where per-launch
overhead amortizes worst.

`fused_match_consensus` collapses the seam: the Hamming matrices, the
2-NN selection, and the budgeted consensus (`ops/ransac.consensus_batch`
— (frames × hypotheses) blocked solves/scores under the adaptive
budget ladder) trace as ONE region with no jit boundaries inside, so
XLA fuses across the former stage boundary and the MXU sees large
uniform blocks. The same entry serves the 2D and 3D matrix tails; the
piecewise field estimator keeps its own per-frame path
(ops/piecewise.estimate_field has no matrix consensus to fuse into).

Mixed precision rides here too: `precision` (the resolved
`match_precision` config field) selects the exact int8 / bf16 / f32
Hamming matmul variant (ops/match.hamming_matrix_mxu — identical
distance matrices, different MXU paths).

`fused_detect_describe` (PR 18) collapses the OTHER program seam the
trace spans flag: detection and description used to reach XLA as two
separately jitted programs with the selected `Keypoints` materialized
between them — per octave on the pyramid path, so an `n_octaves=3`
reference preparation dispatched six programs plus the pyramid resize
and the merge, each boundary a host round-trip on the selected
keypoint set. Here the pyramid build (MXU resize), every octave's
detect→describe pair (Pallas response/extraction kernels where the
frame size supports them and the autotuned tilings exist, the fused
XLA fallbacks otherwise), and the base-coordinate merge trace as ONE
region: the octave keypoint sets stay device-resident intermediates
XLA can schedule freely, and the backend routes the whole region
behind the plan machinery ("register" / "reference_pyramid"
programs), so warm boots replay one stamped executable instead of
re-dispatching the chain.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kcmc_tpu.models.transforms import TransformModel
from kcmc_tpu.ops.describe import describe_keypoints_batch
from kcmc_tpu.ops.detect import detect_keypoints_batch
from kcmc_tpu.ops.match import knn_match_impl
from kcmc_tpu.ops.ransac import RansacResult, consensus_batch


def fused_detect_describe(
    frames: jnp.ndarray,
    *,
    max_keypoints: int,
    detect_threshold: float,
    nms_size: int,
    border: int,
    harris_k: float,
    window_sigma: float,
    blur_sigma: float,
    cand_tile: int,
    oriented: bool,
    precision: str,
    use_pallas: bool,
    n_octaves: int = 1,
    octave_scale: float = 2.0,
    multi_scale: bool = True,
    valid_hw: jnp.ndarray | None = None,
    tiles: dict | None = None,
):
    """Detect + describe a (B, H, W) float32 batch as one traced region.

    Single-scale (`n_octaves <= 1` or `multi_scale=False`): one
    detect→describe pair, the blurred batch riding from the detection
    kernel into description (no recomputed blur). Multi-scale: the ORB
    pyramid — per-octave fixed-K detection/description on MXU-resized
    images merged into one keypoint set in base coordinates
    (ops/pyramid.py). Returns (Keypoints, desc), both with a leading
    batch axis.

    `tiles` carries the autotuned tile parameters stamped for the BASE
    frame shape (backend `_tile_params`); octaves at other shapes keep
    the per-kernel defaults — the dict is static by construction
    (resolved at program-build time, never inside the trace).
    `valid_hw` (traced (2,) ints) masks selection to the true extent
    of bucket-padded frames; bucket routing gates pyramid configs out,
    so it only applies single-scale.
    """
    tiles = tiles or {}

    def stage(fr, k_octave, b):
        t = tiles if tiles.get("shape") == tuple(fr.shape[1:]) else {}
        kps, smooth = detect_keypoints_batch(
            fr,
            max_keypoints=k_octave,
            threshold=detect_threshold,
            nms_size=nms_size,
            border=b,
            harris_k=harris_k,
            use_pallas=use_pallas,
            smooth_sigma=blur_sigma,
            window_sigma=window_sigma,
            cand_tile=cand_tile,
            valid_hw=valid_hw,
            strip=t.get("detect_strip"),
        )
        desc = describe_keypoints_batch(
            fr,
            kps,
            oriented=oriented,
            blur_sigma=blur_sigma,
            use_pallas=use_pallas,
            smooth=smooth,
            precision=precision,
            bands=t.get("patch_bands"),
        )
        return kps, desc

    if n_octaves <= 1 or not multi_scale:
        return stage(frames, max_keypoints, border)

    from kcmc_tpu.ops.pyramid import (
        build_pyramid,
        merge_octave_keypoints,
        per_octave_k,
    )

    octs = build_pyramid(frames, n_octaves, octave_scale)
    ks = per_octave_k(max_keypoints, n_octaves)
    per = []
    for oc, ko in zip(octs, ks):
        b = min(border, min(oc.frames.shape[1:]) // 4)
        per.append(stage(oc.frames, ko, b))
    return merge_octave_keypoints(per, octs)


def fused_match_consensus(
    model: TransformModel,
    desc: jnp.ndarray,
    kp_xy: jnp.ndarray,
    kp_valid: jnp.ndarray,
    ref_desc: jnp.ndarray,
    ref_xy: jnp.ndarray,
    ref_valid: jnp.ndarray,
    keys: jnp.ndarray,
    ratio: float = 0.85,
    max_dist: int = 80,
    mutual: bool = True,
    precision: str = "bf16",
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
    score_cap: int = 0,
    budget_rungs: int = 0,
    early_exit_frac: float = 0.7,
    seed_transform: jnp.ndarray | None = None,
    seed_ok: jnp.ndarray | None = None,
    matches=None,
) -> tuple[RansacResult, jnp.ndarray]:
    """Match a batch's descriptors against the reference and estimate
    per-frame transforms, in one traced region.

    desc: (B, K, W) packed descriptors; kp_xy: (B, K, d); kp_valid:
    (B, K); ref_*: the prepared reference's (K_r, ...) arrays; keys:
    (B,) per-frame PRNG keys. Returns (RansacResult with a leading
    batch axis, n_matches (B,) int32).

    `matches` optionally supplies precomputed per-frame Matches (the
    banded matcher's output — its spatial bucketing happens upstream);
    then the descriptor arguments are unused and only the consensus
    fuses here.

    `seed_transform` / `seed_ok`: the temporal warm start (see
    consensus_batch) — a shared (d+1, d+1) seed scores as hypothesis
    zero on every frame.
    """
    if matches is None:
        matches = jax.vmap(
            lambda d, v: knn_match_impl(
                d, ref_desc, v, ref_valid,
                ratio=ratio, max_dist=max_dist, mutual=mutual,
                precision=precision,
            )
        )(desc, kp_valid)
    src = ref_xy[matches.idx]  # (B, K, d): reference keypoint -> frame
    dst = kp_xy
    res = consensus_batch(
        model,
        src,
        dst,
        matches.valid,
        keys,
        n_hypotheses=n_hypotheses,
        threshold=threshold,
        refine_iters=refine_iters,
        score_cap=score_cap,
        budget_rungs=budget_rungs,
        early_exit_frac=early_exit_frac,
        seed_transform=seed_transform,
        seed_ok=seed_ok,
    )
    n_matches = jnp.sum(matches.valid, axis=1).astype(jnp.int32)
    return res, n_matches
