"""Descriptor sampling patterns — shared, JAX-free constants.

Both execution backends (XLA and pure NumPy) build descriptors from the
*same* host-side pattern constants, which is what makes cross-backend
descriptor parity exact. This module must stay importable without JAX so
the CPU parity backend works on hosts where JAX init is broken or slow.
"""

from __future__ import annotations

import numpy as np

N_BITS = 256
N_WORDS = N_BITS // 32
PATCH_RADIUS = 13  # BRIEF pattern support radius, pixels
MOMENT_RADIUS = 7  # intensity-centroid disc radius (ORB orientation)
N_ORIENT_BINS = 16  # orientation quantization (22.5 deg, ORB-style)
ROT_RADIUS = 15  # rotated-pattern support radius (rotated offsets clipped)
CAND_TILE = 8  # detector candidate-reduction tile side (one keypoint/tile);
# shared so both backends bucket candidates into the same grid
WINDOW_SIGMA = 1.5  # Harris structure-tensor window sigma — shared by the
# jnp/NumPy responses and the fused Pallas kernels (their supports() gate
# sizes VMEM slabs from it), so the paths cannot silently desync

# 3D descriptor support (anisotropic: z-stacks are shallow)
RADIUS_XY = 9.0
RADIUS_Z = 3.0


def make_pattern(seed: int = 7) -> np.ndarray:
    """The BRIEF pair pattern: (N_BITS, 2, 2) float32 (pair, endpoint, (x, y)).

    Gaussian-distributed offsets (sigma = radius/2), clipped to the patch,
    rounded to INTEGER pixel offsets (classic BRIEF/ORB uses integer pixel
    pairs; on TPU integer offsets make descriptor sampling a constant
    one-hot selection — pure MXU work, zero arbitrary gathers). Fixed seed
    => identical pattern across backends.
    """
    rng = np.random.default_rng(seed)
    pts = rng.normal(0.0, PATCH_RADIUS / 2.0, size=(N_BITS, 2, 2))
    return np.rint(np.clip(pts, -PATCH_RADIUS, PATCH_RADIUS)).astype(np.float32)


def make_rotated_patterns(n_bins: int = N_ORIENT_BINS) -> np.ndarray:
    """Per-orientation-bin rotated integer patterns: (n_bins, N_BITS, 2, 2).

    The ORB trick, TPU-shaped: instead of steering the pattern by a
    per-keypoint rotation matrix (which makes sample positions dynamic
    and forces pointwise gathers), quantize orientation into `n_bins`
    bins and precompute the rotated pattern per bin host-side, rounded
    back to integer offsets. Descriptor sampling then stays a constant
    selection for every bin; the keypoint only picks its bin.
    """
    base = make_pattern()  # (N_BITS, 2, 2) integer-valued
    out = np.empty((n_bins,) + base.shape, np.float32)
    for b in range(n_bins):
        th = 2.0 * np.pi * b / n_bins
        c, s = np.cos(th), np.sin(th)
        R = np.array([[c, -s], [s, c]], np.float32)
        rot = base @ R.T  # rotate each (x, y) offset
        out[b] = np.clip(np.rint(rot), -(ROT_RADIUS - 1), ROT_RADIUS - 1)
    return out


def moment_offsets(radius: int = MOMENT_RADIUS) -> np.ndarray:
    """Disc sample offsets and weights for the orientation moment: (P, P, 3)
    float32 of (dx, dy, inside-disc)."""
    ys, xs = np.mgrid[-radius : radius + 1, -radius : radius + 1]
    inside = (xs * xs + ys * ys) <= radius * radius
    return np.stack([xs, ys, inside], axis=-1).astype(np.float32)


def make_pattern_3d(seed: int = 11) -> np.ndarray:
    """(N_BITS, 2, 3) float32 (pair, endpoint, (x, y, z)) INTEGER offsets
    (same integer-quantization rationale as make_pattern: sampling becomes
    a constant one-hot selection on TPU)."""
    rng = np.random.default_rng(seed)
    xy = rng.normal(0.0, RADIUS_XY / 2.0, size=(N_BITS, 2, 2))
    z = rng.normal(0.0, RADIUS_Z / 2.0, size=(N_BITS, 2, 1))
    pts = np.concatenate([xy, z], axis=-1)
    lim = np.array([RADIUS_XY, RADIUS_XY, RADIUS_Z])
    return np.rint(np.clip(pts, -lim, lim)).astype(np.float32)


PATTERN = make_pattern()
ROT_PATTERNS = make_rotated_patterns()
MOMENTS = moment_offsets()
PATTERN_3D = make_pattern_3d()
