"""KNN binary-descriptor matching on TPU: MXU Hamming distance.

Counterpart of the reference's KNN descriptor matcher (SURVEY.md §2 —
per-frame descriptors vs reference-frame descriptors, Hamming distance,
ratio test). TPU-native design: descriptors unpack to ±1 vectors and the
full (K_query, K_ref) Hamming matrix comes off the MXU as a single
matmul — for ±1 bits, dot(a, b) = N_BITS - 2·hamming(a, b), exactly
(products are ±1 and the f32 accumulator is exact for sums ≤ N_BITS) —
then the 2-NN reduces with plain min/argmin passes. Measured on the
v5e: the XOR+SWAR-popcount formulation this replaces was VPU-bound and
`lax.top_k` lowers to a full per-row sort, together 9.3 ms/frame at
K=4096; matmul + min/argmin is 0.70 ms/frame (13x) and 4.5x at K=2048.
No sorting, no variable-length match lists: every query keypoint slot
gets a match index plus a validity flag (ratio test x mutual-nearest x
distance cap x mask).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.ops.describe import N_BITS

_BIG = jnp.uint32((1 << 16) - 1)  # sentinel distance for masked slots:
# any value > N_BITS works; 65535 (not 65536) so the sentinel survives
# the uint16 distance matrix (round 5 — halving the (Kq, Kr) bytes
# halves the match stage's dominant HBM traffic; Hamming distances
# <= 512 are exact in uint16, so nothing else changes)


class Matches(NamedTuple):
    """Per-query-keypoint match against the reference frame's keypoints."""

    idx: jnp.ndarray  # (K,) int32 index into ref keypoints (argmin slot)
    dist: jnp.ndarray  # (K,) int32 best Hamming distance
    second: jnp.ndarray  # (K,) int32 second-best Hamming distance
    valid: jnp.ndarray  # (K,) bool — passed ratio/mutual/cap tests


def popcount_u32(x: jnp.ndarray) -> jnp.ndarray:
    """SWAR population count of a uint32 array (no popcount HW op needed)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def unpack_pm1(desc: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """(..., W) packed uint32 descriptors -> (..., 32*W) ±1 vectors.

    bf16 represents ±1 exactly, so the MXU matmul of two such vectors
    accumulates the exact integer dot product in f32.
    """
    bits = (desc[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    pm = 2 * bits.astype(jnp.int8) - 1
    return pm.reshape(desc.shape[:-1] + (32 * desc.shape[-1],)).astype(dtype)


def hamming_matrix(
    q: jnp.ndarray, r: jnp.ndarray, q_valid: jnp.ndarray, r_valid: jnp.ndarray
) -> jnp.ndarray:
    """(Kq, Kr) Hamming distances; masked slots get a huge sentinel.

    XOR + SWAR popcount — the direct bit-twiddling oracle. The product
    path (`knn_match`) computes the identical matrix on the MXU; this
    stays as the independent formulation tests cross-check against.
    """
    x = q[:, None, :] ^ r[None, :, :]  # (Kq, Kr, W)
    d = jnp.sum(popcount_u32(x), axis=-1).astype(jnp.uint32)
    mask = q_valid[:, None] & r_valid[None, :]
    return jnp.where(mask, d, _BIG)


# Matmul precision variants of the Hamming matrix (`match_precision`
# config field). ALL are exact: the dot product of two ±1 vectors of
# length <= 512 is an integer in [-512, 512], representable without
# rounding both by an f32 accumulator (bf16/float32 operands) and by an
# int32 accumulator (int8 operands) — so the three variants produce the
# IDENTICAL uint16 distance matrix and differ only in which MXU path
# carries the matmul. int8 runs at 2x the bf16 MACs/cycle on v5e-class
# MXUs and halves the operand bytes; float32 stays as the conservative
# reference route.
MATCH_PRECISIONS = ("float32", "bf16", "int8")


def pm1_dtype(precision: str):
    """Operand dtype of the ±1 unpack for a match precision (shared
    with the banded matcher so both routes ride the same MXU path)."""
    if precision == "int8":
        return jnp.int8
    return jnp.float32 if precision == "float32" else jnp.bfloat16


def hamming_matrix_mxu(
    q: jnp.ndarray,
    r: jnp.ndarray,
    q_valid: jnp.ndarray,
    r_valid: jnp.ndarray,
    precision: str = "bf16",
) -> jnp.ndarray:
    """The same (Kq, Kr) matrix as `hamming_matrix`, as one MXU matmul
    (`precision`: see MATCH_PRECISIONS — exact in every variant)."""
    n_bits = 32 * q.shape[-1]
    if precision == "int8":
        s = lax.dot_general(
            unpack_pm1(q, jnp.int8),
            unpack_pm1(r, jnp.int8),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # exact integer dot products in i32
        d = ((n_bits - s) >> 1).astype(jnp.uint16)
    else:
        dt = jnp.float32 if precision == "float32" else jnp.bfloat16
        s = lax.dot_general(
            unpack_pm1(q, dt),
            unpack_pm1(r, dt),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # exact integer-valued dot products in f32
        d = ((n_bits - s) * 0.5).astype(jnp.uint16)
    mask = q_valid[:, None] & r_valid[None, :]
    return jnp.where(mask, d, _BIG.astype(jnp.uint16))


def knn_match_impl(
    q_desc: jnp.ndarray,
    r_desc: jnp.ndarray,
    q_valid: jnp.ndarray,
    r_valid: jnp.ndarray,
    ratio: float = 0.85,
    max_dist: int = 80,
    mutual: bool = True,
    precision: str = "bf16",
) -> Matches:
    """2-NN Hamming match of query descriptors against reference descriptors.

    A match is valid iff: best < `max_dist` bits, best < `ratio` * second
    (Lowe ratio on integer Hamming distances), and — if `mutual` — the
    reference keypoint's own nearest query is this query.

    The 2-NN is two min/argmin passes (mask the argmin slot, min again)
    rather than `lax.top_k`: top_k lowers to a full per-row sort on TPU
    and dominated the whole match stage (see module docstring). Ties
    resolve identically — argmin takes the lowest index, which is the
    slot a stable top-2 would return first, and the runner-up VALUE
    (all the ratio test consumes) is the same either way.

    This is the UNJITTED implementation: the fused register program
    (ops/fused.py) calls it directly inside its own trace so no nested
    pjit boundary sits between the match matrix and the consensus
    scoring. Standalone callers use the jitted `knn_match` wrapper.
    """
    # All-zero descriptors are the invalid sentinel (_finalize_descriptors
    # zeroes masked slots; bin-capacity-dropped keypoints and perfectly
    # flat patches also produce them) — they must not match: an all-zero
    # query's distance to a reference is just the reference's popcount,
    # which is near zero for low-texture references and would pass every
    # test as a spurious correspondence.
    q_valid = q_valid & jnp.any(q_desc != 0, axis=-1)
    r_valid = r_valid & jnp.any(r_desc != 0, axis=-1)
    Di = hamming_matrix_mxu(
        q_desc, r_desc, q_valid, r_valid, precision=precision
    )  # uint16
    Kq, Kr = Di.shape
    best = jnp.min(Di, axis=-1)
    idx = jnp.argmin(Di, axis=-1).astype(jnp.int32)
    taken = idx[:, None] == jnp.arange(Kr, dtype=jnp.int32)[None, :]
    second = jnp.min(jnp.where(taken, _BIG.astype(jnp.uint16), Di), axis=-1)

    ok = (best < max_dist) & (
        best.astype(jnp.float32) < ratio * second.astype(jnp.float32)
    )
    if mutual:
        rev_best = jnp.argmin(Di, axis=0)  # (Kr,) best query for each ref kp
        ok = ok & (rev_best[idx] == jnp.arange(Kq))
    ok = ok & q_valid & (best < jnp.uint16(N_BITS + 1))
    return Matches(
        idx=idx,
        dist=best.astype(jnp.int32),
        second=second.astype(jnp.int32),
        valid=ok,
    )


# The standalone jitted entry (docstring rides along via jit's wraps).
knn_match = jax.jit(knn_match_impl, static_argnames=("mutual", "precision"))
