"""KNN binary-descriptor matching on TPU: XOR + SWAR popcount.

Counterpart of the reference's KNN descriptor matcher (SURVEY.md §2 —
per-frame descriptors vs reference-frame descriptors, Hamming distance,
ratio test). TPU-native design: the full (K_query, K_ref) distance
matrix is computed as a dense batched XOR/popcount reduction — a few
million VPU integer ops per frame, trivially vmapped over the frame
batch; the 2-NN is a `lax.top_k` over the negated distances. No
sorting, no variable-length match lists: every query keypoint slot gets
a match index plus a validity flag (ratio test x mutual-nearest x
distance cap x mask).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from kcmc_tpu.ops.describe import N_BITS

_BIG = jnp.uint32(1 << 16)  # sentinel distance for masked slots (> N_BITS)


class Matches(NamedTuple):
    """Per-query-keypoint match against the reference frame's keypoints."""

    idx: jnp.ndarray  # (K,) int32 index into ref keypoints (argmin slot)
    dist: jnp.ndarray  # (K,) int32 best Hamming distance
    second: jnp.ndarray  # (K,) int32 second-best Hamming distance
    valid: jnp.ndarray  # (K,) bool — passed ratio/mutual/cap tests


def popcount_u32(x: jnp.ndarray) -> jnp.ndarray:
    """SWAR population count of a uint32 array (no popcount HW op needed)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def hamming_matrix(
    q: jnp.ndarray, r: jnp.ndarray, q_valid: jnp.ndarray, r_valid: jnp.ndarray
) -> jnp.ndarray:
    """(Kq, Kr) Hamming distances; masked slots get a huge sentinel."""
    x = q[:, None, :] ^ r[None, :, :]  # (Kq, Kr, W)
    d = jnp.sum(popcount_u32(x), axis=-1).astype(jnp.uint32)
    mask = q_valid[:, None] & r_valid[None, :]
    return jnp.where(mask, d, _BIG)


@functools.partial(jax.jit, static_argnames=("mutual",))
def knn_match(
    q_desc: jnp.ndarray,
    r_desc: jnp.ndarray,
    q_valid: jnp.ndarray,
    r_valid: jnp.ndarray,
    ratio: float = 0.85,
    max_dist: int = 80,
    mutual: bool = True,
) -> Matches:
    """2-NN Hamming match of query descriptors against reference descriptors.

    A match is valid iff: best < `max_dist` bits, best < `ratio` * second
    (Lowe ratio on integer Hamming distances), and — if `mutual` — the
    reference keypoint's own nearest query is this query.
    """
    D = hamming_matrix(q_desc, r_desc, q_valid, r_valid)  # (Kq, Kr) uint32
    Di = D.astype(jnp.int32)
    # top-2 smallest along ref axis
    neg2, idx2 = lax.top_k(-Di, 2)
    best = -neg2[:, 0]
    second = -neg2[:, 1]
    idx = idx2[:, 0]

    ok = (best < max_dist) & (best.astype(jnp.float32) < ratio * second.astype(jnp.float32))
    if mutual:
        rev_best = jnp.argmin(Di, axis=0)  # (Kr,) best query for each ref kp
        ok = ok & (rev_best[idx] == jnp.arange(Di.shape[0]))
    ok = ok & q_valid & (best < jnp.int32(N_BITS + 1))
    return Matches(idx=idx.astype(jnp.int32), dist=best, second=second, valid=ok)
