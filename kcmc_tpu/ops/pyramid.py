"""Multi-octave scale pyramid: true ORB-style multi-scale detection.

BASELINE.json configs[1] names "ORB keypoints"; real ORB is inherently
multi-scale — an image pyramid with per-octave FAST/Harris detection and
scale-aware BRIEF. The single-scale build has a measured ±25% zoom
envelope (DESIGN.md "Zoom envelope of single-scale BRIEF"); beyond that,
zoom/focus drift silently degrades match counts. The pyramid closes
that gap the TPU way:

* Downscaling is a pair of CONSTANT 1D resampling matrices applied as
  matmuls — (H_o, H) @ frame @ (W, W_o) — so the resize runs on the MXU
  with static shapes, no gathers. The matrices use triangle (area-
  antialiased) weights in the pixel-center convention: output pixel i
  samples input position (i + 0.5)·s - 0.5 with a triangle kernel of
  width max(s, 1), the standard antialiased linear resize.
* Octave sizes round UP to multiples of 8 (sublane alignment keeps the
  per-octave detect kernels on their fast paths); the exact per-axis
  scale factors are carried for the coordinate mapping, so rounding
  costs nothing in accuracy.
* Each octave runs the SAME fixed-K detect -> describe stages as the
  base scale (static shapes per octave, compiled once each); keypoint
  coordinates map back to base-frame coords via the pixel-center
  convention, and the per-octave sets concatenate into one fixed-size
  multi-scale keypoint set with an octave id per slot.
* Matching/consensus are unchanged: descriptors extracted at an
  octave's resolution are comparable across octaves (that is the ORB
  scale-invariance construction), so a 1.5-2x zoomed frame matches the
  reference at the octave pair whose scale ratio cancels the zoom.

Octave spacing defaults to 1.5: the single-scale descriptor tolerates
~±25% relative scale, and 1.5-spaced octaves put every zoom within
sqrt(1.5) ≈ 1.22 of some octave pair — gap-free coverage, which 2.0
spacing (worst case sqrt(2) ≈ 1.41) would not give.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from kcmc_tpu.ops.detect import Keypoints


def octave_sizes(
    shape: tuple, n_octaves: int, scale: float
) -> list[tuple[int, int]]:
    """Per-octave (H_o, W_o), octave 0 = full size; rounded up to
    multiples of 8, floored at 32 px."""
    H, W = int(shape[0]), int(shape[1])
    out = []
    for o in range(n_octaves):
        f = scale**o
        ho = max(32, -(-int(round(H / f)) // 8) * 8)
        wo = max(32, -(-int(round(W / f)) // 8) * 8)
        out.append((min(ho, H), min(wo, W)))
    return out


@functools.lru_cache(maxsize=64)
def resize_matrix(n_in: int, n_out: int) -> np.ndarray:
    """(n_out, n_in) antialiased-linear (triangle/area) resampling
    matrix in the pixel-center convention. Shared, JAX-free constant —
    the NumPy backend applies the identical matrix, so both backends
    compute the same pyramid up to float summation order. Cached: the
    NumPy backend calls this per frame, and the per-row moment
    correction below is a Python loop worth building exactly once per
    (n_in, n_out)."""
    s = n_in / n_out
    w = max(s, 1.0)
    centers = (np.arange(n_out, dtype=np.float64) + 0.5) * s - 0.5
    x = np.arange(n_in, dtype=np.float64)
    d = np.abs(x[None, :] - centers[:, None]) / w
    k = np.clip(1.0 - d, 0.0, None)
    k /= k.sum(axis=1, keepdims=True)
    # First-moment correction: a discrete triangle at non-integer scale
    # has a small phase bias (measured ~0.02 px), which would shift
    # every octave keypoint systematically. Project each row onto the
    # {sum = 1, centroid = center} constraint set within
    # span{w, w·(x - c)} — interior rows become exactly linear-
    # preserving; clipped border rows (degenerate variance) keep the
    # edge-clamp behavior.
    for i in range(n_out):
        c = centers[i]
        row = k[i]
        m = float(row @ (x - c))
        v = float(row @ (x - c) ** 2)
        if v > 1e-8 and abs(m) < 0.45 * w:
            g = np.stack([row, row * (x - c)])  # correction directions
            A = np.array([[g[0].sum(), g[1].sum()],
                          [g[0] @ (x - c), g[1] @ (x - c)]])
            rhs = np.array([1.0 - row.sum(), -m])
            try:
                ab = np.linalg.solve(A, rhs)
                k[i] = row + ab[0] * g[0] + ab[1] * g[1]
            except np.linalg.LinAlgError:
                pass
    return k.astype(np.float32)


class Octave(NamedTuple):
    frames: jnp.ndarray  # (B, H_o, W_o) resized batch
    sx: float  # base x = (x_o + 0.5) * sx - 0.5
    sy: float


def build_pyramid(
    frames: jnp.ndarray, n_octaves: int, scale: float
) -> list[Octave]:
    """Resize a (B, H, W) batch into the octave list (octave 0 is the
    input, untouched). Resizes run at HIGHEST precision: the octave
    images feed detection comparisons and descriptor bits, where bf16
    truncation would flip near-equal responses."""
    B, H, W = frames.shape
    sizes = octave_sizes((H, W), n_octaves, scale)
    out = [Octave(frames=frames, sx=1.0, sy=1.0)]
    for o in range(1, n_octaves):
        ho, wo = sizes[o]
        rh = jnp.asarray(resize_matrix(H, ho))
        rw = jnp.asarray(resize_matrix(W, wo))
        small = jnp.einsum(
            "oh,bhw,vw->bov", rh, frames, rw,
            precision=lax.Precision.HIGHEST,
        )
        out.append(Octave(frames=small, sx=W / wo, sy=H / ho))
    return out


def merge_octave_keypoints(
    per_octave: list[tuple[Keypoints, jnp.ndarray]],
    octaves: list[Octave],
) -> tuple[Keypoints, jnp.ndarray]:
    """Concatenate per-octave batched keypoints into one multi-scale
    set in BASE-frame coordinates.

    per_octave: [(Keypoints with (B, K_o, ...) fields, desc (B, K_o,
    W))] per octave. Returns (Keypoints (B, ΣK_o, ...), desc); slots
    are laid out octave-major (octave o's K_o slots are contiguous).
    """
    xs, ss, vs, ds = [], [], [], []
    for (kp, desc), oc in zip(per_octave, octaves):
        sc = jnp.asarray([oc.sx, oc.sy], jnp.float32)
        xs.append((kp.xy + 0.5) * sc - 0.5)
        ss.append(kp.score)
        vs.append(kp.valid)
        ds.append(desc)
    return (
        Keypoints(
            xy=jnp.concatenate(xs, axis=1),
            score=jnp.concatenate(ss, axis=1),
            valid=jnp.concatenate(vs, axis=1),
        ),
        jnp.concatenate(ds, axis=1),
    )


def per_octave_k(max_keypoints: int, n_octaves: int) -> list[int]:
    """Fixed K per octave: an even split rounded up to 8 (static
    shapes; coarser octaves simply leave more slots invalid on sparse
    scenes)."""
    k = max(8, -(-max_keypoints // (n_octaves * 8)) * 8)
    return [k] * n_octaves
