"""Pallas TPU kernel: fused Harris response + NMS + subpixel fields.

The jnp detection path (ops/detect.py) is ~12 separate 1D convolution /
reduce_window passes, each round-tripping the (B, H, W) batch through
HBM — measured ~15 ms of the ~20 ms detect stage on a 64-frame 512x512
batch, making detection ~2/3 of the whole translation pipeline. This
kernel computes the entire dense part of detection — Sobel gradients,
structure tensor, Gaussian windowing, Harris response, separable NMS,
and the quadratic-fit subpixel offset fields — in ONE fused pass with
every intermediate resident in VMEM.

Memory structure (the part that took iteration to get right): a
whole-frame program does NOT fit — Mosaic stack-allocates ~25 live
frame-sized f32 temporaries (~34 MB at 512x512) against ~16 MB of
physical VMEM. So the grid is (batch, row-strips): each program
computes one `_STRIP`-row output band from a (strip + 2*halo)-row
extended slab, shrinking every buffer ~8x. The slab is assembled from
three adjacent input strip blocks (prev/cur/next) of a frame that is
host-padded with one full zero strip above and below — boundary strips
then read genuine zeros with no special cases. Convolutions accumulate
tap-by-tap into explicit VMEM scratch refs, bounding live temporaries.

Semantics notes:

* All convolutions are correlation-form shift-and-add chains over
  statically shifted views. Shifts use `pltpu.roll` with non-negative
  amounts (Mosaic mis-wraps negative dynamic amounts; static negative
  shifts are `(-d) % dim`).
* Zero-padding matches the XLA path's SAME convolutions exactly: the
  real-frame region is re-masked between stages so lane-dim roll
  wrap-around and out-of-frame rows pull only zeros; the NMS max-pool
  compares against -inf outside the frame (reduce_window's SAME
  padding). The subpixel fields use a zero-extended response, which
  differs from the jnp path's edge-replicated padding only on the
  1-pixel frame boundary — excluded by the detector's `border` margin
  (>= conv halo) before any keypoint can reference it.
* Rows of the slab within `halo` of its top/bottom hold partially
  convolved garbage; the output band [halo, halo+STRIP) never reads
  them (NMS reach + subpixel reach < halo by construction).
* Outputs are the same (nms_resp, ox_field, oy_field) triple the jnp
  path produces; keypoint selection (threshold, tile bucketing, top-k)
  stays in XLA where it is cheap (ops/detect.py::_select_keypoints).

Counterpart of the reference `KeypointExtractor` detect stage
(SURVEY.md §2 — reference source unavailable; contract from
BASELINE.json).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kcmc_tpu.ops.patterns import WINDOW_SIGMA

_STRIP = 64  # output rows per program
_HALO = 16  # slab margin; must be >= conv+nms+subpixel reach (10) and 8-aligned

# Sobel taps in correlation form (the XLA path uses conv — flipped —
# semantics, so the antisymmetric difference taps are reversed here;
# smoothing taps are symmetric).
_SM = (0.25, 0.5, 0.25)
_DF = (0.5, 0.0, -0.5)


def _reach(
    nms_size: int, window_sigma: float, smooth_sigma: float | None
) -> int:
    """Influence radius of the fused pass: conv + NMS + subpixel (and
    the optional descriptor-blur free ride)."""
    blur_r = max(1, int(3.0 * window_sigma + 0.5))
    reach = 2 + blur_r + nms_size // 2 + 1
    if smooth_sigma is not None:
        reach = max(reach, max(1, int(3.0 * smooth_sigma + 0.5)))
    return reach


def supports(
    shape: tuple[int, int],
    nms_size: int = 5,
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
) -> bool:
    """Whether the strip kernel can run this configuration whole-width.

    Two gates, both of which the caller must respect by falling back to
    the paneled wrapper (`supports_paneled`) or the jnp path: (a) VMEM —
    the per-lane budget of six (96, Wp) scratch slabs plus
    double-buffered in/out strips is ~6 KB, so Wp beyond ~2048 lanes
    overflows ~16 MB of physical VMEM at compile time; (b) halo — the
    conv + NMS + subpixel (and optional smooth) reach must fit the
    slab's `_HALO` margin.
    """
    Wp = -(-max(shape[1] + _HALO, 128) // 128) * 128
    if Wp > 2048:
        return False
    if smooth_sigma is not None and smooth_sigma <= 0.0:
        return False
    return _reach(nms_size, window_sigma, smooth_sigma) <= _HALO


def supports_paneled(
    nms_size: int = 5,
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
    border: int = 16,
) -> bool:
    """Whether `response_fields_paneled` covers this configuration.

    Size-unbounded by design (no shape argument): the wrapper handles
    any width by adding panels and any height via the strip grid; only
    the filter reach and the border gate it. The paneled wrapper feeds
    the true frame's left/right edges to the kernel as in-panel ZERO
    CONTENT rather than as the frame boundary, so within `_reach`
    columns of those edges nms_resp/ox/oy differ from the whole-frame
    semantics (zeros-as-content vs -inf NMS padding + real-region
    re-masking; the convolutions themselves are identical — zero
    content and zero SAME padding are the same thing). Selection must
    therefore exclude that band: border >= reach.
    """
    if smooth_sigma is not None and smooth_sigma <= 0.0:
        return False
    reach = _reach(nms_size, window_sigma, smooth_sigma)
    return reach <= _HALO and border >= reach


def _roll(a, dy: int, dx: int):
    """Statically shifted view: _roll(a, dy, dx)[i, j] = a[i+dy, j+dx],
    with wrap-around — callers guarantee the wrap region holds the
    values SAME padding would supply (zeros / -inf via masking)."""
    Hs, Wp = a.shape
    if dy:
        a = pltpu.roll(a, (-dy) % Hs, 0)
    if dx:
        a = pltpu.roll(a, (-dx) % Wp, 1)
    return a


def _acc_corr(dst_ref, src_ref, taps, axis: int):
    """dst <- correlation of src with `taps` along `axis`, accumulated
    tap-by-tap in place (bounds the live temporaries to one roll)."""
    r = len(taps) // 2
    for i, w in enumerate(taps):
        d = i - r
        term = w * _roll(src_ref[:, :], d if axis == 0 else 0, d if axis == 1 else 0)
        if i == 0:
            dst_ref[:, :] = term
        else:
            dst_ref[:, :] = dst_ref[:, :] + term


def _detect_kernel(
    prev_ref, cur_ref, next_ref,
    nms_ref, ox_ref, oy_ref,
    f_ref, a_ref, b_ref, c_ref, d_ref, e_ref,
    *, H: int, W: int, harris_k: float, nms_size: int,
    gauss: tuple[float, ...],
    smooth: tuple[float, ...] = (),
    smooth_ref=None,
    strip: int = _STRIP,
):
    s = pl.program_id(1)
    S, h = strip, _HALO
    # Assemble the extended slab: rows [s*S - h, s*S + S + h) of the
    # frame, in frame coordinates (the padded input offsets by one full
    # zero strip, so strip j of the input holds frame rows [j*S - S, ...)).
    f_ref[0:h, :] = prev_ref[S - h :, :]
    f_ref[h : h + S, :] = cur_ref[:, :]
    f_ref[h + S :, :] = next_ref[0:h, :]

    shape = f_ref.shape
    rows = lax.broadcasted_iota(jnp.int32, shape, 0) + (s * S - h)
    cols = lax.broadcasted_iota(jnp.int32, shape, 1)
    real = (rows >= 0) & (rows < H) & (cols < W)
    realf = real.astype(jnp.float32)

    # Free-ride output: the descriptor-stage Gaussian blur of the frame
    # (ops/describe.py needs it; the slab is already resident, so the
    # two 1D passes here replace two full HBM-round-trip convolutions).
    if smooth_ref is not None:
        _acc_corr(a_ref, f_ref, smooth, 0)
        _acc_corr(b_ref, a_ref, smooth, 1)
        smooth_ref[:, :] = b_ref[h : h + S, :W]

    # Gradients: smooth along one axis, difference along the other.
    _acc_corr(a_ref, f_ref, _SM, 0)
    _acc_corr(b_ref, a_ref, _DF, 1)  # gx
    _acc_corr(a_ref, f_ref, _SM, 1)
    _acc_corr(c_ref, a_ref, _DF, 0)  # gy
    # Re-mask: the pad ring picked up conv spill; the window sums below
    # must pull zeros there (SAME semantics).
    b_ref[:, :] = b_ref[:, :] * realf
    c_ref[:, :] = c_ref[:, :] * realf
    # Structure tensor under the Gaussian window.
    a_ref[:, :] = b_ref[:, :] * b_ref[:, :]
    _acc_corr(e_ref, a_ref, gauss, 0)
    _acc_corr(d_ref, e_ref, gauss, 1)  # ixx
    a_ref[:, :] = b_ref[:, :] * c_ref[:, :]
    _acc_corr(e_ref, a_ref, gauss, 0)
    _acc_corr(b_ref, e_ref, gauss, 1)  # ixy (gx dead)
    a_ref[:, :] = c_ref[:, :] * c_ref[:, :]
    _acc_corr(e_ref, a_ref, gauss, 0)
    _acc_corr(c_ref, e_ref, gauss, 1)  # iyy (gy dead)
    det = d_ref[:, :] * c_ref[:, :] - b_ref[:, :] * b_ref[:, :]
    tr = d_ref[:, :] + c_ref[:, :]
    a_ref[:, :] = det - harris_k * tr * tr  # resp

    # NMS: separable max-pool, -inf outside the frame (SAME padding).
    lo, hi = -((nms_size - 1) // 2), nms_size // 2
    b_ref[:, :] = jnp.where(real, a_ref[:, :], -jnp.inf)  # neg
    c_ref[:, :] = b_ref[:, :]
    for d in range(lo, hi + 1):
        if d:
            c_ref[:, :] = jnp.maximum(c_ref[:, :], _roll(b_ref[:, :], d, 0))
    d_ref[:, :] = c_ref[:, :]
    for d in range(lo, hi + 1):
        if d:
            d_ref[:, :] = jnp.maximum(d_ref[:, :], _roll(c_ref[:, :], 0, d))
    neg = b_ref[:, :]
    nms = jnp.where(neg >= d_ref[:, :], neg, -jnp.inf)
    nms_ref[:, :] = nms[h : h + S, :W]

    # Subpixel quadratic fits from the zero-extended response
    # (interior-identical to the jnp path's edge padding).
    c_ref[:, :] = a_ref[:, :] * realf  # rc
    rc = c_ref[:, :]
    right = _roll(rc, 0, 1)
    left = _roll(rc, 0, -1)
    dx = 0.5 * (right - left)
    dxx = right - 2.0 * rc + left
    ox = jnp.where(jnp.abs(dxx) > 1e-8, -dx / dxx, 0.0)
    ox_ref[:, :] = jnp.clip(ox, -0.5, 0.5)[h : h + S, :W]
    down = _roll(rc, 1, 0)
    up = _roll(rc, -1, 0)
    dy = 0.5 * (down - up)
    dyy = down - 2.0 * rc + up
    oy = jnp.where(jnp.abs(dyy) > 1e-8, -dy / dyy, 0.0)
    oy_ref[:, :] = jnp.clip(oy, -0.5, 0.5)[h : h + S, :W]


def _gauss_taps(sigma: float) -> tuple[float, ...]:
    # Host-side numpy mirror of detect._gaussian_kernel1d (f32 math);
    # can't call the jnp version under jit — it would trace.
    r = max(1, int(3.0 * sigma + 0.5))
    xs = np.arange(-r, r + 1, dtype=np.float32)
    g = np.exp(np.float32(-0.5) * (xs / np.float32(sigma)) ** 2)
    return tuple(float(v) for v in (g / g.sum()).astype(np.float32))


@functools.partial(
    jax.jit,
    static_argnames=(
        "harris_k", "nms_size", "window_sigma", "smooth_sigma", "interpret",
        "strip",
    ),
)
def response_fields(
    frames: jnp.ndarray,
    harris_k: float = 0.04,
    nms_size: int = 5,
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
    interpret: bool = False,
    strip: int | None = None,
):
    """Fused dense detection fields for a (B, H, W) batch.

    Returns (nms_resp, ox_field, oy_field), each (B, H, W) f32:
    nms_resp holds the Harris response at local NMS maxima and -inf
    elsewhere; ox/oy are the clipped quadratic-fit subpixel offsets.
    Matches the jnp path (`harris_response` + `_maxpool_same` +
    `_subpixel_fields`) up to float summation order everywhere a
    keypoint can legally land (interior pixels).

    With `smooth_sigma` a fourth array is returned: the sigma-blurred
    frame (SAME zero padding — identical semantics to
    `detect.gaussian_blur`), computed as a free ride on the resident
    slab for the descriptor stage.

    `strip` overrides the output rows per program (the PR-13 autotune
    seam; must be 8-aligned and >= _HALO). Numerically neutral: each
    output pixel's taps and summation order are identical whichever
    strip hosts it — only the grid blocking changes. A candidate too
    large for VMEM fails at compile time; the tuner treats that as
    infeasible and falls back.
    """
    B, H, W = frames.shape
    if not supports((H, W), nms_size, window_sigma, smooth_sigma):
        raise ValueError(
            f"shape={H}x{W}/window_sigma={window_sigma}/nms_size={nms_size}/"
            f"smooth_sigma={smooth_sigma} exceed the kernel's VMEM or halo "
            f"budget ({_HALO}); use the jnp detection path (callers gate "
            "on pallas_detect.supports)"
        )
    gauss = _gauss_taps(window_sigma)

    S, h = strip or _STRIP, _HALO
    if S % 8 or S < h:
        raise ValueError(
            f"strip={S} must be 8-aligned and >= the halo ({h})"
        )
    n_out = -(-H // S)
    # One full zero strip above, content rows padded up to a strip
    # multiple below plus one more zero strip: strip j of the padded
    # array holds frame rows [(j-1)*S, j*S), so a program for output
    # strip s reads input strips (s, s+1, s+2) as prev/cur/next.
    Wp = -(-max(W + h, 128) // 128) * 128
    padded = jnp.pad(
        frames.astype(jnp.float32),
        ((0, 0), (S, (n_out + 1) * S - H), (0, Wp - W)),
    )
    n_in = n_out + 2
    assert padded.shape[1] == n_in * S

    n_outputs = 3 if smooth_sigma is None else 4

    def kernel(*refs):
        ins, outs = refs[:3], refs[3 : 3 + n_outputs]
        scratch = refs[3 + n_outputs :]
        _detect_kernel(
            *ins, *outs[:3], *scratch,
            H=H, W=W, harris_k=harris_k, nms_size=nms_size, gauss=gauss,
            smooth=_gauss_taps(smooth_sigma) if smooth_sigma is not None else (),
            smooth_ref=outs[3] if smooth_sigma is not None else None,
            strip=S,
        )

    strip_in = lambda off: pl.BlockSpec(
        (None, S, Wp), lambda b, s, o=off: (b, s + o, 0)
    )
    scratch = [pltpu.VMEM((S + 2 * h, Wp), jnp.float32) for _ in range(6)]
    out_specs = [
        pl.BlockSpec((None, S, W), lambda b, s: (b, s, 0))
        for _ in range(n_outputs)
    ]
    # Ragged H: out_shape rows are rounded up to the strip size and
    # sliced after (the padded tail computes from genuine zeros).
    Ho = n_out * S
    outs = pl.pallas_call(
        kernel,
        grid=(B, n_out),
        in_specs=[strip_in(0), strip_in(1), strip_in(2)],
        out_specs=out_specs,
        out_shape=[jax.ShapeDtypeStruct((B, Ho, W), jnp.float32)] * n_outputs,
        scratch_shapes=scratch,
        interpret=interpret,
    )(padded, padded, padded)
    return tuple(o[:, :H] for o in outs)


@functools.partial(
    jax.jit,
    static_argnames=(
        "harris_k", "nms_size", "window_sigma", "smooth_sigma",
        "max_panel_w", "interpret",
    ),
)
def response_fields_paneled(
    frames: jnp.ndarray,
    harris_k: float = 0.04,
    nms_size: int = 5,
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
    max_panel_w: int = 2032,
    interpret: bool = False,
):
    """`response_fields` for frames wider than the strip kernel's
    ~2048-lane VMEM gate: overlapping COLUMN PANELS stacked into the
    batch axis, one kernel launch, stitch, discard the `_HALO` overlap.

    Semantics: within each panel's kept core the computed values are
    identical to the whole-frame kernel's — every value depends only on
    content within `_reach` (<= `_HALO`) columns, all present in the
    panel. The one divergence is the true frame's left/right edge band
    (zeros-as-content vs boundary semantics, see `supports_paneled`),
    which callers exclude via `border >= reach`. The descriptor-blur
    free-ride output is exactly identical everywhere (pure convolution:
    zero content == zero SAME padding). Overlap overhead is
    2 * _HALO / core per panel (32/1024 = 3.1% at 2048 wide, where two
    1024-core panels are used).

    `max_panel_w` is the widest panel the strip kernel accepts (tests
    shrink it to force multi-panel runs at small sizes).
    """
    B, H, W = frames.shape
    M = _HALO
    # Largest lane-aligned kept core a panel can carry (aligned panel
    # slicing; the 2*M is the discarded overlap margin).
    core_cap = ((max_panel_w - 2 * M) // 128) * 128
    if core_cap <= 0:
        raise ValueError(f"max_panel_w={max_panel_w} leaves no panel core")
    n_panels = -(-W // core_cap)
    core = min(core_cap, -(-(-(-W // n_panels)) // 128) * 128)
    n_panels = -(-W // core)
    Pw = core + 2 * M
    padded = jnp.pad(frames, ((0, 0), (0, 0), (M, n_panels * core + M - W)))
    panels = jnp.stack(
        [padded[:, :, p * core : p * core + Pw] for p in range(n_panels)],
        axis=1,
    ).reshape(B * n_panels, H, Pw)
    outs = response_fields(
        panels, harris_k=harris_k, nms_size=nms_size,
        window_sigma=window_sigma, smooth_sigma=smooth_sigma,
        interpret=interpret,
    )

    def stitch(o):
        o = o.reshape(B, n_panels, H, Pw)[:, :, :, M : M + core]
        return o.transpose(0, 2, 1, 3).reshape(B, H, n_panels * core)[:, :, :W]

    return tuple(stitch(o) for o in outs)
