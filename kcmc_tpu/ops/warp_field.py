"""Gather-free warps for dense flow fields and homographies.

Completes the gather-free warp family (ops/pallas_warp.py: translation;
ops/warp_separable.py: affine) for the remaining two workloads
(SURVEY.md §0 configs 3-4):

* `warp_batch_flow` — piecewise-rigid dense displacement fields. The
  flow splits into its mean translation (exact, via the separable
  warp's unbounded-offset resample matrices) plus a SMALL residual
  field, which is resampled by a statically-bounded sum of shifted
  views weighted by per-pixel bilinear hats — pure VPU elementwise
  work, no gathers. Piecewise-rigid residuals are local patch motion
  around the global drift, a few pixels by construction.

* `warp_batch_homography` — projective transforms. The homography
  splits as H = A @ N with A its first-order (affine) Taylor expansion
  about the frame center — warped by the separable affine passes — and
  N = A^-1 H a near-identity projective residual warped by the same
  small-field kernel. Wide-field projective drift keeps |N(p) - p|
  to a couple of pixels across the frame.

Frames whose residual exceeds the static bound are zeroed rather than
silently mis-resampled, matching the policy of the other kernels.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kcmc_tpu.ops.warp_separable import warp_batch_affine


def _clamped_shift_matrix(n_in: int, n_out: int, offset) -> jnp.ndarray:
    """(n_out, n_in) matrix: out[i] = in[clip(i + offset, 0, n_in-1)].

    For integer offsets every row is one-hot — an exact shift with
    edge-clamped overhang (the gather warp's tap semantics)."""
    pos = jnp.clip(
        jnp.arange(n_out, dtype=jnp.float32) + offset, 0.0, n_in - 1.0
    )
    src = jnp.arange(n_in, dtype=jnp.float32)
    return jnp.maximum(1.0 - jnp.abs(pos[:, None] - src[None, :]), 0.0)


def _field_resample_small(
    padded: jnp.ndarray, flow: jnp.ndarray, R: int, joint: bool = False
) -> jnp.ndarray:
    """out[p] = padded[p + R+1 + flow[p]] for |flow| <= R over a
    (H+2R+2, W+2R+2) source whose halo carries the border content
    (edge-replicated or real). flow: (H, W, 2) of (ux, uy). The caller
    masks out-of-frame sample positions.

    Default is TWO sequential 1D passes (x then y): 2*(2R+2) shifted
    views instead of the joint form's (2R+2)^2, with each displacement
    component read at the ORIGINAL pixel — an O(|u| * |grad u|)
    approximation, negligible for the smooth patch-grid fields this
    resamples (piecewise flows and projective residuals). `joint=True`
    computes exact 2D bilinear instead.
    """
    H, W = flow.shape[:2]
    ux, uy = flow[..., 0], flow[..., 1]
    mx = jnp.floor(ux)
    my = jnp.floor(uy)
    fx = ux - mx
    fy = uy - my
    mxi = mx.astype(jnp.int32)
    myi = my.astype(jnp.int32)

    if joint:
        out = jnp.zeros((H, W), padded.dtype)
        for ky in range(-R, R + 2):
            wy = jnp.where(myi == ky, 1.0 - fy, 0.0) + jnp.where(
                myi == ky - 1, fy, 0.0
            )
            for kx in range(-R, R + 2):
                wx = jnp.where(mxi == kx, 1.0 - fx, 0.0) + jnp.where(
                    mxi == kx - 1, fx, 0.0
                )
                view = jax.lax.dynamic_slice(
                    padded, (R + 1 + ky, R + 1 + kx), (H, W)
                )
                out = out + (wy * wx) * view
        return out

    # x-pass over the still-y-haloed rows, then y-pass.
    Hh = H + 2 * (R + 1)
    mxi_h = jnp.pad(mxi, ((R + 1, R + 1), (0, 0)), mode="edge")
    fx_h = jnp.pad(fx, ((R + 1, R + 1), (0, 0)), mode="edge")
    r1 = jnp.zeros((Hh, W), padded.dtype)
    for kx in range(-R, R + 2):
        wx = jnp.where(mxi_h == kx, 1.0 - fx_h, 0.0) + jnp.where(
            mxi_h == kx - 1, fx_h, 0.0
        )
        r1 = r1 + wx * jax.lax.dynamic_slice(padded, (0, R + 1 + kx), (Hh, W))
    out = jnp.zeros((H, W), padded.dtype)
    for ky in range(-R, R + 2):
        wy = jnp.where(myi == ky, 1.0 - fy, 0.0) + jnp.where(
            myi == ky - 1, fy, 0.0
        )
        out = out + wy * jax.lax.dynamic_slice(r1, (R + 1 + ky, 0), (H, W))
    return out


@functools.partial(jax.jit, static_argnames=("max_px", "with_ok", "joint"))
def warp_batch_flow(
    frames: jnp.ndarray,
    flows: jnp.ndarray,
    max_px: int = 6,
    with_ok: bool = False,
    joint: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames through (B, H, W, 2) forward displacement
    fields (corrected(p) = frame(p + u(p))) with zero gathers.

    The per-frame mean displacement, rounded to whole pixels, is applied
    exactly as an integer translation onto a haloed canvas (unbounded,
    interpolation-free, source taps edge-clamped like the gather warp's),
    so the result matches one-shot bilinear sampling up to float
    association; the residual — including the fractional part — must
    stay within the static `max_px` bound or the frame is zeroed.
    """
    B, H, W = frames.shape
    frames = jnp.asarray(frames, jnp.float32)
    flows = jnp.asarray(flows, jnp.float32)
    t = jnp.round(jnp.mean(flows, axis=(1, 2)))  # (B, 2) integer (tx, ty)

    # Integer-translate onto a canvas with a (max_px+1)-pixel halo of real
    # border content, so the residual pass's taps near the frame edge read
    # what one-shot bilinear would (clamped to the source frame).
    P = max_px + 1

    def translate_halo(img, txy):
        Kx = _clamped_shift_matrix(W, W + 2 * P, txy[0] - P)
        Ky = _clamped_shift_matrix(H, H + 2 * P, txy[1] - P)
        x = jnp.matmul(img, Kx.T, precision=jax.lax.Precision.HIGHEST)
        return jnp.matmul(Ky, x, precision=jax.lax.Precision.HIGHEST)

    halos = jax.vmap(translate_halo)(frames, t)

    resid = flows - t[:, None, None, :]
    ok = jnp.max(jnp.abs(resid), axis=(1, 2, 3)) <= max_px  # (B,)

    # Residual resample of the translated image: corrected(p) =
    # frame(p + t + r(p)) = shifted(p + r(p)) (r evaluated at p; the
    # default two-pass split is exact up to O(|r| * |grad r|)).
    out = jax.vmap(
        lambda ha, fl: _field_resample_small(ha, fl, max_px, joint=joint)
    )(halos, resid)
    # Coverage: zero where the TRUE sample position leaves the frame.
    xs = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    ys = jnp.arange(H, dtype=jnp.float32)[None, :, None]
    sx = xs + flows[..., 0]
    sy = ys + flows[..., 1]
    inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
    res = jnp.where(ok[:, None, None], out * inb, 0.0)
    return (res, ok) if with_ok else res


@functools.partial(jax.jit, static_argnames=("max_px", "with_ok"))
def warp_batch_rigid3d(
    vols: jnp.ndarray,
    transforms: jnp.ndarray,
    max_px: int = 6,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Correct (B, D, H, W) volumes through (B, 4, 4) rigid transforms
    with zero gathers: integer translation via per-axis clamped shift
    matmuls onto a haloed canvas, then THREE sequential per-axis
    1D resamples of the bounded residual displacement u(p) = M p - p - t.

    The sequential-axis split evaluates each displacement component at
    the ORIGINAL voxel position, an O(|u|*rotation) approximation —
    ~0.03 px at 1 degree of drift rotation, far below the registration
    noise floor. Residuals beyond the static `max_px` bound (or
    non-affine transforms) zero the volume and clear the ok flag.
    """
    B, D, H, W = vols.shape
    vols = jnp.asarray(vols, jnp.float32)
    Ms = jnp.asarray(transforms, jnp.float32)
    P = max_px + 1

    zs = jnp.arange(D, dtype=jnp.float32)[:, None, None]
    ys = jnp.arange(H, dtype=jnp.float32)[None, :, None]
    xs = jnp.arange(W, dtype=jnp.float32)[None, None, :]
    cz, cy, cx = (D - 1) / 2.0, (H - 1) / 2.0, (W - 1) / 2.0

    def per_vol(vol, M):
        ok = (
            (jnp.abs(M[3, 0]) < 1e-12) & (jnp.abs(M[3, 1]) < 1e-12)
            & (jnp.abs(M[3, 2]) < 1e-12) & (jnp.abs(M[3, 3] - 1.0) < 1e-6)
        )
        # Sample positions: p_src = M p (acting on (x, y, z) points).
        sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2] * zs + M[0, 3]
        sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2] * zs + M[1, 3]
        sz = M[2, 0] * xs + M[2, 1] * ys + M[2, 2] * zs + M[2, 3]
        # Integer translation = rounded displacement at the center.
        tc = jnp.round(
            jnp.stack(
                [
                    M[0, 0] * cx + M[0, 1] * cy + M[0, 2] * cz + M[0, 3] - cx,
                    M[1, 0] * cx + M[1, 1] * cy + M[1, 2] * cz + M[1, 3] - cy,
                    M[2, 0] * cx + M[2, 1] * cy + M[2, 2] * cz + M[2, 3] - cz,
                ]
            )
        )
        ux = sx - xs - tc[0]
        uy = sy - ys - tc[1]
        uz = sz - zs - tc[2]
        ok = ok & (
            jnp.maximum(
                jnp.max(jnp.abs(ux)),
                jnp.maximum(jnp.max(jnp.abs(uy)), jnp.max(jnp.abs(uz))),
            )
            <= max_px
        )

        # Integer-translate onto a haloed canvas (clamped taps).
        Kz = _clamped_shift_matrix(D, D + 2 * P, tc[2] - P)
        Ky = _clamped_shift_matrix(H, H + 2 * P, tc[1] - P)
        Kx = _clamped_shift_matrix(W, W + 2 * P, tc[0] - P)
        hp = jnp.einsum(
            "zd,dhw->zhw", Kz, vol, precision=jax.lax.Precision.HIGHEST
        )
        hp = jnp.einsum(
            "yh,zhw->zyw", Ky, hp, precision=jax.lax.Precision.HIGHEST
        )
        hp = jnp.einsum(
            "xw,zyw->zyx", Kx, hp, precision=jax.lax.Precision.HIGHEST
        )  # (D+2P, H+2P, W+2P)

        # Residual per-axis resamples; each pass consumes one halo axis.
        # u must be given on the (partially haloed) grid of that pass.
        def pass_axis(arr, u, axis, out_len):
            m = jnp.floor(u)
            f = u - m
            mi = m.astype(jnp.int32)
            out = jnp.zeros(u.shape, jnp.float32)
            for k in range(-max_px, max_px + 2):
                w = jnp.where(mi == k, 1.0 - f, 0.0) + jnp.where(
                    mi == k - 1, f, 0.0
                )
                start = [0, 0, 0]
                start[axis] = P + k
                size = list(arr.shape)
                size[axis] = out_len
                out = out + w * jax.lax.dynamic_slice(arr, start, size)
            return out

        uxh = jnp.pad(ux, ((P, P), (P, P), (0, 0)), mode="edge")
        r1 = pass_axis(hp, uxh, 2, W)  # (D+2P, H+2P, W)
        uyh = jnp.pad(uy, ((P, P), (0, 0), (0, 0)), mode="edge")
        r2 = pass_axis(r1, uyh, 1, H)  # (D+2P, H, W)
        r3 = pass_axis(r2, uz, 0, D)  # (D, H, W)

        inb = (
            (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
            & (sz >= 0) & (sz <= D - 1)
        )
        return jnp.where(ok & inb, r3, 0.0), ok

    out, oks = jax.vmap(per_vol)(vols, Ms)
    return (out, oks) if with_ok else out


@functools.partial(jax.jit, static_argnames=("max_px", "with_ok"))
def warp_batch_matrix(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    max_px: int = 16,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames through (B, 3, 3) affine/projective
    transforms with zero gathers and ONE bilinear interpolation.

    Round-5 kernel. The Catmull-Smith chain (warp_separable +
    warp_batch_homography) applies FOUR sequential 1D interpolations;
    its composite kernel is measurably smoother and phase-shifted vs
    one-shot bilinear (~0.012 px per-region artifact on TPU — fine
    while "the warp does not feed back into estimation", but the
    round-5 photometric polish DOES feed the warped pixels back, and
    converged to the artifact's optimum ~0.055 px from truth for
    homography). This kernel replaces the chain with:

    1. the analytic source map s(p) = M p (projective divide guarded),
    2. an exact integer center-translation onto a haloed canvas
       (one-hot clamped-shift matmuls — the warp_batch_flow canvas),
    3. a TWO-pass 1D resample of the bounded residual whose x-pass
       phases are evaluated at the CONSUMER's position: canvas row i is
       consumed by output rows y ~ i - P - uy, so the x-phase used for
       row i is ux(x, y_c) with y_c solved by two fixed-point
       iterations of y_c = i - P - uy(x, y_c) (all analytic,
       elementwise). The naive two-pass split reads ux at the output
       pixel instead — an O(|u| * |grad u|) error, which at judged
       rotation/zoom magnitudes is exactly the 0.01-0.03 px artifact.
       With the consumer correction the split matches one-shot 2D
       bilinear to O(|grad u|) ~ 0.005 px.

    Frames whose in-coverage residual displacement (after the integer
    center shift) exceeds `max_px - 0.5` are zeroed and flagged, like
    every bounded kernel in the family. Cost: 2*(2*max_px + 2) fused
    masked shifted views — independent of drift magnitude (the canvas
    absorbs any translation); `max_px` needs to cover rotation/scale/
    projective deviation across the half-frame only.
    """
    B, H, W = frames.shape
    frames = jnp.asarray(frames, jnp.float32)
    Ms = jnp.asarray(transforms, jnp.float32)
    P = max_px + 1
    xs = jnp.arange(W, dtype=jnp.float32)[None, :]
    ys = jnp.arange(H, dtype=jnp.float32)[:, None]
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0

    def per_frame(img, M):
        m = M / jnp.where(jnp.abs(M[2, 2]) > 1e-6, M[2, 2], 1.0)
        g, h = m[2, 0], m[2, 1]

        def smap(x, y):
            wq = g * x + h * y + 1.0
            wq = jnp.where(
                jnp.abs(wq) < 1e-6, jnp.where(wq < 0, -1e-6, 1e-6), wq
            )
            return (
                (m[0, 0] * x + m[0, 1] * y + m[0, 2]) / wq,
                (m[1, 0] * x + m[1, 1] * y + m[1, 2]) / wq,
            )

        sx, sy = smap(xs, ys)  # (H, W)
        sx0, sy0 = smap(cx, cy)
        tcx = jnp.round(sx0 - cx)
        tcy = jnp.round(sy0 - cy)
        ux = sx - xs - tcx
        uy = sy - ys - tcy
        inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
        resid = jnp.maximum(jnp.abs(ux), jnp.abs(uy))
        # margin 0.5: the consumer-evaluated x-phase can exceed the
        # output-pixel residual by O(|uy| * |grad ux|)
        ok = jnp.max(jnp.where(inb, resid, 0.0)) <= max_px - 0.5

        # exact integer translation onto the haloed canvas
        Kx = _clamped_shift_matrix(W, W + 2 * P, tcx - P)
        Ky = _clamped_shift_matrix(H, H + 2 * P, tcy - P)
        hp = jnp.matmul(
            Ky,
            jnp.matmul(img, Kx.T, precision=jax.lax.Precision.HIGHEST),
            precision=jax.lax.Precision.HIGHEST,
        )  # (H + 2P, W + 2P)

        # pass 1 (x) over canvas rows, phases at the consumer position
        ih = jnp.arange(H + 2 * P, dtype=jnp.float32)[:, None]
        yc = ih - P  # consumer estimate, two fixed-point refinements
        for _ in range(2):
            _, sy_c = smap(xs, yc)
            yc = ih - P - (sy_c - yc - tcy)
        sx_c, _ = smap(xs, yc)
        rx = sx_c - xs - tcx  # (H + 2P, W) x-residual for each canvas row
        mx = jnp.floor(rx)
        fx = rx - mx
        mxi = mx.astype(jnp.int32)
        r1 = jnp.zeros((H + 2 * P, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(mxi == k, 1.0 - fx, 0.0) + jnp.where(
                mxi == k - 1, fx, 0.0
            )
            r1 = r1 + wk * jax.lax.dynamic_slice(
                hp, (0, P + k), (H + 2 * P, W)
            )

        # pass 2 (y): phases exact at the output pixel
        my = jnp.floor(uy)
        fy = uy - my
        myi = my.astype(jnp.int32)
        out = jnp.zeros((H, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(myi == k, 1.0 - fy, 0.0) + jnp.where(
                myi == k - 1, fy, 0.0
            )
            out = out + wk * jax.lax.dynamic_slice(r1, (P + k, 0), (H, W))
        return jnp.where(ok & inb, out, 0.0), ok

    out, oks = jax.vmap(per_frame)(frames, Ms)
    return (out, oks) if with_ok else out


def _affine_about_center(M: jnp.ndarray, cx: float, cy: float):
    """First-order Taylor expansion of the projective map at the center:
    returns (A (3,3) affine, ok) with A(p) ~ M(p) near (cx, cy)."""
    m = M / M[2, 2]
    g, h = m[2, 0], m[2, 1]
    w0 = g * cx + h * cy + 1.0
    ok = jnp.abs(w0) > 1e-3
    w0 = jnp.where(ok, w0, 1.0)
    sx0 = (m[0, 0] * cx + m[0, 1] * cy + m[0, 2]) / w0
    sy0 = (m[1, 0] * cx + m[1, 1] * cy + m[1, 2]) / w0
    # d(sx)/dx = (m00 - g*sx)/w at the center, etc.
    a00 = (m[0, 0] - g * sx0) / w0
    a01 = (m[0, 1] - h * sx0) / w0
    a10 = (m[1, 0] - g * sy0) / w0
    a11 = (m[1, 1] - h * sy0) / w0
    A = jnp.array(
        [
            [a00, a01, sx0 - a00 * cx - a01 * cy],
            [a10, a11, sy0 - a10 * cx - a11 * cy],
            [0.0, 0.0, 1.0],
        ],
        dtype=jnp.float32,
    )
    return A, ok


@functools.partial(
    jax.jit, static_argnames=("shear_px", "max_px", "with_ok", "joint")
)
def warp_batch_homography(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    shear_px: int = 8,
    max_px: int = 4,
    with_ok: bool = False,
    joint: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames through (B, 3, 3) homographies with zero
    gathers: separable affine passes for the first-order part, the
    small-field kernel for the projective residual N = A^-1 H.
    """
    B, H, W = frames.shape
    frames = jnp.asarray(frames, jnp.float32)
    Ms = jnp.asarray(transforms, jnp.float32)
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0

    def split(M):
        A, ok = _affine_about_center(M, cx, cy)
        N = jnp.linalg.solve(A, M / M[2, 2])
        return A, N, ok & (jnp.abs(M[2, 2]) > 1e-6)

    As, Ns, oks = jax.vmap(split)(Ms)
    base, affine_ok = warp_batch_affine(frames, As, shear_px=shear_px, with_ok=True)
    oks = oks & affine_ok

    xs = jnp.arange(W, dtype=jnp.float32)[None, :]
    ys = jnp.arange(H, dtype=jnp.float32)[:, None]

    def resid_flow(N):
        w = N[2, 0] * xs + N[2, 1] * ys + N[2, 2]
        w = jnp.where(jnp.abs(w) < 1e-8, 1e-8, w)
        sx = (N[0, 0] * xs + N[0, 1] * ys + N[0, 2]) / w
        sy = (N[1, 0] * xs + N[1, 1] * ys + N[1, 2]) / w
        return jnp.stack([sx - xs, sy - ys], -1)

    flows = jax.vmap(resid_flow)(Ns)  # (B, H, W, 2): N(p) - p
    ok = oks & (jnp.max(jnp.abs(flows), axis=(1, 2, 3)) <= max_px)
    padded = jnp.pad(
        base, ((0, 0), (max_px + 1, max_px + 1), (max_px + 1, max_px + 1)),
        mode="edge",
    )
    out = jax.vmap(
        lambda im, fl: _field_resample_small(im, fl, max_px, joint=joint)
    )(padded, flows)

    # Coverage from the TRUE homography sample positions.
    def inb_mask(M):
        w = M[2, 0] * xs + M[2, 1] * ys + M[2, 2]
        w = jnp.where(jnp.abs(w) < 1e-8, 1e-8, w)
        sx = (M[0, 0] * xs + M[0, 1] * ys + M[0, 2]) / w
        sy = (M[1, 0] * xs + M[1, 1] * ys + M[1, 2]) / w
        return (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)

    inb = jax.vmap(inb_mask)(Ms)
    res = jnp.where(ok[:, None, None], out * inb, 0.0)
    return (res, ok) if with_ok else res
