"""Pallas TPU kernel: fused 3D Harris response + NMS for z-stacks.

The jnp 3D detection path (ops/detect3d.py) is ~25 shift-and-add
convolution passes (3 gradients, 6 structure-tensor entries x 3 blur
axes) plus NMS, each round-tripping the volume batch through HBM —
measured ~21 ms of the ~28 ms detect stage on an 8-volume 32x256x256
batch, with XLA fusion recovering almost none of it. This kernel
computes the whole dense part in one VMEM-resident pass per
(z-block, y-strip) tile.

Memory structure: grid (batch, z-blocks, y-strips) with 8-voxel blocks
in z and y. The padded volume carries one full ZERO block before and
after the content in BOTH z and y (and zero lanes on the right in x),
so a program assembles its (24, 24, Wp) slab from the 3x3 neighborhood
of blocks and every out-of-volume read is a genuine zero. One mask IS
still required: the central difference leaves a nonzero gradient ring
one voxel outside the content, whose products the Gaussian window
would blend back inside (the jnp path's products are zero there), so
gradients are re-masked to the real volume before the products — the
same lesson as the 2D kernel's conv-spill mask. Within the slab, rolls
wrap garbage into the outer ring only; each stage's validity shrinks
by its reach (diff 1 + window blur <= 6 <= 7 < 8 = halo — the bound
`supports()` enforces), so the central 8x8 output block never reads a
contaminated voxel.

The kernel outputs the six blurred structure-tensor entries; the
response, NMS, subpixel fields, thresholding, tile bucketing, and
top-k all stay in XLA (response+NMS are one fused elementwise pass
there, and keeping them out of the kernel holds VMEM to six slab
buffers). Every field therefore matches the jnp path exactly up to
float summation order — no border-semantics differences.

Counterpart of the reference `KeypointExtractor` detect stage for
config 5 (SURVEY.md §2 — reference source unavailable).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kcmc_tpu.ops.pallas_detect import _gauss_taps
from kcmc_tpu.ops.patterns import WINDOW_SIGMA

_BZ = 8  # z-block (and z-halo) size
_BY = 8  # y-strip (and y-halo) size
_DIFF = (0.5, 0.0, -0.5)  # central difference, correlation form


def supports(
    shape: tuple[int, int, int],
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
) -> bool:
    """Whether the fused kernel handles this volume configuration."""
    blur_r = max(1, int(3.0 * window_sigma + 0.5))
    if smooth_sigma is not None:
        if smooth_sigma <= 0.0:
            return False
        blur_r = max(blur_r, max(1, int(3.0 * smooth_sigma + 0.5)))
    if 1 + blur_r + 1 > _BZ:  # diff + blur + NMS reach vs halo
        return False
    Wp = -(-(shape[2] + 8) // 128) * 128
    # 6 slab-sized f32 scratch buffers must fit VMEM with headroom.
    return 6 * 3 * _BZ * 3 * _BY * Wp * 4 <= 11 * 1024 * 1024


def _roll(a, d: int, axis: int):
    if d:
        a = pltpu.roll(a, (-d) % a.shape[axis], axis)
    return a


def _acc_corr(dst_ref, src_ref, taps, axis: int):
    """dst <- correlation of src with `taps` along `axis` (tap-by-tap
    accumulation bounds the live temporaries to one rolled copy)."""
    r = len(taps) // 2
    first = True
    for i, w in enumerate(taps):
        if w == 0.0:
            continue
        term = w * _roll(src_ref[...], i - r, axis)
        if first:
            dst_ref[...] = term
            first = False
        else:
            dst_ref[...] = dst_ref[...] + term


def _structure_kernel(*refs, D: int, H: int, W: int, gauss, smooth_taps=None):
    """Gradients + 3-axis Gaussian window for the six structure-tensor
    entries, written straight to their output blocks. The response /
    NMS tail runs in XLA — it is a single fused elementwise pass there,
    and keeping it out of the kernel holds the VMEM footprint to six
    slab buffers (entry accumulators in VMEM OOM'd at every staging
    the Mosaic stack allocator was offered)."""
    n_out = 7 if smooth_taps is not None else 6
    ins, outs, scratch = refs[:9], refs[9 : 9 + n_out], refs[9 + n_out :]
    f, g1, g2, g3, t1, t2 = scratch
    zi = pl.program_id(1)
    yi = pl.program_id(2)
    # Assemble the 3x3-neighborhood slab: (3*BZ, 3*BY, Wp).
    for dz in range(3):
        for dy in range(3):
            f[dz * _BZ : (dz + 1) * _BZ, dy * _BY : (dy + 1) * _BY, :] = (
                ins[dz * 3 + dy][...]
            )
    # Gradients (correlation form of the jnp path's conv taps).
    _acc_corr(g1, f, _DIFF, 0)  # gz
    _acc_corr(g2, f, _DIFF, 1)  # gy
    _acc_corr(g3, f, _DIFF, 2)  # gx
    # Re-mask to the real volume: the central difference leaves a
    # NONZERO gradient ring one voxel outside the content (it reads the
    # edge voxel against a genuine zero), and the Gaussian window would
    # blend its products back inside — the jnp path's products are
    # zero there. On zero-background synthetic data this is invisible;
    # on real data with a camera offset it inflated the border response
    # ~2x and the detection threshold ~3x before this mask.
    shape = f.shape
    zg = jax.lax.broadcasted_iota(jnp.int32, shape, 0) + (zi * _BZ - _BZ)
    yg = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + (yi * _BY - _BY)
    xg = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
    realf = (
        (zg >= 0) & (zg < D) & (yg >= 0) & (yg < H) & (xg < W)
    ).astype(jnp.float32)
    g1[...] = g1[...] * realf
    g2[...] = g2[...] * realf
    g3[...] = g3[...] * realf
    c = slice(_BZ, 2 * _BZ), slice(_BY, 2 * _BY), slice(0, W)
    # order: sxx, syy, szz, sxy, sxz, syz
    for out, (a, b) in zip(
        outs,
        ((g3, g3), (g2, g2), (g1, g1), (g3, g2), (g3, g1), (g2, g1)),
    ):
        t2[...] = a[...] * b[...]
        _acc_corr(t1, t2, gauss, 0)
        _acc_corr(t2, t1, gauss, 1)
        _acc_corr(t1, t2, gauss, 2)
        out[...] = t1[c[0], c[1], c[2]]
    if smooth_taps is not None:
        # Free-ride output: the descriptor-stage blur of the volume
        # itself (ops/describe3d.py), against the resident slab.
        _acc_corr(t1, f, smooth_taps, 0)
        _acc_corr(t2, t1, smooth_taps, 1)
        _acc_corr(t1, t2, smooth_taps, 2)
        outs[6][...] = t1[c[0], c[1], c[2]]


@functools.partial(
    jax.jit,
    static_argnames=("harris_k", "window_sigma", "smooth_sigma", "interpret"),
)
def response_fields_3d(
    vols: jnp.ndarray,
    harris_k: float = 0.005,
    window_sigma: float = WINDOW_SIGMA,
    smooth_sigma: float | None = None,
    interpret: bool = False,
):
    """(resp, nms_resp) for a (B, D, H, W) volume batch, each (B, D, H, W).

    nms_resp holds the response at 3x3x3 local maxima and -inf
    elsewhere — identical to the jnp path (the NMS runs through the
    same `_maxpool3_same` on the kernel's response). With
    `smooth_sigma` a third array is returned: the sigma-blurred volume
    for the descriptor stage (`gaussian_blur_3d` semantics), a free
    ride on the resident slab.
    """
    B, D, H, W = vols.shape
    gauss = _gauss_taps(window_sigma)
    smooth_taps = (
        _gauss_taps(smooth_sigma) if smooth_sigma is not None else None
    )
    n_out = 7 if smooth_taps is not None else 6
    nz = -(-D // _BZ)
    ny = -(-H // _BY)
    Wp = -(-(W + 8) // 128) * 128
    padded = jnp.pad(
        vols.astype(jnp.float32),
        (
            (0, 0),
            (_BZ, (nz + 1) * _BZ - D),
            (_BY, (ny + 1) * _BY - H),
            (0, Wp - W),
        ),
    )

    def strip_in(dz, dy):
        return pl.BlockSpec(
            (None, _BZ, _BY, Wp),
            lambda b, zi, yi, dz=dz, dy=dy: (b, zi + dz, yi + dy, 0),
        )

    slab = (3 * _BZ, 3 * _BY, Wp)
    kernel = functools.partial(
        _structure_kernel, D=D, H=H, W=W, gauss=gauss,
        smooth_taps=smooth_taps,
    )
    Do, Ho = nz * _BZ, ny * _BY
    outs = pl.pallas_call(
        kernel,
        grid=(B, nz, ny),
        in_specs=[strip_in(dz, dy) for dz in range(3) for dy in range(3)],
        out_specs=[
            pl.BlockSpec((None, _BZ, _BY, W), lambda b, zi, yi: (b, zi, yi, 0))
            for _ in range(n_out)
        ],
        out_shape=[jax.ShapeDtypeStruct((B, Do, Ho, W), jnp.float32)] * n_out,
        scratch_shapes=[pltpu.VMEM(slab, jnp.float32) for _ in range(6)],
        interpret=interpret,
    )(*([padded] * 9))
    sl = np.s_[:, :D, :H]
    sxx, syy, szz, sxy, sxz, syz = (o[sl] for o in outs[:6])
    # Response + NMS: one fused elementwise pass in XLA.
    det = (
        sxx * (syy * szz - syz * syz)
        - sxy * (sxy * szz - syz * sxz)
        + sxz * (sxy * syz - syy * sxz)
    )
    tr = sxx + syy + szz
    resp = det - harris_k * tr * tr * tr
    from kcmc_tpu.ops.detect3d import _maxpool3_same

    nms = jnp.where(
        resp >= jax.vmap(_maxpool3_same)(resp), resp, -jnp.inf
    )
    if smooth_taps is not None:
        return resp, nms, outs[6][sl]
    return resp, nms
