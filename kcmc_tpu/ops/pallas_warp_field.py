"""Pallas TPU kernel: gather-free bilinear warp driven by a patch-grid
displacement field — the piecewise model's re-warp chain, fused.

The XLA path (ops/piecewise.upsample_field + ops/warp_field.
warp_batch_flow) materializes a dense (B, H, W, 2) flow in HBM and then
runs 2*(2R+2) shifted-view passes over frame-sized intermediates —
measured ~0.1 ms/frame at 512², the binding term of every field-polish
pass (DESIGN.md "Piecewise polish, round 5"). This kernel fuses the
whole chain into one VMEM-resident program per (frame, row strip):

1. The per-frame integer mean displacement positions a dynamic source
   window via `pltpu.roll` from SMEM scalars (the translation kernel's
   mechanism, ops/pallas_warp.py, with the same ±PAD exactness window).
2. The residual field (cell values minus that integer mean) upsamples
   IN-KERNEL: one small MXU matmul builds the column interpolation
   (field @ hat_x, K = 128 after padding), and each row interpolation
   is `gh` broadcast FMAs — the dense flow never touches HBM.
3. A two-pass 1D resample applies the bounded residual, with the
   x-pass phases evaluated at the CONSUMER row via two fixed-point
   iterations — the ops/warp_field.warp_batch_matrix correction — so
   the split matches one-shot 2D bilinear to O(|grad u|²) instead of
   the naive split's O(|u|·|grad u|), which at piecewise magnitudes is
   a 0.01-0.1 px warp artifact that feeds straight back into the
   photometric field-polish loop.

Out-of-bounds semantics match the warp family: edge-clamped taps (host
edge padding), per-pixel zeroing where the true sample position leaves
the frame, and whole-frame zero + cleared ok flag when the mean
translation exceeds ±PAD or any cell's residual exceeds max_px - 0.5
(the 0.5 margin covers the consumer-evaluated x-phase overshoot, as in
warp_batch_matrix).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kcmc_tpu.ops.pallas_warp import PAD, _VMEM_BUDGET


def _geometry(H: int, W: int, max_px: int, strip: int):
    RP = max_px + 1
    halo = PAD + RP
    S = -(-H // strip)
    Hw = -(-(strip + 2 * halo) // 8) * 8
    Wp = -(-(W + 2 * halo) // 128) * 128
    return RP, halo, S, Hw, Wp


def _fits(H: int, W: int, max_px: int, strip: int) -> bool:
    RP, _, _, Hw, Wp = _geometry(H, W, max_px, strip)
    CR = strip + 2 * RP
    # window appears ~2x (source + rolled copy); ~6 live (CR, W) phase /
    # accumulator temporaries; one output block
    return (2 * Hw * Wp + 6 * CR * W + strip * W) * 4 <= _VMEM_BUDGET


def pick_strip(shape: tuple[int, int], max_px: int = 6) -> int | None:
    """Strip height whose program fits the VMEM budget. 256 first: at
    512² the whole-frame window (784×896) measured 325 us/frame vs 232
    for 256-row strips — the roll processes the entire window per
    program, so a taller window is pure extra roll work, while the
    strip overlap only costs ~2x HBM reads of a bandwidth-cheap input.
    Frames shorter than 256 rows use one whole-frame program; 128 is
    the narrow-VMEM fallback."""
    H, W = shape
    for strip in (256, H, 128):
        if strip <= H and _fits(H, W, max_px, strip):
            return strip
    return None


def supports(shape: tuple[int, int], max_px: int = 6) -> bool:
    return pick_strip(shape, max_px) is not None


def _make_kernel(H, W, gh, gw, GHp, max_px, strip):
    RP = max_px + 1
    CR = strip + 2 * RP

    def row_interp(urow, inner):
        """Bilinear row interpolation of a column-interpolated field:
        urow (rows, W) cell-space row coords in [0, gh-1]; inner
        (GHp, W) per-cell-row values. gh broadcast FMAs."""
        acc = jnp.zeros(urow.shape, jnp.float32)
        for c in range(gh):
            wc = jnp.maximum(1.0 - jnp.abs(urow - float(c)), 0.0)
            acc = acc + wc * inner[c : c + 1, :]
        return acc

    def kernel(iscal_ref, fscal_ref, src_ref, field_ref, out_ref):
        b = pl.program_id(0)
        s = pl.program_id(1)
        y0 = iscal_ref[b, 0]
        x0 = iscal_ref[b, 1]
        ty = fscal_ref[b, 0]
        tx = fscal_ref[b, 1]
        exact = fscal_ref[b, 2]
        true_h = fscal_ref[b, 3]

        Hw, Wp = src_ref.shape
        full = src_ref[:, :]
        full = pltpu.roll(full, Hw - y0, 0)
        # (slicing to CR rows before the column roll was measured SLOWER
        # — 272 vs 156 us/frame at 512²: the intermediate slice breaks
        # Mosaic's roll pipelining into an extra VMEM copy)
        full = pltpu.roll(full, Wp - x0, 1)

        # --- in-kernel upsample: column interpolation as one matmul ---
        GWp = field_ref.shape[1]
        dcell = jax.lax.broadcasted_iota(jnp.int32, (GWp, W), 0).astype(
            jnp.float32
        )
        xcol = jax.lax.broadcasted_iota(jnp.int32, (GWp, W), 1).astype(
            jnp.float32
        )
        ucol = jnp.clip((xcol + 0.5) * (gw / W) - 0.5, 0.0, gw - 1.0)
        hatx = jnp.maximum(1.0 - jnp.abs(ucol - dcell), 0.0)  # (GWp, W)
        fx_field = field_ref[:GHp, :]
        fy_field = field_ref[GHp : 2 * GHp, :]
        hi = jax.lax.Precision.HIGHEST
        inner_x = jax.lax.dot(fx_field, hatx, precision=hi)  # (GHp, W)
        inner_y = jax.lax.dot(fy_field, hatx, precision=hi)

        def urow_of(y):
            return jnp.clip((y + 0.5) * (gh / H) - 0.5, 0.0, gh - 1.0)

        base = (s * strip).astype(jnp.float32)

        # x-pass phases at the CONSUMER row: canvas row j holds frame
        # row content consumed by output rows y_c with
        # y_c = (base + j - RP) - ry(x, y_c) — two fixed-point steps.
        jrows = jax.lax.broadcasted_iota(jnp.int32, (CR, W), 0).astype(
            jnp.float32
        )
        y_b = jrows + base - float(RP)
        y_c = y_b
        for _ in range(2):
            ry_c = row_interp(urow_of(y_c), inner_y)
            y_c = y_b - ry_c
        rx_c = row_interp(urow_of(y_c), inner_x)  # (CR, W)

        mx = jnp.floor(rx_c)
        fxp = rx_c - mx
        mxi = mx.astype(jnp.int32)
        r1 = jnp.zeros((CR, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(mxi == k, 1.0 - fxp, 0.0) + jnp.where(
                mxi == k - 1, fxp, 0.0
            )
            r1 = r1 + wk * full[:CR, RP + k : RP + k + W]

        # y-pass phases exact at the output pixel
        irows = jax.lax.broadcasted_iota(jnp.int32, (strip, W), 0).astype(
            jnp.float32
        )
        yout = irows + base
        uro = urow_of(yout)
        ry_o = row_interp(uro, inner_y)
        rx_o = row_interp(uro, inner_x)
        my = jnp.floor(ry_o)
        fyp = ry_o - my
        myi = my.astype(jnp.int32)
        acc = jnp.zeros((strip, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(myi == k, 1.0 - fyp, 0.0) + jnp.where(
                myi == k - 1, fyp, 0.0
            )
            acc = acc + wk * r1[RP + k : RP + k + strip, :]

        # Coverage from the TRUE per-pixel sample positions.
        cols = jax.lax.broadcasted_iota(jnp.int32, (strip, W), 1).astype(
            jnp.float32
        )
        sy = yout + ty + ry_o
        sx = cols + tx + rx_o
        inb = (
            (sy >= 0.0) & (sy <= true_h - 1.0)
            & (sx >= 0.0) & (sx <= float(W) - 1.0)
            & (yout <= true_h - 1.0)  # rows padded up to a strip multiple
            & (exact > 0.5)
        )
        out_ref[:, :] = jnp.where(inb, acc, 0.0)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("max_px", "strip", "interpret", "with_ok")
)
def warp_batch_field(
    frames: jnp.ndarray,
    fields: jnp.ndarray,
    max_px: int = 6,
    strip: int | None = None,
    interpret: bool = False,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Correct (B, H, W) frames through (B, gh, gw, 2) cell-centered
    displacement fields (the ops/piecewise convention: corrected(p) =
    frame(p + u(p)), u = bilinear upsample of the field, (ux, uy) last).

    Matches `warp_frame_flow(frames, upsample_field(field))` to
    O(|grad u|²) with zero gathers and no dense flow materialization.
    `with_ok` also returns the (B,) bool flag; frames whose mean
    translation leaves the ±PAD window or whose cell residual exceeds
    max_px - 0.5 are zeroed and flagged, like every bounded kernel in
    the family.
    """
    B, H, W = frames.shape
    _, gh, gw, _ = fields.shape
    if strip is None:
        strip = pick_strip((H, W), max_px)
    if strip is None:
        raise ValueError(
            f"warp_batch_field: no strip height fits VMEM for shape "
            f"{(H, W)}; gate on supports() and use the XLA flow path"
        )
    RP, halo, S, Hw, Wp = _geometry(H, W, max_px, strip)

    frames = jnp.asarray(frames, jnp.float32)
    fields = jnp.asarray(fields, jnp.float32)
    t = jnp.round(jnp.mean(fields, axis=(1, 2)))  # (B, 2) integer (tx, ty)
    resid = fields - t[:, None, None, :]
    maxr = jnp.max(jnp.abs(resid), axis=(1, 2, 3))
    tx, ty = t[:, 0], t[:, 1]
    exact = (
        (ty >= -PAD) & (ty <= PAD) & (tx >= -PAD) & (tx <= PAD)
        & (maxr <= max_px - 0.5)
    ).astype(jnp.float32)
    y0 = jnp.clip(ty.astype(jnp.int32) + PAD, 0, 2 * PAD)
    x0 = jnp.clip(tx.astype(jnp.int32) + PAD, 0, 2 * PAD)
    iscal = jnp.stack([y0, x0], axis=-1)  # (B, 2) int32
    zeros = jnp.zeros_like(ty)
    fscal = jnp.stack(
        [ty, tx, exact, jnp.full((B,), float(H), jnp.float32),
         zeros, zeros, zeros, zeros],
        axis=-1,
    )  # (B, 8) float32

    # Residual field, channels folded onto the sublane axis:
    # rows [0, GHp) = ux cells, rows [GHp, 2 GHp) = uy cells. The
    # padded cells' hat weights vanish (|ucol - d| >= 1), so zero
    # padding is exact.
    GHp = -(-gh // 8) * 8
    GWp = -(-gw // 128) * 128
    fgrid = jnp.moveaxis(resid, -1, 1)  # (B, 2, gh, gw)
    fgrid = jnp.pad(fgrid, ((0, 0), (0, 0), (0, GHp - gh), (0, GWp - gw)))
    fgrid = fgrid.reshape(B, 2 * GHp, GWp)

    # Edge-pad so taps clamp like the gather warp; bottom/right padding
    # additionally covers the strip-multiple and tile-alignment slack.
    hp_total = (S - 1) * strip + Hw
    padded = jnp.pad(
        frames,
        ((0, 0), (halo, hp_total - H - halo), (halo, Wp - W - halo)),
        mode="edge",
    )
    if S == 1:
        strips = padded[:, None]  # (B, 1, Hw, Wp) — no read amplification
    else:
        strips = jnp.stack(
            [
                jax.lax.slice_in_dim(padded, s * strip, s * strip + Hw, axis=1)
                for s in range(S)
            ],
            axis=1,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (None, None, Hw, Wp), lambda b, s, iscal: (b, s, 0, 0)
            ),
            pl.BlockSpec(
                (None, 2 * GHp, GWp), lambda b, s, iscal: (b, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec((None, strip, W), lambda b, s, iscal: (b, s, 0)),
    )
    out = pl.pallas_call(
        _make_kernel(H, W, gh, gw, GHp, max_px, strip),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S * strip, W), jnp.float32),
        interpret=interpret,
    )(iscal, fscal, strips, fgrid)
    out = out[:, :H, :]
    return (out, exact > 0.5) if with_ok else out


def _fits_matrix(H: int, W: int, max_px: int, strip: int) -> bool:
    RP, _, _, Hw, Wp = _geometry(H, W, max_px, strip)
    CR = strip + 2 * RP
    # ~20 live (CR, W) temporaries measured: the analytic smap chains
    # (consumer fixed-point iterations, projective divides) each pin
    # several stack slots (a 256-row strip at 512² compiled standalone
    # but hit a 20.2 MB scoped-vmem OOM inside the fused batch program)
    return (2 * Hw * Wp + 20 * CR * W + strip * W) * 4 <= _VMEM_BUDGET


def pick_strip_matrix(shape: tuple[int, int], max_px: int = 16) -> int | None:
    """Strip height for the matrix-warp kernel (same 256-first rationale
    as pick_strip; the larger default residual bound widens CR)."""
    H, W = shape
    for strip in (256, H, 128):
        if strip <= H and _fits_matrix(H, W, max_px, strip):
            return strip
    return None


def supports_matrix(shape: tuple[int, int], max_px: int = 16) -> bool:
    return pick_strip_matrix(shape, max_px) is not None


def _make_matrix_kernel(H, W, max_px, strip):
    RP = max_px + 1
    CR = strip + 2 * RP

    def kernel(iscal_ref, fscal_ref, src_ref, out_ref, maxr_ref):
        b = pl.program_id(0)
        s = pl.program_id(1)
        y0 = iscal_ref[b, 0]
        x0 = iscal_ref[b, 1]
        m00 = fscal_ref[b, 0]
        m01 = fscal_ref[b, 1]
        m02 = fscal_ref[b, 2]
        m10 = fscal_ref[b, 3]
        m11 = fscal_ref[b, 4]
        m12 = fscal_ref[b, 5]
        g = fscal_ref[b, 6]
        h = fscal_ref[b, 7]
        tcx = fscal_ref[b, 8]
        tcy = fscal_ref[b, 9]
        true_h = fscal_ref[b, 10]

        Hw, Wp = src_ref.shape
        full = src_ref[:, :]
        full = pltpu.roll(full, Hw - y0, 0)
        full = pltpu.roll(full, Wp - x0, 1)

        def smap(x, y):
            wq = g * x + h * y + 1.0
            wq = jnp.where(
                jnp.abs(wq) < 1e-6, jnp.where(wq < 0, -1e-6, 1e-6), wq
            )
            return (
                (m00 * x + m01 * y + m02) / wq,
                (m10 * x + m11 * y + m12) / wq,
            )

        base = (s * strip).astype(jnp.float32)

        # x-pass phases at the consumer row (two fixed-point steps —
        # the ops/warp_field.warp_batch_matrix correction, evaluated
        # analytically per canvas row)
        jrows = jax.lax.broadcasted_iota(jnp.int32, (CR, W), 0).astype(
            jnp.float32
        )
        xcols = jax.lax.broadcasted_iota(jnp.int32, (CR, W), 1).astype(
            jnp.float32
        )
        y_b = jrows + base - float(RP)
        y_c = y_b
        for _ in range(2):
            _, sy_c = smap(xcols, y_c)
            y_c = y_b - (sy_c - y_c - tcy)
        sx_c, _ = smap(xcols, y_c)
        rx = sx_c - xcols - tcx
        mx = jnp.floor(rx)
        fxp = rx - mx
        mxi = mx.astype(jnp.int32)
        r1 = jnp.zeros((CR, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(mxi == k, 1.0 - fxp, 0.0) + jnp.where(
                mxi == k - 1, fxp, 0.0
            )
            r1 = r1 + wk * full[:CR, RP + k : RP + k + W]

        # y-pass phases exact at the output pixel
        irows = jax.lax.broadcasted_iota(jnp.int32, (strip, W), 0).astype(
            jnp.float32
        )
        ocols = jax.lax.broadcasted_iota(jnp.int32, (strip, W), 1).astype(
            jnp.float32
        )
        yout = irows + base
        sx_o, sy_o = smap(ocols, yout)
        ux = sx_o - ocols - tcx
        uy = sy_o - yout - tcy
        my = jnp.floor(uy)
        fyp = uy - my
        myi = my.astype(jnp.int32)
        acc = jnp.zeros((strip, W), jnp.float32)
        for k in range(-max_px, max_px + 2):
            wk = jnp.where(myi == k, 1.0 - fyp, 0.0) + jnp.where(
                myi == k - 1, fyp, 0.0
            )
            acc = acc + wk * r1[RP + k : RP + k + strip, :]

        inb = (
            (sx_o >= 0.0) & (sx_o <= float(W) - 1.0)
            & (sy_o >= 0.0) & (sy_o <= true_h - 1.0)
            & (yout <= true_h - 1.0)
        )
        resid = jnp.maximum(jnp.abs(ux), jnp.abs(uy))
        maxr_ref[...] = jnp.full(
            (8, 128), jnp.max(jnp.where(inb, resid, 0.0)), jnp.float32
        )
        out_ref[:, :] = jnp.where(inb, acc, 0.0)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("max_px", "strip", "interpret", "with_ok")
)
def warp_batch_matrix_pallas(
    frames: jnp.ndarray,
    transforms: jnp.ndarray,
    max_px: int = 16,
    strip: int | None = None,
    interpret: bool = False,
    with_ok: bool = False,
) -> jnp.ndarray:
    """Pallas form of ops/warp_field.warp_batch_matrix: correct
    (B, H, W) frames through (B, 3, 3) affine/projective transforms
    with ONE bilinear interpolation and zero gathers.

    Identical math to the XLA kernel — analytic source map, exact
    integer center translation (here a `pltpu.roll` window instead of
    one-hot shift matmuls), consumer-phase-corrected two-pass bounded
    resample — but the 2*(2*max_px + 2) masked shifted views run over
    the VMEM-resident strip instead of HBM-sized intermediates, and
    the mask/residual fields are computed in-kernel rather than
    materialized at (B, H, W). Same policy: frames whose in-coverage
    residual exceeds max_px - 0.5 (or whose center translation leaves
    the ±PAD window, or a degenerate M[2,2]) are zeroed and flagged.
    The residual maximum is reduced per strip in-kernel and combined
    on the host, so the flag is exact over pixels, like the XLA form.
    """
    B, H, W = frames.shape
    if strip is None:
        strip = pick_strip_matrix((H, W), max_px)
    if strip is None:
        raise ValueError(
            f"warp_batch_matrix_pallas: no strip fits VMEM for {(H, W)}; "
            "gate on supports_matrix() and use warp_batch_matrix"
        )
    RP, halo, S, Hw, Wp = _geometry(H, W, max_px, strip)
    frames = jnp.asarray(frames, jnp.float32)
    Ms = jnp.asarray(transforms, jnp.float32)
    cy, cx = (H - 1) / 2.0, (W - 1) / 2.0

    def prep(M):
        den = jnp.where(jnp.abs(M[2, 2]) > 1e-6, M[2, 2], 1.0)
        m = M / den
        g, h = m[2, 0], m[2, 1]
        w0 = g * cx + h * cy + 1.0
        w0 = jnp.where(jnp.abs(w0) < 1e-6, 1.0, w0)
        sx0 = (m[0, 0] * cx + m[0, 1] * cy + m[0, 2]) / w0
        sy0 = (m[1, 0] * cx + m[1, 1] * cy + m[1, 2]) / w0
        tcx = jnp.round(sx0 - cx)
        tcy = jnp.round(sy0 - cy)
        okm = jnp.abs(M[2, 2]) > 1e-6
        return m, tcx, tcy, okm

    ms, tcxs, tcys, okm = jax.vmap(prep)(Ms)
    exact_t = (
        (tcys >= -PAD) & (tcys <= PAD) & (tcxs >= -PAD) & (tcxs <= PAD)
    )
    y0 = jnp.clip(tcys.astype(jnp.int32) + PAD, 0, 2 * PAD)
    x0 = jnp.clip(tcxs.astype(jnp.int32) + PAD, 0, 2 * PAD)
    iscal = jnp.stack([y0, x0], axis=-1)
    fscal = jnp.stack(
        [
            ms[:, 0, 0], ms[:, 0, 1], ms[:, 0, 2],
            ms[:, 1, 0], ms[:, 1, 1], ms[:, 1, 2],
            ms[:, 2, 0], ms[:, 2, 1],
            tcxs, tcys, jnp.full((B,), float(H), jnp.float32),
            jnp.zeros((B,), jnp.float32),
        ],
        axis=-1,
    )  # (B, 12)

    hp_total = (S - 1) * strip + Hw
    padded = jnp.pad(
        frames,
        ((0, 0), (halo, hp_total - H - halo), (halo, Wp - W - halo)),
        mode="edge",
    )
    if S == 1:
        strips = padded[:, None]
    else:
        strips = jnp.stack(
            [
                jax.lax.slice_in_dim(padded, s * strip, s * strip + Hw, axis=1)
                for s in range(S)
            ],
            axis=1,
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, S),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (None, None, Hw, Wp), lambda b, s, iscal: (b, s, 0, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((None, strip, W), lambda b, s, iscal: (b, s, 0)),
            pl.BlockSpec(
                (None, None, 8, 128), lambda b, s, iscal: (b, s, 0, 0)
            ),
        ],
    )
    out, maxr = pl.pallas_call(
        _make_matrix_kernel(H, W, max_px, strip),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, S * strip, W), jnp.float32),
            jax.ShapeDtypeStruct((B, S, 8, 128), jnp.float32),
        ],
        interpret=interpret,
    )(iscal, fscal, strips)
    ok = (
        okm & exact_t
        & (jnp.max(maxr, axis=(1, 2, 3)) <= max_px - 0.5)
    )
    res = jnp.where(ok[:, None, None], out[:, :H, :], 0.0)
    return (res, ok) if with_ok else res
