"""Request-latency telemetry: mergeable log-bucket histograms.

The serving plane needs to answer "how long did *this user's frame*
take, and where did it wait?" — per-request, not run-aggregate. The
obs layer's stage timers and traces are run-scoped; this module adds
the request-scoped primitives:

* `LatencyHistogram` — a fixed log-scale-bucket histogram: bucket
  edges are a deterministic integer-nanosecond geometric ladder
  (2^(1/4) spacing from 1 µs to ~134 s), recording is O(1) (one
  bisect + three integer adds), and two histograms with the same
  scheme merge EXACTLY (integer counts, integer nanosecond sums) —
  associative and commutative, across threads, sessions, and
  processes. That exact mergeability is what lets a fleet aggregator
  (or the serve plane's own rollup) combine per-session histograms
  into a plane-wide view that is bit-identical to recording every
  sample into one histogram.
* `SegmentLatencies` — a thread-safe recorder keyed by
  (lifecycle segment, QoS rung). Segment names are drawn from the
  canonical vocabulary in `obs/registry.py` (REQUEST_SEGMENTS /
  JOURNAL_SPANS); `kcmc check`'s span-registry pass verifies every
  `observe(...)` call site against it.
* `RequestClock` — the per-batch timestamp carrier the serve
  scheduler threads through dispatch → drain so each frame's segment
  durations land in its session's recorder.
* `render_prometheus` — Prometheus text exposition of the `metrics`
  verb payload (counters, gauges, cumulative histogram buckets), so a
  router or scraper health-checks a replica without parsing the human
  heartbeat.

Quantiles are estimated at the geometric midpoint of the covering
bucket: with 2^(1/4) ≈ 1.19 bucket spacing the relative error of any
reported percentile is bounded by 2^(1/8) - 1 ≈ 9% (the unit suite
pins this bound against exact percentiles).

Everything here is stdlib-only and import-light — scrapers and the
`kcmc_tpu top` dashboard must not pull in an accelerator stack.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from math import ceil, sqrt

# -- bucket scheme ---------------------------------------------------------
#
# Upper bucket edges in integer nanoseconds: T0 * 2^(i / PER_OCTAVE),
# rounded — a pure function of the index, so every process computes the
# identical ladder and cross-process merges line up bucket for bucket.
# 1 µs resolution floor; 27 octaves tops out at ~134 s (a serve request
# slower than that is a wedge, not a latency).
T0_NS = 1_000
PER_OCTAVE = 4
N_OCTAVES = 27

_EDGES_NS: tuple[int, ...] = tuple(
    round(T0_NS * 2.0 ** (i / PER_OCTAVE))
    for i in range(N_OCTAVES * PER_OCTAVE + 1)
)
_N_BUCKETS = len(_EDGES_NS) + 1  # + overflow

_SCHEME = {"t0_ns": T0_NS, "per_octave": PER_OCTAVE, "octaves": N_OCTAVES}

# The QoS rung a record lands under when the caller doesn't say:
# sessions dispatching at full consensus budgets.
DEFAULT_RUNG = "full"


class LatencyHistogram:
    """Fixed log-bucket histogram of durations (seconds in, exact
    integer-nanosecond state inside).

    NOT internally locked: a single owner thread may record freely;
    concurrent producers go through `SegmentLatencies` (which guards
    its histograms with one lock). All state is integers, so `merge`
    is exact — associative, commutative, order-independent.
    """

    __slots__ = ("counts", "count", "sum_ns", "max_ns")

    def __init__(self):
        self.counts = [0] * _N_BUCKETS
        self.count = 0
        self.sum_ns = 0
        self.max_ns = 0

    # -- recording ---------------------------------------------------------

    def record(self, seconds: float, n: int = 1) -> None:
        """O(1): one bisect over the precomputed integer edges plus
        integer adds. `n` records the same duration n times (a batch
        of frames sharing one measured seam)."""
        ns = int(seconds * 1e9)
        if ns < 0:
            ns = 0
        idx = bisect_left(_EDGES_NS, ns)
        self.counts[idx] += n
        self.count += n
        self.sum_ns += ns * n
        if ns > self.max_ns:
            self.max_ns = ns

    # -- merge (exact) -----------------------------------------------------

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold `other` into self (exact integer addition); returns
        self. Histograms always share the module's fixed scheme, so
        any two merge."""
        sc, oc = self.counts, other.counts
        for i, c in enumerate(oc):
            if c:
                sc[i] += c
        self.count += other.count
        self.sum_ns += other.sum_ns
        if other.max_ns > self.max_ns:
            self.max_ns = other.max_ns
        return self

    def clone(self) -> "LatencyHistogram":
        h = LatencyHistogram()
        h.counts = list(self.counts)
        h.count = self.count
        h.sum_ns = self.sum_ns
        h.max_ns = self.max_ns
        return h

    # -- quantiles ---------------------------------------------------------

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (q in [0, 100]) in seconds: geometric
        midpoint of the covering bucket, clamped to the observed max —
        relative error bounded by the bucket ratio (≈9%). None when
        empty."""
        if self.count <= 0:
            return None
        rank = max(1, ceil(q / 100.0 * self.count))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                est = self._bucket_value_ns(i)
                return min(est, self.max_ns) / 1e9
        return self.max_ns / 1e9  # unreachable; defensive

    @staticmethod
    def _bucket_value_ns(i: int) -> float:
        if i == 0:
            return _EDGES_NS[0] / 2.0
        if i >= len(_EDGES_NS):
            return float(_EDGES_NS[-1])
        return sqrt(float(_EDGES_NS[i - 1]) * float(_EDGES_NS[i]))

    # -- export ------------------------------------------------------------

    def summary(self) -> dict:
        """THE per-histogram schema every surface shares — the
        `metrics` verb, `timing["latency"]`, `kcmc_tpu report --json`
        (one schema, asserted in tests): count / sum_s / p50_s /
        p90_s / p99_s / max_s."""

        def _r(v):
            return None if v is None else round(v, 6)

        return {
            "count": int(self.count),
            "sum_s": round(self.sum_ns / 1e9, 6),
            "p50_s": _r(self.quantile(50)),
            "p90_s": _r(self.quantile(90)),
            "p99_s": _r(self.quantile(99)),
            "max_s": round(self.max_ns / 1e9, 6),
        }

    def to_dict(self) -> dict:
        """JSON state: sparse bucket counts + integer sums. Two
        histograms fed the same samples in any split produce the SAME
        dict — the bit-identity contract the fleet aggregator needs."""
        return {
            "scheme": dict(_SCHEME),
            "counts": {
                str(i): c for i, c in enumerate(self.counts) if c
            },
            "count": int(self.count),
            "sum_ns": int(self.sum_ns),
            "max_ns": int(self.max_ns),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        if d.get("scheme") != _SCHEME:
            raise ValueError(
                f"incompatible latency-histogram scheme {d.get('scheme')!r}"
                f" (this build uses {_SCHEME})"
            )
        h = cls()
        for k, c in (d.get("counts") or {}).items():
            h.counts[int(k)] = int(c)
        h.count = int(d.get("count", 0))
        h.sum_ns = int(d.get("sum_ns", 0))
        h.max_ns = int(d.get("max_ns", 0))
        return h


def merge_histograms(*hists: LatencyHistogram) -> LatencyHistogram:
    """Pure merge of any number of histograms (exact; empty in, empty
    out)."""
    out = LatencyHistogram()
    for h in hists:
        out.merge(h)
    return out


class RequestClock:
    """Per-batch lifecycle timestamps the scheduler threads from
    `take_batch` through dispatch to drain. `t_submit` holds each
    frame's submit-entry `perf_counter()` stamp (the anchor of
    `request.total`); the remaining fields are batch-level."""

    __slots__ = (
        "t_submit", "t_formed", "t_dispatched", "t_host", "rung", "trace",
    )

    def __init__(self, t_submit, t_formed: float, trace: dict | None = None):
        self.t_submit = t_submit
        self.t_formed = t_formed
        self.t_dispatched: float | None = None
        self.t_host: float | None = None
        self.rung: str = DEFAULT_RUNG
        # Distributed-trace context of the request(s) in this batch
        # ({"trace_id", "span_id", ...}, obs/tracing.py) — threads the
        # id from submit through dispatch to drain so device spans and
        # bucket exemplars name the originating trace.
        self.trace = trace


class SegmentLatencies:
    """Thread-safe latency recorder keyed by (segment, QoS rung).

    One lock guards the key map and every record — records are
    tens-per-batch integer adds, never per-pixel, so contention is
    negligible (the bench acceptance gate pins total overhead < 2%).
    Segment names at `observe` call sites are literals from
    `obs/registry.py`; the span-registry pass enforces it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[tuple[str, str], LatencyHistogram] = {}

    def observe(
        self, segment: str, seconds: float, n: int = 1,
        rung: str = DEFAULT_RUNG,
    ) -> None:
        key = (segment, rung)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LatencyHistogram()
            h.record(seconds, n=n)

    @property
    def count(self) -> int:
        with self._lock:
            return sum(h.count for h in self._hists.values())

    # -- merge / snapshot --------------------------------------------------

    def _snapshot(self) -> dict[tuple[str, str], LatencyHistogram]:
        with self._lock:
            return {k: h.clone() for k, h in self._hists.items()}

    def merge_from(self, other: "SegmentLatencies") -> "SegmentLatencies":
        """Fold `other`'s histograms into self, exactly. Snapshots
        `other` under its own lock first, so the two locks are never
        held together (no cross-recorder lock order to violate)."""
        snap = other._snapshot()
        with self._lock:
            for key, h in snap.items():
                mine = self._hists.get(key)
                if mine is None:
                    self._hists[key] = h
                else:
                    mine.merge(h)
        return self

    def segment_total(self, segment: str) -> LatencyHistogram:
        """All rungs of one segment merged (exact)."""
        with self._lock:
            hists = [
                h.clone()
                for (seg, _), h in self._hists.items()
                if seg == segment
            ]
        return merge_histograms(*hists)

    # -- export ------------------------------------------------------------

    def report(self) -> dict:
        """The shared latency-section schema:
        ``{"segments": {segment: {rung: summary}},
        "totals": {segment: summary}}`` — `totals` merges a segment's
        rungs. Deterministically ordered."""
        snap = self._snapshot()
        segments: dict = {}
        totals: dict[str, LatencyHistogram] = {}
        for (seg, rung) in sorted(snap):
            h = snap[(seg, rung)]
            segments.setdefault(seg, {})[rung] = h.summary()
            t = totals.get(seg)
            totals[seg] = h.clone() if t is None else t.merge(h)
        return {
            "segments": segments,
            "totals": {seg: totals[seg].summary() for seg in sorted(totals)},
        }

    def hist_dicts(self) -> dict:
        """Full bucket state per (segment, rung) —
        ``{segment: {rung: LatencyHistogram.to_dict()}}`` — the
        exact-merge transport for the fleet aggregator and the
        Prometheus renderer."""
        snap = self._snapshot()
        out: dict = {}
        for (seg, rung) in sorted(snap):
            out.setdefault(seg, {})[rung] = snap[(seg, rung)].to_dict()
        return out


# -- Prometheus text exposition --------------------------------------------


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace(
        "\n", r"\n"
    )


def _fmt_le(ns: int) -> str:
    return f"{ns / 1e9:.9g}"


def render_prometheus(metrics: dict) -> str:
    """Prometheus text exposition (version 0.0.4) of a `metrics` verb
    payload: request-latency histograms (cumulative buckets + sum +
    count per segment/rung), serve counters, serve gauges, SLO burn
    gauges, and — when the payload carries an `exemplars` section
    (obs/tracing.py) — OpenMetrics ``# {trace_id=...}`` exemplar
    suffixes on the matching bucket lines. Works on a live reply or a
    dumped snapshot — pure dict in, text out. Every `# TYPE` line has
    a matching `# HELP` line (format-test enforced)."""
    lines: list[str] = []

    exemplars = metrics.get("exemplars") or {}
    hists = (metrics.get("plane") or {}).get("histograms") or {}
    if hists:
        lines.append(
            "# HELP kcmc_request_latency_seconds Per-request lifecycle"
            " segment latency (log-bucket histogram)."
        )
        lines.append("# TYPE kcmc_request_latency_seconds histogram")
        for seg in sorted(hists):
            for rung in sorted(hists[seg]):
                d = hists[seg][rung]
                labels = (
                    f'segment="{_prom_escape(seg)}",'
                    f'rung="{_prom_escape(rung)}"'
                )
                counts = [0] * _N_BUCKETS
                for k, c in (d.get("counts") or {}).items():
                    counts[int(k)] = int(c)
                total = int(d.get("count", 0))
                acc = 0
                seg_ex = (exemplars.get(seg) or {}).get(rung) or {}
                for i, edge in enumerate(_EDGES_NS):
                    acc += counts[i]
                    # render populated prefixes only (a subset of le's
                    # plus +Inf is valid exposition); stop once the
                    # cumulative count is complete
                    if counts[i]:
                        line = (
                            "kcmc_request_latency_seconds_bucket"
                            f'{{{labels},le="{_fmt_le(edge)}"}} {acc}'
                        )
                        ex = seg_ex.get(str(i))
                        if isinstance(ex, dict) and ex.get("trace_id"):
                            line += (
                                " # {trace_id=\""
                                f"{_prom_escape(ex['trace_id'])}\"}} "
                                f"{float(ex.get('value_s', 0.0)):.9g}"
                            )
                        lines.append(line)
                    if acc >= total - counts[-1]:
                        break
                lines.append(
                    "kcmc_request_latency_seconds_bucket"
                    f'{{{labels},le="+Inf"}} {total}'
                )
                lines.append(
                    "kcmc_request_latency_seconds_sum"
                    f"{{{labels}}} {int(d.get('sum_ns', 0)) / 1e9:.9g}"
                )
                lines.append(
                    "kcmc_request_latency_seconds_count"
                    f"{{{labels}}} {total}"
                )

    for name, value in sorted((metrics.get("counters") or {}).items()):
        metric = f"kcmc_serve_{name}_total"
        lines.append(f"# HELP {metric} Serve counter `{name}`.")
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {int(value)}")

    gauges = dict(metrics.get("gauges") or {})
    queues = gauges.pop("queues", None)
    for name, value in sorted(gauges.items()):
        metric = f"kcmc_serve_{name}"
        lines.append(f"# HELP {metric} Serve gauge `{name}`.")
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric} {float(value):.9g}")
    if queues:
        lines.append(
            "# HELP kcmc_serve_queue_frames Undispatched frames"
            " queued per open session."
        )
        lines.append("# TYPE kcmc_serve_queue_frames gauge")
        for sid in sorted(queues):
            lines.append(
                "kcmc_serve_queue_frames"
                f'{{session="{_prom_escape(sid)}"}} {int(queues[sid])}'
            )

    slo = metrics.get("slo")
    if slo:
        from .slo import render_slo_prometheus  # lazy: avoids cycle

        lines.extend(render_slo_prometheus(slo))
    return "\n".join(lines) + "\n"


__all__ = [
    "DEFAULT_RUNG",
    "LatencyHistogram",
    "RequestClock",
    "SegmentLatencies",
    "merge_histograms",
    "render_prometheus",
]
