"""Heartbeat: periodic liveness + progress lines for unattended runs.

A multi-hour streaming run's only live signal used to be `--progress`
frame counts (stdout, chatty) or nothing. The heartbeat thread samples
the run every `interval_s` seconds and emits ONE line to stderr —
frames done / total, fps, stall fractions, robustness counters — so a
supervisor (or a human tailing the log) can distinguish "slow but
alive" from "wedged" without attaching a debugger. Complements the
`_StallWatchdog` (which hard-exits on zero progress): the watchdog
acts, the heartbeat narrates.

Lifecycle: `start()` spawns one daemon thread; `stop()` signals and
JOINS it (bounded by one interval), so tests can assert no thread
leaks. Sampling failures are swallowed after one diagnostic — a
telemetry bug must never take down the run it observes.
"""

from __future__ import annotations

import logging
import sys
import threading


def _default_emit(message: str) -> None:
    """Log through `kcmc_tpu.heartbeat` when a handler is attached AND
    the record would actually pass level filtering; plain stderr
    otherwise. Whoever set heartbeat_s>0 asked for the output — an
    embedder who attached a handler to `kcmc_tpu` but left the default
    WARNING level must still see the liveness line, not have INFO
    records silently filtered away."""
    logger = logging.getLogger("kcmc_tpu.heartbeat")
    if logging.getLogger("kcmc_tpu").handlers and logger.isEnabledFor(
        logging.INFO
    ):
        logger.info(message)
    else:
        print(f"[kcmc heartbeat] {message}", file=sys.stderr, flush=True)


class Heartbeat:
    """Emit `sample()`'s message every `interval_s` seconds on a
    background thread. `sample` returns the line to emit (str) or None
    to skip a beat."""

    def __init__(self, interval_s: float, sample, emit=None):
        if interval_s <= 0:
            raise ValueError(
                f"heartbeat interval must be positive seconds, got {interval_s}"
            )
        self.interval_s = float(interval_s)
        self._sample = sample
        self._emit = emit if emit is not None else _default_emit
        self._stop = threading.Event()
        # start/stop are a cross-thread handoff in serving: a session's
        # heartbeat starts on the client thread (session construction)
        # and stops on the scheduler thread (finalize) — the handle
        # swap is guarded so the joiner always sees the started thread.
        self._lifecycle = threading.Lock()
        self._thread: threading.Thread | None = None
        self.beats = 0  # emitted lines (lifecycle tests)

    def start(self) -> "Heartbeat":
        with self._lifecycle:
            if self._thread is not None:
                return self  # already running (idempotent)
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="kcmc-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        warned = False
        while not self._stop.wait(self.interval_s):
            try:
                msg = self._sample()
            except Exception as e:
                if not warned:
                    warned = True
                    self._emit(f"heartbeat sampler failed ({e!r}); muting")
                continue
            if msg:
                self._emit(msg)
                self.beats += 1

    def stop(self) -> None:
        """Signal and join the thread (idempotent; bounded wait)."""
        with self._lifecycle:
            # set INSIDE the lock: a stop racing a start must not have
            # its signal cleared by the start it lost the race to
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=self.interval_s + 5.0)

    @property
    def running(self) -> bool:
        with self._lifecycle:
            t = self._thread
        return t is not None and t.is_alive()

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def aggregate_sampler(snapshot):
    """Build a `Heartbeat` sample() over MANY live sessions.

    A single run's heartbeat narrates one progress counter; the serving
    layer has N concurrent streams plus a scheduler, so its liveness
    line aggregates: per-session frames/fps, totals, scheduler queue
    depths, and admission decisions. `snapshot()` returns a dict:

    * ``sessions`` — list of ``{"name", "frames", "fps"}`` (required;
      an empty list emits an idle line); entries may carry ``idle_s``
      (client-liveness age) and a per-session ``robustness`` counter
      dict;
    * ``queues`` — optional ``{session name: queued frames}``;
    * ``admission`` — optional counters dict (e.g. ``accepted``,
      ``degraded``, ``rejected``) — rendered only when any is nonzero;
    * ``robustness`` — optional aggregate recovery counters (retries,
      failovers, rescued frames, journal saves) — rendered only when
      any is nonzero, so a healthy plane's line stays short;
    * ``latency`` — optional end-to-end request-latency digest
      (``{"p50_ms", "p99_ms"}`` of the plane's ``request.total``
      histogram) — the liveness line's tail-latency pulse;
    * ``slo`` — optional pre-formatted SLO burn-rate line from
      ``SLOEngine.heartbeat()`` (obs/slo.py) — rendered verbatim
      between the latency pulse and the staleness list;
    * ``stale`` — optional ``{session name: idle seconds}`` of clients
      approaching the staleness reap;
    * ``loop_beat_age_s`` — optional scheduler-loop liveness age; ages
      beyond 30 s are flagged as a WEDGE (the scheduler-queue-wedge
      watchdog's narration — heavy device batches legitimately hold
      the loop for seconds, a wedged queue holds it forever);
    * ``extra`` — optional pre-formatted string appended verbatim.

    Returns the sample callable to hand to ``Heartbeat``.
    """

    def sample() -> str:
        snap = snapshot()
        sessions = snap.get("sessions") or []
        if not sessions:
            parts = ["0 sessions (idle)"]
        else:
            total = sum(int(s.get("frames", 0)) for s in sessions)
            fps = sum(float(s.get("fps", 0.0)) for s in sessions)
            parts = [
                f"{len(sessions)} session(s), {total} frames total, "
                f"{fps:.1f} fps",
                " ".join(
                    f"{s.get('name', '?')}={int(s.get('frames', 0))}"
                    f"@{float(s.get('fps', 0.0)):.1f}fps"
                    for s in sessions
                ),
            ]
        queues = snap.get("queues")
        if queues:
            parts.append(
                "queued "
                + " ".join(f"{k}={int(v)}" for k, v in sorted(queues.items()))
            )
        admission = snap.get("admission")
        if admission and any(admission.values()):
            parts.append(
                "admission "
                + " ".join(f"{k}={v}" for k, v in sorted(admission.items()))
            )
        robustness = snap.get("robustness")
        if robustness and any(robustness.values()):
            parts.append(
                "robustness "
                + " ".join(
                    f"{k}={v}"
                    for k, v in sorted(robustness.items())
                    if v
                )
            )
        lat = snap.get("latency")
        if lat and lat.get("p99_ms") is not None:
            parts.append(
                f"latency p50={float(lat.get('p50_ms', 0.0)):.0f}ms "
                f"p99={float(lat['p99_ms']):.0f}ms"
            )
        slo = snap.get("slo")
        if slo:
            parts.append(str(slo))
        stale = snap.get("stale")
        if stale:
            parts.append(
                "stale "
                + " ".join(
                    f"{k}={float(v):.0f}s" for k, v in sorted(stale.items())
                )
            )
        beat_age = snap.get("loop_beat_age_s")
        if beat_age is not None and float(beat_age) > 30.0:
            parts.append(f"SCHEDULER WEDGED {float(beat_age):.0f}s")
        extra = snap.get("extra")
        if extra:
            parts.append(str(extra))
        return ", ".join(parts)

    return sample
