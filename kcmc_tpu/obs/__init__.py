"""kcmc_tpu.obs — the run-telemetry subsystem.

Four pieces (docs/OBSERVABILITY.md):

* `trace` — thread-aware span `Tracer`, Chrome trace-event export
  (`--trace PATH`, Perfetto-loadable);
* `records` — per-frame quality records streamed to a JSONL sidecar
  through a bounded background writer (`--frame-records PATH`);
* `manifest` + `heartbeat` — the run manifest embedded in both
  artifacts, and the periodic stderr progress line (`--heartbeat S`);
* `report` — the `kcmc_tpu report` renderer over either artifact.

`run.RunTelemetry` coordinates them per run; `log` owns the
`kcmc_tpu` logger and the `advise()` warning-routing seam. Everything
is off by default and costs one None-check per batch when disabled.
"""

from __future__ import annotations

__all__ = [
    "Tracer",
    "FrameRecordStream",
    "Heartbeat",
    "aggregate_sampler",
    "RunTelemetry",
    "build_manifest",
    "get_logger",
    "setup_cli_logging",
    "advise",
]


def __getattr__(name):  # lazy: obs imports must not tax the hot path
    if name == "Tracer":
        from kcmc_tpu.obs.trace import Tracer

        return Tracer
    if name == "FrameRecordStream":
        from kcmc_tpu.obs.records import FrameRecordStream

        return FrameRecordStream
    if name in ("Heartbeat", "aggregate_sampler"):
        from kcmc_tpu.obs import heartbeat

        return getattr(heartbeat, name)
    if name == "RunTelemetry":
        from kcmc_tpu.obs.run import RunTelemetry

        return RunTelemetry
    if name == "build_manifest":
        from kcmc_tpu.obs.manifest import build_manifest

        return build_manifest
    if name in ("get_logger", "setup_cli_logging", "advise"):
        from kcmc_tpu.obs import log

        return getattr(log, name)
    raise AttributeError(f"module 'kcmc_tpu.obs' has no attribute {name!r}")
