"""`kcmc_tpu report`: render a human-readable run report.

Consumes either artifact a run leaves behind:

* a frame-records JSONL (`--frame-records PATH`) — header manifest,
  per-frame quality records, optional run summary line;
* a transforms `.npz` (`correct --transforms PATH`) — per-frame
  diagnostic arrays plus the JSON-encoded `timing`/`robustness`
  payloads the CLI embeds.

and renders: the manifest line, the stage/stall table (totals, counts,
per-stage means — the `StageTimer` payload), frame-quality percentiles,
the worst-N frames by consensus support, and the robustness-ladder
summary. Pure stdlib + numpy: auditing a run must not require an
accelerator stack.

A `kcmc check --json` artifact (kind: kcmc_check) is also accepted and
renders as the static-analysis summary line — the CI job's one-stop
"what did this run conclude" renderer.

The timing keys and span names this renderer reads are the canonical
vocabulary of `kcmc_tpu/obs/registry.py`; `kcmc check`'s span-registry
pass verifies every literal here against it, so a producer rename
cannot silently drop a series from this report.
"""

from __future__ import annotations

import json

import numpy as np

# Per-frame metrics the percentile table covers, in display order.
_METRICS = (
    ("n_keypoints", "keypoints"),
    ("n_matches", "matches"),
    ("n_inliers", "inliers"),
    ("inlier_ratio", "inlier_ratio"),
    ("rms_residual_px", "residual_px"),
    ("template_corr", "template_corr"),
    ("coverage", "coverage"),
)
_PCTS = (5, 25, 50, 75, 95)


def load_run(path: str) -> dict:
    """Normalize either artifact into
    {manifest, records: [dict], timing, robustness, source}.
    Distributed-tracing span shards (a shard .jsonl or a directory of
    them) normalize to {source, spans} and render as the critical-path
    report."""
    p = str(path)
    if p.endswith(".npz"):
        return _load_npz(p)
    spans = _load_maybe_spans(p)
    if spans is not None:
        return spans
    return _load_jsonl(p)


def _load_maybe_spans(path: str) -> dict | None:
    """The artifact as {source, spans} if it is a span shard (header
    kind kcmc_span_shard) or a directory containing shards; None
    otherwise — frame-records JSONLs have a different header kind and
    fall through to the frame-quality loader."""
    import os

    from kcmc_tpu.obs.tracing import SHARD_KIND, collect_spans

    if os.path.isdir(path):
        try:
            spans = collect_spans([path])
        except (OSError, ValueError):
            return None
        return {"source": path, "spans": spans} if spans else None
    try:
        with open(path, encoding="utf-8") as f:
            first = json.loads(f.readline() or "null")
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    if not (isinstance(first, dict) and first.get("kind") == SHARD_KIND):
        return None
    return {"source": path, "spans": collect_spans([path])}


def _load_jsonl(path: str) -> dict:
    from kcmc_tpu.obs.records import read_jsonl

    header, records, summary = read_jsonl(path)
    out = {
        "source": path,
        "manifest": (header or {}).get("manifest"),
        "records": records,
        "timing": (summary or {}).get("timing"),
        "robustness": (summary or {}).get("robustness"),
    }
    if summary is None:
        out["incomplete"] = True  # killed run: no summary line
    elif "error" in summary:
        out["error"] = summary["error"]
    return out


def _load_npz(path: str) -> dict:
    with np.load(path, allow_pickle=False) as data:
        keys = set(data.files)

        def _json_scalar(key):
            if key not in keys:
                return None
            try:
                return json.loads(str(data[key]))
            except (json.JSONDecodeError, ValueError):
                return None

        n = 0
        for k in ("n_inliers", "n_matches", "n_keypoints", "rms_residual"):
            if k in keys:
                n = len(data[k])
                break
        cols = {
            k: np.asarray(data[k])
            for k in (
                "n_keypoints", "n_matches", "n_inliers", "rms_residual",
                "template_corr", "coverage", "warp_ok", "warp_rescued",
                "frames_failed",
            )
            if k in keys
        }
        timing = _json_scalar("timing")
        robustness = _json_scalar("robustness")
        manifest = _json_scalar("manifest")
    records = []
    for i in range(n):
        nm = int(cols["n_matches"][i]) if "n_matches" in cols else 0
        ni = int(cols["n_inliers"][i]) if "n_inliers" in cols else 0
        rec = {
            "frame": i,
            "n_matches": nm,
            "n_inliers": ni,
            "inlier_ratio": ni / max(nm, 1),
        }
        if "n_keypoints" in cols:
            rec["n_keypoints"] = int(cols["n_keypoints"][i])
        if "rms_residual" in cols:
            rec["rms_residual_px"] = float(cols["rms_residual"][i])
        if "template_corr" in cols:
            rec["template_corr"] = float(cols["template_corr"][i])
        if "coverage" in cols:
            rec["coverage"] = float(cols["coverage"][i])
        if "warp_ok" in cols:
            rec["warp_ok"] = bool(cols["warp_ok"][i])
        if "warp_rescued" in cols:
            rec["warp_rescued"] = bool(cols["warp_rescued"][i])
        if "frames_failed" in cols:
            rec["failed"] = bool(cols["frames_failed"][i])
        records.append(rec)
    return {
        "source": path,
        "manifest": manifest,
        "records": records,
        "timing": timing,
        "robustness": robustness,
    }


def _metric_values(records: list[dict], key: str) -> np.ndarray:
    vals = [
        r[key]
        for r in records
        if r.get(key) is not None and np.isfinite(r[key])
    ]
    return np.asarray(vals, np.float64)


def _fmt(v: float) -> str:
    if abs(v) >= 1000 or v == int(v):
        return f"{v:.0f}"
    return f"{v:.3f}"


def _stage_table(timing: dict) -> list[str]:
    lines = []
    stages = timing.get("stages_s", {})
    counts = timing.get("stage_counts", {})
    means = timing.get("stage_mean_s", {})
    if stages:
        lines.append("Stages:")
        lines.append(
            f"  {'stage':<20} {'total_s':>10} {'count':>7} {'mean_s':>10}"
        )
        for name, total in sorted(stages.items(), key=lambda kv: -kv[1]):
            c = counts.get(name, 0)
            m = means.get(name, total / c if c else 0.0)
            lines.append(
                f"  {name:<20} {total:>10.3f} {c:>7d} {m:>10.4f}"
            )
        lines.append(f"  {'TOTAL':<20} {timing.get('total_s', 0.0):>10.3f}")
    stalls = timing.get("stalls_s", {})
    if stalls:
        sc = timing.get("stall_counts", {})
        total = timing.get("total_s") or 0.0
        lines.append("Pipeline stalls (consumer blocked inside stages):")
        lines.append(
            f"  {'seam':<20} {'total_s':>10} {'count':>7} {'of run':>7}"
        )
        for name, s in sorted(stalls.items(), key=lambda kv: -kv[1]):
            frac = f"{100 * s / total:.1f}%" if total else "-"
            lines.append(
                f"  {name:<20} {s:>10.3f} {sc.get(name, 0):>7d} {frac:>7}"
            )
    feed = timing.get("feeder")
    if feed:
        # pooled-ingest accounting (io/feeder.py): which pool flavor
        # decoded, how wide, and how much it retried
        lines.append(
            "Feeder (decode pool): "
            f"mode={feed.get('mode', '?')} workers={feed.get('workers', '?')}"
            f" prefetch={feed.get('prefetch_chunks', '?')} chunks"
            f"  decoded {feed.get('frames', 0)} frames in"
            f" {feed.get('chunks', 0)} chunks / {feed.get('spans', 0)} spans"
            + (
                f"  (io_retries {feed['io_retries']})"
                if feed.get("io_retries")
                else ""
            )
        )
    lines.extend(_latency_table(timing))
    fps = timing.get("frames_per_sec")
    if fps:
        lines.append(f"Throughput: {fps:.1f} frames/sec")
    plan = timing.get("plan_cache")
    if plan:
        # Warm-up / compile accounting (kcmc_tpu/plans): what this run
        # compiled vs deserialized, and how batches routed to buckets.
        lines.append("Warm-up / compile cache (execution plans):")
        cache = plan.get("cache_dir") or "off"
        lines.append(
            f"  persistent cache: {cache}  rung: {plan.get('rung', 'full')}"
        )
        if plan.get("buckets"):
            lines.append(
                "  buckets: "
                + ", ".join("x".join(map(str, b)) for b in plan["buckets"])
                + (
                    f"  routed exact={plan.get('bucket_exact', 0)}"
                    f" padded={plan.get('bucket_padded', 0)}"
                    f" fallback={plan.get('bucket_fallback', 0)}"
                )
            )
        lines.append(
            f"  programs compiled: {plan.get('programs_compiled', 0)}"
            f" in {plan.get('compile_s', 0.0):.2f}s"
            f"  (stamp hits {plan.get('stamp_hits', 0)},"
            f" misses {plan.get('stamp_misses', 0)})"
        )
        for ev in (plan.get("events") or [])[:8]:
            shape = "x".join(str(s) for s in ev.get("shape", []))
            hit = ev.get("stamp_hit")
            tag = "" if hit is None else (" [cached]" if hit else " [fresh]")
            lines.append(
                f"    {ev.get('program', '?'):<18} {shape:<12}"
                f" {ev.get('dtype', ''):<8} {ev.get('seconds', 0.0):>8.3f}s"
                f"{tag}"
            )
    return lines


def _fmt_ms(v) -> str:
    """Milliseconds, or the em dash for stats a pre-latency-plane
    artifact (or an empty histogram) doesn't carry — the renderer must
    never crash on old runs."""
    if v is None:
        return "—"
    try:
        return f"{float(v) * 1e3:.2f}"
    except (TypeError, ValueError):
        return "—"


def _latency_table(timing: dict) -> list[str]:
    """The "Request latency" section (docs/OBSERVABILITY.md): one row
    per (lifecycle segment, QoS rung) from `timing["latency"]` — the
    same schema the serve `metrics` verb exports. Absent on pre-plane
    artifacts (rendered as nothing, not a crash)."""
    lat = timing.get("latency")
    if not isinstance(lat, dict):
        return []
    segments = lat.get("segments")
    if not isinstance(segments, dict) or not segments:
        return []
    lines = ["Request latency (per lifecycle segment; ms):"]
    lines.append(
        f"  {'segment':<22} {'rung':<9} {'count':>8} {'p50':>9}"
        f" {'p90':>9} {'p99':>9} {'max':>9}"
    )
    for seg in sorted(segments):
        rungs = segments[seg]
        if not isinstance(rungs, dict):
            continue
        for rung in sorted(rungs):
            s = rungs[rung] or {}
            lines.append(
                f"  {seg:<22} {rung:<9} {s.get('count', 0):>8}"
                f" {_fmt_ms(s.get('p50_s')):>9}"
                f" {_fmt_ms(s.get('p90_s')):>9}"
                f" {_fmt_ms(s.get('p99_s')):>9}"
                f" {_fmt_ms(s.get('max_s')):>9}"
            )
    return lines


def _deadline_qos_table(timing) -> list[str]:
    """The "Deadline QoS" report section (docs/SERVING.md "Latency
    QoS"): the session's scheduling class and its deadline scorecard
    from `timing["deadline_qos"]`. Always present, like the critical-
    path table: artifacts that predate latency QoS (or batch-class runs
    that never touched a deadline) render the em dash rather than
    omitting the section — and never crash, whatever shape the artifact
    has."""
    dq = (timing or {}).get("deadline_qos") if isinstance(
        timing, dict
    ) else None
    if not isinstance(dq, dict) or not dq:
        return [
            "Deadline QoS: — (no latency-class activity in this "
            "artifact)"
        ]
    hits = int(dq.get("deadline_hits") or 0)
    misses = int(dq.get("deadline_misses") or 0)
    rate = (
        f"{100.0 * hits / (hits + misses):.1f}%"
        if (hits + misses) else "—"
    )
    return [
        "Deadline QoS:",
        f"  class={dq.get('qos_class') or '—'}"
        f" deadline_hits={hits} deadline_misses={misses}"
        f" hit_rate={rate}"
        f" preempted_dispatches={int(dq.get('preempted_dispatches') or 0)}",
    ]


def _critical_path_summary(spans) -> dict | None:
    """Per-request dominant-segment histogram from distributed-tracing
    span shards: {n_traces, dominant: {segment: count}, slowest:
    [{trace_id, total_s, dominant}]}. None when the artifact predates
    tracing (no spans) — the renderers show "—" instead of a table."""
    if not spans:
        return None
    from kcmc_tpu.obs.tracing import critical_path, slowest, stitch

    traces = stitch(spans)
    counts: dict[str, int] = {}
    total_by: dict[str, float] = {}
    for trace_spans in traces.values():
        cp = critical_path(trace_spans)
        dom = cp.get("dominant")
        if dom is None:
            continue
        counts[dom] = counts.get(dom, 0) + 1
        total_by[dom] = total_by.get(dom, 0.0) + float(
            cp.get("total_s") or 0.0
        )
    if not counts:
        return None
    return {
        "n_traces": len(traces),
        "dominant": counts,
        "mean_total_s": {
            seg: total_by[seg] / counts[seg] for seg in counts
        },
        "slowest": slowest(traces, n=5),
    }


def _critical_path_table(spans) -> list[str]:
    """The "Critical path" report section. Always present: artifacts
    without span shards (every pre-tracing run) render "—" rather than
    omitting the section, so a reader knows tracing simply wasn't on —
    and never crash, whatever shape the artifact has."""
    cp = _critical_path_summary(spans)
    if cp is None:
        return ["Critical path: — (no span shards in this artifact)"]
    n = sum(cp["dominant"].values())
    lines = [
        f"Critical path ({cp['n_traces']} traced requests, "
        "dominant segment per request):",
        f"  {'dominant segment':<22} {'requests':>9} {'share':>7}"
        f" {'mean e2e':>10}",
    ]
    for seg, c in sorted(cp["dominant"].items(), key=lambda kv: -kv[1]):
        lines.append(
            f"  {seg:<22} {c:>9} {100.0 * c / n:>6.1f}%"
            f" {_fmt_ms(cp['mean_total_s'][seg]):>8}ms"
        )
    rows = cp.get("slowest") or []
    if rows:
        lines.append("  slowest:")
        for r in rows:
            lines.append(
                f"    {r['trace_id']}  {_fmt_ms(r['total_s']):>8}ms"
                f"  dominant={r.get('dominant') or '—'}"
            )
    return lines


def render_report(run: dict, top: int = 10) -> str:
    """The human-readable report text."""
    lines = [f"# kcmc run report — {run.get('source', '?')}"]
    if run.get("spans") is not None:
        # A span-shard artifact IS the critical-path report — no
        # frame-quality sections to render.
        lines.append("")
        lines.extend(_critical_path_table(run["spans"]))
        return "\n".join(lines) + "\n"
    man = run.get("manifest")
    if man:
        v = man.get("versions", {})
        cfg = man.get("config", {})
        bits = []
        if cfg.get("model"):
            bits.append(f"model={cfg['model']}")
        if man.get("backend"):
            bits.append(f"backend={man['backend']}")
        if man.get("config_sha256"):
            bits.append(f"config={man['config_sha256'][:12]}")
        if v.get("kcmc_tpu"):
            bits.append(f"kcmc_tpu {v['kcmc_tpu']}")
        if v.get("jax"):
            bits.append(f"jax {v['jax']}")
        rt = man.get("backend_runtime") or {}
        devs = rt.get("devices") or []
        if devs:
            bits.append(
                f"{len(devs)}x {devs[0].get('platform', '?')}"
            )
        if man.get("fault_plan"):
            bits.append(f"fault_plan={man['fault_plan']!r}")
        lines.append("Manifest: " + ", ".join(bits))
    if run.get("incomplete"):
        lines.append(
            "NOTE: no run-summary line — the run did not close cleanly "
            "(killed mid-run?); records below cover what was flushed."
        )
    if run.get("error"):
        lines.append(f"RUN FAILED: {run['error']}")

    records = run.get("records") or []
    n_failed = sum(1 for r in records if r.get("failed"))
    n_rescued = sum(1 for r in records if r.get("warp_rescued"))
    n_failover = sum(1 for r in records if r.get("failover"))
    escalated = any(r.get("escalated") for r in records)
    frame_bits = [f"Frames: {len(records)}"]
    if n_failed:
        frame_bits.append(f"failed={n_failed}")
    if n_rescued:
        frame_bits.append(f"warp_rescued={n_rescued}")
    if n_failover:
        frame_bits.append(f"failover={n_failover}")
    if escalated:
        frame_bits.append("warp ESCALATED")
    lines.append(" ".join(frame_bits))

    timing = run.get("timing")
    if timing:
        lines.append("")
        lines.extend(_stage_table(timing))

    if records:
        lines.append("")
        lines.append("Frame quality percentiles:")
        header = "  " + f"{'metric':<14}" + "".join(
            f"{f'p{p}':>10}" for p in _PCTS
        )
        lines.append(header)
        for key, label in _METRICS:
            vals = _metric_values(records, key)
            if len(vals) == 0:
                continue
            pcts = np.percentile(vals, _PCTS)
            lines.append(
                f"  {label:<14}" + "".join(f"{_fmt(p):>10}" for p in pcts)
            )
        worst = _worst_frames(records, top)
        if worst:
            lines.append("")
            lines.append(
                f"Worst {len(worst)} frames (by inlier support):"
            )
            lines.append(
                f"  {'frame':>7} {'inliers':>8} {'ratio':>7} "
                f"{'resid_px':>9}  flags"
            )
            for r in worst:
                flags = ",".join(
                    f
                    for f in ("failed", "failover", "warp_rescued")
                    if r.get(f)
                ) or "-"
                resid = r.get("rms_residual_px")
                lines.append(
                    f"  {r['frame']:>7} {r.get('n_inliers', 0):>8} "
                    f"{(r.get('inlier_ratio') or 0):>7.3f} "
                    f"{'-' if resid is None else f'{resid:9.3f}'}  {flags}"
                )

    rb = run.get("robustness")
    if rb:
        lines.append("")
        lines.append(
            "Robustness ladder: "
            f"io_retries={rb.get('io_retries', 0)} "
            f"device_retries={rb.get('device_retries', 0)} "
            f"backend_failovers={rb.get('backend_failovers', 0)} "
            f"failed_frames={rb.get('failed_frames', 0)} "
            f"rescued_frames={rb.get('rescued_frames', 0)} "
            f"faults_injected={rb.get('faults_injected', 0)}"
        )
        # Serve-plane durability counters appear only when the run was
        # a serve session that touched them (docs/ROBUSTNESS.md).
        serve_bits = []
        if rb.get("journal_saves") or rb.get("journal_failures"):
            serve_bits.append(
                f"journal_saves={rb.get('journal_saves', 0)} "
                f"journal_failures={rb.get('journal_failures', 0)}"
            )
        if rb.get("deduped_frames"):
            serve_bits.append(f"deduped_frames={rb['deduped_frames']}")
        if rb.get("resumed_from_frame", -1) >= 0:
            serve_bits.append(
                f"resumed_from_frame={rb['resumed_from_frame']}"
            )
        if serve_bits:
            lines.append("  serve durability: " + " ".join(serve_bits))
        if rb.get("quarantined_parts"):
            lines.append(
                f"  quarantined checkpoint parts: {rb['quarantined_parts']}"
            )
    lines.append("")
    lines.extend(_deadline_qos_table(run.get("timing")))
    lines.append("")
    lines.extend(_critical_path_table(run.get("spans")))
    return "\n".join(lines) + "\n"


def _worst_frames(records: list[dict], top: int) -> list[dict]:
    """Failed frames first, then lowest inlier ratio, residual as the
    tiebreak (descending badness)."""

    def badness(r):
        resid = r.get("rms_residual_px")
        return (
            0 if r.get("failed") else 1,
            r.get("inlier_ratio") if r.get("inlier_ratio") is not None else 0,
            -(resid if resid is not None else 0.0),
        )

    ranked = sorted(records, key=badness)
    return ranked[: max(0, int(top))]


def _load_maybe_check(path: str) -> dict | None:
    """The artifact if it is a `kcmc check --json` report, else None.

    A check report is one JSON object with kind == "kcmc_check";
    frame-records JSONLs (multi-line) and npz (binary) both fail the
    single-object parse, so misdetection is structurally impossible."""
    if path.endswith(".npz"):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            obj = json.loads(f.read(1 << 22))
    except (OSError, UnicodeDecodeError, ValueError):
        return None
    if isinstance(obj, dict) and obj.get("kind") == "kcmc_check":
        return obj
    return None


def render_check(obj: dict) -> str:
    """One summary line (+ any new findings) for a check artifact."""
    ok = bool(obj.get("ok"))
    lines = [
        "kcmc check: "
        f"{obj.get('findings', 0)} findings "
        f"({obj.get('baselined', 0)} baselined, "
        f"{obj.get('new', 0)} new, "
        f"{obj.get('new_errors', 0)} new errors, "
        f"{obj.get('stale_baseline', 0)} stale baseline) -> "
        f"{'OK' if ok else 'FAIL'}"
    ]
    for f in obj.get("new_findings", []):
        lines.append(
            f"  {f.get('path')}:{f.get('line')}: {f.get('severity')} "
            f"[{f.get('rule')}] {f.get('message')}"
        )
    return "\n".join(lines)


def main(path: str, top: int = 10, as_json: bool = False) -> int:
    import sys
    import zipfile

    check = _load_maybe_check(path)
    if check is not None:
        if as_json:
            print(json.dumps(check))
        else:
            print(render_check(check))
        return 0
    try:
        run = load_run(path)
    except (
        OSError,
        ValueError,  # covers json.JSONDecodeError + np.load refusals
        UnicodeDecodeError,
        zipfile.BadZipFile,
    ) as e:
        print(
            f"kcmc report: {path!r} is not a readable run artifact "
            f"(expected a --frame-records JSONL, a `correct "
            f"--transforms` .npz, or a trace span shard): {e}",
            file=sys.stderr,
        )
        return 2
    if as_json:
        print(json.dumps(_json_summary(run, top)))
    else:
        print(render_report(run, top=top), end="")
    return 0


def _json_summary(run: dict, top: int) -> dict:
    records = run.get("records") or []
    metrics = {}
    for key, label in _METRICS:
        vals = _metric_values(records, key)
        if len(vals):
            metrics[label] = {
                f"p{p}": float(v)
                for p, v in zip(_PCTS, np.percentile(vals, _PCTS))
            }
    timing = run.get("timing")
    return {
        "source": run.get("source"),
        "n_frames": len(records),
        "manifest": run.get("manifest"),
        "timing": timing,
        "robustness": run.get("robustness"),
        # the request-latency section, surfaced top-level with the
        # SAME schema as the serve `metrics` verb (one schema,
        # asserted in tests); None on pre-latency-plane artifacts
        "latency": (timing or {}).get("latency"),
        # the Deadline QoS scorecard (class, hits/misses, preempted
        # dispatches); None on pre-QoS artifacts and batch-class runs
        "deadline_qos": (timing or {}).get("deadline_qos"),
        "metrics": metrics,
        "worst_frames": [
            r.get("frame") for r in _worst_frames(records, top)
        ],
        # dominant-segment histogram from span shards; None on every
        # pre-tracing artifact (the text report renders "—")
        "critical_path": _critical_path_summary(run.get("spans")),
        "incomplete": bool(run.get("incomplete")),
    }
