"""SLO engine: declarative objectives + multi-window burn rate.

The latency histograms (obs/latency.py) already merge EXACTLY —
integer bucket counts, associative, commutative — which means "what
fraction of requests beat threshold T in window W" is computable by
*subtracting two cumulative snapshots*, with no sampling error and no
float drift. This module turns that into alerting:

* `Objective` — a declarative target parsed from the config's
  `slo_objectives` spec string. Two kinds:
  - latency: ``rung:threshold_s:fraction`` — "fraction of requests
    on this QoS rung complete under threshold_s" (measured on the
    `request.total` segment);
  - availability: ``avail:fraction`` — "fraction of submitted frames
    are served, not rejected".
  Objectives are ';'-separated: ``"full:0.25:0.99;avail:0.999"``.
* `SLOEngine` — per-window rings of (timestamp, good, total)
  cumulative snapshots. The burn rate over window W is
  ``bad_fraction(W) / error_budget`` where error_budget =
  1 - target fraction: burn 1.0 consumes the budget exactly at the
  sustainable rate, burn 14.4 exhausts a 30-day budget in 2 days.
  Windows follow the standard multi-window pattern: fast 5m/1h pages
  on sudden burn, slow 6h/3d catches slow leaks.
* Surfacing — `gauges()` becomes the `slo` section of the `metrics`
  verb (rendered as `kcmc_slo_*` in the Prometheus exposition),
  `heartbeat()` is one short line for the aggregate heartbeat, and
  `alerts()` yields page/ticket lines for the router's alert log
  (both windows of a pair must burn — the standard AND — so a blip
  never pages).

Stdlib-only; the engine never touches the scheduler's locks — it is
fed already-snapshotted histogram dicts.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from collections import deque

from .latency import _EDGES_NS

# Multi-window ladder (seconds). Fast windows page, slow windows
# ticket; pairs are ANDed in `alerts()`.
WINDOWS: dict[str, float] = {
    "5m": 300.0,
    "1h": 3600.0,
    "6h": 21600.0,
    "3d": 259200.0,
}

# Burn thresholds per window pair (Google SRE workbook defaults,
# scaled to a 30-day budget): page when both fast windows burn at
# 14.4x, ticket when both slow windows burn at 1x.
PAGE_BURN = 14.4
TICKET_BURN = 1.0

_SAMPLES_PER_WINDOW = 64  # ring resolution: W/64 between snapshots

# The segment latency objectives measure: end-to-end, submit→fetched.
_LATENCY_SEGMENT = "request.total"


class Objective:
    """One declarative target. kind is "latency" (rung + threshold_s
    + target) or "availability" (target only). Latency objectives
    carry a `qos_class`: the "latency" rung IS the latency scheduling
    class (serve/session.py records latency-class streams under it),
    and every other rung — "full", "degraded", or the "batch"
    pseudo-rung that folds both — measures batch-class traffic, so
    per-class SLOs need no grammar beyond the existing rung slot."""

    __slots__ = (
        "kind", "rung", "threshold_s", "target", "name", "qos_class"
    )

    def __init__(self, kind, target, rung=None, threshold_s=None):
        self.kind = kind
        self.target = float(target)
        self.rung = rung
        self.threshold_s = threshold_s
        self.qos_class = None
        if kind == "latency":
            self.name = f"latency_{rung}_lt_{threshold_s:g}s"
            self.qos_class = "latency" if rung == "latency" else "batch"
        else:
            self.name = "availability"

    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)

    def describe(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.kind == "latency":
            d["rung"] = self.rung
            d["threshold_s"] = self.threshold_s
            d["qos_class"] = self.qos_class
        return d


def parse_objectives(spec: str) -> list[Objective]:
    """Parse the `slo_objectives` config spec. ';'-separated entries,
    each ``rung:threshold_s:fraction`` (latency) or
    ``avail:fraction`` (availability). Raises ValueError with the
    offending entry on malformed input — config `__post_init__`
    calls this so a bad spec fails at construction, not at alert
    time."""
    objectives: list[Objective] = []
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = [p.strip() for p in entry.split(":")]
        try:
            if parts[0] == "avail":
                if len(parts) != 2:
                    raise ValueError
                target = float(parts[1])
                if not 0.0 < target < 1.0:
                    raise ValueError
                objectives.append(Objective("availability", target))
            else:
                if len(parts) != 3:
                    raise ValueError
                rung, threshold_s, target = (
                    parts[0], float(parts[1]), float(parts[2]),
                )
                if threshold_s <= 0 or not 0.0 < target < 1.0:
                    raise ValueError
                objectives.append(
                    Objective("latency", target, rung, threshold_s)
                )
        except (ValueError, IndexError):
            raise ValueError(
                f"malformed slo_objectives entry {entry!r} (want"
                f" 'rung:threshold_s:fraction' or 'avail:fraction')"
            ) from None
    return objectives


# The "batch" pseudo-rung: batch-class traffic spans the healthy and
# degraded rungs (a degraded stream is still batch-class work), so a
# batch-class objective folds both — exact, bucket counts are
# integers. The "latency" rung needs no fold: latency-class streams
# record under it natively.
_BATCH_FOLD = ("full", "degraded")


def _good_total_latency(hists: dict, rung: str, threshold_s: float):
    """(good, total) cumulative counts for one latency objective from
    a `plane.histograms`-shaped dict — exact, because bucket counts
    are integers and the threshold is resolved to a bucket edge. A
    request is "good" when its bucket's upper edge ≤ threshold. The
    "batch" pseudo-rung folds the full + degraded rungs (the batch
    QoS class); any concrete rung reads its own series."""
    rungs = hists.get(_LATENCY_SEGMENT) or {}
    sources = _BATCH_FOLD if rung == "batch" else (rung,)
    thr_ns = int(threshold_s * 1e9)
    k = bisect_right(_EDGES_NS, thr_ns)  # buckets [0, k) are good
    good = total = 0
    for r in sources:
        d = rungs.get(r)
        if not isinstance(d, dict):
            continue
        for idx, c in (d.get("counts") or {}).items():
            if int(idx) < k:
                good += int(c)
        total += int(d.get("count", 0))
    return good, total


def _good_total_availability(counters: dict):
    done = int(counters.get("frames_done", 0) or 0)
    rejected = int(counters.get("rejected_frames", 0) or 0)
    return done, done + rejected


class SLOEngine:
    """Multi-window burn-rate engine over cumulative (good, total)
    snapshots. `tick` is cheap (a handful of integer folds, bounded
    rings) and lock-cheap; feed it the already-exported histogram
    dicts from `metrics`/`snapshot` or the fleet merge."""

    def __init__(self, objectives, now=None):
        if isinstance(objectives, str):
            objectives = parse_objectives(objectives)
        self.objectives: list[Objective] = list(objectives)
        self._lock = threading.Lock()
        self._now = now or time.monotonic
        t0 = self._now()
        # Per (objective, window): ring of (t, good, total). Seeded
        # with the zero state so burn is defined from the first tick.
        self._rings: dict[tuple[str, str], deque] = {}
        for obj in self.objectives:
            for w in WINDOWS:
                ring = deque(maxlen=_SAMPLES_PER_WINDOW + 2)
                ring.append((t0, 0, 0))
                self._rings[(obj.name, w)] = ring
        self._last: dict[str, tuple[int, int]] = {
            obj.name: (0, 0) for obj in self.objectives
        }

    def tick(self, hists: dict | None, counters: dict | None) -> None:
        """Fold the current cumulative state into every window ring
        (rate-limited per ring to W/64 so a 3d ring costs the same as
        a 5m ring)."""
        if not self.objectives:
            return
        hists = hists or {}
        counters = counters or {}
        t = self._now()
        with self._lock:
            for obj in self.objectives:
                if obj.kind == "latency":
                    good, total = _good_total_latency(
                        hists, obj.rung, obj.threshold_s
                    )
                else:
                    good, total = _good_total_availability(counters)
                self._last[obj.name] = (good, total)
                for w, w_s in WINDOWS.items():
                    ring = self._rings[(obj.name, w)]
                    min_dt = w_s / _SAMPLES_PER_WINDOW
                    if ring and t - ring[-1][0] < min_dt:
                        continue
                    ring.append((t, good, total))

    def burn_rates(self) -> dict:
        """``{objective: {window: burn}}``. Burn for window W is the
        bad fraction of requests in the last W seconds divided by the
        error budget; 0.0 when the window saw no traffic. The window
        delta uses the newest snapshot at least W old (or the oldest
        held), then adds everything since the last tick via the
        cumulative `_last` state — exact integer subtraction."""
        t = self._now()
        out: dict = {}
        with self._lock:
            for obj in self.objectives:
                cur_good, cur_total = self._last[obj.name]
                per_w: dict = {}
                for w, w_s in WINDOWS.items():
                    ring = self._rings[(obj.name, w)]
                    base = ring[0]
                    for sample in reversed(ring):
                        if t - sample[0] >= w_s:
                            base = sample
                            break
                    d_total = cur_total - base[2]
                    d_good = cur_good - base[1]
                    if d_total <= 0:
                        per_w[w] = 0.0
                    else:
                        bad_frac = (d_total - d_good) / d_total
                        per_w[w] = round(bad_frac / obj.budget(), 4)
                out[obj.name] = per_w
        return out

    # -- surfacing ---------------------------------------------------------

    def gauges(self) -> dict:
        """The `slo` section of the metrics payload: objectives,
        per-window burn rates, and current alert lines."""
        burns = self.burn_rates()
        return {
            "objectives": [o.describe() for o in self.objectives],
            "burn_rates": burns,
            "alerts": self._alerts(burns),
        }

    def _alerts(self, burns: dict) -> list[str]:
        alerts: list[str] = []
        for obj in self.objectives:
            b = burns.get(obj.name) or {}
            if (
                b.get("5m", 0.0) >= PAGE_BURN
                and b.get("1h", 0.0) >= PAGE_BURN
            ):
                alerts.append(
                    f"PAGE slo={obj.name} burn 5m={b['5m']:g}"
                    f" 1h={b['1h']:g} (>= {PAGE_BURN:g})"
                )
            elif (
                b.get("6h", 0.0) >= TICKET_BURN
                and b.get("3d", 0.0) >= TICKET_BURN
            ):
                alerts.append(
                    f"TICKET slo={obj.name} burn 6h={b['6h']:g}"
                    f" 3d={b['3d']:g} (>= {TICKET_BURN:g})"
                )
        return alerts

    def alerts(self) -> list[str]:
        return self._alerts(self.burn_rates())

    def heartbeat(self) -> str:
        """One short line for the aggregate heartbeat: the worst
        (fast, slow) burn across objectives."""
        burns = self.burn_rates()
        if not burns:
            return ""
        fast = max(b.get("5m", 0.0) for b in burns.values())
        slow = max(b.get("6h", 0.0) for b in burns.values())
        n_alerts = len(self._alerts(burns))
        line = f"slo burn 5m={fast:g} 6h={slow:g}"
        if n_alerts:
            line += f" ALERTS={n_alerts}"
        return line


def render_slo_prometheus(slo: dict) -> list[str]:
    """Prometheus lines for an `slo` metrics section: one
    `kcmc_slo_burn_rate` gauge per (objective, window), one
    `kcmc_slo_target` per objective, one `kcmc_slo_alerts` count.
    Returns [] for payloads without the section (pre-PR snapshots)."""
    if not isinstance(slo, dict) or not slo.get("objectives"):
        return []
    # objective -> qos_class, for the per-class labels below (absent
    # on pre-QoS payloads and availability objectives — those lines
    # simply omit the label, so old scrapes keep parsing)
    classes = {
        obj.get("name"): obj.get("qos_class")
        for obj in slo.get("objectives") or []
        if isinstance(obj, dict)
    }

    def _labels(name: str, extra: str = "") -> str:
        qc = classes.get(name)
        cls = f',qos_class="{qc}"' if qc else ""
        return f'objective="{name}"{cls}{extra}'

    lines = [
        "# HELP kcmc_slo_burn_rate Error-budget burn rate per"
        " objective and window (1.0 = sustainable).",
        "# TYPE kcmc_slo_burn_rate gauge",
    ]
    burns = slo.get("burn_rates") or {}
    for name in sorted(burns):
        for w in WINDOWS:
            v = (burns[name] or {}).get(w)
            if v is None:
                continue
            window = f',window="{w}"'
            lines.append(
                f"kcmc_slo_burn_rate{{{_labels(name, window)}}}"
                f" {float(v):.9g}"
            )
    lines.append(
        "# HELP kcmc_slo_target Objective target fraction."
    )
    lines.append("# TYPE kcmc_slo_target gauge")
    for obj in slo.get("objectives") or []:
        if isinstance(obj, dict) and obj.get("name"):
            lines.append(
                f'kcmc_slo_target{{{_labels(obj["name"])}}}'
                f" {float(obj.get('target', 0.0)):.9g}"
            )
    lines.append(
        "# HELP kcmc_slo_alerts Number of currently firing SLO alerts."
    )
    lines.append("# TYPE kcmc_slo_alerts gauge")
    lines.append(f"kcmc_slo_alerts {len(slo.get('alerts') or [])}")
    return lines


__all__ = [
    "PAGE_BURN",
    "TICKET_BURN",
    "WINDOWS",
    "Objective",
    "SLOEngine",
    "parse_objectives",
    "render_slo_prometheus",
]
