"""Per-run observability coordinator.

One object owns the run's tracer, frame-record stream, and heartbeat so
the orchestrator (`corrector.py`) carries a single nullable handle:
`RunTelemetry.begin(...)` returns None when every observability knob is
off — the disabled cost is one `is not None` check per batch — and
otherwise wires:

* the run manifest (obs/manifest.py), embedded in both artifacts;
* a `Tracer` attached to the run's `StageTimer` (stage/stall spans)
  and handed to the dispatch and writer seams;
* a `FrameRecordStream` fed from the drain path (`note_batch`);
* a `Heartbeat` narrating progress/stalls/robustness to stderr.

`finish(timing)` stamps the final timing into the trace metadata and
the records summary line; `close()` (idempotent, called from the
orchestrator's `finally`) guarantees the heartbeat thread is joined
and partial artifacts are flushed even when the run dies — a
post-mortem trace of a crashed run is the whole point.
"""

from __future__ import annotations

import time

from kcmc_tpu.obs.manifest import build_manifest


class RunTelemetry:
    @classmethod
    def begin(
        cls, config, backend=None, backend_name=None, timer=None,
        report=None, total=None,
    ):
        """Construct only when some observability surface is enabled;
        None otherwise (the hot paths test one attribute). The enabled
        predicate lives in `CorrectorConfig.observability_enabled` —
        one definition for this gate and the orchestrator's."""
        if not getattr(config, "observability_enabled", False):
            return None
        return cls(
            config, backend=backend, backend_name=backend_name,
            timer=timer, report=report, total=total,
        )

    def __init__(
        self, config, backend=None, backend_name=None, timer=None,
        report=None, total=None,
    ):
        self.config = config
        self.report = report  # RobustnessReport (may be None)
        self.timer = timer
        self.total = total
        self.frames_done = 0
        self._t0 = time.perf_counter()
        self._finished = False
        self.manifest = build_manifest(
            config=config, backend=backend, backend_name=backend_name
        )
        self.tracer = None
        if getattr(config, "trace_path", None):
            from kcmc_tpu.obs.trace import Tracer

            self.tracer = Tracer(metadata={"manifest": self.manifest})
            if timer is not None:
                timer.tracer = self.tracer
        self.records = None
        if getattr(config, "frame_records_path", None):
            from kcmc_tpu.obs.records import FrameRecordStream

            self.records = FrameRecordStream(
                config.frame_records_path,
                manifest=self.manifest,
                tracer=self.tracer,
            )
        self.heartbeat = None
        if getattr(config, "heartbeat_s", 0) > 0:
            from kcmc_tpu.obs.heartbeat import Heartbeat

            self.heartbeat = Heartbeat(config.heartbeat_s, self._sample)
            self.heartbeat.start()

    def set_total(self, total: int) -> None:
        self.total = int(total)

    def resumed(self, done: int) -> None:
        """The run restored `done` frames from a checkpoint: switch the
        frame-records sink to append mode (the killed run's records are
        the post-mortem — truncating them would destroy the artifact)
        and mark the resume point on the trace."""
        if self.records is not None:
            self.records.mark_resume(done)
        if self.tracer is not None:
            self.tracer.instant(
                "checkpoint_resume", cat="checkpoint",
                args={"done": int(done)},
            )

    # -- drain-path hook ---------------------------------------------------

    def note_batch(
        self, first_frame: int, n: int, host: dict, escalated: bool = False
    ) -> None:
        """Record one drained batch: progress for the heartbeat, a
        frames_done counter sample for the trace, and per-frame quality
        records. `host` is the drained output dict (post-rescue)."""
        self.frames_done += int(n)
        if self.tracer is not None:
            self.tracer.counter("frames_done", {"frames": self.frames_done})
        if self.records is not None:
            from kcmc_tpu.obs.records import records_from_batch

            rep = self.report
            failed = (
                frozenset(rep.failed_frame_indices)
                if rep is not None and rep.failed_frame_indices
                else frozenset()
            )
            failover = (
                frozenset(rep.failover_frame_indices)
                if rep is not None
                and getattr(rep, "failover_frame_indices", None)
                else frozenset()
            )
            self.records.append(
                records_from_batch(
                    int(first_frame),
                    host,
                    model=self.config.model,
                    n=int(n),
                    failed=failed,
                    failover=failover,
                    escalated=escalated,
                )
            )

    def checkpoint_saved(self, done: int) -> None:
        if self.tracer is not None:
            self.tracer.instant(
                "checkpoint_save", cat="checkpoint", args={"done": int(done)}
            )

    # -- heartbeat ---------------------------------------------------------

    def _sample(self) -> str:
        elapsed = max(time.perf_counter() - self._t0, 1e-9)
        done = self.frames_done
        total = f"/{self.total}" if self.total else ""
        parts = [
            f"{done}{total} frames",
            f"{done / elapsed:.1f} fps",
            f"{elapsed:.0f}s elapsed",
        ]
        timer = self.timer
        if timer is not None and timer.stalls:
            # dict() snapshot: this runs on the heartbeat thread while
            # the consumer inserts stall keys; PyDict_Copy is atomic
            # under the GIL, Python-level .items() iteration is not
            stalls = dict(timer.stalls)
            frac = {k: v / elapsed for k, v in stalls.items() if v > 0}
            if frac:
                top = sorted(frac.items(), key=lambda kv: -kv[1])[:3]
                parts.append(
                    "stalls "
                    + " ".join(f"{k}={100 * v:.0f}%" for k, v in top)
                )
        rep = self.report
        if rep is not None and rep.any():
            parts.append(
                f"retries io={rep.io_retries} dev={rep.device_retries} "
                f"failovers={rep.backend_failovers} "
                f"failed={rep.failed_frames}"
            )
        return ", ".join(parts)

    # -- teardown ----------------------------------------------------------

    def finish(self, timing: dict | None = None, error: str | None = None):
        """Stop the heartbeat and flush both artifacts. Idempotent —
        the orchestrator calls it with the final timing on success and
        again (a no-op) from its `finally`; on the error path the
        `finally` call flushes whatever was collected."""
        if self._finished:
            return
        self._finished = True
        if self.heartbeat is not None:
            self.heartbeat.stop()
        summary: dict = {"frames": self.frames_done}
        if timing is not None:
            summary["timing"] = timing
        if self.report is not None and self.report.any():
            summary["robustness"] = self.report.as_dict()
        if error is not None:
            summary["error"] = error
        if self.records is not None:
            try:
                self.records.close(summary=summary)
            except Exception:
                if error is None:  # don't mask the run's own failure
                    raise
        if self.tracer is not None:
            if timing is not None:
                self.tracer.metadata["timing"] = timing
            if error is not None:
                self.tracer.metadata["error"] = error
            try:
                self.tracer.write(self.config.trace_path)
            except Exception:
                if error is None:  # don't mask the run's own failure
                    raise

    def close(self, exc: BaseException | None = None) -> None:
        """`finally`-path teardown: flush with the error recorded when
        the run is unwinding, no-op when finish() already ran."""
        if self._finished:
            return
        self.finish(
            timing=None, error=repr(exc) if exc is not None else "unfinished"
        )
