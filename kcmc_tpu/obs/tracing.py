"""Distributed tracing: one causal trace per request, client to chip.

The per-process `Tracer` (obs/trace.py) answers "where did *this
process* spend time"; the serve fleet needs the cross-process cut —
"where did *this request* spend time, across client, router, replica,
scheduler, and device". This module adds the Dapper-style substrate:

* **Ids** — `mint_trace_id` (128-bit) / `mint_span_id` (64-bit), hex
  strings minted from `os.urandom` so concurrent sessions and threads
  never collide (no shared counter, no lock).
* **Context** — a `trace` field rides every serve-protocol message
  (`serve/proto.py`): ``{"trace_id", "span_id"}`` where `span_id` is
  the *sender's* span, i.e. the parent of whatever the receiver
  records. `child_context` advances the tree one hop.
* **Span shards** — each process appends finished spans to a bounded
  JSONL shard (`SpanShard`), torn-tail tolerant exactly like
  `obs/records.py`: a header line, one JSON object per span, and a
  reader (`read_span_shard`) that yields only complete spans even
  after kill -9 mid-write. A bounded in-memory ring backs the live
  `trace` verb so `kcmc_tpu trace <addr>` works without file access.
* **Collection** — `collect_spans` merges shards (files, dirs, or
  already-loaded lists); `stitch` groups them into per-trace causal
  trees; `critical_path` names the dominant lifecycle segment of each
  request (device vs queue vs migration); `chrome_trace` exports a
  stitched multi-process Chrome trace (wall-clock timestamps, one pid
  row per producing process).
* **Exemplars** — `ExemplarStore` attaches real trace ids to the
  latency histogram buckets (bounded, last-wins per bucket) WITHOUT
  touching `LatencyHistogram.to_dict`: the bit-identity merge
  contract of the histograms is load-bearing for the fleet
  aggregator, so exemplars ride a parallel `exemplars` section of the
  `metrics` payload and the OpenMetrics ``# {trace_id=...}`` suffix.

Span names recorded here are literals from `obs/registry.py`
(TRACE_SPANS / REQUEST_SEGMENTS / FLEET_SPANS); `kcmc check`'s
span-registry pass verifies every emission site.

Everything here is stdlib-only and import-light — the collector and
the `kcmc_tpu trace` CLI must not pull in an accelerator stack.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from bisect import bisect_left
from collections import deque

from .latency import _EDGES_NS, DEFAULT_RUNG

SHARD_KIND = "kcmc_span_shard"
SHARD_VERSION = 1

# Default bound on spans kept per process (ring + file). A request
# emits ~1 span per hop plus ~5 per batch, so 4096 covers hundreds of
# requests; older spans age out of the ring, later spans are dropped
# from the file (counted, never torn).
DEFAULT_SHARD_CAP = 4096

# The per-request lifecycle segments a critical path is computed over
# (request.total excluded: it IS the whole path, not a part of it).
_PATH_SEGMENTS = (
    "request.admission",
    "request.queue_wait",
    "request.batch_form",
    "request.dispatch",
    "request.device",
    "request.drain",
    "request.delivery",
    "fleet.migrate",
)


# -- id minting --------------------------------------------------------------


def mint_trace_id() -> str:
    """128-bit trace id as 32 lowercase hex chars (W3C-width)."""
    return os.urandom(16).hex()


def mint_span_id() -> str:
    """64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


def new_context() -> dict:
    """Root context a client mints per call: fresh trace, root span."""
    return {"trace_id": mint_trace_id(), "span_id": mint_span_id()}


def child_context(parent: dict | None) -> dict | None:
    """Advance the causal tree one hop: same trace, fresh span id,
    the parent's span id preserved as `parent_id`. None in, None out
    (untraced callers stay untraced)."""
    if not parent or not parent.get("trace_id"):
        return None
    return {
        "trace_id": str(parent["trace_id"]),
        "span_id": mint_span_id(),
        "parent_id": str(parent.get("span_id") or ""),
    }


def valid_context(trace) -> dict | None:
    """Validate a wire-side `trace` field: a dict with a non-empty
    string trace_id, or None. Garbage never propagates."""
    if not isinstance(trace, dict):
        return None
    tid = trace.get("trace_id")
    if not isinstance(tid, str) or not tid:
        return None
    out = {"trace_id": tid}
    for k in ("span_id", "parent_id"):
        v = trace.get(k)
        if isinstance(v, str) and v:
            out[k] = v
    return out


# -- span shard (bounded, torn-tail-tolerant JSONL) --------------------------


class SpanShard:
    """Bounded per-process span sink: an in-memory ring (the live
    `trace` verb's source) plus an optional append-only JSONL file
    (the collector's source). Thread-safe; every line is one complete
    JSON object flushed whole, so a kill -9 tears at most the final
    line and `read_span_shard` recovers everything before it.
    """

    def __init__(self, path: str | None = None, cap: int = DEFAULT_SHARD_CAP):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(cap)))
        self._cap = max(1, int(cap))
        self._written = 0
        self.dropped = 0
        self._path = path
        self._fh = None
        if path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")
            if self._fh.tell() == 0:
                header = {
                    "kind": SHARD_KIND,
                    "version": SHARD_VERSION,
                    "pid": os.getpid(),
                    "host": socket.gethostname(),
                }
                self._fh.write(
                    json.dumps(header, allow_nan=False) + "\n"
                )
                self._fh.flush()

    @property
    def path(self) -> str | None:
        return self._path

    # The emitter is named `complete` on purpose: it is the same
    # registry-checked emitter vocabulary as Tracer.complete, so the
    # span-registry pass verifies every literal span name used here.
    def complete(
        self,
        name: str,
        t0: float,
        dur_s: float,
        *,
        trace_id: str | None = None,
        span_id: str | None = None,
        parent_id: str | None = None,
        args: dict | None = None,
    ) -> None:
        """Record one finished span. `t0` is wall-clock epoch seconds
        (time.time) so shards from different processes stitch."""
        span = {
            "name": name,
            "t0": round(float(t0), 6),
            "dur_s": round(float(dur_s), 6),
            "trace_id": trace_id,
            "span_id": span_id or mint_span_id(),
            "parent_id": parent_id,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        }
        if args:
            span["args"] = args
        with self._lock:
            self._ring.append(span)
            if self._fh is not None:
                if self._written < self._cap:
                    try:
                        self._fh.write(
                            json.dumps(span, allow_nan=False) + "\n"
                        )
                        self._fh.flush()
                        self._written += 1
                    except (OSError, ValueError):
                        pass  # a full disk must never fail serving
                else:
                    self.dropped += 1

    # Registry-checked counter emitter (same contract as `complete`):
    # a zero-duration span carrying an increment, so counter series —
    # the dispatch-`why` vocabulary — ride the shard and the span-
    # registry pass verifies every literal name used here.
    def counter(
        self,
        name: str,
        t0: float,
        n: int = 1,
        *,
        trace_id: str | None = None,
        args: dict | None = None,
    ) -> None:
        """Record one counter increment as a zero-duration span."""
        self.complete(
            name,
            t0,
            0.0,
            trace_id=trace_id,
            args={**(args or {}), "n": int(n)},
        )

    def tail(self, n: int | None = None) -> list[dict]:
        """Most recent spans from the in-memory ring (newest last)."""
        with self._lock:
            spans = list(self._ring)
        return spans if n is None else spans[-n:]

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None:
            try:
                fh.close()
            except OSError:
                pass


def read_span_shard(path: str) -> list[dict]:
    """Read one span shard, tolerating a torn tail: yields only
    complete span lines. Raises ValueError only when the header (line
    0) is unparseable — same contract as `obs/records.read_jsonl`."""
    spans: list[dict] = []
    with open(path, "r", encoding="utf-8") as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                if i == 0:
                    raise ValueError(
                        f"{path}: not a span shard (unparseable header)"
                    )
                continue  # torn tail / partial write: skip
            if not isinstance(obj, dict):
                continue
            if obj.get("kind"):
                continue  # header / metadata lines
            if "name" in obj and "dur_s" in obj:
                spans.append(obj)
    return spans


# -- collection / stitching --------------------------------------------------


def collect_spans(sources) -> list[dict]:
    """Merge spans from shard files, directories of shards (every
    ``*.jsonl`` inside), or already-loaded span lists."""
    spans: list[dict] = []
    for src in sources:
        if isinstance(src, list):
            spans.extend(s for s in src if isinstance(s, dict))
        elif os.path.isdir(src):
            for fn in sorted(os.listdir(src)):
                if fn.endswith(".jsonl"):
                    spans.extend(read_span_shard(os.path.join(src, fn)))
        else:
            spans.extend(read_span_shard(src))
    return spans


def stitch(spans) -> dict[str, list[dict]]:
    """Group spans into per-trace causal trees:
    ``{trace_id: [spans sorted by t0]}``. Untraced spans (no
    trace_id) are dropped — they belong to no request."""
    traces: dict[str, list[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: (s.get("t0") or 0.0))
    return traces


def _span_weight(s: dict) -> float:
    """A span's total contribution: batch-level spans carry
    args={"n": frames}, matching SegmentLatencies.observe(n=...) so
    span sums telescope exactly against the histogram sums."""
    n = 1
    args = s.get("args")
    if isinstance(args, dict):
        try:
            n = max(1, int(args.get("n", 1)))
        except (TypeError, ValueError):
            n = 1
    return float(s.get("dur_s") or 0.0) * n


def critical_path(trace_spans) -> dict:
    """Per-request attribution: summed duration per lifecycle
    segment, the dominant one, and the end-to-end total. Device vs
    queue vs migration in one dict."""
    by_seg: dict[str, float] = {}
    total = 0.0
    for s in trace_spans:
        name = s.get("name")
        if name in _PATH_SEGMENTS:
            by_seg[name] = by_seg.get(name, 0.0) + _span_weight(s)
        elif name == "request.total":
            total += _span_weight(s)
    dominant = max(by_seg, key=by_seg.get) if by_seg else None
    if total <= 0.0:
        total = sum(by_seg.values())
    return {"segments": by_seg, "dominant": dominant, "total_s": total}


def slowest(traces: dict[str, list[dict]], n: int = 10) -> list[dict]:
    """Slowest-N requests: ``[{"trace_id", "total_s", "dominant",
    "n_spans"}]`` sorted slowest first."""
    rows = []
    for tid, spans in traces.items():
        cp = critical_path(spans)
        rows.append(
            {
                "trace_id": tid,
                "total_s": cp["total_s"],
                "dominant": cp["dominant"],
                "n_spans": len(spans),
            }
        )
    rows.sort(key=lambda r: -r["total_s"])
    return rows[: max(0, int(n))]


def chrome_trace(spans) -> dict:
    """Stitched multi-process Chrome trace: wall-clock microsecond
    timestamps, the producing process as the pid row, span/trace ids
    in args. Loadable in Perfetto / chrome://tracing."""
    events: list[dict] = []
    pids = set()
    for s in spans:
        pid = int(s.get("pid") or 0)
        pids.add(pid)
        args = dict(s.get("args") or {})
        for k in ("trace_id", "span_id", "parent_id"):
            if s.get(k):
                args[k] = s[k]
        events.append(
            {
                "name": s.get("name"),
                "ph": "X",
                "ts": float(s.get("t0") or 0.0) * 1e6,
                "dur": float(s.get("dur_s") or 0.0) * 1e6,
                "pid": pid,
                "tid": int(s.get("tid") or 0),
                "cat": "trace",
                "args": args,
            }
        )
    for pid in sorted(pids):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0,
                "dur": 0,
                "pid": pid,
                "tid": 0,
                "args": {"name": f"kcmc pid {pid}"},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- exemplars ---------------------------------------------------------------


class ExemplarStore:
    """Bounded last-wins exemplar map: (segment, rung, bucket) →
    {"trace_id", "value_s"}. Lives BESIDE the latency histograms —
    never inside `LatencyHistogram.to_dict`, whose bit-identity is
    the fleet merge contract. Export shape:
    ``{segment: {rung: {bucket_index: {"trace_id", "value_s"}}}}``.
    """

    def __init__(self, cap: int = 256):
        self._lock = threading.Lock()
        self._cap = max(1, int(cap))
        self._by_key: dict[tuple[str, str, int], dict] = {}

    def note(
        self,
        segment: str,
        seconds: float,
        trace_id: str | None,
        rung: str = DEFAULT_RUNG,
    ) -> None:
        """O(1): bucket the observation exactly as LatencyHistogram
        does, then last-wins overwrite. No-op without a trace id."""
        if not trace_id:
            return
        ns = int(seconds * 1e9)
        if ns < 0:
            ns = 0
        idx = bisect_left(_EDGES_NS, ns)
        key = (segment, rung, idx)
        with self._lock:
            if key not in self._by_key and len(self._by_key) >= self._cap:
                # bounded: evict the oldest-inserted entry
                self._by_key.pop(next(iter(self._by_key)))
            self._by_key[key] = {
                "trace_id": trace_id,
                "value_s": round(seconds, 6),
            }

    def export(self) -> dict:
        with self._lock:
            items = list(self._by_key.items())
        out: dict = {}
        for (seg, rung, idx), ex in items:
            out.setdefault(seg, {}).setdefault(rung, {})[str(idx)] = dict(ex)
        return out

    @staticmethod
    def merge_exports(exports) -> dict:
        """Fold exemplar exports last-wins (iteration order wins) —
        the fleet aggregator's exemplar counterpart to the exact
        histogram merge."""
        out: dict = {}
        for exp in exports:
            if not isinstance(exp, dict):
                continue
            for seg, rungs in exp.items():
                if not isinstance(rungs, dict):
                    continue
                for rung, buckets in rungs.items():
                    if not isinstance(buckets, dict):
                        continue
                    dst = out.setdefault(seg, {}).setdefault(rung, {})
                    for idx, ex in buckets.items():
                        if isinstance(ex, dict) and ex.get("trace_id"):
                            dst[str(idx)] = dict(ex)
        return out


def top_exemplar(exemplars: dict, segment: str) -> dict | None:
    """The exemplar from the highest populated bucket of a segment
    (any rung) — the one living next to p99 in `kcmc_tpu top`."""
    best_idx, best = -1, None
    rungs = exemplars.get(segment) or {}
    if not isinstance(rungs, dict):
        return None
    for buckets in rungs.values():
        if not isinstance(buckets, dict):
            continue
        for idx, ex in buckets.items():
            try:
                i = int(idx)
            except (TypeError, ValueError):
                continue
            if i > best_idx and isinstance(ex, dict) and ex.get("trace_id"):
                best_idx, best = i, ex
    return best


__all__ = [
    "DEFAULT_SHARD_CAP",
    "ExemplarStore",
    "SpanShard",
    "child_context",
    "chrome_trace",
    "collect_spans",
    "critical_path",
    "mint_span_id",
    "mint_trace_id",
    "new_context",
    "read_span_shard",
    "slowest",
    "stitch",
    "top_exemplar",
    "valid_context",
]
