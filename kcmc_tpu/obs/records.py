"""Per-frame quality records: a JSONL sidecar stream of registration
diagnostics.

NoRMCorre-style motion-correction practice audits corrections through
per-frame diagnostics — keypoint counts, inlier ratios, residuals —
not just a run-level mean. The pipeline already computes all of them
per batch; `FrameRecordStream` serializes one JSON object per frame to
a sidecar file (`--frame-records PATH`) through the same bounded
background-writer machinery as TIFF writeback (`io/async_writer.py`'s
`AsyncBatchWriter` wrapping a line sink), so record IO overlaps device
compute and a full queue applies backpressure instead of unbounded
memory.

File layout (one JSON object per line):

* line 1 — header: ``{"kind": "kcmc_frame_records", "version": 1,
  "manifest": {...}}`` (the run manifest, obs/manifest.py);
* one record per frame, in frame order:
  ``frame``, ``model``, ``n_keypoints``, ``n_matches``, ``n_inliers``,
  ``inlier_ratio``, ``rms_residual_px``, ``warp_ok``, plus
  ``template_corr``/``coverage`` when quality metrics ran and the
  ``warp_rescued``/``failed``/``failover``/``escalated`` robustness
  flags;
* optional final summary line — ``{"kind": "kcmc_run_summary",
  "timing": {...}, "robustness": {...}}`` (absent if the run died
  before close; `kcmc_tpu report` degrades gracefully).

A checkpoint-resumed run (the obs knobs are resume-signature neutral)
APPENDS to an existing records file instead of truncating the killed
run's post-mortem: a ``{"kind": "kcmc_run_resume", ...}`` marker line
separates the segments. Records at or past the resume cursor are
pruned first — drains outrun checkpoint saves, so the killed run's
tail covers frames the resumed run re-registers — keeping the
one-record-per-frame invariant. Readers skip marker lines.

Non-finite floats are written as JSON ``null`` (bare ``NaN`` tokens are
non-standard JSON and break strict parsers).
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

RECORD_KIND = "kcmc_frame_records"
SUMMARY_KIND = "kcmc_run_summary"
RESUME_KIND = "kcmc_run_resume"

# Keys every record carries (the golden-schema contract).
REQUIRED_RECORD_KEYS = (
    "frame",
    "model",
    "n_keypoints",
    "n_matches",
    "n_inliers",
    "inlier_ratio",
    "rms_residual_px",
    "warp_ok",
    "failed",
    "failover",
    "escalated",
)


def _num(v, ndigits: int = 4):
    """float -> JSON-safe rounded value (non-finite -> None)."""
    f = float(v)
    if not math.isfinite(f):
        return None
    return round(f, ndigits)


def records_from_batch(
    first_frame: int,
    host: dict,
    model: str,
    n: int | None = None,
    failed: frozenset | set = frozenset(),
    failover: frozenset | set = frozenset(),
    escalated: bool = False,
) -> list[dict]:
    """Build per-frame record dicts from one drained batch's host
    output dict (keys as produced by the backends: n_keypoints,
    n_matches, n_inliers, rms_residual, optional template_corr /
    coverage / warp_ok / warp_rescued)."""

    def col(key):
        v = host.get(key)
        return None if v is None else np.asarray(v)

    n_kp = col("n_keypoints")
    n_match = col("n_matches")
    n_in = col("n_inliers")
    resid = col("rms_residual")
    corr = col("template_corr")
    cover = col("coverage")
    ok = col("warp_ok")
    rescued = col("warp_rescued")
    if n is None:
        for c in (n_in, n_match, n_kp, resid, ok):
            if c is not None:
                n = len(c)
                break
        else:
            return []
    recs = []
    for i in range(n):
        frame = int(first_frame + i)
        nm = int(n_match[i]) if n_match is not None else 0
        ni = int(n_in[i]) if n_in is not None else 0
        rec = {
            "frame": frame,
            "model": model,
            "n_keypoints": int(n_kp[i]) if n_kp is not None else 0,
            "n_matches": nm,
            "n_inliers": ni,
            "inlier_ratio": _num(ni / max(nm, 1)),
            "rms_residual_px": _num(resid[i]) if resid is not None else None,
            "warp_ok": bool(ok[i]) if ok is not None else True,
            "failed": frame in failed,
            "failover": frame in failover,
            "escalated": bool(escalated),
        }
        if rescued is not None:
            rec["warp_rescued"] = bool(rescued[i])
        if corr is not None:
            rec["template_corr"] = _num(corr[i])
        if cover is not None:
            rec["coverage"] = _num(cover[i])
        recs.append(rec)
    return recs


def _prune_for_resume(path: str, resume_done: int) -> bool:
    """Rewrite an existing records file for a resume at frame
    `resume_done`: keep the header, structural (`kind`) lines, and
    records for frames BELOW the cursor; drop records the resumed run
    will re-emit (drains outrun checkpoint saves, so the killed run's
    tail overlaps the replay) and any torn partial line. Returns False
    when the file is not a recognizable records file (caller starts
    fresh)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
        first = json.loads(lines[0])
        if not (
            isinstance(first, dict) and first.get("kind") == RECORD_KIND
        ):
            return False
    except (json.JSONDecodeError, UnicodeDecodeError, OSError, IndexError):
        return False
    kept = [lines[0]]
    for line in lines[1:]:
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail from the kill
        if "kind" in obj or int(obj.get("frame", -1)) < resume_done:
            kept.append(line if line.endswith("\n") else line + "\n")
    tmp = path + ".resume-tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.writelines(kept)
    os.replace(tmp, path)
    return True


class _JsonlSink:
    """The inner writer AsyncBatchWriter drives: serializes record
    dicts to JSONL on the WORKER thread (json.dumps stays off the
    consumer/dispatch thread) and appends them to the file.

    With `resume_done` set and an existing file whose first line is a
    valid records header, the sink prunes records >= the resume cursor
    (see _prune_for_resume) and appends — the killed run's records ARE
    the post-mortem artifact — starting with a resume-marker line.
    """

    def __init__(
        self, path: str, header: dict, resume_done: int | None = None
    ):
        self.n_pages = 0  # records written (AsyncBatchWriter protocol)
        mode = "w"
        if (
            resume_done is not None
            and os.path.exists(path)
            and os.path.getsize(path) > 0
            and _prune_for_resume(path, resume_done)
        ):
            mode = "a"
        self._f = open(path, mode, encoding="utf-8")
        if mode == "a":
            self._write_obj(dict(header, kind=RESUME_KIND))
        else:
            self._write_obj(header)

    def _write_obj(self, obj: dict) -> None:
        self._f.write(json.dumps(obj, allow_nan=False))
        self._f.write("\n")

    def append_batch(self, records, n_threads: int = 0) -> None:
        for rec in records:
            self._write_obj(rec)
        self.n_pages += len(records)

    def checkpoint_state(self) -> dict:
        self._f.flush()
        return {"n_records": self.n_pages}

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class FrameRecordStream:
    """Bounded background JSONL writer for per-frame quality records.

    `append(records)` enqueues a drained batch's records and returns
    immediately; one worker thread serializes and writes them in order
    (the `AsyncBatchWriter` pattern — bounded queue, backpressure on
    full, worker errors surface on the consumer thread at the next
    call). `close(summary=)` flushes, appends the run-summary line,
    and closes the file.
    """

    def __init__(
        self,
        path: str,
        manifest: dict | None = None,
        depth: int = 4,
        tracer=None,
    ):
        self.path = str(path)
        self._manifest = manifest
        self._depth = depth
        self._tracer = tracer
        # Lazy open: the file is created at the first append (or at
        # close, so even a run that died pre-drain leaves an artifact).
        # The delay is what lets a checkpoint resume — detected AFTER
        # telemetry is armed but before any batch drains — switch the
        # sink to prune+append mode instead of truncating the killed
        # run's records (mark_resume).
        self._sink = None
        self._writer = None
        self._resume_done: int | None = None
        self._closed = False

    def mark_resume(self, done: int) -> None:
        """Called when the run resumed a checkpoint at frame `done`:
        prune records the replay re-emits and append to the existing
        file rather than truncating it. No-op once the file is open."""
        if self._sink is None:
            self._resume_done = int(done)

    def _ensure_open(self) -> None:
        if self._sink is not None:
            return
        from kcmc_tpu.io.async_writer import AsyncBatchWriter

        header = {"kind": RECORD_KIND, "version": 1}
        if self._manifest is not None:
            header["manifest"] = self._manifest
        self._sink = _JsonlSink(
            self.path, header, resume_done=self._resume_done
        )
        self._writer = AsyncBatchWriter(
            self._sink, depth=self._depth, tracer=self._tracer
        )

    def append(self, records: list[dict]) -> None:
        if records:
            self._ensure_open()
            self._writer.append_batch(records)

    @property
    def n_records(self) -> int:
        """Records DURABLE in the file (lags appends by the queue)."""
        return self._sink.n_pages if self._sink is not None else 0

    def close(self, summary: dict | None = None) -> None:
        """Flush the queue, append the summary line (if any), close.
        Idempotent; a second close's summary is dropped."""
        if self._closed:
            return
        self._closed = True
        self._ensure_open()
        try:
            self._writer.flush()
            if summary is not None:
                self._sink.append_batch(
                    [dict(summary, kind=SUMMARY_KIND)]
                )
        finally:
            self._writer.close()


def read_jsonl(path: str) -> tuple[dict | None, list[dict], dict | None]:
    """Parse a frame-records file -> (header, records, summary).
    Tolerates a torn final line (killed runs)."""
    header, records, summary = None, [], None
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                if i == 0:
                    raise
                continue  # torn tail line from a killed run
            kind = obj.get("kind")
            if i == 0 and kind == RECORD_KIND:
                header = obj
            elif kind == SUMMARY_KIND:
                summary = obj
            elif kind is not None:
                continue  # resume markers / future structural lines
            else:
                records.append(obj)
    return header, records, summary
