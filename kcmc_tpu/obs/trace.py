"""Thread-aware span tracing with Chrome trace-event JSON export.

`Tracer` collects *complete* duration events ("ph": "X"), instants, and
counters from every thread of a run — the consumer thread's stage/stall
spans (`StageTimer` emits into an attached tracer), the dispatch seam's
per-batch spans, and the background writer thread's per-batch
encode+write spans — and exports the Chrome trace-event format that
`chrome://tracing` and Perfetto (ui.perfetto.dev) load directly.

Cost model: a disabled run carries no tracer at all (`timer.tracer is
None` is the only check on the hot path); an enabled run pays one
`time.perf_counter()` pair and one small dict append per span, behind
one lock (spans are tens-per-batch, not per-pixel).

Every exported event carries ``name``/``ph``/``ts``/``dur``/``pid``/
``tid`` (``dur`` is 0 for non-duration phases) — the invariant the
golden-schema tests pin. Timestamps are microseconds since tracer
construction.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time


class Tracer:
    """Collect spans across threads; export Chrome trace-event JSON."""

    def __init__(self, metadata: dict | None = None):
        self._t0 = time.perf_counter()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._thread_names: dict[int, str] = {}
        self.metadata: dict = dict(metadata or {})

    # -- recording ---------------------------------------------------------

    def _append(self, ev: dict) -> None:
        tid = threading.get_ident()
        tname = threading.current_thread().name
        ev["pid"] = self._pid
        ev["tid"] = tid
        with self._lock:
            # thread-name registration shares the event lock — it is
            # already being taken, and the export-side iteration must
            # not race a first-sighting insert
            if tid not in self._thread_names:
                self._thread_names[tid] = tname
            self._events.append(ev)

    def complete(
        self,
        name: str,
        t0: float,
        dur_s: float,
        cat: str = "stage",
        args: dict | None = None,
        trace_id: str | None = None,
        span_id: str | None = None,
        parent_id: str | None = None,
    ) -> None:
        """Record a finished span: `t0` is its start as a
        `time.perf_counter()` value, `dur_s` its duration in seconds.
        Distributed-trace identity (`trace_id`/`span_id`/`parent_id`,
        obs/tracing.py) rides in `args` so Perfetto shows which fleet
        request a process-local span served — the golden event schema
        (name/ph/ts/dur/pid/tid) is untouched."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (t0 - self._t0) * 1e6,
            "dur": dur_s * 1e6,
        }
        if trace_id or span_id or parent_id:
            args = dict(args or {})
            for k, v in (
                ("trace_id", trace_id),
                ("span_id", span_id),
                ("parent_id", parent_id),
            ):
                if v:
                    args[k] = v
        if args:
            ev["args"] = args
        self._append(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "stage", args: dict | None = None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.complete(
                name, t0, time.perf_counter() - t0, cat=cat, args=args
            )

    def instant(self, name: str, cat: str = "event", args: dict | None = None):
        """A zero-duration marker (checkpoint saves, escalation flips)."""
        ev = {
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "dur": 0,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name: str, values: dict) -> None:
        """A counter sample (e.g. frames_done over time); `values` maps
        series name -> number."""
        self._append(
            {
                "name": name,
                "cat": "counter",
                "ph": "C",
                "ts": (time.perf_counter() - self._t0) * 1e6,
                "dur": 0,
                "args": {k: float(v) for k, v in values.items()},
            }
        )

    # -- export ------------------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot of recorded events plus per-thread name metadata."""
        with self._lock:
            evs = list(self._events)
            names = sorted(self._thread_names.items())
        for tid, tname in names:
            evs.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "ts": 0,
                    "dur": 0,
                    "pid": self._pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return evs

    def to_json(self) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        return {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "metadata": self.metadata,
        }

    def write(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f)
            f.write("\n")
