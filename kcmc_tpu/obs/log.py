"""The `kcmc_tpu` logger and the advisory-warning routing seam.

The library's advisory diagnostics (rescue-fraction warnings, checkpoint
quarantine, zlib downgrade, degradation-ladder recoveries) historically
went through `warnings.warn(RuntimeWarning)` — correct for library use,
where the host application owns warning policy, but noisy and
unstructured for CLI runs. `advise()` is the one seam both worlds share:

* library default: `warnings.warn` exactly as before (so `pytest.warns`
  contracts and embedder warning filters keep working);
* CLI runs (`setup_cli_logging`, wired to `--verbose`/`--quiet`): the
  same messages flow through `logging.getLogger("kcmc_tpu")` to stderr,
  leaving stdout to the machine-readable JSON summaries.
"""

from __future__ import annotations

import logging
import sys
import warnings

LOGGER_NAME = "kcmc_tpu"

# Flipped by setup_cli_logging(); module state rather than logger state
# so library embedders who attach their OWN handlers to "kcmc_tpu"
# don't silently lose the warnings.warn behavior they may filter on.
_route_to_logger = False

# Tag attribute marking handlers we installed, so repeated
# setup_cli_logging calls replace rather than stack them.
_HANDLER_TAG = "_kcmc_cli_handler"


def get_logger(name: str | None = None) -> logging.Logger:
    """The package logger (or a named child, e.g. ``heartbeat``)."""
    return logging.getLogger(
        LOGGER_NAME if not name else f"{LOGGER_NAME}.{name}"
    )


def advise(
    message: str,
    category: type[Warning] = RuntimeWarning,
    stacklevel: int = 2,
) -> None:
    """Emit an advisory diagnostic.

    Routed through the `kcmc_tpu` logger at WARNING level when CLI
    logging is configured (`setup_cli_logging`), else through
    `warnings.warn` — the library's historical behavior.
    """
    if _route_to_logger:
        get_logger().warning(message)
    else:
        warnings.warn(message, category, stacklevel=stacklevel + 1)


def cli_logging_active() -> bool:
    return _route_to_logger


def setup_cli_logging(
    verbose: int = 0, quiet: int = 0, stream=None
) -> logging.Logger:
    """Configure the `kcmc_tpu` logger for a CLI process.

    Logs go to stderr (stdout stays machine-readable JSON). `verbose`
    and `quiet` are repeat counts: the base level is WARNING; each
    ``-v`` lowers it one step (INFO, then DEBUG) and each ``-q`` raises
    it one step (ERROR, then CRITICAL). Also routes `advise()`
    diagnostics through the logger instead of `warnings.warn`.
    Idempotent: repeated calls replace the handler, never stack it.
    """
    global _route_to_logger
    level = logging.WARNING + 10 * (int(quiet) - int(verbose))
    level = min(max(level, logging.DEBUG), logging.CRITICAL)
    logger = logging.getLogger(LOGGER_NAME)
    for h in list(logger.handlers):
        if getattr(h, _HANDLER_TAG, False):
            logger.removeHandler(h)
    handler = logging.StreamHandler(stream if stream is not None else sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s [kcmc %(levelname)s] %(message)s", datefmt="%H:%M:%S"
        )
    )
    # Level filtering happens on loggers only: the heartbeat child sets
    # itself to INFO so explicit --heartbeat output survives the
    # default WARNING level without requiring -v.
    handler.setLevel(logging.NOTSET)
    setattr(handler, _HANDLER_TAG, True)
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False
    _route_to_logger = True
    return logger


def reset_cli_logging() -> None:
    """Undo setup_cli_logging (tests; idempotent)."""
    global _route_to_logger
    logger = logging.getLogger(LOGGER_NAME)
    for h in list(logger.handlers):
        if getattr(h, _HANDLER_TAG, False):
            logger.removeHandler(h)
    logger.setLevel(logging.NOTSET)
    logger.propagate = True
    _route_to_logger = False
