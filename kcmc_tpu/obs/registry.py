"""Canonical telemetry-name registry (docs/ANALYSIS.md, pass 4).

THE vocabulary of the observability surface: every trace-span /
instant / counter name the package emits, and every key of the
`timing` payload (`StageTimer.report` plus the orchestrator's
additions) that `obs/report.py`, `__main__.py`, and `bench.py` render.
`kcmc check`'s span-registry pass enforces both directions — an
emission site using an unregistered literal fails CI, and a registered
name with no remaining emission site is flagged stale — so renaming a
span can never silently drop a series from the report or a Perfetto
dashboard again.

Adding a name: put it in the right group below, then use the same
literal at the emission site and (if rendered) in obs/report.py.
Removing a producer: delete the name here in the same PR, or the
stale-entry warning fires.
"""

from __future__ import annotations

# -- trace spans (Tracer.complete / StageTimer stage+stall) ----------------

# StageTimer.stage(...) intervals: the coarse where-did-time-go view.
STAGE_SPANS = frozenset(
    {
        "prepare_reference",
        "refine_template",
        "register_batches",
        "resume_restore",
        "warp",
    }
)

# StageTimer.stall(...)/add_stall(...) seams: consumer time blocked
# inside a stage on something that should overlap.
STALL_SPANS = frozenset(
    {
        "prefetch_wait",
        "drain_sync",
        "writer_backpressure",
        "writer_flush",
        "template_update",
        # Consumer wait on a not-yet-finished staged H2D upload slot
        # (corrector._dispatch_batches double buffering, PR 18).
        "upload_wait",
    }
)

# Per-batch dispatch + background-writer worker spans.
DISPATCH_SPANS = frozenset({"dispatch_batch"})

# Upload-worker spans (PR 18 double-buffered H2D): one `upload.stage`
# per staged batch on the kcmc-upload worker's track — the host-side
# asarray + ownership copy that now overlaps device execution.
UPLOAD_SPANS = frozenset({"upload.stage"})
WRITER_SPANS = frozenset(
    {
        "writer.append_batch",
        "writer.backpressure",
        "writer.flush",
    }
)

# Plan-runtime compile accounting (plans/runtime.py `timed`): the span
# is `plan_build` inside an ExecutionPlan build, `jit_compile` for a
# lazily triggered inline build.
PLAN_SPANS = frozenset({"plan_build", "jit_compile"})

# Feeder (io/feeder.py): one span per pooled chunk, submit -> fully
# decoded+reassembled (args carry lo/hi/span count). The consumer-side
# wait on an undecoded head chunk still lands in `prefetch_wait`.
FEEDER_SPANS = frozenset({"feeder.decode"})

# Zero-duration instants.
INSTANT_NAMES = frozenset(
    {
        "checkpoint_save",
        "checkpoint_resume",
        "plan_cache_hit",
        "plan_cache_miss",
        # Pipelined-collective breadcrumb (PR 18): one instant per
        # sharded-program build recording the ppermute ring layout
        # (chunks, devices, shape) — the collective itself traces
        # inside the compiled program, invisible to the host tracer.
        "collective.chunk",
    }
)

# Counter series.
COUNTER_NAMES = frozenset({"frames_done"})

# Dispatch-decision explainability counters (serve/scheduler.py,
# docs/SERVING.md "Latency QoS"): every dispatch records exactly one
# `why` — the full vocabulary of reasons a window left the queue.
# Emitted as SpanShard.counter(...) instants when tracing is armed and
# mirrored in the scheduler's `stats` payload; the `why` also rides
# the per-batch request.dispatch span as an arg.
DISPATCH_WHY_COUNTERS = frozenset(
    {
        # the window filled to batch_size — the throughput-optimal case
        "dispatch.why.full_window",
        # head-of-line deadline minus the dispatch horizon went
        # negative: a partial window dispatched NOW on the smallest
        # covering batch-ladder rung
        "dispatch.why.deadline_forced",
        # a latency-class session jumped the weighted round-robin
        "dispatch.why.preempted",
        # a deadline-forced partial deferred by serve_latency_fill_floor
        # fired once the window reached the floor
        "dispatch.why.fill_floor",
        # a partial window with no deadline pressure (tail/trickle
        # drain — the pre-QoS scheduler's only partial case)
        "dispatch.why.flush",
    }
)

# Request-lifecycle latency segments (obs/latency.py): the shared
# vocabulary of the per-request telemetry plane — every
# `SegmentLatencies.observe(...)` site in serve/scheduler.py,
# serve/session.py, and corrector.py uses these literals, and the
# `metrics` verb / `kcmc_tpu report` latency section / `kcmc_tpu top`
# render exactly them. Serve records the full ladder; one-shot runs
# record the dispatch/device/drain subset (no client queue exists).
REQUEST_SEGMENTS = frozenset(
    {
        "request.admission",  # submit entry -> admitted to the queue
        "request.queue_wait",  # admitted -> taken into a batch
        "request.batch_form",  # take_batch stack+pad
        "request.dispatch",  # batch formed -> device dispatch returned
        "request.device",  # dispatch returned -> host materialized
        "request.drain",  # materialized -> session accounting done
        "request.delivery",  # accounted -> fetched by the client
        "request.total",  # submit entry -> fetched (end to end)
    }
)

# Durable-journal DURATION spans (serve/session.py, serve/scheduler.py):
# tracer spans (cat "journal") AND latency segments, so durability cost
# shows up both in Perfetto and in the `metrics` verb. These replaced
# the PR-14 `journal_save`/`journal_resume` instants.
JOURNAL_SPANS = frozenset({"journal.save", "journal.resume"})

# Object-store I/O spans (io/objectstore.py, cat "object"): one
# `object.get` per ObjectStack.read (covers every chunk GET it issued,
# hedges included — args carry lo/hi), one `object.put` per verified
# chunk/manifest upload (args carry key/bytes).
OBJECT_SPANS = frozenset({"object.get", "object.put"})

# Fleet-router DURATION spans (serve/router.py): latency segments the
# router records into its own SegmentLatencies — `fleet.migrate` is
# one whole session migration (pick survivor -> resume_session ->
# tail replay -> rebind), surfaced through the router's `metrics`
# rollup so migration cost is visible fleet-wide. With distributed
# tracing (PR 19) the router also emits the migration as a link span
# into its span shard, carrying the migrated session's trace id so
# the stitched trace crosses replicas.
FLEET_SPANS = frozenset({"fleet.migrate"})

# Distributed-tracing RPC hop spans (obs/tracing.py SpanShard): one
# span per protocol hop of a traced request — the client side of a
# call (serve/client.py), the router forward (serve/router.py), and
# the replica handling it (serve/server.py). Together with the
# REQUEST_SEGMENTS spans the scheduler/session emit per batch, they
# form the causal tree `kcmc_tpu trace` stitches.
TRACE_SPANS = frozenset({"rpc.client", "rpc.router", "rpc.server"})

SPAN_NAMES = (
    STAGE_SPANS
    | STALL_SPANS
    | DISPATCH_SPANS
    | UPLOAD_SPANS
    | WRITER_SPANS
    | PLAN_SPANS
    | FEEDER_SPANS
    | INSTANT_NAMES
    | COUNTER_NAMES
    | DISPATCH_WHY_COUNTERS
    | REQUEST_SEGMENTS
    | JOURNAL_SPANS
    | FLEET_SPANS
    | TRACE_SPANS
    | OBJECT_SPANS
)

# -- timing payload keys ---------------------------------------------------

# Keys of `CorrectionResult.timing`: StageTimer.report's own output
# plus what the orchestrator/plan layers attach. obs/report.py and the
# CLI summary read EXACTLY these literals.
TIMING_KEYS = frozenset(
    {
        # StageTimer.report
        "stages_s",
        "stage_counts",
        "stage_mean_s",
        "stalls_s",
        "stall_counts",
        "total_s",
        "frames_per_sec",
        # orchestrator attachments
        "robustness",
        "warp_escalated",
        "pipeline",
        "restored_frames",
        # plans/runtime.py snapshot
        "plan_cache",
        # pooled-ingest accounting (io/feeder.py via correct_file)
        "feeder",
        # serve session result timing (serve/session.py; the transport
        # reads n_frames back in serve/server.py close_session)
        "n_frames",
        "elapsed_s",
        # request-latency section (obs/latency.py SegmentLatencies
        # .report(): {"segments": ..., "totals": ...}) — attached by
        # serve session finalize and RunTelemetry.finish, rendered by
        # obs/report.py and the `metrics` verb consumers
        "latency",
        # deadline-QoS section (serve/session.py finalize): qos_class,
        # deadline hit/miss counts, preemption exposure — rendered as
        # the "Deadline QoS" table by obs/report.py; absent on every
        # pre-QoS artifact (the table renders "—", never crashes)
        "deadline_qos",
    }
)
