"""Run manifest: what exactly ran, hashed for cross-run attribution.

A throughput or quality trajectory across PRs/rounds is only
attributable when each artifact records the resolved configuration and
environment it came from. `build_manifest` captures the resolved
`CorrectorConfig` (plus a sha256 of its canonical JSON — two runs with
the same hash ran the same pipeline), package/python/jax versions, the
execution backend's device inventory, and the armed fault plan. It is
embedded in the Chrome-trace metadata, the frame-records JSONL header,
and (in slim form) bench.py's judged output line.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import platform
import sys
import time


def _jsonable(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return repr(v)


def config_digest(config) -> tuple[dict, str]:
    """(resolved config as a JSON-safe dict, sha256 of its canonical
    JSON). Key-sorted serialization so the digest is field-order
    independent."""
    cfg = {
        k: _jsonable(v) for k, v in dataclasses.asdict(config).items()
    }
    canon = json.dumps(cfg, sort_keys=True, separators=(",", ":"))
    return cfg, hashlib.sha256(canon.encode()).hexdigest()


def runtime_versions() -> dict:
    """Package/interpreter/accelerator-stack versions (jax optional —
    report-only processes never force an accelerator import)."""
    from kcmc_tpu import __version__

    out = {
        "kcmc_tpu": __version__,
        "python": platform.python_version(),
        "platform": platform.platform(),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        out["jax"] = getattr(jax, "__version__", "unknown")
        np_mod = sys.modules.get("numpy")
        if np_mod is not None:
            out["numpy"] = np_mod.__version__
    else:
        import numpy as np_mod

        out["numpy"] = np_mod.__version__
    return out


def device_inventory() -> list[dict]:
    """The visible accelerator devices, if jax is already imported and
    initialized cleanly; never *initializes* a backend itself (that can
    dial a wedged tunnel) and never raises."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    try:
        return [
            {
                "id": int(d.id),
                "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", "")),
            }
            for d in jax.devices()
        ]
    except Exception:
        return []


def build_manifest(
    config=None,
    backend=None,
    backend_name: str | None = None,
    extra: dict | None = None,
) -> dict:
    """Assemble the run manifest.

    `backend` may expose a `runtime_info()` seam (both in-tree backends
    do) describing its execution environment; otherwise the generic
    jax device inventory is recorded.
    """
    manifest: dict = {
        "kind": "kcmc_run_manifest",
        "version": 1,
        "created_unix_s": round(time.time(), 3),
        "versions": runtime_versions(),
        "argv": list(sys.argv),
    }
    if backend_name:
        manifest["backend"] = backend_name
    info = getattr(backend, "runtime_info", None)
    if info is not None:
        try:
            manifest["backend_runtime"] = _jsonable(info())
        except Exception:
            pass
    if "backend_runtime" not in manifest:
        devs = device_inventory()
        if devs:
            manifest["backend_runtime"] = {"devices": devs}
    if config is not None:
        cfg, digest = config_digest(config)
        manifest["config"] = cfg
        manifest["config_sha256"] = digest
        manifest["fault_plan"] = cfg.get("fault_plan")
    if extra:
        manifest.update(_jsonable(extra))
    return manifest


def slim_manifest() -> dict:
    """The compact environment stamp bench.py embeds in its judged
    line: versions + first-device identity, no config."""
    out = {"versions": runtime_versions()}
    devs = device_inventory()
    if devs:
        out["device"] = devs[0]
        out["n_devices"] = len(devs)
    return out
