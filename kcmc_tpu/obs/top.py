"""`kcmc_tpu top`: a live terminal dashboard over one serve replica.

Polls the `metrics` and `stats` verbs every refresh interval and
renders a compact view — per-session frames/fps/queue depth, the
supervisor state (strikes, rebuild, scheduler-wedge age), and the
plane's per-segment latency p50/p99 — so an operator watching a
replica sees queue pressure and tail latency move in real time
without Prometheus in the loop. `--once` renders a single frame and
exits (the CI smoke and scripting hook).

Pure stdlib + the bundled ServeClient: no accelerator imports, no
extra threads (the poll loop IS the program), safe to point at a
production replica — both verbs are read-only.
"""

from __future__ import annotations

import time

# ANSI: clear screen + home. Plain writes otherwise — no curses, so
# output also behaves piped into a file or CI log.
_CLEAR = "\x1b[2J\x1b[H"

# Render order for the segment table: the lifecycle ladder first, the
# durability spans last. Anything else (future segments) sorts after.
_SEGMENT_ORDER = (
    "request.admission",
    "request.queue_wait",
    "request.batch_form",
    "request.dispatch",
    "request.device",
    "request.drain",
    "request.delivery",
    "request.total",
    "journal.save",
    "journal.resume",
)


def parse_addr(addr: str, default_port: int = 7733) -> tuple[str, int]:
    """'host:port' | 'host' | ':port' -> (host, port)."""
    addr = (addr or "").strip()
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)
    return addr or "127.0.0.1", default_port


def _ms(v) -> str:
    if v is None:
        return "—"
    return f"{float(v) * 1e3:.1f}ms"


def _seg_rank(seg: str) -> tuple[int, str]:
    try:
        return (_SEGMENT_ORDER.index(seg), seg)
    except ValueError:
        return (len(_SEGMENT_ORDER), seg)


def render(metrics: dict, stats: dict, addr: str) -> str:
    """One dashboard frame (pure dict -> str; unit-testable)."""
    lines: list[str] = []
    g = metrics.get("gauges") or {}
    c = metrics.get("counters") or {}
    sup = stats.get("supervisor") or {}
    lines.append(
        f"kcmc_tpu top — {addr}   "
        f"{time.strftime('%H:%M:%S')}   "
        f"sessions={g.get('sessions_open', 0)} "
        f"inflight={g.get('inflight_batches', 0)} "
        f"queued={g.get('queued_frames', 0)} "
        f"occupancy={g.get('batch_occupancy', 0.0)}"
    )
    wedge = float(sup.get("loop_beat_age_s", g.get("loop_beat_age_s", 0.0)))
    sup_bits = [
        f"frames_done={c.get('frames_done', 0)}",
        f"strikes={sup.get('backend_strikes', g.get('backend_strikes', 0))}",
        "rebuilding="
        + ("yes" if sup.get("backend_rebuilding") else "no"),
        f"rebuilds={sup.get('backend_rebuilds', 0)}",
        f"wedge_age={wedge:.1f}s" + (" WEDGED" if wedge > 30.0 else ""),
    ]
    if c.get("rejected_frames"):
        sup_bits.append(f"rejected={c['rejected_frames']}")
    if c.get("degraded_batches"):
        sup_bits.append(f"degraded_batches={c['degraded_batches']}")
    lines.append("supervisor: " + " ".join(sup_bits))

    totals = (metrics.get("plane") or {}).get("totals") or {}
    lines.append("")
    if totals:
        lines.append(
            f"  {'segment':<22} {'count':>8} {'p50':>10} {'p99':>10}"
            f" {'max':>10}"
        )
        for seg in sorted(totals, key=_seg_rank):
            s = totals[seg]
            lines.append(
                f"  {seg:<22} {s.get('count', 0):>8}"
                f" {_ms(s.get('p50_s')):>10} {_ms(s.get('p99_s')):>10}"
                f" {_ms(s.get('max_s')):>10}"
            )
    else:
        lines.append(
            "  (no request latency yet"
            + (
                ""
                if metrics.get("latency_telemetry", True)
                else " — latency_telemetry is OFF on this server"
            )
            + ")"
        )

    sessions = metrics.get("sessions") or {}
    lines.append("")
    lines.append(
        f"  {'session':<12} {'tenant':<12} {'frames':>8} {'fps':>8}"
        f" {'queued':>7} {'deg':>4} {'p50':>10} {'p99':>10}"
    )
    for sid in sorted(sessions):
        s = sessions[sid]
        tot = (s.get("totals") or {}).get("request.total") or {}
        lines.append(
            f"  {sid:<12} {str(s.get('tenant', '?')):<12}"
            f" {s.get('frames', 0):>8} {s.get('fps', 0.0):>8.1f}"
            f" {s.get('queued', 0):>7}"
            f" {'yes' if s.get('degraded') else 'no':>4}"
            f" {_ms(tot.get('p50_s')):>10} {_ms(tot.get('p99_s')):>10}"
        )
    if not sessions:
        lines.append("  (no live sessions)")
    return "\n".join(lines) + "\n"


def main(args) -> int:
    """`kcmc_tpu top` body (argparse args from __main__): poll
    metrics+stats, render, repeat. `--once` prints one frame (exit 1
    if the server is unreachable); the live loop keeps retrying a
    flapping server and exits 0 on Ctrl-C."""
    import sys

    from kcmc_tpu.serve.client import ServeClient, ServeError

    host, port = parse_addr(args.addr)
    addr = f"{host}:{port}"
    interval = max(float(args.interval), 0.2)
    client = None
    try:
        while True:
            try:
                if client is None:
                    client = ServeClient(host=host, port=port)
                frame = render(client.metrics(), client.stats(), addr)
            except (ServeError, OSError) as e:
                if client is not None:
                    client.close()
                    client = None
                if args.once:
                    print(f"kcmc top: {addr} unreachable: {e}",
                          file=sys.stderr)
                    return 1
                frame = (
                    f"kcmc_tpu top — {addr}   (unreachable: {e}; "
                    "retrying)\n"
                )
            if args.once:
                print(frame, end="")
                return 0
            print(_CLEAR + frame, end="", flush=True)
            time.sleep(interval)
    except KeyboardInterrupt:
        print()
        return 0
    finally:
        if client is not None:
            client.close()
