"""`kcmc_tpu top`: a live terminal dashboard over serve replicas.

Polls the `metrics` and `stats` verbs every refresh interval and
renders a compact view — per-session frames/fps/queue depth, the
supervisor state (strikes, rebuild, scheduler-wedge age), and the
plane's per-segment latency p50/p99 — so an operator watching a
replica sees queue pressure and tail latency move in real time
without Prometheus in the loop. `--once` renders a single frame and
exits (the CI smoke and scripting hook).

Fleet mode: pass several `host:port` targets (or one router address —
a router's `metrics` payload is already fleet-merged) and top scrapes
each and exact-merges the payloads via the serve/fleet.py histogram
contract into ONE dashboard, with a per-replica health block.

Pure stdlib + the bundled ServeClient: no accelerator imports, no
extra threads (the poll loop IS the program), safe to point at a
production replica — both verbs are read-only.
"""

from __future__ import annotations

import time

# ANSI: clear screen + home. Plain writes otherwise — no curses, so
# output also behaves piped into a file or CI log.
_CLEAR = "\x1b[2J\x1b[H"

# Render order for the segment table: the lifecycle ladder first, the
# durability spans last. Anything else (future segments) sorts after.
_SEGMENT_ORDER = (
    "request.admission",
    "request.queue_wait",
    "request.batch_form",
    "request.dispatch",
    "request.device",
    "request.drain",
    "request.delivery",
    "request.total",
    "journal.save",
    "journal.resume",
    "fleet.migrate",
)


def parse_addr(addr: str, default_port: int = 7733) -> tuple[str, int]:
    """'host:port' | 'host' | ':port' -> (host, port)."""
    addr = (addr or "").strip()
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)
    return addr or "127.0.0.1", default_port


def _ms(v) -> str:
    if v is None:
        return "—"
    return f"{float(v) * 1e3:.1f}ms"


def _seg_rank(seg: str) -> tuple[int, str]:
    try:
        return (_SEGMENT_ORDER.index(seg), seg)
    except ValueError:
        return (len(_SEGMENT_ORDER), seg)


def render(metrics: dict, stats: dict, addr: str) -> str:
    """One dashboard frame (pure dict -> str; unit-testable)."""
    lines: list[str] = []
    g = metrics.get("gauges") or {}
    c = metrics.get("counters") or {}
    sup = stats.get("supervisor") or {}
    lines.append(
        f"kcmc_tpu top — {addr}   "
        f"{time.strftime('%H:%M:%S')}   "
        f"sessions={g.get('sessions_open', 0)} "
        f"inflight={g.get('inflight_batches', 0)} "
        f"queued={g.get('queued_frames', 0)} "
        f"occupancy={g.get('batch_occupancy', 0.0)}"
    )
    wedge = float(sup.get("loop_beat_age_s", g.get("loop_beat_age_s", 0.0)))
    sup_bits = [
        f"frames_done={c.get('frames_done', 0)}",
        f"strikes={sup.get('backend_strikes', g.get('backend_strikes', 0))}",
        "rebuilding="
        + ("yes" if sup.get("backend_rebuilding") else "no"),
        f"rebuilds={sup.get('backend_rebuilds', 0)}",
        f"wedge_age={wedge:.1f}s" + (" WEDGED" if wedge > 30.0 else ""),
    ]
    if c.get("rejected_frames"):
        sup_bits.append(f"rejected={c['rejected_frames']}")
    if c.get("degraded_batches"):
        sup_bits.append(f"degraded_batches={c['degraded_batches']}")
    lines.append("supervisor: " + " ".join(sup_bits))

    # Deadline QoS header line — only when the plane has QoS activity
    # (pre-QoS replicas and idle batch-only planes stay one line
    # shorter). Stats block preferred; flat counters are the fleet
    # fallback (merge_fleet_metrics sums them across replicas).
    dq = stats.get("deadline_qos") or {}
    pre = int(dq.get("preemptions", c.get("preemptions", 0)))
    starve = int(
        dq.get("starvation_grants", c.get("starvation_grants", 0))
    )
    rej = int(
        dq.get(
            "rejected_deadline_submits",
            c.get("rejected_deadline_submits", 0),
        )
    )
    hits = int(dq.get("deadline_hits", c.get("deadline_hits", 0)))
    misses = int(dq.get("deadline_misses", c.get("deadline_misses", 0)))
    if pre or starve or rej or hits or misses:
        rate = (
            f"{100.0 * hits / (hits + misses):.1f}%"
            if (hits + misses) else "—"
        )
        lines.append(
            "deadline qos: "
            f"hit_rate={rate} ({hits}/{hits + misses}) "
            f"preemptions={pre} starvation_grants={starve} "
            f"admission_rejects={rej}"
        )

    # Fleet block: present when the payload came from a router (or
    # was merged from several replicas by the multi-target poll).
    fleet = metrics.get("fleet")
    if fleet and fleet.get("replicas"):
        lines.append(
            f"fleet: {fleet.get('n_replicas', 0)} replicas, "
            f"{fleet.get('n_healthy', 0)} healthy"
        )
        for rid in sorted(fleet["replicas"]):
            r = fleet["replicas"][rid]
            rg = r.get("gauges") or {}
            lines.append(
                f"  {rid:<22} {str(r.get('state', '?')):<10}"
                f" sessions={rg.get('sessions_open', 0)}"
                f" queued={rg.get('queued_frames', 0)}"
                f" inflight={rg.get('inflight_batches', 0)}"
            )

    totals = (metrics.get("plane") or {}).get("totals") or {}
    exemplars = metrics.get("exemplars") or {}
    lines.append("")
    if totals:
        header = (
            f"  {'segment':<22} {'count':>8} {'p50':>10} {'p99':>10}"
            f" {'max':>10}"
        )
        if exemplars:
            header += "  exemplar"
        lines.append(header)
        for seg in sorted(totals, key=_seg_rank):
            s = totals[seg]
            row = (
                f"  {seg:<22} {s.get('count', 0):>8}"
                f" {_ms(s.get('p50_s')):>10} {_ms(s.get('p99_s')):>10}"
                f" {_ms(s.get('max_s')):>10}"
            )
            if exemplars:
                # a trace id FROM the segment's slowest populated
                # bucket — copy it into `kcmc_tpu trace` to see why
                from kcmc_tpu.obs.tracing import top_exemplar

                ex = top_exemplar(exemplars, seg)
                row += f"  {ex['trace_id']}" if ex else "  —"
            lines.append(row)
    else:
        lines.append(
            "  (no request latency yet"
            + (
                ""
                if metrics.get("latency_telemetry", True)
                else " — latency_telemetry is OFF on this server"
            )
            + ")"
        )

    sessions = metrics.get("sessions") or {}
    lines.append("")
    lines.append(
        f"  {'session':<12} {'tenant':<12} {'class':<8} {'frames':>8}"
        f" {'fps':>8} {'queued':>7} {'deg':>4} {'dl-hit':>7}"
        f" {'p50':>10} {'p99':>10}"
    )
    for sid in sorted(sessions):
        s = sessions[sid]
        tot = (s.get("totals") or {}).get("request.total") or {}
        # pre-QoS payloads carry neither field: render "—", never crash
        klass = str(s.get("qos_class") or "—")
        dh = int(s.get("deadline_hits", 0))
        dm = int(s.get("deadline_misses", 0))
        dl_hit = f"{100.0 * dh / (dh + dm):.0f}%" if (dh + dm) else "—"
        lines.append(
            f"  {sid:<12} {str(s.get('tenant', '?')):<12}"
            f" {klass:<8}"
            f" {s.get('frames', 0):>8} {s.get('fps', 0.0):>8.1f}"
            f" {s.get('queued', 0):>7}"
            f" {'yes' if s.get('degraded') else 'no':>4}"
            f" {dl_hit:>7}"
            f" {_ms(tot.get('p50_s')):>10} {_ms(tot.get('p99_s')):>10}"
        )
    if not sessions:
        lines.append("  (no live sessions)")
    return "\n".join(lines) + "\n"


def _merge_stats(stats_by: dict) -> dict:
    """Fleet view of N replicas' `stats` supervisor blocks: worst-case
    rollup (max wedge age, summed strikes/rebuilds, any rebuilding) —
    the dashboard header should show the sickest replica's numbers."""
    sup = {
        "backend_strikes": 0,
        "backend_rebuilds": 0,
        "backend_rebuilding": False,
        "loop_beat_age_s": 0.0,
    }
    dq = {
        "preemptions": 0,
        "starvation_grants": 0,
        "rejected_deadline_submits": 0,
        "deadline_hits": 0,
        "deadline_misses": 0,
    }
    for st in stats_by.values():
        s = (st or {}).get("supervisor") or {}
        sup["backend_strikes"] += int(s.get("backend_strikes", 0))
        sup["backend_rebuilds"] += int(s.get("backend_rebuilds", 0))
        sup["backend_rebuilding"] |= bool(s.get("backend_rebuilding"))
        sup["loop_beat_age_s"] = max(
            sup["loop_beat_age_s"], float(s.get("loop_beat_age_s", 0.0))
        )
        d = (st or {}).get("deadline_qos") or {}
        for k in dq:
            dq[k] += int(d.get(k, 0))
    return {"supervisor": sup, "deadline_qos": dq}


def main(args) -> int:
    """`kcmc_tpu top` body (argparse args from __main__): poll
    metrics+stats, render, repeat. One target renders that replica
    (or router — a router's payload already carries the fleet block);
    several targets are scraped individually and exact-merged
    client-side (serve/fleet.py merge contract) into one fleet
    dashboard. `--once` prints one frame (exit 1 when every target is
    unreachable); the live loop keeps retrying flapping targets and
    exits 0 on Ctrl-C."""
    import sys

    from kcmc_tpu.serve.client import ServeClient, ServeError

    raw = getattr(args, "addrs", None) or [args.addr]
    targets = [parse_addr(a) for a in raw]
    addrs = [f"{h}:{p}" for h, p in targets]
    label = addrs[0] if len(addrs) == 1 else (
        f"fleet({len(addrs)}): " + ",".join(addrs)
    )
    interval = max(float(args.interval), 0.2)
    clients: dict[str, ServeClient] = {}
    try:
        while True:
            payloads: dict[str, dict] = {}
            stats_by: dict[str, dict] = {}
            down: dict[str, str] = {}
            for (host, port), addr in zip(targets, addrs):
                try:
                    c = clients.get(addr)
                    if c is None:
                        c = clients[addr] = ServeClient(
                            host=host, port=port
                        )
                    payloads[addr] = c.metrics()
                    stats_by[addr] = c.stats()
                except (ServeError, OSError) as e:
                    c = clients.pop(addr, None)
                    if c is not None:
                        c.close()
                    down[addr] = str(e)
            if not payloads:
                err = "; ".join(f"{a}: {e}" for a, e in down.items())
                if args.once:
                    print(f"kcmc top: unreachable: {err}",
                          file=sys.stderr)
                    return 1
                frame = (
                    f"kcmc_tpu top — {label}   (unreachable: {err}; "
                    "retrying)\n"
                )
            elif len(addrs) == 1:
                addr = addrs[0]
                frame = render(payloads[addr], stats_by[addr], addr)
            else:
                from kcmc_tpu.serve.fleet import merge_fleet_metrics

                states = {a: "HEALTHY" for a in payloads}
                states.update({a: "UNREACHABLE" for a in down})
                merged = merge_fleet_metrics(payloads, states=states)
                frame = render(merged, _merge_stats(stats_by), label)
            if args.once:
                print(frame, end="")
                return 0
            print(_CLEAR + frame, end="", flush=True)
            time.sleep(interval)
    except KeyboardInterrupt:
        print()
        return 0
    finally:
        for c in clients.values():
            c.close()
