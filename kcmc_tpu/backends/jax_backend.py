"""The TPU-native execution backend: one jitted, vmapped batch program.

This is the heart of the framework (BASELINE.json north star): detect ->
describe -> match -> RANSAC -> warp compiled as a *single* XLA program
over a (B, H, W) frame batch. The reference-frame descriptors are
computed once and closed over as constants of the batch step; RANSAC
keys are folded from the global frame index so results are independent
of batch boundaries and reproducible across runs, devices, and chunk
sizes.

Multi-device execution wraps this same per-batch program in shard_map
(kcmc_tpu.parallel), sharding the batch axis over the mesh — the batch
program itself is mesh-agnostic.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from kcmc_tpu.backends import register_backend
from kcmc_tpu.config import CorrectorConfig
from kcmc_tpu.models import get_model
from kcmc_tpu.ops import piecewise as pw
from kcmc_tpu.ops.describe import describe_keypoints
from kcmc_tpu.ops.detect import detect_keypoints
from kcmc_tpu.ops.match import knn_match
from kcmc_tpu.ops.warp import warp_batch_with_ok, warp_frame_flow, warp_volume


@jax.jit
def _template_corr(
    corrected: jnp.ndarray, ref_frame: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Per-frame Pearson correlation against the reference — the
    standard registration-quality diagnostic — computed over the warp
    coverage mask, so the zeros the warp wrote outside its coverage
    never depress the score (a large exactly-corrected drift scores the
    same ~1.0 as a small one). Frames a bounded warp zeroed entirely
    read ~0 here; the corrector recomputes after a rescue."""
    axes = tuple(range(1, corrected.ndim))
    m = mask.astype(corrected.dtype)
    n = jnp.maximum(jnp.sum(m, axis=axes, keepdims=True), 1.0)
    cm = jnp.sum(corrected * m, axis=axes, keepdims=True) / n
    rm = jnp.sum(ref_frame * m, axis=axes, keepdims=True) / n
    c = (corrected - cm) * m
    r = (ref_frame - rm) * m
    num = jnp.sum(c * r, axis=axes)
    den = jnp.sqrt(jnp.sum(c * c, axis=axes) * jnp.sum(r * r, axis=axes))
    return num / jnp.maximum(den, 1e-12)


@functools.partial(jax.jit, static_argnames=("dtype_name",))
def _cast_corrected(corrected: jnp.ndarray, dtype_name: str) -> jnp.ndarray:
    """Round/clip/cast resampled frames to an integer output dtype ON
    DEVICE (mirrors corrector._cast_output), so the device->host copy
    moves the small integer array instead of float32."""
    from kcmc_tpu.utils.dtypes import int_clip_bounds

    dt = jnp.dtype(dtype_name)
    # Bounds exactly representable in the compute float dtype: clipping
    # int32 against float32(2**31-1)==2**31.0 would wrap boundary values
    # to INT32_MIN on the astype.
    lo, hi = int_clip_bounds(dt, corrected.dtype)
    return jnp.clip(jnp.rint(corrected), lo, hi).astype(dt)


def _sanitize_nonfinite(
    frames: jnp.ndarray, valid_mask: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Replace non-finite pixels with each frame's finite mean (the
    `sanitize_input` config knob; see config.py for the rationale).
    `valid_mask` (broadcastable bool, optional) restricts the mean to
    the valid extent of bucket-padded frames, so the replacement value
    matches what the unpadded frame would have computed (the zero pad
    is finite and never replaced either way)."""
    finite = jnp.isfinite(frames)
    stat = finite if valid_mask is None else finite & valid_mask
    axes = tuple(range(1, frames.ndim))
    n = jnp.maximum(jnp.sum(stat, axis=axes, keepdims=True), 1)
    mean = jnp.sum(jnp.where(stat, frames, 0.0), axis=axes, keepdims=True) / n
    return jnp.where(finite, frames, mean)


def _mask_valid_extent(
    corrected: jnp.ndarray, transforms: jnp.ndarray, valid_hw: jnp.ndarray
) -> jnp.ndarray:
    """Zero warped pixels whose SOURCE sample lies outside the valid
    (h, w) extent of a bucket-padded frame (kcmc_tpu/plans).

    The unbucketed gather warp writes 0 for out-of-bounds samples; on a
    padded canvas those samples land in the zero pad instead and a
    bilinear read straddling the valid edge would blend real pixels
    against pad zeros — up to a pixel-intensity difference along a
    1-px boundary curve. Recomputing the source coverage (one fused
    elementwise pass; ops/warp.coverage_mask with the valid extent)
    restores out-of-bounds-is-zero exactly, for every warp kernel
    family."""
    from kcmc_tpu.ops.warp import coverage_mask

    B, H, W = corrected.shape
    return jax.vmap(
        lambda img, M: img * coverage_mask((H, W), M, valid_hw=valid_hw)
    )(corrected, transforms)


@jax.jit
def _blend_template(
    ref_frame: jnp.ndarray,
    frames: jnp.ndarray,
    ok: jnp.ndarray,
    alpha: jnp.ndarray,
) -> jnp.ndarray:
    """Rolling-template blend ON DEVICE: (1 - alpha) * template + alpha *
    mean of the window's successfully-warped frames (the corrector's
    `_rolled_template` math; masked-sum formulation so the program is
    shape-static). An all-out-of-bounds window keeps the template
    unchanged, exactly like the host path."""
    okf = ok.astype(jnp.float32)
    n = jnp.sum(okf)
    w = okf.reshape((-1,) + (1,) * (frames.ndim - 1))
    mean = jnp.sum(frames * w, axis=0) / jnp.maximum(n, 1.0)
    blended = (1.0 - alpha) * ref_frame + alpha * mean
    return jnp.where(n > 0.0, blended, ref_frame)


@functools.partial(jax.jit, static_argnames=("shape",))
def _coverage_matrix(transforms: jnp.ndarray, shape) -> jnp.ndarray:
    from kcmc_tpu.ops.warp import coverage_mask

    return jax.vmap(lambda M: coverage_mask(shape, M))(transforms)


@functools.partial(jax.jit, static_argnames=("shape",))
def _coverage_matrix3d(transforms: jnp.ndarray, shape) -> jnp.ndarray:
    from kcmc_tpu.ops.warp import coverage_mask_3d

    return jax.vmap(lambda M: coverage_mask_3d(shape, M))(transforms)


@functools.partial(jax.jit, static_argnames=("shape",))
def _coverage_field(fields: jnp.ndarray, shape) -> jnp.ndarray:
    from kcmc_tpu.ops.piecewise import upsample_field
    from kcmc_tpu.ops.warp import coverage_mask_flow

    return jax.vmap(
        lambda f: coverage_mask_flow(upsample_field(f, shape))
    )(fields)


_EXPORT_ADVISED = False  # one background-export notice per process


class UploadedBatch:
    """Ownership mark for a frame batch staged on device ahead of its
    dispatch (`JaxBackend.stage_upload` — the double-buffered H2D
    path). `process_batch_async` treats a wrapped buffer as its OWN
    (donation-eligible, no defensive copy): the wrapper exists so a
    pre-staged upload can never be confused with a caller-held device
    array, which must be copied before donation."""

    __slots__ = ("array",)

    def __init__(self, array):
        self.array = array

    @property
    def shape(self):
        return self.array.shape


@functools.cache
def _runtime_error_types() -> tuple:
    """Device-runtime exception types whose instances MAY be transient
    (wedged link, exhausted HBM, preempted donation). The corrector's
    retry engine still gates on the message's status markers
    (utils/faults.classify_transient), so compile/shape errors of the
    same type stay fatal."""
    types = []
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        types.append(XlaRuntimeError)
    except ImportError:
        pass
    try:  # newer jaxlib re-exports under jax.errors
        types.append(jax.errors.JaxRuntimeError)
    except AttributeError:
        pass
    return tuple(types)


@register_backend("jax")
class JaxBackend:
    """XLA-compiled pipeline; runs on TPU (or any JAX backend)."""

    name = "jax"
    # Plugin-seam version flag: the orchestrator passes frame batches in
    # their native dtype (uint16 etc.) only to backends declaring this;
    # the batch program casts to float32 on device.
    accepts_native_dtype = True

    # Robustness seam: exception types the retry engine may classify as
    # transient device errors (message status markers decide per
    # instance). Plugin backends can declare their own tuple.
    @property
    def transient_error_types(self) -> tuple:
        return _runtime_error_types()

    def runtime_info(self) -> dict:
        """Execution-environment description for the run manifest
        (obs/manifest.py): jax version + the device inventory this
        backend will actually dispatch to (the mesh's devices when
        sharded). Never raises — a wedged tunnel must not take down
        the run that is trying to record it."""
        info: dict = {"backend": self.name, "jax": jax.__version__}
        try:
            devs = (
                list(self.mesh.devices.flat)
                if self.mesh is not None
                else jax.devices()
            )
            info["devices"] = [
                {
                    "id": int(d.id),
                    "platform": str(d.platform),
                    "kind": str(getattr(d, "device_kind", "")),
                }
                for d in devs
            ]
            if self.mesh is not None:
                info["mesh_shape"] = {
                    str(k): int(v)
                    for k, v in zip(
                        self.mesh.axis_names, self.mesh.devices.shape
                    )
                }
            if self._plan.enabled:
                info["plan"] = {
                    "buckets": [list(b) for b in self._plan.buckets],
                    "compile_cache_dir": self._plan.cache_dir,
                    "rung": self._plan.rung,
                }
        except Exception:
            pass
        return info

    def plan_cache_stats(self) -> dict:
        """Execution-plan snapshot (bucket routing counters, compile
        events, plan-stamp hits/misses) — lands in timing["plan_cache"],
        the run manifest, and the serve `stats` verb."""
        return self._plan.stats()

    def __init__(self, config: CorrectorConfig, mesh=None, **_options):
        self.config = config
        if mesh is None:
            # Config/CLI/env mesh surface: resolve the 1-D frame-axis
            # mesh here so `MotionCorrector(mesh_devices=N)`, --devices,
            # and KCMC_DEVICES all reach the same sharded path as an
            # explicit `mesh=` (which always wins when passed).
            from kcmc_tpu.parallel.mesh import resolve_mesh

            mesh = resolve_mesh(config.mesh_devices)
        self.mesh = mesh  # jax.sharding.Mesh: shard frame batches over it
        self._batch_fns: dict[Any, Any] = {}
        # K need not divide the mesh: prepare_reference pads the
        # keypoint arrays with masked rows (the pre-round-6 hard
        # divisibility error is gone — see parallel/sharded.py's
        # pad_reference_to_mesh).
        #
        # Execution-plan runtime (kcmc_tpu/plans): shape-bucket routing,
        # the persistent compile cache (compile_cache_dir /
        # KCMC_COMPILE_CACHE — enabled as a construction side effect
        # when configured), and compile accounting (every program's
        # first build is timed, stamped, and traced).
        from kcmc_tpu.plans.runtime import PlanRuntime

        self._plan = PlanRuntime(config, backend_name=self.name, mesh=mesh)
        # Per-shape autotuned tile parameters (plans/autotune.py),
        # resolved once per backend instance per shape at program-build
        # time. {} everywhere tuning is off/inapplicable.
        self._tile_cache: dict[tuple, dict] = {}

    # -- reference preparation --------------------------------------------

    def _mesh_ref(self, ref: dict) -> dict:
        """Mesh-pad a prepared reference's keypoint arrays (masked rows)
        so K divides the device count — a no-op single-chip and when K
        already divides (see parallel/sharded.pad_reference_to_mesh)."""
        if self.mesh is None:
            return ref
        from kcmc_tpu.parallel.sharded import mesh_size, pad_reference_to_mesh

        return pad_reference_to_mesh(ref, mesh_size(self.mesh))

    def prepare_reference(self, ref_frame: np.ndarray) -> dict:
        shape = tuple(int(s) for s in np.shape(ref_frame))
        bucket = self._plan.route(shape) if len(shape) == 2 else None
        return self._prepare_reference_impl(ref_frame, bucket)

    def _get_prep_fn(self, shape, bucketed: bool):
        """The single-scale 2D reference detect+describe as ONE jitted
        (and plan-instrumented) program — the "reference" program of
        the execution plan, so its trace rides the exported-program
        bridge on warm starts and its compile is stamped/accounted like
        the batch program's."""
        key = ("prep", shape, self.config, bucketed)
        fn = self._batch_fns.get(key)
        if fn is None:
            cfg = self.config

            def detect_describe(frame, valid_hw=None):
                kps = detect_keypoints(
                    frame,
                    max_keypoints=cfg.max_keypoints,
                    threshold=cfg.detect_threshold,
                    nms_size=cfg.nms_size,
                    border=cfg.border,
                    harris_k=cfg.harris_k,
                    window_sigma=cfg.harris_window_sigma,
                    cand_tile=cfg.cand_tile,
                    valid_hw=valid_hw,
                )
                desc = describe_keypoints(
                    frame, kps, oriented=cfg.resolved_oriented(),
                    blur_sigma=cfg.blur_sigma,
                    precision=cfg.resolved_match_precision(
                        self._on_accelerator()
                    ),
                )
                return {"xy": kps.xy, "desc": desc, "valid": kps.valid}

            if bucketed:
                def prep(frame, valid_hw):
                    return detect_describe(frame, valid_hw)
            else:
                def prep(frame):
                    return detect_describe(frame)

            fn = self._instrument_program("reference", shape, jax.jit(prep))
            self._batch_fns[key] = fn
        return fn

    def _get_pyramid_prep_fn(self, shape):
        """The MULTI-SCALE reference detect+describe as one jitted,
        plan-instrumented program (the "reference_pyramid" program).

        Before PR 18 the pyramid reference path ran eagerly: the
        pyramid resize, each octave's separately jitted detect and
        describe programs, and the merge dispatched one by one with
        the selected keypoint sets materialized between them. Routing
        the whole `fused_detect_describe` region through
        `_instrument_program` makes it ONE traced program — compile
        accounting, plan stamps, and the exported-program cold-start
        bridge included, exactly like the single-scale "reference"
        program — whose autotuned tilings replay from the plan stamps
        on warm boots."""
        key = ("prep_pyramid", shape, self.config)
        fn = self._batch_fns.get(key)
        if fn is None:
            tiles = self._tile_params(shape)
            on_acc = self._on_accelerator()

            def prep(frame):
                kps, desc = self._detect_describe_2d(
                    frame[None], on_acc, tiles=tiles
                )
                return {
                    "xy": kps.xy[0], "desc": desc[0], "valid": kps.valid[0],
                }

            fn = self._instrument_program(
                "reference_pyramid", shape, jax.jit(prep)
            )
            self._batch_fns[key] = fn
        return fn

    def _prepare_reference_impl(self, ref_frame, bucket) -> dict:
        cfg = self.config
        frame = jnp.asarray(ref_frame, jnp.float32)
        if cfg.sanitize_input:
            # Sanitize at the TRUE shape (before any bucket padding) so
            # the finite-mean replacement value matches the unbucketed
            # path exactly.
            frame = _sanitize_nonfinite(frame[None])[0]
        if frame.ndim == 2:
            if cfg.n_octaves > 1:
                # Multi-scale reference through the SAME fused pyramid
                # region as the batch program (shared octave layout and
                # coordinate convention), as ONE jitted and plan-
                # accounted program — see _get_pyramid_prep_fn.
                prep = self._get_pyramid_prep_fn(
                    tuple(int(s) for s in frame.shape)
                )
                got = prep(frame)
                return self._mesh_ref({
                    "xy": got["xy"], "desc": got["desc"],
                    "valid": got["valid"], "frame": frame,
                })
            valid_hw = None
            plan_frame = frame
            if bucket is not None:
                # Execution-plan bucket routing: detect on the frame
                # zero-padded to the bucket shape, selection masked to
                # the true extent — identical keypoints/descriptors to
                # the unpadded frame (ops/detect.valid_extent_mask),
                # from the BUCKET-shaped compiled programs. The ref
                # dict keeps the true-shape template in "frame" (the
                # host-facing seam: failover, rescue polish, rolling
                # blends, checkpoints) and the padded one in
                # "_plan_frame" (the batch program's canvas).
                h, w = int(frame.shape[0]), int(frame.shape[1])
                if (h, w) != bucket:
                    plan_frame = jnp.pad(
                        frame, ((0, bucket[0] - h), (0, bucket[1] - w))
                    )
                valid_hw = jnp.asarray([h, w], jnp.int32)
            prep = self._get_prep_fn(
                tuple(int(s) for s in plan_frame.shape), bucket is not None
            )
            got = prep(
                plan_frame, *(() if valid_hw is None else (valid_hw,))
            )
            ref = {
                "xy": got["xy"], "desc": got["desc"], "valid": got["valid"],
                "frame": frame,
            }
            if bucket is not None:
                ref["_plan_frame"] = plan_frame
            return self._mesh_ref(ref)
        from kcmc_tpu.ops.detect3d import detect_keypoints_3d
        from kcmc_tpu.ops.describe3d import describe_keypoints_3d

        kps = detect_keypoints_3d(
            frame,
            max_keypoints=cfg.max_keypoints,
            threshold=cfg.detect_threshold,
            border=min(cfg.border, min(frame.shape) // 4),
        )
        desc = describe_keypoints_3d(frame, kps, blur_sigma=cfg.blur_sigma)
        return self._mesh_ref(
            {"xy": kps.xy, "desc": desc, "valid": kps.valid, "frame": frame}
        )

    def update_reference(
        self, ref: dict, tail_corrected, tail_ok, window: int, alpha: float
    ) -> dict:
        """Device-resident rolling-template update (the zero-stall seam).

        `tail_corrected` / `tail_ok`: per-batch corrected-frame and
        warp_ok arrays (device jax.Arrays straight from in-flight batch
        outputs, or host arrays) whose concatenation covers AT LEAST
        the last `window` frames — only the trailing `window` frames
        are blended, frame-exactly. Returns the newly prepared
        reference dict; the blended template rides in ``ref["frame"]``.

        Nothing here synchronizes the device stream or touches the
        host: the blend is one jitted program over arrays that may
        still be executing asynchronously, and the descriptor
        re-extraction reuses `prepare_reference`'s jitted pipeline on
        the device-resident result. Bit-compatibility note: frames the
        bounded warp kernels flagged (warp_ok False) are EXCLUDED from
        the blend here, where the host path blends their per-frame
        exact-warp rescue — identical whenever no frame exceeds the
        warp bounds (the steady-state regime this path exists for).
        """
        if not tail_corrected:
            return ref
        frames = jnp.concatenate(
            [jnp.asarray(c, jnp.float32) for c in tail_corrected]
        )[-window:]
        ok = jnp.concatenate(
            [jnp.asarray(k).astype(bool) for k in tail_ok]
        )[-window:]
        if self.mesh is not None:
            # Mesh runs: the tail arrived frame-SHARDED straight from
            # the in-flight sharded batch outputs; one all-gather per
            # array replicates the averaging window (it is small —
            # `window` frames) so the blend and the reference
            # re-extraction run replicated on every chip, mirroring the
            # host path's semantics exactly. Still no host round trip
            # and no pipeline flush.
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(self.mesh, PartitionSpec())
            frames = jax.device_put(frames, rep)
            ok = jax.device_put(ok, rep)
        blend_shape = tuple(int(s) for s in frames.shape)
        # First-build accounting only (the blend's trace+compile
        # happens inside this dispatch; nothing here blocks the device
        # stream). prepare_reference below keeps its own accounting.
        with self._plan.maybe_timed("update_reference", blend_shape, "float32"):
            new_frame = _blend_template(
                jnp.asarray(ref["frame"], jnp.float32),
                frames,
                ok,
                jnp.float32(alpha),
            )
        return self.prepare_reference(new_frame)

    # -- batch processing --------------------------------------------------

    def process_batch(
        self, frames: np.ndarray, ref: dict, frame_indices: np.ndarray
    ) -> dict:
        """Register+correct a (B, ...) batch against the prepared reference.

        Returns host numpy arrays: transforms/fields, corrected frames,
        per-frame diagnostics.
        """
        out = self.process_batch_async(frames, ref, frame_indices)
        return jax.tree.map(np.asarray, out)

    def stage_upload(self, frames) -> "UploadedBatch":
        """Upload one frame batch to the device AHEAD of dispatch — the
        double-buffered H2D slot (`upload_overlap`).

        Performs exactly the upload work `process_batch_async` would do
        inline for a host batch: the native-dtype `jnp.asarray` onto
        the device, plus the donation defensive copy when the caller
        handed us a live device array (asarray was the identity) on the
        donating single-device path. The result is wrapped in
        `UploadedBatch` as an ownership mark: a staged buffer is OURS
        to donate, so dispatch skips the defensive copy it would
        otherwise need — staging must never ADD a copy to the path it
        accelerates. Thread-safe by construction (pure uploads, no
        backend state), so the corrector runs it on its upload worker
        while the previous batch executes."""
        shape = tuple(frames.shape[1:])
        plan = self._plan
        bucket = plan.route(shape) if plan.active else None
        frames_j = jnp.asarray(frames)
        if (
            frames_j is frames
            and self.mesh is None
            and self.config.donate_buffers
            and (bucket is None or bucket == shape)
        ):
            frames_j = jnp.array(frames_j, copy=True)
        return UploadedBatch(frames_j)

    def process_batch_async(
        self, frames, ref: dict, frame_indices, to_host=True, cast_dtype=None,
        emit_frames=True, seed=None,
    ) -> dict:
        """Dispatch one batch; return the *device* output arrays without
        blocking. With `to_host` (the orchestrator's host-fed path) the
        device->host copies of this batch start immediately so they overlap
        with the compute of later batches (the host<->device link is the
        scarce resource for host-fed stacks); `to_host=False` keeps
        everything on device (device-resident pipelines, benchmarking).

        Frames upload in their NATIVE dtype (a uint16 microscopy batch is
        half the bytes of float32 on the scarce host->device link) and are
        cast to float32 on device by the batch program. `cast_dtype`
        (integer targets) additionally rounds/clips/casts the corrected
        frames on device BEFORE the device->host copy — for a uint16
        stack the two together halve the tunnel traffic in each
        direction.

        `emit_frames=False` (registration-only runs: transform export,
        stabilization pass 1) drops the corrected frames from the
        returned dict so their device->host copy — the dominant
        transfer — never happens. The warp still executes on device
        (it is part of the compiled program, and the quality metrics
        read it); only the transfer is skipped.

        `seed` (warm_start configs, matrix models): a ((d+1, d+1)
        transform, ok-bool) pair — typically the previous batch's last
        transform, still an ASYNC device array — scored as hypothesis
        zero of every frame's consensus (temporal warm start; see
        ops/ransac.consensus_batch). None dispatches an identity seed
        with ok=False, so the compiled signature is seed-invariant."""
        staged = isinstance(frames, UploadedBatch)
        if staged:
            # Pre-staged by `stage_upload` (the double-buffered H2D
            # slot): the buffer is already on device and already OURS —
            # the asarray/defensive-copy ownership logic below ran on
            # the upload worker, so re-running it here would add the
            # copy that staging exists to hide.
            frames = frames.array
        shape = tuple(frames.shape[1:])
        plan = self._plan
        bucket = plan.route(shape) if plan.active else None
        frames_j = frames if staged else jnp.asarray(frames)
        if (
            not staged
            and frames_j is frames
            and self.mesh is None
            and self.config.donate_buffers
            and (bucket is None or bucket == shape)
        ):
            # The register program donates its frame buffer (arg 0).
            # A host batch just uploaded is ours to give away; a
            # caller-passed DEVICE array (asarray was the identity) is
            # the caller's to keep — copy so donation eats the copy.
            # Bucket-PADDED dispatches skip this: jnp.pad below already
            # produces a fresh owned buffer.
            frames_j = jnp.array(frames_j, copy=True)
        valid_hw = None
        if bucket is not None:
            # Execution-plan bucket routing: pad to the smallest
            # covering bucket so this batch hits a warm bucket-shaped
            # executable instead of a fresh per-shape trace; detection
            # is masked to the true extent inside the program and the
            # corrected frames slice back below (parity-clean — see
            # kcmc_tpu/plans and tests/test_plans.py).
            if bucket != shape:
                plan.note_route("bucket_padded")
                frames_j = jnp.pad(
                    frames_j,
                    (
                        (0, 0),
                        (0, bucket[0] - shape[0]),
                        (0, bucket[1] - shape[1]),
                    ),
                )
            else:
                plan.note_route("bucket_exact")
            valid_hw = jnp.asarray(shape, jnp.int32)
            fn = self._get_batch_fn(bucket, bucketed=True)
        else:
            if plan.active and plan.routable(shape):
                plan.note_route("bucket_fallback")
            fn = self._get_batch_fn(shape)
        idx_j = jnp.asarray(frame_indices, jnp.uint32)
        B_caller = None
        if self.mesh is not None:
            from kcmc_tpu.parallel.sharded import (
                mesh_size,
                pad_batch_to_mesh,
                shard_frames,
            )

            # Uneven batches (batch_size % n_devices != 0) pad to the
            # mesh by repeating the last frame — same trick the
            # orchestrator uses for short tails — and outputs slice
            # back below, so any batch size shards.
            frames_j, idx_j, B_in = pad_batch_to_mesh(
                frames_j, idx_j, mesh_size(self.mesh)
            )
            if int(frames_j.shape[0]) != B_in:
                B_caller = B_in
            frames_j = shard_frames(frames_j, self.mesh)
            idx_j = shard_frames(idx_j, self.mesh)
        args = (
            frames_j, ref["xy"], ref["desc"], ref["valid"],
            ref["_plan_frame"] if valid_hw is not None else ref["frame"],
            idx_j,
        )
        if self.config.warm_start and self.config.model != "piecewise":
            dd = 4 if len(shape) == 3 else 3
            if seed is None:
                seed_M = jnp.eye(dd, dtype=jnp.float32)
                seed_ok = jnp.bool_(False)
            else:
                seed_M = jnp.asarray(seed[0], jnp.float32)
                seed_ok = jnp.asarray(seed[1], bool)
            args = args + (seed_M, seed_ok)
        if valid_hw is not None:
            args = args + (valid_hw,)
        out = fn(*args)
        if B_caller is not None:
            out = {k: v[:B_caller] for k, v in out.items()}
        if valid_hw is not None and bucket != shape and "corrected" in out:
            # Slice the corrected frames back to the true extent ON
            # DEVICE, before any D2H copy — downstream (quality
            # metrics, rescue, writers, templates) sees true-shape
            # arrays exactly as on the unbucketed path.
            out = dict(out)
            out["corrected"] = out["corrected"][:, : shape[0], : shape[1]]
        if (
            self.config.quality_metrics
            and "corrected" in out
            and ref.get("frame") is not None
            and not ref.get("_skip_quality")
        ):
            out = dict(out)
            # Plan accounting for the quality helpers: they are their
            # own jitted programs compiled per TRUE shape (not per
            # bucket — they read the sliced-back frames), so their
            # first build is timed/stamped like the register program's
            # and the retrace sentinel can see it (small programs:
            # one ~ms compile per new true shape).
            with plan.maybe_timed("quality", shape, "float32"):
                if "field" in out:
                    mask = _coverage_field(out["field"], shape)
                elif out["transform"].shape[-1] == 4:
                    mask = _coverage_matrix3d(out["transform"], shape)
                else:
                    mask = _coverage_matrix(out["transform"], shape)
                out["template_corr"] = _template_corr(
                    out["corrected"], ref["frame"], mask
                )
                out["coverage"] = jnp.mean(
                    mask.astype(jnp.float32), axis=tuple(range(1, mask.ndim))
                )
        if not emit_frames and "corrected" in out:
            out = dict(out)  # quality metrics above already read it
            del out["corrected"]
        if cast_dtype is not None and "corrected" in out:
            dt = np.dtype(cast_dtype)
            if np.issubdtype(dt, np.integer):
                out = dict(out)
                with plan.maybe_timed("cast", shape, dt.name):
                    out["corrected"] = _cast_corrected(
                        out["corrected"], dt.name
                    )
        if to_host:
            for v in out.values():  # start D2H copies in the background
                if hasattr(v, "copy_to_host_async"):
                    v.copy_to_host_async()
        return out

    def _get_batch_fn(self, shape, bucketed: bool = False):
        key = (shape, self.config, bucketed)
        fn = self._batch_fns.get(key)
        if fn is None:
            fn = self._instrument_program(
                "register", shape, self._build_batch_fn(shape, bucketed)
            )
            self._batch_fns[key] = fn
        return fn

    def _instrument_program(self, program, shape, fn):
        """Compile accounting + exported-program bridging for a hot
        jitted program ("register", "reference").

        The first call per input dtype (each dtype is its own compiled
        executable) runs under the plan runtime's timer —
        `jit_compile`/`plan_build` trace spans, stamp hit/miss
        counters, persistent-cache stamps. With a persistent cache
        configured, the first call also consults the exported-program
        blob cache (plans/exports.py): a hit DESERIALIZES the traced
        program in milliseconds and serves the first calls through
        it — skipping seconds of Python retracing — while a background
        thread warms the ordinary jit path (its XLA compile hits the
        persistent cache) and dispatch swaps over; a miss runs the
        normal trace+compile and exports+primes the blob in the
        background for the next process. Steady state is the plain jit
        call either way, behind one dict lookup per call."""
        import threading

        plan = self._plan
        routes: dict[str, Any] = {}  # dtype -> "jit" | Exported bridge
        lock = threading.Lock()
        use_exports = self.mesh is None  # shard_map programs: jit only

        def specs_of(arrs):
            return [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrs]

        def first_call(lead, args, dt):
            key = plan.program_stamp_key(program, shape, dt)
            exp = None
            if use_exports and plan.cache.persistent:
                from kcmc_tpu.plans.exports import load_exported

                exp = load_exported(plan.cache_dir, key)
            if exp is not None:
                with plan.timed(program, shape, dt):
                    out = exp.call(lead, *args)
                with lock:
                    # Swap-to-jit warming starts at the first STEADY
                    # bridged call (see dispatch), not here: a short
                    # cold-start process that only ever makes one call
                    # should not pay a concurrent dummy execution, and
                    # long-lived processes reach their second call
                    # within one batch anyway.
                    routes[dt] = (exp, specs_of((lead,) + args))
                return out
            with plan.timed(program, shape, dt):
                out = fn(lead, *args)
            with lock:
                routes[dt] = "jit"
            if use_exports and plan.cache.persistent:
                from kcmc_tpu.plans.exports import export_and_prime

                # Non-daemon (a daemon thread killed mid-XLA-compile
                # aborts interpreter teardown), so a short-lived CLI
                # process may visibly wait at exit for this — say so,
                # once per process.
                global _EXPORT_ADVISED
                if not _EXPORT_ADVISED:
                    _EXPORT_ADVISED = True
                    from kcmc_tpu.obs.log import advise

                    advise(
                        "kcmc: exporting freshly compiled programs to "
                        "the plan cache in the background — a short-"
                        "lived process may wait for it at exit; later "
                        "processes start warm",
                        stacklevel=2,
                    )
                specs = specs_of((lead,) + args)
                threading.Thread(
                    target=export_and_prime,
                    args=(plan.cache_dir, key, fn, specs),
                    name="kcmc-plan-export",
                    daemon=False,
                ).start()
            return out

        # Swap-to-jit warming starts only after a few STEADY bridged
        # calls: a short-lived CLI process serves its handful of
        # batches through the exported program and exits immediately —
        # starting the (non-daemon: a daemon thread killed mid-XLA-
        # compile aborts interpreter teardown) retrace+compile thread
        # there would block exit to rebuild a program the process will
        # never use. Long-lived processes cross the threshold within
        # their first seconds of traffic.
        _SWAP_AFTER_CALLS = 4
        swap_calls: dict[str, int] = {}

        def start_swap(dt, exp, specs):
            def warm_jit():
                # Populate the jit dispatch cache off the latency path
                # (one zero-filled call; the XLA compile is a
                # persistent-cache deserialize), then swap steady-state
                # dispatch back to the plain jit call.
                try:
                    dummy = [np.zeros(s.shape, s.dtype) for s in specs]
                    jax.block_until_ready(fn(*dummy))
                except Exception:
                    return  # keep bridging; exp.call stays correct
                with lock:
                    routes[dt] = "jit"

            threading.Thread(
                target=warm_jit, name="kcmc-plan-swap", daemon=False
            ).start()

        def dispatch(lead, *args):
            dt = str(lead.dtype)
            route = routes.get(dt)
            if route == "jit":
                return fn(lead, *args)
            if route is not None:
                exp, specs = route
                n = swap_calls.get(dt, 0) + 1
                swap_calls[dt] = n
                if n == _SWAP_AFTER_CALLS:
                    start_swap(dt, exp, specs)
                return exp.call(lead, *args)  # bridging
            if plan.first_time(program, shape, dt):
                return first_call(lead, tuple(args), dt)
            return fn(lead, *args)

        return dispatch

    def _build_batch_fn(self, shape, bucketed: bool = False):
        """Assemble the LOCAL batch program: stage-wise over the batch —
        vmapped detection, batched descriptor extraction (Pallas patch
        kernel on accelerators), vmapped match + consensus, then the
        batch-level gather-free warp. Batch-level is where the Pallas
        kernels live (their batch axis is a grid axis, which cannot sit
        inside a vmap); the jnp fallbacks fuse identically. Multi-device
        execution wraps the same local program in shard_map.

        `bucketed` builds the execution-plan variant: a trailing
        `valid_hw` (2,) int argument carries the true extent of
        bucket-padded frames through detection masking, the sanitize
        statistics, and the post-warp valid-coverage zeroing — one
        compiled program per BUCKET serves every true shape within it.
        """
        is_3d = len(shape) == 3
        local = (
            self._build_local_3d(shape)
            if is_3d
            else self._build_local_2d(shape, bucketed=bucketed)
        )
        if self.mesh is not None:
            from kcmc_tpu.parallel.sharded import (
                make_sharded_batch_fn,
                mesh_size,
            )
            from kcmc_tpu.plans.runtime import _live_tracers

            # Trailing replicated args: the warm-start seed pair (a
            # shared (d+1, d+1) matrix + () bool) precedes the bucketed
            # valid_hw extent — all tiny, identical on every chip.
            warm = self.config.warm_start and self.config.model != "piecewise"
            chunks = int(self.config.collective_chunks)
            if chunks >= 2:
                # Host-side breadcrumb (collectives trace inside the
                # program, invisible to the host tracer): one instant
                # per sharded-program build recording the ring layout.
                for tr in _live_tracers():
                    tr.instant(
                        "collective.chunk",
                        args={
                            "chunks": chunks,
                            "devices": mesh_size(self.mesh),
                            "shape": list(shape),
                        },
                    )
            return make_sharded_batch_fn(
                local, self.mesh,
                extra_replicated=(2 if warm else 0) + (1 if bucketed else 0),
                collective_chunks=chunks,
            )
        # Buffer donation (the kcmc-check donation-audit contract): the
        # corrected output matches the frame batch's shape/dtype only
        # for float32 uploads (integer batches cast on device, so XLA
        # simply skips the alias for them), and process_batch_async
        # owns the uploaded buffer — a caller-held device array is
        # defensively copied there before dispatch. Halves the frame
        # memory held per in-flight batch (docs/PERFORMANCE.md).
        return jax.jit(local, donate_argnums=self._donate_argnums())

    def _detect_describe_2d(
        self, frames, use_pallas: bool, multi_scale=True, valid_hw=None,
        tiles=None,
    ):
        """The 2D detect+describe stage for a (B, H, W) float32 batch:
        single-scale by default; with `n_octaves > 1`, the ORB scale
        pyramid — per-octave fixed-K detection and description on
        MXU-resized images, merged into one multi-scale keypoint set in
        base coordinates (ops/pyramid.py). Shared by the batch program
        and prepare_reference so reference and frame keypoints always
        come from the same pipeline. `valid_hw` (traced (2,) ints)
        masks selection to the true extent of bucket-padded frames
        (execution plans; single-scale only — bucket routing gates
        pyramid configs out)."""
        cfg = self.config
        from kcmc_tpu.ops.fused import fused_detect_describe

        # Autotuned tilings apply at the tuned (base) frame shape only;
        # other shapes in the same program (pyramid octaves) keep the
        # per-kernel defaults. `tiles` is resolved at BUILD time (the
        # tuning search times candidate kernels — it must never run
        # inside a trace), so it arrives as a plain dict of static
        # ints, keyed by the shape it was tuned for.
        return fused_detect_describe(
            frames,
            max_keypoints=cfg.max_keypoints,
            detect_threshold=cfg.detect_threshold,
            nms_size=cfg.nms_size,
            border=cfg.border,
            harris_k=cfg.harris_k,
            window_sigma=cfg.harris_window_sigma,
            blur_sigma=cfg.blur_sigma,
            cand_tile=cfg.cand_tile,
            oriented=cfg.resolved_oriented(),
            precision=cfg.resolved_match_precision(self._on_accelerator()),
            use_pallas=use_pallas,
            n_octaves=cfg.n_octaves,
            octave_scale=cfg.octave_scale,
            multi_scale=multi_scale,
            valid_hw=valid_hw,
            tiles=tiles,
        )

    def _build_local_2d(self, shape, bucketed: bool = False):
        cfg = self.config
        oriented = cfg.resolved_oriented()
        use_pallas_patches = self._on_accelerator()
        base_key = jax.random.key(cfg.seed)
        is_pw = cfg.model == "piecewise"
        precision = cfg.resolved_match_precision(self._on_accelerator())
        warm = cfg.warm_start and not is_pw
        # Autotuned tile parameters for this shape, resolved NOW (build
        # time — the candidate-timing search must never run inside a
        # trace; see plans/autotune.py).
        tiles = self._tile_params(shape)
        if bucketed and is_pw:
            raise ValueError(
                "bucketed execution covers 2D matrix models only (the "
                "piecewise patch grid spans the frame; routing gates it "
                "out) — this is a routing bug, not a user error"
            )
        if is_pw:
            flow_warp = self._resolve_flow_warp()
            field_warp = self._resolve_field_warp(shape)
        else:
            model = get_model(cfg.model)
            batch_warp = self._resolve_batch_warp(shape)

        banded_geom = None
        if cfg.match_radius is not None:
            from kcmc_tpu.ops.match_banded import make_geometry

            banded_geom = make_geometry(
                shape, cfg.match_radius, cfg.max_keypoints,
                cfg.max_keypoints, tile=cfg.match_tile,
                slack=cfg.match_slack, nms_tile=cfg.cand_tile,
            )

        def core(frames, ref_xy, ref_desc, ref_valid, ref_frame, indices,
                 valid_hw, seed_M=None, seed_ok=None):
            # Frames upload in their native dtype (uint16 stacks halve
            # the host->device bytes); all math runs in float32.
            frames = frames.astype(jnp.float32)
            if valid_hw is None:
                vwarp = batch_warp if not is_pw else None
                valid_rect = None
            else:
                # Bucketed program (execution plans): frames are
                # zero-padded to this bucket; `valid_hw` carries the
                # true (h, w) extent. Three seams keep the padded run
                # parity-clean vs the unpadded one: the sanitize
                # statistics restrict to the valid rect, detection
                # masks selection to it, and every warp's output zeroes
                # pixels whose source sample left it (the unbucketed
                # out-of-bounds-is-zero semantics).
                from kcmc_tpu.ops.warp import valid_rect_mask

                valid_rect = valid_rect_mask(shape, valid_hw)

                def vwarp(fr, Ms):
                    c, ok = batch_warp(fr, Ms)
                    return _mask_valid_extent(c, Ms, valid_hw), ok

            if cfg.sanitize_input:
                frames = _sanitize_nonfinite(frames, valid_rect)
            if banded_geom is not None:
                from kcmc_tpu.ops.match_banded import build_banded_ref

                # Template keypoints bucketed once per batch, shared by
                # every frame's banded match (outside the vmap below).
                bref = build_banded_ref(
                    banded_geom, ref_xy, ref_desc, ref_valid,
                    precision=precision,
                )
            keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(indices)
            kps, desc = self._detect_describe_2d(
                frames, use_pallas_patches, valid_hw=valid_hw, tiles=tiles
            )

            def banded_matches(kps_b, desc_b):
                from kcmc_tpu.ops.match_banded import banded_match

                return jax.vmap(
                    lambda d, xy, v: banded_match(
                        banded_geom,
                        bref,
                        d,
                        xy,
                        v,
                        ratio=cfg.ratio,
                        max_dist=cfg.max_hamming,
                        mutual=cfg.mutual,
                        precision=precision,
                    )
                )(desc_b, kps_b.xy, kps_b.valid)

            if is_pw:
                # The piecewise field estimator keeps its per-frame
                # path (no matrix consensus to fuse into); its matcher
                # still benefits from the precision variants.
                def tail(frame, kp, d, key, m):
                    if m is None:
                        m = knn_match(
                            d,
                            ref_desc,
                            kp.valid,
                            ref_valid,
                            ratio=cfg.ratio,
                            max_dist=cfg.max_hamming,
                            mutual=cfg.mutual,
                            precision=precision,
                        )
                    # Correspondences: reference keypoint -> frame pos.
                    src = ref_xy[m.idx]
                    dst = kp.xy
                    res = pw.estimate_field(
                        src,
                        dst,
                        m.valid,
                        key,
                        grid=cfg.patch_grid,
                        shape=shape,
                        n_global_hyps=cfg.n_hypotheses,
                        patch_hyps=cfg.patch_hypotheses,
                        global_threshold=cfg.global_threshold,
                        patch_threshold=cfg.inlier_threshold,
                        prior=cfg.patch_prior,
                        smooth_sigma=cfg.field_smooth_sigma,
                        passes=cfg.field_passes,
                        refine_reach_scale=cfg.refine_reach_scale,
                        patch_model=cfg.patch_model,
                        refine_hyps=cfg.refine_hypotheses,
                    )
                    return {
                        "n_keypoints": jnp.sum(kp.valid).astype(jnp.int32),
                        "n_matches": jnp.sum(m.valid).astype(jnp.int32),
                        # warping is batch-level for BOTH flow paths now
                        # (the correlation polish needs the warped batch)
                        "field": res.field,
                        "n_inliers": res.n_inliers,
                        "rms_residual": res.rms_residual,
                    }

                def tail_batch(frames_b, kps_b, desc_b, keys_b, sM, sok):
                    del sM, sok  # fields have no transform seed
                    if banded_geom is not None:
                        m = banded_matches(kps_b, desc_b)
                        return jax.vmap(tail)(frames_b, kps_b, desc_b, keys_b, m)
                    return jax.vmap(
                        lambda f, kp, d, k: tail(f, kp, d, k, None)
                    )(frames_b, kps_b, desc_b, keys_b)
            else:
                # Fused match→consensus (PR 13): the Hamming matrices,
                # 2-NN selection, and the budget-laddered hypothesis
                # consensus trace as ONE region over the whole batch —
                # no nested-pjit seam between match and consensus, and
                # (frames × hypotheses) blocked solves/scores instead
                # of B×H per-frame launches (ops/fused.py).
                from kcmc_tpu.ops.fused import fused_match_consensus

                def tail_batch(frames_b, kps_b, desc_b, keys_b, sM, sok):
                    del frames_b
                    m = (
                        banded_matches(kps_b, desc_b)
                        if banded_geom is not None
                        else None
                    )
                    res, n_matches = fused_match_consensus(
                        model,
                        desc_b,
                        kps_b.xy,
                        kps_b.valid,
                        ref_desc,
                        ref_xy,
                        ref_valid,
                        keys_b,
                        ratio=cfg.ratio,
                        max_dist=cfg.max_hamming,
                        mutual=cfg.mutual,
                        precision=precision,
                        n_hypotheses=cfg.n_hypotheses,
                        threshold=cfg.inlier_threshold,
                        refine_iters=cfg.refine_iters,
                        score_cap=cfg.score_cap,
                        budget_rungs=cfg.budget_rungs,
                        early_exit_frac=cfg.early_exit_frac,
                        seed_transform=sM,
                        seed_ok=sok,
                        matches=m,
                    )
                    return {
                        "n_keypoints": jnp.sum(
                            kps_b.valid, axis=1
                        ).astype(jnp.int32),
                        "n_matches": n_matches,
                        "transform": res.transform,
                        "n_inliers": res.n_inliers,
                        "rms_residual": res.rms_residual,
                    }

            out = tail_batch(frames, kps, desc, keys, seed_M, seed_ok)
            if not is_pw and cfg.n_octaves > 1 and cfg.pyramid_refine:
                # Coarse-to-fine: the multi-scale estimate's floor is
                # the coarse octave's localization noise (subpixel
                # error x octave factor in base coords). Warp each
                # frame by the coarse estimate and re-register single-
                # scale: the residual motion is near-identity, so
                # localization is full-resolution. The intermediate
                # warp rides the resolved gather-free batch kernel —
                # for similarity that is the separable chain, whose
                # scale matmuls handle the pyramid's large zooms
                # unbounded (the per-frame GATHER warp used here
                # through round 5's first session cost ~10 ms/frame on
                # TPU and made the pyramid row ~20x slower than
                # single-scale, 75 vs 1505 fps). Frames the bounded
                # kernel flags (rotation beyond the shear bound — far
                # outside the judged regime) skip the fine pass and
                # keep the coarse estimate instead of refining against
                # a zeroed image.
                # Composition: corrected0(p) = frame(M1 p), pass 2
                # gives corrected0 = ref-aligned via M_r, so
                # ref -> frame is M1 @ M_r.
                coarse = out["transform"]
                corrected0, ok0 = vwarp(frames, coarse)
                kps2, desc2 = self._detect_describe_2d(
                    corrected0, use_pallas_patches, multi_scale=False,
                    tiles=tiles,
                )
                keys2 = jax.vmap(
                    lambda k: jax.random.fold_in(k, 1)
                )(keys)
                # Fine pass: residual motion is near-identity, so the
                # caller's temporal seed (which targets the FULL
                # motion) does not apply here.
                out2 = tail_batch(corrected0, kps2, desc2, keys2, None, None)
                coarse_matches = out["n_matches"]
                out = dict(out2)
                eye = jnp.broadcast_to(
                    jnp.eye(3, dtype=coarse.dtype), coarse.shape
                )
                fine = jnp.where(
                    ok0[:, None, None], out2["transform"], eye
                )
                # full-f32 compose: TPU's default einsum precision is
                # bf16-grade, and the coarse matrix carries
                # O(frame-size) translation entries — an unpinned
                # compose alone injects ~0.1-0.5 px of corner error at
                # 512² (the same trap ops/polish.py documents)
                out["transform"] = jnp.einsum(
                    "bij,bjk->bik", coarse, fine,
                    precision=jax.lax.Precision.HIGHEST,
                )
                # standard keys report the FINAL (fine) fit; the coarse
                # pass's match count stays visible for diagnosis
                out["coarse_n_matches"] = coarse_matches
            # Batch-level warp: (corrected, ok) — frames a bounded
            # gather-free kernel could not resample are zeroed and
            # flagged via the per-frame `warp_ok` diagnostic.
            if is_pw:
                out = dict(out)

                def warp_flows(field):
                    if field_warp is not None:  # fused Pallas route
                        return field_warp(frames, field)
                    flows = jax.vmap(
                        lambda f: pw.upsample_field(f, shape)
                    )(field)
                    if flow_warp is not None:
                        return flow_warp(frames, flows)
                    return (
                        jax.vmap(warp_frame_flow)(frames, flows),
                        jnp.ones(frames.shape[0], bool),  # gather: unbounded
                    )

                corrected, ok = warp_flows(out["field"])
                for _ in range(int(cfg.field_polish)):
                    delta = pw.correlation_polish(
                        corrected, ref_frame, cfg.patch_grid
                    )
                    # a frame the bounded flow kernel zeroed has no
                    # pixels to correlate — leave its field alone (the
                    # host rescue re-warps it from the field as-is)
                    delta = jnp.where(
                        ok[:, None, None, None], delta, 0.0
                    )
                    out["field"] = out["field"] + delta
                    corrected, ok = warp_flows(out["field"])
                out["corrected"], out["warp_ok"] = corrected, ok
            else:
                out = dict(out)
                corrected, ok = vwarp(frames, out["transform"])
                for _ in range(int(cfg.transform_polish)):
                    from kcmc_tpu.ops.polish import polish_transforms

                    # Photometric polish: measure the warped frames'
                    # per-region residual shifts against the template,
                    # fit the model family's own update, compose, and
                    # re-warp (ops/polish.py — the piecewise
                    # field_polish mechanism for matrix models).
                    # Frames the bounded kernel zeroed have no pixels
                    # to correlate — keep their transform for the host
                    # rescue path.
                    newM = polish_transforms(
                        corrected, ref_frame, out["transform"],
                        cfg.model, grid=cfg.polish_grid,
                        valid_hw=valid_hw,
                    )
                    out["transform"] = jnp.where(
                        ok[:, None, None], newM, out["transform"]
                    )
                    corrected, ok = vwarp(frames, out["transform"])
                out["corrected"], out["warp_ok"] = corrected, ok
            return out

        # Signature variants: the warm-start seed (a shared (3, 3)
        # matrix + () bool, replicated over the mesh like valid_hw)
        # and the execution-plan valid_hw extent append as trailing
        # replicated args in that order.
        if bucketed and warm:
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices, seed_M, seed_ok, valid_hw):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame,
                    indices, valid_hw, seed_M, seed_ok,
                )
        elif bucketed:
            # Execution-plan variant: the trailing valid_hw (2,) int
            # array rides through shard_map replicated (P() spec).
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices, valid_hw):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame,
                    indices, valid_hw,
                )
        elif warm:
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices, seed_M, seed_ok):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame,
                    indices, None, seed_M, seed_ok,
                )
        else:
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame,
                    indices, None,
                )

        return local

    def _build_local_3d(self, shape):
        cfg = self.config
        base_key = jax.random.key(cfg.seed)
        vol_warp = self._resolve_volume_warp()
        use_pallas = self._on_accelerator()
        precision = cfg.resolved_match_precision(self._on_accelerator())
        warm = cfg.warm_start
        model = get_model(cfg.model)
        if model.ndim != 3:
            raise ValueError(
                f"3D stacks require a 3D model (rigid3d), got {cfg.model!r}"
            )
        from kcmc_tpu.ops.describe3d import describe_keypoints_3d_batch
        from kcmc_tpu.ops.detect3d import detect_keypoints_3d_batch
        from kcmc_tpu.ops.fused import fused_match_consensus

        def core(frames, ref_xy, ref_desc, ref_valid, ref_frame, indices,
                 seed_M=None, seed_ok=None):
            del ref_frame  # 3D path has no photometric polish (yet)
            frames = frames.astype(jnp.float32)  # native-dtype upload
            if cfg.sanitize_input:
                frames = _sanitize_nonfinite(frames)
            keys = jax.vmap(lambda i: jax.random.fold_in(base_key, i))(indices)
            # smooth (the descriptor-stage blur) rides along with the
            # fused detection kernel's resident slab, as in 2D.
            kps, smooth = detect_keypoints_3d_batch(
                frames,
                max_keypoints=cfg.max_keypoints,
                threshold=cfg.detect_threshold,
                border=min(cfg.border, min(shape) // 4),
                use_pallas=use_pallas,
                smooth_sigma=cfg.blur_sigma,
            )
            desc = describe_keypoints_3d_batch(
                frames, kps, blur_sigma=cfg.blur_sigma, use_pallas=use_pallas,
                smooth=smooth,
            )
            # Fused match→consensus at batch level (PR 13): the former
            # per-frame vmap of knn_match + ransac_estimate — the worst
            # per-launch amortization of any config at rigid3d's small
            # batch sizes — becomes one (frames × hypotheses) region.
            res, n_matches = fused_match_consensus(
                model,
                desc,
                kps.xy,
                kps.valid,
                ref_desc,
                ref_xy,
                ref_valid,
                keys,
                ratio=cfg.ratio,
                max_dist=cfg.max_hamming,
                mutual=cfg.mutual,
                precision=precision,
                n_hypotheses=cfg.n_hypotheses,
                threshold=cfg.inlier_threshold,
                refine_iters=cfg.refine_iters,
                score_cap=cfg.score_cap,
                budget_rungs=cfg.budget_rungs,
                early_exit_frac=cfg.early_exit_frac,
                seed_transform=seed_M,
                seed_ok=seed_ok,
            )
            out = {
                "transform": res.transform,
                "n_keypoints": jnp.sum(kps.valid, axis=1).astype(jnp.int32),
                "n_matches": n_matches,
                "n_inliers": res.n_inliers,
                "rms_residual": res.rms_residual,
            }
            if vol_warp is not None:
                out["corrected"], out["warp_ok"] = vol_warp(
                    frames, out["transform"]
                )
            else:
                out["corrected"] = jax.vmap(warp_volume)(
                    frames, out["transform"]
                )
                # gather warp: unbounded
                out["warp_ok"] = jnp.ones(frames.shape[0], bool)
            return out

        if warm:
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices, seed_M, seed_ok):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame,
                    indices, seed_M, seed_ok,
                )
        else:
            def local(frames, ref_xy, ref_desc, ref_valid, ref_frame,
                      indices):
                return core(
                    frames, ref_xy, ref_desc, ref_valid, ref_frame, indices,
                )

        return local

    def rescue_warp(self, frames, out: dict, ref: dict | None = None) -> np.ndarray:
        """Exact unbounded resample for frames a bounded gather-free
        kernel flagged (`warp_ok` False): the consensus transform/field
        is correct far beyond the warp kernels' static motion bounds
        (the KNN matcher is global), so the rare out-of-bound frame is
        re-warped through the XLA gather path instead of being zeroed.

        frames: (n, H, W) or (n, D, H, W); out: the per-frame outputs
        (already host/NumPy, sliced to the same n frames). Returns the
        corrected frames.

        With `ref` and a 2D matrix model, the photometric transform
        polish runs here too (the in-program polish skipped these
        frames — their bounded-warp output was zeroed, leaving nothing
        to correlate): same passes, measured on the exact gather-warped
        pixels. `out["transform"]` is updated in place so the exported
        transforms match the rescued pixels.
        """
        cfg = self.config
        # Upload in the native dtype and widen ON DEVICE: a uint16
        # rescue batch crosses the host->device link at half the bytes
        # of a host-side float32 cast (the kcmc-check dtype-flow rule).
        frames = jnp.asarray(frames).astype(jnp.float32)
        if cfg.sanitize_input:
            # The batch program sanitized its own input; the rescue
            # path re-warps the RAW host frames, so the fully-finite
            # output guarantee must be re-applied here too.
            frames = _sanitize_nonfinite(frames)
        if cfg.model == "piecewise":
            from kcmc_tpu.ops.piecewise import upsample_field

            shape = tuple(frames.shape[1:])
            flows = jax.vmap(lambda f: upsample_field(f, shape))(
                jnp.asarray(out["field"], jnp.float32)
            )
            return np.asarray(jax.vmap(warp_frame_flow)(frames, flows))
        transforms = jnp.asarray(out["transform"], jnp.float32)
        if frames.ndim == 4:
            return np.asarray(jax.vmap(warp_volume)(frames, transforms))
        from kcmc_tpu.ops.warp import warp_frame

        corrected = jax.vmap(warp_frame)(frames, transforms)
        if ref is not None and ref.get("frame") is not None:
            from kcmc_tpu.ops.polish import polish_transforms

            ref_frame = jnp.asarray(ref["frame"], jnp.float32)
            for _ in range(int(cfg.transform_polish)):
                transforms = polish_transforms(
                    corrected, ref_frame, transforms, cfg.model,
                    grid=cfg.polish_grid,
                )
                corrected = jax.vmap(warp_frame)(frames, transforms)
            out["transform"] = np.asarray(transforms)
        return np.asarray(corrected)

    def _tile_params(self, shape) -> dict:
        """Autotuned tile parameters for this 2D frame shape (PR 13):
        {"shape": shape, "detect_strip": int|None, "patch_bands":
        int|None}, or {} when tuning is off/inapplicable.

        Runs at program-BUILD time only — the candidate search times
        real device work through honest_time, which must never execute
        inside a trace. Winners persist as plan stamps (PlanRuntime.
        tile), so a warm boot replays them with zero candidate
        compiles; within a process this cache makes repeated builds
        free."""
        cfg = self.config
        if (
            len(shape) != 2
            or not cfg.autotune_tiles
            or not self._on_accelerator()
        ):
            return {}
        shape = tuple(int(s) for s in shape)
        cached = self._tile_cache.get(shape)
        if cached is not None:
            return cached
        import numpy as _np

        from kcmc_tpu.utils.profiling import honest_time

        tiles: dict = {"shape": shape}

        from kcmc_tpu.ops.pallas_detect import _STRIP as _DETECT_STRIP
        from kcmc_tpu.ops.pallas_detect import response_fields, supports

        if supports(
            shape, cfg.nms_size, cfg.harris_window_sigma, cfg.blur_sigma
        ):
            frames0 = _np.zeros((4,) + shape, _np.float32)

            def measure_detect(c):
                return honest_time(
                    lambda f: response_fields(
                        f, harris_k=cfg.harris_k, nms_size=cfg.nms_size,
                        window_sigma=cfg.harris_window_sigma,
                        smooth_sigma=cfg.blur_sigma, strip=c,
                    ),
                    frames0, iters=6, min_warmup_s=0.1,
                )

            tiles["detect_strip"] = self._plan.tile(
                "detect_strip", shape, "float32",
                candidates=(32, 64, 128), default=_DETECT_STRIP,
                measure=measure_detect,
            )

        from kcmc_tpu.ops.pallas_patch import extract_blended, feasible_bands
        from kcmc_tpu.ops.patterns import PATCH_RADIUS, ROT_RADIUS

        r = ROT_RADIUS if cfg.resolved_oriented() else PATCH_RADIUS
        P = 2 * r + 2
        bands = feasible_bands(shape, P, itemsize=2)
        if len(bands) > 1:
            r1 = (P - 2) // 2 + 1
            padded0 = _np.zeros(
                (2, shape[0] + 2 * r1, shape[1] + 2 * r1), _np.float32
            ).astype(jnp.bfloat16)
            # Keypoints spread uniformly over the frame so every band's
            # dispatch runs are exercised (all-zero positions would
            # degenerate the banded layout to one run and mis-rank).
            K = cfg.max_keypoints
            xs = _np.linspace(0, shape[1] - 1, K, dtype=_np.float32)
            ys = _np.linspace(0, shape[0] - 1, K, dtype=_np.float32)
            xy0 = _np.broadcast_to(
                _np.stack([xs, ys], -1), (2, K, 2)
            ).copy()

            def measure_bands(c):
                return honest_time(
                    lambda p, x: extract_blended(
                        p, x, P, out_dtype=jnp.bfloat16, bands=c
                    ),
                    padded0, xy0, iters=4, min_warmup_s=0.1,
                )

            tiles["patch_bands"] = self._plan.tile(
                "patch_bands", shape, "bf16",
                candidates=bands, default=bands[0],
                measure=measure_bands,
            )

        self._tile_cache[shape] = tiles
        return tiles

    def _donate_argnums(self) -> tuple:
        """Argnums the single-device register program donates: the
        frame batch (arg 0), unless `donate_buffers` is off. The
        reference arrays (args 1-4) are reused across every batch and
        must never be donated."""
        return (0,) if self.config.donate_buffers else ()

    @staticmethod
    def _on_accelerator() -> bool:
        # Where the gather-free kernels pay off (and, for Pallas, lower
        # via TPU Mosaic). "axon" is this image's tunneled-TPU platform.
        return jax.default_backend() in ("tpu", "axon")

    def _shear_bound_px(self, shape) -> int:
        """The separable warp's static shear bound for this frame shape:
        `max_rotation_deg` (ergonomic, per-shape) wins over the raw
        `max_shear_px` pixel knob when set."""
        cfg = self.config
        if cfg.max_rotation_deg is None:
            return cfg.max_shear_px
        import math

        side = max(shape)
        return int(
            math.ceil(math.tan(math.radians(cfg.max_rotation_deg)) * side / 2.0)
        )

    def _matrix_resid_px(self, shape) -> int:
        """Residual-displacement bound for the small-field matrix warp:
        the rotation allowance (shear bound, from max_rotation_deg /
        max_shear_px) plus the projective allowance plus a ~1.5% scale
        margin — the three non-translation terms the kernel's canvas
        cannot absorb. Floor of 12 keeps the default drift regime
        rescue-free."""
        cfg = self.config
        scale_margin = max(4, int(cfg.max_scale_dev * max(shape) / 2) + 1)
        return max(
            12,
            self._shear_bound_px(shape)
            + cfg.max_projective_px
            + scale_margin,
        )

    def _resolve_batch_warp(self, shape):
        """Pick the batched warp implementation per the `warp` policy.

        Returns fn(frames (B,H,W), transforms (B,3,3)) ->
        (corrected (B,H,W), ok (B,) bool). ok is False for frames a
        bounded gather-free kernel zeroed instead of mis-resampling.
        """
        cfg = self.config
        on_tpu = self._on_accelerator()
        from kcmc_tpu.ops.pallas_warp import supports as pallas_warp_fits

        # The whole-frame Pallas translation kernel VMEM-OOMs at compile
        # time beyond ~512^2 (see pallas_warp.supports); "auto" falls
        # through to the separable pass chain (still gather-free) for
        # larger frames. An explicit warp="pallas" request is honored
        # as asked — the compile error is then the honest answer.
        use_pallas = cfg.warp == "pallas" or (
            cfg.warp == "auto"
            and cfg.model == "translation"
            and on_tpu
            and pallas_warp_fits(shape)
        )
        if use_pallas:
            from kcmc_tpu.ops.pallas_warp import warp_batch_translation

            interp = not on_tpu  # interpret mode off-TPU
            return functools.partial(
                warp_batch_translation, interpret=interp, with_ok=True
            )
        from kcmc_tpu.ops.pallas_warp import supports_strips

        if (
            cfg.warp == "auto"
            and cfg.model == "translation"
            and on_tpu
            and supports_strips(shape)
        ):
            # Large-frame route (1024²/2048²): the whole-frame window
            # exceeds VMEM, but row strips with a 2*PAD halo fit at any
            # height — replaces the separable scale-matmul fallback's
            # ~1.4 ms/frame at 2048² with ~0.3 (DESIGN.md "Large-frame
            # support", round-5 build of the round-4 sizing). The strip
            # height autotunes per shape (PR 13 — resolved here at
            # build time, stamped through the plan cache).
            from kcmc_tpu.ops.pallas_warp import (
                _STRIP_ROWS,
                warp_batch_translation_strips,
            )

            strip = None
            if cfg.autotune_tiles:
                cands = tuple(
                    c for c in (64, 128, 256) if supports_strips(shape, c)
                )
                if len(cands) > 1:
                    import numpy as _np

                    from kcmc_tpu.utils.profiling import honest_time

                    frames0 = _np.zeros((4,) + tuple(shape), _np.float32)
                    eyes0 = _np.tile(
                        _np.eye(3, dtype=_np.float32), (4, 1, 1)
                    )

                    def measure_warp(c):
                        return honest_time(
                            lambda f, M: warp_batch_translation_strips(
                                f, M, strip_rows=c
                            ),
                            frames0, eyes0, iters=6, min_warmup_s=0.1,
                        )

                    strip = self._plan.tile(
                        "warp_strips", shape, "float32",
                        candidates=cands, default=_STRIP_ROWS,
                        measure=measure_warp,
                    )
            return functools.partial(
                warp_batch_translation_strips, with_ok=True,
                strip_rows=strip,
            )
        use_matrix = cfg.warp == "matrix" or (
            cfg.warp == "auto"
            and cfg.model in ("rigid", "affine", "homography")
            and on_tpu
        )
        if use_matrix:
            from kcmc_tpu.ops.pallas_warp_field import (
                supports_matrix,
                warp_batch_matrix_pallas,
            )

            mpx = self._matrix_resid_px(shape)
            if on_tpu and supports_matrix(shape, mpx):
                return functools.partial(
                    warp_batch_matrix_pallas, max_px=mpx, with_ok=True
                )
            from kcmc_tpu.ops.warp_field import warp_batch_matrix

            # Single-interpolation small-field kernel: exact to ~1e-4
            # px vs the gather warp (the 4-pass separable chain's
            # ~0.012 px artifact was fine until the round-5 photometric
            # polish started feeding warped pixels back into the
            # transform — it converged to the artifact's optimum, 0.055
            # px from truth for homography). Similarity stays on the
            # separable chain below: its zoom envelope (±25%) is far
            # beyond any practical residual bound, while the scale
            # matmul passes handle zoom unbounded.
            return functools.partial(
                warp_batch_matrix,
                max_px=self._matrix_resid_px(shape),
                with_ok=True,
            )
        if cfg.warp == "separable" and cfg.model == "homography":
            # Explicit zoom-unbounded homography route: the separable
            # affine chain for the first-order part plus the small-
            # field kernel for the projective residual. The auto path
            # prefers warp_batch_matrix (one interpolation, exact to
            # ~1e-4 px); this chain stays selectable for projective
            # content whose zoom exceeds the matrix kernel's residual
            # bound.
            from kcmc_tpu.ops.warp_field import warp_batch_homography

            return functools.partial(
                warp_batch_homography,
                shear_px=self._shear_bound_px(shape),
                max_px=cfg.max_projective_px,
                with_ok=True,
            )
        use_separable = cfg.warp == "separable" or (
            cfg.warp == "auto"
            and cfg.model in ("translation", "similarity")
            and on_tpu
        )
        if use_separable:
            from kcmc_tpu.ops.warp_separable import warp_batch_affine

            # Pure translation has structurally zero shear (the model
            # can't produce rotation), so the ±shear_px masked-shift
            # loops collapse to their k=0 term — at 2048² that is 2.9
            # -> ~0.5 ms/frame of warp (the 17-pass shear loop was the
            # whole cost; measured, DESIGN.md "Large-frame support").
            shear = 0 if cfg.model == "translation" else self._shear_bound_px(shape)
            return functools.partial(
                warp_batch_affine,
                shear_px=shear,
                with_ok=True,
            )
        return warp_batch_with_ok

    def _resolve_flow_warp(self):
        """Batched dense-flow warp for the piecewise model, or None to
        warp per-frame inside the vmap (the gather path, default off-TPU)."""
        cfg = self.config
        if cfg.warp == "auto" and self._on_accelerator():
            from kcmc_tpu.ops.warp_field import warp_batch_flow

            return functools.partial(
                warp_batch_flow, max_px=cfg.max_flow_px, with_ok=True
            )
        return None

    def _resolve_field_warp(self, shape):
        """Fused field->frame warp (Pallas, round 5): upsample + bounded
        resample in one VMEM-resident kernel, consumer-phase-corrected
        (ops/pallas_warp_field.py). Preferred over upsample_field +
        warp_batch_flow on accelerators — it skips the dense (B, H, W, 2)
        flow round-trip that binds every field-polish pass, and its
        warp artifact vs one-shot bilinear is ~30x smaller than the
        naive two-pass split's (the pixels feed back into the
        photometric polish). None when VMEM-unsupported or off-TPU."""
        cfg = self.config
        if cfg.warp != "auto" or not self._on_accelerator():
            return None
        from kcmc_tpu.ops import pallas_warp_field as pwf

        if not pwf.supports(shape, cfg.max_flow_px):
            return None
        return functools.partial(
            pwf.warp_batch_field, max_px=cfg.max_flow_px, with_ok=True
        )

    def _resolve_volume_warp(self):
        """Batched gather-free 3D rigid warp, or None for the per-frame
        trilinear gather path (default off-TPU)."""
        cfg = self.config
        if cfg.warp == "auto" and self._on_accelerator():
            from kcmc_tpu.ops.warp_field import warp_batch_rigid3d

            return functools.partial(
                warp_batch_rigid3d, max_px=cfg.max_flow_px, with_ok=True
            )
        return None

