"""Execution-backend plugin registry.

The load-bearing seam of the reference architecture (SURVEY.md §1: the
orchestrator stays backend-agnostic; `backend=` selects the kernel
implementations). Backends register themselves under a string name; the
`MotionCorrector` looks them up here.

Built-in backends:

* ``"jax"`` — the TPU-native path (XLA-jitted, vmapped, Pallas warp).
* ``"numpy"`` — pure-NumPy mirror of the same algorithm, used for the
  judged CPU-parity comparison and as the oracle in tests.

Third-party backends can call :func:`register_backend` directly.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

_REGISTRY: dict[str, type] = {}


def register_backend(name: str):
    """Class decorator: register an execution backend under `name`."""

    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def _ensure_builtins() -> None:
    # Import for side effect: the modules self-register. Lazy so that
    # `import kcmc_tpu` stays cheap and numpy-only users never pay JAX
    # import cost (and vice versa).
    import importlib

    for mod in ("kcmc_tpu.backends.jax_backend", "kcmc_tpu.backends.numpy_backend"):
        try:
            importlib.import_module(mod)
        except ImportError:
            pass


def available_backends() -> list[str]:
    _ensure_builtins()
    return sorted(_REGISTRY)


def get_backend(name: str, config, **options):
    """Instantiate the backend registered under `name`.

    `options` are backend-specific (e.g. `mesh=` for the jax backend).
    """
    if name not in _REGISTRY:
        _ensure_builtins()
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown backend {name!r}; available: {available_backends()}"
        )
    return _REGISTRY[name](config, **options)
