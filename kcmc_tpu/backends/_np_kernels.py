"""Pure-NumPy kernel implementations for the CPU parity backend.

These mirror the algorithmic definitions of the JAX ops (same Harris
response, same BRIEF pattern constant, same Hamming matching rules, same
weighted solvers, same RANSAC structure) so the two backends agree to
registration accuracy. They are *not* translations of the XLA code:
no masking tricks are needed on the host, so the natural dynamic-shape
NumPy style is used. RANSAC sampling uses a Philox generator seeded per
(seed, frame) — deterministic, but not bit-identical to the JAX PRNG;
parity is at the transform-RMSE level (the judged metric), not bitwise.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np

from kcmc_tpu.ops.patterns import (
    CAND_TILE,
    MOMENT_RADIUS as _MOMENT_RADIUS,
    MOMENTS as _MOMENTS,
    N_BITS,
    N_ORIENT_BINS,
    N_WORDS,
    PATCH_RADIUS,
    PATTERN,
    PATTERN_3D,
    ROT_PATTERNS,
    WINDOW_SIGMA,
)

# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------


def conv2d_same(img: np.ndarray, kernel: np.ndarray) -> np.ndarray:
    """Same-padded 2D correlation (matches lax.conv's flip-free semantics
    for the symmetric kernels we use)."""
    kh, kw = kernel.shape
    ph, pw = kh // 2, kw // 2
    padded = np.pad(img, ((ph, kh - 1 - ph), (pw, kw - 1 - pw)))
    win = np.lib.stride_tricks.sliding_window_view(padded, (kh, kw))
    return np.einsum("ijkl,kl->ij", win, kernel, optimize=True).astype(np.float32)


def gaussian_blur(img: np.ndarray, sigma: float) -> np.ndarray:
    radius = max(1, int(3.0 * sigma + 0.5))
    x = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    k /= k.sum()
    img = conv2d_same(img, k[None, :])
    img = conv2d_same(img, k[:, None])
    return img


_SOBEL_X = np.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], dtype=np.float32) / 8.0
_SOBEL_Y = _SOBEL_X.T


def harris_response(img: np.ndarray, k: float = 0.04, window_sigma: float = WINDOW_SIGMA) -> np.ndarray:
    gx = conv2d_same(img, _SOBEL_X)
    gy = conv2d_same(img, _SOBEL_Y)
    ixx = gaussian_blur(gx * gx, window_sigma)
    iyy = gaussian_blur(gy * gy, window_sigma)
    ixy = gaussian_blur(gx * gy, window_sigma)
    det = ixx * iyy - ixy * ixy
    trace = ixx + iyy
    return det - k * trace * trace


def detect_keypoints(
    img: np.ndarray,
    max_keypoints: int = 512,
    threshold: float = 1e-4,
    nms_size: int = 5,
    border: int = 16,
    harris_k: float = 0.04,
    window_sigma: float = WINDOW_SIGMA,
    cand_tile: int = CAND_TILE,
):
    """Returns (xy (K,2), score (K,), valid (K,)) with K = max_keypoints."""
    H, W = img.shape
    resp = harris_response(img, k=harris_k, window_sigma=window_sigma)
    r = nms_size // 2
    padded = np.pad(resp, r, constant_values=-np.inf)
    win = np.lib.stride_tricks.sliding_window_view(padded, (nms_size, nms_size))
    local_max = win.max(axis=(2, 3))
    is_max = resp >= local_max
    ys, xs = np.mgrid[0:H, 0:W]
    inb = (ys >= border) & (ys < H - border) & (xs >= border) & (xs < W - border)
    # Peak over the selectable region only — mirrors ops/detect.py's
    # border-excluded peak (background offsets spike the border ring).
    sel = np.where(is_max & inb, resp, -np.inf)
    peak = max(sel.max(), 1e-12)
    cand = is_max & inb & (resp > threshold * peak)
    masked = np.where(cand, resp, -np.inf)
    # Tile-bucketed candidate reduction — same rule as ops/detect.py
    # (strongest surviving pixel per tile, then global top-k), so the
    # two backends select the same keypoint set.
    T = cand_tile
    Hp, Wp = -(-H // T) * T, -(-W // T) * T
    m = np.full((Hp, Wp), -np.inf, np.float32)
    m[:H, :W] = masked
    tiles = m.reshape(Hp // T, T, Wp // T, T).transpose(0, 2, 1, 3)
    tiles = tiles.reshape(Hp // T, Wp // T, T * T)
    tile_val = tiles.max(-1)
    tile_arg = tiles.argmax(-1)
    k = min(max_keypoints, tile_val.size)
    order = np.argsort(-tile_val.ravel(), kind="stable")[:k]
    scores = tile_val.ravel()[order]
    if k < max_keypoints:
        pad = max_keypoints - k
        scores = np.concatenate([scores, np.full(pad, -np.inf, np.float32)])
        order = np.concatenate([order, np.zeros(pad, order.dtype)])
    valid = np.isfinite(scores)
    within = tile_arg.ravel()[order]
    tw = tile_val.shape[1]
    iy = (order // tw) * T + within // T
    ix = (order % tw) * T + within % T
    iy = np.clip(iy, 0, H - 1)
    ix = np.clip(ix, 0, W - 1)

    # quadratic subpixel refinement (same formula as ops/detect.py)
    xy = np.stack([ix, iy], axis=-1).astype(np.float32)
    cy = np.clip(iy, 1, H - 2)
    cx = np.clip(ix, 1, W - 2)
    c = resp[cy, cx]
    dx = 0.5 * (resp[cy, cx + 1] - resp[cy, cx - 1])
    dy = 0.5 * (resp[cy + 1, cx] - resp[cy - 1, cx])
    dxx = resp[cy, cx + 1] - 2 * c + resp[cy, cx - 1]
    dyy = resp[cy + 1, cx] - 2 * c + resp[cy - 1, cx]
    with np.errstate(divide="ignore", invalid="ignore"):
        ox = np.where(np.abs(dxx) > 1e-8, -dx / dxx, 0.0)
        oy = np.where(np.abs(dyy) > 1e-8, -dy / dyy, 0.0)
    off = np.clip(np.stack([ox, oy], -1), -0.5, 0.5)
    xy = np.where(valid[:, None], xy + off, 0.0).astype(np.float32)
    scores = np.where(valid, scores, 0.0).astype(np.float32)
    return xy, scores, valid


def bilinear_sample(img: np.ndarray, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Edge-clamped bilinear sampling (interior only — callers keep pts inside)."""
    H, W = img.shape
    x = np.clip(x, 0.0, W - 1.0)
    y = np.clip(y, 0.0, H - 1.0)
    x0 = np.floor(x).astype(np.int32)
    y0 = np.floor(y).astype(np.int32)
    fx = x - x0
    fy = y - y0
    x1 = np.minimum(x0 + 1, W - 1)
    y1 = np.minimum(y0 + 1, H - 1)
    return (
        img[y0, x0] * (1 - fx) * (1 - fy)
        + img[y0, x1] * fx * (1 - fy)
        + img[y1, x0] * (1 - fx) * fy
        + img[y1, x1] * fx * fy
    ).astype(np.float32)


def describe_keypoints(
    img: np.ndarray, xy: np.ndarray, valid: np.ndarray, oriented: bool, blur_sigma: float = 2.0
) -> np.ndarray:
    smooth = gaussian_blur(img, blur_sigma)
    # pixels at descriptor precision — mirror of the jax paths' round-5
    # bf16 quantization point incl. the per-frame mean removal
    # (ops/describe.py: large DC backgrounds would otherwise exceed
    # bf16's relative step and wipe the content)
    fin = np.isfinite(smooth)
    mu = np.float32(smooth[fin].mean()) if fin.any() else np.float32(0.0)
    smooth = (smooth - mu).astype(
        np.float32
    ).astype(ml_dtypes.bfloat16).astype(np.float32)
    K = xy.shape[0]
    if oriented:
        r = _MOMENT_RADIUS
        H, W = img.shape
        cx = np.clip(np.round(xy[:, 0]).astype(np.int32), r, W - r - 1)
        cy = np.clip(np.round(xy[:, 1]).astype(np.int32), r, H - r - 1)
        angles = np.empty(K, np.float32)
        moms = _MOMENTS
        for i in range(K):
            patch = smooth[cy[i] - r : cy[i] + r + 1, cx[i] - r : cx[i] + r + 1]
            w = patch * moms[..., 2]
            angles[i] = np.arctan2((w * moms[..., 1]).sum(), (w * moms[..., 0]).sum())
        # Quantized orientation bins with precomputed rotated integer
        # patterns — same definition as ops/describe.py (ORB-style).
        nb = N_ORIENT_BINS
        bins = np.mod(np.rint(angles * (nb / (2.0 * np.pi))).astype(np.int64), nb)
        offs = ROT_PATTERNS[bins]  # (K, N_BITS, 2, 2)
    else:
        offs = np.broadcast_to(PATTERN[None], (K,) + PATTERN.shape)
    pos = xy[:, None, None, :] + offs  # (K,B,2,2)
    vals = bilinear_sample(smooth, pos[..., 0], pos[..., 1])
    # Descriptor values are bf16-quantized framework-wide (round 5 —
    # the jax paths' bandwidth precision; see ops/describe.py): the
    # oracle quantizes at the same point so comparison ties fall the
    # same way.
    vals = vals.astype(np.float32).astype(ml_dtypes.bfloat16)
    bits = (vals[..., 0] < vals[..., 1]).astype(np.uint32)  # (K, B)
    b = bits.reshape(K, N_WORDS, 32)
    desc = (b << np.arange(32, dtype=np.uint32)[None, None, :]).sum(-1).astype(np.uint32)
    desc[~valid] = 0
    return desc


# ---------------------------------------------------------------------------
# matching
# ---------------------------------------------------------------------------

if hasattr(np, "bitwise_count"):
    def _popcount(x: np.ndarray) -> np.ndarray:
        return np.bitwise_count(x)
else:  # pragma: no cover - old numpy
    _POP8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.uint8)

    def _popcount(x: np.ndarray) -> np.ndarray:
        return _POP8[x.view(np.uint8)].reshape(x.shape + (4,)).sum(-1)


def knn_match(
    q_desc, r_desc, q_valid, r_valid, ratio=0.85, max_dist=80, mutual=True
):
    """Same rules as ops/match.py; returns (idx, dist, second, valid)."""
    BIG = (1 << 16) - 1  # matches ops/match.py _BIG (uint16-compatible sentinel)
    # Zero descriptors are the invalid sentinel — same rule as
    # ops/match.py's knn_match (flat patches / masked slots never match).
    q_valid = q_valid & (q_desc != 0).any(-1)
    r_valid = r_valid & (r_desc != 0).any(-1)
    x = q_desc[:, None, :] ^ r_desc[None, :, :]
    D = _popcount(x).sum(-1).astype(np.int64)
    mask = q_valid[:, None] & r_valid[None, :]
    D = np.where(mask, D, BIG)
    part = np.argpartition(D, 1, axis=1)[:, :2]
    d2 = np.take_along_axis(D, part, axis=1)
    swap = d2[:, 0] > d2[:, 1]
    part[swap] = part[swap][:, ::-1]
    d2[swap] = d2[swap][:, ::-1]
    idx, best, second = part[:, 0], d2[:, 0], d2[:, 1]
    ok = (best < max_dist) & (best < ratio * second)
    if mutual:
        rev = np.argmin(D, axis=0)
        ok &= rev[idx] == np.arange(D.shape[0])
    ok &= q_valid & (best <= N_BITS)
    return idx.astype(np.int32), best, second, ok


# ---------------------------------------------------------------------------
# solvers (mirror kcmc_tpu/models/transforms.py in float64 for stability)
# ---------------------------------------------------------------------------


def _wmean(x, w):
    return (x * w[:, None]).sum(0) / max(w.sum(), 1e-8)


def apply_np(M, pts):
    d = pts.shape[-1]
    lin = pts @ M[:d, :d].T + M[:d, d]
    w = pts @ M[d, :d] + M[d, d]
    w = np.where(np.abs(w) < 1e-8, np.where(w < 0, -1e-8, 1e-8), w)
    return lin / w[..., None]


def solve_translation(src, dst, w):
    if w.sum() < 1e-3:
        return np.eye(3, dtype=np.float32)
    M = np.eye(3, dtype=np.float32)
    M[:2, 2] = _wmean(dst - src, w)
    return M


def solve_rigid(src, dst, w):
    if w.sum() < 1e-3:
        return np.eye(3, dtype=np.float32)
    cs, cd = _wmean(src, w), _wmean(dst, w)
    s, d = src - cs, dst - cd
    a = (w * (s[:, 0] * d[:, 0] + s[:, 1] * d[:, 1])).sum()
    b = (w * (s[:, 0] * d[:, 1] - s[:, 1] * d[:, 0])).sum()
    n = np.hypot(a, b)
    if n < 1e-6:
        return np.eye(3, dtype=np.float32)
    c, sn = a / n, b / n
    R = np.array([[c, -sn], [sn, c]], dtype=np.float64)
    t = cd - R @ cs
    M = np.eye(3, dtype=np.float32)
    M[:2, :2] = R
    M[:2, 2] = t
    return M


def solve_similarity(src, dst, w):
    """Weighted 2D similarity (Umeyama) — mirror of
    models/transforms.solve_similarity."""
    if w.sum() < 1e-3:
        return np.eye(3, dtype=np.float32)
    cs, cd = _wmean(src, w), _wmean(dst, w)
    s, d = src - cs, dst - cd
    a = (w * (s[:, 0] * d[:, 0] + s[:, 1] * d[:, 1])).sum()
    b = (w * (s[:, 0] * d[:, 1] - s[:, 1] * d[:, 0])).sum()
    var_s = max((w * (s[:, 0] ** 2 + s[:, 1] ** 2)).sum(), 1e-8)
    n = np.hypot(a, b)
    if n < 1e-6:
        return np.eye(3, dtype=np.float32)
    scale = n / var_s
    c, sn = a / n, b / n
    R = scale * np.array([[c, -sn], [sn, c]], dtype=np.float64)
    t = cd - R @ cs
    M = np.eye(3, dtype=np.float32)
    M[:2, :2] = R
    M[:2, 2] = t
    return M


def _norm_T(pts, w):
    c = _wmean(pts, w)
    rms = np.sqrt(max(_wmean(((pts - c) ** 2).sum(-1, keepdims=True), w)[0], 1e-16))
    s = np.sqrt(pts.shape[1]) / rms
    T = np.eye(pts.shape[1] + 1)
    T[:-1, :-1] *= s
    T[:-1, -1] = -s * c
    Ti = np.eye(pts.shape[1] + 1)
    Ti[:-1, :-1] /= s
    Ti[:-1, -1] = c
    return T, Ti


def solve_affine(src, dst, w):
    if w.sum() < 1e-3:
        return np.eye(3, dtype=np.float32)
    src = src.astype(np.float64)
    dst = dst.astype(np.float64)
    Ts, _ = _norm_T(src, w)
    Td, Tdi = _norm_T(dst, w)
    sn = apply_np(Ts, src)
    dn = apply_np(Td, dst)
    A = np.concatenate([sn, np.ones((len(sn), 1))], axis=1)
    Aw = A * w[:, None]
    M33 = A.T @ Aw + 1e-8 * np.eye(3)
    P = np.linalg.solve(M33, Aw.T @ dn).T
    Mn = np.eye(3)
    Mn[:2, :] = P
    M = Tdi @ Mn @ Ts
    return (M / M[2, 2]).astype(np.float32)


def solve_homography(src, dst, w):
    if w.sum() < 1e-3:
        return np.eye(3, dtype=np.float32)
    src = src.astype(np.float64)
    dst = dst.astype(np.float64)
    Ts, _ = _norm_T(src, w)
    Td, Tdi = _norm_T(dst, w)
    sn = apply_np(Ts, src)
    dn = apply_np(Td, dst)
    x, y = sn[:, 0], sn[:, 1]
    u, v = dn[:, 0], dn[:, 1]
    z = np.zeros_like(x)
    o = np.ones_like(x)
    r1 = np.stack([-x, -y, -o, z, z, z, u * x, u * y, u], -1)
    r2 = np.stack([z, z, z, -x, -y, -o, v * x, v * y, v], -1)
    rows = np.concatenate([r1, r2], 0)
    rw = np.concatenate([w, w], 0)
    ATA = rows.T @ (rows * rw[:, None])
    _, vecs = np.linalg.eigh(ATA)
    Hn = vecs[:, 0].reshape(3, 3)
    Hm = Tdi @ Hn @ Ts
    Hm /= np.linalg.norm(Hm)
    if Hm[2, 2] < 0:
        Hm = -Hm
    if abs(Hm[2, 2]) > 1e-6:
        Hm = Hm / Hm[2, 2]
    if not np.isfinite(Hm).all():
        return np.eye(3, dtype=np.float32)
    return Hm.astype(np.float32)


def solve_rigid3d(src, dst, w):
    if w.sum() < 1e-3:
        return np.eye(4, dtype=np.float32)
    src = src.astype(np.float64)
    dst = dst.astype(np.float64)
    cs, cd = _wmean(src, w), _wmean(dst, w)
    Hm = ((src - cs) * w[:, None]).T @ (dst - cd)
    U, _, Vt = np.linalg.svd(Hm)
    D = np.diag([1.0, 1.0, np.linalg.det(Vt.T @ U.T)])
    R = Vt.T @ D @ U.T
    M = np.eye(4)
    M[:3, :3] = R
    M[:3, 3] = cd - R @ cs
    return M.astype(np.float32)


SOLVERS = {
    "translation": (solve_translation, 1, 2),
    "rigid": (solve_rigid, 2, 2),
    "similarity": (solve_similarity, 2, 2),
    "affine": (solve_affine, 3, 2),
    "homography": (solve_homography, 4, 2),
    "rigid3d": (solve_rigid3d, 3, 3),
}


# ---------------------------------------------------------------------------
# RANSAC
# ---------------------------------------------------------------------------


def ransac_estimate(
    model_name: str,
    src: np.ndarray,
    dst: np.ndarray,
    valid: np.ndarray,
    rng: np.random.Generator,
    n_hypotheses: int = 128,
    threshold: float = 2.0,
    refine_iters: int = 2,
):
    """Same structure as ops/ransac.py (fixed H, argmax consensus, IRLS)."""
    solve, m, d = SOLVERS[model_name]
    eye = np.eye(d + 1, dtype=np.float32)
    idx_valid = np.flatnonzero(valid)
    thr2 = threshold * threshold
    if len(idx_valid) < m:
        return eye, 0, np.zeros(len(src), bool), 0.0

    best_M, best_n = eye, -1
    for _ in range(n_hypotheses):
        pick = rng.choice(idx_valid, size=m, replace=False)
        w = np.zeros(len(src), np.float32)
        w[pick] = 1.0
        M = solve(src, dst, w)
        r = ((apply_np(M, src) - dst) ** 2).sum(-1)
        n = int(((r < thr2) & valid).sum())
        if n > best_n:
            best_M, best_n = M, n

    M, n_in = best_M, best_n
    for _ in range(refine_iters):
        r = ((apply_np(M, src) - dst) ** 2).sum(-1)
        w = ((r < thr2) & valid).astype(np.float32)
        M2 = solve(src, dst, w)
        r2 = ((apply_np(M2, src) - dst) ** 2).sum(-1)
        n2 = int(((r2 < thr2) & valid).sum())
        if n2 >= n_in:
            M, n_in = M2, n2

    # Final polish on the consensus set, bounded rollback — mirrors
    # ops/ransac.py (this backend's f64 solvers are already the
    # "accurate" variant for every model).
    r = ((apply_np(M, src) - dst) ** 2).sum(-1)
    wf = ((r < thr2) & valid).astype(np.float32)
    nf = int(wf.sum())
    Mp = solve(src, dst, wf)
    rp = ((apply_np(Mp, src) - dst) ** 2).sum(-1)
    np_ = int(((rp < thr2) & valid).sum())
    if np_ >= max(m, int(np.ceil(0.8 * nf))):
        M = Mp

    r = ((apply_np(M, src) - dst) ** 2).sum(-1)
    inl = (r < thr2) & valid
    n = int(inl.sum())
    rms = float(np.sqrt(r[inl].mean())) if n else 0.0
    return M, n, inl, rms


# ---------------------------------------------------------------------------
# warping
# ---------------------------------------------------------------------------


def warp_frame(frame: np.ndarray, M: np.ndarray) -> np.ndarray:
    H, W = frame.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    w = M[2, 0] * xs + M[2, 1] * ys + M[2, 2]
    w = np.where(np.abs(w) < 1e-8, 1e-8, w)
    sx = (M[0, 0] * xs + M[0, 1] * ys + M[0, 2]) / w
    sy = (M[1, 0] * xs + M[1, 1] * ys + M[1, 2]) / w
    out = bilinear_sample(frame, sx, sy)
    inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
    return (out * inb).astype(np.float32)


def warp_frame_flow(frame: np.ndarray, flow: np.ndarray) -> np.ndarray:
    H, W = frame.shape
    ys, xs = np.meshgrid(np.arange(H, dtype=np.float32), np.arange(W, dtype=np.float32), indexing="ij")
    sx = xs + flow[..., 0]
    sy = ys + flow[..., 1]
    out = bilinear_sample(frame, sx, sy)
    inb = (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
    return (out * inb).astype(np.float32)


# ---------------------------------------------------------------------------
# 3D volumetric kernels (config 5) — mirror kcmc_tpu/ops/detect3d.py /
# describe3d.py / warp.py::warp_volume with the same constants so the
# two backends agree to registration accuracy.
# ---------------------------------------------------------------------------


def _conv3d_axis(vol: np.ndarray, k: np.ndarray, axis: int) -> np.ndarray:
    """SAME-padded 1D convolution along one axis of a (D, H, W) volume."""
    taps = len(k)
    R = taps // 2
    pad = [(R, taps - 1 - R) if a == axis else (0, 0) for a in range(3)]
    padded = np.pad(vol, pad)
    out = np.zeros_like(vol, dtype=np.float32)
    for i in range(taps):
        sl = tuple(
            slice(i, i + vol.shape[a]) if a == axis else slice(None)
            for a in range(3)
        )
        out += np.float32(k[i]) * padded[sl]
    return out


def gaussian_blur_3d(vol: np.ndarray, sigma: float) -> np.ndarray:
    radius = max(1, int(3.0 * sigma + 0.5))
    x = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    k /= k.sum()
    for axis in range(3):
        vol = _conv3d_axis(vol, k, axis)
    return vol


_DIFF3 = np.array([-0.5, 0.0, 0.5], dtype=np.float32)


def harris_response_3d(
    vol: np.ndarray, k: float = 0.005, window_sigma: float = WINDOW_SIGMA
) -> np.ndarray:
    gz = _conv3d_axis(vol, _DIFF3, 0)
    gy = _conv3d_axis(vol, _DIFF3, 1)
    gx = _conv3d_axis(vol, _DIFF3, 2)
    sxx = gaussian_blur_3d(gx * gx, window_sigma)
    syy = gaussian_blur_3d(gy * gy, window_sigma)
    szz = gaussian_blur_3d(gz * gz, window_sigma)
    sxy = gaussian_blur_3d(gx * gy, window_sigma)
    sxz = gaussian_blur_3d(gx * gz, window_sigma)
    syz = gaussian_blur_3d(gy * gz, window_sigma)
    det = (
        sxx * (syy * szz - syz * syz)
        - sxy * (sxy * szz - syz * sxz)
        + sxz * (sxy * syz - syy * sxz)
    )
    trace = sxx + syy + szz
    return det - k * trace * trace * trace


def detect_keypoints_3d(
    vol: np.ndarray,
    max_keypoints: int = 256,
    threshold: float = 1e-4,
    border: int = 6,
    harris_k: float = 0.005,
):
    """Returns (xyz (K,3), score (K,), valid (K,)); same selection rules
    as ops/detect3d.py (3x3x3 NMS, border-excluded relative threshold,
    per-(1,8,8)-tile bucketing, per-axis parabola subpixel)."""
    D, H, W = vol.shape
    resp = harris_response_3d(vol, k=harris_k)
    mx = resp
    for axis in range(3):
        pad = [(1, 1) if a == axis else (0, 0) for a in range(3)]
        p = np.pad(mx, pad, constant_values=-np.inf)
        sl = lambda i: tuple(
            slice(i, i + resp.shape[a]) if a == axis else slice(None)
            for a in range(3)
        )
        mx = np.maximum(np.maximum(p[sl(0)], p[sl(1)]), p[sl(2)])
    is_max = resp >= mx
    zs, ys, xs = np.mgrid[0:D, 0:H, 0:W]
    bz = min(border, max(1, D // 8))
    inb = (
        (zs >= bz) & (zs < D - bz)
        & (ys >= border) & (ys < H - border)
        & (xs >= border) & (xs < W - border)
    )
    sel = np.where(is_max & inb, resp, -np.inf)
    peak = max(sel.max(), 1e-12)
    cand = is_max & inb & (resp > threshold * peak)
    masked = np.where(cand, resp, -np.inf)

    T = 8
    Hp, Wp = -(-H // T) * T, -(-W // T) * T
    m = np.full((D, Hp, Wp), -np.inf, np.float32)
    m[:, :H, :W] = masked
    tiles = m.reshape(D, Hp // T, T, Wp // T, T).transpose(0, 1, 3, 2, 4)
    tiles = tiles.reshape(D, Hp // T, Wp // T, T * T)
    tile_val = tiles.max(-1)
    tile_arg = tiles.argmax(-1)
    k_ = min(max_keypoints, tile_val.size)
    order = np.argsort(-tile_val.ravel(), kind="stable")[:k_]
    scores = tile_val.ravel()[order]
    if k_ < max_keypoints:
        pad = max_keypoints - k_
        scores = np.concatenate([scores, np.full(pad, -np.inf, np.float32)])
        order = np.concatenate([order, np.zeros(pad, order.dtype)])
    valid = np.isfinite(scores)
    within = tile_arg.ravel()[order]
    th, tw = tile_val.shape[1], tile_val.shape[2]
    iz = order // (th * tw)
    iy = ((order // tw) % th) * T + within // T
    ix = (order % tw) * T + within % T
    iy = np.clip(iy, 0, H - 1)
    ix = np.clip(ix, 0, W - 1)

    cz = np.clip(iz, 1, D - 2)
    cy = np.clip(iy, 1, H - 2)
    cx = np.clip(ix, 1, W - 2)
    c = resp[cz, cy, cx]

    def axis_off(plus, minus):
        d1 = 0.5 * (plus - minus)
        d2 = plus - 2.0 * c + minus
        with np.errstate(divide="ignore", invalid="ignore"):
            o = np.where(np.abs(d2) > 1e-8, -d1 / d2, 0.0)
        return np.clip(o, -0.5, 0.5)

    ox = axis_off(resp[cz, cy, cx + 1], resp[cz, cy, cx - 1])
    oy = axis_off(resp[cz, cy + 1, cx], resp[cz, cy - 1, cx])
    oz = axis_off(resp[cz + 1, cy, cx], resp[cz - 1, cy, cx])
    xyz = np.stack(
        [ix + ox, iy + oy, iz + oz], axis=-1
    ).astype(np.float32)
    xyz = np.where(valid[:, None], xyz, 0.0).astype(np.float32)
    scores = np.where(valid, scores, 0.0).astype(np.float32)
    return xyz, scores, valid


def trilinear_sample(vol: np.ndarray, x, y, z) -> np.ndarray:
    """Edge-clamped trilinear sampling of a (D, H, W) volume."""
    D, H, W = vol.shape
    x = np.clip(x, 0.0, W - 1.0)
    y = np.clip(y, 0.0, H - 1.0)
    z = np.clip(z, 0.0, D - 1.0)
    x0 = np.floor(x).astype(np.int32)
    y0 = np.floor(y).astype(np.int32)
    z0 = np.floor(z).astype(np.int32)
    fx, fy, fz = x - x0, y - y0, z - z0
    x1 = np.minimum(x0 + 1, W - 1)
    y1 = np.minimum(y0 + 1, H - 1)
    z1 = np.minimum(z0 + 1, D - 1)
    return (
        vol[z0, y0, x0] * (1 - fx) * (1 - fy) * (1 - fz)
        + vol[z0, y0, x1] * fx * (1 - fy) * (1 - fz)
        + vol[z0, y1, x0] * (1 - fx) * fy * (1 - fz)
        + vol[z0, y1, x1] * fx * fy * (1 - fz)
        + vol[z1, y0, x0] * (1 - fx) * (1 - fy) * fz
        + vol[z1, y0, x1] * fx * (1 - fy) * fz
        + vol[z1, y1, x0] * (1 - fx) * fy * fz
        + vol[z1, y1, x1] * fx * fy * fz
    ).astype(np.float32)


def describe_keypoints_3d(
    vol: np.ndarray, xyz: np.ndarray, valid: np.ndarray, blur_sigma: float = 2.0
) -> np.ndarray:
    """(K, N_WORDS) 3D-BRIEF descriptors — same PATTERN_3D constant and
    comparison rule as ops/describe3d.py."""
    smooth = gaussian_blur_3d(vol, blur_sigma)
    K = xyz.shape[0]
    pos = xyz[:, None, None, :] + PATTERN_3D[None]  # (K, N_BITS, 2, 3)
    vals = trilinear_sample(smooth, pos[..., 0], pos[..., 1], pos[..., 2])
    bits = (vals[..., 0] < vals[..., 1]).astype(np.uint32)  # (K, N_BITS)
    b = bits.reshape(K, N_WORDS, 32)
    desc = (
        (b << np.arange(32, dtype=np.uint32)[None, None, :]).sum(-1)
    ).astype(np.uint32)
    desc[~valid] = 0
    return desc


def warp_volume(vol: np.ndarray, M: np.ndarray) -> np.ndarray:
    """Trilinear inverse warp of a (D, H, W) volume through a 4x4
    transform (ref -> frame coords, acting on (x, y, z))."""
    D, H, W = vol.shape
    zs, ys, xs = np.mgrid[0:D, 0:H, 0:W].astype(np.float32)
    sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2] * zs + M[0, 3]
    sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2] * zs + M[1, 3]
    sz = M[2, 0] * xs + M[2, 1] * ys + M[2, 2] * zs + M[2, 3]
    out = trilinear_sample(vol, sx, sy, sz)
    inb = (
        (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
        & (sz >= 0) & (sz <= D - 1)
    )
    return (out * inb).astype(np.float32)
