"""Pure-NumPy CPU backend — the parity oracle.

Implements the same backend protocol as the JAX backend using the
kernels in `_np_kernels`. This is the "CPU backend" of the judged
accuracy metric (BASELINE.md: transform-RMSE parity vs CPU): both
backends implement the identical algorithm, so their recovered
transforms agree to registration accuracy.
"""

from __future__ import annotations

import numpy as np

from kcmc_tpu.backends import register_backend
from kcmc_tpu.backends import _np_kernels as K
from kcmc_tpu.config import CorrectorConfig


def template_corr_np(
    corrected: np.ndarray, ref_frame: np.ndarray, mask: np.ndarray | None = None
) -> np.ndarray:
    """Per-frame Pearson correlation against the reference, over the
    warp-coverage mask (NumPy mirror of the jax backend's quality
    metric; also used by the corrector to refresh rescued frames)."""
    axes = tuple(range(1, corrected.ndim))
    if mask is None:
        mask = np.ones(corrected.shape, bool)
    m = mask.astype(corrected.dtype)
    n = np.maximum(m.sum(axis=axes, keepdims=True), 1.0)
    cm = (corrected * m).sum(axis=axes, keepdims=True) / n
    rm = (ref_frame * m).sum(axis=axes, keepdims=True) / n
    c = (corrected - cm) * m
    r = (ref_frame - rm) * m
    num = (c * r).sum(axis=axes)
    den = np.sqrt((c * c).sum(axis=axes) * (r * r).sum(axis=axes))
    return (num / np.maximum(den, 1e-12)).astype(np.float32)


def _coverage_mask_np(shape, M: np.ndarray) -> np.ndarray:
    """In-bounds source-sample mask of the 2D matrix warp (NumPy mirror
    of ops/warp.coverage_mask)."""
    H, W = shape
    ys, xs = np.mgrid[0:H, 0:W].astype(np.float32)
    w = M[2, 0] * xs + M[2, 1] * ys + M[2, 2]
    w = np.where(np.abs(w) < 1e-8, 1e-8, w)
    sx = (M[0, 0] * xs + M[0, 1] * ys + M[0, 2]) / w
    sy = (M[1, 0] * xs + M[1, 1] * ys + M[1, 2]) / w
    return (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)


def _coverage_mask_3d_np(shape, M: np.ndarray) -> np.ndarray:
    D, H, W = shape
    zs, ys, xs = np.mgrid[0:D, 0:H, 0:W].astype(np.float32)
    sx = M[0, 0] * xs + M[0, 1] * ys + M[0, 2] * zs + M[0, 3]
    sy = M[1, 0] * xs + M[1, 1] * ys + M[1, 2] * zs + M[1, 3]
    sz = M[2, 0] * xs + M[2, 1] * ys + M[2, 2] * zs + M[2, 3]
    return (
        (sx >= 0) & (sx <= W - 1) & (sy >= 0) & (sy <= H - 1)
        & (sz >= 0) & (sz <= D - 1)
    )


def coverage_masks_np(shape, out: dict) -> np.ndarray:
    """Per-frame warp-coverage masks from a batch's transform/field
    outputs (host side): (n, *shape) bool. Dispatches on the model
    family — dense flow for piecewise fields, 4x4 volumetric or 3x3
    planar matrices otherwise."""
    if "field" in out:
        from kcmc_tpu.utils.synthetic import upsample_field

        masks = []
        for f in np.asarray(out["field"], np.float32):
            flow = upsample_field(f, shape)
            ys, xs = np.mgrid[0 : shape[0], 0 : shape[1]].astype(np.float32)
            sx = xs + flow[..., 0]
            sy = ys + flow[..., 1]
            masks.append(
                (sx >= 0) & (sx <= shape[1] - 1)
                & (sy >= 0) & (sy <= shape[0] - 1)
            )
        return np.stack(masks)
    Ms = np.asarray(out["transform"], np.float32)
    fn = _coverage_mask_3d_np if Ms.shape[-1] == 4 else _coverage_mask_np
    return np.stack([fn(shape, M) for M in Ms])


def _measure_shifts_np(
    corrected: np.ndarray, template: np.ndarray, grid, exact: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """NumPy mirror of ops/polish.measure_shifts (one frame):
    center-weighted two-way symmetric cross-correlation at the 3x3
    integer shifts, separable quadratic peak fit, clamped to ±1 px,
    plus the normalized-correlation significance gate. Returns
    (d (gh, gw, 2), significant (gh, gw)). `exact` mirrors the jax
    path's split: the per-region estimator (piecewise field polish)
    vs the index-shifted ring-window formulation (matrix polish)."""
    from kcmc_tpu.ops.polish import region_patches, region_window

    H, W = corrected.shape
    gh, gw = grid
    sh, sw = H // gh, W // gw
    window_frac = 0.25

    def patches(x):
        return region_patches(x, grid)

    if exact:
        w = region_window(sh, sw, window_frac, xp=np, ring=False).astype(
            np.float64
        )

        def zero_mean(p):
            return p - np.sum(w * p, axis=-1, keepdims=True)

        C = zero_mean(patches(corrected))
        T0 = zero_mean(patches(template))
        tpad = np.pad(template, 1, mode="edge")
        cpad = np.pad(corrected, 1, mode="edge")

        def score(dy, dx):
            t = zero_mean(
                patches(tpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W])
            )
            c = zero_mean(
                patches(cpad[1 - dy : 1 - dy + H, 1 - dx : 1 - dx + W])
            )
            return np.sum(w * (C * t + c * T0), axis=-1)

        s_c = score(0, 0)
        s_xm, s_xp = score(0, -1), score(0, 1)
        s_ym, s_yp = score(-1, 0), score(1, 0)
        e_c = np.sum(w * C * C, axis=-1)
        e_t = np.sum(w * T0 * T0, axis=-1)
    else:
        w = region_window(sh, sw, window_frac, xp=np).astype(np.float64)

        def zero_mean(p):
            return p - np.sum(w * p, axis=-1, keepdims=True)

        # index-shifted two-term structure — mirror of the round-5
        # measure_shifts rewrite (template-side arrays shift; the
        # batch side is read once per term)
        CP = patches(corrected).astype(np.float64)
        V = w * zero_mean(CP)
        T0 = zero_mean(patches(template).astype(np.float64))
        shifts = [(0, 0), (0, -1), (0, 1), (-1, 0), (1, 0)]
        tpad = np.pad(template.astype(np.float64), 1, mode="edge")
        t0w = (w * T0).reshape(gh, gw, sh, sw)
        t0w = t0w.swapaxes(1, 2).reshape(gh * sh, gw * sw)
        t0wpad = np.pad(t0w, ((1, 1 + H - gh * sh), (1, 1 + W - gw * sw)))
        scores = [
            np.sum(
                V * patches(tpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W]),
                axis=-1,
            )
            + np.sum(
                CP
                * patches(t0wpad[1 + dy : 1 + dy + H, 1 + dx : 1 + dx + W]),
                axis=-1,
            )
            for dy, dx in shifts
        ]
        s_c, s_xm, s_xp, s_ym, s_yp = scores
        e_c = np.sum(V * CP, axis=-1)
        e_t = np.sum(w * T0 * T0, axis=-1)
    # significance gate — mirror of ops/polish.measure_shifts
    significant = s_c > 0.2 * np.sqrt(e_c * e_t * 4.0) + 1e-12

    def subpixel(sm, sp):
        denom = sm - 2.0 * s_c + sp
        with np.errstate(divide="ignore", invalid="ignore"):
            off = np.where(
                denom < -1e-12, 0.5 * (sm - sp) / denom, np.sign(sp - sm)
            )
        return np.clip(np.where(significant, off, 0.0), -1.0, 1.0)

    d = np.stack(
        [subpixel(s_xm, s_xp), subpixel(s_ym, s_yp)], axis=-1
    ).astype(np.float32)
    return d, significant


def _corr_polish_np(
    corrected: np.ndarray, template: np.ndarray, grid
) -> np.ndarray:
    """NumPy mirror of ops/piecewise.correlation_polish (one frame):
    the negated measured shifts, added to the displacement field."""
    d, _ = _measure_shifts_np(corrected, template, grid, exact=True)
    return -d


def _polish_transform_np(
    corrected: np.ndarray, template: np.ndarray, M: np.ndarray,
    model_name: str, grid,
) -> np.ndarray:
    """NumPy mirror of ops/polish.polish_transforms (one frame):
    measure per-region residual shifts of the warped frame against the
    template, fit the model family's weighted solver to the region
    correspondences (c -> c - d, significance-gated weights), and
    compose M' = M @ A."""
    H, W = corrected.shape
    gh, gw = grid
    d, sig = _measure_shifts_np(corrected, template, grid)
    # Coverage gate — mirror of ops/polish.polish_transforms: regions
    # whose Gaussian window sees the warp's out-of-coverage zeros
    # (>= 2% window mass) measure template content against synthetic
    # black; drop them from the fit.
    from kcmc_tpu.ops.polish import region_patches, region_window

    cov = _coverage_mask_np((H, W), M).astype(np.float64)
    w = region_window(H // gh, W // gw, 0.25, xp=np)
    sig = sig & ((region_patches(cov, grid) * w).sum(-1) >= 0.98)
    cy = (np.arange(gh, dtype=np.float64) + 0.5) * H / gh - 0.5
    cx = (np.arange(gw, dtype=np.float64) + 0.5) * W / gw - 0.5
    centers = np.stack(np.meshgrid(cx, cy, indexing="xy"), axis=-1).reshape(-1, 2)
    wts = sig.reshape(-1).astype(np.float64)
    solve, min_samples, _d = K.SOLVERS[model_name]
    # same well-posedness margin as ops/polish.polish_transforms
    if wts.sum() < 2.0 * min_samples:
        return M
    A = solve(centers, centers - d.reshape(-1, 2), wts)
    if not np.all(np.isfinite(A)):
        return M
    return (M.astype(np.float64) @ A).astype(np.float32)


def _sanitize_nonfinite_np(frame: np.ndarray) -> np.ndarray:
    """Replace non-finite pixels with the frame's finite mean (mirror
    of the jax backend's `sanitize_input` path, for parity)."""
    finite = np.isfinite(frame)
    if finite.all():
        return frame
    mean = frame[finite].mean() if finite.any() else 0.0
    return np.where(finite, frame, np.float32(mean))


@register_backend("numpy")
class NumpyBackend:
    name = "numpy"
    # Plugin-seam version flag: batches may arrive in their native dtype
    # (uint16 etc.); _process_one casts each frame to float32.
    accepts_native_dtype = True

    def __init__(self, config: CorrectorConfig, **_options):
        if config.match_radius is not None:
            # ADVICE r4: silently running the dense matcher here would
            # give a banded config different matcher SEMANTICS per
            # backend (candidate universe, ratio-test second-best,
            # capacity drops) with no warning — refuse instead.
            raise ValueError(
                "backend='numpy' has no banded-matching mirror; "
                "match_radius configs change matcher semantics (bounded "
                "candidate universe, bucket capacities) that the dense "
                "NumPy matcher cannot reproduce. Use backend='jax' for "
                "banded matching, or match_radius=None with the numpy "
                "oracle."
            )
        self.config = config
        # Mesh no-op mirror: the numpy oracle is single-host by nature.
        # `mesh_devices` (and an explicit mesh= option) are accepted and
        # ignored so one config runs on either backend — in particular
        # the degradation ladder's failover from a SHARDED jax run
        # lands here without a config scrub.
        self.mesh = None

    def runtime_info(self) -> dict:
        """Execution-environment description for the run manifest
        (obs/manifest.py) — the numpy oracle runs on the host CPU."""
        import platform

        info = {
            "backend": self.name,
            "numpy": np.__version__,
            "processor": platform.processor() or platform.machine(),
        }
        if self.config.mesh_devices:
            # recorded so a manifest shows the knob was set but unused
            info["mesh_devices_ignored"] = int(self.config.mesh_devices)
        if getattr(self.config, "plan_buckets", ()):
            # Execution plans amortize COMPILATION, which the numpy
            # oracle has none of — buckets are accepted and ignored
            # (like mesh_devices) so a bucketed jax config fails over
            # here without a config scrub; recorded for the manifest.
            info["plan_buckets_ignored"] = [
                list(b) for b in self.config.plan_buckets
            ]
        return info

    def _detect_describe_2d(self, frame: np.ndarray, multi_scale=True):
        """Single-scale detect+describe, or the ORB scale pyramid when
        n_octaves > 1 — the same octave sizes, resize matrices, and
        coordinate mapping as the jax backend (ops/pyramid.py exports
        the JAX-free constants), so cross-backend parity holds for
        multi-scale configs too."""
        cfg = self.config

        def stage(fr, k_octave, border):
            xy, score, valid = K.detect_keypoints(
                fr,
                max_keypoints=k_octave,
                threshold=cfg.detect_threshold,
                nms_size=cfg.nms_size,
                border=border,
                harris_k=cfg.harris_k,
                window_sigma=cfg.harris_window_sigma,
                cand_tile=cfg.cand_tile,
            )
            desc = K.describe_keypoints(
                fr, xy, valid,
                oriented=cfg.resolved_oriented(), blur_sigma=cfg.blur_sigma,
            )
            return xy, score, valid, desc

        if cfg.n_octaves <= 1 or not multi_scale:
            return stage(frame, cfg.max_keypoints, cfg.border)

        from kcmc_tpu.ops.pyramid import (
            octave_sizes,
            per_octave_k,
            resize_matrix,
        )

        H, W = frame.shape
        sizes = octave_sizes((H, W), cfg.n_octaves, cfg.octave_scale)
        ks = per_octave_k(cfg.max_keypoints, cfg.n_octaves)
        xs, ss, vs, ds = [], [], [], []
        for o, ((ho, wo), ko) in enumerate(zip(sizes, ks)):
            if o == 0:
                fr, sx, sy = frame, 1.0, 1.0
            else:
                rh = resize_matrix(H, ho)
                rw = resize_matrix(W, wo)
                fr = (rh @ frame @ rw.T).astype(np.float32)
                sx, sy = W / wo, H / ho
            b = min(cfg.border, min(ho, wo) // 4)
            xy, score, valid, desc = stage(fr, ko, b)
            xs.append((xy + 0.5) * np.float32([sx, sy]) - 0.5)
            ss.append(score)
            vs.append(valid)
            ds.append(desc)
        return (
            np.concatenate(xs).astype(np.float32),
            np.concatenate(ss),
            np.concatenate(vs),
            np.concatenate(ds),
        )

    def prepare_reference(self, ref_frame: np.ndarray) -> dict:
        cfg = self.config
        ref_frame = np.asarray(ref_frame, np.float32)
        if cfg.sanitize_input:
            ref_frame = _sanitize_nonfinite_np(ref_frame)
        if ref_frame.ndim == 3:
            frame = ref_frame
            xyz, score, valid = K.detect_keypoints_3d(
                frame,
                max_keypoints=cfg.max_keypoints,
                threshold=cfg.detect_threshold,
                border=min(cfg.border, min(frame.shape) // 4),
            )
            desc = K.describe_keypoints_3d(
                frame, xyz, valid, blur_sigma=cfg.blur_sigma
            )
            return {"xy": xyz, "desc": desc, "valid": valid, "frame": frame}
        xy, score, valid, desc = self._detect_describe_2d(ref_frame)
        return {"xy": xy, "desc": desc, "valid": valid, "frame": ref_frame}

    def update_reference(
        self, ref: dict, tail_corrected, tail_ok, window: int, alpha: float
    ) -> dict:
        """Host-side mirror of the jax backend's device-resident
        rolling-template seam: same signature, same frame-exact window
        semantics, and BIT-IDENTICAL blend math to the corrector's
        legacy `_rolled_template` path (np.mean over the ok-masked
        window, then the (1-alpha)/alpha blend in the same order)."""
        if not tail_corrected:
            return ref
        frames = np.concatenate(
            [np.asarray(c, np.float32) for c in tail_corrected]
        )[-window:]
        ok = np.concatenate([np.asarray(k, bool) for k in tail_ok])[-window:]
        frames = frames[ok]
        if len(frames) == 0:  # every frame out of warp bounds: keep ref
            return ref
        mean = np.mean(frames, axis=0, dtype=np.float32)
        new_frame = (1.0 - alpha) * np.asarray(
            ref["frame"], np.float32
        ) + alpha * mean
        return self.prepare_reference(new_frame)

    def process_batch(
        self, frames: np.ndarray, ref: dict, frame_indices: np.ndarray
    ) -> dict:
        cfg = self.config
        out: dict[str, list] = {k: [] for k in self._keys()}
        for frame, gidx in zip(frames, frame_indices):
            self._process_one(np.asarray(frame, np.float32), int(gidx), ref, out)
        merged = {k: np.stack(v) for k, v in out.items()}
        if (
            cfg.quality_metrics
            and "corrected" in merged
            and "frame" in ref
            and not ref.get("_skip_quality")
        ):
            masks = coverage_masks_np(merged["corrected"].shape[1:], merged)
            merged["template_corr"] = template_corr_np(
                merged["corrected"], ref["frame"], masks
            )
            merged["coverage"] = masks.mean(
                axis=tuple(range(1, masks.ndim))
            ).astype(np.float32)
        return merged

    def _keys(self):
        cfg = self.config
        base = [
            "corrected", "warp_ok", "n_keypoints", "n_matches",
            "n_inliers", "rms_residual",
        ]
        if (
            cfg.model != "piecewise"
            and cfg.n_octaves > 1
            and cfg.pyramid_refine
        ):
            base.append("coarse_n_matches")
        return base + (["field"] if cfg.model == "piecewise" else ["transform"])

    def _process_one(self, frame, gidx, ref, out):
        cfg = self.config
        if cfg.sanitize_input:
            frame = _sanitize_nonfinite_np(frame)
        if frame.ndim == 3:
            self._process_one_3d(frame, gidx, ref, out)
            return
        xy, score, valid, desc = self._detect_describe_2d(frame)
        idx, dist, second, ok = K.knn_match(
            desc,
            ref["desc"],
            valid,
            ref["valid"],
            ratio=cfg.ratio,
            max_dist=cfg.max_hamming,
            mutual=cfg.mutual,
        )
        src = ref["xy"][idx]
        dst = xy
        rng = np.random.default_rng([cfg.seed, gidx])
        out["n_keypoints"].append(np.int32(valid.sum()))
        out["n_matches"].append(np.int32(ok.sum()))
        out["warp_ok"].append(np.bool_(True))  # gather warp: unbounded

        if cfg.model == "piecewise":
            field, flow, n_in, rms = self._estimate_field(src, dst, ok, rng, frame.shape)
            corrected = K.warp_frame_flow(frame, flow)
            for _ in range(int(cfg.field_polish)):
                # photometric polish — mirror of the jax backend's
                # ops/piecewise.correlation_polish + re-warp
                from kcmc_tpu.utils.synthetic import upsample_field

                field = field + _corr_polish_np(
                    corrected, ref["frame"], cfg.patch_grid
                )
                flow = upsample_field(field, frame.shape)
                corrected = K.warp_frame_flow(frame, flow)
            out["field"].append(field)
            out["corrected"].append(corrected)
            out["n_inliers"].append(np.int32(n_in))
            out["rms_residual"].append(np.float32(rms))
        else:
            M, n_in, inl, rms = K.ransac_estimate(
                cfg.model,
                src,
                dst,
                ok,
                rng,
                n_hypotheses=cfg.n_hypotheses,
                threshold=cfg.inlier_threshold,
                refine_iters=cfg.refine_iters,
            )
            if cfg.n_octaves > 1 and cfg.pyramid_refine:
                # Coarse-to-fine mirror of the jax backend: exactly
                # warp by the coarse multi-scale estimate, re-register
                # single-scale (full-resolution localization), compose
                # ref->frame as M_coarse @ M_residual.
                corrected0 = K.warp_frame(frame, M)
                xy2, _, valid2, desc2 = self._detect_describe_2d(
                    corrected0, multi_scale=False
                )
                idx2, _, _, ok2 = K.knn_match(
                    desc2, ref["desc"], valid2, ref["valid"],
                    ratio=cfg.ratio, max_dist=cfg.max_hamming,
                    mutual=cfg.mutual,
                )
                rng2 = np.random.default_rng([cfg.seed, gidx, 1])
                Mr, n_in, inl, rms = K.ransac_estimate(
                    cfg.model, ref["xy"][idx2], xy2, ok2, rng2,
                    n_hypotheses=cfg.n_hypotheses,
                    threshold=cfg.inlier_threshold,
                    refine_iters=cfg.refine_iters,
                )
                out["coarse_n_matches"].append(out["n_matches"].pop())
                out["n_matches"].append(np.int32(ok2.sum()))
                # the jax backend reports the FINE pass's keypoint
                # count under refine — keep diagnostics parity
                out["n_keypoints"].pop()
                out["n_keypoints"].append(np.int32(valid2.sum()))
                M = (M @ Mr).astype(np.float32)
            corrected = K.warp_frame(frame, M)
            for _ in range(int(cfg.transform_polish)):
                # photometric transform polish — mirror of the jax
                # backend's ops/polish.polish_transforms + re-warp
                M = _polish_transform_np(
                    corrected, ref["frame"], M, cfg.model, cfg.polish_grid
                )
                corrected = K.warp_frame(frame, M)
            out["transform"].append(M)
            out["corrected"].append(corrected)
            out["n_inliers"].append(np.int32(n_in))
            out["rms_residual"].append(np.float32(rms))

    def _process_one_3d(self, frame, gidx, ref, out):
        """Volumetric (rigid3d) mirror of the jax backend's 3D tail."""
        cfg = self.config
        xyz, score, valid = K.detect_keypoints_3d(
            frame,
            max_keypoints=cfg.max_keypoints,
            threshold=cfg.detect_threshold,
            border=min(cfg.border, min(frame.shape) // 4),
        )
        desc = K.describe_keypoints_3d(
            frame, xyz, valid, blur_sigma=cfg.blur_sigma
        )
        idx, dist, second, ok = K.knn_match(
            desc,
            ref["desc"],
            valid,
            ref["valid"],
            ratio=cfg.ratio,
            max_dist=cfg.max_hamming,
            mutual=cfg.mutual,
        )
        src = ref["xy"][idx]
        dst = xyz
        rng = np.random.default_rng([cfg.seed, gidx])
        out["n_keypoints"].append(np.int32(valid.sum()))
        out["n_matches"].append(np.int32(ok.sum()))
        out["warp_ok"].append(np.bool_(True))  # gather warp: unbounded
        M, n_in, inl, rms = K.ransac_estimate(
            cfg.model,
            src,
            dst,
            ok,
            rng,
            n_hypotheses=cfg.n_hypotheses,
            threshold=cfg.inlier_threshold,
            refine_iters=cfg.refine_iters,
        )
        out["transform"].append(M)
        out["corrected"].append(K.warp_volume(frame, M))
        out["n_inliers"].append(np.int32(n_in))
        out["rms_residual"].append(np.float32(rms))

    def _estimate_field(self, src, dst, ok, rng, shape):
        """Mirror of ops/piecewise.estimate_field in NumPy (including
        the residual refinement passes)."""
        cfg = self.config
        gh, gw = cfg.patch_grid
        H, W = shape
        Mg, n_g, inl_g, rms_g = K.ransac_estimate(
            "translation", src, dst, ok, rng,
            n_hypotheses=cfg.n_hypotheses, threshold=cfg.global_threshold,
        )
        g_t = Mg[:2, 2]
        cy = (np.arange(gh, dtype=np.float32) + 0.5) * H / gh - 0.5
        cx = (np.arange(gw, dtype=np.float32) + 0.5) * W / gw - 0.5
        reach = 1.5 * max(H / gh, W / gw)
        thr = cfg.inlier_threshold
        pmodel = cfg.patch_model

        def center_disp(Mp, c):
            # displacement AT the patch center (mirror of the jax
            # backend's per-patch evaluation, incl. the trust region
            # for multi-DoF patch fits)
            return Mp[:2, :2] @ c + Mp[:2, 2] - c

        def clamp(delta, cap):
            nrm = float(np.sqrt((delta**2).sum()) + 1e-12)
            return delta * min(1.0, cap / nrm)

        field = np.zeros((gh, gw, 2), np.float32)
        for i in range(gh):
            for j in range(gw):
                c = np.array([cx[j], cy[i]], np.float32)
                member = inl_g & (((src - c) ** 2).sum(-1) < reach * reach)
                Mp, n_p, _, _ = K.ransac_estimate(
                    pmodel, src, dst, member, rng,
                    n_hypotheses=cfg.patch_hypotheses, threshold=thr,
                )
                disp = g_t + clamp(
                    center_disp(Mp, c) - g_t, 2.0 * cfg.global_threshold
                )
                lam = n_p / (n_p + cfg.patch_prior)
                field[i, j] = lam * disp + (1 - lam) * g_t
        field = self._smooth_field(field, cfg.field_smooth_sigma)

        pitch = max(H / gh, W / gw)
        for it in range(cfg.field_passes - 1):
            # refinement reach shrink (mirror of ops/piecewise.py)
            reach_r = max(
                reach * cfg.refine_reach_scale ** (it + 1), 0.75 * pitch
            )
            pred = self._sample_field_at(field, src, shape)
            resid = dst - src - pred
            gate = inl_g & ((resid**2).sum(-1) < (2.0 * thr) ** 2)
            dst_resid = dst - pred
            r = np.zeros((gh, gw, 2), np.float32)
            for i in range(gh):
                for j in range(gw):
                    c = np.array([cx[j], cy[i]], np.float32)
                    member = gate & (((src - c) ** 2).sum(-1) < reach_r * reach_r)
                    # refine-pass hypothesis budget mirrors the jax
                    # backend (CorrectorConfig.refine_hypotheses)
                    Mp, n_p, _, _ = K.ransac_estimate(
                        pmodel, src, dst_resid, member, rng,
                        n_hypotheses=(
                            cfg.refine_hypotheses or cfg.patch_hypotheses
                        ),
                        threshold=thr,
                    )
                    lam = n_p / (n_p + cfg.patch_prior)
                    r[i, j] = lam * clamp(center_disp(Mp, c), 2.0 * thr)
            field = self._smooth_field(field + r, cfg.field_smooth_sigma)

        from kcmc_tpu.utils.synthetic import upsample_field

        flow = upsample_field(field, shape)
        return field, flow, n_g, rms_g

    @staticmethod
    def _sample_field_at(field, pts, shape):
        """Bilinear sample of a cell-centered (gh, gw, 2) field at
        (N, 2) points (mirror of ops/piecewise.sample_field_at)."""
        gh, gw, _ = field.shape
        H, W = shape
        gx = np.clip((pts[:, 0] + 0.5) * gw / W - 0.5, 0, gw - 1)
        gy = np.clip((pts[:, 1] + 0.5) * gh / H - 0.5, 0, gh - 1)
        x0 = np.floor(gx).astype(np.int32)
        y0 = np.floor(gy).astype(np.int32)
        x1 = np.minimum(x0 + 1, gw - 1)
        y1 = np.minimum(y0 + 1, gh - 1)
        fx = (gx - x0)[:, None]
        fy = (gy - y0)[:, None]
        flat = field.reshape(-1, 2)
        return (
            flat[y0 * gw + x0] * (1 - fx) * (1 - fy)
            + flat[y0 * gw + x1] * fx * (1 - fy)
            + flat[y1 * gw + x0] * (1 - fx) * fy
            + flat[y1 * gw + x1] * fx * fy
        ).astype(np.float32)

    @staticmethod
    def _smooth_field(field, sigma):
        if sigma <= 0:
            return field
        radius = max(1, int(2.0 * sigma + 0.5))
        x = np.arange(-radius, radius + 1, dtype=np.float32)
        k = np.exp(-0.5 * (x / max(sigma, 1e-6)) ** 2)
        k /= k.sum()
        ones = np.ones(field.shape[:2], np.float32)

        def blur(c):
            p = np.pad(c, radius)
            win = np.lib.stride_tricks.sliding_window_view(p, (2 * radius + 1, 2 * radius + 1))
            k2 = np.outer(k, k)
            return np.einsum("ijkl,kl->ij", win, k2, optimize=True)

        num = np.stack([blur(field[..., i]) for i in range(2)], -1)
        den = blur(ones)[..., None]
        return (num / np.maximum(den, 1e-6)).astype(np.float32)
