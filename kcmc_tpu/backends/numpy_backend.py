"""Pure-NumPy CPU backend — the parity oracle.

Implements the same backend protocol as the JAX backend using the
kernels in `_np_kernels`. This is the "CPU backend" of the judged
accuracy metric (BASELINE.md: transform-RMSE parity vs CPU): both
backends implement the identical algorithm, so their recovered
transforms agree to registration accuracy.
"""

from __future__ import annotations

import numpy as np

from kcmc_tpu.backends import register_backend
from kcmc_tpu.backends import _np_kernels as K
from kcmc_tpu.config import CorrectorConfig


def template_corr_np(corrected: np.ndarray, ref_frame: np.ndarray) -> np.ndarray:
    """Per-frame Pearson correlation against the reference (NumPy
    mirror of the jax backend's quality metric; also used by the
    corrector to refresh rescued frames)."""
    axes = tuple(range(1, corrected.ndim))
    c = corrected - corrected.mean(axis=axes, keepdims=True)
    r = ref_frame - ref_frame.mean()
    num = (c * r).sum(axis=axes)
    den = np.sqrt((c * c).sum(axis=axes) * (r * r).sum())
    return (num / np.maximum(den, 1e-12)).astype(np.float32)


@register_backend("numpy")
class NumpyBackend:
    name = "numpy"

    def __init__(self, config: CorrectorConfig, **_options):
        self.config = config
        if config.model == "rigid3d":
            raise NotImplementedError(
                "numpy backend: 3D volumetric path not yet implemented; "
                "use backend='jax'"
            )

    def prepare_reference(self, ref_frame: np.ndarray) -> dict:
        cfg = self.config
        if ref_frame.ndim != 2:
            raise NotImplementedError("numpy backend supports 2D frames")
        xy, score, valid = K.detect_keypoints(
            np.asarray(ref_frame, np.float32),
            max_keypoints=cfg.max_keypoints,
            threshold=cfg.detect_threshold,
            nms_size=cfg.nms_size,
            border=cfg.border,
            harris_k=cfg.harris_k,
        )
        desc = K.describe_keypoints(
            np.asarray(ref_frame, np.float32),
            xy,
            valid,
            oriented=cfg.resolved_oriented(),
            blur_sigma=cfg.blur_sigma,
        )
        return {
            "xy": xy, "desc": desc, "valid": valid,
            "frame": np.asarray(ref_frame, np.float32),
        }

    def process_batch(
        self, frames: np.ndarray, ref: dict, frame_indices: np.ndarray
    ) -> dict:
        cfg = self.config
        out: dict[str, list] = {k: [] for k in self._keys()}
        for frame, gidx in zip(frames, frame_indices):
            self._process_one(np.asarray(frame, np.float32), int(gidx), ref, out)
        merged = {k: np.stack(v) for k, v in out.items()}
        if cfg.quality_metrics and "corrected" in merged and "frame" in ref:
            merged["template_corr"] = template_corr_np(
                merged["corrected"], ref["frame"]
            )
        return merged

    def _keys(self):
        base = [
            "corrected", "warp_ok", "n_keypoints", "n_matches",
            "n_inliers", "rms_residual",
        ]
        return base + (["field"] if self.config.model == "piecewise" else ["transform"])

    def _process_one(self, frame, gidx, ref, out):
        cfg = self.config
        xy, score, valid = K.detect_keypoints(
            frame,
            max_keypoints=cfg.max_keypoints,
            threshold=cfg.detect_threshold,
            nms_size=cfg.nms_size,
            border=cfg.border,
            harris_k=cfg.harris_k,
        )
        desc = K.describe_keypoints(
            frame, xy, valid, oriented=cfg.resolved_oriented(), blur_sigma=cfg.blur_sigma
        )
        idx, dist, second, ok = K.knn_match(
            desc,
            ref["desc"],
            valid,
            ref["valid"],
            ratio=cfg.ratio,
            max_dist=cfg.max_hamming,
            mutual=cfg.mutual,
        )
        src = ref["xy"][idx]
        dst = xy
        rng = np.random.default_rng([cfg.seed, gidx])
        out["n_keypoints"].append(np.int32(valid.sum()))
        out["n_matches"].append(np.int32(ok.sum()))
        out["warp_ok"].append(np.bool_(True))  # gather warp: unbounded

        if cfg.model == "piecewise":
            field, flow, n_in, rms = self._estimate_field(src, dst, ok, rng, frame.shape)
            out["field"].append(field)
            out["corrected"].append(K.warp_frame_flow(frame, flow))
            out["n_inliers"].append(np.int32(n_in))
            out["rms_residual"].append(np.float32(rms))
        else:
            M, n_in, inl, rms = K.ransac_estimate(
                cfg.model,
                src,
                dst,
                ok,
                rng,
                n_hypotheses=cfg.n_hypotheses,
                threshold=cfg.inlier_threshold,
                refine_iters=cfg.refine_iters,
            )
            out["transform"].append(M)
            out["corrected"].append(K.warp_frame(frame, M))
            out["n_inliers"].append(np.int32(n_in))
            out["rms_residual"].append(np.float32(rms))

    def _estimate_field(self, src, dst, ok, rng, shape):
        """Mirror of ops/piecewise.estimate_field in NumPy."""
        cfg = self.config
        gh, gw = cfg.patch_grid
        H, W = shape
        Mg, n_g, inl_g, rms_g = K.ransac_estimate(
            "translation", src, dst, ok, rng,
            n_hypotheses=cfg.n_hypotheses, threshold=cfg.global_threshold,
        )
        g_t = Mg[:2, 2]
        cy = (np.arange(gh, dtype=np.float32) + 0.5) * H / gh - 0.5
        cx = (np.arange(gw, dtype=np.float32) + 0.5) * W / gw - 0.5
        reach = 1.5 * max(H / gh, W / gw)
        field = np.zeros((gh, gw, 2), np.float32)
        for i in range(gh):
            for j in range(gw):
                c = np.array([cx[j], cy[i]], np.float32)
                member = inl_g & (((src - c) ** 2).sum(-1) < reach * reach)
                Mp, n_p, _, _ = K.ransac_estimate(
                    "translation", src, dst, member, rng,
                    n_hypotheses=cfg.patch_hypotheses, threshold=cfg.inlier_threshold,
                )
                lam = n_p / (n_p + cfg.patch_prior)
                field[i, j] = lam * Mp[:2, 2] + (1 - lam) * g_t
        field = self._smooth_field(field, cfg.field_smooth_sigma)
        from kcmc_tpu.utils.synthetic import upsample_field

        flow = upsample_field(field, shape)
        return field, flow, n_g, rms_g

    @staticmethod
    def _smooth_field(field, sigma):
        if sigma <= 0:
            return field
        radius = max(1, int(2.0 * sigma + 0.5))
        x = np.arange(-radius, radius + 1, dtype=np.float32)
        k = np.exp(-0.5 * (x / max(sigma, 1e-6)) ** 2)
        k /= k.sum()
        ones = np.ones(field.shape[:2], np.float32)

        def blur(c):
            p = np.pad(c, radius)
            win = np.lib.stride_tricks.sliding_window_view(p, (2 * radius + 1, 2 * radius + 1))
            k2 = np.outer(k, k)
            return np.einsum("ijkl,kl->ij", win, k2, optimize=True)

        num = np.stack([blur(field[..., i]) for i in range(2)], -1)
        den = blur(ones)[..., None]
        return (num / np.maximum(den, 1e-6)).astype(np.float32)
