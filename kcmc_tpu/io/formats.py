"""Pluggable streaming ingest: one reader protocol, many formats.

SURVEY.md §1 names the stack-I/O layer "TIFF/array ingest"; the
microscopy ecosystem this targets ships HDF5 / Zarr / raw-binary stacks
as often as TIFF. The whole file-scale streaming machinery (prefetch
thread, checkpoint-resume, stall watchdog, registration-only passes)
only needs the small duck-typed protocol `TiffStack` already satisfies:

    len(reader)            -> frame count
    reader.frame_shape     -> per-frame shape tuple
    reader.dtype           -> numpy dtype of stored frames
    reader.read(lo, hi)    -> (hi-lo, *frame_shape) ndarray
    context manager        -> closes underlying handles

This module provides that protocol over:

* ``ZarrStack``   — Zarr v2 directory stores. Uses the ``zarr`` package
  when installed; otherwise a built-in pure-Python reader handles the
  common case (C-order, 3D/4D, raw/zlib/gzip chunks) with an explicit
  error for exotic compressors. No hard dependency either way.
* ``HDF5Stack``   — HDF5 datasets via ``h5py`` (guarded import), with
  single-3D-dataset auto-discovery.
* ``NpyStack``    — ``.npy`` arrays, memory-mapped (zero-copy slicing).
* ``RawStack``    — headerless binary via ``np.memmap`` (shape + dtype
  supplied by the caller).
* ``ArrayStack``  — any in-memory array-like with axis-0 slicing.

``open_stack`` dispatches on extension / source type and is what
``MotionCorrector.correct_file`` uses, so ``correct_file("stack.zarr",
checkpoint=...)`` streams with the same kill-safe resume machinery as a
TIFF. Output writing stays TIFF (the one format with a native threaded
encoder here); registration-only runs have no output file at all.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np


class _BaseStack:
    """Context-manager plumbing shared by the readers."""

    frame_shape: tuple
    dtype: np.dtype

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):  # pragma: no cover - trivial default
        pass

    def __len__(self) -> int:
        return self._n


class ArrayStack(_BaseStack):
    """Adapter for any array-like with numpy-style axis-0 slicing
    (ndarray, memmap, dask/zarr arrays, h5py datasets...)."""

    def __init__(self, source):
        if getattr(source, "ndim", len(getattr(source, "shape", ()))) not in (3, 4):
            raise ValueError(
                "stack source must be 3D (T, H, W) or 4D (T, D, H, W), "
                f"got shape {getattr(source, 'shape', None)}"
            )
        self.source = source
        self._n = source.shape[0]
        self.frame_shape = tuple(source.shape[1:])
        self.dtype = np.dtype(source.dtype)

    def read(self, lo: int, hi: int) -> np.ndarray:
        return np.asarray(self.source[lo:hi])


class NpyStack(ArrayStack):
    """A ``.npy`` stack, memory-mapped: reads touch only the sliced
    frames, so 100 GB files stream fine."""

    def __init__(self, path):
        super().__init__(np.load(path, mmap_mode="r"))


class RawStack(ArrayStack):
    """Headerless binary: caller supplies shape and dtype (the usual
    acquisition-software dump: fixed-size frames, C order, optional
    fixed header skipped via ``offset`` bytes)."""

    def __init__(self, path, shape, dtype, offset: int = 0):
        mm = np.memmap(
            path, dtype=np.dtype(dtype), mode="r", offset=int(offset),
            shape=tuple(int(s) for s in shape),
        )
        super().__init__(mm)


class HDF5Stack(_BaseStack):
    """An HDF5 dataset. `dataset` names it; omitted, the file must
    contain exactly one 3D/4D dataset (auto-discovered)."""

    def __init__(self, path, dataset: str | None = None):
        try:
            import h5py
        except ImportError as e:  # pragma: no cover - present on image
            raise ImportError(
                "HDF5 ingest needs the optional h5py package"
            ) from e
        self._f = h5py.File(path, "r")
        if dataset is None:
            cands = []

            def visit(name, obj):
                if isinstance(obj, h5py.Dataset) and obj.ndim in (3, 4):
                    cands.append(name)

            self._f.visititems(visit)
            if len(cands) != 1:
                self._f.close()
                raise ValueError(
                    f"{path}: expected exactly one 3D/4D dataset, found "
                    f"{cands or 'none'} — pass dataset='name'"
                )
            dataset = cands[0]
        self._d = self._f[dataset]
        if self._d.ndim not in (3, 4):
            self._f.close()
            raise ValueError(
                f"dataset {dataset!r} is {self._d.ndim}D, need 3D/4D"
            )
        self._n = self._d.shape[0]
        self.frame_shape = tuple(self._d.shape[1:])
        self.dtype = np.dtype(self._d.dtype)

    def read(self, lo: int, hi: int) -> np.ndarray:
        return np.asarray(self._d[lo:hi])

    def close(self):
        self._f.close()


class _MiniZarr:
    """Pure-Python Zarr v2 array reader: C-order, raw/zlib/gzip chunks.

    Covers the stores scientific pipelines commonly write without
    pulling in the zarr/numcodecs stack; anything fancier (blosc, F
    order, filters) gets an explicit error pointing at the optional
    dependency.
    """

    def __init__(self, path):
        self.path = path
        with open(os.path.join(path, ".zarray")) as f:
            meta = json.load(f)
        if meta.get("zarr_format") != 2:
            raise ValueError(f"{path}: only zarr v2 stores supported")
        if meta.get("order", "C") != "C":
            raise ValueError(
                f"{path}: F-order store needs the optional zarr package"
            )
        if meta.get("filters"):
            raise ValueError(
                f"{path}: filtered store needs the optional zarr package"
            )
        comp = meta.get("compressor")
        cid = None if comp is None else comp.get("id")
        if cid not in (None, "zlib", "gzip"):
            raise ValueError(
                f"{path}: compressor {cid!r} needs the optional zarr "
                "package (built-in reader handles raw/zlib/gzip)"
            )
        self._zlib = cid is not None
        self.shape = tuple(meta["shape"])
        self.chunks = tuple(meta["chunks"])
        self.dtype = np.dtype(meta["dtype"])
        self.fill = meta.get("fill_value", 0) or 0
        self.sep = meta.get("dimension_separator", ".")
        self.ndim = len(self.shape)

    def _chunk(self, idx) -> np.ndarray:
        name = self.sep.join(str(i) for i in idx)
        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return np.full(self.chunks, self.fill, self.dtype)
        with open(p, "rb") as f:
            buf = f.read()
        if self._zlib:
            # zlib stream or gzip wrapper — wbits=47 accepts both
            buf = zlib.decompress(buf, 47)
        return np.frombuffer(buf, self.dtype).reshape(self.chunks)

    def __getitem__(self, sl) -> np.ndarray:
        lo, hi = sl.start or 0, sl.stop if sl.stop is not None else self.shape[0]
        hi = min(hi, self.shape[0])
        out = np.empty((hi - lo,) + self.shape[1:], self.dtype)
        c0 = self.chunks[0]
        grids = [
            -(-s // c) for s, c in zip(self.shape[1:], self.chunks[1:])
        ]
        for ci in range(lo // c0, -(-hi // c0)):
            t0 = ci * c0
            s_lo, s_hi = max(lo, t0), min(hi, t0 + c0)
            idx_rest = np.ndindex(*grids)
            for rest in idx_rest:
                chunk = self._chunk((ci,) + rest)
                # destination window of this chunk in the spatial dims
                dst = [slice(s_lo - lo, s_hi - lo)]
                src = [slice(s_lo - t0, s_hi - t0)]
                ok = True
                for d, (ri, c, s) in enumerate(
                    zip(rest, self.chunks[1:], self.shape[1:])
                ):
                    a, b = ri * c, min((ri + 1) * c, s)
                    if a >= b:
                        ok = False
                        break
                    dst.append(slice(a, b))
                    src.append(slice(0, b - a))
                if ok:
                    out[tuple(dst)] = chunk[tuple(src)]
        return out


class ZarrStack(ArrayStack):
    """A Zarr v2 array store (directory). Prefers the optional ``zarr``
    package (full format coverage); falls back to the built-in reader
    for plain C-order raw/zlib/gzip stores."""

    def __init__(self, path):
        path = os.fspath(path)
        try:
            import zarr  # optional

            arr = zarr.open_array(path, mode="r")
        except ImportError:
            arr = _MiniZarr(path)
        if len(arr.shape) not in (3, 4):
            raise ValueError(
                f"{path}: zarr array is {len(arr.shape)}D, need 3D/4D"
            )
        super().__init__(arr)


def open_stack(source, n_threads: int = 0, **reader_options):
    """Open any supported stack source with the streaming-reader
    protocol.

    source: a path (dispatched on extension: .tif/.tiff, .zarr
    directory, .h5/.hdf5, .npy, .raw/.bin/.dat), an object already
    implementing the protocol (returned as-is), or an array-like
    (wrapped in ArrayStack). reader_options are format-specific
    (HDF5Stack's ``dataset``, RawStack's ``shape``/``dtype``/
    ``offset``).
    """
    def no_options(fmt):
        # Silently absorbing options a format doesn't take would let a
        # stale reader_options dict (e.g. an HDF5 dataset= against a
        # TIFF) "succeed" while reading something else entirely.
        if reader_options:
            raise ValueError(
                f"{fmt} sources take no reader_options, got "
                f"{sorted(reader_options)}"
            )

    if not isinstance(source, (str, os.PathLike)):
        no_options("array/reader")
        if hasattr(source, "read") and hasattr(source, "frame_shape"):
            return source  # already a protocol reader
        return ArrayStack(source)
    path = os.fspath(source)
    ext = os.path.splitext(path)[1].lower()
    if ext in (".tif", ".tiff"):
        from kcmc_tpu.io.tiff import TiffStack

        no_options("TIFF")
        return TiffStack(path, n_threads=n_threads)
    if ext == ".zarr" or os.path.isdir(path) and os.path.exists(
        os.path.join(path, ".zarray")
    ):
        no_options("Zarr")
        return ZarrStack(path)
    if ext in (".h5", ".hdf5"):
        return HDF5Stack(path, **reader_options)
    if ext == ".npy":
        no_options(".npy")
        return NpyStack(path)
    if ext in (".raw", ".bin", ".dat"):
        return RawStack(path, **reader_options)
    raise ValueError(
        f"unrecognized stack format {ext!r} for {path} — supported: "
        ".tif/.tiff, .zarr, .h5/.hdf5, .npy, .raw/.bin/.dat, or pass "
        "an array / reader object"
    )
