"""Pluggable streaming ingest: one reader protocol, many formats.

SURVEY.md §1 names the stack-I/O layer "TIFF/array ingest"; the
microscopy ecosystem this targets ships HDF5 / Zarr / raw-binary stacks
as often as TIFF. The whole file-scale streaming machinery (prefetch
thread, checkpoint-resume, stall watchdog, registration-only passes)
only needs the small duck-typed protocol `TiffStack` already satisfies:

    len(reader)            -> frame count
    reader.frame_shape     -> per-frame shape tuple
    reader.dtype           -> numpy dtype of stored frames
    reader.read(lo, hi)    -> (hi-lo, *frame_shape) ndarray
    context manager        -> closes underlying handles

This module provides that protocol over:

* ``ZarrStack``   — Zarr v2 directory stores. Uses the ``zarr`` package
  when installed; otherwise a built-in pure-Python reader handles the
  common case (C-order, 3D/4D, raw/zlib/gzip chunks) with an explicit
  error for exotic compressors. No hard dependency either way.
* ``HDF5Stack``   — HDF5 datasets via ``h5py`` (guarded import), with
  single-3D-dataset auto-discovery.
* ``NpyStack``    — ``.npy`` arrays, memory-mapped (zero-copy slicing).
* ``RawStack``    — headerless binary via ``np.memmap`` (shape + dtype
  supplied by the caller).
* ``ArrayStack``  — any in-memory array-like with axis-0 slicing.

``open_stack`` dispatches on extension / source type and is what
``MotionCorrector.correct_file`` uses, so ``correct_file("stack.zarr",
checkpoint=...)`` streams with the same kill-safe resume machinery as a
TIFF. Since round 5 the WRITE side is pluggable too: ``ZarrWriter``
implements the TiffWriter streaming protocol (incremental append,
checkpoint_state/resume, parallel deflate) over a Zarr v2 directory
store, and ``HDF5Writer`` the same over a contiguous early-allocated
HDF5 dataset (uncompressed — the layout that keeps SIGKILL from
corrupting HDF5 metadata), so ``correct_file("in.zarr",
output="out.zarr")`` and ``correct_file("in.h5", output="out.h5")``
round-trip without transcoding to TIFF. Registration-only runs have no
output file at all.
"""

from __future__ import annotations

import json
import os
import zlib

import numpy as np


class _BaseStack:
    """Context-manager plumbing shared by the readers."""

    frame_shape: tuple
    dtype: np.dtype

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):  # pragma: no cover - trivial default
        pass

    def __len__(self) -> int:
        return self._n


class ArrayStack(_BaseStack):
    """Adapter for any array-like with numpy-style axis-0 slicing
    (ndarray, memmap, dask/zarr arrays, h5py datasets...)."""

    def __init__(self, source):
        if getattr(source, "ndim", len(getattr(source, "shape", ()))) not in (3, 4):
            raise ValueError(
                "stack source must be 3D (T, H, W) or 4D (T, D, H, W), "
                f"got shape {getattr(source, 'shape', None)}"
            )
        self.source = source
        self._n = source.shape[0]
        self.frame_shape = tuple(source.shape[1:])
        self.dtype = np.dtype(source.dtype)

    def read(self, lo: int, hi: int) -> np.ndarray:
        return np.asarray(self.source[lo:hi])


class NpyStack(ArrayStack):
    """A ``.npy`` stack, memory-mapped: reads touch only the sliced
    frames, so 100 GB files stream fine."""

    def __init__(self, path):
        super().__init__(np.load(path, mmap_mode="r"))


class RawStack(ArrayStack):
    """Headerless binary: caller supplies shape and dtype (the usual
    acquisition-software dump: fixed-size frames, C order, optional
    fixed header skipped via ``offset`` bytes)."""

    def __init__(self, path, shape, dtype, offset: int = 0):
        mm = np.memmap(
            path, dtype=np.dtype(dtype), mode="r", offset=int(offset),
            shape=tuple(int(s) for s in shape),
        )
        super().__init__(mm)


class HDF5Stack(_BaseStack):
    """An HDF5 dataset. `dataset` names it; omitted, the file must
    contain exactly one 3D/4D dataset (auto-discovered)."""

    def __init__(self, path, dataset: str | None = None):
        try:
            import h5py
        except ImportError as e:  # pragma: no cover - present on image
            raise ImportError(
                "HDF5 ingest needs the optional h5py package"
            ) from e
        self._f = h5py.File(path, "r")
        if dataset is None:
            cands = []

            def visit(name, obj):
                if isinstance(obj, h5py.Dataset) and obj.ndim in (3, 4):
                    cands.append(name)

            self._f.visititems(visit)
            if len(cands) != 1:
                self._f.close()
                raise ValueError(
                    f"{path}: expected exactly one 3D/4D dataset, found "
                    f"{cands or 'none'} — pass dataset='name'"
                )
            dataset = cands[0]
        self._d = self._f[dataset]
        if self._d.ndim not in (3, 4):
            self._f.close()
            raise ValueError(
                f"dataset {dataset!r} is {self._d.ndim}D, need 3D/4D"
            )
        self._n = self._d.shape[0]
        self.frame_shape = tuple(self._d.shape[1:])
        self.dtype = np.dtype(self._d.dtype)

    def read(self, lo: int, hi: int) -> np.ndarray:
        return np.asarray(self._d[lo:hi])

    def close(self):
        self._f.close()


class _MiniZarr:
    """Pure-Python Zarr v2 array reader: C-order, raw/zlib/gzip chunks.

    Covers the stores scientific pipelines commonly write without
    pulling in the zarr/numcodecs stack; anything fancier (blosc, F
    order, filters) gets an explicit error pointing at the optional
    dependency.
    """

    def __init__(self, path):
        self.path = path
        with open(os.path.join(path, ".zarray")) as f:
            meta = json.load(f)
        if meta.get("zarr_format") != 2:
            raise ValueError(f"{path}: only zarr v2 stores supported")
        if meta.get("order", "C") != "C":
            raise ValueError(
                f"{path}: F-order store needs the optional zarr package"
            )
        if meta.get("filters"):
            raise ValueError(
                f"{path}: filtered store needs the optional zarr package"
            )
        comp = meta.get("compressor")
        cid = None if comp is None else comp.get("id")
        if cid not in (None, "zlib", "gzip"):
            raise ValueError(
                f"{path}: compressor {cid!r} needs the optional zarr "
                "package (built-in reader handles raw/zlib/gzip)"
            )
        self._zlib = cid is not None
        self.shape = tuple(meta["shape"])
        self.chunks = tuple(meta["chunks"])
        self.dtype = np.dtype(meta["dtype"])
        self.fill = meta.get("fill_value", 0) or 0
        self.sep = meta.get("dimension_separator", ".")
        self.ndim = len(self.shape)

    def _chunk(self, idx) -> np.ndarray:
        name = self.sep.join(str(i) for i in idx)
        p = os.path.join(self.path, name)
        if not os.path.exists(p):
            return np.full(self.chunks, self.fill, self.dtype)
        with open(p, "rb") as f:
            buf = f.read()
        if self._zlib:
            # zlib stream or gzip wrapper — wbits=47 accepts both
            buf = zlib.decompress(buf, 47)
        return np.frombuffer(buf, self.dtype).reshape(self.chunks)

    def __getitem__(self, sl) -> np.ndarray:
        lo, hi = sl.start or 0, sl.stop if sl.stop is not None else self.shape[0]
        hi = min(hi, self.shape[0])
        out = np.empty((hi - lo,) + self.shape[1:], self.dtype)
        c0 = self.chunks[0]
        grids = [
            -(-s // c) for s, c in zip(self.shape[1:], self.chunks[1:])
        ]
        for ci in range(lo // c0, -(-hi // c0)):
            t0 = ci * c0
            s_lo, s_hi = max(lo, t0), min(hi, t0 + c0)
            idx_rest = np.ndindex(*grids)
            for rest in idx_rest:
                chunk = self._chunk((ci,) + rest)
                # destination window of this chunk in the spatial dims
                dst = [slice(s_lo - lo, s_hi - lo)]
                src = [slice(s_lo - t0, s_hi - t0)]
                ok = True
                for _d, (ri, c, s) in enumerate(
                    zip(rest, self.chunks[1:], self.shape[1:])
                ):
                    a, b = ri * c, min((ri + 1) * c, s)
                    if a >= b:
                        ok = False
                        break
                    dst.append(slice(a, b))
                    src.append(slice(0, b - a))
                if ok:
                    out[tuple(dst)] = chunk[tuple(src)]
        return out


class ZarrStack(ArrayStack):
    """A Zarr v2 array store (directory). Prefers the optional ``zarr``
    package (full format coverage); falls back to the built-in reader
    for plain C-order raw/zlib/gzip stores."""

    def __init__(self, path):
        path = os.fspath(path)
        self.path = path
        try:
            import zarr  # optional

            arr = zarr.open_array(path, mode="r")
        except ImportError:
            arr = _MiniZarr(path)
        if len(arr.shape) not in (3, 4):
            raise ValueError(
                f"{path}: zarr array is {len(arr.shape)}D, need 3D/4D"
            )
        super().__init__(arr)


class ZarrWriter:
    """Incremental Zarr v2 directory-store writer with the TiffWriter
    streaming protocol: frames append one (or one batch) at a time as
    the stream comes off the device, with kill-safe checkpoint/resume.

    Layout: C-order, chunks of ONE frame ((1, *frame_shape) — the
    time-chunked layout streaming pipelines re-read), dimension
    separator ".", compression "none" or "deflate" (zlib level 6, the
    same codec/level as the TIFF deflate path). One chunk file per
    frame makes resume semantics trivial: chunks below the checkpoint
    cursor were completely written before the checkpoint saved, a torn
    tail chunk is simply overwritten when its frame is re-appended,
    and — unlike TIFF — there is no offset chain, so already-written
    bytes can never be perturbed by a resume.
    """

    def __init__(
        self,
        path,
        n_frames: int,
        frame_shape: tuple,
        dtype,
        compression: str = "none",
    ):
        if compression not in ("none", "deflate"):
            raise ValueError(
                "zarr output supports compression 'none' or 'deflate', "
                f"got {compression!r}"
            )
        self.path = os.fspath(path)
        self.compression = compression
        self.shape = (int(n_frames),) + tuple(int(s) for s in frame_shape)
        self.dtype = np.dtype(dtype)
        os.makedirs(self.path, exist_ok=True)
        # fresh construction = fresh run: drop stale chunk entries from
        # a previous (different) run so a shorter rerun can't leave a
        # mix. Nested layouts (dimension_separator "/", which the
        # READER supports) store chunks as subdirectories — remove
        # those trees too, not just flat files.
        import shutil

        for name in os.listdir(self.path):
            if name[:1].isdigit():
                p = os.path.join(self.path, name)
                if os.path.isdir(p):
                    shutil.rmtree(p)
                else:
                    os.remove(p)
        meta = {
            "zarr_format": 2,
            "shape": list(self.shape),
            "chunks": [1] + list(self.shape[1:]),
            "dtype": self.dtype.str,
            "compressor": (
                {"id": "zlib", "level": 6}
                if compression == "deflate" else None
            ),
            "fill_value": 0,
            "order": "C",
            "filters": None,
            "dimension_separator": ".",
        }
        with open(os.path.join(self.path, ".zarray"), "w") as f:
            json.dump(meta, f)
        self.n_pages = 0

    def _chunk_path(self, t: int) -> str:
        name = ".".join([str(t)] + ["0"] * (len(self.shape) - 1))
        return os.path.join(self.path, name)

    def _encode(self, frame: np.ndarray) -> bytes:
        raw = np.ascontiguousarray(frame, self.dtype).tobytes()
        return zlib.compress(raw, 6) if self.compression == "deflate" else raw

    def append_batch(self, frames: np.ndarray, n_threads: int = 0) -> None:
        frames = np.asarray(frames)
        if tuple(frames.shape[1:]) != self.shape[1:]:
            raise ValueError(
                f"frame shape {frames.shape[1:]} != store {self.shape[1:]}"
            )
        if self.n_pages + len(frames) > self.shape[0]:
            raise ValueError(
                f"appending {len(frames)} frames past the store's "
                f"{self.shape[0]}-frame shape (at {self.n_pages})"
            )
        if n_threads > 1 and self.compression == "deflate":
            # zlib releases the GIL on large buffers; encode in parallel,
            # write in order (same thread-budget contract as TiffWriter)
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(n_threads) as ex:
                blobs = list(ex.map(self._encode, frames))
        else:
            blobs = [self._encode(f) for f in frames]
        for blob in blobs:
            with open(self._chunk_path(self.n_pages), "wb") as f:
                f.write(blob)
            self.n_pages += 1

    def checkpoint_state(self) -> dict:
        return {
            "format": "zarr",
            "n_pages": int(self.n_pages),
            # recorded for parity with the TIFF deflate pin; zarr resume
            # never re-touches written bytes, so a zlib build change
            # only affects frames not yet written
            "zlib": zlib.ZLIB_RUNTIME_VERSION,
        }

    @classmethod
    def resume(cls, path, state: dict, compression: str = "none") -> "ZarrWriter":
        path = os.fspath(path)
        if state.get("format") != "zarr":
            raise OSError(f"{path}: checkpoint writer state is not zarr")
        try:
            with open(os.path.join(path, ".zarray")) as f:
                meta = json.load(f)
        except (ValueError, KeyError) as e:
            # torn/corrupt metadata must surface as OSError — the
            # corrector's resume handler restarts from scratch on
            # OSError, exactly like a torn TIFF
            raise OSError(
                f"{path}: unreadable .zarray at resume: {e}"
            ) from e
        self = object.__new__(cls)
        self.path = path
        self.compression = compression
        self.shape = tuple(meta["shape"])
        self.dtype = np.dtype(meta["dtype"])
        comp = meta.get("compressor")
        want = {"id": "zlib", "level": 6} if compression == "deflate" else None
        if comp != want:
            raise OSError(
                f"{path}: store compressor {comp} does not match the "
                f"resume compression {compression!r}"
            )
        try:
            n = int(state["n_pages"])
        except (KeyError, TypeError, ValueError) as e:
            raise OSError(
                f"{path}: malformed zarr writer state: {e}"
            ) from e
        # all checkpointed chunks must exist (the output is the
        # persistence layer, exactly like the TIFF resume contract)
        if n > 0 and not os.path.exists(self._chunk_path(n - 1)):
            raise OSError(f"{path}: chunk {n - 1} missing at resume")
        self.n_pages = n
        return self

    def close(self):
        pass


class HDF5Writer:
    """Incremental HDF5 writer with the TiffWriter streaming protocol.

    Kill-safety is the design constraint: HDF5's chunked layout updates
    a B-tree on every chunk write, and a SIGKILL mid-update can corrupt
    the FILE — not just the tail frame — which would break the resume
    contract (already-written frames must survive any kill). So the
    dataset is CONTIGUOUS with early allocation: all space and all
    metadata are written at creation, after which appends are pure data
    writes at fixed offsets (raw-file semantics — a torn tail frame is
    simply overwritten when re-appended; the resumed DATASET is
    bit-identical to an uninterrupted run's, though whole-file bytes
    are not — HDF5 object headers embed creation timestamps). Contiguous layout cannot
    compress; `compression="deflate"` is refused with a pointer to the
    `.zarr` egress, whose one-chunk-per-frame layout compresses AND
    keeps the same kill-safety.
    """

    dataset_name = "data"

    def __init__(
        self,
        path,
        n_frames: int,
        frame_shape: tuple,
        dtype,
        compression: str = "none",
    ):
        import h5py

        if compression != "none":
            raise ValueError(
                "HDF5 egress is uncompressed (contiguous layout is what "
                "makes kill+resume safe — chunked+gzip HDF5 can corrupt "
                "the whole file on SIGKILL); use a .zarr output for "
                "compressed kill-safe egress"
            )
        self.path = os.fspath(path)
        self.compression = compression
        self.shape = (int(n_frames),) + tuple(int(s) for s in frame_shape)
        self.dtype = np.dtype(dtype)
        self._f = h5py.File(self.path, "w")
        # contiguous + ALLOC_TIME_EARLY: the whole dataset (and every
        # byte of metadata) exists on disk before the first append
        space = h5py.h5s.create_simple(self.shape)
        dcpl = h5py.h5p.create(h5py.h5p.DATASET_CREATE)
        dcpl.set_layout(h5py.h5d.CONTIGUOUS)
        dcpl.set_alloc_time(h5py.h5d.ALLOC_TIME_EARLY)
        h5py.h5d.create(
            self._f.id, self.dataset_name.encode(),
            h5py.h5t.py_create(self.dtype, logical=True), space, dcpl,
        ).close()
        self._f.flush()
        self._d = self._f[self.dataset_name]
        self.n_pages = 0

    def append_batch(self, frames: np.ndarray, n_threads: int = 0) -> None:
        del n_threads  # uncompressed: the write is I/O-bound
        frames = np.asarray(frames)
        if tuple(frames.shape[1:]) != self.shape[1:]:
            raise ValueError(
                f"frame shape {frames.shape[1:]} != dataset {self.shape[1:]}"
            )
        if self.n_pages + len(frames) > self.shape[0]:
            raise ValueError(
                f"appending {len(frames)} frames past the dataset's "
                f"{self.shape[0]}-frame shape (at {self.n_pages})"
            )
        self._d[self.n_pages : self.n_pages + len(frames)] = frames.astype(
            self.dtype, copy=False
        )
        self._f.flush()
        self.n_pages += len(frames)

    def checkpoint_state(self) -> dict:
        return {"format": "hdf5", "n_pages": int(self.n_pages)}

    @classmethod
    def resume(cls, path, state: dict, compression: str = "none") -> "HDF5Writer":
        import h5py

        path = os.fspath(path)
        if state.get("format") != "hdf5":
            raise OSError(f"{path}: checkpoint writer state is not hdf5")
        if compression != "none":
            raise OSError(
                f"{path}: HDF5 egress is uncompressed; resume asked for "
                f"{compression!r}"
            )
        self = object.__new__(cls)
        self.path = path
        self.compression = compression
        try:
            self._f = h5py.File(path, "r+")
            self._d = self._f[cls.dataset_name]
        except (OSError, KeyError) as e:
            raise OSError(
                f"{path}: unreadable HDF5 output at resume: {e}"
            ) from e
        self.shape = tuple(self._d.shape)
        self.dtype = np.dtype(self._d.dtype)
        try:
            n = int(state["n_pages"])
        except (KeyError, TypeError, ValueError) as e:
            raise OSError(
                f"{path}: malformed hdf5 writer state: {e}"
            ) from e
        if n > self.shape[0]:
            raise OSError(
                f"{path}: checkpoint cursor {n} beyond dataset "
                f"length {self.shape[0]}"
            )
        self.n_pages = n
        return self

    def close(self):
        self._f.close()


def make_writer(
    output, n_frames: int, frame_shape: tuple, dtype,
    compression: str = "none", bigtiff: bool = False,
    object_opts: dict | None = None,
):
    """Streaming-writer factory: dispatch on the output extension
    (.zarr -> ZarrWriter, .h5/.hdf5 -> HDF5Writer, object-store URLs
    -> ObjectStoreWriter, else TiffWriter). `object_opts` carries the
    object-path robustness wiring (chunk_frames/part_bytes/fault_plan/
    retry/report/tracer/client) and applies to URL outputs only."""
    from kcmc_tpu.io import objectstore

    if objectstore.is_object_url(output):
        opts = dict(object_opts or {})
        return objectstore.ObjectStoreWriter(
            output, n_frames, frame_shape, dtype,
            compression=compression, **opts,
        )
    out = os.fspath(output).lower()
    if out.endswith(".zarr"):
        return ZarrWriter(
            output, n_frames, frame_shape, dtype, compression=compression
        )
    if out.endswith((".h5", ".hdf5")):
        return HDF5Writer(
            output, n_frames, frame_shape, dtype, compression=compression
        )
    from kcmc_tpu.io.tiff import TiffWriter

    return TiffWriter(output, compression=compression, bigtiff=bigtiff)


def resume_writer(
    output, state: dict, compression: str = "none",
    object_opts: dict | None = None,
):
    """Resume-side counterpart of `make_writer`."""
    from kcmc_tpu.io import objectstore

    if objectstore.is_object_url(output):
        return objectstore.ObjectStoreWriter.resume(
            output, state, compression=compression, object_opts=object_opts
        )
    out = os.fspath(output).lower()
    if out.endswith(".zarr"):
        return ZarrWriter.resume(output, state, compression=compression)
    if out.endswith((".h5", ".hdf5")):
        return HDF5Writer.resume(output, state, compression=compression)
    from kcmc_tpu.io.tiff import TiffWriter

    return TiffWriter.resume(output, state, compression=compression)


def open_stack(source, n_threads: int = 0, **reader_options):
    """Open any supported stack source with the streaming-reader
    protocol.

    source: a path (dispatched on extension: .tif/.tiff, .zarr
    directory, .h5/.hdf5, .npy, .raw/.bin/.dat), an object-store URL
    (``emu://...`` -> ObjectStack over the chunked bucket layout), an
    object already implementing the protocol (returned as-is), or an
    array-like (wrapped in ArrayStack). reader_options are
    format-specific (HDF5Stack's ``dataset``, RawStack's ``shape``/
    ``dtype``/``offset``).
    """
    def no_options(fmt):
        # Silently absorbing options a format doesn't take would let a
        # stale reader_options dict (e.g. an HDF5 dataset= against a
        # TIFF) "succeed" while reading something else entirely.
        if reader_options:
            raise ValueError(
                f"{fmt} sources take no reader_options, got "
                f"{sorted(reader_options)}"
            )

    if not isinstance(source, (str, os.PathLike)):
        no_options("array/reader")
        if hasattr(source, "read") and hasattr(source, "frame_shape"):
            return source  # already a protocol reader
        return ArrayStack(source)
    from kcmc_tpu.io import objectstore

    if objectstore.is_object_url(source):
        no_options("object-store")
        return objectstore.ObjectStack(source, n_threads=n_threads)
    path = os.fspath(source)
    ext = os.path.splitext(path)[1].lower()
    if ext in (".tif", ".tiff"):
        from kcmc_tpu.io.tiff import TiffStack

        opts = dict(reader_options)
        force_python = bool(opts.pop("force_python", False))
        if opts:
            raise ValueError(
                f"TIFF sources take no reader_options beyond "
                f"'force_python', got {sorted(opts)}"
            )
        return TiffStack(
            path, n_threads=n_threads, force_python=force_python
        )
    if ext == ".zarr" or os.path.isdir(path) and os.path.exists(
        os.path.join(path, ".zarray")
    ):
        no_options("Zarr")
        return ZarrStack(path)
    if ext in (".h5", ".hdf5"):
        return HDF5Stack(path, **reader_options)
    if ext == ".npy":
        no_options(".npy")
        return NpyStack(path)
    if ext in (".raw", ".bin", ".dat"):
        return RawStack(path, **reader_options)
    raise ValueError(
        f"unrecognized stack format {ext!r} for {path} — supported: "
        ".tif/.tiff, .zarr, .h5/.hdf5, .npy, .raw/.bin/.dat, or pass "
        "an array / reader object"
    )
