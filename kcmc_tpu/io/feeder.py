"""Feeder-side scale-out: process-based decode pools + sharded ingest.

The device path registers ~4k frames/sec/chip while host TIFF decode
binds at 1.6-2.2k fps uncompressed and ~233 fps on the single-core
pure-Python deflate fallback (docs/PERFORMANCE.md "What binds where") —
so the PR-5 mesh multiplies compute the PR-2 single-producer prefetch
thread cannot fill. This module is the host half of closing that gap:

* **DecodePool** — a pool of decode workers. Two flavors behind one
  interface: ``kind="process"`` (a spawn-context ProcessPoolExecutor)
  for the GIL-bound pure-Python codecs (deflate/LZW/packbits TIFF
  fallback, zlib Zarr chunks), ``kind="thread"`` where decode releases
  the GIL (uncompressed python TIFF — file reads + frombuffer). Work
  items are SEEKABLE page spans: each worker opens its own reader
  handle from a pickleable source spec and decodes ``read(lo, hi)``
  independently, so there is no shared file cursor and no cross-worker
  coordination.
* **pooled_chunks** — the sharded chunk iterator: each chunk's page
  range splits into per-worker spans submitted concurrently, chunks are
  reassembled IN ORDER on the consumer thread, and at most ``prefetch``
  chunks are in flight (bounded memory: ~prefetch x chunk_size frames).
  No extra threads: the consumer itself tops up the submission window
  and blocks only on the head chunk, so a ``KeyboardInterrupt`` lands
  in the consumer exactly like any synchronous read (the PR-2
  ``ChunkedStackLoader`` contract), and a worker crash surfaces as an
  exception carrying the worker-side traceback — never a hang or a
  truncated-but-clean end of stream.
* **shared_pool** — a process-wide pool registry so every run (and
  every serve session) in one process shares ONE warm pool per
  (kind, workers) instead of paying spawn + import per run.
* **host_local_range** — the multi-host seam (PERFORMANCE.md
  "Multi-chip scaling", DCN note): the contiguous frame range THIS host
  should decode for its local chips, matching
  ``parallel.mesh.shard_host_local_frames``'s process-ordered frame
  axis, so an N-host feeder decodes 1/N of the stack per host with no
  cross-host pixel movement.

`ChunkedStackLoader` (io/reader.py) routes through this module when
`io_workers >= 2` and the source classifies as pool-friendly;
`CorrectorConfig.io_workers` / `io_prefetch` (docs/API.md) are the
config surface, and `correct_file` derives the prefetch depth from its
dispatch window (depth x batch frames ahead).
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from collections import deque
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from concurrent.futures.process import BrokenProcessPool

import numpy as np

# Auto worker-count ceiling: decode workers beyond this see diminishing
# returns against the PCIe/ICI feed they fill, and an 8-chip host has
# better uses for its remaining cores (the dispatch thread, the writer).
_AUTO_WORKER_CAP = 8


def resolve_workers(requested: int) -> int:
    """The `io_workers`/`--io-threads` value -> a concrete worker count
    (0 = auto: one per CPU, capped at 8; N >= 1 = exactly N)."""
    n = int(requested)
    if n > 0:
        return n
    return max(1, min(os.cpu_count() or 1, _AUTO_WORKER_CAP))


def derive_prefetch(io_prefetch: int, batch: int, chunk: int, depth: int = 3) -> int:
    """Prefetch depth in CHUNKS for a streaming run (0 = auto).

    Auto keeps `depth x batch` decoded frames ahead of the consumer —
    one chunk per in-flight dispatch-window slot plus one being
    consumed — replacing the fixed prefetch=2 of the single-producer
    era, whose two chunks could starve a deep mesh window.
    """
    if io_prefetch and io_prefetch > 0:
        return int(io_prefetch)
    frames_ahead = max(1, int(depth) * max(1, int(batch)))
    return max(2, -(-frames_ahead // max(1, int(chunk))) + 1)


def host_local_range(
    n_frames: int,
    process_index: int | None = None,
    process_count: int | None = None,
) -> tuple[int, int]:
    """The [lo, hi) frame range THIS host decodes on a multi-host mesh.

    Hosts partition the frame axis into contiguous near-equal blocks in
    process order — the layout `parallel.mesh.shard_host_local_frames`
    assembles into the global sharded batch — so each host's feeder
    decodes only the frames destined for its local chips and no pixels
    cross the DCN. With explicit index/count arguments this is a pure
    function (unit-testable without jax); defaults read
    `jax.process_index()` / `jax.process_count()`.
    """
    if process_index is None or process_count is None:
        import jax

        process_index = jax.process_index()
        process_count = jax.process_count()
    if process_count < 1 or not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"{process_count} process(es)"
        )
    n = int(n_frames)
    per = -(-n // int(process_count))  # ceil: early hosts take the slack
    lo = min(int(process_index) * per, n)
    return lo, min(lo + per, n)


# ---------------------------------------------------------------------------
# source classification + worker-side respec
# ---------------------------------------------------------------------------


def classify_source(source) -> str | None:
    """Which pool flavor (if any) pays for this reader.

    "process": decode is GIL-bound pure-Python codec work — the
    deflate/LZW/packbits TIFF fallback, zlib/gzip Zarr chunks — where
    thread fan-out serializes on the interpreter lock.
    "thread": decode releases the GIL (uncompressed python-path TIFF,
    raw Zarr chunks) — concurrent chunk fetch helps, processes add only
    pickling.
    None: the legacy single-producer prefetch thread is already right —
    the native TIFF decoder fans its own threads out per read, h5py is
    not thread-safe, and memmap-backed sources are one memcpy.
    """
    from kcmc_tpu.io.formats import ZarrStack, _MiniZarr
    from kcmc_tpu.io.objectstore import ObjectStack
    from kcmc_tpu.io.tiff import TiffStack

    if isinstance(source, TiffStack):
        if source.backend == "native":
            return None
        return "thread" if source.compression == 1 else "process"
    if isinstance(source, ZarrStack):
        inner = source.source
        if isinstance(inner, _MiniZarr):
            return "process" if inner._zlib else "thread"
    if isinstance(source, ObjectStack):
        # ranged GETs block on I/O (GIL released in socket/file ops);
        # deflate chunks add GIL-bound zlib decode on top, so they pay
        # for real interpreters. Thread workers also share the per-URL
        # hedge/latency state with the consumer; process workers keep
        # their own (documented in PERFORMANCE.md).
        return "process" if source.compression == "deflate" else "thread"
    return None


def source_spec(source, source_path, reader_options: dict | None):
    """A pickleable respec workers reopen the source from, or None when
    the source has no cross-process identity (in-memory arrays, reader
    objects without a path). Python-decode TIFF sources pin
    ``force_python=True`` so no worker races to build (or silently
    switches to) the native decoder mid-run. Object-store sources
    respec by URL — each worker's `open_stack` builds a per-worker
    client connection (and self-arms any ``KCMC_FAULT_PLAN``)."""
    from kcmc_tpu.io.objectstore import ObjectStack

    if isinstance(source, ObjectStack):
        return ("stack", source.path, ())
    if source_path is None:
        return None
    from kcmc_tpu.io.tiff import TiffStack

    opts = dict(reader_options or {})
    if isinstance(source, TiffStack) and source.backend == "python":
        opts["force_python"] = True
    return ("stack", os.fspath(source_path), tuple(sorted(opts.items())))


# Per-process (and per-thread, for the thread flavor) reader cache:
# opening parses metadata once; spans then seek independently.
_READER_CACHE = threading.local()


def _decode_span(spec, lo: int, hi: int) -> np.ndarray:
    """Worker entry: decode pages [lo, hi) of the respec'd source."""
    cache = getattr(_READER_CACHE, "readers", None)
    if cache is None:
        cache = _READER_CACHE.readers = {}
    reader = cache.pop(spec, None)
    if reader is None:
        _kind, path, opts = spec
        from kcmc_tpu.io.formats import open_stack

        reader = open_stack(path, **dict(opts))
    cache[spec] = reader  # re-insert: dict order doubles as LRU order
    while len(cache) > 8:  # shared pools outlive runs — cap open handles
        _stale_spec, stale = next(iter(cache.items()))
        del cache[_stale_spec]
        try:
            stale.close()
        except Exception:
            pass
    return np.ascontiguousarray(reader.read(lo, hi))


# ---------------------------------------------------------------------------
# the pool
# ---------------------------------------------------------------------------


class DecodePool:
    """A fixed pool of decode workers (see module docstring).

    ``kind="process"`` spawns fresh interpreters (spawn context — safe
    next to JAX/XLA threads, and `kcmc_tpu`'s lazy package init keeps
    the worker import jax-free and light); ``kind="thread"`` shares the
    process. `submit` returns a concurrent.futures.Future whose result
    is the decoded (hi-lo, *frame_shape) array; worker exceptions
    propagate with the worker-side traceback attached, and a hard
    worker death surfaces as BrokenProcessPool (`broken` flips True so
    the shared registry replaces the pool).
    """

    def __init__(self, workers: int, kind: str = "process"):
        if kind not in ("process", "thread"):
            raise ValueError(f"DecodePool kind must be process|thread, got {kind!r}")
        if workers < 1:
            raise ValueError(f"DecodePool needs >= 1 worker, got {workers}")
        self.workers = int(workers)
        self.kind = kind
        self.broken = False
        if kind == "process":
            import multiprocessing

            # spawn, never fork: this process carries JAX/XLA (and
            # writer/heartbeat) threads, and a forked child of a
            # threaded process is undefined behavior waiting to happen.
            # Spawn implies the STANDARD multiprocessing contract: a
            # script that reaches a pooled run from module level needs
            # the usual `if __name__ == "__main__":` guard (the CLI,
            # pytest, and serve all satisfy it already). The lazy
            # kcmc_tpu package init keeps each worker's import
            # numpy-light and jax-free.
            self._ex = ProcessPoolExecutor(
                max_workers=self.workers,
                mp_context=multiprocessing.get_context("spawn"),
            )
        else:
            self._ex = ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix="kcmc-decode"
            )

    def submit(self, spec, lo: int, hi: int):
        return self._ex.submit(_decode_span, spec, lo, hi)

    def shutdown(self, wait: bool = True) -> None:
        self._ex.shutdown(wait=wait, cancel_futures=True)


_SHARED_LOCK = threading.Lock()
_SHARED: dict[tuple[str, int], DecodePool] = {}


def shared_pool(kind: str, workers: int) -> DecodePool:
    """The process-wide shared pool for (kind, workers): every
    streaming run and serve session in one process reuses the same warm
    workers instead of paying spawn + import per run. Broken pools
    (a worker died) are replaced transparently."""
    key = (kind, int(workers))
    with _SHARED_LOCK:
        pool = _SHARED.get(key)
        if pool is None or pool.broken:
            if pool is not None:
                pool.shutdown(wait=False)
            pool = _SHARED[key] = DecodePool(workers, kind)
        return pool


def shutdown_shared_pools() -> None:
    """Tear down every shared pool (serve shutdown, interpreter exit).
    Safe to call repeatedly; pools recreate on demand."""
    with _SHARED_LOCK:
        pools = list(_SHARED.values())
        _SHARED.clear()
    for pool in pools:
        pool.shutdown(wait=False)


atexit.register(shutdown_shared_pools)


# ---------------------------------------------------------------------------
# sharded, ordered, bounded chunk iteration
# ---------------------------------------------------------------------------


def _spans(lo: int, hi: int, workers: int) -> list[tuple[int, int]]:
    """Split a chunk's page range into per-worker spans (>= 4 pages per
    span — below that the submit/pickle overhead beats the decode)."""
    n = hi - lo
    size = max(4, -(-n // max(1, workers)))
    return [(a, min(a + size, hi)) for a in range(lo, hi, size)]


def pooled_chunks(
    pool: DecodePool,
    spec,
    start: int,
    stop: int,
    chunk_size: int,
    prefetch: int,
    fault_plan=None,
    retry=None,
    report=None,
    on_wait=None,
    tracer=None,
    stats: dict | None = None,
):
    """Yield (lo, hi, frames) chunks in order, decoded by `pool`.

    The submission window holds at most `prefetch` chunks (bounded
    memory); each chunk is sharded into per-worker spans. Fault
    injection (surface ``io_read``) and transient-retry semantics match
    `ChunkedStackLoader._read`: the step index is drawn at submission
    in chunk order, injection fires at collection, and a transient
    failure (injected or worker-side) resubmits the chunk's spans up to
    the policy's attempt budget with backoff, counting
    `report.io_retries`. `on_wait(seconds)` fires when the consumer
    actually blocked on the head chunk (the `prefetch_wait` stall);
    `tracer` records one `feeder.decode` span per chunk.

    `retry` is a utils/faults.RetryPolicy, the string ``"default"``
    (resolved through `utils.faults.default_io_retry_policy` — THE
    shared ingest-surface construction point, so backoff/jitter/
    classification cannot drift between reader, feeder, and the
    object-store path), or None (read exactly once).
    """
    from kcmc_tpu.utils.faults import (
        classify_transient,
        default_io_retry_policy,
    )

    if retry == "default":
        retry = default_io_retry_policy(None)

    if stats is not None:
        stats["chunks"] = stats.get("chunks", 0)
        stats["spans"] = stats.get("spans", 0)
        stats["frames"] = stats.get("frames", 0)
        stats["io_retries"] = stats.get("io_retries", 0)
        stats.setdefault("max_inflight_chunks", 0)
    pending: deque = deque()  # (lo, hi, spans, futures, t_submit, step)
    nxt = start

    def submit_chunk() -> bool:
        nonlocal nxt
        if nxt >= stop:
            return False
        lo, hi = nxt, min(nxt + chunk_size, stop)
        nxt = hi
        step = fault_plan.op_index("io_read") if fault_plan is not None else None
        spans = _spans(lo, hi, pool.workers)
        try:
            futs = [pool.submit(spec, a, b) for a, b in spans]
        except BrokenProcessPool as e:
            # A dead worker can surface at SUBMIT time (the executor
            # noticed before our next collect): same contract as the
            # collect-side path — mark the pool broken and raise with
            # attribution, never leak the raw executor error.
            pool.broken = True
            raise RuntimeError(
                f"decode pool worker died while submitting pages "
                f"[{lo}, {hi}) of {spec[1]!r} (the pool is torn "
                "down; a rerun builds a fresh one)"
            ) from e
        pending.append((lo, hi, spans, futs, time.perf_counter(), step))
        if stats is not None:
            stats["chunks"] += 1
            stats["spans"] += len(spans)
            stats["max_inflight_chunks"] = max(
                stats["max_inflight_chunks"], len(pending)
            )
        return True

    def collect(futs):
        """Wait for one chunk's spans; returns parts. Times the
        consumer's actual blocked span for the stall telemetry."""
        t0 = None
        parts = []
        for f in futs:
            if t0 is None and not f.done():
                t0 = time.perf_counter()
            parts.append(f.result())
        if t0 is not None and on_wait is not None:
            on_wait(time.perf_counter() - t0)
        return parts

    try:
        while True:
            while len(pending) < max(1, prefetch) and submit_chunk():
                pass
            if not pending:
                return
            lo, hi, spans, futs, t_sub, step = pending.popleft()
            attempts = max(1, retry.attempts if retry is not None else 1)
            last_futs = futs
            for attempt in range(attempts):
                try:
                    if fault_plan is not None:
                        fault_plan.maybe_fail("io_read", step)
                    parts = collect(last_futs)
                    break
                except BrokenProcessPool as e:
                    pool.broken = True
                    raise RuntimeError(
                        f"decode pool worker died while decoding pages "
                        f"[{lo}, {hi}) of {spec[1]!r} (the pool is torn "
                        "down; a rerun builds a fresh one)"
                    ) from e
                except Exception as e:
                    if attempt == attempts - 1 or not classify_transient(e):
                        raise
                    if report is not None:
                        report.io_retries += 1
                    if stats is not None:
                        stats["io_retries"] += 1
                    if retry is not None:
                        retry.sleep(retry.delay(attempt))
                    # resubmit only if a span actually failed (an
                    # injected fault leaves the decoded spans reusable)
                    if any(
                        f.done() and f.exception() is not None
                        for f in last_futs
                    ):
                        last_futs = [pool.submit(spec, a, b) for a, b in spans]
            frames = parts[0] if len(parts) == 1 else np.concatenate(parts)
            if tracer is not None:
                tracer.complete(
                    "feeder.decode",
                    t_sub,
                    time.perf_counter() - t_sub,
                    cat="feeder",
                    args={"lo": int(lo), "hi": int(hi), "spans": len(spans)},
                )
            if stats is not None:
                stats["frames"] += int(hi - lo)
            yield lo, hi, frames
    finally:
        for entry in pending:
            for f in entry[3]:
                f.cancel()
