"""Background-prefetching chunked stack loader.

Overlaps host-side decode (the native threaded TIFF decoder, or any
array-like source) with device compute: a reader thread keeps a small
queue of decoded (lo, hi, ndarray) chunks ahead of the consumer, so the
TPU never waits on disk or decompression. This is the host half of the
streaming pipeline; the device half is the orchestrator's dispatch-ahead
window (corrector.py).

Chunk reads are the run's storage-failure surface: with a
`RetryPolicy` attached (corrector runs pass theirs), transient read
errors (flaky NFS, dropped object-store connections — anything
`classify_transient` accepts) are retried with exponential backoff
before surfacing; a `FaultPlan` injects deterministic faults here for
chaos testing (surface ``io_read``).
"""

from __future__ import annotations

import os
import queue
import threading
import time

import numpy as np

from kcmc_tpu.io.tiff import TiffStack


class ChunkedStackLoader:
    """Iterate (lo, hi, frames) chunks of a stack with background prefetch.

    source: any io.formats protocol reader (TiffStack, ZarrStack,
    HDF5Stack, ...), a path (dispatched via open_stack), or any
    array-like with numpy-style slicing along axis 0 (ndarray, memmap,
    zarr-ish).

    fault_plan / retry / report: optional robustness wiring
    (utils/faults.FaultPlan, utils/faults.RetryPolicy,
    utils/metrics.RobustnessReport) — chunk reads are retried per the
    policy, injected faults fire per the plan, retries are counted in
    the report. All None by default: the bare loader reads exactly
    once. ``retry="default"`` resolves through
    `utils.faults.default_io_retry_policy`, the shared ingest-surface
    construction point (corrector runs, the feeder, and the
    object-store path all build theirs there, so backoff/jitter/
    classification cannot drift between surfaces).

    on_wait: optional callback(seconds) invoked whenever the CONSUMER
    blocks waiting for the prefetch thread — the pipeline-stall
    telemetry hook (a well-fed pipeline never calls it).

    io_workers / pool / source_path / reader_options: the feeder-pool
    seam (io/feeder.py; docs/PERFORMANCE.md "Streaming pipeline
    anatomy"). With `io_workers >= 2` and a source whose decode is
    pool-friendly (`feeder.classify_source`), chunks are sharded into
    per-worker page spans and decoded by a process pool (GIL-bound
    pure-Python codecs) or a thread pool (GIL-releasing decode) with
    ordered reassembly — pass an explicit `pool` (e.g. from
    `feeder.shared_pool`) to reuse warm workers across loaders, and
    `source_path`/`reader_options` when `source` is an already-open
    reader so workers can reopen it (paths passed AS `source` respec
    themselves). `tracer` records one `feeder.decode` span per pooled
    chunk; `stats` (a dict) accumulates feeder counters in place.
    """

    def __init__(
        self,
        source,
        chunk_size: int = 64,
        start: int = 0,
        stop: int | None = None,
        prefetch: int = 2,
        n_threads: int = 0,
        fault_plan=None,
        retry=None,
        report=None,
        on_wait=None,
        io_workers: int = 0,
        pool=None,
        source_path=None,
        reader_options: dict | None = None,
        tracer=None,
        stats: dict | None = None,
    ):
        self._own = False
        if isinstance(source, (str, os.PathLike)):
            from kcmc_tpu.io.formats import open_stack

            source_path = source if source_path is None else source_path
            source = open_stack(source, n_threads=n_threads)
            self._own = True
        self.source = source
        self.n_total = len(source)
        self.start = start
        self.stop = self.n_total if stop is None else min(stop, self.n_total)
        self.chunk_size = chunk_size
        self.prefetch = max(1, prefetch)
        self._fault_plan = fault_plan
        if retry == "default":
            from kcmc_tpu.utils.faults import default_io_retry_policy

            retry = default_io_retry_policy(None)
        self._retry = retry
        self._report = report
        self._on_wait = on_wait
        self._tracer = tracer
        self.stats = stats if stats is not None else {}
        self._pool = None
        self._spec = None
        if pool is not None or io_workers >= 2:
            from kcmc_tpu.io import feeder

            kind = feeder.classify_source(self.source)
            spec = feeder.source_spec(self.source, source_path, reader_options)
            if kind is not None and spec is not None:
                self._pool = (
                    pool
                    if pool is not None
                    else feeder.shared_pool(kind, io_workers)
                )
                self._spec = spec
                self.stats.setdefault("mode", self._pool.kind)
                self.stats.setdefault("workers", self._pool.workers)
            elif kind == "process":
                # Pool requested but unusable (no reopenable path): the
                # GIL serializes this source's pure-Python codec.
                self._advise_single_core()
        elif self._gil_bound():
            # No pool requested on a GIL-bound source: the run decodes
            # single-core (satellite of ROADMAP item 3 — make the cliff
            # visible instead of silently eating a many-x slowdown).
            self._advise_single_core()

    def _gil_bound(self) -> bool:
        from kcmc_tpu.io import feeder

        return feeder.classify_source(self.source) == "process"

    def _advise_single_core(self) -> None:
        # once per run: segmented runs build one loader per span but
        # share a stats dict, so the advisory does not repeat
        if self.stats.get("single_core_advised"):
            return
        self.stats["single_core_advised"] = True
        from kcmc_tpu.obs.log import advise

        name = getattr(self.source, "path", type(self.source).__name__)
        advise(
            f"kcmc: {name}: compressed pages decode through the "
            "pure-Python fallback codec on a single core (GIL-bound, "
            "~233 fps for deflate); set io_workers >= 2 (CLI "
            "--io-threads) to decode in a process pool, or install a "
            "C++ toolchain so the native threaded decoder builds",
            stacklevel=3,
        )

    def _read_raw(self, lo: int, hi: int) -> np.ndarray:
        if hasattr(self.source, "read"):  # io.formats protocol readers
            return self.source.read(lo, hi)
        return np.asarray(self.source[lo:hi])

    def _read(self, lo: int, hi: int) -> np.ndarray:
        """One chunk read, retried per the attached policy.

        Transient failures (OS-level IO errors, injected transient
        faults) back off and retry up to the policy's attempt budget;
        fatal errors and exhausted budgets raise to the consumer.
        """
        plan, policy = self._fault_plan, self._retry
        if plan is None and policy is None:
            return self._read_raw(lo, hi)  # zero-overhead happy path
        from kcmc_tpu.utils.faults import classify_transient

        step = plan.op_index("io_read") if plan is not None else None
        attempts = policy.attempts if policy is not None else 1
        for attempt in range(attempts):
            try:
                if plan is not None:
                    plan.maybe_fail("io_read", step)
                return self._read_raw(lo, hi)
            except Exception as e:
                if attempt == attempts - 1 or not classify_transient(e):
                    raise
                if self._report is not None:
                    self._report.io_retries += 1
                if policy is not None:
                    policy.sleep(policy.delay(attempt))
        raise AssertionError("unreachable")  # loop always returns/raises

    def __len__(self) -> int:
        return self.stop - self.start

    def __iter__(self):
        if self._pool is not None:
            from kcmc_tpu.io import feeder

            yield from feeder.pooled_chunks(
                self._pool,
                self._spec,
                self.start,
                self.stop,
                self.chunk_size,
                self.prefetch,
                fault_plan=self._fault_plan,
                retry=self._retry,
                report=self._report,
                on_wait=self._on_wait,
                tracer=self._tracer,
                stats=self.stats,
            )
            return
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop_flag = threading.Event()

        def producer():
            try:
                for lo in range(self.start, self.stop, self.chunk_size):
                    if stop_flag.is_set():
                        return
                    hi = min(lo + self.chunk_size, self.stop)
                    q.put((lo, hi, self._read(lo, hi)))
            except Exception as e:  # surface decode errors to consumer
                q.put(e)
                return
            except BaseException as e:
                # KeyboardInterrupt/SystemExit in the producer thread
                # are NOT decode errors, but a clean end-of-stream here
                # would let the consumer finish successfully on
                # truncated data — surface a loud, correctly-attributed
                # error instead, and let the original exception
                # terminate this thread.
                q.put(RuntimeError(
                    f"stack read interrupted by {type(e).__name__} in "
                    "the prefetch thread (not an input decode error)"
                ))
                raise
            q.put(None)

        t = threading.Thread(
            target=producer, name="kcmc-prefetch", daemon=True
        )
        t.start()
        try:
            while True:
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    t0 = time.perf_counter()
                    item = q.get()
                    if self._on_wait is not None:
                        self._on_wait(time.perf_counter() - t0)
                if item is None:
                    return
                if isinstance(item, Exception):
                    # the exception object still carries the producer-
                    # side traceback; raising appends the consumer frame
                    raise item
                yield item
        finally:
            stop_flag.set()
            # drain so the producer's blocked put() can finish
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)

    def close(self):
        if self._own:
            self.source.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
