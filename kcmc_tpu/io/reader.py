"""Background-prefetching chunked stack loader.

Overlaps host-side decode (the native threaded TIFF decoder, or any
array-like source) with device compute: a reader thread keeps a small
queue of decoded (lo, hi, ndarray) chunks ahead of the consumer, so the
TPU never waits on disk or decompression. This is the host half of the
streaming pipeline; the device half is the orchestrator's dispatch-ahead
window (corrector.py).
"""

from __future__ import annotations

import os
import queue
import threading

import numpy as np

from kcmc_tpu.io.tiff import TiffStack


class ChunkedStackLoader:
    """Iterate (lo, hi, frames) chunks of a stack with background prefetch.

    source: any io.formats protocol reader (TiffStack, ZarrStack,
    HDF5Stack, ...), a path (dispatched via open_stack), or any
    array-like with numpy-style slicing along axis 0 (ndarray, memmap,
    zarr-ish).
    """

    def __init__(
        self,
        source,
        chunk_size: int = 64,
        start: int = 0,
        stop: int | None = None,
        prefetch: int = 2,
        n_threads: int = 0,
    ):
        self._own = False
        if isinstance(source, (str, os.PathLike)):
            from kcmc_tpu.io.formats import open_stack

            source = open_stack(source, n_threads=n_threads)
            self._own = True
        self.source = source
        self.n_total = len(source)
        self.start = start
        self.stop = self.n_total if stop is None else min(stop, self.n_total)
        self.chunk_size = chunk_size
        self.prefetch = max(1, prefetch)

    def _read(self, lo: int, hi: int) -> np.ndarray:
        if hasattr(self.source, "read"):  # io.formats protocol readers
            return self.source.read(lo, hi)
        return np.asarray(self.source[lo:hi])

    def __len__(self) -> int:
        return self.stop - self.start

    def __iter__(self):
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop_flag = threading.Event()

        def producer():
            try:
                for lo in range(self.start, self.stop, self.chunk_size):
                    if stop_flag.is_set():
                        return
                    hi = min(lo + self.chunk_size, self.stop)
                    q.put((lo, hi, self._read(lo, hi)))
            except BaseException as e:  # surface decode errors to consumer
                q.put(e)
                return
            q.put(None)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop_flag.set()
            # drain so the producer's blocked put() can finish
            while t.is_alive():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)

    def close(self):
        if self._own:
            self.source.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
