"""Object-store-native ingest/egress with a built-in emulator.

Production frame stacks live in GCS/S3 buckets (ROADMAP item 3), and
the network is the least reliable component in the pipeline — so the
cloud path ships WITH its fault model, not before it. This module is
both halves:

* **Client abstraction** — `ObjectStoreClient` is a small protocol
  (range GET, atomic PUT, multipart PUT, list/head/rename/delete) any
  real cloud SDK can implement behind `register_scheme`. The built-in
  `EmulatedObjectStore` (scheme ``emu://``) backs a "bucket" with a
  local directory — atomic PUTs via tmp+rename, multipart staging that
  keeps incomplete uploads invisible, sha256 etags — so CI exercises
  every cloud failure mode with zero network access. The emulator is
  also the fault-injection point: armed with a `FaultPlan`, every op
  draws one ``object``-surface index and applies the matched clause
  (drop/throttle raise, ``stall=`` sleeps against the per-attempt
  deadline, ``truncate``/``flip`` mangle bodies so the checksum layer
  has something real to catch).

* **ObjectStack** — the streaming-reader protocol over a chunked
  bucket layout (Zarr-style: one ``chunk-NNNNNNNN`` object per
  ``chunk_frames`` frames plus a checksummed manifest). Reads ride the
  shared jittered `RetryPolicy` with per-attempt deadline caps;
  **hedged reads** fire a second ranged GET when the first exceeds the
  live latency-histogram p95 (first-wins, loser cancelled); corrupt
  bodies quarantine-and-refetch exactly like PR-2 checkpoint parts
  (in-flight corruption refetches; at-rest corruption quarantines the
  object and aborts loudly). Pickles by URL: `feeder.source_spec`
  respecs it, so `pooled_chunks` workers open per-worker connections
  and share the per-URL hedge/latency state in-process.

* **ObjectStoreWriter** — sharded cloud-native egress with the
  TiffWriter streaming protocol (`append_batch` / `checkpoint_state` /
  `close` / `n_pages`), so it slots under `AsyncBatchWriter` and the
  checkpoint machinery unchanged. Chunk objects upload via multipart
  PUT (verified: a torn/mangled upload fails the etag check and
  retries); a **durable high-water-mark manifest** (atomic,
  self-checksummed, previous generation kept as the rewind point)
  advances after every completed chunk, and `checkpoint_state()`
  flushes the partial tail first — so kill -9 → restart → resume
  re-uploads only past the manifest's high-water mark and the final
  chunk set is byte-identical to an uninterrupted run.

Bucket layout (one stack per URL prefix; keys relative to it)::

    chunk-00000000        frames [0, chunk_frames) — raw or zlib(6)
    chunk-00000001        frames [chunk_frames, 2*chunk_frames)
    ...
    .manifest.json        {"manifest": {...}, "sha256": <self-check>}
    .manifest.prev.json   previous manifest generation (rewind point)

The manifest records shape/dtype/compression/chunk_frames, the durable
frame count, and one ``{key, frames, sha256, size}`` entry per chunk —
everything deterministic (sorted-keys JSON, no timestamps), so resumed
and uninterrupted runs produce byte-identical manifests too.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
import zlib
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from concurrent.futures import wait as _fut_wait

import numpy as np

from kcmc_tpu.utils.faults import (
    FatalFaultError,
    TransientFaultError,
    classify_transient,
    default_io_retry_policy,
    resolve_fault_plan,
)

MANIFEST_KEY = ".manifest.json"
PREV_MANIFEST_KEY = ".manifest.prev.json"
_MANIFEST_FORMAT = "kcmc-object-v1"

# Defaults for standalone (non-corrector) users; CorrectorConfig's
# object_* fields override via arm()/make_writer object_opts.
_DEFAULT_CHUNK_FRAMES = 64
_DEFAULT_PART_BYTES = 8 << 20
_DEFAULT_HEDGE_MS = 50.0
_DEFAULT_TIMEOUT_S = 30.0
# Hedging needs a live p95 before it can mean anything: below this many
# recorded GETs the first read of a cold bucket would hedge against an
# empty histogram.
_HEDGE_WARMUP = 16


class ObjectStoreError(OSError):
    """Base object-store failure (classified transient by the retry
    engine unless a permanent subclass)."""


class ObjectNotFound(FileNotFoundError, ObjectStoreError):
    """Missing object/bucket — permanent; retrying cannot help."""


class ObjectStoreThrottled(ObjectStoreError):
    """HTTP 429/503-style backpressure from the store — transient, but
    counted separately so the degradation advisory can name it."""


class ObjectIntegrityError(RuntimeError):
    """At-rest corruption: the STORED object no longer matches its
    manifest checksum. Refetching cannot recover the bytes, so this is
    fatal (RuntimeError — `classify_transient` returns False); the
    corrupt object is quarantined (renamed ``*.corrupt``) before this
    raises, leaving the evidence for the operator."""


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# ---------------------------------------------------------------------------
# URL scheme registry
# ---------------------------------------------------------------------------

_SCHEMES: dict[str, object] = {}


def register_scheme(scheme: str, factory) -> None:
    """Register ``factory(path) -> client`` for ``<scheme>://<path>``
    URLs — the seam a real S3/GCS client plugs into."""
    _SCHEMES[str(scheme)] = factory


def is_object_url(source) -> bool:
    """True when `source` is an object-store URL string
    (``emu://...``, ``s3://...``, ``gs://...``)."""
    if not isinstance(source, str):
        return False
    scheme, sep, _rest = source.partition("://")
    return bool(sep) and (scheme in _SCHEMES or scheme in ("s3", "gs"))


def client_for_url(url: str, fault_plan=None):
    """Build the client for an object URL. ``emu://`` maps the URL path
    to a local bucket directory; ``s3://``/``gs://`` point at the
    `register_scheme` seam (no cloud SDK is baked into this build)."""
    url = str(url)
    scheme, sep, path = url.partition("://")
    if not sep:
        raise ValueError(f"not an object-store URL: {url!r}")
    factory = _SCHEMES.get(scheme)
    if factory is None:
        raise ValueError(
            f"no client registered for scheme {scheme!r} ({url!r}); this "
            "build ships the emu:// emulator only — implement the "
            "ObjectStoreClient protocol over your cloud SDK and add it "
            "via kcmc_tpu.io.objectstore.register_scheme"
        )
    client = factory(path)
    if fault_plan is not None:
        client.fault_plan = fault_plan
    return client


# ---------------------------------------------------------------------------
# the in-process emulator
# ---------------------------------------------------------------------------


class EmulatedObjectStore:
    """Directory-backed object store with cloud PUT/GET semantics.

    One instance per "bucket" (a stack prefix): keys are paths relative
    to `root`. PUTs are atomic (tmp file + `os.replace`); multipart
    uploads stage parts under ``.multipart/<upload_id>/`` and become
    visible only at complete (assembled, then atomically renamed) — a
    kill mid-upload leaves no partial object, exactly the cloud
    contract. Etags are sha256 of the full object content, computed
    from disk so at-rest corruption is observable through `head`.

    `fault_plan` arms the ``object`` fault surface: every op draws one
    op index and applies any matched clause — see the module docstring.
    Instances are cheap and stateless beyond the root path, so
    per-worker "connections" are simply per-worker instances.
    """

    scheme = "emu"

    def __init__(self, root, fault_plan=None):
        self.root = os.fspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.fault_plan = fault_plan
        self._tmp_count = 0
        self._tmp_lock = threading.Lock()

    def url(self, key: str = "") -> str:
        return f"emu://{self.root}" + (f"/{key}" if key else "")

    def _path(self, key: str) -> str:
        key = str(key)
        p = os.path.normpath(os.path.join(self.root, key))
        if not p.startswith(os.path.normpath(self.root)):
            raise ValueError(f"object key escapes the bucket: {key!r}")
        return p

    def _gate(self, op: str, deadline_s: float | None) -> str | None:
        """Apply any matched object-surface fault clause to this op.
        Returns "truncate"/"flip" for the caller to mangle the body, or
        None; raising clauses raise here. A stall longer than the
        per-attempt deadline sleeps only the deadline, then times out
        as a transient — one wedged request can never cost more than
        `deadline_s` before the retry/hedge machinery takes over."""
        plan = self.fault_plan
        if plan is None:
            return None
        step = plan.op_index("object")
        stall = plan.take_stall("object", step)
        if stall > 0.0:
            if deadline_s is not None and stall > float(deadline_s):
                time.sleep(float(deadline_s))
                raise TimeoutError(
                    f"object {op} exceeded the {float(deadline_s):.3g}s "
                    f"per-attempt deadline (stalled {stall:.3g}s)"
                )
            time.sleep(stall)
        act = plan.take_action("object", step)
        if act == "transient":
            raise TransientFaultError(
                f"injected object fault: connection dropped during {op} "
                f"[step={step}]"
            )
        if act == "fatal":
            raise FatalFaultError(
                f"injected fatal object fault during {op} [step={step}]"
            )
        if act == "throttle":
            raise ObjectStoreThrottled(
                f"injected throttle: HTTP 429 Too Many Requests during "
                f"{op} [step={step}]"
            )
        return act  # None | truncate | flip

    @staticmethod
    def _mangle(act: str | None, data: bytes) -> bytes:
        if act == "truncate" and data:
            return data[: len(data) // 2]
        if act == "flip" and data:
            i = len(data) // 2
            return data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
        return data

    def _tmp(self) -> str:
        with self._tmp_lock:
            self._tmp_count += 1
            n = self._tmp_count
        return os.path.join(
            self.root, f".tmp-{os.getpid()}-{threading.get_ident()}-{n}"
        )

    def _write_atomic(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = self._tmp()
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # -- ops ---------------------------------------------------------------

    def head(self, key: str, deadline_s: float | None = None) -> dict:
        self._gate("HEAD", deadline_s)
        p = self._path(key)
        try:
            with open(p, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise ObjectNotFound(f"{self.url(key)}: no such object") from None
        return {"size": len(data), "etag": sha256_hex(data)}

    def get(
        self,
        key: str,
        start: int = 0,
        length: int | None = None,
        deadline_s: float | None = None,
    ) -> bytes:
        """Ranged GET: bytes [start, start+length) of the object (the
        whole object with the defaults)."""
        act = self._gate("GET", deadline_s)
        p = self._path(key)
        try:
            with open(p, "rb") as f:
                f.seek(int(start))
                body = f.read() if length is None else f.read(int(length))
        except FileNotFoundError:
            raise ObjectNotFound(f"{self.url(key)}: no such object") from None
        return self._mangle(act, body)

    def put(
        self, key: str, data: bytes, deadline_s: float | None = None
    ) -> str:
        """Atomic PUT; returns the etag of the STORED content (so a
        mangled upload is detectable by the caller's verify)."""
        act = self._gate("PUT", deadline_s)
        stored = self._mangle(act, bytes(data))
        self._write_atomic(self._path(key), stored)
        return sha256_hex(stored)

    # -- multipart ---------------------------------------------------------

    def multipart_begin(self, key: str, deadline_s: float | None = None) -> str:
        self._gate("MULTIPART-BEGIN", deadline_s)
        with self._tmp_lock:
            self._tmp_count += 1
            uid = f"mp-{os.getpid()}-{self._tmp_count}"
        os.makedirs(os.path.join(self.root, ".multipart", uid), exist_ok=True)
        return uid

    def multipart_put_part(
        self,
        key: str,
        upload_id: str,
        part_index: int,
        data: bytes,
        deadline_s: float | None = None,
    ) -> str:
        act = self._gate("MULTIPART-PUT", deadline_s)
        stored = self._mangle(act, bytes(data))
        part = os.path.join(
            self.root, ".multipart", str(upload_id), f"{int(part_index):06d}"
        )
        self._write_atomic(part, stored)
        return sha256_hex(stored)

    def multipart_complete(
        self,
        key: str,
        upload_id: str,
        n_parts: int,
        deadline_s: float | None = None,
    ) -> str:
        self._gate("MULTIPART-COMPLETE", deadline_s)
        stage = os.path.join(self.root, ".multipart", str(upload_id))
        chunks = []
        for i in range(int(n_parts)):
            part = os.path.join(stage, f"{i:06d}")
            try:
                with open(part, "rb") as f:
                    chunks.append(f.read())
            except FileNotFoundError:
                raise ObjectStoreError(
                    f"{self.url(key)}: multipart upload {upload_id} is "
                    f"missing part {i} at complete"
                ) from None
        body = b"".join(chunks)
        self._write_atomic(self._path(key), body)
        self.multipart_abort(key, upload_id)  # drop the staging dir
        return sha256_hex(body)

    def multipart_abort(self, key: str, upload_id: str) -> None:
        import shutil

        stage = os.path.join(self.root, ".multipart", str(upload_id))
        shutil.rmtree(stage, ignore_errors=True)

    # -- listing / lifecycle -----------------------------------------------

    def list(self, prefix: str = "") -> list[str]:
        keys = []
        for dirpath, dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            if rel == ".":
                rel = ""
            if rel.split(os.sep, 1)[0] == ".multipart":
                dirs[:] = []
                continue
            for name in files:
                if name.startswith(".tmp-"):
                    continue
                key = os.path.join(rel, name) if rel else name
                key = key.replace(os.sep, "/")
                if key.startswith(prefix):
                    keys.append(key)
        return sorted(keys)

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def rename(self, key: str, new_key: str) -> None:
        """Server-side rename — the quarantine primitive (``*.corrupt``
        keeps the evidence out of the data path)."""
        try:
            os.replace(self._path(key), self._path(new_key))
        except FileNotFoundError:
            raise ObjectNotFound(f"{self.url(key)}: no such object") from None


register_scheme("emu", EmulatedObjectStore)


# ---------------------------------------------------------------------------
# shared per-URL read state: latency histogram, counters, advisory
# ---------------------------------------------------------------------------

# Keyed by stack URL and shared process-wide, so the consumer's reader
# and every thread-pool feeder worker aggregate into ONE live p95 and
# one set of hedge/throttle counters (timing["feeder"]["object"]).
# Process-pool workers keep their own registries — their counters are
# invisible to the consumer, which the docs call out.
_STATE_LOCK = threading.Lock()
_URL_STATE: dict[str, dict] = {}

_HEDGE_POOL: ThreadPoolExecutor | None = None
_HEDGE_POOL_LOCK = threading.Lock()


def _url_state(url: str) -> dict:
    from kcmc_tpu.obs.latency import LatencyHistogram

    with _STATE_LOCK:
        st = _URL_STATE.get(url)
        if st is None:
            st = _URL_STATE[url] = {
                "hist": LatencyHistogram(),
                "stats": {
                    "gets": 0,
                    "hedged": 0,
                    "hedge_wins": 0,
                    "retries": 0,
                    "throttled": 0,
                    "refetched": 0,
                    "puts": 0,
                },
                "advised": False,
            }
        return st


def _hedge_executor() -> ThreadPoolExecutor:
    global _HEDGE_POOL
    with _HEDGE_POOL_LOCK:
        if _HEDGE_POOL is None:
            _HEDGE_POOL = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="kcmc-objget"
            )
        return _HEDGE_POOL


def _shutdown_hedge_pool(wait: bool = False) -> None:
    """Drop the lazy hedge pool; the next hedged GET rebuilds it.
    ``wait=True`` joins the workers — tests run under the concurrency
    sanitizer use it so no kcmc-objget thread outlives the test."""
    global _HEDGE_POOL
    with _HEDGE_POOL_LOCK:
        pool, _HEDGE_POOL = _HEDGE_POOL, None
    if pool is not None:
        pool.shutdown(wait=wait, cancel_futures=True)


atexit.register(_shutdown_hedge_pool)


def stats_snapshot(url: str) -> dict:
    """Counters + live latency for one stack URL (what `correct_file`
    merges into ``timing["feeder"]["object"]``)."""
    st = _url_state(str(url))
    with _STATE_LOCK:
        out = dict(st["stats"])
        hist = st["hist"]
        p95 = hist.quantile(95) if hist.count else None
    out["p95_ms"] = round(p95 * 1e3, 3) if p95 is not None else None
    gets = max(out["gets"], 1)
    out["hedge_rate"] = round(out["hedged"] / gets, 4)
    return out


def reset_url_state(url: str | None = None) -> None:
    """Drop the shared per-URL read state (tests; None = all URLs)."""
    with _STATE_LOCK:
        if url is None:
            _URL_STATE.clear()
        else:
            _URL_STATE.pop(str(url), None)


# ---------------------------------------------------------------------------
# manifest helpers (shared by reader + writer)
# ---------------------------------------------------------------------------


def _manifest_bytes(manifest: dict) -> bytes:
    body = json.dumps(manifest, sort_keys=True)
    return json.dumps(
        {"manifest": manifest, "sha256": sha256_hex(body.encode())},
        sort_keys=True,
    ).encode()


def _parse_manifest(raw: bytes) -> dict:
    """Decode + self-checksum-verify manifest bytes; raises ValueError
    on any corruption."""
    doc = json.loads(raw.decode())
    manifest, check = doc["manifest"], doc["sha256"]
    body = json.dumps(manifest, sort_keys=True)
    if sha256_hex(body.encode()) != check:
        raise ValueError("manifest self-checksum mismatch")
    if manifest.get("format") != _MANIFEST_FORMAT:
        raise ValueError(f"unknown manifest format {manifest.get('format')!r}")
    return manifest


def _get_at_rest(client, key: str, retry=None) -> bytes:
    """GET whose body provably matches the STORED object (sha vs head
    etag), retrying in-flight corruption — so a decision to quarantine
    is always about at-rest state, never a flaky wire."""
    attempts = retry.attempts if retry is not None else 3
    deadline = getattr(retry, "deadline_s", None) or _DEFAULT_TIMEOUT_S
    last: Exception | None = None
    for attempt in range(attempts):
        try:
            body = client.get(key, deadline_s=deadline)
            try:
                etag = client.head(key, deadline_s=deadline)["etag"]
            except ObjectNotFound:
                raise
            except Exception:
                etag = None  # can't confirm; accept the body
            if etag is not None and sha256_hex(body) != etag:
                raise TransientFaultError(
                    f"{key}: body/etag mismatch (in-flight corruption)"
                )
            return body
        except Exception as e:
            last = e
            if attempt == attempts - 1 or not classify_transient(e):
                raise
            if retry is not None:
                retry.sleep(retry.delay(attempt))
    raise last  # pragma: no cover — loop always returns/raises


def load_manifest(client, retry=None, report=None, quarantine=True) -> dict:
    """Load + verify the stack manifest; a corrupt current generation
    is quarantined (``.manifest.json.corrupt``) and the PREVIOUS
    generation — the last good high-water mark — is used instead.
    Raises ObjectNotFound when no usable generation exists."""
    last_err: Exception | None = None
    for key in (MANIFEST_KEY, PREV_MANIFEST_KEY):
        try:
            raw = _get_at_rest(client, key, retry=retry)
        except ObjectNotFound as e:
            last_err = e
            continue
        try:
            return _parse_manifest(raw)
        except (ValueError, KeyError, TypeError) as e:
            last_err = e
            if quarantine:
                try:
                    client.rename(key, key + ".corrupt")
                except ObjectStoreError:
                    pass
                if report is not None:
                    report.quarantined_parts.append(
                        getattr(client, "url", lambda k: k)(key)
                    )
    raise ObjectNotFound(
        f"no usable stack manifest in {getattr(client, 'root', client)!r} "
        f"(last error: {last_err})"
    )


# ---------------------------------------------------------------------------
# ingest: the streaming-reader protocol over a chunked bucket
# ---------------------------------------------------------------------------


class ObjectStack:
    """Read a chunked object-store stack through the io.formats reader
    protocol (``len`` / ``frame_shape`` / ``dtype`` / ``read(lo, hi)``).

    Robustness wiring (`arm`): the shared `FaultPlan` is pushed into
    the client (injection happens inside ops, so every consumer path is
    exercised); reads retry per the jittered `RetryPolicy` with
    per-attempt deadline caps, counting `RobustnessReport.io_retries`;
    whole-chunk GETs verify sha256 against the manifest and
    quarantine-and-refetch on mismatch; ranged (sub-chunk) GETs verify
    length. Hedging: once the per-URL latency histogram has
    `_HEDGE_WARMUP` samples, a GET outlasting max(live p95, hedge_ms)
    fires one hedge GET — first result wins, the loser is cancelled.

    Workers built from a `feeder.source_spec` respec self-arm the
    fault plan from ``KCMC_FAULT_PLAN`` so pooled chaos runs inject in
    every per-worker client, not just the consumer's.
    """

    def __init__(self, url, n_threads: int = 0, client=None):
        del n_threads  # concurrency comes from the feeder pool + hedges
        self.path = str(url)
        self._client = client if client is not None else client_for_url(url)
        self._retry = default_io_retry_policy(None)
        self._report = None
        self._tracer = None
        self._hedge_ms = _DEFAULT_HEDGE_MS
        self._timeout_s = _DEFAULT_TIMEOUT_S
        # pooled workers reopen from the spec: arm the env-var plan so
        # chaos injection follows the read into every worker client
        if getattr(self._client, "fault_plan", None) is None:
            plan = resolve_fault_plan(None)
            if plan is not None:
                self._client.fault_plan = plan
        man = load_manifest(self._client, retry=self._retry)
        self.shape = tuple(int(s) for s in man["shape"])
        self.dtype = np.dtype(str(man["dtype"]))
        self.frame_shape = self.shape[1:]
        self.compression = str(man.get("compression", "none"))
        self.chunk_frames = int(man["chunk_frames"])
        self._entries = list(man["chunks"])
        self._n = int(man["n_frames"])
        self._frame_bytes = int(
            np.prod(self.frame_shape, dtype=np.int64)
        ) * self.dtype.itemsize

    def arm(
        self,
        fault_plan=None,
        retry=None,
        report=None,
        tracer=None,
        hedge_ms: float | None = None,
        timeout_s: float | None = None,
    ) -> "ObjectStack":
        """Attach the run's robustness wiring (corrector runs call this
        right after `open_stack`). Returns self for chaining."""
        if fault_plan is not None:
            self._client.fault_plan = fault_plan
        if retry is not None:
            self._retry = retry
        if report is not None:
            self._report = report
        if tracer is not None:
            self._tracer = tracer
        if hedge_ms is not None:
            self._hedge_ms = float(hedge_ms)
        if timeout_s is not None:
            self._timeout_s = float(timeout_s)
        return self

    def __len__(self) -> int:
        return self._n

    def stats_snapshot(self) -> dict:
        return stats_snapshot(self.path)

    # -- counters / advisory ----------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        st = _url_state(self.path)
        with _STATE_LOCK:
            st["stats"][key] += n

    def _maybe_advise(self) -> None:
        """Once per URL: name the knob when the object path degrades —
        hedge-fire rate above 20% (after warm-up) or any throttle
        retries observed (the PR-9 single-core-decode advisory
        pattern)."""
        st = _url_state(self.path)
        with _STATE_LOCK:
            if st["advised"]:
                return
            s = st["stats"]
            gets, hedged, throttled = s["gets"], s["hedged"], s["throttled"]
            degraded_hedge = gets >= 50 and hedged / gets > 0.2
            if not (degraded_hedge or throttled):
                return
            st["advised"] = True
            rate = hedged / max(gets, 1)
        from kcmc_tpu.obs.log import advise

        advise(
            f"kcmc: {self.path}: object-store path degrading (hedge rate "
            f"{rate:.0%}, {throttled} throttled retries); raise "
            "io_workers (CLI --io-threads) to widen the request fan-out, "
            "or raise object_hedge_ms if hedges fire on healthy latency",
            stacklevel=3,
        )

    # -- fetch machinery ---------------------------------------------------

    def _deadline(self) -> float:
        d = getattr(self._retry, "deadline_s", None)
        return float(d) if d else self._timeout_s

    def _hedge_threshold(self) -> float | None:
        if self._hedge_ms <= 0.0:
            return None
        st = _url_state(self.path)
        with _STATE_LOCK:
            hist = st["hist"]
            if hist.count < _HEDGE_WARMUP:
                return None
            p95 = hist.quantile(95)
        if p95 is None:
            return None
        return max(float(p95), self._hedge_ms / 1e3)

    def _record(self, dur: float) -> None:
        st = _url_state(self.path)
        with _STATE_LOCK:
            st["hist"].record(dur)

    def _hedged_get(self, key: str, start: int, length: int | None) -> bytes:
        """One GET attempt, hedged: when the primary outlasts the live
        p95 (floored at hedge_ms), fire a second identical ranged GET —
        first to finish wins, the loser is cancelled (best effort: an
        already-running loser completes in its pool thread and its body
        is dropped)."""
        client, deadline = self._client, self._deadline()

        def fetch():
            t0 = time.perf_counter()
            body = client.get(
                key, start=start, length=length, deadline_s=deadline
            )
            return body, time.perf_counter() - t0

        self._count("gets")
        thresh = self._hedge_threshold()
        if thresh is None:
            body, dur = fetch()
            self._record(dur)
            return body
        ex = _hedge_executor()
        primary = ex.submit(fetch)
        try:
            body, dur = primary.result(timeout=thresh)
            self._record(dur)
            return body
        except _FutureTimeout:
            pass
        self._count("hedged")
        hedge = ex.submit(fetch)
        pending = {primary, hedge}
        err: Exception | None = None
        while pending:
            done, pending = _fut_wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                try:
                    body, dur = f.result()
                except Exception as e:
                    err = e
                    continue
                for p in pending:
                    p.cancel()
                if f is hedge:
                    self._count("hedge_wins")
                self._record(dur)
                return body
        raise err

    def _quarantine_at_rest(self, key: str, expect_sha: str) -> None:
        """A body failed its checksum: decide in-flight vs at-rest via
        the stored etag. At-rest -> quarantine + fatal (the bytes are
        gone); in-flight/unknown -> return so the caller refetches."""
        try:
            etag = self._client.head(key, deadline_s=self._deadline())["etag"]
        except Exception:
            return  # can't confirm at-rest state: treat as in-flight
        if etag == expect_sha:
            return  # stored copy is fine: the wire mangled it
        try:
            self._client.rename(key, key + ".corrupt")
        except ObjectStoreError:
            pass
        if self._report is not None:
            self._report.quarantined_parts.append(f"{self.path}/{key}")
        raise ObjectIntegrityError(
            f"{self.path}/{key}: object corrupt at rest (stored etag "
            f"{etag[:12]} != manifest {expect_sha[:12]}); quarantined as "
            f"{key}.corrupt — the frames it held are unrecoverable"
        )

    def _get_checked(
        self,
        key: str,
        start: int,
        length: int | None,
        expect_len: int,
        verify_sha: str | None,
    ) -> bytes:
        """One logical GET: hedged, retried per the policy, length- and
        checksum-verified (quarantine-and-refetch on corrupt bodies)."""
        policy = self._retry
        attempts = policy.attempts if policy is not None else 1
        for attempt in range(attempts):
            try:
                body = self._hedged_get(key, start, length)
                if len(body) != expect_len:
                    raise TransientFaultError(
                        f"{self.path}/{key}: truncated object body "
                        f"({len(body)} of {expect_len} bytes)"
                    )
                if verify_sha is not None and sha256_hex(body) != verify_sha:
                    self._count("refetched")
                    self._quarantine_at_rest(key, verify_sha)
                    raise TransientFaultError(
                        f"{self.path}/{key}: object body checksum mismatch "
                        "(in-flight corruption); refetching"
                    )
                return body
            except Exception as e:
                if isinstance(e, ObjectStoreThrottled):
                    self._count("throttled")
                    self._maybe_advise()
                if attempt == attempts - 1 or not classify_transient(e):
                    raise
                self._count("retries")
                if self._report is not None:
                    self._report.io_retries += 1
                if policy is not None:
                    policy.sleep(policy.delay(attempt))
        raise AssertionError("unreachable")  # loop always returns/raises

    def read(self, lo: int, hi: int) -> np.ndarray:
        lo, hi = max(0, int(lo)), min(self._n, int(hi))
        n = max(0, hi - lo)
        out = np.empty((n,) + tuple(self.frame_shape), self.dtype)
        if n == 0:
            return out
        t0 = time.perf_counter()
        cf = self.chunk_frames
        for ci in range(lo // cf, (hi - 1) // cf + 1):
            entry = self._entries[ci]
            base = ci * cf
            clo, chi = max(lo, base), min(hi, base + int(entry["frames"]))
            fl, fh = clo - base, chi - base  # frame span within the chunk
            whole = fl == 0 and fh == int(entry["frames"])
            if self.compression == "deflate" or whole:
                # compressed chunks cannot be ranged; whole-chunk reads
                # get the full integrity check either way
                body = self._get_checked(
                    entry["key"], 0, None,
                    expect_len=int(entry["size"]),
                    verify_sha=entry["sha256"],
                )
                if self.compression == "deflate":
                    body = zlib.decompress(body)
                frames = np.frombuffer(body, self.dtype).reshape(
                    (int(entry["frames"]),) + tuple(self.frame_shape)
                )[fl:fh]
            else:
                # genuine range request: only the needed byte span moves
                body = self._get_checked(
                    entry["key"],
                    fl * self._frame_bytes,
                    (fh - fl) * self._frame_bytes,
                    expect_len=(fh - fl) * self._frame_bytes,
                    verify_sha=None,
                )
                frames = np.frombuffer(body, self.dtype).reshape(
                    (fh - fl,) + tuple(self.frame_shape)
                )
            out[clo - lo : chi - lo] = frames
        self._maybe_advise()
        if self._tracer is not None:
            self._tracer.complete(
                "object.get",
                t0,
                time.perf_counter() - t0,
                cat="object",
                args={"lo": int(lo), "hi": int(hi)},
            )
        return out

    def close(self) -> None:
        pass  # clients are stateless; nothing to release

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# egress: sharded chunk-object writer with a durable manifest
# ---------------------------------------------------------------------------


class ObjectStoreWriter:
    """Streaming egress to a chunked object-store stack (TiffWriter
    protocol: `append_batch` / `checkpoint_state` / `close` /
    `n_pages` — slots under `AsyncBatchWriter` unchanged).

    Frames buffer until a full ``chunk_frames`` chunk exists, which
    uploads (multipart when the encoded blob exceeds ``part_bytes``)
    with write-side verification: the store's returned etag must match
    the blob's sha256, so an injected truncate/flip (or any torn
    upload) retries instead of persisting garbage. After every
    completed chunk the manifest advances atomically (previous
    generation kept as the rewind point). `checkpoint_state()` uploads
    the partial tail chunk first, so the state it returns is the
    durable high-water mark; `resume` verifies every chunk at rest
    (etag vs manifest), refuses a store behind the checkpoint cursor
    (OSError -> the corrector restarts from scratch), and reloads the
    partial tail into the buffer — so a resumed run re-uploads only
    past the high-water mark and the final chunk set is byte-identical
    to an uninterrupted run.
    """

    def __init__(
        self,
        url,
        n_frames: int,
        frame_shape: tuple,
        dtype,
        compression: str = "none",
        chunk_frames: int = _DEFAULT_CHUNK_FRAMES,
        part_bytes: int = _DEFAULT_PART_BYTES,
        client=None,
        fault_plan=None,
        retry=None,
        report=None,
        tracer=None,
    ):
        if compression not in ("none", "deflate"):
            raise ValueError(
                "object egress supports compression 'none' or 'deflate', "
                f"got {compression!r}"
            )
        self.path = str(url)
        self._client = client if client is not None else client_for_url(url)
        if fault_plan is not None:
            self._client.fault_plan = fault_plan
        self.compression = compression
        self.shape = (int(n_frames),) + tuple(int(s) for s in frame_shape)
        self.dtype = np.dtype(dtype)
        self.chunk_frames = max(1, int(chunk_frames))
        self.part_bytes = max(1, int(part_bytes))
        self._retry = retry if retry is not None else default_io_retry_policy(None)
        self._report = report
        self._tracer = tracer
        # fresh construction = fresh run (the ZarrWriter contract):
        # drop stale chunks/manifests from a previous run at this URL
        for key in self._client.list(""):
            if key.startswith("chunk-") or key.startswith(".manifest"):
                self._client.delete(key)
        self._entries: list[dict] = []  # completed full chunks
        self._buf: list[np.ndarray] = []  # tail frames (< chunk_frames)
        self._tail_dirty = False  # buffered frames not yet durable
        self._last_manifest: bytes | None = None
        self.n_pages = 0

    # -- upload machinery --------------------------------------------------

    def _encode(self, frames: np.ndarray) -> bytes:
        raw = np.ascontiguousarray(frames, self.dtype).tobytes()
        return zlib.compress(raw, 6) if self.compression == "deflate" else raw

    def _deadline(self) -> float | None:
        d = getattr(self._retry, "deadline_s", None)
        return float(d) if d else _DEFAULT_TIMEOUT_S

    def _put_verified(self, key: str, blob: bytes) -> str:
        """Upload one object (multipart past `part_bytes`), retried per
        the policy; the stored etag must equal the blob's sha256 — a
        torn or mangled upload never becomes the durable copy."""
        policy = self._retry
        attempts = policy.attempts if policy is not None else 1
        want, deadline = sha256_hex(blob), self._deadline()
        client = self._client
        t0 = time.perf_counter()
        for attempt in range(attempts):
            try:
                if len(blob) > self.part_bytes:
                    uid = client.multipart_begin(key, deadline_s=deadline)
                    try:
                        n_parts = 0
                        for off in range(0, len(blob), self.part_bytes):
                            client.multipart_put_part(
                                key, uid, n_parts,
                                blob[off : off + self.part_bytes],
                                deadline_s=deadline,
                            )
                            n_parts += 1
                        etag = client.multipart_complete(
                            key, uid, n_parts, deadline_s=deadline
                        )
                    except BaseException:
                        client.multipart_abort(key, uid)
                        raise
                else:
                    etag = client.put(key, blob, deadline_s=deadline)
                if etag != want:
                    raise TransientFaultError(
                        f"{self.path}/{key}: upload verification failed "
                        f"(stored etag {etag[:12]} != blob {want[:12]}); "
                        "re-uploading"
                    )
                st = _url_state(self.path)
                with _STATE_LOCK:
                    st["stats"]["puts"] += 1
                if self._tracer is not None:
                    self._tracer.complete(
                        "object.put",
                        t0,
                        time.perf_counter() - t0,
                        cat="object",
                        args={"key": key, "bytes": len(blob)},
                    )
                return etag
            except Exception as e:
                if isinstance(e, ObjectStoreThrottled):
                    st = _url_state(self.path)
                    with _STATE_LOCK:
                        st["stats"]["throttled"] += 1
                if attempt == attempts - 1 or not classify_transient(e):
                    raise
                st = _url_state(self.path)
                with _STATE_LOCK:
                    st["stats"]["retries"] += 1
                if self._report is not None:
                    self._report.io_retries += 1
                if policy is not None:
                    policy.sleep(policy.delay(attempt))
        raise AssertionError("unreachable")  # loop always returns/raises

    @staticmethod
    def _chunk_key(ci: int) -> str:
        return f"chunk-{ci:08d}"

    def _manifest(self, entries: list[dict]) -> dict:
        return {
            "format": _MANIFEST_FORMAT,
            "shape": list(self.shape),
            "dtype": self.dtype.str,
            "compression": self.compression,
            "chunk_frames": int(self.chunk_frames),
            "n_frames": int(sum(int(e["frames"]) for e in entries)),
            "chunks": entries,
        }

    def _flush_manifest(self, entries: list[dict]) -> None:
        data = _manifest_bytes(self._manifest(entries))
        # keep the previous generation as the rewind point (written
        # from memory: no GET in the durability path)
        if self._last_manifest is not None and self._last_manifest != data:
            self._put_verified(PREV_MANIFEST_KEY, self._last_manifest)
        self._put_verified(MANIFEST_KEY, data)
        self._last_manifest = data

    def _upload_chunk(self, ci: int, frames: np.ndarray) -> dict:
        blob = self._encode(frames)
        key = self._chunk_key(ci)
        self._put_verified(key, blob)
        return {
            "key": key,
            "frames": int(len(frames)),
            "sha256": sha256_hex(blob),
            "size": len(blob),
        }

    # -- streaming-writer protocol ----------------------------------------

    def append_batch(self, frames: np.ndarray, n_threads: int = 0) -> None:
        del n_threads  # encode cost is chunk-level; uploads dominate
        frames = np.asarray(frames)
        if tuple(frames.shape[1:]) != self.shape[1:]:
            raise ValueError(
                f"frame shape {frames.shape[1:]} != store {self.shape[1:]}"
            )
        if self.n_pages + len(frames) > self.shape[0]:
            raise ValueError(
                f"appending {len(frames)} frames past the store's "
                f"{self.shape[0]}-frame shape (at {self.n_pages})"
            )
        if len(frames) == 0:
            return
        self._buf.append(np.ascontiguousarray(frames, self.dtype))
        self.n_pages += len(frames)
        self._tail_dirty = True
        buffered = sum(len(b) for b in self._buf)
        if buffered >= self.chunk_frames:
            pending = np.concatenate(self._buf) if len(self._buf) > 1 else self._buf[0]
            off = 0
            while len(pending) - off >= self.chunk_frames:
                chunk = pending[off : off + self.chunk_frames]
                self._entries.append(
                    self._upload_chunk(len(self._entries), chunk)
                )
                off += self.chunk_frames
                self._flush_manifest(list(self._entries))
            tail = pending[off:]
            self._buf = [tail] if len(tail) else []
            self._tail_dirty = bool(len(tail))

    def _flush_tail(self) -> None:
        """Make every appended frame durable: upload the partial tail
        chunk (re-uploaded full later when more frames complete it) and
        advance the manifest to cover it."""
        if not self._tail_dirty:
            return
        tail = (
            np.concatenate(self._buf) if len(self._buf) > 1 else self._buf[0]
        )
        entry = self._upload_chunk(len(self._entries), tail)
        self._flush_manifest(list(self._entries) + [entry])
        self._buf = [tail]
        self._tail_dirty = False

    def checkpoint_state(self) -> dict:
        self._flush_tail()
        return {
            "format": "object",
            "n_pages": int(self.n_pages),
            # deflate chunk bytes are zlib-build-sensitive, exactly the
            # TIFF/Zarr deflate pin
            "zlib": zlib.ZLIB_RUNTIME_VERSION,
        }

    @classmethod
    def resume(
        cls, url, state: dict, compression: str = "none", object_opts=None
    ) -> "ObjectStoreWriter":
        """Resume against the durable manifest. OSError on anything the
        resume contract cannot honor (store behind the checkpoint
        cursor, at-rest chunk corruption below it, layout mismatch) —
        the corrector's resume handler restarts from scratch on
        OSError, exactly like a torn TIFF."""
        opts = dict(object_opts or {})
        if state.get("format") != "object":
            raise OSError(f"{url}: checkpoint writer state is not object")
        client = opts.get("client")
        if client is None:
            client = client_for_url(url, fault_plan=opts.get("fault_plan"))
        elif opts.get("fault_plan") is not None:
            client.fault_plan = opts["fault_plan"]
        retry = opts.get("retry") or default_io_retry_policy(None)
        report = opts.get("report")
        try:
            man = load_manifest(client, retry=retry, report=report)
        except ObjectNotFound as e:
            raise OSError(f"{url}: no usable egress manifest at resume: {e}") from e
        if str(man.get("compression", "none")) != compression:
            raise OSError(
                f"{url}: store compression {man.get('compression')!r} does "
                f"not match the resume compression {compression!r}"
            )
        try:
            n = int(state["n_pages"])
        except (KeyError, TypeError, ValueError) as e:
            raise OSError(f"{url}: malformed object writer state: {e}") from e
        durable = int(man["n_frames"])
        if durable < n:
            raise OSError(
                f"{url}: durable high-water mark {durable} is behind the "
                f"checkpoint cursor {n} (manifest rewound or egress torn)"
            )
        self = object.__new__(cls)
        self.path = str(url)
        self._client = client
        self.compression = compression
        self.shape = tuple(int(s) for s in man["shape"])
        self.dtype = np.dtype(str(man["dtype"]))
        self.chunk_frames = int(man["chunk_frames"])
        self.part_bytes = max(1, int(opts.get("part_bytes", _DEFAULT_PART_BYTES)))
        self._retry = retry
        self._report = report
        self._tracer = opts.get("tracer")
        self._last_manifest = None
        deadline = getattr(retry, "deadline_s", None) or _DEFAULT_TIMEOUT_S
        # verify every chunk at rest below the cursor; reload the
        # partial tail into the buffer so its chunk re-uploads FULL
        entries: list[dict] = []
        buf: list[np.ndarray] = []
        base = 0
        for e in man["chunks"]:
            frames_e = int(e["frames"])
            if base >= n:
                break  # past the cursor: stale bytes, overwritten later
            try:
                etag = client.head(e["key"], deadline_s=deadline)["etag"]
            except ObjectNotFound:
                etag = None
            if etag != e["sha256"]:
                if etag is not None:
                    try:
                        client.rename(e["key"], e["key"] + ".corrupt")
                    except ObjectStoreError:
                        pass
                    if report is not None:
                        report.quarantined_parts.append(
                            f"{url}/{e['key']}"
                        )
                raise OSError(
                    f"{url}: chunk object {e['key']} "
                    f"{'corrupt' if etag is not None else 'missing'} at "
                    "resume (durable frames lost below the checkpoint "
                    "cursor)"
                )
            keep = min(frames_e, n - base)
            if keep == self.chunk_frames:
                entries.append(dict(e))
            else:
                # partial tail: pull its live frames back into the
                # buffer so future appends complete the chunk in place
                body = _get_at_rest(client, e["key"], retry=retry)
                if sha256_hex(body) != e["sha256"]:
                    raise OSError(
                        f"{url}: chunk object {e['key']} unreadable at "
                        "resume (checksum mismatch)"
                    )
                if compression == "deflate":
                    body = zlib.decompress(body)
                frames = np.frombuffer(body, self.dtype).reshape(
                    (frames_e,) + self.shape[1:]
                )
                buf = [np.array(frames[:keep])]
            base += frames_e
        self._entries = entries
        self._buf = buf
        self._tail_dirty = False
        self.n_pages = n
        return self

    def close(self) -> None:
        self._flush_tail()


def put_stack(
    url,
    stack: np.ndarray,
    chunk_frames: int = _DEFAULT_CHUNK_FRAMES,
    compression: str = "none",
    part_bytes: int = _DEFAULT_PART_BYTES,
    client=None,
) -> str:
    """Upload an in-memory stack as a chunked object-store stack (the
    test/bench fixture helper — and the way a local stack becomes a
    bucket-resident one). Returns the URL."""
    stack = np.asarray(stack)
    w = ObjectStoreWriter(
        url,
        len(stack),
        tuple(stack.shape[1:]),
        stack.dtype,
        compression=compression,
        chunk_frames=chunk_frames,
        part_bytes=part_bytes,
        client=client,
    )
    w.append_batch(stack)
    w.close()
    return str(url)
