"""Bounded background writeback for the streaming pipeline.

`correct_file` used to call `writer.append_batch` inside the drain
callback on the consumer thread, so TIFF/Zarr/HDF5 encode+write
serialized with device dispatch — every page written was a page the
accelerator waited for. `AsyncBatchWriter` wraps any streaming writer
(the TiffWriter protocol: `append_batch` / `checkpoint_state` /
`close`) with a bounded FIFO queue and one worker thread. The
object-store egress path (io/objectstore.py ObjectStoreWriter) rides
this unchanged — its multipart uploads and retry backoff run on the
worker thread here, overlapping network time with device dispatch, and
`checkpoint_state`'s flush-first contract is exactly what makes its
manifest a durable high-water mark:

* appends ENQUEUE and return immediately; a full queue blocks the
  caller (backpressure — bounded memory, and the blocked time is
  recorded in `stats()["backpressure_s"]` for the stall telemetry);
* the single worker preserves append order exactly;
* worker exceptions surface on the CONSUMER thread at the next
  append/flush/checkpoint_state/close, the same contract
  `ChunkedStackLoader` uses for prefetch-thread decode errors;
* `checkpoint_state()` flushes first, so the state it returns is the
  writer's durable high-water mark — a checkpoint can never claim
  frames the worker had not yet written, and kill/resume semantics are
  byte-identical to synchronous writes.
"""

from __future__ import annotations

import queue
import threading
import time


class AsyncBatchWriter:
    """Wrap a streaming writer with a depth-bounded background append
    queue. `depth` is the maximum number of batches in flight (>= 1).
    `tracer` (an obs.trace.Tracer, optional) records each worker-side
    append and consumer-side backpressure/flush wait as spans — the
    writer thread shows up as its own track in the exported trace."""

    def __init__(self, writer, depth: int = 2, tracer=None):
        if depth < 1:
            raise ValueError(f"AsyncBatchWriter depth must be >= 1, got {depth}")
        self.writer = writer
        self._tracer = tracer
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._exc: BaseException | None = None
        self._exc_lock = threading.Lock()
        self._close_lock = threading.Lock()
        # Guards the advisory counters: the worker and the consumer
        # both accumulate into _stats (write_s/batches vs
        # backpressure_s/flush_s), and stats() snapshots from whatever
        # thread asks — the race pass (`kcmc check`) holds all three
        # sides to one lock.
        self._stats_lock = threading.Lock()
        self._closed = False
        self._stats = {
            "backpressure_s": 0.0,  # consumer blocked on a full queue
            "flush_s": 0.0,  # consumer blocked draining for a checkpoint
            "write_s": 0.0,  # worker time actually encoding+writing
            "batches": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name="kcmc-writer", daemon=True
        )
        self._thread.start()

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                with self._exc_lock:
                    failed = self._exc is not None
                if failed:  # after a failure: drain, don't write
                    continue
                frames, n_threads = item
                t0 = time.perf_counter()
                try:
                    self.writer.append_batch(frames, n_threads=n_threads)
                    dt = time.perf_counter() - t0
                    with self._stats_lock:
                        self._stats["write_s"] += dt
                        self._stats["batches"] += 1
                        batches = self._stats["batches"]
                    if self._tracer is not None:
                        self._tracer.complete(
                            "writer.append_batch", t0, dt, cat="writer",
                            args={"batch": batches},
                        )
                except BaseException as e:  # surfaced on the consumer
                    with self._exc_lock:
                        self._exc = e
            finally:
                self._q.task_done()

    def _check(self) -> None:
        # The read-and-clear is atomic across threads: a pending worker
        # failure surfaces on exactly ONE caller (serving tears writers
        # down from the scheduler thread while the opener may also be
        # closing — both racing into here must not both re-raise).
        with self._exc_lock:
            exc, self._exc = self._exc, None
        if exc is not None:
            raise exc

    # -- consumer-side protocol -------------------------------------------

    def append_batch(self, frames, n_threads: int = 0) -> None:
        # The closed-check and enqueue happen under the close lock:
        # otherwise a concurrent close() could slip between them, retire
        # the worker, and leave this batch silently parked behind the
        # shutdown sentinel — written to nobody. A close() racing a
        # backpressure-blocked append waits for it (the worker is still
        # draining, so the put always completes).
        with self._close_lock:
            if self._closed:
                raise ValueError("append_batch on a closed AsyncBatchWriter")
            self._check()
            item = (frames, n_threads)
            try:
                self._q.put_nowait(item)
            except queue.Full:
                t0 = time.perf_counter()
                self._q.put(item)
                dt = time.perf_counter() - t0
                with self._stats_lock:
                    self._stats["backpressure_s"] += dt
                if self._tracer is not None:
                    self._tracer.complete(
                        "writer.backpressure", t0, dt, cat="stall"
                    )
        # re-check AFTER enqueuing so a worker failure surfaces at most
        # one append late, not only at close
        self._check()

    def flush(self) -> None:
        """Block until every enqueued batch is durable in the inner
        writer (or its failure has surfaced)."""
        self._check()
        t0 = time.perf_counter()
        self._q.join()
        dt = time.perf_counter() - t0
        with self._stats_lock:
            self._stats["flush_s"] += dt
        if self._tracer is not None and dt > 0:
            self._tracer.complete("writer.flush", t0, dt, cat="stall")
        self._check()

    def checkpoint_state(self) -> dict:
        """Durable high-water-mark state: flushes, then delegates."""
        self.flush()
        return self.writer.checkpoint_state()

    @property
    def n_pages(self) -> int:
        """Pages DURABLE in the inner writer (lags appends by the queue)."""
        return self.writer.n_pages

    def stats(self) -> dict:
        with self._stats_lock:
            return dict(self._stats)

    def close(self) -> None:
        """Flush, stop the worker, close the inner writer; re-raises a
        pending worker failure.

        Idempotent AND thread-safe: the serving scheduler tears down a
        session's writer from ITS thread while the session opener (or a
        `finally` on the submitting thread) may close concurrently —
        exactly one caller performs the teardown, any concurrent caller
        blocks until it is done, and a pending worker error surfaces
        exactly once across all of them (`_check`'s atomic
        read-and-clear)."""
        with self._close_lock:
            if not self._closed:
                self._closed = True
                if self._thread.is_alive():
                    self._q.put(None)
                    self._thread.join()
                self.writer.close()
        self._check()
