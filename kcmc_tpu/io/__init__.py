"""Stack I/O: TIFF read/write (native threaded decoder), pluggable
streaming ingest (Zarr/HDF5/npy/raw/array via one reader protocol),
chunked prefetch loading, and the sharded decode-pool feeder."""

from kcmc_tpu.io import feeder
from kcmc_tpu.io.async_writer import AsyncBatchWriter
from kcmc_tpu.io.feeder import DecodePool
from kcmc_tpu.io.formats import (
    ArrayStack,
    HDF5Stack,
    NpyStack,
    RawStack,
    ZarrStack,
    open_stack,
)
from kcmc_tpu.io.objectstore import (
    EmulatedObjectStore,
    ObjectStack,
    ObjectStoreWriter,
    put_stack,
)
from kcmc_tpu.io.reader import ChunkedStackLoader
from kcmc_tpu.io.tiff import TiffStack, read_stack, write_stack

__all__ = [
    "ArrayStack",
    "AsyncBatchWriter",
    "ChunkedStackLoader",
    "DecodePool",
    "EmulatedObjectStore",
    "HDF5Stack",
    "NpyStack",
    "ObjectStack",
    "ObjectStoreWriter",
    "RawStack",
    "TiffStack",
    "ZarrStack",
    "feeder",
    "open_stack",
    "put_stack",
    "read_stack",
    "write_stack",
]
