"""Stack I/O: TIFF read/write (native threaded decoder) + chunked loading."""

from kcmc_tpu.io.reader import ChunkedStackLoader
from kcmc_tpu.io.tiff import TiffStack, read_stack, write_stack

__all__ = ["ChunkedStackLoader", "TiffStack", "read_stack", "write_stack"]
