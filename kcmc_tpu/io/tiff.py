"""Multi-page TIFF stack I/O: native threaded decoder + NumPy fallback.

Microscopy motion-correction stacks arrive as multi-page grayscale TIFF
(often LZW/Deflate-compressed). Decoding is the host-side bottleneck the
TPU pipeline streams from, so it is implemented natively
(kcmc_tpu/native/stackio.cpp): IFD tables are parsed once, then page
ranges decode in parallel with a thread pool straight into a NumPy
buffer. The native library is built on first use with the system g++
(no Python build deps; ctypes ABI) and cached beside the source; when a
toolchain is unavailable the pure-NumPy fallback below implements the
same format subset (and doubles as the correctness oracle in tests).

Supported subset (both paths): classic + BigTIFF, II/MM byte order,
single-sample grayscale, stripped layout, compression none / LZW /
Deflate / PackBits, 8/16/32-bit integer and 32/64-bit float samples.

Writing (`write_stack`) emits classic little-endian multi-page TIFF,
optionally Deflate- or PackBits-compressed.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import tempfile
import threading
import zlib
from pathlib import Path

import numpy as np

_DTYPES = {
    0: np.dtype(np.uint8),
    1: np.dtype(np.uint16),
    2: np.dtype(np.uint32),
    3: np.dtype(np.int8),
    4: np.dtype(np.int16),
    5: np.dtype(np.int32),
    6: np.dtype(np.float32),
    7: np.dtype(np.float64),
}

_NATIVE_SRC = Path(__file__).resolve().parent.parent / "native" / "stackio.cpp"
_native_lock = threading.Lock()
_native_lib = None
_native_failed = False


class _StackInfo(ctypes.Structure):
    _fields_ = [
        ("n_pages", ctypes.c_uint64),
        ("width", ctypes.c_uint32),
        ("height", ctypes.c_uint32),
        ("dtype", ctypes.c_int32),
    ]


def _build_native() -> ctypes.CDLL | None:
    """Compile and load the native decoder; None if no toolchain."""
    so_path = _NATIVE_SRC.parent / "_stackio.so"
    try:
        src_mtime = _NATIVE_SRC.stat().st_mtime
    except OSError:  # source not shipped: degrade to the NumPy decoder
        return None
    if not os.access(_NATIVE_SRC.parent, os.W_OK):
        # Per-user private cache dir (0700, ownership-checked): a fixed
        # world-shared /tmp name would let another local user plant or
        # swap the library, and a fresh mkdtemp per process would
        # recompile on every import and leak directories.
        build_dir = Path(tempfile.gettempdir()) / f"kcmc_native_{os.getuid()}"
        try:
            build_dir.mkdir(mode=0o700, exist_ok=True)
            st = build_dir.lstat()
        except OSError:  # e.g. planted file/symlink at the path
            return None
        import stat as stat_mod

        if (
            not stat_mod.S_ISDIR(st.st_mode)
            or st.st_uid != os.getuid()
            or st.st_mode & 0o077
        ):
            return None
        so_path = build_dir / "kcmc_stackio.so"
    if not so_path.exists() or so_path.stat().st_mtime < src_mtime:
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            str(_NATIVE_SRC), "-o", str(so_path), "-lz", "-pthread",
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, timeout=120
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
    try:
        lib = ctypes.CDLL(str(so_path))
    except OSError:
        return None
    lib.kcmc_open.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(_StackInfo),
    ]
    lib.kcmc_open.restype = ctypes.c_int
    lib.kcmc_read_pages.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.kcmc_read_pages.restype = ctypes.c_int
    lib.kcmc_last_error.argtypes = [ctypes.c_void_p]
    lib.kcmc_last_error.restype = ctypes.c_char_p
    lib.kcmc_close.argtypes = [ctypes.c_void_p]
    lib.kcmc_close.restype = None
    try:  # encoder exports (absent in a stale cached .so: decode-only)
        lib.kcmc_deflate_bound.argtypes = [ctypes.c_uint64]
        lib.kcmc_deflate_bound.restype = ctypes.c_uint64
        lib.kcmc_deflate_pages.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.kcmc_deflate_pages.restype = ctypes.c_int
        lib.kcmc_zlib_version.argtypes = []
        lib.kcmc_zlib_version.restype = ctypes.c_char_p
    except AttributeError:
        pass
    return lib


def _deflate_encoder_id(pin_python: bool = False) -> str:
    """Identity of the zlib build(s) a deflate stream will be written
    with: recorded in resume checkpoints, because byte-identical resume
    holds only when the resumed run compresses through the same encoder
    (zlib output is deterministic per build+level, but zlib-ng or a
    version skew produces valid-yet-different bytes)."""
    py = f"py:{zlib.ZLIB_RUNTIME_VERSION}"
    if pin_python:
        return py
    lib = _get_native()
    if lib is not None and hasattr(lib, "kcmc_deflate_pages"):
        ver = (
            lib.kcmc_zlib_version().decode()
            if hasattr(lib, "kcmc_zlib_version")
            else "?"
        )
        return f"{py}+native:{ver}"
    return py


def _get_native():
    global _native_lib, _native_failed
    with _native_lock:
        if _native_lib is None and not _native_failed:
            _native_lib = _build_native()
            _native_failed = _native_lib is None
    return _native_lib


# ---------------------------------------------------------------------------
# pure-NumPy fallback parser (same subset; also the test oracle)
# ---------------------------------------------------------------------------

_TYPE_SIZE = {1: 1, 2: 1, 3: 2, 4: 4, 5: 8, 6: 1, 7: 1, 8: 2, 9: 4, 10: 8,
              11: 4, 12: 8, 13: 4, 16: 8, 17: 8, 18: 8}


def _lzw_decode_py(data: bytes, expected: int) -> bytes:
    out = bytearray()
    table: list[bytes] = [bytes([i]) for i in range(256)] + [b"", b""]
    width, next_code = 9, 258
    prev: bytes | None = None
    bitbuf, bits = 0, 0
    for byte in data:
        bitbuf = (bitbuf << 8) | byte
        bits += 8
        while bits >= width:
            code = (bitbuf >> (bits - width)) & ((1 << width) - 1)
            bits -= width
            if code == 256:
                table = table[:258]
                width, next_code, prev = 9, 258, None
                continue
            if code == 257:
                return bytes(out[:expected])
            if prev is None:
                entry = table[code]
            elif code < len(table):
                entry = table[code]
                table.append(prev + entry[:1])
                next_code += 1
            else:
                entry = prev + prev[:1]
                table.append(entry)
                next_code += 1
            out += entry
            prev = entry
            if next_code >= 2047:
                width = 12
            elif next_code >= 1023:
                width = 11
            elif next_code >= 511:
                width = 10
            if len(out) >= expected:
                return bytes(out[:expected])
    return bytes(out[:expected])


def _packbits_decode_py(data: bytes, expected: int) -> bytes:
    out = bytearray()
    i = 0
    n = len(data)
    while i < n and len(out) < expected:
        c = data[i]
        i += 1
        if c < 128:
            out += data[i : i + c + 1]
            i += c + 1
        elif c != 128:
            out += bytes([data[i]]) * (257 - c)
            i += 1
    return bytes(out[:expected])


class _PyTiffParser:
    """Minimal classic/BigTIFF IFD walker for the supported subset."""

    def __init__(self, path: str):
        self.f = open(path, "rb")
        hdr = self.f.read(4)
        if hdr[:2] == b"II":
            self.en = "<"
        elif hdr[:2] == b"MM":
            self.en = ">"
        else:
            raise ValueError(f"{path}: not a TIFF")
        magic = struct.unpack(self.en + "H", hdr[2:4])[0]
        if magic == 42:
            self.big = False
            (off,) = struct.unpack(self.en + "I", self.f.read(4))
        elif magic == 43:
            self.big = True
            osz, _ = struct.unpack(self.en + "HH", self.f.read(4))
            if osz != 8:
                raise ValueError("bad BigTIFF header")
            (off,) = struct.unpack(self.en + "Q", self.f.read(8))
        else:
            raise ValueError("bad TIFF magic")
        self.pages = []
        self.meta = None
        while off:
            off = self._read_ifd(off)

    def _values(self, type_, count, raw):
        tsz = _TYPE_SIZE.get(type_)
        if tsz is None:
            return None
        total = tsz * count
        field = 8 if self.big else 4
        if total <= field:
            buf = raw[:total]
        else:
            fmt = self.en + ("Q" if self.big else "I")
            (ptr,) = struct.unpack(fmt, raw)
            keep = self.f.tell()
            self.f.seek(ptr)
            buf = self.f.read(total)
            self.f.seek(keep)
        code = {1: "B", 2: "b", 3: "H", 4: "I", 5: "Q", 6: "b", 7: "B",
                8: "h", 9: "i", 16: "Q", 17: "q", 18: "Q"}.get(type_)
        if code is None:
            if type_ in (11, 12):
                code = "f" if type_ == 11 else "d"
            else:
                return None
        vals = struct.unpack(self.en + code * count, buf[: tsz * count])
        return list(vals)

    def _read_ifd(self, off):
        f = self.f
        f.seek(off)
        if self.big:
            (n,) = struct.unpack(self.en + "Q", f.read(8))
            esz = 20
        else:
            (n,) = struct.unpack(self.en + "H", f.read(2))
            esz = 12
        tags = {}
        base = f.tell()
        for i in range(n):
            f.seek(base + i * esz)
            tag, type_ = struct.unpack(self.en + "HH", f.read(4))
            if self.big:
                (count,) = struct.unpack(self.en + "Q", f.read(8))
                raw = f.read(8)
            else:
                (count,) = struct.unpack(self.en + "I", f.read(4))
                raw = f.read(4)
            vals = self._values(type_, count, raw)
            if vals is not None:
                tags[tag] = vals
        f.seek(base + n * esz)
        (nxt,) = struct.unpack(
            self.en + ("Q" if self.big else "I"),
            f.read(8 if self.big else 4),
        )

        if any(t in tags for t in (322, 323, 324, 325)):
            raise ValueError("tiled TIFF not supported")
        width = tags[256][0]
        height = tags[257][0]
        bits = tags.get(258, [8])[0]
        comp = tags.get(259, [1])[0]
        spp = tags.get(277, [1])[0]
        fmt = tags.get(339, [1])[0]
        if spp != 1:
            raise ValueError("only single-sample (grayscale) TIFF supported")
        if comp not in (1, 5, 8, 32946, 32773):
            raise ValueError(f"unsupported compression {comp}")
        offsets = tags[273]
        counts = tags[279]
        rps = tags.get(278, [height])[0] or height
        meta = (width, height, bits, comp, fmt)
        if self.meta is None:
            self.meta = meta
        elif meta != self.meta:
            raise ValueError("non-uniform pages")
        strips = []
        rows_left = height
        for o, c in zip(offsets, counts):
            rows = min(rps, rows_left)
            rows_left -= rows
            strips.append((o, c, rows))
        self.pages.append(strips)
        return nxt

    @property
    def dtype(self) -> np.dtype:
        _, _, bits, _, fmt = self.meta
        if fmt == 3:
            base = {32: np.float32, 64: np.float64}[bits]
        elif fmt == 2:
            base = {8: np.int8, 16: np.int16, 32: np.int32}[bits]
        else:
            base = {8: np.uint8, 16: np.uint16, 32: np.uint32}[bits]
        return np.dtype(base).newbyteorder(self.en)

    def read_page(self, idx: int) -> np.ndarray:
        width, height, bits, comp, _ = self.meta
        row_bytes = width * (bits // 8)
        chunks = []
        for off, cnt, rows in self.pages[idx]:
            self.f.seek(off)
            data = self.f.read(cnt)
            want = row_bytes * rows
            if comp == 1:
                raw = data[:want]
            elif comp == 5:
                raw = _lzw_decode_py(data, want)
            elif comp in (8, 32946):
                raw = zlib.decompress(data)[:want]
            else:
                raw = _packbits_decode_py(data, want)
            if len(raw) < want:
                raw = raw + b"\0" * (want - len(raw))
            chunks.append(raw)
        buf = b"".join(chunks)
        arr = np.frombuffer(buf, dtype=self.dtype, count=width * height)
        return arr.reshape(height, width).astype(self.dtype.newbyteorder("="))

    def close(self):
        self.f.close()


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


class TiffStack:
    """A multi-page TIFF opened for random page-range access.

    Uses the native threaded decoder when available; NumPy fallback
    otherwise. Context-manager friendly.
    """

    def __init__(
        self,
        path: str | os.PathLike,
        n_threads: int = 0,
        force_python: bool = False,
    ):
        # force_python (also the KCMC_FORCE_PY_TIFF env var) pins the
        # pure-NumPy decoder: decode-pool workers (io/feeder.py) respec
        # python-path sources with it so no worker races to build — or
        # silently switches to — the native library mid-run, and tests/
        # benchmarks use it to measure the GIL-bound fallback
        # deterministically on toolchain-equipped hosts.
        self.path = os.fspath(path)
        self.n_threads = n_threads
        self._handle = None
        self._py = None
        env = os.environ.get("KCMC_FORCE_PY_TIFF", "").strip().lower()
        if force_python or env not in ("", "0", "false", "no"):
            lib = None
        else:
            lib = _get_native()
        if lib is not None:
            handle = ctypes.c_void_p()
            info = _StackInfo()
            rc = lib.kcmc_open(
                self.path.encode(), ctypes.byref(handle), ctypes.byref(info)
            )
            if rc == 0:
                self._lib = lib
                self._handle = handle
                self.n_frames = int(info.n_pages)
                self.frame_shape = (int(info.height), int(info.width))
                self.dtype = _DTYPES[int(info.dtype)]
                return
            err = lib.kcmc_last_error(handle).decode()
            lib.kcmc_close(handle)
            # Fall through to the Python parser for a consistent error
            # message — or success, if only the native path is limited.
            self._native_error = err
        self._py = _PyTiffParser(self.path)
        self.n_frames = len(self._py.pages)
        self.frame_shape = (self._py.meta[1], self._py.meta[0])
        self.dtype = self._py.dtype.newbyteorder("=")

    @property
    def shape(self) -> tuple[int, int, int]:
        return (self.n_frames,) + self.frame_shape

    def read(self, lo: int = 0, hi: int | None = None) -> np.ndarray:
        """Decode pages [lo, hi) into a (n, H, W) array."""
        hi = self.n_frames if hi is None else min(hi, self.n_frames)
        if not 0 <= lo <= hi:
            raise IndexError(f"page range [{lo}, {hi})")
        n = hi - lo
        out = np.empty((n,) + self.frame_shape, self.dtype)
        if self._handle is not None:
            rc = self._lib.kcmc_read_pages(
                self._handle, lo, hi,
                out.ctypes.data_as(ctypes.c_void_p), self.n_threads,
            )
            if rc != 0:
                raise IOError(
                    f"{self.path}: "
                    f"{self._lib.kcmc_last_error(self._handle).decode()}"
                )
        else:
            for i in range(n):
                out[i] = self._py.read_page(lo + i)
        return out

    def __len__(self) -> int:
        return self.n_frames

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            lo, hi, step = idx.indices(self.n_frames)
            arr = self.read(lo, hi)
            return arr[::step] if step != 1 else arr
        if idx < 0:
            idx += self.n_frames
        return self.read(idx, idx + 1)[0]

    def close(self):
        if self._handle is not None:
            self._lib.kcmc_close(self._handle)
            self._handle = None
        if self._py is not None:
            self._py.close()
            self._py = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def backend(self) -> str:
        return "native" if self._handle is not None else "python"

    @property
    def compression(self) -> int | None:
        """TIFF compression tag of the stack's pages (1 = none, 5 =
        LZW, 8/32946 = deflate, 32773 = packbits), or None when only
        the native decoder parsed the file (it does not surface the
        tag). The feeder uses this to route GIL-bound pure-Python
        codecs through the process pool."""
        if self._py is not None:
            return int(self._py.meta[3])
        return None


def read_stack(path: str | os.PathLike, lo: int = 0, hi: int | None = None,
               n_threads: int = 0) -> np.ndarray:
    """Read a (T, H, W) stack from a multi-page TIFF."""
    with TiffStack(path, n_threads=n_threads) as ts:
        return ts.read(lo, hi)


_SAMPLE_FORMAT = {"u": 1, "i": 2, "f": 3}
_COMP_CODES = {"none": 1, "deflate": 8, "packbits": 32773}


def _packbits_encode(row: bytes) -> bytes:
    # Literal-only PackBits (valid, if not maximally compact).
    out = bytearray()
    i = 0
    while i < len(row):
        n = min(128, len(row) - i)
        out.append(n - 1)
        out += row[i : i + n]
        i += n
    return bytes(out)


class TiffWriter:
    """Incremental little-endian multi-page TIFF writer (classic or BigTIFF).

    Pages append one at a time (streaming pipelines write corrected
    frames as they come off the device); all pages must share shape and
    dtype. compression: "none" | "deflate" | "packbits".

    `bigtiff=True` writes 64-bit-offset BigTIFF — required for stacks
    past the classic format's 4 GiB offset ceiling (a 512x512x10k-frame
    uint16 stack is 5 GB); both this module's reader and the native C++
    decoder read it back.
    """

    def __init__(
        self,
        path: str | os.PathLike,
        compression: str = "none",
        bigtiff: bool = False,
    ):
        if compression not in _COMP_CODES:
            raise ValueError(f"compression must be one of {sorted(_COMP_CODES)}")
        self.compression = compression
        self.bigtiff = bool(bigtiff)
        self._f = open(path, "wb")
        if self.bigtiff:
            # BigTIFF header: II, 43, offset size 8, pad 0, first-IFD u64
            self._f.write(b"II\x2b\x00" + struct.pack("<HH", 8, 0))
            self._f.write(struct.pack("<Q", 0))
            self._ifd_ptr_pos = 8
        else:
            self._f.write(b"II\x2a\x00")
            self._f.write(struct.pack("<I", 0))  # first-IFD offset patched later
            self._ifd_ptr_pos = 4
        self._meta = None  # (H, W, dtype)
        self.n_pages = 0
        # Set by resume() when the checkpointed stream was written by
        # the Python zlib path: keeps the resumed bytes identical even
        # if the native parallel encoder has become available since.
        self._pin_python_deflate = False

    # struct formats per flavor: next-IFD pointer, entry-count, entry
    @property
    def _ptr_fmt(self):
        return "<Q" if self.bigtiff else "<I"

    def _check_frame(self, frame: np.ndarray) -> np.ndarray:
        frame = np.ascontiguousarray(frame)
        if frame.ndim != 2:
            raise ValueError(f"frame must be 2D, got {frame.shape}")
        dt = frame.dtype
        if dt.kind not in _SAMPLE_FORMAT or dt.itemsize not in (1, 2, 4, 8):
            raise ValueError(f"unsupported dtype {dt}")
        meta = (frame.shape[0], frame.shape[1], dt)
        if self._meta is None:
            self._meta = meta
        elif meta != self._meta:
            raise ValueError(f"page {meta} != first page {self._meta}")
        return frame

    def append(self, frame: np.ndarray) -> None:
        frame = self._check_frame(frame)
        raw = frame.astype(frame.dtype.newbyteorder("<"), copy=False).tobytes()
        if self.compression == "deflate":
            data = zlib.compress(raw, 6)
        elif self.compression == "packbits":
            data = _packbits_encode(raw)
        else:
            data = raw
        self._write_page(frame.shape[0], frame.shape[1], frame.dtype, data)

    def append_batch(self, frames: np.ndarray, n_threads: int = 0) -> None:
        """Append a (T, H, W) batch of pages.

        With deflate compression and the native library available, the
        pages compress in parallel through `kcmc_deflate_pages` —
        bitwise-identical to the per-page Python path ONLY when both
        link the same zlib build (checkpoints record the encoder id and
        resume() pins/warns on mismatch; see _deflate_encoder_id);
        otherwise this is a plain per-page loop. The streaming drain
        hands whole batches here, keeping compressed streaming off the
        single-thread zlib ceiling.
        """
        frames = np.asarray(frames)
        if frames.ndim != 3:
            raise ValueError(f"batch must be (T, H, W), got {frames.shape}")
        if (
            self.compression == "deflate"
            and len(frames) > 1
            and not self._pin_python_deflate
        ):
            lib = _get_native()
            if lib is not None and hasattr(lib, "kcmc_deflate_pages"):
                first = self._check_frame(frames[0])
                le = np.ascontiguousarray(
                    frames.astype(first.dtype.newbyteorder("<"), copy=False)
                )
                n = len(le)
                page_bytes = le[0].nbytes
                bound = int(lib.kcmc_deflate_bound(page_bytes))
                buf = ctypes.create_string_buffer(bound * n)
                sizes = (ctypes.c_uint64 * n)()
                rc = lib.kcmc_deflate_pages(
                    le.ctypes.data_as(ctypes.c_void_p), n, page_bytes, 6,
                    buf, bound, sizes, n_threads,
                )
                if rc == 0:
                    H, W = le.shape[1:]
                    mv = memoryview(buf)
                    for i in range(n):
                        self._write_page(
                            H, W, first.dtype,
                            bytes(mv[i * bound : i * bound + int(sizes[i])]),
                        )
                    return
                # encoder failure: fall through to the Python path
        for fr in frames:
            self.append(fr)

    def _write_page(self, H: int, W: int, dt: np.dtype, data: bytes) -> None:
        f = self._f
        strip_off = f.tell()
        # Classic TIFF carries 32-bit offsets; refuse to stream past them
        # with a clear error instead of corrupting the file mid-write.
        if not self.bigtiff and strip_off + len(data) + 256 >= 2**32:
            raise ValueError(
                "classic TIFF output would exceed 4 GiB; pass bigtiff=True "
                "(64-bit offsets), write compressed (compression='deflate'), "
                "or split the stack across files"
            )
        f.write(data)
        if f.tell() % 2:
            f.write(b"\0")  # word-align the IFD
        ifd_off = f.tell()
        # patch previous next-IFD (or the header's first-IFD) pointer
        f.seek(self._ifd_ptr_pos)
        f.write(struct.pack(self._ptr_fmt, ifd_off))
        f.seek(ifd_off)

        entries = [
            (256, 4, 1, W),                            # ImageWidth
            (257, 4, 1, H),                            # ImageLength
            (258, 3, 1, dt.itemsize * 8),              # BitsPerSample
            (259, 3, 1, _COMP_CODES[self.compression]),
            (262, 3, 1, 1),                            # Photometric: BlackIsZero
            (273, 16 if self.bigtiff else 4, 1, strip_off),  # StripOffsets
            (277, 3, 1, 1),                            # SamplesPerPixel
            (278, 4, 1, H),                            # RowsPerStrip
            (279, 4, 1, len(data)),                    # StripByteCounts
            (339, 3, 1, _SAMPLE_FORMAT[dt.kind]),      # SampleFormat
        ]
        if self.bigtiff:
            f.write(struct.pack("<Q", len(entries)))
            for tag, type_, count, value in entries:
                f.write(struct.pack("<HHQQ", tag, type_, count, value))
        else:
            f.write(struct.pack("<H", len(entries)))
            for tag, type_, count, value in entries:
                f.write(struct.pack("<HHII", tag, type_, count, value))
        self._ifd_ptr_pos = f.tell()
        f.write(struct.pack(self._ptr_fmt, 0))  # next IFD (patched on next append)
        self.n_pages += 1

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- checkpoint/resume (streaming-resume support, corrector.py) --------

    def checkpoint_state(self) -> dict:
        """Flush and capture the writer's exact append cursor.

        The returned dict, stored in a resume checkpoint, lets
        `TiffWriter.resume` reopen the file mid-stream and continue
        producing a byte-identical TIFF: file size, the position of the
        open next-IFD pointer, page count, and page metadata.
        """
        self._f.flush()
        state = {
            "file_size": self._f.tell(),
            "ifd_ptr_pos": self._ifd_ptr_pos,
            "n_pages": self.n_pages,
            "bigtiff": self.bigtiff,
            "meta": None
            if self._meta is None
            else [self._meta[0], self._meta[1], self._meta[2].str],
        }
        if self.compression == "deflate":
            state["encoder"] = _deflate_encoder_id(self._pin_python_deflate)
        return state

    @classmethod
    def resume(cls, path, state: dict, compression: str = "none") -> "TiffWriter":
        """Reopen a partially-written TIFF at a checkpointed state.

        Truncates anything appended after the checkpoint (a kill can
        leave a torn page) and re-zeros the last completed page's
        next-IFD pointer, restoring the byte-exact writer state, so the
        resumed stream is indistinguishable from an uninterrupted one.

        For deflate streams the checkpoint records which zlib build(s)
        wrote the file; when the recorded encoder was the Python path,
        the resumed writer pins itself to it (so a native encoder that
        appeared since cannot change the bytes), and when the recorded
        encoder is no longer reproducible (zlib version skew) a warning
        downgrades the guarantee to pixel-identical for this resume.
        """
        if compression not in _COMP_CODES:
            raise ValueError(f"compression must be one of {sorted(_COMP_CODES)}")
        # A file SHORTER than the checkpoint (replaced/partial copy)
        # must not be zero-extended by truncate() into silent garbage
        # pages — fail so the caller restarts from scratch.
        if os.path.getsize(path) < int(state["file_size"]):
            raise OSError(
                f"{path}: shorter than the checkpointed cursor "
                f"({os.path.getsize(path)} < {state['file_size']} bytes)"
            )
        w = cls.__new__(cls)
        w.compression = compression
        w.bigtiff = bool(state.get("bigtiff", False))
        w._f = open(path, "r+b")
        # ...and an unrelated file that happens to be big enough must
        # not be truncated into a corrupt TIFF: the header must match
        # the checkpointed flavor before any destructive write.
        magic = w._f.read(4)
        want = b"II\x2b\x00" if w.bigtiff else b"II\x2a\x00"
        if magic != want:
            w._f.close()
            raise OSError(
                f"{path}: header {magic!r} does not match the "
                f"checkpointed output ({want!r}) — not resuming"
            )
        w._f.truncate(state["file_size"])
        w._ifd_ptr_pos = int(state["ifd_ptr_pos"])
        # a torn append may have patched the open next-IFD pointer
        w._f.seek(w._ifd_ptr_pos)
        w._f.write(struct.pack(w._ptr_fmt, 0))
        w._f.seek(int(state["file_size"]))
        meta = state.get("meta")
        w._meta = (
            None
            if meta is None
            else (int(meta[0]), int(meta[1]), np.dtype(meta[2]))
        )
        w.n_pages = int(state["n_pages"])
        w._pin_python_deflate = False
        if compression == "deflate":
            recorded = state.get("encoder")
            if recorded == _deflate_encoder_id(pin_python=True):
                # Stream written by Python zlib only: pin the resumed
                # writer to it so the bytes stay identical even if the
                # native encoder is available now.
                w._pin_python_deflate = True
            elif recorded is not None and recorded != _deflate_encoder_id():
                from kcmc_tpu.obs.log import advise

                advise(
                    f"kcmc: resume checkpoint was written by deflate "
                    f"encoder {recorded!r} but this run would use "
                    f"{_deflate_encoder_id()!r}; the resumed file will "
                    "be pixel-identical but may not be byte-identical "
                    "to an uninterrupted run",
                    stacklevel=2,
                )
        return w


def write_stack(
    path: str | os.PathLike,
    stack: np.ndarray,
    compression: str = "none",
    bigtiff: bool = False,
) -> None:
    """Write a (T, H, W) array as little-endian multi-page TIFF
    (classic, or BigTIFF with `bigtiff=True` for >4 GiB stacks)."""
    stack = np.asarray(stack)
    if stack.ndim == 2:
        stack = stack[None]
    if stack.ndim != 3:
        raise ValueError(f"stack must be (T, H, W), got {stack.shape}")
    with TiffWriter(path, compression=compression, bigtiff=bigtiff) as w:
        for frame in stack:
            w.append(frame)
