"""StreamScheduler: cross-session batching, fairness, and QoS admission.

The resident serving plane's core loop. One warm backend (owned by the
corrector the scheduler is built around) serves every session:

* **Cross-stream batching** — ready frames are taken per session (each
  device batch carries ONE session's reference, the per-entry-ref
  dispatch seam from the zero-stall pipeline) and interleaved through a
  single bounded in-flight window (`serve_inflight` batches), so the
  upload of one tenant's batch overlaps the compute of another's and
  the accelerator never idles while ANY stream has work.
* **Fairness** — weighted round-robin across sessions with ready
  frames: a session opened with weight w gets w interleaved slots per
  cycle, so a bulk-backfill tenant cannot starve a live interactive
  stream.
* **Admission control + QoS** — a submit that would push a session's
  pending queue past `serve_queue_depth` is rejected 429-style, but
  rejection is the LAST resort: past `serve_degrade_watermark` of the
  bound, the session's batches dispatch through a degraded backend
  (reduced RANSAC hypothesis budget and refine/polish passes — the
  consensus-stage rungs of the robustness ladder, which never change
  reference preparation) so the backlog drains faster at reduced
  accuracy instead of being refused. Decisions are counted in
  `stats()` and narrated by the aggregate heartbeat.

Device errors walk the SAME degradation ladder as one-shot runs
(retry -> numpy failover -> mark-failed + trajectory rescue), per
session, via each session's corrector view; a fatal error fails that
ONE stream, never the serving process.

Threading model: ONE scheduler thread owns dispatch, drains, template
updates, and finalization; client threads only enqueue (submit/open/
close) under the scheduler lock and wait on per-session conditions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from kcmc_tpu.obs.log import advise


class OverloadedError(RuntimeError):
    """429-style admission rejection: the session's queue is full even
    after QoS degradation engaged. Carries `.code` for transports."""

    code = 429

    def __init__(self, message: str, queued: int, limit: int):
        super().__init__(message)
        self.queued = int(queued)
        self.limit = int(limit)


class StreamScheduler:
    """Multiplex concurrent `Session` streams through one warm backend.

    `corrector` is the resident MotionCorrector whose backend (and
    compiled batch programs) every session shares; its config supplies
    `batch_size` and the serve_* QoS knobs.
    """

    def __init__(self, corrector, heartbeat_s: float = 0.0):
        self.mc = corrector
        cfg = corrector.config
        self.B = cfg.batch_size
        self.inflight_depth = cfg.serve_inflight
        self.queue_depth = cfg.serve_queue_depth
        self.watermark = cfg.serve_degrade_watermark
        # RLock: paths like a take_batch failure call session methods
        # (fail -> _cond, built on this same lock) while already
        # holding it — reentrancy beats a deadlock class.
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._sessions: dict[str, object] = {}
        self._reserved: set = set()  # sids mid-construction (open_session)
        self._order: list[str] = []  # weighted round-robin schedule
        self._rr = 0
        self._window: deque = deque()  # in-flight entries (scheduler thread)
        # Per-backend-instance cache of whether process_batch_async
        # accepts the warm-start `seed` kwarg (scheduler thread only).
        self._seed_accepts: dict[int, bool] = {}
        self._degraded_backend = None
        self._degraded_build = threading.Lock()
        # Frame shapes whose degraded-budget programs have been warmed
        # (and those with a warm-up in flight or permanently failed —
        # never re-attempted). See _warm_degraded_shape.
        self._degraded_warm_started: set = set()
        # Recently closed session ids: a `results` poll racing a
        # concurrent close must read "exhausted", not "no such session"
        # (bounded — ids only, never session state).
        self._closed_ids: set = set()
        self._closed_order: deque = deque(maxlen=4096)
        # The most recently closed Session OBJECTS, so a close_session
        # that timed out client-side can be retried without losing the
        # stream's final result, and a late results poll can still
        # deliver undelivered spans. Small and bounded — these retain
        # result arrays (pixels included for emit sessions).
        self._recent: dict[str, object] = {}
        self._recent_depth = 16
        self._running = False
        self._thread: threading.Thread | None = None
        # Non-daemon degraded-budget warm-up threads (XLA-reaching work
        # must never run on a daemon thread — PR-7 rule); joined on
        # stop(). See _spawn_warmup.
        self._warm_threads: list[threading.Thread] = []
        self._heartbeat = None
        self._heartbeat_s = float(heartbeat_s)
        self._seq = 0
        self._stats = {
            "accepted_frames": 0,
            "rejected_submits": 0,
            "rejected_frames": 0,
            "degrade_events": 0,
            "degraded_batches": 0,
            "batches": 0,
            "occupied_frames": 0,  # valid frames across dispatched batches
            "frames_done": 0,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StreamScheduler":
        with self._lock:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="kcmc-serve-scheduler", daemon=True
        )
        self._thread.start()
        if self.watermark < 1.0:
            # Prewarm the QoS escape hatch's CONSTRUCTION (backend +
            # mesh setup). Its compiled batch programs are shape-
            # dependent, so those warm later, per shape, as sessions'
            # references are prepared (_warm_degraded_shape) — well
            # before overload can engage on that shape.
            self._spawn_warmup(
                self._warm_degraded, "kcmc-serve-degraded-warm"
            )
        if self._heartbeat_s > 0:
            from kcmc_tpu.obs.heartbeat import Heartbeat, aggregate_sampler

            self._heartbeat = Heartbeat(
                self._heartbeat_s, aggregate_sampler(self.snapshot)
            )
            self._heartbeat.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop the scheduler thread. In-flight batches drain; sessions
        still open are finalized (complete streams) or failed (streams
        with frames left) — a clean shutdown closes sessions first."""
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        with self._wake:
            self._running = False
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            warm, self._warm_threads = self._warm_threads, []
        for t in warm:
            t.join(timeout=timeout)

    def _spawn_warmup(self, target, name: str, args: tuple = ()) -> None:
        """Degraded-budget warm-up threads reach jax compile (backend
        construction, batch-program builds), so they are NON-daemon and
        joined on stop — a daemon thread killed mid-XLA-compile aborts
        interpreter teardown (the PR-7 rule, enforced by `kcmc check`'s
        daemon-xla pass). Bounded: one construction warm-up plus one
        per distinct frame shape."""
        t = threading.Thread(
            target=target, name=name, args=args, daemon=False
        )
        with self._lock:
            self._warm_threads = [
                w for w in self._warm_threads if w.is_alive()
            ]
            self._warm_threads.append(t)
            # start INSIDE the lock: stop() swaps the list under the
            # same lock, so every thread it joins has been started
            # (join on a never-started thread raises), and a racing
            # spawn's is_alive() prune cannot drop a tracked thread
            # between append and start
            t.start()

    def __enter__(self) -> "StreamScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- session management (client threads) -------------------------------

    def open_session(
        self,
        tenant: str = "default",
        weight: int = 1,
        reference=None,
        template_update_every: int | None = None,
        emit_frames: bool = False,
        output: str | None = None,
        expected_frames: int | None = None,
        output_dtype="float32",
        compression: str = "none",
        session_id: str | None = None,
        telemetry: bool = True,
    ):
        """Open a stream: builds a per-session corrector view sharing
        the warm backend, registers it with the fairness schedule, and
        returns the `Session`."""
        from kcmc_tpu.serve.session import Session

        view = self.mc.stream_view(
            reference=reference,
            template_update_every=template_update_every,
        )
        ref_arr = None
        if isinstance(reference, np.ndarray):
            # Validate BEFORE any session state exists: a bad reference
            # must fail without arming (and leaking) telemetry
            # artifact-path claims.
            ref_arr = np.asarray(reference, np.float32)
            if ref_arr.ndim != 2:
                raise ValueError(
                    f"reference frame must be 2-D, got shape "
                    f"{ref_arr.shape}"
                )
        with self._wake:
            if not self._running:
                raise RuntimeError("scheduler is not running")
            self._seq += 1
            sid = session_id if session_id else f"s{self._seq:04d}"
            if sid in self._sessions or sid in self._reserved:
                raise ValueError(f"session id {sid!r} already open")
            self._reserved.add(sid)
        # Construct OUTSIDE the plane lock: telemetry arming builds a
        # manifest (version probes, config digest) — other tenants'
        # submits and the scheduler loop must not stall behind it. The
        # reservation above keeps the sid unique meanwhile.
        sess = None
        try:
            sess = Session(
                view, self._lock, sid, tenant=tenant, weight=weight,
                emit_frames=emit_frames, output=output,
                expected_frames=expected_frames, output_dtype=output_dtype,
                compression=compression, telemetry=telemetry,
            )
            with self._wake:
                # Reference staging happens under the plane lock with
                # the registration: the scheduler thread reads the
                # staged source under the same lock, and ref_arr is
                # already float32 so this is pointer work, not a copy.
                if ref_arr is not None:
                    sess.set_reference(ref_arr)
                self._sessions[sid] = sess
                self._rebuild_order()
                self._wake.notify_all()
            return sess
        except BaseException as e:
            # A constructed-but-never-registered session still owns
            # telemetry (artifact-path claims): release it, or the
            # registry treats those paths as live forever.
            if sess is not None and sess.telemetry is not None:
                try:
                    sess.telemetry.close(e)
                except Exception:
                    pass
            raise
        finally:
            with self._wake:
                self._reserved.discard(sid)

    def _rebuild_order(self) -> None:
        # Weighted round-robin schedule: a session with weight w appears
        # w times per cycle, interleaved (not clustered) so a heavy
        # tenant's extra slots spread across the cycle.
        sids = sorted(self._sessions)
        if not sids:
            self._order = []
            self._rr = 0
            return
        maxw = max(self._sessions[s].weight for s in sids)
        self._order = [
            s
            for round_i in range(maxw)
            for s in sids
            if round_i < self._sessions[s].weight
        ]
        self._rr %= len(self._order)

    def submit(self, session_id: str, frames) -> dict:
        """Admission-controlled submit. Returns a decision dict
        ``{"accepted", "queued", "degraded"}``; raises OverloadedError
        when the queue bound is exceeded (the last resort — QoS
        degradation engages first, at the watermark)."""
        frames = np.asarray(frames)
        n = 1 if frames.ndim == 2 else len(frames)
        with self._wake:
            sess = self._get(session_id)
            queued = sess.backlog()
            if queued + n > self.queue_depth:
                self._stats["rejected_submits"] += 1
                self._stats["rejected_frames"] += n
                raise OverloadedError(
                    f"session {session_id}: queue {queued}+{n} frames "
                    f"exceeds serve_queue_depth={self.queue_depth} "
                    "(submit less per call, or wait for results)",
                    queued=queued, limit=self.queue_depth,
                )
            engage = (
                not sess.degraded
                and self.watermark < 1.0
                and queued + n > self.watermark * self.queue_depth
            )
            # Validate/admit BEFORE flipping QoS state: a mis-shaped
            # submit raises here and must not leave the session
            # permanently degraded by load it never added.
            sess.add_frames(frames)
            self._stats["accepted_frames"] += n
            if engage:
                sess.degraded = True
                self._stats["degrade_events"] += 1
                advise(
                    f"kcmc serve: session {session_id} backlog "
                    f"{queued + n}/{self.queue_depth} frames passed the "
                    f"{self.watermark:.0%} watermark; dispatching its "
                    "batches at degraded consensus budgets until it drains",
                    stacklevel=2,
                )
            self._wake.notify_all()
            return {
                "accepted": n,
                "queued": sess.backlog(),
                "degraded": sess.degraded,
            }

    def close_session(self, session_id: str, timeout: float | None = None):
        """Mark a stream complete; block until its remaining frames
        drain and it finalizes. Returns the final CorrectionResult.
        Retryable: a close that timed out client-side can be reissued —
        a recently reaped session still returns its final result
        (transforms/diagnostics; retained results drop emit pixels)."""
        with self._wake:
            sess = self._sessions.get(session_id)
            if sess is not None:
                sess.begin_close()
                self._wake.notify_all()
        if sess is None:
            # Already finalized and reaped (e.g. a retry after a
            # timed-out close): result() returns immediately.
            sess = self.lookup_session(session_id)
        return sess.result(timeout=timeout)

    def _get(self, session_id: str):
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        return sess

    def session_closed(self, session_id: str) -> bool:
        """Whether `session_id` was a real session that has since
        closed (vs never existing) — lets a `results` poll racing a
        concurrent close report "exhausted" instead of erroring."""
        with self._lock:
            return session_id in self._closed_ids

    def lookup_session(self, session_id: str):
        """A live session, or a recently closed one retained for late
        result()/fetch() reads (e.g. a close_session retry after a
        client-side timeout); KeyError otherwise."""
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None:
                sess = self._recent.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        return sess

    def _record_closed_locked(self, sess) -> None:
        if len(self._closed_order) == self._closed_order.maxlen:
            self._closed_ids.discard(self._closed_order[0])
        self._closed_order.append(sess.sid)
        self._closed_ids.add(sess.sid)
        # Retention must not pin pixels: an emit session's final result
        # holds the whole corrected stack, so once a client has RECEIVED
        # it (delivered flag — an undelivered result stays whole for the
        # still-blocked/retrying waiter), a later retried close gets
        # transforms/diagnostics only. Undelivered `results` spans in
        # _outs keep their pixels — a racing poll still gets them, and
        # fetch releases each span as it delivers.
        res = sess._result
        if sess._result_delivered and res is not None and (
            res.corrected is not None and len(res.corrected)
        ):
            sess._result = dataclasses.replace(
                res, corrected=np.empty((0,), np.float32)
            )
        self._recent[sess.sid] = sess
        while len(self._recent) > self._recent_depth:
            self._recent.pop(next(iter(self._recent)))

    # -- stats / heartbeat --------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
            st = dict(self._stats)
            inflight = len(self._window)
            # backlog() walks session queues the scheduler mutates —
            # snapshot it under the plane lock, not after it
            queues = {s.sid: s.backlog() for s in sessions}
            degraded_active = sorted(
                s.sid for s in sessions if s.degraded
            )
            db = self._degraded_backend
        batches = max(st["batches"], 1)
        out = {
            "sessions_open": len(sessions),
            "queues": queues,
            "inflight_batches": inflight,
            "batch_size": self.B,
            "batch_occupancy": round(
                st["occupied_frames"] / (batches * self.B), 4
            ),
            "frames_done": st["frames_done"],
            "admission": {
                "accepted_frames": st["accepted_frames"],
                "rejected_submits": st["rejected_submits"],
                "rejected_frames": st["rejected_frames"],
                "degrade_events": st["degrade_events"],
                "degraded_batches": st["degraded_batches"],
                "degraded_active": degraded_active,
            },
        }
        # Execution-plan / compile-cache accounting (kcmc_tpu/plans):
        # operators verify a resident server actually starts (and
        # stays) warm — zero stamp_misses after the first boot means
        # every program deserialized from the persistent cache. The
        # degraded QoS rung's backend keeps its own counters.
        stats_fn = getattr(self.mc.backend, "plan_cache_stats", None)
        if stats_fn is not None:
            try:
                ps = stats_fn()
                if ps.get("enabled") or ps.get("programs_compiled"):
                    out["plan_cache"] = ps
            except Exception:
                pass
        dstats_fn = getattr(db, "plan_cache_stats", None) if db else None
        if dstats_fn is not None:
            try:
                dps = dstats_fn()
                if dps.get("programs_compiled"):
                    out["plan_cache_degraded"] = dps
            except Exception:
                pass
        return out

    def snapshot(self) -> dict:
        """Aggregate-heartbeat snapshot (obs.heartbeat.aggregate_sampler)."""
        with self._lock:
            sessions = list(self._sessions.values())
            st = dict(self._stats)
            inflight = len(self._window)
            queues = {s.sid: s.backlog() for s in sessions}
            snaps = [s.snapshot() for s in sessions]
        batches = max(st["batches"], 1)
        return {
            "sessions": snaps,
            "queues": queues,
            "admission": {
                "rejected": st["rejected_frames"],
                "degraded": st["degraded_batches"],
            },
            "extra": (
                f"occupancy={st['occupied_frames'] / (batches * self.B):.2f}"
                f" inflight={inflight}"
            ),
        }

    # -- QoS ----------------------------------------------------------------

    def _get_degraded_backend(self):
        """The reduced-budget backend overload dispatches through: the
        consensus-stage knobs shrink (hypothesis budgets, refine/polish
        passes) while every reference-preparation knob stays identical,
        so a session's prepared reference is valid on both backends.
        Built once (prewarmed from `start`; the build lock keeps the
        warm thread and the scheduler thread from racing)."""
        with self._degraded_build:
            if self._degraded_backend is None:
                from kcmc_tpu.backends import get_backend

                cfg = self.mc.config
                dcfg = cfg.replace(
                    n_hypotheses=max(16, cfg.n_hypotheses // 4),
                    refine_iters=min(cfg.refine_iters, 1),
                    patch_hypotheses=max(8, cfg.patch_hypotheses // 4),
                    field_passes=1,
                    field_polish=min(int(cfg.field_polish), 1),
                    transform_polish=0,
                )
                backend = get_backend(self.mc.backend_name, dcfg)
                # Tag the reduced-budget rung in its plan runtime: its
                # compile stamps and stats are keyed/labelled
                # "degraded", so a restarted server's prefetches hit
                # the persistent cache for THIS rung's programs too
                # (the config digest already differs; the label makes
                # stats and stamps readable).
                plan = getattr(backend, "_plan", None)
                if plan is not None:
                    plan.rung = "degraded"
                # Publish under the PLANE lock: stats() reads the
                # handle there without ever waiting behind this
                # build (seconds of XLA compile when overload first
                # engages); _degraded_build keeps builders serialized.
                with self._lock:
                    self._degraded_backend = backend
            return self._degraded_backend

    def _warm_degraded(self) -> None:
        try:
            self._get_degraded_backend()
        except Exception as e:
            advise(
                f"kcmc serve: degraded-backend prewarm failed ({e}); "
                "overloaded batches will dispatch at full budgets",
                stacklevel=2,
            )

    def _maybe_warm_degraded_shape(self, sess) -> None:
        """Kick a background compile of the degraded backend's batch
        program for `sess`'s frame shape, once per shape. Called right
        after the session's reference is prepared — the queue cannot
        reach the watermark before at least one reference exists, so
        the warm-up races only the RAMP to overload, not overload
        itself; without it, the first degraded dispatch would pay the
        reduced-budget JIT inline on the scheduler thread at peak
        backlog."""
        if self.watermark >= 1.0 or sess.ref_frame is None:
            return
        shape = tuple(sess.frame_shape)
        with self._lock:
            if shape in self._degraded_warm_started:
                return
            self._degraded_warm_started.add(shape)
        ref, ref_frame = sess.ref, sess.ref_frame
        self._spawn_warmup(
            self._warm_degraded_shape,
            "kcmc-serve-degraded-warm-shape",
            args=(shape, ref, ref_frame),
        )

    def _warm_degraded_shape(self, shape, ref, ref_frame) -> None:
        try:
            backend = self._get_degraded_backend()
            # The session's own reference content: realistic keypoints,
            # and a reference prepared by the FULL backend is valid on
            # the degraded one (reference-prep knobs are identical).
            dummy = np.broadcast_to(
                ref_frame, (self.B,) + shape
            ).astype(np.float32)
            out = backend.process_batch(dummy, ref, np.arange(self.B))
            for v in out.values():
                np.asarray(v)  # block until the compile+run finished
        except Exception as e:
            advise(
                f"kcmc serve: degraded-program warm-up for frame shape "
                f"{shape} failed ({e}); the first overloaded batch of "
                "that shape compiles inline",
                stacklevel=2,
            )

    def _maybe_restore_locked(self, sess) -> None:
        # Hysteresis: quality restores once the backlog drains below
        # half the watermark (not the instant it dips under it).
        if sess.degraded and sess.backlog() <= (
            0.5 * self.watermark * self.queue_depth
        ):
            sess.degraded = False

    # -- the scheduler loop --------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                if not self._running:
                    break
            try:
                self._loop_once()
            except Exception as e:
                # The scheduler thread is the whole serving plane: an
                # unexpected error must degrade to a warning, never
                # wedge every tenant behind a dead loop. (Session-
                # attributable failures are already routed to fail();
                # this is the backstop for scheduler-side bugs.)
                advise(
                    f"kcmc serve: scheduler error "
                    f"({type(e).__name__}: {e}); continuing",
                    stacklevel=2,
                )
                time.sleep(0.05)
        # Shutdown: drain in-flight work, then finalize complete streams
        # and fail incomplete ones (waiters must not hang).
        while self._window:
            self._drain_one()
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
            for sess in leftovers:
                self._record_closed_locked(sess)
            self._rebuild_order()
        for sess in leftovers:
            if sess.closed:
                continue
            if not sess.drained_out():
                sess.fail(RuntimeError("serve scheduler stopped mid-stream"))
            sess.begin_close()
            sess.finalize()

    def _loop_once(self) -> None:
        """One scheduler-loop iteration: dispatch a ready batch, else
        drain, else idle-wait for work."""
        self._prepare_references()
        with self._wake:
            picked = self._pick_locked() if self._running else None
        if picked is not None:
            sess, (n, batch, idx, ref), degraded = picked
            backend = self.mc.backend
            if degraded:
                try:
                    backend = self._get_degraded_backend()
                except Exception:
                    pass  # prewarm already advised; full budgets
            entry = self._dispatch(
                sess, backend, n, batch, idx, ref, degraded
            )
            if entry is not None:
                with self._lock:
                    # stats()/snapshot() read the window depth under
                    # the plane lock; mutations take it too (drains
                    # still materialize OUTSIDE it)
                    self._window.append(entry)
                while len(self._window) >= self.inflight_depth:
                    self._drain_one()
            self._finalize_ready()
            return
        if self._window:
            self._drain_one()
            self._finalize_ready()
            return
        self._finalize_ready()
        with self._wake:
            if self._running and self._pick_preview_locked() is None:
                self._wake.wait(timeout=0.1)

    def _prepare_references(self) -> None:
        """Prepare staged references OUTSIDE the lock (device compute,
        possibly a JIT compile — client submits must keep flowing on
        every other session meanwhile). Scheduler thread only."""
        with self._lock:
            needing = [
                s
                for s in self._sessions.values()
                if s.error is None and not s.closed and s.needs_reference()
            ]
        for sess in needing:
            try:
                sess.prepare_reference_now()
            except BaseException as e:
                sess.fail(e)
            else:
                self._maybe_warm_degraded_shape(sess)

    def _pick_preview_locked(self):
        """Whether ANY session has dispatchable or finalizable work
        (idle-wait predicate; does not consume anything)."""
        for sess in self._sessions.values():
            if sess.error is None and not sess.closed and (
                sess.ready_count() or sess.needs_reference()
            ):
                return sess
            if sess.closing and not sess.closed and sess.drained_out():
                return sess
        return None

    def _pick_locked(self):
        """Weighted round-robin pick: returns (session, padded batch,
        degraded flag) for the next session with ready frames, else
        None."""
        order = self._order
        for i in range(len(order)):
            sid = order[(self._rr + i) % len(order)]
            sess = self._sessions.get(sid)
            if sess is None or sess.closed or sess.error is not None:
                continue
            if sess.ready_count() > 0:
                try:
                    taken = sess.take_batch(self.B)
                except Exception as e:
                    # Batch-forming failure is that ONE stream's
                    # problem (fail drops its pending frames, so this
                    # cannot respin) — the plane keeps serving.
                    sess.fail(e)
                    continue
                if taken is not None:
                    self._rr = (self._rr + i + 1) % len(order)
                    return sess, taken, sess.degraded
        return None

    def _finalize_ready(self) -> None:
        """Finalize sessions whose streams fully drained after
        begin_close, OUTSIDE the scheduler lock (writer close blocks),
        then drop closed sessions from the schedule."""
        with self._lock:
            ready = [
                s for s in self._sessions.values()
                if s.closing and not s.closed and s.drained_out()
            ]
        for s in ready:
            s.finalize()
        with self._lock:
            done = [(sid, s) for sid, s in self._sessions.items() if s.closed]
            for sid, s in done:
                del self._sessions[sid]
                self._record_closed_locked(s)
            if done:
                self._rebuild_order()

    def _dispatch(self, sess, backend, n, batch, idx, ref, degraded):
        """Dispatch one session batch; on a dispatch-time error, flush
        the window first (ordering + the ladder's synthesis template),
        then walk the session's degradation ladder. Returns a window
        entry, or None when the error path already accounted the
        batch."""
        if (
            not getattr(backend, "accepts_native_dtype", False)
            and batch.dtype != np.float32
        ):
            batch = batch.astype(np.float32)
        dispatch = getattr(backend, "process_batch_async", None)
        with self._lock:
            # scheduler-thread QoS counters share the plane lock with
            # the stats()/snapshot() readers
            self._stats["batches"] += 1
            self._stats["occupied_frames"] += int(n)
            if degraded:
                self._stats["degraded_batches"] += 1
        kept = batch if sess.wants_pixels() else None
        kw = {}
        warm = (
            sess.mc.config.warm_start
            and sess.mc.config.model != "piecewise"
            and dispatch is not None
        )
        if warm:
            # Plugin-seam guard (the corrector's _dispatch_accepts
            # convention): a backend implementing the original async
            # seam without a `seed` parameter keeps working — it just
            # never warm-starts. Cached per backend instance.
            bkey = id(backend)
            ok = self._seed_accepts.get(bkey)
            if ok is None:
                ok = sess.mc._dispatch_accepts(dispatch, "seed")
                self._seed_accepts[bkey] = ok
            warm = ok
        if warm and sess.warm_seed is not None:
            # Temporal warm start, per SESSION: each stream's own last
            # transform seeds its next batch's consensus (streams are
            # independent temporal histories — never share seeds).
            kw["seed"] = (sess.warm_seed, True)
        try:
            if dispatch is not None:
                out = dispatch(batch, ref, idx, **kw)
            else:
                out = backend.process_batch(batch, ref, idx)
        except Exception as e:
            while self._window:
                self._drain_one()
            self._ladder(sess, e, backend, batch, ref, idx, n, kept)
            return None
        if warm and "transform" in out:
            sess.warm_seed = out["transform"][n - 1]
        return (sess, n, out, kept, batch, idx, ref, backend)

    def _drain_one(self) -> None:
        """Drain the oldest in-flight entry: materialize to host (where
        a deferred async device error surfaces — it walks the ladder),
        then hand the batch to its session."""
        with self._lock:
            if not self._window:
                return
            sess, n, out, kept, batch, idx, ref, backend = (
                self._window.popleft()
            )
        try:
            # Registration-only sessions (no emit, no server-side file,
            # no rolling template) never touch pixels: leave `corrected`
            # on device instead of paying a (B, H, W) host transfer per
            # batch — the same drop the one-shot registration-only path
            # makes before materializing.
            host = {
                k: np.asarray(v)[:n]
                for k, v in out.items()
                if sess.wants_pixels() or k != "corrected"
            }
            sess.mc._note_out_template(host)
        except Exception as e:
            self._ladder(sess, e, backend, batch, ref, idx, n, kept)
            return
        self._account_done(sess, n, host, kept, ref)

    def _ladder(self, sess, exc, backend, batch, ref, idx, n, kept) -> None:
        """Walk the session's degradation ladder for a failed batch
        (retry -> failover backend -> mark-failed); a fatal error fails
        that ONE stream, never the serving process."""
        try:
            out, failed = sess.mc._ladder_batch(
                exc, backend, batch, ref, idx, {}, None, n, True, None
            )
        except BaseException as e:
            sess.fail(e)
            sess.entry_done()
            return
        host = {
            k: np.asarray(v)[:n]
            for k, v in out.items()
            if sess.wants_pixels() or k != "corrected"
        }
        kept = sess.mc._failed_kept(host, kept, failed)
        self._account_done(sess, n, host, kept, ref)

    def _account_done(self, sess, n, host, kept, ref) -> None:
        try:
            sess.on_drained(n, host, kept, ref)
        except BaseException as e:
            sess.fail(e)
        finally:
            sess.entry_done()
        with self._lock:
            self._stats["frames_done"] += int(n)
            self._maybe_restore_locked(sess)
