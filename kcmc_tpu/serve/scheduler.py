"""StreamScheduler: cross-session batching, fairness, and QoS admission.

The resident serving plane's core loop. One warm backend (owned by the
corrector the scheduler is built around) serves every session:

* **Cross-stream batching** — ready frames are taken per session (each
  device batch carries ONE session's reference, the per-entry-ref
  dispatch seam from the zero-stall pipeline) and interleaved through a
  single bounded in-flight window (`serve_inflight` batches), so the
  upload of one tenant's batch overlaps the compute of another's and
  the accelerator never idles while ANY stream has work.
* **Fairness** — weighted round-robin across sessions with ready
  frames: a session opened with weight w gets w interleaved slots per
  cycle, so a bulk-backfill tenant cannot starve a live interactive
  stream.
* **Admission control + QoS** — a submit that would push a session's
  pending queue past `serve_queue_depth` is rejected 429-style, but
  rejection is the LAST resort: past `serve_degrade_watermark` of the
  bound, the session's batches dispatch through a degraded backend
  (reduced RANSAC hypothesis budget and refine/polish passes — the
  consensus-stage rungs of the robustness ladder, which never change
  reference preparation) so the backlog drains faster at reduced
  accuracy instead of being refused. Decisions are counted in
  `stats()` and narrated by the aggregate heartbeat.

Device errors walk the SAME degradation ladder as one-shot runs
(retry -> numpy failover -> mark-failed + trajectory rescue), per
session, via each session's corrector view; a fatal error fails that
ONE stream, never the serving process.

Threading model: ONE scheduler thread owns dispatch, drains, template
updates, and finalization; client threads only enqueue (submit/open/
close) under the scheduler lock and wait on per-session conditions.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
import uuid
from collections import deque

import numpy as np

from kcmc_tpu.obs.latency import SegmentLatencies
from kcmc_tpu.obs.log import advise
from kcmc_tpu.plans.buckets import batch_ladder, route_batch


class OverloadedError(RuntimeError):
    """429-style admission rejection: the session's queue is full even
    after QoS degradation engaged — or, with predictive admission, a
    deadline the horizon model already predicts will be missed
    (`predicted_wait_s` carries the prediction so clients can back off
    an informed amount). Carries `.code` for transports."""

    code = 429

    def __init__(
        self, message: str, queued: int, limit: int,
        predicted_wait_s: float | None = None,
    ):
        super().__init__(message)
        self.queued = int(queued)
        self.limit = int(limit)
        self.predicted_wait_s = (
            float(predicted_wait_s) if predicted_wait_s is not None else None
        )


class StreamScheduler:
    """Multiplex concurrent `Session` streams through one warm backend.

    `corrector` is the resident MotionCorrector whose backend (and
    compiled batch programs) every session shares; its config supplies
    `batch_size` and the serve_* QoS knobs.
    """

    def __init__(self, corrector, heartbeat_s: float = 0.0):
        self.mc = corrector
        cfg = corrector.config
        self.B = cfg.batch_size
        self.inflight_depth = cfg.serve_inflight
        self.queue_depth = cfg.serve_queue_depth
        self.watermark = cfg.serve_degrade_watermark
        self.journal_dir = cfg.serve_journal_dir
        self.journal_every = cfg.serve_journal_every
        self.session_timeout_s = cfg.serve_session_timeout_s
        # Latency QoS (docs/SERVING.md "Latency QoS"): the deadline-
        # aware dispatch knobs, plus the halving batch-bucket ladder a
        # deadline-forced partial window pads to (smallest covering
        # rung — a smaller compiled program is a faster one).
        self.fill_floor = cfg.serve_latency_fill_floor
        self.admission_predict = cfg.serve_latency_admission
        self.horizon_refresh_s = cfg.serve_latency_horizon_refresh_s
        self.starvation_limit = cfg.serve_latency_starvation_limit
        self._rungs = batch_ladder(self.B)
        # Dispatch-horizon model cache (predicted seconds from "dispatch
        # now" to results, per rung): recomputed from the live latency
        # histograms at most every horizon_refresh_s — scheduling
        # decisions read a dict, not quantile math.
        self._horizon_cache: dict | None = None
        self._horizon_last = -float("inf")
        # Bounded-starvation ledger: batch-class sessions a latency
        # preemption skipped while they had ready frames accumulate
        # credit; at serve_latency_starvation_limit one gets the next
        # slot unconditionally (credit reset, grant counted).
        self._starve_credit: dict[str, int] = {}
        # Latency sessions whose deadline-forced partial is being held
        # below serve_latency_fill_floor (the dispatch that finally
        # fires records why="fill_floor").
        self._floor_deferred: set = set()
        # (shape, rung) partial-window programs already background-
        # compiled for latency streams (see _maybe_warm_partial_rungs).
        self._rung_warm_started: set = set()
        # The serve plane's OWN fault-plan instance, for the surfaces
        # the plane (not a session) owns: `scheduler` here, `transport`
        # in serve/server.py's handler. Sessions arm their own plans
        # (per-stream deterministic op counters) for device/io/journal.
        from kcmc_tpu.utils.faults import resolve_fault_plan

        self.fault_plan = resolve_fault_plan(cfg.fault_plan, seed=cfg.seed)
        # RLock: paths like a take_batch failure call session methods
        # (fail -> _cond, built on this same lock) while already
        # holding it — reentrancy beats a deadlock class.
        self._lock = threading.RLock()
        self._wake = threading.Condition(self._lock)
        self._sessions: dict[str, object] = {}
        self._reserved: set = set()  # sids mid-construction (open_session)
        self._order: list[str] = []  # weighted round-robin schedule
        self._rr = 0
        self._window: deque = deque()  # in-flight entries (scheduler thread)
        # Per-backend-instance cache of whether process_batch_async
        # accepts the warm-start `seed` kwarg (scheduler thread only).
        self._seed_accepts: dict[int, bool] = {}
        self._degraded_backend = None
        self._degraded_build = threading.Lock()
        # Frame shapes whose degraded-budget programs have been warmed
        # (and those with a warm-up in flight or permanently failed —
        # never re-attempted). See _warm_degraded_shape.
        self._degraded_warm_started: set = set()
        # Recently closed session ids: a `results` poll racing a
        # concurrent close must read "exhausted", not "no such session"
        # (bounded — ids only, never session state).
        self._closed_ids: set = set()
        self._closed_order: deque = deque(maxlen=4096)
        # The most recently closed Session OBJECTS, so a close_session
        # that timed out client-side can be retried without losing the
        # stream's final result, and a late results poll can still
        # deliver undelivered spans. Small and bounded — these retain
        # result arrays (pixels included for emit sessions).
        self._recent: dict[str, object] = {}
        self._recent_depth = 16
        self._running = False
        self._thread: threading.Thread | None = None
        # Non-daemon degraded-budget warm-up threads (XLA-reaching work
        # must never run on a daemon thread — PR-7 rule); joined on
        # stop(). See _spawn_warmup.
        self._warm_threads: list[threading.Thread] = []
        self._heartbeat = None
        self._heartbeat_s = float(heartbeat_s)
        self._seq = 0
        # Backend supervision (docs/ROBUSTNESS.md "Serve-plane
        # failures"): consecutive primary-backend batch failures;
        # at cfg.serve_backend_strikes (a fatal dispatch error counts
        # as the full threshold) the backend is quarantined and rebuilt
        # on a background thread while the ladder's failover rung keeps
        # sessions flowing. All under the plane lock.
        self._strikes = 0
        self._strike_limit = cfg.serve_backend_strikes
        self._rebuilding = False
        # Monotonic stamp of the last rebuild attempt's completion: a
        # POISON batch (one tenant's content deterministically fatal in
        # the kernel) recovers on the failover rung and keeps coming,
        # and without a cooldown every recurrence would quarantine +
        # rebuild + re-prewarm the whole plane forever.
        self._last_rebuild = -float("inf")
        # Serializes resume_session end to end (journal load ->
        # open -> restore): a replayed/raced resume of the same id
        # must observe the winner's FULLY restored session, never a
        # freshly opened one whose cursor is still 0.
        self._resume_lock = threading.Lock()
        # Liveness beat of the scheduler loop (monotonic): stats() and
        # the wedge watchdog read its age — a large age with pending
        # work means the loop is wedged, not idle.
        self._loop_beat = time.monotonic()
        # Plane-wide request-latency rollup of CLOSED sessions
        # (obs/latency.py): each session's histograms fold in exactly
        # once at close (`_record_closed_locked`), so `metrics()`'s
        # plane view = this accumulator merged with the live sessions
        # — an EXACT merge, bit-identical to recording every sample
        # into one histogram (the fleet-aggregation contract).
        self._lat_closed = SegmentLatencies()
        # Distributed tracing (obs/tracing.py, docs/OBSERVABILITY.md
        # "Distributed tracing"): one bounded span shard per serving
        # process when `trace_shard_dir` is set — traced requests emit
        # their lifecycle-segment and rpc.server spans here, the
        # `trace` verb serves its in-memory ring, and the collector
        # stitches the shard with the router's and the client's.
        self.trace_shard = None
        if cfg.trace_shard_dir:
            from kcmc_tpu.obs.tracing import SpanShard

            self.trace_shard = SpanShard(
                os.path.join(
                    cfg.trace_shard_dir,
                    f"spans-{os.getpid()}-{uuid.uuid4().hex[:8]}.jsonl",
                ),
                cap=cfg.trace_shard_cap,
            )
        # Exemplars: bounded last-wins (segment, rung, bucket) ->
        # trace id, exported as the `exemplars` metrics section so the
        # p99 bucket names real traces. Parallel to the histograms —
        # their bit-identity merge contract stays untouched.
        self._exemplars = None
        if cfg.latency_telemetry:
            from kcmc_tpu.obs.tracing import ExemplarStore

            self._exemplars = ExemplarStore()
        # SLO burn-rate engine (obs/slo.py): armed by the declarative
        # `slo_objectives` config spec; ticked by the scheduler loop
        # and surfaced via metrics()/snapshot().
        self._slo = None
        self._slo_tick_last = 0.0
        if cfg.slo_objectives:
            from kcmc_tpu.obs.slo import SLOEngine

            self._slo = SLOEngine(cfg.slo_objectives)
        self._stats = {
            "accepted_frames": 0,
            "rejected_submits": 0,
            "rejected_frames": 0,
            "degrade_events": 0,
            "degraded_batches": 0,
            "batches": 0,
            "occupied_frames": 0,  # valid frames across dispatched batches
            "frames_done": 0,
            # serve fault tolerance (PR 14)
            "deduped_frames": 0,  # idempotent-submit replays dropped
            "backend_rebuilds": 0,  # quarantine->rebuild cycles started
            "sessions_resumed": 0,  # journal resumes served
            "sessions_reaped": 0,  # stale sessions journaled + closed
            # latency QoS (PR 20, docs/SERVING.md "Latency QoS")
            "preemptions": 0,  # latency dispatches that jumped the WRR
            "starvation_grants": 0,  # starved batch sessions given a slot
            "rejected_deadline_submits": 0,  # predictive-admission 429s
            "deadline_hits": 0,  # folded from sessions at close
            "deadline_misses": 0,
            # Every dispatch records exactly one `why` (the literal
            # keys ARE the registry-checked counter vocabulary —
            # obs/registry.py DISPATCH_WHY_COUNTERS; mirrored as
            # SpanShard counters when tracing is armed).
            "dispatch_why": {
                "dispatch.why.full_window": 0,
                "dispatch.why.deadline_forced": 0,
                "dispatch.why.preempted": 0,
                "dispatch.why.fill_floor": 0,
                "dispatch.why.flush": 0,
            },
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "StreamScheduler":
        with self._lock:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="kcmc-serve-scheduler", daemon=True
        )
        self._thread.start()
        if self.watermark < 1.0:
            # Prewarm the QoS escape hatch's CONSTRUCTION (backend +
            # mesh setup). Its compiled batch programs are shape-
            # dependent, so those warm later, per shape, as sessions'
            # references are prepared (_warm_degraded_shape) — well
            # before overload can engage on that shape.
            self._spawn_warmup(
                self._warm_degraded, "kcmc-serve-degraded-warm"
            )
        if self._heartbeat_s > 0:
            from kcmc_tpu.obs.heartbeat import Heartbeat, aggregate_sampler

            self._heartbeat = Heartbeat(
                self._heartbeat_s, aggregate_sampler(self.snapshot)
            )
            self._heartbeat.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop the scheduler thread. In-flight batches drain; sessions
        still open are finalized (complete streams) or failed (streams
        with frames left) — a clean shutdown closes sessions first."""
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        with self._wake:
            self._running = False
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        with self._lock:
            warm, self._warm_threads = self._warm_threads, []
        for t in warm:
            t.join(timeout=timeout)
        if self.trace_shard is not None:
            self.trace_shard.close()

    def _spawn_warmup(self, target, name: str, args: tuple = ()) -> None:
        """Degraded-budget warm-up threads reach jax compile (backend
        construction, batch-program builds), so they are NON-daemon and
        joined on stop — a daemon thread killed mid-XLA-compile aborts
        interpreter teardown (the PR-7 rule, enforced by `kcmc check`'s
        daemon-xla pass). Bounded: one construction warm-up plus one
        per distinct frame shape."""
        t = threading.Thread(
            target=target, name=name, args=args, daemon=False
        )
        with self._lock:
            self._warm_threads = [
                w for w in self._warm_threads if w.is_alive()
            ]
            self._warm_threads.append(t)
            # start INSIDE the lock: stop() swaps the list under the
            # same lock, so every thread it joins has been started
            # (join on a never-started thread raises), and a racing
            # spawn's is_alive() prune cannot drop a tracked thread
            # between append and start
            t.start()

    def __enter__(self) -> "StreamScheduler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- session management (client threads) -------------------------------

    def open_session(
        self,
        tenant: str = "default",
        weight: int = 1,
        reference=None,
        template_update_every: int | None = None,
        emit_frames: bool = False,
        output: str | None = None,
        expected_frames: int | None = None,
        output_dtype="float32",
        compression: str = "none",
        session_id: str | None = None,
        telemetry: bool = True,
        qos_class: str = "batch",
        deadline_ms: float | None = None,
    ):
        """Open a stream: builds a per-session corrector view sharing
        the warm backend, registers it with the fairness schedule, and
        returns the `Session`. `qos_class` ("latency" | "batch") picks
        the scheduling class; `deadline_ms` is the session-default
        per-frame deadline (docs/SERVING.md "Latency QoS")."""
        from kcmc_tpu.serve.session import Session

        view = self.mc.stream_view(
            reference=reference,
            template_update_every=template_update_every,
        )
        ref_arr = None
        if isinstance(reference, np.ndarray):
            # Validate BEFORE any session state exists: a bad reference
            # must fail without arming (and leaking) telemetry
            # artifact-path claims.
            ref_arr = np.asarray(reference, np.float32)
            if ref_arr.ndim != 2:
                raise ValueError(
                    f"reference frame must be 2-D, got shape "
                    f"{ref_arr.shape}"
                )
        with self._wake:
            if not self._running:
                raise RuntimeError("scheduler is not running")
            self._seq += 1
            sid = session_id if session_id else f"s{self._seq:04d}"
            if sid in self._sessions or sid in self._reserved:
                raise ValueError(f"session id {sid!r} already open")
            self._reserved.add(sid)
        # Construct OUTSIDE the plane lock: telemetry arming builds a
        # manifest (version probes, config digest) — other tenants'
        # submits and the scheduler loop must not stall behind it. The
        # reservation above keeps the sid unique meanwhile.
        sess = None
        try:
            sess = Session(
                view, self._lock, sid, tenant=tenant, weight=weight,
                emit_frames=emit_frames, output=output,
                expected_frames=expected_frames, output_dtype=output_dtype,
                compression=compression, telemetry=telemetry,
                trace_shard=self.trace_shard, exemplars=self._exemplars,
                qos_class=qos_class, deadline_ms=deadline_ms,
            )
            if self.journal_dir:
                from kcmc_tpu.serve.journal import SessionJournal

                # The session's own fault plan / report: journal faults
                # and durability counters are per-stream like every
                # other robustness surface.
                sess.attach_journal(
                    SessionJournal(
                        self.journal_dir, sid, every=self.journal_every,
                        fault_plan=sess.mc._fault_plan,
                        report=sess.mc._robustness,
                    )
                )
            with self._wake:
                # Reference staging happens under the plane lock with
                # the registration: the scheduler thread reads the
                # staged source under the same lock, and ref_arr is
                # already float32 so this is pointer work, not a copy.
                if ref_arr is not None:
                    sess.set_reference(ref_arr)
                self._sessions[sid] = sess
                self._rebuild_order()
                self._wake.notify_all()
            return sess
        except BaseException as e:
            # A constructed-but-never-registered session still owns
            # telemetry (artifact-path claims): release it, or the
            # registry treats those paths as live forever.
            if sess is not None and sess.telemetry is not None:
                try:
                    sess.telemetry.close(e)
                except Exception:
                    pass
            raise
        finally:
            with self._wake:
                self._reserved.discard(sid)

    def _rebuild_order(self) -> None:
        # Weighted round-robin schedule: a session with weight w appears
        # w times per cycle, interleaved (not clustered) so a heavy
        # tenant's extra slots spread across the cycle.
        sids = sorted(self._sessions)
        if not sids:
            self._order = []
            self._rr = 0
            return
        maxw = max(self._sessions[s].weight for s in sids)
        self._order = [
            s
            for round_i in range(maxw)
            for s in sids
            if round_i < self._sessions[s].weight
        ]
        self._rr %= len(self._order)

    def resume_session(self, session_id: str) -> tuple:
        """Resume a journaled stream on this (possibly restarted)
        server: returns ``(session, cursor, resumed)``.

        Idempotent by construction — the client reconnect path calls
        it blindly. A session still live on this server returns as-is
        (``resumed=False``) with its current submit cursor, so a
        client that merely lost its socket re-syncs without touching
        session state. Otherwise the journal is loaded (quarantined
        with a warning when corrupt), validated against the serving
        config's resume signature, and a fresh session is rehydrated
        from the snapshot; the client re-submits frames from `cursor`.
        """
        from kcmc_tpu.serve import journal as journal_mod

        with self._resume_lock:
            return self._resume_session_locked(session_id, journal_mod)

    def _resume_session_locked(self, session_id: str, journal_mod) -> tuple:
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is not None:
                return sess, sess.submitted, False
        if not self.journal_dir:
            raise KeyError(
                f"no open session {session_id!r} (and journaling is "
                "off — set serve_journal_dir / --journal-dir to make "
                "streams resumable)"
            )
        path = journal_mod.journal_path(self.journal_dir, session_id)
        # Collect any part quarantined during the load so the counter
        # reaches the resumed session's RobustnessReport below — the
        # documented contract; corruption must not be advisory-only.
        from kcmc_tpu.utils.metrics import RobustnessReport

        load_report = RobustnessReport()
        loaded = journal_mod.load_session_journal(path, report=load_report)
        if loaded is None:
            raise KeyError(
                f"no journal for session {session_id!r} under "
                f"{self.journal_dir} (never journaled, already closed, "
                "or quarantined as corrupt)"
            )
        meta, segments, arrays = loaded
        if meta.get("backend") and meta["backend"] != self.mc.backend_name:
            # same gate the one-shot checkpoint signature carries: a
            # stream's numerics (warm seeds, template history) must not
            # mix two backends across the resume seam
            raise ValueError(
                f"session {session_id!r} was journaled on backend "
                f"{meta['backend']!r}; this server runs "
                f"{self.mc.backend_name!r} — restart with the original "
                "backend to resume it"
            )
        want = journal_mod.serve_config_signature(self.mc.config)
        if meta.get("config") != want:
            raise ValueError(
                f"session {session_id!r} was journaled under an "
                "incompatible serving config (resume-signature "
                "mismatch); restart the server with the original "
                "config to resume it"
            )
        if meta.get("output"):
            raise ValueError(
                f"session {session_id!r} wrote a server-side output "
                "file; those streams are not journal-resumable (the "
                "writer state is not journaled) — use correct_file "
                "checkpoints for durable file runs"
            )
        tue = meta.get("template_update_every")
        try:
            sess = self.open_session(
                tenant=meta.get("tenant", "default"),
                weight=int(meta.get("weight", 1)),
                # 0 is meaningful (an explicit no-rolling override), so
                # only an absent key falls back to the server default
                template_update_every=int(tue) if tue is not None else None,
                emit_frames=bool(meta.get("emit_frames", False)),
                expected_frames=meta.get("expected_frames"),
                session_id=session_id,
            )
        except ValueError:
            # two clients racing to resume the same stream: the loser's
            # open collides with the winner's registration — hand back
            # the now-live session (same contract as the live-check)
            with self._lock:
                live = self._sessions.get(session_id)
            if live is not None:
                return live, live.submitted, False
            raise
        if load_report.quarantined_parts:
            with self._lock:
                sess.mc._robustness.quarantined_parts.extend(
                    load_report.quarantined_parts
                )
        # restore takes the plane lock itself, releasing it around the
        # boundary template blend (device-frame-sized host compute must
        # not stall other tenants); _resume_lock + the restore guard
        # keep the gap safe
        t_restore = time.perf_counter()
        try:
            sess.restore_from_journal(
                meta, segments, arrays, journal=sess.journal
            )
        except BaseException as e:
            # The open above registered the session: left alive but
            # un-restored, the live-check would hand it back on the
            # next resume with cursor 0 and the client would silently
            # re-submit the whole stream as fresh frames. Fail it so
            # the scheduler finalizes and removes it (the error keeps
            # the on-disk journal for the retry), then surface the
            # restore error.
            sess.fail(e)
            with self._wake:
                self._wake.notify_all()
            raise
        restore_dur = time.perf_counter() - t_restore
        with self._wake:
            self._stats["sessions_resumed"] += 1
            self._wake.notify_all()
        # resume cost is a DURATION span (trace) + latency segment
        # (`metrics` verb) — rehydration is real work (array decode,
        # boundary re-roll), not an instant
        if sess.telemetry is not None and sess.telemetry.tracer is not None:
            sess.telemetry.tracer.complete(
                "journal.resume", t_restore, restore_dur, cat="journal",
                args={"done": int(meta["done"])},
            )
        if sess.lat is not None:
            sess.lat.observe("journal.resume", restore_dur)
        advise(
            f"kcmc serve: session {session_id} resumed from its "
            f"journal at frame {int(meta['done'])}",
            stacklevel=2,
        )
        return sess, int(meta["done"]), True

    def submit(
        self, session_id: str, frames, first: int | None = None,
        trace: dict | None = None, deadline_ms: float | None = None,
        replay: bool = False,
    ):
        """Admission-controlled submit. Returns a decision dict
        ``{"accepted", "queued", "degraded", "next"}``; raises
        OverloadedError when the queue bound is exceeded (the last
        resort — QoS degradation engages first, at the watermark).

        `first` is the idempotency key: the session-global index of
        this call's first frame. A retried submit (client reconnect
        after a transport timeout) replays frames the server already
        admitted — the overlap is deduplicated here, so retries never
        double-process a frame; a `first` PAST the session cursor is a
        gap (lost frames) and is rejected so a stream can never
        silently skip. Without `first` (legacy callers) frames append
        unconditionally.

        `trace` is the request's distributed-trace context (the
        server's span for this call, obs/tracing.py): the admitted
        frames inherit it, so their queue/dispatch/device/drain spans
        and bucket exemplars name the originating trace id.

        `deadline_ms` stamps this call's frames with a per-frame
        deadline (milliseconds from now; overrides the session
        default). With `serve_latency_admission` on, a submit whose
        PREDICTED wait — the dispatch-horizon model plus the plane's
        backlog in device-p50 units — already exceeds its deadline is
        rejected 429-style up front with `predicted_wait_s`, instead
        of being admitted into a miss (docs/SERVING.md "Latency QoS")."""
        t_call = time.perf_counter()  # request.total's anchor
        frames = np.asarray(frames)
        if frames.ndim == 2:
            frames = frames[None]
        n = len(frames)
        with self._wake:
            sess = self._get(session_id)
            deduped = 0
            if first is not None:
                expected = sess.submitted
                if int(first) > expected:
                    raise ValueError(
                        f"session {session_id}: submit gap — frames "
                        f"{expected}..{int(first)} were never received "
                        "(resync from resume_session's cursor)"
                    )
                deduped = min(expected - int(first), n)
                if deduped:
                    frames = frames[deduped:]
                    n -= deduped
                if n == 0:
                    # pure replay: touch liveness, change nothing
                    sess.deduped_frames += deduped
                    self._stats["deduped_frames"] += deduped
                    sess.last_activity = time.monotonic()
                    return {
                        "accepted": 0,
                        "queued": sess.backlog(),
                        "degraded": sess.degraded,
                        "deduped": deduped,
                        "next": sess.submitted,
                    }
            queued = sess.backlog()
            if queued + n > self.queue_depth:
                self._stats["rejected_submits"] += 1
                self._stats["rejected_frames"] += n
                raise OverloadedError(
                    f"session {session_id}: queue {queued}+{n} frames "
                    f"exceeds serve_queue_depth={self.queue_depth} "
                    "(submit less per call, or wait for results)",
                    queued=queued, limit=self.queue_depth,
                )
            eff_dl = deadline_ms if deadline_ms is not None else (
                sess.deadline_ms
            )
            # `replay` marks a migration re-delivery: admission already
            # ran once when the client first submitted these frames, and
            # rejecting them now would strand the stream mid-migrate —
            # prediction never re-judges spent budget.
            if (
                self.admission_predict and eff_dl is not None and n
                and not replay
            ):
                predicted = self._predicted_wait_locked(sess, queued + n)
                if (
                    predicted is not None
                    and predicted > float(eff_dl) / 1000.0
                ):
                    # Reject-with-hint: admitting would only manufacture
                    # a deadline miss — tell the client how long the
                    # plane predicts it would actually take.
                    self._stats["rejected_submits"] += 1
                    self._stats["rejected_frames"] += n
                    self._stats["rejected_deadline_submits"] += 1
                    raise OverloadedError(
                        f"session {session_id}: predicted wait "
                        f"{predicted:.3f}s exceeds the "
                        f"{float(eff_dl) / 1000.0:.3f}s deadline "
                        "(predictive admission — retry later, relax "
                        "deadline_ms, or disable "
                        "serve_latency_admission)",
                        queued=queued, limit=self.queue_depth,
                        predicted_wait_s=round(predicted, 4),
                    )
            engage = (
                not sess.degraded
                and self.watermark < 1.0
                and queued + n > self.watermark * self.queue_depth
            )
            # Validate/admit BEFORE flipping QoS state: a mis-shaped
            # submit raises here and must not leave the session
            # permanently degraded by load it never added.
            sess.add_frames(frames, deadline_ms=deadline_ms)
            self._stats["accepted_frames"] += n
            if sess.lat is not None and n:
                # Per-request lifecycle tracing (obs/latency.py): each
                # admitted frame's clock starts at the submit call;
                # admission covers the lock wait + decision, and the
                # (t_call, t_admitted) stamps seed queue_wait/total.
                t_adm = time.perf_counter()
                sess._t_submit.extend([(t_call, t_adm)] * n)
                rung = sess._rung()
                sess.lat.observe(
                    "request.admission", t_adm - t_call, n=n, rung=rung,
                )
                if trace is not None:
                    sess.note_trace(trace, n)
                    sess.trace_obs(
                        "request.admission", t_adm - t_call, n, rung,
                        trace,
                    )
            # Dedup counts only once the trimmed remainder is ADMITTED:
            # a rejected/raising submit will be retried verbatim, and
            # counting its overlap on every attempt would inflate the
            # replay counters with phantom frames.
            if deduped:
                sess.deduped_frames += deduped
                self._stats["deduped_frames"] += deduped
            if engage:
                sess.degraded = True
                self._stats["degrade_events"] += 1
                advise(
                    f"kcmc serve: session {session_id} backlog "
                    f"{queued + n}/{self.queue_depth} frames passed the "
                    f"{self.watermark:.0%} watermark; dispatching its "
                    "batches at degraded consensus budgets until it drains",
                    stacklevel=2,
                )
            self._wake.notify_all()
            return {
                "accepted": n,
                "queued": sess.backlog(),
                "degraded": sess.degraded,
                "deduped": deduped,
                "next": sess.submitted,
            }

    def close_session(self, session_id: str, timeout: float | None = None):
        """Mark a stream complete; block until its remaining frames
        drain and it finalizes. Returns the final CorrectionResult.
        Retryable: a close that timed out client-side can be reissued —
        a recently reaped session still returns its final result
        (transforms/diagnostics; retained results drop emit pixels)."""
        with self._wake:
            sess = self._sessions.get(session_id)
            if sess is not None:
                sess.begin_close()
                self._wake.notify_all()
        if sess is None:
            # Already finalized and reaped (e.g. a retry after a
            # timed-out close): result() returns immediately.
            sess = self.lookup_session(session_id)
        out = sess.result(timeout=timeout)
        # A client-initiated close that successfully consumed the
        # result IS the clean close, even when the stream was already
        # finalized by a staleness reap or shutdown drain (which keep
        # the journal) — discard it, or resume_session could resurrect
        # a stream its client believes complete into a duplicate.
        # Under _resume_lock, and only while no LIVE session holds the
        # sid: a session resumed between the reap and this close retry
        # shares the journal path, and discarding it out from under
        # that live stream would silently destroy its durability.
        with self._resume_lock:
            with self._lock:
                live = self._sessions.get(session_id)
                j = sess.journal
                if live is None or live is sess:
                    sess.journal = None
            if j is not None and (live is None or live is sess):
                j.discard()
        return out

    def _get(self, session_id: str):
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        return sess

    def session_closed(self, session_id: str) -> bool:
        """Whether `session_id` was a real session that has since
        closed (vs never existing) — lets a `results` poll racing a
        concurrent close report "exhausted" instead of erroring."""
        with self._lock:
            return session_id in self._closed_ids

    def lookup_session(self, session_id: str):
        """A live session, or a recently closed one retained for late
        result()/fetch() reads (e.g. a close_session retry after a
        client-side timeout); KeyError otherwise."""
        with self._lock:
            sess = self._sessions.get(session_id)
            if sess is None:
                sess = self._recent.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        return sess

    def _record_closed_locked(self, sess) -> None:
        if len(self._closed_order) == self._closed_order.maxlen:
            self._closed_ids.discard(self._closed_order[0])
        self._closed_order.append(sess.sid)
        self._closed_ids.add(sess.sid)
        # Fold the stream's deadline scorecard into the plane counters
        # (same exactly-once close seam as the latency fold below) and
        # drop its QoS ledger entries.
        self._stats["deadline_hits"] += int(sess.deadline_hits)
        self._stats["deadline_misses"] += int(sess.deadline_misses)
        self._starve_credit.pop(sess.sid, None)
        self._floor_deferred.discard(sess.sid)
        # Fold the stream's latency histograms into the plane rollup
        # exactly once — finalize has already closed its delivery
        # segments, so nothing records into `sess.lat` after this and
        # the plane view stays an exact merge.
        if sess.lat is not None and not sess._lat_folded:
            sess._lat_folded = True
            self._lat_closed.merge_from(sess.lat)
        # Retention must not pin pixels: an emit session's final result
        # holds the whole corrected stack, so once a client has RECEIVED
        # it (delivered flag — an undelivered result stays whole for the
        # still-blocked/retrying waiter), a later retried close gets
        # transforms/diagnostics only. Undelivered `results` spans in
        # _outs keep their pixels — a racing poll still gets them, and
        # fetch releases each span as it delivers.
        res = sess._result
        if sess._result_delivered and res is not None and (
            res.corrected is not None and len(res.corrected)
        ):
            sess._result = dataclasses.replace(
                res, corrected=np.empty((0,), np.float32)
            )
        self._recent[sess.sid] = sess
        while len(self._recent) > self._recent_depth:
            self._recent.pop(next(iter(self._recent)))

    # -- stats / heartbeat --------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
            st = dict(self._stats)
            # deep-copy the nested why dict (the shallow dict() above
            # shares it with the scheduler thread's increments) and
            # fold LIVE sessions' deadline scorecards over the closed
            # accumulator, all under the plane lock
            why = dict(self._stats["dispatch_why"])
            d_hits = st["deadline_hits"] + sum(
                s.deadline_hits for s in sessions
            )
            d_misses = st["deadline_misses"] + sum(
                s.deadline_misses for s in sessions
            )
            qos_classes = {s.sid: s.qos_class for s in sessions}
            inflight = len(self._window)
            # backlog() walks session queues the scheduler mutates —
            # snapshot it under the plane lock, not after it
            queues = {s.sid: s.backlog() for s in sessions}
            degraded_active = sorted(
                s.sid for s in sessions if s.degraded
            )
            db = self._degraded_backend
            strikes = self._strikes
            rebuilding = self._rebuilding
            beat_age = time.monotonic() - self._loop_beat
            # per-session robustness: the plane-locked snapshots the
            # drain path maintains (never the live report objects)
            robustness = {
                s.sid: dict(s._rb) for s in sessions if s._rb
            }
            journal = {
                s.sid: {
                    "saves": s.journal.saves,
                    "failures": s.journal.failures,
                    "last_saved": s.journal.last_saved,
                }
                for s in sessions
                if s.journal is not None
            }
        batches = max(st["batches"], 1)
        out = {
            "sessions_open": len(sessions),
            "queues": queues,
            "inflight_batches": inflight,
            "batch_size": self.B,
            "batch_occupancy": round(
                st["occupied_frames"] / (batches * self.B), 4
            ),
            "frames_done": st["frames_done"],
            "admission": {
                "accepted_frames": st["accepted_frames"],
                "rejected_submits": st["rejected_submits"],
                "rejected_frames": st["rejected_frames"],
                "degrade_events": st["degrade_events"],
                "degraded_batches": st["degraded_batches"],
                "degraded_active": degraded_active,
            },
            # serve-plane fault tolerance (docs/ROBUSTNESS.md):
            # supervisor state, the loop-wedge gauge, and per-session
            # recovery/durability counters for operators and the CI
            # chaos canaries.
            "supervisor": {
                "backend_strikes": strikes,
                "backend_rebuilding": rebuilding,
                "backend_rebuilds": st["backend_rebuilds"],
                "loop_beat_age_s": round(max(beat_age, 0.0), 3),
            },
            "resilience": {
                "deduped_frames": st["deduped_frames"],
                "sessions_resumed": st["sessions_resumed"],
                "sessions_reaped": st["sessions_reaped"],
                "journal_dir": self.journal_dir,
            },
            # deadline-QoS explainability (docs/SERVING.md "Latency
            # QoS"): the dispatch-decision vocabulary, the fairness
            # counters bounding batch-class starvation, and the plane's
            # deadline scorecard (closed sessions + live)
            "deadline_qos": {
                "dispatch_why": why,
                "preemptions": st["preemptions"],
                "starvation_grants": st["starvation_grants"],
                "rejected_deadline_submits": st[
                    "rejected_deadline_submits"
                ],
                "deadline_hits": d_hits,
                "deadline_misses": d_misses,
                "qos_classes": qos_classes,
            },
        }
        if robustness:
            out["robustness"] = robustness
        if journal:
            out["journal"] = journal
        # Execution-plan / compile-cache accounting (kcmc_tpu/plans):
        # operators verify a resident server actually starts (and
        # stays) warm — zero stamp_misses after the first boot means
        # every program deserialized from the persistent cache. The
        # degraded QoS rung's backend keeps its own counters.
        stats_fn = getattr(self.mc.backend, "plan_cache_stats", None)
        if stats_fn is not None:
            try:
                ps = stats_fn()
                if ps.get("enabled") or ps.get("programs_compiled"):
                    out["plan_cache"] = ps
            except Exception:
                pass
        dstats_fn = getattr(db, "plan_cache_stats", None) if db else None
        if dstats_fn is not None:
            try:
                dps = dstats_fn()
                if dps.get("programs_compiled"):
                    out["plan_cache_degraded"] = dps
            except Exception:
                pass
        return out

    def metrics(self) -> dict:
        """The scrapeable request-latency/health payload behind the
        `metrics` serve verb (docs/OBSERVABILITY.md "Request
        latency"): plane-wide per-(segment, rung) latency summaries +
        full mergeable histogram state, per-live-session summaries,
        and the serve counters/gauges a router or Prometheus scraper
        health-checks replicas on. The plane view is an EXACT merge of
        the closed-session rollup and every live session — merging the
        per-session histograms yourself reproduces it bit for bit
        (the fleet-aggregation contract, pinned in tests)."""
        per_session: dict = {}
        plane = SegmentLatencies()
        with self._lock:
            sessions = list(self._sessions.values())
            st = dict(self._stats)
            inflight = len(self._window)
            queues = {s.sid: s.backlog() for s in sessions}
            degraded = {s.sid: s.degraded for s in sessions}
            strikes = self._strikes
            rebuilding = self._rebuilding
            beat_age = time.monotonic() - self._loop_beat
            why = dict(self._stats["dispatch_why"])
            d_hits = st["deadline_hits"] + sum(
                s.deadline_hits for s in sessions
            )
            d_misses = st["deadline_misses"] + sum(
                s.deadline_misses for s in sessions
            )
            # Merge INSIDE the plane lock: a session folding into
            # _lat_closed (close happens under this lock) between the
            # live-session snapshot and these merges would otherwise be
            # counted twice, breaking the bit-exact merge contract a
            # scrape relies on. The lock is reentrant, so s.snapshot()
            # is fine here; merges are ~100 integer adds per source.
            plane.merge_from(self._lat_closed)
            for s in sessions:
                snap = s.snapshot()
                entry = {
                    "tenant": s.tenant,
                    "frames": snap.get("frames", 0),
                    "fps": round(float(snap.get("fps", 0.0)), 2),
                    "queued": queues.get(s.sid, 0),
                    "degraded": bool(degraded.get(s.sid)),
                    "qos_class": snap.get("qos_class", "batch"),
                }
                for k in (
                    "deadline_hits", "deadline_misses",
                    "preempted_dispatches",
                ):
                    if k in snap:
                        entry[k] = snap[k]
                if s.lat is not None:
                    plane.merge_from(s.lat)
                    rep = s.lat.report()
                    entry["segments"] = rep["segments"]
                    entry["totals"] = rep["totals"]
                    entry["histograms"] = s.lat.hist_dicts()
                per_session[s.sid] = entry
        plane_rep = plane.report()
        batches = max(st["batches"], 1)
        payload = {
            "schema": "kcmc_metrics/1",
            "latency_telemetry": bool(self.mc.config.latency_telemetry),
            "plane": {
                "segments": plane_rep["segments"],
                "totals": plane_rep["totals"],
                "histograms": plane.hist_dicts(),
            },
            "sessions": per_session,
            "counters": {
                "frames_done": st["frames_done"],
                "accepted_frames": st["accepted_frames"],
                "rejected_submits": st["rejected_submits"],
                "rejected_frames": st["rejected_frames"],
                "deduped_frames": st["deduped_frames"],
                "degrade_events": st["degrade_events"],
                "degraded_batches": st["degraded_batches"],
                "batches": st["batches"],
                "backend_rebuilds": st["backend_rebuilds"],
                "sessions_resumed": st["sessions_resumed"],
                "sessions_reaped": st["sessions_reaped"],
                # deadline QoS — flat ints so merge_fleet_metrics'
                # counter summation folds them across replicas
                "preemptions": st["preemptions"],
                "starvation_grants": st["starvation_grants"],
                "rejected_deadline_submits": st[
                    "rejected_deadline_submits"
                ],
                "deadline_hits": d_hits,
                "deadline_misses": d_misses,
                **{
                    k.replace("dispatch.why.", "dispatch_why_"): v
                    for k, v in why.items()
                },
            },
            "gauges": {
                "sessions_open": len(sessions),
                "inflight_batches": inflight,
                "batch_size": self.B,
                "batch_occupancy": round(
                    st["occupied_frames"] / (batches * self.B), 4
                ),
                "queued_frames": sum(queues.values()),
                "backend_strikes": strikes,
                "backend_rebuilding": int(rebuilding),
                "loop_beat_age_s": round(max(beat_age, 0.0), 3),
                "queues": queues,
            },
        }
        if self._exemplars is not None:
            ex = self._exemplars.export()
            if ex:
                payload["exemplars"] = ex
        if self._slo is not None:
            self._slo.tick(
                payload["plane"]["histograms"], payload["counters"]
            )
            payload["slo"] = self._slo.gauges()
        return payload

    def trace_dump(self) -> list:
        """Recent finished spans from the process span ring (the
        `trace` serve verb); [] when tracing is unarmed."""
        if self.trace_shard is None:
            return []
        return self.trace_shard.tail()

    def _slo_tick(self) -> None:
        """Advance the burn-rate windows from the scheduler loop (at
        most 1/s) so the SLO state moves even when nobody scrapes."""
        if self._slo is None:
            return
        now = time.monotonic()
        if now - self._slo_tick_last < 1.0:
            return
        self._slo_tick_last = now
        plane = SegmentLatencies()
        with self._lock:
            plane.merge_from(self._lat_closed)
            for s in self._sessions.values():
                if s.lat is not None:
                    plane.merge_from(s.lat)
            counters = dict(self._stats)
        self._slo.tick(plane.hist_dicts(), counters)

    def _latency_beat(self) -> dict | None:
        """End-to-end p50/p99 for the heartbeat line: the plane's
        `request.total` across closed + live sessions (exact merge;
        ~100 integer adds per source — beat-cheap)."""
        with self._lock:
            # under the plane lock for the same close-fold consistency
            # as metrics() — a folding session must never count twice
            h = self._lat_closed.segment_total("request.total")
            for s in self._sessions.values():
                if s.lat is not None:
                    h.merge(s.lat.segment_total("request.total"))
        if not h.count:
            return None
        return {
            "p50_ms": round((h.quantile(50) or 0.0) * 1e3, 1),
            "p99_ms": round((h.quantile(99) or 0.0) * 1e3, 1),
        }

    def snapshot(self) -> dict:
        """Aggregate-heartbeat snapshot (obs.heartbeat.aggregate_sampler)."""
        with self._lock:
            sessions = list(self._sessions.values())
            st = dict(self._stats)
            inflight = len(self._window)
            queues = {s.sid: s.backlog() for s in sessions}
            snaps = [s.snapshot() for s in sessions]
            rebuilding = self._rebuilding
            beat_age = time.monotonic() - self._loop_beat
        batches = max(st["batches"], 1)
        # Aggregate the per-session robustness snapshots so the
        # liveness line narrates recovery (retries/failovers/rescues)
        # next to progress — "slow but surviving" vs "wedged".
        rb_total: dict[str, int] = {}
        for s in snaps:
            for k, v in (s.get("robustness") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    rb_total[k] = rb_total.get(k, 0) + int(v)
        rb_total.pop("resumed_from_frame", None)
        for k in ("deduped_frames", "sessions_resumed", "sessions_reaped"):
            if st.get(k):
                rb_total[k] = st[k]
        extra = (
            f"occupancy={st['occupied_frames'] / (batches * self.B):.2f}"
            f" inflight={inflight}"
        )
        if rebuilding:
            extra += " BACKEND-REBUILDING"
        out = {
            "sessions": snaps,
            "queues": queues,
            "admission": {
                "rejected": st["rejected_frames"],
                "degraded": st["degraded_batches"],
            },
            "extra": extra,
            "loop_beat_age_s": round(max(beat_age, 0.0), 3),
        }
        lat = self._latency_beat()
        if lat is not None:
            out["latency"] = lat
        if self._slo is not None:
            slo_line = self._slo.heartbeat()
            if slo_line:
                out["slo"] = slo_line
        if any(rb_total.values()):
            out["robustness"] = rb_total
        if self.session_timeout_s > 0:
            stale = {
                s["name"]: s["idle_s"]
                for s in snaps
                if s.get("idle_s", 0) > 0.5 * self.session_timeout_s
            }
            if stale:
                out["stale"] = stale
        return out

    # -- QoS ----------------------------------------------------------------

    def _horizon_model(self) -> dict:
        """The dispatch-horizon model (plane lock taken; reentrant from
        the pick path): per-segment p50s from the live PR-15 latency
        histograms — closed-session rollup merged with every live
        session, the same exact-merge plane view `metrics()` serves.
        Cached for `serve_latency_horizon_refresh_s`, so the scheduling
        hot path reads a dict, not quantile math. Zeros until the
        plane has history — callers must treat an all-zero model as
        "no prediction", never as "instant"."""
        now = time.monotonic()
        with self._lock:
            if (
                self._horizon_cache is not None
                and now - self._horizon_last < self.horizon_refresh_s
            ):
                return self._horizon_cache
            self._horizon_last = now
            plane = SegmentLatencies()
            plane.merge_from(self._lat_closed)
            for s in self._sessions.values():
                if s.lat is not None:
                    plane.merge_from(s.lat)
            model = {}
            for seg in (
                "request.batch_form", "request.dispatch", "request.device"
            ):
                h = plane.segment_total(seg)
                model[seg] = (
                    float(h.quantile(50) or 0.0) if h.count else 0.0
                )
            self._horizon_cache = model
            return model

    def _horizon(self, b: int) -> float:
        """Predicted seconds from "dispatch a b-frame window now" to
        its results: batch-form p50 + dispatch p50 + device p50 scaled
        by the rung's share of the full window. 0.0 with no history."""
        m = self._horizon_model()
        return (
            m["request.batch_form"]
            + m["request.dispatch"]
            + m["request.device"] * (b / max(self.B, 1))
        )

    def _predicted_wait_locked(self, sess, queued: int) -> float | None:
        """Predicted seconds until a frame admitted NOW into `sess`
        (bringing its queue to `queued`) has results: the horizon
        model's form+dispatch cost plus the whole plane's backlog —
        in-flight window entries and every session's queued frames,
        in full-window units — at device p50 each. None (never
        reject) until the plane has device history. Plane lock held."""
        m = self._horizon_model()
        dev = m["request.device"]
        if dev <= 0.0:
            return None
        total_queued = int(queued)
        for s in self._sessions.values():
            if s is not sess:
                total_queued += s.backlog()
        backlog_batches = len(self._window) + max(
            1, -(-total_queued // self.B)
        )
        return (
            m["request.batch_form"]
            + m["request.dispatch"]
            + backlog_batches * dev
        )

    def _latency_take_locked(self, sess, peek: bool = False):
        """Decide dispatch-NOW vs defer for a ready latency-class
        session (plane lock held). Returns ``(target_rung, why)`` to
        dispatch, or None to defer — positive deadline slack against
        the dispatch horizon buys time for the window to fill, which
        is what turns the pre-QoS flush-everything behavior into
        deadline-aware batching. `peek` makes it side-effect-free
        (the idle-wait preview must mirror this exactly or the loop
        busy-spins on a deferred session)."""
        n = sess.ready_count()
        if n >= self.B:
            return self.B, "full_window"
        # growth is impossible past a rolling-template boundary gate
        # or once the stream is closing — waiting would be a pure
        # latency tax with zero fill upside
        can_grow = (not sess.closing) and len(sess.pending) == n
        head = sess.head_deadline()
        if head is None:
            # no deadline signal: the pre-QoS behavior (dispatch the
            # partial immediately, padded to the full window)
            return self.B, "flush"
        target = route_batch(n, self._rungs) or self.B
        horizon = self._horizon(target)
        if horizon <= 0.0:
            # cold plane (no device history yet): deferring would wait
            # until the deadline INSTANT and then dispatch with zero
            # margin — flush instead until the model warms
            return self.B, "flush"
        slack = head - time.time()
        if slack > horizon:
            if can_grow:
                return None  # the deadline affords waiting for fill
            return self.B, "flush"
        # deadline pressure: head-of-line deadline minus horizon went
        # non-positive — dispatch the partial at the smallest covering
        # batch-ladder rung, unless the fill floor holds it
        floor_n = min(int(np.ceil(self.fill_floor * self.B)), self.B)
        if n < floor_n and slack > 0 and can_grow:
            # below the fill floor with slack remaining: hold the
            # forced dispatch (bounded — the deadline itself releases
            # it), so trickle traffic cannot collapse throughput into
            # one-frame windows
            if not peek:
                self._floor_deferred.add(sess.sid)
            return None
        why = (
            "fill_floor"
            if sess.sid in self._floor_deferred
            else "deadline_forced"
        )
        if not peek:
            self._floor_deferred.discard(sess.sid)
        return target, why

    def _get_degraded_backend(self):
        """The reduced-budget backend overload dispatches through: the
        consensus-stage knobs shrink (hypothesis budgets, refine/polish
        passes) while every reference-preparation knob stays identical,
        so a session's prepared reference is valid on both backends.
        Built once (prewarmed from `start`; the build lock keeps the
        warm thread and the scheduler thread from racing)."""
        with self._degraded_build:
            if self._degraded_backend is None:
                from kcmc_tpu.backends import get_backend

                cfg = self.mc.config
                dcfg = cfg.replace(
                    n_hypotheses=max(16, cfg.n_hypotheses // 4),
                    refine_iters=min(cfg.refine_iters, 1),
                    patch_hypotheses=max(8, cfg.patch_hypotheses // 4),
                    field_passes=1,
                    field_polish=min(int(cfg.field_polish), 1),
                    transform_polish=0,
                )
                backend = get_backend(self.mc.backend_name, dcfg)
                # Tag the reduced-budget rung in its plan runtime: its
                # compile stamps and stats are keyed/labelled
                # "degraded", so a restarted server's prefetches hit
                # the persistent cache for THIS rung's programs too
                # (the config digest already differs; the label makes
                # stats and stamps readable).
                plan = getattr(backend, "_plan", None)
                if plan is not None:
                    plan.rung = "degraded"
                # Publish under the PLANE lock: stats() reads the
                # handle there without ever waiting behind this
                # build (seconds of XLA compile when overload first
                # engages); _degraded_build keeps builders serialized.
                with self._lock:
                    self._degraded_backend = backend
            return self._degraded_backend

    def _warm_degraded(self) -> None:
        try:
            self._get_degraded_backend()
        except Exception as e:
            advise(
                f"kcmc serve: degraded-backend prewarm failed ({e}); "
                "overloaded batches will dispatch at full budgets",
                stacklevel=2,
            )

    def _maybe_warm_degraded_shape(self, sess) -> None:
        """Kick a background compile of the degraded backend's batch
        program for `sess`'s frame shape, once per shape. Called right
        after the session's reference is prepared — the queue cannot
        reach the watermark before at least one reference exists, so
        the warm-up races only the RAMP to overload, not overload
        itself; without it, the first degraded dispatch would pay the
        reduced-budget JIT inline on the scheduler thread at peak
        backlog."""
        if self.watermark >= 1.0 or sess.ref_frame is None:
            return
        shape = tuple(sess.frame_shape)
        with self._lock:
            if shape in self._degraded_warm_started:
                return
            self._degraded_warm_started.add(shape)
        ref, ref_frame = sess.ref, sess.ref_frame
        self._spawn_warmup(
            self._warm_degraded_shape,
            "kcmc-serve-degraded-warm-shape",
            args=(shape, ref, ref_frame),
        )

    def _warm_degraded_shape(self, shape, ref, ref_frame) -> None:
        try:
            backend = self._get_degraded_backend()
            # The session's own reference content: realistic keypoints,
            # and a reference prepared by the FULL backend is valid on
            # the degraded one (reference-prep knobs are identical).
            dummy = np.broadcast_to(
                ref_frame, (self.B,) + shape
            ).astype(np.float32)
            out = backend.process_batch(dummy, ref, np.arange(self.B))
            for v in out.values():
                np.asarray(v)  # block until the compile+run finished
        except Exception as e:
            advise(
                f"kcmc serve: degraded-program warm-up for frame shape "
                f"{shape} failed ({e}); the first overloaded batch of "
                "that shape compiles inline",
                stacklevel=2,
            )

    def _maybe_warm_partial_rungs(self, sess) -> None:
        """Kick a background compile of the PRIMARY backend's batch
        programs for the partial batch-ladder rungs of `sess`'s frame
        shape, once per (shape, rung). Only latency-class streams
        trigger it — they are the only ones whose deadline-forced
        dispatches pad to partial rungs — and, like the degraded warm,
        it runs right after the reference is prepared so the first
        forced partial never pays a JIT inline at peak deadline
        pressure."""
        if sess.qos_class != "latency" or sess.ref_frame is None:
            return
        shape = tuple(sess.frame_shape)
        with self._lock:
            todo = tuple(
                rung
                for rung in self._rungs
                if rung < self.B
                and (shape, rung) not in self._rung_warm_started
            )
            self._rung_warm_started.update((shape, r) for r in todo)
        if not todo:
            return
        ref, ref_frame = sess.ref, sess.ref_frame
        self._spawn_warmup(
            self._warm_partial_rungs,
            "kcmc-serve-rung-warm",
            args=(shape, todo, ref, ref_frame),
        )

    def _warm_partial_rungs(self, shape, rungs, ref, ref_frame) -> None:
        for rung in rungs:
            try:
                backend = self.mc.backend
                dummy = np.broadcast_to(
                    ref_frame, (rung,) + tuple(shape)
                ).astype(np.float32)
                out = backend.process_batch(dummy, ref, np.arange(rung))
                for v in out.values():
                    np.asarray(v)  # block until the compile+run finished
            except Exception as e:
                advise(
                    f"kcmc serve: partial-rung warm-up (batch {rung}, "
                    f"frame shape {shape}) failed ({e}); the first "
                    "deadline-forced dispatch at that rung compiles "
                    "inline",
                    stacklevel=2,
                )

    def _maybe_restore_locked(self, sess) -> None:
        # Hysteresis: quality restores once the backlog drains below
        # half the watermark (not the instant it dips under it).
        if sess.degraded and sess.backlog() <= (
            0.5 * self.watermark * self.queue_depth
        ):
            sess.degraded = False

    # -- the scheduler loop --------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                if not self._running:
                    break
            try:
                self._loop_once()
            except Exception as e:
                # The scheduler thread is the whole serving plane: an
                # unexpected error must degrade to a warning, never
                # wedge every tenant behind a dead loop. (Session-
                # attributable failures are already routed to fail();
                # this is the backstop for scheduler-side bugs.)
                advise(
                    f"kcmc serve: scheduler error "
                    f"({type(e).__name__}: {e}); continuing",
                    stacklevel=2,
                )
                time.sleep(0.05)
        # Shutdown: drain in-flight work, then finalize complete streams
        # and fail incomplete ones (waiters must not hang).
        while self._window:
            self._drain_one()
        with self._lock:
            leftovers = list(self._sessions.values())
            self._sessions.clear()
            for sess in leftovers:
                self._record_closed_locked(sess)
            self._rebuild_order()
        for sess in leftovers:
            if sess.closed:
                continue
            # Graceful drain (SIGTERM / stop): every still-open stream
            # goes to its journal first — drained state is durable, so
            # a restarted server resumes it from this exact frame.
            sess.maybe_journal(force=True)
            sess.keep_journal = True
            if not sess.drained_out():
                sess.fail(
                    RuntimeError(
                        "serve scheduler stopped mid-stream"
                        + (
                            " (journaled — resume_session on a "
                            "restarted server continues from the last "
                            "durable frame)"
                            if sess.journal is not None
                            else ""
                        )
                    )
                )
            sess.begin_close()
            sess.finalize()

    def _loop_once(self) -> None:
        """One scheduler-loop iteration: dispatch a ready batch, else
        drain, else idle-wait for work."""
        with self._lock:
            self._loop_beat = time.monotonic()
        if self.fault_plan is not None:
            # `scheduler` chaos surface: a stall clause wedges this
            # iteration (the stats/heartbeat wedge gauge must notice);
            # a raising clause exercises the loop's error backstop.
            # One op index per iteration, so step=N clauses target the
            # Nth loop pass deterministically (like every surface).
            step = self.fault_plan.op_index("scheduler")
            stall = self.fault_plan.take_stall("scheduler", step)
            if stall > 0:
                advise(
                    f"kcmc serve: injected scheduler stall of "
                    f"{stall:.2f}s",
                    stacklevel=2,
                )
                time.sleep(stall)
            self.fault_plan.maybe_fail("scheduler", step)
        self._reap_stale()
        self._slo_tick()
        self._prepare_references()
        with self._wake:
            picked = self._pick_locked() if self._running else None
        if picked is not None:
            sess, (n, batch, idx, ref, clock), degraded, why = picked
            backend = self.mc.backend
            if degraded:
                try:
                    backend = self._get_degraded_backend()
                except Exception:
                    pass  # prewarm already advised; full budgets
            entry = self._dispatch(
                sess, backend, n, batch, idx, ref, degraded, clock, why
            )
            if entry is not None:
                with self._lock:
                    # stats()/snapshot() read the window depth under
                    # the plane lock; mutations take it too (drains
                    # still materialize OUTSIDE it)
                    self._window.append(entry)
                while len(self._window) >= self.inflight_depth:
                    self._drain_one()
            self._finalize_ready()
            return
        if self._window:
            self._drain_one()
            self._finalize_ready()
            return
        self._finalize_ready()
        with self._wake:
            if self._running and self._pick_preview_locked() is None:
                self._wake.wait(timeout=0.1)

    def _reap_stale(self) -> None:
        """Journal-and-close sessions whose client has gone quiet past
        `serve_session_timeout_s` (scheduler thread). Only fully
        drained, not-closing sessions are eligible — a reap never
        abandons admitted work. The journal survives (keep_journal), so
        a client that merely slept can `resume_session` later; without
        journaling only fully-FETCHED sessions are reaped (undelivered
        spans would outlive the reap only in the bounded retention —
        an eviction would silently end the returning client's stream),
        and the freed session's final result stays fetchable through
        the recently-closed retention."""
        if self.session_timeout_s <= 0:
            return
        now = time.monotonic()
        stale = []
        with self._lock:
            for s in self._sessions.values():
                if (
                    s.error is None
                    and not s.closing
                    # a thread blocked in fetch()/result() is a LIVE
                    # client whose activity clock went stale mid-wait
                    and s.waiters == 0
                    and now - s.last_activity > self.session_timeout_s
                    and s.drained_out()
                    # no-data-loss gate: without a journal, a reaped
                    # session's undelivered spans survive only in the
                    # bounded retention — an eviction would turn them
                    # into a silent "exhausted" for the returning
                    # client. And a journal never stores corrected
                    # PIXELS, so an emit-frames session's undelivered
                    # spans would not survive a reap+resume either.
                    # Both are reaped only once everything was fetched.
                    and (
                        (s.journal is not None and not s.emit_frames)
                        or s.fully_delivered()
                    )
                ):
                    # Close atomically with the check (begin_close is
                    # reentrant on the plane lock): once closing is
                    # set no new submit can slip in, so the journal
                    # written below is the stream's final state.
                    s.keep_journal = True
                    self._stats["sessions_reaped"] += 1
                    s.begin_close()
                    # capture idle at check time: a client thread
                    # waking into fetch() after we drop the lock
                    # refreshes last_activity and would make the
                    # advisory below log a nonsensical "idle for 0s"
                    stale.append((s, now - s.last_activity))
        for sess, idle_s in stale:
            sess.maybe_journal(force=True)
            if sess.journal is not None and sess.journal.last_saved > 0:
                fate = "journaled and reaped — resume_session restores it"
            elif sess.journal is not None:
                # journaling armed but the stream never drained a frame
                # — there is nothing durable to resume
                fate = "reaped (no frames drained, nothing to journal)"
            else:
                fate = (
                    "reaped (journaling is off; its final result stays "
                    "fetchable through the recently-closed retention)"
                )
            advise(
                f"kcmc serve: session {sess.sid} idle for "
                f"{idle_s:.3g}s (> "
                f"serve_session_timeout_s={self.session_timeout_s:g}); "
                f"{fate}",
                stacklevel=2,
            )
        # finalization happens in _finalize_ready on this same thread

    def _prepare_references(self) -> None:
        """Prepare staged references OUTSIDE the lock (device compute,
        possibly a JIT compile — client submits must keep flowing on
        every other session meanwhile). Scheduler thread only."""
        with self._lock:
            needing = [
                s
                for s in self._sessions.values()
                if s.error is None and not s.closed and s.needs_reference()
            ]
        for sess in needing:
            try:
                sess.prepare_reference_now()
            except BaseException as e:
                sess.fail(e)
            else:
                self._maybe_warm_degraded_shape(sess)
                self._maybe_warm_partial_rungs(sess)

    def _pick_preview_locked(self):
        """Whether ANY session has dispatchable or finalizable work
        (idle-wait predicate; does not consume anything). Mirrors the
        pick's latency-deferral decision exactly — a deferred latency
        session must NOT read as dispatchable, or the loop busy-spins
        instead of idle-waiting (the 0.1s wait bounds the deadline-
        expiry reaction granularity; documented in PERFORMANCE.md)."""
        for sess in self._sessions.values():
            if sess.error is None and not sess.closed and (
                sess.ready_count() or sess.needs_reference()
            ):
                if sess.needs_reference() or sess.qos_class != "latency":
                    return sess
                if self._latency_take_locked(sess, peek=True) is not None:
                    return sess
                continue
            if sess.closing and not sess.closed and sess.drained_out():
                return sess
        return None

    def _ready_batch_sessions_locked(self):
        """Batch-class sessions with dispatchable frames (lock held) —
        the preemption fast path's skip set and starvation ledger."""
        return [
            s
            for s in self._sessions.values()
            if s.qos_class != "latency" and s.error is None
            and not s.closed and s.ready_count() > 0
        ]

    def _pick_locked(self):
        """The dispatch pick. Latency-class sessions with deadline
        pressure (or a full window) jump the weighted round-robin —
        earliest head-of-line deadline first — with starvation bounded
        by an aging credit counter: every batch-class session a
        preemption skips gains credit, and one at
        `serve_latency_starvation_limit` takes the slot unconditionally
        before the next jump. Everything else is the weighted
        round-robin. Returns (session, padded batch, degraded flag,
        why) or None; `why` is the dispatch-decision vocabulary
        (obs/registry.py DISPATCH_WHY_COUNTERS)."""
        order = self._order
        if not order:
            return None
        lat_ready = sorted(
            (
                s
                for s in self._sessions.values()
                if s.qos_class == "latency" and s.error is None
                and not s.closed and s.ready_count() > 0
            ),
            key=lambda s: (
                d if (d := s.head_deadline()) is not None else float("inf")
            ),
        )
        for sess in lat_ready:
            take = self._latency_take_locked(sess)
            if take is None:
                continue  # deferred: slack buys fill time
            target, why = take
            skipped = self._ready_batch_sessions_locked()
            if skipped:
                # bounded starvation: a batch session jumped past its
                # aging limit gets this slot instead of the preemption
                starved = next(
                    (
                        s for s in skipped
                        if self._starve_credit.get(s.sid, 0)
                        >= self.starvation_limit
                    ),
                    None,
                )
                if starved is not None:
                    try:
                        taken = starved.take_batch(self.B)
                    except Exception as e:
                        starved.fail(e)
                        taken = None
                    if taken is not None:
                        self._starve_credit[starved.sid] = 0
                        self._stats["starvation_grants"] += 1
                        why_b = (
                            "full_window"
                            if taken[0] >= self.B
                            else "flush"
                        )
                        return starved, taken, starved.degraded, why_b
            try:
                taken = sess.take_batch(self.B, target=target)
            except Exception as e:
                # Batch-forming failure is that ONE stream's problem
                # (fail drops its pending frames, so this cannot
                # respin) — the plane keeps serving.
                sess.fail(e)
                continue
            if taken is None:
                continue
            if skipped:
                self._stats["preemptions"] += 1
                sess.preempted_dispatches += 1
                for s in skipped:
                    self._starve_credit[s.sid] = (
                        self._starve_credit.get(s.sid, 0) + 1
                    )
                if why in ("full_window", "flush"):
                    # deadline_forced / fill_floor outrank preempted in
                    # the why vocabulary — they explain the TIMING, the
                    # jump is visible in the preemption counters either
                    # way
                    why = "preempted"
            self._floor_deferred.discard(sess.sid)
            return sess, taken, sess.degraded, why
        for i in range(len(order)):
            sid = order[(self._rr + i) % len(order)]
            sess = self._sessions.get(sid)
            if sess is None or sess.closed or sess.error is not None:
                continue
            if sess.qos_class == "latency":
                continue  # taken (or deliberately deferred) above
            if sess.ready_count() > 0:
                try:
                    taken = sess.take_batch(self.B)
                except Exception as e:
                    sess.fail(e)
                    continue
                if taken is not None:
                    self._rr = (self._rr + i + 1) % len(order)
                    # a served batch session starts its aging over
                    self._starve_credit.pop(sid, None)
                    why = (
                        "full_window" if taken[0] >= self.B else "flush"
                    )
                    return sess, taken, sess.degraded, why
        return None

    def _finalize_ready(self) -> None:
        """Finalize sessions whose streams fully drained after
        begin_close, OUTSIDE the scheduler lock (writer close blocks),
        then drop closed sessions from the schedule."""
        with self._lock:
            ready = [
                s for s in self._sessions.values()
                if s.closing and not s.closed and s.drained_out()
            ]
        for s in ready:
            s.finalize()
        with self._lock:
            done = [(sid, s) for sid, s in self._sessions.items() if s.closed]
            for sid, s in done:
                del self._sessions[sid]
                self._record_closed_locked(s)
            if done:
                self._rebuild_order()

    def _dispatch(
        self, sess, backend, n, batch, idx, ref, degraded, clock=None,
        why="full_window",
    ):
        """Dispatch one session batch; on a dispatch-time error, flush
        the window first (ordering + the ladder's synthesis template),
        then walk the session's degradation ladder. Returns a window
        entry, or None when the error path already accounted the
        batch. `clock` is the batch's RequestClock (take_batch) — the
        dispatch segment closes here, device/drain close at drain.
        `why` is the pick's dispatch-decision reason: counted in
        `stats`, mirrored as a registry-checked SpanShard counter when
        tracing is armed, and ridden on the request.dispatch span."""
        if (
            not getattr(backend, "accepts_native_dtype", False)
            and batch.dtype != np.float32
        ):
            batch = batch.astype(np.float32)
        dispatch = getattr(backend, "process_batch_async", None)
        with self._lock:
            # scheduler-thread QoS counters share the plane lock with
            # the stats()/snapshot() readers
            self._stats["batches"] += 1
            self._stats["occupied_frames"] += int(n)
            if degraded:
                self._stats["degraded_batches"] += 1
            self._stats["dispatch_why"]["dispatch.why." + why] += 1
        if self.trace_shard is not None:
            # the same literal vocabulary the _stats seed registers
            # (obs/registry.py DISPATCH_WHY_COUNTERS) — one counter
            # instant per dispatch decision on the span shard
            self.trace_shard.counter("dispatch.why." + why, time.time())
        kept = batch if sess.wants_pixels() else None
        kw = {}
        warm = (
            sess.mc.config.warm_start
            and sess.mc.config.model != "piecewise"
            and dispatch is not None
        )
        if warm:
            # Plugin-seam guard (the corrector's _dispatch_accepts
            # convention): a backend implementing the original async
            # seam without a `seed` parameter keeps working — it just
            # never warm-starts. Cached per backend instance.
            bkey = id(backend)
            ok = self._seed_accepts.get(bkey)
            if ok is None:
                ok = sess.mc._dispatch_accepts(dispatch, "seed")
                self._seed_accepts[bkey] = ok
            warm = ok
        if warm and sess.warm_seed is not None:
            # Temporal warm start, per SESSION: each stream's own last
            # transform seeds its next batch's consensus (streams are
            # independent temporal histories — never share seeds).
            kw["seed"] = (sess.warm_seed, True)
        # Chaos surface: the serve dispatch is the same `device` fault
        # surface the one-shot `_dispatch_batches` arms, on the
        # SESSION's own plan (per-stream deterministic step counters).
        plan = sess.mc._fault_plan
        step = plan.op_index("device") if plan is not None else None
        try:
            if plan is not None:
                plan.maybe_fail("device", step)
            if dispatch is not None:
                out = dispatch(batch, ref, idx, **kw)
            else:
                out = backend.process_batch(batch, ref, idx)
        except Exception as e:
            while self._window:
                self._drain_one()
            self._ladder(
                sess, e, backend, batch, ref, idx, n, kept, step, clock
            )
            return None
        if clock is not None and sess.lat is not None:
            clock.rung = "degraded" if degraded else (
                "latency" if sess.qos_class == "latency" else "full"
            )
            clock.t_dispatched = time.perf_counter()
            sess.lat.observe(
                "request.dispatch", clock.t_dispatched - clock.t_formed,
                n=n, rung=clock.rung,
            )
            if clock.trace is not None:
                sess.trace_obs(
                    "request.dispatch",
                    clock.t_dispatched - clock.t_formed,
                    n, clock.rung, clock.trace,
                    args={"why": why},
                )
        if warm and "transform" in out:
            sess.warm_seed = out["transform"][n - 1]
        return (sess, n, out, kept, batch, idx, ref, backend, clock)

    def _drain_one(self) -> None:
        """Drain the oldest in-flight entry: materialize to host (where
        a deferred async device error surfaces — it walks the ladder),
        then hand the batch to its session."""
        with self._lock:
            if not self._window:
                return
            sess, n, out, kept, batch, idx, ref, backend, clock = (
                self._window.popleft()
            )
        try:
            # Registration-only sessions (no emit, no server-side file,
            # no rolling template) never touch pixels: leave `corrected`
            # on device instead of paying a (B, H, W) host transfer per
            # batch — the same drop the one-shot registration-only path
            # makes before materializing.
            host = {
                k: np.asarray(v)[:n]
                for k, v in out.items()
                if sess.wants_pixels() or k != "corrected"
            }
            sess.mc._note_out_template(host)
        except Exception as e:
            self._ladder(sess, e, backend, batch, ref, idx, n, kept,
                         clock=clock)
            return
        if clock is not None:
            # device-execution segment ends when host arrays exist
            clock.t_host = time.perf_counter()
        if backend is self.mc.backend:
            with self._lock:
                # a clean primary drain resets the supervisor's strikes
                self._strikes = 0
        self._account_done(sess, n, host, kept, ref, clock)

    def _ladder(
        self, sess, exc, backend, batch, ref, idx, n, kept, step=None,
        clock=None,
    ) -> None:
        """Walk the session's degradation ladder for a failed batch and
        feed the backend supervisor. Transient errors walk the PR-2
        ladder (retry with backoff -> failover backend -> mark-failed)
        and count a strike against the primary; a FATAL error on the
        primary no longer fails the stream — it quarantines the backend
        (rebuilt off the request path, `_rebuild_backend`) and recovers
        THIS batch on the failover rung directly, so a wedged
        accelerator drops zero sessions. Genuine per-stream bugs still
        fail their one stream: a batch the failover backend also
        rejects fatally has no rung left."""
        from kcmc_tpu.utils import faults

        with self._lock:
            current = backend is self.mc.backend
            # The degraded QoS twin shares the physical device, so its
            # failures feed the same supervisor (strike + failover
            # recovery) — a wedge under overload is still a wedge.
            degraded_rung = (
                backend is not None and backend is self._degraded_backend
            )
        # Every window entry dispatched on a real backend — current
        # primary, the degraded QoS twin, or a RETIRED backend an
        # entry was in flight on when a rebuild swapped it out — walks
        # the failover recovery below on a fatal error (zero-drop
        # contract across the swap race). Only `batch is None`
        # registration-only drains lack the re-execution rung. The
        # current/degraded distinction above exists solely for strike
        # accounting: retired backends must not strike the fresh
        # primary.
        primary = backend is not None
        extra = getattr(backend, "transient_error_types", ())
        transient = faults.classify_transient(exc, extra)
        if (current or degraded_rung) and batch is not None:
            self._note_strike(exc, fatal=not transient)
        if not transient and primary and batch is not None:
            try:
                got = self._failover_batch(
                    sess, exc, batch, ref, idx, n, backend, step
                )
            except BaseException as e:
                # The entry MUST be accounted on every path: an
                # unexpected error here (failover-backend construction,
                # classification) would otherwise leak the in-flight
                # count and wedge the stream's close forever.
                sess.fail(e)
                sess.entry_done()
                return
            if got is None:
                return  # no rung left: the stream was failed already
            out, failed = got
        else:
            try:
                out, failed = sess.mc._ladder_batch(
                    exc, backend, batch, ref, idx, {}, step, n, True, None
                )
            except BaseException as e:
                sess.fail(e)
                sess.entry_done()
                return
        host = {
            k: np.asarray(v)[:n]
            for k, v in out.items()
            if sess.wants_pixels() or k != "corrected"
        }
        kept = sess.mc._failed_kept(host, kept, failed)
        if clock is not None:
            # laddered batches close their device segment here — the
            # retry/failover walk is honest device-side time
            clock.t_host = time.perf_counter()
        self._account_done(sess, n, host, kept, ref, clock)

    # -- backend supervision (quarantine + off-path rebuild) ----------------

    # Minimum spacing between rebuild attempts: inside it a strike-out
    # skips the quarantine (batches still recover on the failover rung)
    # so a deterministically-poison batch cannot thrash the plane with
    # endless rebuild + re-prewarm cycles.
    REBUILD_COOLDOWN_S = 30.0

    def _note_strike(self, exc, fatal: bool) -> None:
        """Count one batch failure on the supervised device (primary or
        its degraded QoS twin); at the strike limit (a fatal error
        counts as the whole limit) quarantine the backend and kick the
        background rebuild."""
        if self._strike_limit <= 0:
            return
        start = False
        with self._lock:
            self._strikes = (
                self._strike_limit if fatal else self._strikes + 1
            )
            if (
                self._strikes >= self._strike_limit
                and not self._rebuilding
                and time.monotonic() - self._last_rebuild
                > self.REBUILD_COOLDOWN_S
            ):
                self._rebuilding = True
                self._stats["backend_rebuilds"] += 1
                start = True
        if start:
            advise(
                f"kcmc serve: primary backend quarantined after "
                f"{'a fatal' if fatal else 'repeated'} dispatch error "
                f"({type(exc).__name__}: {exc}); rebuilding it off the "
                "request path — batches recover on the failover rung "
                "meanwhile, no session is dropped",
                stacklevel=2,
            )
            self._spawn_warmup(
                self._rebuild_backend, "kcmc-serve-backend-rebuild"
            )

    def _failover_batch(self, sess, exc, batch, ref, idx, n, backend, step):
        """Recover one batch of a quarantined primary directly on the
        ladder's lower rungs (the primary is known-wedged, so retrying
        it would only burn the backoff budget): the canonical
        `_ladder_batch` with `skip_to_failover` — failover backend,
        then mark-failed, identical counters/advisories to the
        one-shot path. Returns (host out, mark_failed), or None after
        failing the stream (no rung left)."""
        try:
            return sess.mc._ladder_batch(
                exc, backend, batch, ref, idx, {}, step, n, True, None,
                skip_to_failover=True,
            )
        except BaseException as e:
            # No rung left (fatal failover error, mark-failed
            # unavailable): that ONE stream fails, accounted here.
            sess.fail(e)
            sess.entry_done()
            return None

    def _rebuild_backend(self) -> None:
        """Quarantine recovery (non-daemon warm-up thread, joined on
        stop): construct a FRESH primary backend — warm-booting through
        the persistent compile/export caches when configured — pre-warm
        each live session's frame shape on it, then swap it in under
        the plane lock. Sessions re-stage their references so the
        scheduler re-prepares them on the new backend; in-flight
        entries dispatched on the quarantined backend re-dispatch
        through the ladder when their drain surfaces the error."""
        from kcmc_tpu.backends import get_backend

        try:
            # forward an explicitly constructed mesh (the mesh= ctor
            # path, not config.mesh_devices) like _get_escalation_backend
            # — a rebuild must not silently unshard the plane
            mesh = getattr(self.mc.backend, "mesh", None)
            options = {"mesh": mesh} if mesh is not None else {}
            new = get_backend(self.mc.backend_name, self.mc.config, **options)
            with self._lock:
                shapes = {
                    tuple(s.frame_shape): s.ref_frame
                    for s in self._sessions.values()
                    if s.frame_shape is not None and s.ref_frame is not None
                }
            for shape, ref_frame in shapes.items():
                try:
                    ref = new.prepare_reference(
                        np.asarray(ref_frame, np.float32)
                    )
                    dummy = np.broadcast_to(
                        ref_frame, (self.B,) + shape
                    ).astype(np.float32)
                    out = new.process_batch(dummy, ref, np.arange(self.B))
                    for v in out.values():
                        np.asarray(v)  # block until compile+run finished
                except Exception:
                    pass  # that shape compiles inline at first dispatch
        except Exception as e:
            advise(
                f"kcmc serve: backend rebuild failed "
                f"({type(e).__name__}: {e}); keeping the quarantined "
                "backend — batches keep recovering on the failover rung",
                stacklevel=2,
            )
            with self._lock:
                self._rebuilding = False
                self._last_rebuild = time.monotonic()
            return
        with self._wake:
            self.mc.backend = new
            self._seed_accepts.clear()
            # The degraded QoS twin was built against the quarantined
            # device context — invalidate it so overload traffic lazily
            # rebuilds it on the fresh one instead of failing streams.
            self._degraded_backend = None
            self._degraded_warm_started.clear()
            for s in self._sessions.values():
                s.adopt_backend(new)
            self._strikes = 0
            self._rebuilding = False
            self._last_rebuild = time.monotonic()
            self._wake.notify_all()
        advise(
            "kcmc serve: rebuilt primary backend swapped in; sessions "
            "re-prepare their references on it and dispatch resumes",
            stacklevel=2,
        )

    def _account_done(self, sess, n, host, kept, ref, clock=None) -> None:
        try:
            sess.on_drained(n, host, kept, ref, clock=clock)
        except BaseException as e:
            sess.fail(e)
        finally:
            sess.entry_done()
        with self._lock:
            self._stats["frames_done"] += int(n)
            self._maybe_restore_locked(sess)
